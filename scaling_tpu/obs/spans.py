"""Phase tracing: ``with obs.span("ckpt.commit", step=N): ...``.

Every span lands twice:

- as an observation in the default registry's ``span_seconds`` histogram
  (labelled by span name) — cheap, in-memory, flushed with the per-step
  registry snapshot;
- as a structured ``span`` event through :meth:`logger.log_event`, so
  the PR 4 supervision events and the new telemetry share ONE stream and
  the run-dir analyzer (``python -m scaling_tpu.obs report``) can
  attribute barrier waits and checkpoint commits per host without a
  second file format.

Spans nest (thread-local stack; the parent's name is recorded on the
child) and are exception-safe: a body that raises still emits the span,
marked ``ok=false`` with the exception type, and the exception
propagates untouched.

Distributed tracing rides the same stream (docs/OBSERVABILITY.md
"Tracing"): a per-thread trace context — adopted via
:func:`trace_context` or inherited from the enclosing span — stamps
``trace`` / ``span_id`` / ``parent_span_id`` onto span events, and a
provider hook registered with :func:`logger.set_trace_provider` stamps
``trace`` onto every OTHER ``log_event`` record emitted under an active
context. Trace-less code paths emit byte-identical records to before:
no ids are allocated and no trace fields appear unless a context is
active, which is also what keeps warmup traffic out of the trace
coverage denominator.

Device-drain semantics reuse :class:`SynchronizedTimer`'s contract
without forcing a sync: a span measures host wall time unless the caller
hands it device work via ``sp.wait_for(x)``, in which case the exit
drains ``x`` first so the measured time covers the device work. The
default is drain-free — the step path must not gain device syncs outside
profiler windows (unit-asserted).

No jax at module level; the drain imports it lazily.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
import uuid
from contextlib import contextmanager
from typing import Any, Iterator, Optional

from ..logging import logger
from ..logging.logger import set_trace_provider
from .registry import get_registry

_local = threading.local()


def _stack() -> list:
    stack = getattr(_local, "stack", None)
    if stack is None:
        stack = []
        _local.stack = stack
    return stack


# ------------------------------------------------------------- trace ids
def new_trace_id() -> str:
    """A fresh 16-hex trace id (one per originating request)."""
    return uuid.uuid4().hex[:16]


def new_span_id() -> str:
    """A fresh 8-hex span id (allocated only for traced spans)."""
    return uuid.uuid4().hex[:8]


def derive_trace_id(*parts: Any) -> str:
    """Deterministic trace id from identity parts. Cross-host work that
    shares an identity but never an RPC envelope — a capacity lease
    ``(host, epoch)``, a checkpoint ``commit:step-N`` — derives the SAME
    trace id independently on every host, so the analyzer reassembles
    one fleet-wide trace without any context having crossed the wire."""
    raw = "\x1f".join(str(p) for p in parts)
    return hashlib.sha1(raw.encode("utf-8")).hexdigest()[:16]


@contextmanager
def trace_context(trace_id: Optional[str],
                  parent_span_id: Optional[str] = None) -> Iterator[None]:
    """Adopt an inbound trace context for this thread.

    Spans opened in the body (and ``log_event`` records emitted in it)
    carry ``trace_id``; the previous context is restored on exit, so
    nested adoption — a worker dispatching one request per envelope —
    composes. ``trace_id=None`` adopts the empty context (explicitly
    masking any ambient trace, which the warmup path relies on)."""
    prev = getattr(_local, "trace", None)
    _local.trace = (trace_id, parent_span_id) if trace_id else None
    try:
        yield
    finally:
        _local.trace = prev


def current_trace() -> Optional[dict]:
    """The active trace context as a JSON-safe dict — the exact value an
    RPC envelope's ``trace`` key carries (``{"trace_id": ...,
    "parent_span_id": ...}``), or ``None`` outside any context. The
    innermost traced span wins over an adopted context so the receiver
    links to the sender's actual span."""
    stack = _stack()
    if stack and stack[-1].trace_id:
        return {"trace_id": stack[-1].trace_id,
                "parent_span_id": stack[-1].span_id}
    ctx = getattr(_local, "trace", None)
    if ctx is not None and ctx[0]:
        return {"trace_id": ctx[0], "parent_span_id": ctx[1]}
    return None


def current_trace_id() -> Optional[str]:
    """Just the active ``trace_id`` (what journal records store)."""
    t = current_trace()
    return t["trace_id"] if t else None


def _trace_event_fields() -> Optional[dict]:
    """Provider for :func:`logger.set_trace_provider`: the ``trace``
    field to stamp onto non-span ``log_event`` records. Explicit fields
    win over the provider in ``log_event``, and the provider returns
    ``None`` outside any context so trace-less records stay
    byte-identical to the pre-tracing stream."""
    tid = current_trace_id()
    return {"trace": tid} if tid else None


set_trace_provider(_trace_event_fields)


class Span:
    """Handle yielded by :func:`span`; mutate it to enrich the record."""

    __slots__ = ("name", "fields", "_wait_for", "duration_s", "trace_id",
                 "span_id", "parent_span_id")

    def __init__(self, name: str, fields: dict):
        self.name = name
        self.fields = fields
        self._wait_for: Any = None
        self.duration_s: Optional[float] = None
        self.trace_id: Optional[str] = None
        self.span_id: Optional[str] = None
        self.parent_span_id: Optional[str] = None

    def wait_for(self, x: Any) -> Any:
        """Drain ``x`` (``jax.block_until_ready``) before the span closes,
        so the measured time covers its device work. Returns ``x``."""
        self._wait_for = x
        return x

    def annotate(self, **fields: Any) -> None:
        """Attach extra fields to the emitted span event."""
        self.fields.update(fields)


def current_span() -> Optional[Span]:
    stack = _stack()
    return stack[-1] if stack else None


@contextmanager
def span(name: str, *, step: Optional[int] = None, level: str = "debug",
         registry=None, **fields: Any) -> Iterator[Span]:
    """Trace one phase. ``level`` controls only the console mirror of the
    event (per-step phases default to ``debug`` so steady-state training
    does not quadruple its console output); the events file — when
    configured — receives every span regardless."""
    sp = Span(name, dict(fields))
    stack = _stack()
    parent = stack[-1].name if stack else None
    # resolve the trace lineage at entry, per thread: the enclosing span
    # wins (its span_id becomes the parent link), else the adopted
    # context; with neither the span stays trace-less and allocates no
    # ids at all — the pre-tracing fast path, byte-identical records
    if stack and stack[-1].trace_id:
        sp.trace_id = stack[-1].trace_id
        sp.parent_span_id = stack[-1].span_id
    else:
        ctx = getattr(_local, "trace", None)
        if ctx is not None and ctx[0]:
            sp.trace_id, sp.parent_span_id = ctx
    if sp.trace_id:
        sp.span_id = new_span_id()
    stack.append(sp)
    ok = True
    error: Optional[str] = None
    start = time.perf_counter()
    try:
        yield sp
        if sp._wait_for is not None:
            # drain INSIDE the measured window: the caller explicitly
            # asked for SynchronizedTimer semantics on this span —
            # opt-in via sp.wait_for(x), never the default
            import jax

            jax.block_until_ready(sp._wait_for)  # sta: disable=STA010
    except BaseException as e:
        ok = False
        error = type(e).__name__
        raise
    finally:
        duration = time.perf_counter() - start
        sp.duration_s = duration
        stack.pop()
        _emit(sp, parent, duration, ok, error, step, level, registry)


def _emit(sp: Span, parent: Optional[str], duration: float, ok: bool,
          error: Optional[str], step: Optional[int], level: str,
          registry) -> None:
    reg = registry if registry is not None else get_registry()
    reg.histogram("span_seconds", labels={"span": sp.name}).observe(duration)
    event_fields = dict(sp.fields)
    event_fields.update(span=sp.name, dur_s=round(duration, 6), ok=ok)
    if parent is not None:
        event_fields["parent"] = parent
    if step is not None:
        event_fields["step"] = step
    if error is not None:
        event_fields["error"] = error
    # trace lineage (explicit annotate() fields win, like host below):
    # only traced spans carry the columns, so trace-less runs emit the
    # exact records they always did
    if sp.trace_id is not None:
        event_fields.setdefault("trace", sp.trace_id)
        event_fields.setdefault("span_id", sp.span_id)
        if sp.parent_span_id is not None:
            event_fields.setdefault("parent_span_id", sp.parent_span_id)
    # host + relaunch epoch ride every span so the analyzer can attribute
    # per host AND per supervisor epoch — the same step gets re-saved and
    # the same barrier re-waited after a relaunch, and merging those
    # incidents would corrupt the arrived-last verdict
    for env_var, field in (("SCALING_TPU_HOST_ID", "host"),
                           ("SCALING_TPU_COORD_EPOCH", "epoch")):
        raw = os.environ.get(env_var)
        if raw is not None and field not in event_fields:
            try:
                event_fields[field] = int(raw)
            except ValueError:
                logger.warning(f"non-integer {env_var} {raw!r} ignored")
    # spans skip the per-record fsync: 3-4 of them land per training
    # step, and the durability contract belongs to lifecycle events
    logger.log_event("span", _level=level, _fsync=False, **event_fields)
