"""Unified telemetry (ISSUE 5, docs/OBSERVABILITY.md).

- :mod:`.registry` — process-wide metrics registry (counters, gauges,
  histograms with labels), per-step JSONL flush + Prometheus textfile.
- :mod:`.spans` — ``with obs.span("ckpt.commit", step=N)`` phase
  tracing, emitted through ``logger.log_event`` into the same stream as
  the supervision events.
- :mod:`.hardware` — device memory / live-array gauges, step-time EMA,
  achieved-TFLOPs and MFU math.
- :mod:`.telemetry` — the per-step driver the trainer owns.
- :mod:`.report` / ``python -m scaling_tpu.obs`` — run-dir analyzer
  turning events + metrics JSONL into a health report.

jax-free at import time (functions import it lazily): the analyzer CLI
and the supervisor's relaunch path must not pay backend init.
"""

from .hardware import (
    StepTimeEMA,
    achieved_tflops,
    device_memory_snapshot,
    mfu,
    update_hardware_gauges,
)
from .registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_registry,
    host_id,
)
from .spans import (
    Span,
    current_span,
    current_trace,
    current_trace_id,
    derive_trace_id,
    new_trace_id,
    span,
    trace_context,
)
from .telemetry import StepTelemetry

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "Span",
    "StepTelemetry",
    "StepTimeEMA",
    "achieved_tflops",
    "current_span",
    "current_trace",
    "current_trace_id",
    "derive_trace_id",
    "device_memory_snapshot",
    "get_registry",
    "host_id",
    "mfu",
    "new_trace_id",
    "span",
    "trace_context",
    "update_hardware_gauges",
]
