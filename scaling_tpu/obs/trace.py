"""Distributed-trace reassembly + critical-path analyzer.

``python -m scaling_tpu.obs trace <run_dir>`` reads the SAME event
stream the report reads (docs/OBSERVABILITY.md "Tracing") and regroups
it per trace: every record stamped with a ``trace`` id — or carrying
the id in a batch span's ``traces`` / ``chunk_traces`` list — belongs
to the request (or lease / commit) that originated it, no matter which
host's events file it landed in. Per-host timestamps are aligned with
the control plane's skew-immune ``clock-offset`` probes before any
cross-host ordering is derived, so a failover trace that dies on host 1
and resumes on host 0 still reads as one finite, ordered timeline.

Per trace the analyzer attributes wall time into phases:

- ``queue_wait`` — submission until the first compute span touches it;
- ``rpc``        — ``serve.replica.rpc_client`` time under the trace;
- ``prefill``    — ``serve.prefill`` / ``serve.prefill_chunk`` plus the
  chunk share of ``serve.mixed`` ticks (``chunk_traces``);
- ``decode``     — ``serve.decode`` plus the decode share of
  ``serve.mixed`` (``traces``);
- ``failover``   — positive gaps where consecutive host-stamped records
  of the trace jump hosts (replica death + re-dispatch, or a
  backpressure retry elsewhere); zero for a healthy single-replica
  trace;
- ``other``      — the unattributed residual of end-to-end time.

Batch spans serve many requests at once, so a span's full duration is
attributed to EVERY trace riding it — phase seconds answer "how long
did this request sit in phase X", not "how much device time did it
consume"; concurrent requests legitimately share the same wall time.

The critical path of a trace is its largest phase; the fleet-wide
breakdown counts traces per winning phase so "the fleet is queue-bound"
is one line, not a spreadsheet. CI gates: ``--assert-trace-coverage``
(missing data FAILS — a run that stamped nothing must not pass a
coverage floor by silence) and ``--assert-critical-path PHASE:SECONDS``
(no trace may spend more than the ceiling in that phase).

Pure stdlib + deterministic rendering, like the report: exit 0 clean,
1 a gate fired, 2 no parseable telemetry at all.
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import defaultdict
from pathlib import Path
from typing import Dict, List, Optional

from .report import RunData, load_run_dir

SCHEMA_VERSION = 1

PHASES = ("queue_wait", "rpc", "prefill", "decode", "failover", "other")

# span name -> phase it feeds (mixed is split by which list carries the
# trace id, so it is handled out of band)
_RPC_SPANS = ("serve.replica.rpc_client",)
_PREFILL_SPANS = ("serve.prefill", "serve.prefill_chunk")
_DECODE_SPANS = ("serve.decode",)
_MIXED_SPAN = "serve.mixed"
# spans that mark "the engine is working on this request" — the end of
# queue_wait is the first of these; admit/rpc are submission machinery
_COMPUTE_SPANS = set(_PREFILL_SPANS + _DECODE_SPANS + (_MIXED_SPAN,))


# ------------------------------------------------------------ assembly
def clock_offsets(data: RunData) -> Dict[int, float]:
    """Per-host clock offset (seconds AHEAD of the shared reference)
    from the ``clock-offset`` events each host emits at control-plane
    construction. Latest probe per host wins; a host that never probed
    aligns at 0 — single-host runs have nothing to align."""
    out: Dict[int, float] = {}
    for e in data.lifecycle:
        if e.get("event") != "clock-offset":
            continue
        try:
            out[int(e["host"])] = float(e["offset_s"])
        except (KeyError, TypeError, ValueError):
            continue
    return out


def _rec_trace_ids(rec: dict) -> List[str]:
    """Every trace id a record belongs to: the scalar ``trace`` stamp
    plus batch-span membership lists."""
    out: List[str] = []
    tid = rec.get("trace")
    if isinstance(tid, str):
        out.append(tid)
    for key in ("traces", "chunk_traces"):
        ids = rec.get(key)
        if isinstance(ids, list):
            out.extend(t for t in ids if isinstance(t, str) and t not in out)
    return out


def _aligned(rec: dict, offsets: Dict[int, float]) -> Optional[float]:
    """Record end timestamp on the shared clock (host offset removed)."""
    ts = rec.get("ts")
    if ts is None:
        return None
    host = rec.get("host")
    off = offsets.get(int(host), 0.0) if isinstance(host, int) else 0.0
    return float(ts) - off


def _start(rec: dict, end: float) -> float:
    """Span records carry their END ts; the interval starts dur_s
    earlier. Point events start where they end."""
    return end - float(rec.get("dur_s") or 0.0)


def assemble_traces(data: RunData) -> Dict[str, List[dict]]:
    """trace id -> its records, each annotated with aligned ``_end`` /
    ``_start`` floats, ordered by start time."""
    offsets = clock_offsets(data)
    by_trace: Dict[str, List[dict]] = defaultdict(list)
    for rec in data.events:
        ids = _rec_trace_ids(rec)
        if not ids:
            continue
        end = _aligned(rec, offsets)
        if end is None:
            continue
        annotated = dict(rec, _end=end, _start=_start(rec, end))
        for tid in ids:
            by_trace[tid].append(annotated)
    for recs in by_trace.values():
        recs.sort(key=lambda r: (r["_start"], r["_end"]))
    return dict(by_trace)


def trace_phases(tid: str, recs: List[dict]) -> Dict[str, float]:
    """Attribute one trace's wall time into the PHASES buckets."""
    t0 = min(r["_start"] for r in recs)
    t1 = max(r["_end"] for r in recs)
    phases = {p: 0.0 for p in PHASES}
    first_compute: Optional[float] = None
    for r in recs:
        name = r.get("span")
        dur = float(r.get("dur_s") or 0.0)
        if name in _RPC_SPANS:
            phases["rpc"] += dur
        elif name in _PREFILL_SPANS:
            phases["prefill"] += dur
        elif name in _DECODE_SPANS:
            phases["decode"] += dur
        elif name == _MIXED_SPAN:
            # one mixed tick serves chunked prefills AND decodes: the
            # list the id rides in says which side this trace was on
            if tid in (r.get("chunk_traces") or ()):
                phases["prefill"] += dur
            if tid in (r.get("traces") or ()):
                phases["decode"] += dur
        if name in _COMPUTE_SPANS and (first_compute is None
                                       or r["_start"] < first_compute):
            first_compute = r["_start"]
    if first_compute is not None:
        phases["queue_wait"] = max(0.0, first_compute - t0)
    # failover: the trace's host-stamped records jump hosts only when a
    # replica died (journal re-dispatch) or the router retried elsewhere
    # — the positive gap between the hosts is time the request spent
    # stranded. Router-side records carry no host and are skipped.
    hosted = [r for r in recs if isinstance(r.get("host"), int)]
    for prev, cur in zip(hosted, hosted[1:]):
        if prev["host"] != cur["host"]:
            phases["failover"] += max(0.0, cur["_start"] - prev["_end"])
    e2e = max(0.0, t1 - t0)
    attributed = sum(phases[p] for p in PHASES if p != "other")
    phases["other"] = max(0.0, e2e - attributed)
    phases["e2e"] = e2e
    return phases


def critical_phase(phases: Dict[str, float]) -> str:
    """The phase that dominated this trace — deterministic tie-break on
    PHASES order."""
    return max(PHASES, key=lambda p: (phases.get(p, 0.0),
                                      -PHASES.index(p)))


# ------------------------------------------------------------ analysis
def analyze(data: RunData,
            traces: Optional[Dict[str, List[dict]]] = None) -> dict:
    """The full machine-readable payload the renderer + gates read."""
    if traces is None:
        traces = assemble_traces(data)
    reqs = [e for e in data.lifecycle if e.get("event") == "serve-request"]
    completed = [r for r in reqs if r.get("status") == "completed"]
    per_trace: Dict[str, dict] = {}
    for tid, recs in traces.items():
        phases = trace_phases(tid, recs)
        hosts = sorted({r["host"] for r in recs
                        if isinstance(r.get("host"), int)})
        per_trace[tid] = {
            "records": len(recs),
            "hosts": hosts,
            "phases": {k: round(v, 6) for k, v in phases.items()},
            "critical_phase": critical_phase(phases),
            "req": next((r.get("req") for r in recs
                         if r.get("event") == "serve-request"), None),
            "status": next((r.get("status") for r in recs
                            if r.get("event") == "serve-request"), None),
        }
    # coverage: of the requests the engine says completed, how many are
    # reconstructable — trace-stamped AND backed by at least one compute
    # span record. An untraced or span-less request drags coverage down;
    # that is the point of the gate.
    covered = 0
    for r in completed:
        tid = r.get("trace")
        if not isinstance(tid, str):
            continue
        recs = traces.get(tid) or []
        if any(rec.get("span") in _COMPUTE_SPANS or
               rec.get("span") in _RPC_SPANS or
               rec.get("span") == "serve.admit" for rec in recs):
            covered += 1
    coverage = covered / len(completed) if completed else None
    sheds = sum(1 for e in data.lifecycle if e.get("event") == "serve-shed")
    fleet = {p: 0.0 for p in PHASES}
    winners = {p: 0 for p in PHASES}
    for t in per_trace.values():
        for p in PHASES:
            fleet[p] += t["phases"].get(p, 0.0)
        winners[t["critical_phase"]] += 1
    return {
        "schema_version": SCHEMA_VERSION,
        "traces": len(per_trace),
        "requests_completed": len(completed),
        "requests_total": len(reqs),
        "sheds": sheds,
        "coverage": coverage,
        "clock_offsets": {str(h): round(v, 6)
                          for h, v in sorted(clock_offsets(data).items())},
        "fleet_phase_seconds": {p: round(fleet[p], 6) for p in PHASES},
        "critical_path_counts": winners,
        "per_trace": per_trace,
    }


# ----------------------------------------------------------- rendering
def _fmt_s(v: float) -> str:
    return f"{v:.3f}s"


def render(payload: dict, traces: Dict[str, List[dict]],
           slowest: int) -> str:
    lines = ["== traces =="]
    cov = payload["coverage"]
    lines.append(
        f"  traces={payload['traces']} "
        f"completed_requests={payload['requests_completed']} "
        f"sheds={payload['sheds']} coverage="
        + (f"{cov:.1%}" if cov is not None else "(no completed requests)")
    )
    if payload["clock_offsets"]:
        lines.append("  clock offsets: " + " ".join(
            f"host{h}={o:+.3f}s"
            for h, o in payload["clock_offsets"].items()
        ))
    per = payload["per_trace"]
    if not per:
        lines.append("  (no trace-stamped records — pre-tracing run dir, "
                     "or only warmup traffic)")
        return "\n".join(lines) + "\n"
    fleet = payload["fleet_phase_seconds"]
    grand = sum(fleet.values()) or 1.0
    winners = payload["critical_path_counts"]
    lines.append("== fleet phase breakdown ==")
    for p in PHASES:
        lines.append(
            f"  {p:<10} {_fmt_s(fleet[p]):>10}  {fleet[p] / grand:6.1%}  "
            f"critical for {winners[p]} trace(s)"
        )
    ranked = sorted(per.items(), key=lambda kv: -kv[1]["phases"]["e2e"])
    lines.append(f"== slowest {min(slowest, len(ranked))} trace(s) ==")
    for tid, t in ranked[:slowest]:
        hosts = ",".join(map(str, t["hosts"])) or "-"
        lines.append(
            f"  {tid} req={t['req']} status={t['status']} "
            f"e2e={_fmt_s(t['phases']['e2e'])} hosts=[{hosts}] "
            f"critical={t['critical_phase']} "
            + " ".join(f"{p}={_fmt_s(t['phases'][p])}" for p in PHASES)
        )
        recs = traces[tid]
        t0 = min(r["_start"] for r in recs)
        for r in recs[:20]:
            name = r.get("span") or r.get("event")
            host = r.get("host")
            detail = f" ({_fmt_s(float(r['dur_s']))})" if r.get("dur_s") \
                else ""
            lines.append(
                f"    +{r['_start'] - t0:8.4f}s "
                + (f"host{host} " if host is not None else "       ")
                + f"{name}{detail}"
            )
        if len(recs) > 20:
            lines.append(f"    ... {len(recs) - 20} more record(s)")
    return "\n".join(lines) + "\n"


# --------------------------------------------------------------- gates
def check_gates(payload: dict,
                assert_trace_coverage: Optional[float] = None,
                assert_critical_path: Optional[List[str]] = None
                ) -> List[str]:
    """Failure messages (empty == pass). Missing data FAILS a requested
    gate, mirroring the report's gate contract."""
    failures: List[str] = []
    if assert_trace_coverage is not None:
        cov = payload["coverage"]
        if cov is None:
            failures.append(
                "assert-trace-coverage: no completed serve-request "
                "events in the run dir — nothing to measure coverage "
                "over (crashed before any completion, or not a serving "
                "run?)"
            )
        elif cov < assert_trace_coverage:
            failures.append(
                f"assert-trace-coverage: {cov:.3f} < floor "
                f"{assert_trace_coverage:.3f} "
                f"({payload['requests_completed']} completed request(s), "
                "untraced or span-less ones drag this down — a producer "
                "stopped stamping, or events were lost)"
            )
    for spec in assert_critical_path or []:
        try:
            phase, raw = spec.split(":", 1)
            ceiling = float(raw)
        except ValueError:
            failures.append(
                f"assert-critical-path: malformed spec {spec!r} "
                "(expected PHASE:SECONDS)"
            )
            continue
        if phase not in PHASES:
            failures.append(
                f"assert-critical-path: unknown phase {phase!r} "
                f"(one of {', '.join(PHASES)})"
            )
            continue
        per = payload["per_trace"]
        if not per:
            failures.append(
                f"assert-critical-path: no traces in the run dir to "
                f"check {phase} against"
            )
            continue
        worst_tid = max(per, key=lambda t: per[t]["phases"].get(phase, 0.0))
        worst = per[worst_tid]["phases"].get(phase, 0.0)
        if worst > ceiling:
            failures.append(
                f"assert-critical-path: {phase} {worst:.3f}s > ceiling "
                f"{ceiling:.3f}s (trace {worst_tid}, "
                f"req={per[worst_tid]['req']})"
            )
    return failures


# ----------------------------------------------------------------- cli
def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m scaling_tpu.obs trace",
        description="per-trace timeline + critical-path analyzer "
        "(docs/OBSERVABILITY.md Tracing)",
    )
    parser.add_argument("run_dir", help="directory holding the run's "
                        "events JSONL files (searched recursively)")
    parser.add_argument("--slowest", type=int, default=5, metavar="N",
                        help="render the N slowest trace timelines "
                        "(default 5)")
    parser.add_argument("--json", metavar="FILE",
                        help="also write the machine-readable payload")
    parser.add_argument("--assert-trace-coverage", type=float,
                        metavar="FLOOR",
                        help="fail (exit 1) when the fraction of "
                        "completed requests reconstructable as traces "
                        "is below FLOOR, or no completions exist at all")
    parser.add_argument("--assert-critical-path", action="append",
                        metavar="PHASE:SECONDS",
                        help="fail (exit 1) when any trace spent more "
                        "than SECONDS in PHASE (one of "
                        + ", ".join(PHASES) + "); repeatable")
    args = parser.parse_args(argv)

    run_dir = Path(args.run_dir)
    if not run_dir.is_dir():
        print(f"error: {run_dir} is not a directory", file=sys.stderr)
        return 2
    data = load_run_dir(run_dir)
    if not data.events and not data.steps and not data.registry:
        print(
            f"error: no telemetry records under {run_dir} "
            f"({data.files} jsonl file(s), {data.bad_lines} unparseable "
            "line(s)) — was the run launched with a log_dir / "
            "SCALING_TPU_EVENTS_PATH?",
            file=sys.stderr,
        )
        return 2
    traces = assemble_traces(data)
    payload = analyze(data, traces)
    print(render(payload, traces, args.slowest), end="")

    failures = check_gates(
        payload,
        assert_trace_coverage=args.assert_trace_coverage,
        assert_critical_path=args.assert_critical_path,
    )
    if (args.assert_trace_coverage is not None
            or args.assert_critical_path):
        print("== gates ==")
        if failures:
            for f in failures:
                print(f"  FAIL {f}")
        else:
            print("  PASS")
    if args.json:
        # stays raw, same rationale as the report CLI: obs cannot
        # import resilience's retry_io without inverting the layering
        Path(args.json).write_text(  # sta: disable=STA011
            json.dumps(payload, indent=1, sort_keys=True) + "\n"
        )
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
