"""Hardware gauges: device memory, live arrays, step-time EMA, MFU.

Everything here is host-side bookkeeping — ``memory_stats()`` is a
runtime query against the allocator and ``jax.live_arrays()`` walks the
client's tracking table; neither blocks on device work, so the per-step
gauge update adds NO device syncs (unit-asserted in
tests/core/test_obs/test_step_path.py).

MFU follows the PaLM appendix-B accounting the transformer entrypoint
already logs (models/transformer/utils/get_tflops.py): the model
declares its FLOPs-per-token estimate once, the trainer divides achieved
token throughput by the hardware's peak-flop token rate. jax imports
stay inside functions so the analyzer CLI never pays backend init.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .registry import MetricsRegistry, get_registry


def device_memory_snapshot() -> List[Dict]:
    """Per-local-device allocator stats; zeros where the backend keeps
    none (CPU)."""
    import jax

    out: List[Dict] = []
    for d in jax.local_devices():
        try:
            stats = d.memory_stats()
        except (RuntimeError, NotImplementedError):
            # some backends raise rather than returning None
            stats = None
        stats = stats or {}
        out.append({
            "device": d.id,
            "platform": d.platform,
            "bytes_in_use": int(stats.get("bytes_in_use", 0)),
            "peak_bytes_in_use": int(stats.get("peak_bytes_in_use", 0)),
            "bytes_limit": int(stats.get("bytes_limit", 0)),
        })
    return out


def update_hardware_gauges(registry: Optional[MetricsRegistry] = None) -> Dict:
    """Refresh device-memory and live-array gauges; returns an aggregate
    summary (max across local devices) for merging into step metrics."""
    import jax

    reg = registry if registry is not None else get_registry()
    max_in_use = 0
    max_peak = 0
    for rec in device_memory_snapshot():
        labels = {"device": str(rec["device"])}
        reg.gauge("device_bytes_in_use", labels).set(rec["bytes_in_use"])
        reg.gauge("device_peak_bytes_in_use", labels).set(
            rec["peak_bytes_in_use"]
        )
        max_in_use = max(max_in_use, rec["bytes_in_use"])
        max_peak = max(max_peak, rec["peak_bytes_in_use"])
    live = len(jax.live_arrays())
    reg.gauge("live_arrays").set(live)
    return {
        "device_bytes_in_use": max_in_use,
        "device_peak_bytes_in_use": max_peak,
        "live_arrays": live,
    }


class StepTimeEMA:
    """Exponential moving average of fetched step durations — the smooth
    signal regression gates and dashboards want, next to the raw
    per-step value."""

    def __init__(self, alpha: float = 0.1):
        assert 0 < alpha <= 1
        self.alpha = alpha
        self.value: Optional[float] = None

    def update(self, duration_s: float) -> float:
        if self.value is None:
            self.value = float(duration_s)
        else:
            self.value = (
                self.alpha * float(duration_s) + (1 - self.alpha) * self.value
            )
        return self.value


def achieved_tflops(flops_per_token: float, tokens_per_step: float,
                    step_time_s: float) -> float:
    """Model-FLOPs throughput actually sustained, pod-wide."""
    return flops_per_token * tokens_per_step / step_time_s / 1e12


def mfu(achieved_tflops_total: float, world_size: int,
        peak_tflops_per_device: float) -> float:
    """Model FLOPs Utilization: achieved over the pod's peak."""
    return achieved_tflops_total / (world_size * peak_tflops_per_device)
