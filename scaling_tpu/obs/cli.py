"""CLI: ``python -m scaling_tpu.obs report <run_dir>``.

Renders the health report on stdout; ``--json`` additionally writes the
machine-readable payload. Exit codes: 0 clean, 1 a ``--assert-*`` gate
fired, 2 the run dir held no parseable telemetry at all.

``python -m scaling_tpu.obs trace <run_dir>`` delegates to the
distributed-trace analyzer (:mod:`.trace`), which owns its own flag set
— the two commands share only the run-dir loader and exit-code
contract.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from .report import (
    check_gates,
    load_run_dir,
    mfu_section,
    render_report,
    tuner_section,
)


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "trace":
        # the trace analyzer owns its own argparse (different flags,
        # same exit-code contract) — dispatch before parsing so its
        # --help renders its flags, not the report's
        from .trace import main as trace_main

        return trace_main(argv[1:])
    parser = argparse.ArgumentParser(
        prog="python -m scaling_tpu.obs",
        description="run-dir telemetry analyzer (docs/OBSERVABILITY.md)",
    )
    parser.add_argument("command", choices=["report", "trace"])
    parser.add_argument("run_dir", help="directory holding the run's "
                        "events/metrics JSONL files (searched recursively)")
    parser.add_argument("--json", metavar="FILE",
                        help="also write a machine-readable report")
    parser.add_argument("--assert-mfu", type=float, metavar="FLOOR",
                        help="fail (exit 1) when mean MFU is below FLOOR")
    parser.add_argument("--assert-step-time", type=float, metavar="CEIL",
                        help="fail (exit 1) when p50 step time exceeds "
                        "CEIL seconds")
    parser.add_argument("--assert-tuner-calibration", type=float,
                        metavar="CEIL",
                        help="fail (exit 1) when the tuner's relative "
                        "prediction error vs measured step time exceeds "
                        "CEIL (docs/TUNING.md calibration loop)")
    parser.add_argument("--assert-serve-throughput", type=float,
                        metavar="FLOOR",
                        help="fail (exit 1) when serving output tokens/s "
                        "is below FLOOR (docs/SERVING.md gates)")
    parser.add_argument("--assert-ttft", type=float, metavar="CEIL",
                        help="fail (exit 1) when serving p99 "
                        "time-to-first-token exceeds CEIL seconds")
    parser.add_argument("--assert-spec-accept-rate", type=float,
                        metavar="FLOOR",
                        help="fail (exit 1) when the speculative-decoding "
                        "accept rate is below FLOOR, or the run recorded "
                        "no speculation telemetry (docs/SERVING.md)")
    parser.add_argument("--assert-max-resizes", type=int, metavar="CEIL",
                        help="fail (exit 1) when a supervised run resized "
                        "(downsize OR elastic upsize) more than CEIL "
                        "times, or the run dir holds no supervisor "
                        "telemetry at all (docs/RESILIENCE.md elastic "
                        "capacity); the flap drill's zero-churn gate")
    parser.add_argument("--assert-max-downsizes", type=int, metavar="CEIL",
                        help="alias of --assert-max-resizes (predates "
                        "elastic upsizing; counts BOTH directions so a "
                        "flapping host cannot pass on a technicality)")
    parser.add_argument("--assert-max-shed-rate", type=float,
                        metavar="CEIL",
                        help="fail (exit 1) when the serving shed rate "
                        "exceeds CEIL, or the run dir holds no shed "
                        "telemetry at all (docs/SERVING.md resilience)")
    parser.add_argument("--assert-max-serve-timeouts", type=int,
                        metavar="CEIL",
                        help="fail (exit 1) when more than CEIL serving "
                        "requests hit their deadline, or the run dir "
                        "holds no timeout telemetry at all")
    parser.add_argument("--assert-max-replica-skew", type=float,
                        metavar="CEIL",
                        help="fail (exit 1) when the fleet's per-replica "
                        "completed-request skew (max/min) exceeds CEIL, "
                        "or the run dir holds no replica telemetry at "
                        "all (docs/SERVING.md the fleet)")
    parser.add_argument("--assert-max-replica-restarts", type=int,
                        metavar="CEIL",
                        help="fail (exit 1) when the process fleet's "
                        "supervisor performed more than CEIL relaunches, "
                        "or the run dir holds no fleet supervision "
                        "telemetry at all (docs/SERVING.md process mode)")
    args = parser.parse_args(argv)

    run_dir = Path(args.run_dir)
    if not run_dir.is_dir():
        print(f"error: {run_dir} is not a directory", file=sys.stderr)
        return 2
    data = load_run_dir(run_dir)
    if not data.events and not data.steps and not data.registry:
        print(
            f"error: no telemetry records under {run_dir} "
            f"({data.files} jsonl file(s), {data.bad_lines} unparseable "
            "line(s)) — was the run launched with a log_dir / "
            "SCALING_TPU_EVENTS_PATH?",
            file=sys.stderr,
        )
        return 2
    print(render_report(data, run_dir), end="")

    _, tuner_stats = tuner_section(data)
    failures = check_gates(
        data, assert_mfu=args.assert_mfu,
        assert_step_time=args.assert_step_time,
        assert_tuner_calibration=args.assert_tuner_calibration,
        tuner_stats=tuner_stats,
        assert_serve_throughput=args.assert_serve_throughput,
        assert_ttft=args.assert_ttft,
        assert_spec_accept_rate=args.assert_spec_accept_rate,
        assert_max_downsizes=args.assert_max_downsizes,
        assert_max_resizes=args.assert_max_resizes,
        assert_max_shed_rate=args.assert_max_shed_rate,
        assert_max_serve_timeouts=args.assert_max_serve_timeouts,
        assert_max_replica_skew=args.assert_max_replica_skew,
        assert_max_replica_restarts=args.assert_max_replica_restarts,
    )
    if (args.assert_mfu is not None or args.assert_step_time is not None
            or args.assert_tuner_calibration is not None
            or args.assert_serve_throughput is not None
            or args.assert_ttft is not None
            or args.assert_spec_accept_rate is not None
            or args.assert_max_downsizes is not None
            or args.assert_max_resizes is not None
            or args.assert_max_shed_rate is not None
            or args.assert_max_serve_timeouts is not None
            or args.assert_max_replica_skew is not None
            or args.assert_max_replica_restarts is not None):
        print("== gates ==")
        if failures:
            for f in failures:
                print(f"  FAIL {f}")
        else:
            print("  PASS")

    if args.json:
        from .report import serving_section

        _, stats = mfu_section(data)
        stats = {**stats, **tuner_stats, **serving_section(data)[1]}
        payload = {
            "files": data.files,
            "bad_lines": data.bad_lines,
            "events": len(data.events),
            "step_records": len(data.steps),
            "registry_records": len(data.registry),
            "stats": stats,
            "gate_failures": failures,
        }
        # stays raw: obs cannot import resilience's retry_io without
        # inverting the layering (resilience wraps its I/O in obs spans),
        # and a failed report write already fails the CLI loudly
        Path(args.json).write_text(  # sta: disable=STA011
            json.dumps(payload, indent=1) + "\n"
        )
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
