"""Process-wide metrics registry: counters, gauges, histograms with labels.

The per-process half of the telemetry layer (docs/OBSERVABILITY.md): every
subsystem records into one registry, and the trainer flushes a snapshot
per fetched step to the metrics JSONL sink (the same file
``logger.log_metrics`` appends its per-step records to), plus — when
configured — a Prometheus-textfile render for node-exporter-style
scraping. Megatron-style achieved-TFLOPs accounting (arxiv 2104.04473)
only works when the numbers are *collected* somewhere; this is that
somewhere.

No jax at module level (same rule as :mod:`scaling_tpu.resilience`): the
analyzer CLI and supervisor import this on the relaunch critical path.
"""

from __future__ import annotations

import bisect
import json
import math
import os
import threading
import time
from pathlib import Path
from typing import Dict, List, Mapping, Optional, Tuple

# logging is jax-free and sits BELOW obs in the layering (obs.spans
# already imports it at module level); the reverse direction never
# happens at import time
from ..logging.logger import append_jsonl_line

LabelKey = Tuple[Tuple[str, str], ...]

# cardinality guard: one call site interpolating an unbounded value
# into a label (a request id, a trace id, a raw path) would grow the
# registry — and every snapshot / textfile render, forever — without
# bound. Past this many distinct label sets per metric NAME, new series
# fold into one ``__overflow__`` series so aggregate totals stay right
# while the per-label split is capped.
MAX_SERIES_PER_METRIC = 64
OVERFLOW_LABELS: LabelKey = (("__overflow__", "true"),)

# latency-shaped default buckets (seconds): spans range from sub-ms file
# ops to multi-minute checkpoint writes / barrier waits
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 30.0,
    60.0, 120.0, 300.0, 600.0,
)


def _label_key(labels: Optional[Mapping[str, object]]) -> LabelKey:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _render_name(name: str, labels: LabelKey) -> str:
    if not labels:
        return name
    inner = ",".join(f"{k}={v}" for k, v in labels)
    return f"{name}{{{inner}}}"


def _prom_name(name: str, labels: LabelKey) -> str:
    if not labels:
        return name
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return f"{name}{{{inner}}}"


class Counter:
    """Monotonically increasing count (steps taken, retries, relaunches)."""

    kind = "counter"

    def __init__(self, name: str, labels: LabelKey, lock: threading.Lock):
        self.name = name
        self.labels = labels
        self._lock = lock
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        # coerce like Gauge.set does: a numpy scalar slipped in here
        # would otherwise survive to json.dumps in flush_step and abort
        # the training step with a TypeError
        amount = float(amount)
        if amount < 0:
            raise ValueError(f"counter {self.name} cannot decrease ({amount})")
        with self._lock:
            self.value += amount


class Gauge:
    """Point-in-time value (bytes in use, MFU, heartbeat send lag)."""

    kind = "gauge"

    def __init__(self, name: str, labels: LabelKey, lock: threading.Lock):
        self.name = name
        self.labels = labels
        self._lock = lock
        self.value: Optional[float] = None

    def set(self, value: float) -> None:
        with self._lock:
            self.value = float(value)


class Histogram:
    """Bucketed distribution (span durations, barrier waits)."""

    kind = "histogram"

    def __init__(self, name: str, labels: LabelKey, lock: threading.Lock,
                 buckets: Tuple[float, ...] = DEFAULT_BUCKETS):
        self.name = name
        self.labels = labels
        self._lock = lock
        self.buckets = tuple(sorted(buckets))
        # counts[i] = observations <= buckets[i]; counts[-1] = overflow
        self._counts = [0] * (len(self.buckets) + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        v = float(value)
        idx = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self._counts[idx] += 1
            self.sum += v
            self.count += 1

    def bucket_counts(self) -> Dict[str, int]:
        """Cumulative counts keyed by upper bound (Prometheus ``le``)."""
        out: Dict[str, int] = {}
        running = 0
        for bound, n in zip(self.buckets, self._counts):
            running += n
            out[f"{bound:g}"] = running
        out["+Inf"] = running + self._counts[-1]
        return out


class MetricsRegistry:
    """Registry of named metrics; get-or-create per (name, labels).

    ``flush_step`` appends one JSONL snapshot record and (optionally)
    rewrites the Prometheus textfile atomically. Thread-safe: the span
    recorder observes from watchdog/async-writer threads while the train
    loop flushes.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[Tuple[str, LabelKey], object] = {}
        self._metrics_path: Optional[str] = None
        self._textfile_path: Optional[str] = None
        # cardinality guard state: distinct series per metric name, and
        # which names already warned (once per name, not per call)
        self._series_per_name: Dict[str, int] = {}
        self._overflow_warned: set = set()

    def configure(self, *, metrics_path: Optional[str] = None,
                  textfile_path: Optional[str] = None) -> None:
        """Pin explicit sink paths (otherwise ``flush_step`` falls back to
        the logger's resolved metrics path)."""
        if metrics_path is not None:
            self._metrics_path = metrics_path
        if textfile_path is not None:
            self._textfile_path = textfile_path

    def _get(self, cls, name: str, labels, **kwargs):
        key = (name, _label_key(labels))
        warn_overflow = False
        try:
            with self._lock:
                existing = self._metrics.get(key)
                if existing is None and key[1] \
                        and key[1] != OVERFLOW_LABELS \
                        and self._series_per_name.get(name, 0) \
                        >= MAX_SERIES_PER_METRIC:
                    # cap hit: this NEW label set folds into the shared
                    # overflow series instead of minting another one
                    if name not in self._overflow_warned:
                        self._overflow_warned.add(name)
                        warn_overflow = True
                    key = (name, OVERFLOW_LABELS)
                    existing = self._metrics.get(key)
                if existing is not None:
                    if not isinstance(existing, cls):
                        raise TypeError(
                            f"metric {name!r} already registered as "
                            f"{existing.kind}, requested {cls.kind}"
                        )
                    return existing
                metric = cls(name, key[1], self._lock, **kwargs)
                self._metrics[key] = metric
                self._series_per_name[name] = \
                    self._series_per_name.get(name, 0) + 1
                return metric
        finally:
            if warn_overflow:
                # outside the lock: the logger does I/O, and telemetry
                # must never stall a concurrent observe()
                from ..logging.logger import logger

                logger.warning(
                    f"metric {name!r} exceeded {MAX_SERIES_PER_METRIC} "
                    "distinct label sets — folding further series into "
                    "__overflow__ (an unbounded value is leaking into a "
                    "label; fix the call site)"
                )

    def counter(self, name: str, labels: Optional[Mapping] = None) -> Counter:
        return self._get(Counter, name, labels)

    def gauge(self, name: str, labels: Optional[Mapping] = None) -> Gauge:
        return self._get(Gauge, name, labels)

    def histogram(self, name: str, labels: Optional[Mapping] = None,
                  buckets: Optional[Tuple[float, ...]] = None) -> Histogram:
        kwargs = {"buckets": buckets} if buckets else {}
        return self._get(Histogram, name, labels, **kwargs)

    # ------------------------------------------------------------ export
    def snapshot(self) -> Dict[str, Dict]:
        """JSON-ready view: ``{"counters": {...}, "gauges": {...},
        "histograms": {name: {"sum":, "count":, "buckets": {...}}}}``."""
        counters: Dict[str, float] = {}
        gauges: Dict[str, float] = {}
        histograms: Dict[str, Dict] = {}
        # hold the lock across the reads, not just the item copy: a
        # histogram observed from the async-writer thread mid-snapshot
        # must not render sum/count/buckets that disagree
        with self._lock:
            items = sorted(self._metrics.items(), key=lambda kv: kv[0])
            for (name, labels), m in items:
                rendered = _render_name(name, labels)
                if isinstance(m, Counter):
                    counters[rendered] = m.value
                elif isinstance(m, Gauge):
                    if m.value is not None:
                        gauges[rendered] = m.value
                elif isinstance(m, Histogram):
                    histograms[rendered] = {
                        "sum": m.sum, "count": m.count,
                        "buckets": m.bucket_counts(),
                    }
        return {"counters": counters, "gauges": gauges,
                "histograms": histograms}

    def render_textfile(self) -> str:
        """Prometheus exposition text (textfile-collector compatible)."""
        lines: List[str] = []
        typed: set = set()
        # same locking rule as snapshot(): reads stay consistent with
        # concurrent observers
        with self._lock:
            items = sorted(self._metrics.items(), key=lambda kv: kv[0])
            for (name, labels), m in items:
                if name not in typed:
                    lines.append(f"# TYPE {name} {m.kind}")
                    typed.add(name)
                if isinstance(m, Histogram):
                    for le, n in m.bucket_counts().items():
                        blabels = labels + (("le", le),)
                        lines.append(f"{_prom_name(name + '_bucket', blabels)} {n}")
                    lines.append(f"{_prom_name(name + '_sum', labels)} {m.sum:g}")
                    lines.append(f"{_prom_name(name + '_count', labels)} {m.count}")
                else:
                    v = m.value
                    if v is None:
                        continue
                    rendered = "NaN" if isinstance(v, float) and math.isnan(v) else f"{v:g}"
                    lines.append(f"{_prom_name(name, labels)} {rendered}")
        return "\n".join(lines) + "\n"

    def write_textfile(self, path: Path | str) -> None:
        """Atomic replace: scrapers must never read a torn render."""
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        tmp = path.with_name(f".{path.name}.tmp{os.getpid()}")
        # stays raw: obs cannot import resilience's retry_io without
        # inverting the layering, and telemetry is best-effort by
        # contract — a retry loop in the scrape render would stall the
        # step it is measuring (flush callers catch and warn instead)
        tmp.write_text(self.render_textfile())  # sta: disable=STA011
        os.replace(tmp, path)  # sta: disable=STA011

    # ------------------------------------------------------------- flush
    def flush_step(self, step: int) -> None:
        """Append one snapshot record to the metrics JSONL sink.

        The path resolves to the explicitly configured one, else the
        logger's metrics path (``SCALING_TPU_METRICS_PATH`` env /
        ``LoggerConfig``); with neither configured this is a no-op, so
        always-on instrumentation costs nothing on unconfigured runs."""
        path = self._metrics_path
        if path is None:
            from ..logging import logger

            path = logger.metrics_path()
        if path is None:
            return
        rec = {
            "kind": "registry", "step": step, "ts": time.time(),
            "host": host_id(), **self.snapshot(),
        }
        Path(path).parent.mkdir(parents=True, exist_ok=True)
        append_jsonl_line(path, json.dumps(_json_safe(rec), sort_keys=True))
        textfile = self._textfile_path or os.environ.get(
            "SCALING_TPU_METRICS_TEXTFILE"
        )
        if textfile:
            self.write_textfile(textfile)

    def reset(self) -> None:
        """Drop every metric (tests; a fresh process never needs this)."""
        with self._lock:
            self._metrics.clear()
            self._series_per_name.clear()
            self._overflow_warned.clear()


def _json_safe(obj):
    """Map non-finite floats to None so the record is valid JSON for
    every parser (bare ``NaN`` tokens are a Python-only dialect; a NaN
    gauge during the incident the telemetry exists to diagnose must not
    corrupt the file). The textfile render keeps its own NaN handling."""
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else None
    if isinstance(obj, dict):
        return {k: _json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_safe(v) for v in obj]
    return obj


def host_id() -> int:
    """This process's host id: the supervisor's env var when present,
    else the logger's rank — the SAME fallback ``log_metrics`` stamps on
    step records, so the two record kinds in one metrics file can never
    disagree about who wrote them."""
    from ..logging.logger import _host_id, logger

    return _host_id(logger._rank)


_default = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-wide default registry every subsystem records into."""
    return _default
