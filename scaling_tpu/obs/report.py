"""Run-dir analyzer: events + metrics JSONL -> health report.

``python -m scaling_tpu.obs report <run_dir>`` walks every ``*.jsonl``
under the run directory (however the launcher named them — per-host
``host0_events.jsonl``, ``metrics_rank_0.jsonl``, one shared file —
records classify themselves), and renders:

- step-time percentiles per host + straggler verdict;
- MFU / achieved-TFLOPs / throughput summary;
- barrier-wait attribution per barrier and per host (the host that
  waits ~0 arrived last — it made everyone else wait), the offline
  echo of the live ``_on_step_stall`` straggler table;
- checkpoint commit latency breakdown per step
  (stage / manifest / rename / commit-barrier / latest);
- the restart / preemption timeline from the supervision events;
- optional CI-style gates (``--assert-mfu``, ``--assert-step-time``).

Pure stdlib + deterministic rendering: the golden-report test pins the
exact output for a canned run dir, so keep formatting changes deliberate.
"""

from __future__ import annotations

import dataclasses
import json
import math
from collections import defaultdict
from pathlib import Path
from typing import Dict, List, Optional, Tuple

# lifecycle events the timeline renders (everything except the
# high-frequency span records); unknown event names still render — a new
# subsystem's events must not be invisible to post-mortems
SPAN_EVENT = "span"

CKPT_PHASES = (
    "trainer.save", "ckpt.stage", "ckpt.manifest", "ckpt.rename",
    "ckpt.commit_barrier", "ckpt.latest",
)


@dataclasses.dataclass
class RunData:
    events: List[dict]
    steps: List[dict]
    registry: List[dict]
    files: int
    bad_lines: int

    @property
    def spans(self) -> List[dict]:
        return [e for e in self.events if e.get("event") == SPAN_EVENT]

    @property
    def lifecycle(self) -> List[dict]:
        return [e for e in self.events if e.get("event") != SPAN_EVENT]


def load_run_dir(run_dir: Path | str, recursive: bool = True) -> RunData:
    """Parse every JSONL under ``run_dir``; tolerant of torn tails (a
    SIGKILLed host's last line) and foreign files — unparseable lines
    are counted, never fatal. ``recursive=False`` reads only the
    directory's own files (callers that walk subdirectories themselves
    would otherwise double-count them)."""
    run_dir = Path(run_dir)
    events: List[dict] = []
    steps: List[dict] = []
    registry: List[dict] = []
    files = 0
    bad = 0
    glob = run_dir.rglob if recursive else run_dir.glob
    for path in sorted(glob("*.jsonl")):
        files += 1
        try:
            # stays raw: the report reader is already fault-tolerant by
            # design — an unreadable file counts as bad and the report
            # proceeds (torn tails are data, not errors, post-crash)
            text = path.read_text()
        except OSError:
            bad += 1
            continue
        for line in text.splitlines():
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                bad += 1
                continue
            if not isinstance(rec, dict):
                bad += 1
                continue
            if "event" in rec:
                events.append(rec)
            elif rec.get("kind") == "step":
                steps.append(rec)
            elif rec.get("kind") == "registry":
                registry.append(rec)
            else:
                bad += 1
    events.sort(key=lambda r: r.get("ts", 0.0))
    steps.sort(key=lambda r: (r.get("step", 0), r.get("host", 0)))
    return RunData(events=events, steps=steps, registry=registry,
                   files=files, bad_lines=bad)


# ------------------------------------------------------------------ math
def percentile(values: List[float], q: float) -> float:
    """Nearest-rank percentile (deterministic, no interpolation)."""
    assert values
    s = sorted(values)
    idx = max(0, min(len(s) - 1, math.ceil(q / 100.0 * len(s)) - 1))
    return s[idx]


def _fmt_s(v: float) -> str:
    return f"{v:.3f}s"


# -------------------------------------------------------------- sections
def step_time_section(data: RunData) -> List[str]:
    by_host: Dict[int, List[float]] = defaultdict(list)
    for rec in data.steps:
        dur = rec.get("metrics", {}).get("step_duration")
        if dur is not None:
            by_host[int(rec.get("host", 0))].append(float(dur))
    lines = ["== step time =="]
    if not by_host:
        lines.append("  (no step records)")
        return lines
    p50s: Dict[int, float] = {}
    for host in sorted(by_host):
        vals = by_host[host]
        p50s[host] = percentile(vals, 50)
        lines.append(
            f"  host {host}: n={len(vals)} p50={_fmt_s(percentile(vals, 50))} "
            f"p90={_fmt_s(percentile(vals, 90))} "
            f"p99={_fmt_s(percentile(vals, 99))} max={_fmt_s(max(vals))}"
        )
    if len(p50s) > 1:
        fastest = min(p50s.values())
        slowest_host = max(p50s, key=lambda h: p50s[h])
        ratio = p50s[slowest_host] / fastest if fastest > 0 else float("inf")
        if ratio > 1.2:
            lines.append(
                f"  straggler: host {slowest_host} "
                f"(p50 {ratio:.2f}x the fastest host)"
            )
        else:
            lines.append(f"  stragglers: none (p50 spread {ratio:.2f}x)")
    return lines


def mfu_section(data: RunData) -> Tuple[List[str], Dict[str, float]]:
    """Render + return the summary stats the gates check."""
    mfus: List[float] = []
    tflops: List[float] = []
    tokens: List[float] = []
    step_times: List[float] = []
    for rec in data.steps:
        m = rec.get("metrics", {})
        v = m.get("mfu", m.get("palm_mfu"))
        if v is not None:
            mfus.append(float(v))
        if m.get("achieved_tflops") is not None:
            tflops.append(float(m["achieved_tflops"]))
        if m.get("tokens_per_second") is not None:
            tokens.append(float(m["tokens_per_second"]))
        if m.get("step_duration") is not None:
            step_times.append(float(m["step_duration"]))
    lines = ["== mfu / throughput =="]
    stats: Dict[str, float] = {}
    if step_times:
        stats["step_time_p50"] = percentile(step_times, 50)
    if mfus:
        stats["mfu_mean"] = sum(mfus) / len(mfus)
        lines.append(
            f"  mfu: mean={stats['mfu_mean']:.4f} "
            f"p50={percentile(mfus, 50):.4f} min={min(mfus):.4f} "
            f"max={max(mfus):.4f}"
        )
    else:
        lines.append("  mfu: (not recorded — configure trainer.telemetry)")
    if tflops:
        lines.append(
            f"  achieved_tflops: mean={sum(tflops) / len(tflops):.1f} "
            f"max={max(tflops):.1f}"
        )
    if tokens:
        lines.append(
            f"  tokens_per_second: mean={sum(tokens) / len(tokens):.0f} "
            f"max={max(tokens):.0f}"
        )
    return lines, stats


def _epoch_key(rec: dict) -> Tuple:
    """Attribution key prefix: a relaunched pod re-waits the same barrier
    and re-saves the same step in a later supervisor epoch, and merging
    those incidents would corrupt the arrived-last verdict. Spans without
    an epoch (single-epoch runs, old files) sort first unchanged."""
    epoch = rec.get("epoch")
    return (epoch is not None, epoch if epoch is not None else 0)


def _epoch_label(key: Tuple) -> str:
    has_epoch, epoch = key
    return f"epoch {epoch} " if has_epoch else ""


def barrier_section(data: RunData) -> List[str]:
    """Per-barrier wait attribution (per supervisor epoch). The LAST
    host to arrive waits ~0 and is the one every peer waited on;
    per-host blame aggregates the time it cost its peers."""
    waits: Dict[Tuple, Dict[int, float]] = defaultdict(lambda: defaultdict(float))
    ok_waits: Dict[Tuple, Dict[int, float]] = defaultdict(lambda: defaultdict(float))
    failed: Dict[Tuple, str] = {}
    for sp in data.spans:
        if sp.get("span") != "barrier.wait":
            continue
        key = _epoch_key(sp) + (str(sp.get("barrier", "?")),)
        host = int(sp.get("host", 0))
        waits[key][host] += float(sp.get("dur_s", 0.0))
        if sp.get("ok", True):
            ok_waits[key][host] += float(sp.get("dur_s", 0.0))
        else:
            failed[key] = str(sp.get("error", "error"))
    lines = ["== barrier wait attribution =="]
    if not waits:
        lines.append("  (no barrier spans)")
        return lines
    blame: Dict[int, float] = defaultdict(float)
    blame_barriers: Dict[int, int] = defaultdict(int)
    for key in sorted(waits):
        per_host = waits[key]
        label = _epoch_label(key[:2]) + key[2]
        rendered = " ".join(
            f"host{h}={_fmt_s(per_host[h])}" for h in sorted(per_host)
        )
        suffix = ""
        # the arrived-last verdict only makes sense over SUCCESSFUL
        # waits: when the barrier failed, the culprit is whoever never
        # produced a span (the dead/hung host) — blaming the survivor
        # whose timeout was marginally shorter misattributes the cost
        succeeded = ok_waits.get(key, {})
        if len(succeeded) > 1:
            last = min(succeeded, key=lambda h: succeeded[h])
            cost = sum(v for h, v in succeeded.items() if h != last)
            blame[last] += cost
            blame_barriers[last] += 1
            suffix = f" -> host {last} arrived last"
        if key in failed:
            suffix += f" [FAILED: {failed[key]}]"
        lines.append(f"  {label}: {rendered}{suffix}")
    for host in sorted(blame):
        lines.append(
            f"  blame: host {host} kept peers waiting "
            f"{_fmt_s(blame[host])} across {blame_barriers[host]} barrier(s)"
        )
    return lines


def checkpoint_section(data: RunData) -> List[str]:
    by_step: Dict[Tuple, Dict[str, float]] = defaultdict(dict)
    for sp in data.spans:
        name = sp.get("span")
        if name not in CKPT_PHASES or "step" not in sp:
            continue
        # per (epoch, step): a relaunched pod re-saves the same step
        key = _epoch_key(sp) + (int(sp["step"]),)
        # multihost: keep the slowest host's phase time (the pod-wide cost)
        prev = by_step[key].get(name, 0.0)
        by_step[key][name] = max(prev, float(sp.get("dur_s", 0.0)))
    lines = ["== checkpoint commits =="]
    if not by_step:
        lines.append("  (no checkpoint spans)")
        return lines
    for key in sorted(by_step):
        phases = by_step[key]
        parts = [
            f"{phase.split('.', 1)[-1]}={_fmt_s(phases[phase])}"
            for phase in CKPT_PHASES if phase in phases
        ]
        lines.append(
            f"  {_epoch_label(key[:2])}step {key[2]}: " + " ".join(parts)
        )
    return lines


def step_span_sums(spans: List[dict], names: Tuple[str, ...],
                   drop_earliest_step: bool = True
                   ) -> Dict[int, Dict[int, Dict[str, float]]]:
    """Per-host, per-step summed durations of the given span names —
    the ONE aggregation both the report's pipeline section and the
    schedule simulator's profile calibration
    (``parallel.pipeline_schedule._durations_from_run_dir``) read, so
    the compile-step-drop policy cannot diverge between them. With
    ``drop_earliest_step`` (default), each host's earliest step is
    removed when later steps exist — it carries the jit compile."""
    by_host: Dict[int, Dict[int, Dict[str, float]]] = defaultdict(dict)
    for sp in spans:
        name = sp.get("span")
        if name not in names or "step" not in sp:
            continue
        rec = by_host[int(sp.get("host", 0))].setdefault(int(sp["step"]), {})
        rec[name] = rec.get(name, 0.0) + float(sp.get("dur_s", 0.0))
    if drop_earliest_step:
        for host, steps in by_host.items():
            if len(steps) > 1:
                del steps[min(steps)]
    return dict(by_host)


def step_compute_samples(
    by_host: Dict[int, Dict[int, Dict[str, float]]]
) -> List[float]:
    """Per-host AMORTIZED per-step compute seconds from fwdbwd/sync sums.

    Under ``log_interval > 1`` the trainer skips the device sync on most
    steps: their records carry only the ~ms ``step.fwdbwd`` dispatch,
    and the next synced step's ``step.sync`` drains the whole backlog.
    A per-step percentile would read dispatch latency as compute, so the
    sample is per host: (sum of all kept fwdbwd + sync) / kept steps —
    the same amortization the trainer's own ``step_duration`` uses."""
    samples: List[float] = []
    for steps in by_host.values():
        if not steps:
            continue
        total = sum(sum(rec.get(n, 0.0) for n in ("step.fwdbwd", "step.sync"))
                    for rec in steps.values())
        samples.append(total / len(steps))
    return samples


def _pipeline_tick_counts(pp: int, virtual: int, slices: int,
                          gas: int) -> Tuple[str, int, int]:
    """(schedule label, work ticks, total ticks) of the spatial executor
    (parallel/pipeline.py) — closed-form, mirroring the schedule DSL's
    simulator without importing jax-bearing packages here."""
    if virtual > 1:
        return f"interleaved(v={virtual})", gas * virtual, gas * virtual + pp - 1
    if slices > 1:
        return f"token-slice(S={slices})", gas * slices, gas * slices + pp - 1
    return "fill-drain", gas, gas + pp - 1


def pipeline_section(data: RunData) -> List[str]:
    """Pipeline bubble attribution: the schedule shape comes from the
    trainer's ``pipeline-config`` event; the measured step compute from
    the ``step.fwdbwd`` (dispatch) + ``step.sync`` (drain) spans. The
    schedule's tick counts attribute that measured time into busy vs
    fill/drain-idle seconds, next to the same attribution for the naive
    fill-drain schedule on the same shape. Rendered only for pipelined
    runs (no event -> no section, so single-path run dirs are
    unchanged)."""
    cfgs = [e for e in data.lifecycle if e.get("event") == "pipeline-config"]
    if not cfgs:
        return []
    cfg = cfgs[-1]
    pp = int(cfg.get("pp", 1))
    virtual = int(cfg.get("virtual", 1))
    slices = int(cfg.get("token_slices", 1))
    gas = int(cfg.get("gas", 1))
    label, work, total = _pipeline_tick_counts(pp, virtual, slices, gas)
    bubble = (total - work) / total if total else 0.0
    _, fd_work, fd_total = _pipeline_tick_counts(pp, 1, 1, gas)
    fd_bubble = (fd_total - fd_work) / fd_total if fd_total else 0.0
    lines = ["== pipeline =="]
    lines.append(
        f"  schedule: {label} pp={pp} gas={gas} "
        f"({work} work ticks / {total} total per pass)"
    )
    lines.append(
        f"  predicted bubble: {bubble:.1%} "
        f"(fill-drain on this shape: {fd_bubble:.1%})"
    )
    by_host = step_span_sums(data.spans, ("step.fwdbwd", "step.sync"))
    samples = step_compute_samples(by_host)
    if not samples:
        lines.append("  measured: (no step.fwdbwd/step.sync spans)")
        return lines
    p50 = percentile(samples, 50)
    n_steps = sum(len(steps) for steps in by_host.values())
    idle_s = p50 * bubble
    lines.append(
        f"  measured step compute (fwdbwd+sync amortized over {n_steps} "
        f"steps): {_fmt_s(p50)}"
    )
    lines.append(
        f"  attributed: per-tick {_fmt_s(p50 / total)}, "
        f"fill/drain idle {_fmt_s(idle_s)}/step ({bubble:.1%} of compute)"
    )
    return lines


def tuner_section(data: RunData) -> Tuple[List[str], Dict[str, float]]:
    """Prediction-vs-measured for the auto-sharding tuner (docs/TUNING.md
    "calibration loop"): the ``tuner-prediction`` event carries the cost
    model's predicted step seconds for the layout this run executes; the
    measured side is the span-measured step compute (fwdbwd+sync — the
    window the cost model actually prices), falling back to the
    ``step_duration`` metric when the run recorded no spans. The relative
    calibration error is returned for the ``--assert-tuner-calibration``
    gate. Rendered only when a prediction event exists, so run dirs from
    untuned launches (and the committed golden reports) are unchanged."""
    preds = [
        e for e in data.lifecycle if e.get("event") == "tuner-prediction"
    ]
    if not preds:
        return [], {}
    pred = preds[-1]
    lines = ["== tuner =="]
    stats: Dict[str, float] = {}
    label = pred.get("label", "?")
    source = pred.get("source", "?")
    try:
        predicted = float(pred["predicted_step_s"])
    except (KeyError, TypeError, ValueError):
        lines.append(
            f"  prediction event for {label} carries no predicted_step_s"
        )
        return lines, stats
    stats["tuner_predicted_step_s"] = predicted
    lines.append(
        f"  layout {label}: predicted {_fmt_s(predicted)}/step "
        f"(calibration: {source})"
    )
    samples = step_compute_samples(
        step_span_sums(data.spans, ("step.fwdbwd", "step.sync"))
    )
    if samples:
        measured = percentile(samples, 50)
        measured_how = "span-measured compute (fwdbwd+sync p50)"
    else:
        durs = [
            float(r["metrics"]["step_duration"]) for r in data.steps
            if r.get("metrics", {}).get("step_duration") is not None
        ]
        if not durs:
            lines.append("  measured: (no spans or step_duration records)")
            return lines, stats
        measured = percentile(durs, 50)
        measured_how = "step_duration p50 (no spans in this run dir)"
    stats["tuner_measured_step_s"] = measured
    err = (predicted - measured) / measured if measured > 0 else math.inf
    stats["tuner_calibration_error"] = err
    lines.append(f"  measured: {_fmt_s(measured)}/step [{measured_how}]")
    lines.append(
        f"  calibration error: {err:+.1%} (predicted vs measured; the cost "
        f"model {'over' if err > 0 else 'under'}-prices this layout)"
    )
    return lines, stats


def serving_section(data: RunData) -> Tuple[List[str], Dict[str, float]]:
    """Serving-engine health (docs/SERVING.md): per-request
    ``serve-request`` events carry TTFT / ITL / preemption counts, the
    final ``serve-summary`` carries wall-clock throughput. Percentiles
    are computed over the per-request events (exact, not histogram
    buckets); throughput comes from the summary event when present and
    falls back to tokens/wall derived from the request events. Rendered
    only when serve events exist, so training run dirs (and the
    committed golden reports) are unchanged. The returned stats feed the
    ``--assert-serve-throughput`` / ``--assert-ttft`` gates."""
    reqs = [e for e in data.lifecycle if e.get("event") == "serve-request"]
    summaries = [
        e for e in data.lifecycle if e.get("event") == "serve-summary"
    ]
    if not reqs and not summaries:
        return [], {}
    lines = ["== serving =="]
    stats: Dict[str, float] = {}
    ttfts = sorted(
        float(e["ttft_s"]) for e in reqs if e.get("ttft_s") is not None
    )
    if summaries:
        s = summaries[-1]
        try:
            stats["serve_tokens_per_s"] = float(s["tokens_per_s"])
            lines.append(
                f"  throughput: {stats['serve_tokens_per_s']:.1f} output "
                f"tokens/s ({int(s.get('output_tokens', 0))} tokens over "
                f"{float(s.get('wall_s', 0.0)):.3f}s, "
                f"{int(s.get('requests', 0))} request(s))"
            )
        except (KeyError, TypeError, ValueError):
            lines.append("  throughput: (summary event carries no "
                         "tokens_per_s)")
        lines.append(
            f"  engine: ticks={int(s.get('ticks', 0))} "
            f"preemptions={int(s.get('preemptions', 0))} "
            f"prefill_compiles={int(s.get('prefill_compiles', 0))}"
        )
        # raw-speed rails (docs/SERVING.md "Raw speed"): shared-prefix
        # reuse and self-drafting speculation report their win here —
        # the artifacts a prefix/spec perf claim is judged on
        hit = s.get("prefix_hit_tokens")
        if hit:
            stats["serve_prefix_hit_rate"] = float(
                s.get("prefix_hit_rate") or 0.0
            )
            lines.append(
                f"  prefix cache: {int(hit)} tokens hit, "
                f"{int(s.get('prefilled_tokens', 0))} prefilled "
                f"({int(s.get('prompt_tokens', 0))} prompt tokens "
                f"submitted; hit rate "
                f"{stats['serve_prefix_hit_rate']:.1%})"
            )
        if s.get("spec_accept_rate") is not None:
            stats["serve_spec_accept_rate"] = float(s["spec_accept_rate"])
            lines.append(
                f"  speculation: accepted "
                f"{int(s.get('spec_accepted_tokens', 0))}/"
                f"{int(s.get('spec_drafted_tokens', 0))} drafts "
                f"(accept rate {stats['serve_spec_accept_rate']:.1%})"
            )
        # resilience rails (docs/SERVING.md "Resilience"): overload
        # sheds, deadline timeouts, supervised restarts, drain state —
        # the artifacts the --assert-max-shed-rate /
        # --assert-max-serve-timeouts gates read. Only rendered when
        # the summary carries the fields, so pre-resilience run dirs
        # (and committed golden reports) are unchanged.
        if "requests_shed" in s or "requests_timeout" in s:
            shed = int(s.get("requests_shed", 0))
            timeouts = int(s.get("requests_timeout", 0))
            rate = float(s.get("shed_rate") or 0.0)
            # the supervisor logs serve-restart per relaunch — even one
            # that crashed before journaling anything (a serve-resume
            # is only emitted once a replay has content); a manual
            # `--resume` run has no supervisor, so fall back to its
            # serve-resume events
            restarts = sum(
                1 for e in data.lifecycle
                if e.get("event") == "serve-restart"
            ) or sum(
                1 for e in data.lifecycle if e.get("event") == "serve-resume"
            )
            stats["serve_shed_rate"] = rate
            stats["serve_timeouts"] = float(timeouts)
            stats["serve_restarts"] = float(restarts)
            line = (f"  resilience: shed={shed} (rate {rate:.1%}) "
                    f"timeouts={timeouts} restarts={restarts}")
            if s.get("drained"):
                line += (f" [drained; {int(s.get('unsubmitted', 0))} "
                         "unsubmitted]")
            lines.append(line)
        # fleet rows (docs/SERVING.md "The fleet"): per-replica load /
        # completion split plus the router's dispatch-policy stats —
        # what the --assert-max-replica-skew gate reads. Only rendered
        # when the summary carries replica_stats, so single-engine run
        # dirs (and committed goldens) are unchanged.
        reps = s.get("replica_stats")
        if isinstance(reps, list) and reps:
            router = s.get("router") or {}
            counts = [int(r.get("requests", 0)) for r in reps]
            if min(counts) > 0:
                skew = max(counts) / min(counts)
            elif max(counts) > 0:
                skew = math.inf
            else:
                skew = 1.0
            stats["serve_replicas"] = float(len(reps))
            stats["serve_replica_skew"] = skew
            affinity = int(router.get("affinity_dispatches", 0))
            dispatches = int(router.get("dispatches", 0))
            stats["serve_affinity_hit_rate"] = float(
                router.get("affinity_hit_rate") or 0.0
            )
            lines.append(
                f"  fleet: replicas={len(reps)} dispatches={dispatches} "
                f"affinity_hits={affinity} "
                f"({stats['serve_affinity_hit_rate']:.1%}) "
                f"retries_elsewhere={int(router.get('retries_elsewhere', 0))}"
                f" rejected={int(router.get('rejected', 0))} "
                f"skew={'inf' if skew == math.inf else format(skew, '.2f')}"
            )
            for r in reps:
                row = (
                    f"    replica {r.get('replica')}: "
                    f"requests={int(r.get('requests', 0))} "
                    f"tokens={int(r.get('output_tokens', 0))} "
                    f"dispatches={int(r.get('dispatches', 0))} "
                    f"timeouts={int(r.get('timeouts', 0))} "
                    f"pressure={float(r.get('pool_pressure', 0.0)):.2f}"
                )
                if r.get("host") is not None:
                    row += f" host={r['host']}"
                if not r.get("alive", True):
                    row += " [FAILED]"
                lines.append(row)
        # host-mode attribution (docs/SERVING.md "Host mode"): which
        # hosts the placement plan expected vs which actually published
        # a rendezvous record. A planned host that never reported is a
        # machine the fleet silently ran without — the
        # --assert-max-replica-restarts gate fails on it loudly.
        hosts_planned = s.get("fleet_hosts")
        if isinstance(hosts_planned, list) and hosts_planned:
            reported = {int(h) for h in (s.get("hosts_reported") or [])}
            missing = [h for h in hosts_planned if int(h) not in reported]
            stats["serve_fleet_hosts"] = float(len(hosts_planned))
            stats["serve_hosts_missing"] = float(len(missing))
            line = (f"  hosts: planned={hosts_planned} "
                    f"reported={sorted(reported)} "
                    f"submit_dups={int(s.get('submit_dups', 0))} "
                    f"rpc_retries={int(s.get('rpc_retries', 0))}")
            if missing:
                line += f" MISSING={missing}"
            lines.append(line)
        if s.get("spec_k_sweep"):
            # the --spec-k-sweep arm: every draft length's measured
            # tokens/s + accept rate, best-k first-class
            lines.append(
                f"  spec-k sweep: best k={s.get('spec_k_best')} of "
                + ", ".join(
                    f"k={row.get('spec_k')}:"
                    f"{float(row.get('tokens_per_s', 0.0)):.1f}t/s"
                    for row in s["spec_k_sweep"]
                )
            )
    elif reqs:
        # crashed/partial run: derive throughput from what finished
        tokens = sum(int(e.get("output_tokens", 0)) for e in reqs)
        ts = [float(e["ts"]) for e in reqs if e.get("ts") is not None]
        wall = max(ts) - min(ts) if len(ts) > 1 else 0.0
        if wall > 0:
            stats["serve_tokens_per_s"] = tokens / wall
            lines.append(
                f"  throughput: {stats['serve_tokens_per_s']:.1f} output "
                f"tokens/s ({tokens} tokens, derived from "
                f"{len(reqs)} request events — no serve-summary)"
            )
        else:
            lines.append(
                f"  throughput: ({tokens} tokens over {len(reqs)} "
                "request(s); too few events to derive a rate)"
            )
    # process-fleet supervision timeline (docs/SERVING.md "Process
    # mode"): every replica lifecycle event — readiness, deaths,
    # relaunches, autoscale spawns/drains, give-ups — in wall order,
    # plus the restart tally the --assert-max-replica-restarts gate
    # reads. Rendered only when replica lifecycle events exist, so
    # non-fleet run dirs (and committed goldens) are unchanged.
    fleet_events = sorted(
        (
            e for e in data.lifecycle
            if str(e.get("event", "")).startswith("serve-replica-")
            and e.get("ts") is not None
        ),
        key=lambda e: float(e["ts"]),
    )
    if fleet_events:
        def count(name):
            return sum(1 for e in fleet_events if e["event"] == name)

        restarts = count("serve-replica-restart")
        stats["serve_replica_restarts"] = float(restarts)
        stats["serve_replica_spawns"] = float(count("serve-replica-spawn"))
        stats["serve_replica_drains"] = float(count("serve-replica-drain"))
        lines.append(
            f"  fleet timeline: restarts={restarts} "
            f"spawns={int(stats['serve_replica_spawns'])} "
            f"drains={int(stats['serve_replica_drains'])} "
            f"dead={count('serve-replica-dead')} "
            f"hung={count('serve-replica-hung')} "
            f"gave_up={count('serve-replica-give-up')}"
        )
        # per-host attribution (host mode): where the deaths and
        # relaunches actually happened — a whole-host failure reads as
        # one host absorbing every dead/restart while the others stay
        # clean
        by_host: dict = {}
        for e in fleet_events:
            if e.get("host") is not None:
                by_host.setdefault(int(e["host"]), []).append(e["event"])
        if by_host:
            lines.append("  fleet timeline by host: " + "; ".join(
                f"host {h}: "
                f"ready={by_host[h].count('serve-replica-ready')} "
                f"dead={by_host[h].count('serve-replica-dead')} "
                f"restarts={by_host[h].count('serve-replica-restart')}"
                for h in sorted(by_host)
            ))
        t0 = float(fleet_events[0]["ts"])
        shown = fleet_events[:30]
        for e in shown:
            what = e["event"][len("serve-replica-"):]
            who = e.get("replica")
            detail = " ".join(
                f"{k}={e[k]}" for k in (
                    "host", "rc", "attempt", "budget", "backoff_s",
                    "recovered", "redispatch", "redispatched", "stranded",
                    "attempts", "hb_age_s", "loop_age_s", "restarts",
                )
                if e.get(k) is not None
            )
            lines.append(
                f"    +{float(e['ts']) - t0:7.3f}s "
                + (f"replica {who}" if who is not None else "fleet")
                + f" {what}" + (f" ({detail})" if detail else "")
            )
        if len(fleet_events) > len(shown):
            lines.append(
                f"    ... {len(fleet_events) - len(shown)} more event(s)"
            )
    if ttfts:
        stats["serve_ttft_p50_s"] = percentile(ttfts, 50)
        stats["serve_ttft_p99_s"] = percentile(ttfts, 99)
        lines.append(
            f"  ttft: p50={_fmt_s(stats['serve_ttft_p50_s'])} "
            f"p99={_fmt_s(stats['serve_ttft_p99_s'])} "
            f"max={_fmt_s(max(ttfts))} (n={len(ttfts)})"
        )
    if reqs:
        itls = sorted(
            float(e["itl_mean_s"]) for e in reqs
            if e.get("itl_mean_s") is not None
        )
        if itls:
            lines.append(
                f"  itl (per-request mean): p50={_fmt_s(percentile(itls, 50))} "
                f"p99={_fmt_s(percentile(itls, 99))}"
            )
        preempted = sum(1 for e in reqs if int(e.get("preemptions", 0)) > 0)
        if preempted:
            lines.append(
                f"  preempted-and-resumed: {preempted} of {len(reqs)} "
                "request(s)"
            )
    # distributed-trace summary (docs/OBSERVABILITY.md "Tracing"): one
    # line when the run stamped traces — coverage plus the phase that
    # dominates the most traces' critical paths, pointing at
    # ``obs trace`` for the full timelines. Absent when no request
    # carries a trace, so pre-tracing run dirs (and the committed
    # golden reports) stay byte-identical.
    if any("trace" in e for e in reqs):
        from .trace import PHASES, analyze  # local: trace imports report

        t = analyze(data)
        cov = t["coverage"]
        if cov is not None:
            stats["serve_trace_coverage"] = cov
        counts = t["critical_path_counts"]
        top = max(PHASES, key=lambda p: (counts.get(p, 0),
                                         -PHASES.index(p)))
        lines.append(
            f"  traces: {t['traces']} reconstructed, coverage "
            + (f"{cov:.1%}" if cov is not None else "n/a")
            + f", top critical-path phase: {top} "
            f"({counts.get(top, 0)} trace(s)) — see `obs trace`"
        )
    # tick-time attribution: where the engine's device time actually went
    # (serve.prefill_chunk = chunked prefill, serve.prefill = whole-prompt
    # buckets, serve.decode = the per-tick decode step). This is the rail
    # a prefill/decode-mix perf claim is judged on — a chunking change
    # that quietly starves decode shows up here, not in averages.
    phases = (
        ("mixed", "serve.mixed"),
        ("decode", "serve.decode"),
        ("prefill-chunk", "serve.prefill_chunk"),
        ("prefill", "serve.prefill"),
        ("draft", "serve.draft"),
    )
    sums: Dict[str, Tuple[float, int]] = {}
    for sp in data.spans:
        for label, name in phases:
            if sp.get("span") == name and sp.get("dur_s") is not None:
                total, count = sums.get(label, (0.0, 0))
                sums[label] = (total + float(sp["dur_s"]), count + 1)
    if sums:
        grand = sum(t for t, _ in sums.values())
        parts = []
        for label, _ in phases:
            if label not in sums:
                continue
            t, count = sums[label]
            share = t / grand if grand > 0 else 0.0
            stats[f"serve_{label.replace('-', '_')}_s"] = t
            parts.append(f"{label} {share:.0%} ({t:.3f}s/{count})")
        lines.append("  tick time: " + "  ".join(parts))
    return lines, stats


def world_size_transitions(data: RunData) -> List[str]:
    """World-size transitions of an elastic run, as ``old->new`` labels:
    supervisor ``downsize`` / ``upsize`` events (the replan decisions,
    both directions) and trainer ``ckpt-reshard`` events (a restore that
    actually crossed mesh shapes). Deduplicated consecutively — N hosts
    restoring the same transition is one transition."""
    out: List[str] = []
    for e in data.lifecycle:
        if e.get("event") in ("downsize", "upsize"):
            label = (f"{e.get('old_world', '?')}->{e.get('new_world', '?')}"
                     f" ({e['event']}/{e.get('source', '?')})")
        elif e.get("event") == "ckpt-reshard":
            label = (f"{e.get('saved_world', '?')}->"
                     f"{e.get('restoring_world', '?')} (reshard "
                     f"{e.get('saved', '?')} -> {e.get('restoring', '?')})")
        else:
            continue
        if not out or out[-1] != label:
            out.append(label)
    return out


def timeline_section(data: RunData) -> List[str]:
    lines = ["== restart / preemption timeline =="]
    lifecycle = data.lifecycle
    if not lifecycle:
        lines.append("  (no lifecycle events)")
        return lines
    t0 = lifecycle[0].get("ts", 0.0)
    for e in lifecycle:
        fields = {
            k: v for k, v in sorted(e.items()) if k not in ("event", "ts")
        }
        rendered = " ".join(f"{k}={v}" for k, v in fields.items())
        offset = e.get("ts", t0) - t0
        lines.append(f"  +{offset:8.1f}s {e['event']}" +
                     (f" {rendered}" if rendered else ""))
    restarts = sum(1 for e in lifecycle if e["event"] == "relaunch")
    preempts = sum(
        1 for e in lifecycle
        if e["event"] in ("preempt-broadcast", "preempt-relay")
    )
    stalls = sum(1 for e in lifecycle if e["event"] == "step-stall")
    downsizes = sum(1 for e in lifecycle if e["event"] == "downsize")
    upsizes = sum(1 for e in lifecycle if e["event"] == "upsize")
    totals = (
        f"  totals: restarts={restarts} preemptions={preempts} "
        f"stalls={stalls}"
    )
    if downsizes:
        # appended only for elastic runs so committed golden reports
        # from non-elastic runs stay byte-identical
        totals += f" downsizes={downsizes}"
    if upsizes:
        totals += f" upsizes={upsizes}"
    lines.append(totals)
    transitions = world_size_transitions(data)
    if transitions:
        lines.append("  world-size transitions: " + ", ".join(transitions))
    return lines


def render_report(data: RunData, run_dir: Path | str = "") -> str:
    hosts = sorted(
        {int(r.get("host", 0)) for r in data.steps}
        | {int(e["host"]) for e in data.events if isinstance(e.get("host"), int)}
    )
    steps = [r.get("step", 0) for r in data.steps]
    header = [
        "== run summary ==",
        f"  dir: {run_dir}",
        f"  files={data.files} events={len(data.events)} "
        f"step_records={len(data.steps)} registry_records={len(data.registry)} "
        f"unparseable_lines={data.bad_lines}",
        f"  hosts: {', '.join(map(str, hosts)) if hosts else '(none)'}",
        f"  steps: {min(steps)}..{max(steps)}" if steps else "  steps: (none)",
    ]
    mfu_lines, _ = mfu_section(data)
    tuner_lines, _ = tuner_section(data)
    serving_lines, _ = serving_section(data)
    sections = [
        header,
        step_time_section(data),
        mfu_lines,
        pipeline_section(data),  # empty (omitted) for non-pipelined runs
        tuner_lines,  # empty (omitted) for untuned runs
        serving_lines,  # empty (omitted) for non-serving runs
        barrier_section(data),
        checkpoint_section(data),
        timeline_section(data),
    ]
    return "\n".join("\n".join(s) for s in sections if s) + "\n"


def check_gates(data: RunData, assert_mfu: Optional[float] = None,
                assert_step_time: Optional[float] = None,
                assert_tuner_calibration: Optional[float] = None,
                tuner_stats: Optional[Dict[str, float]] = None,
                assert_serve_throughput: Optional[float] = None,
                assert_ttft: Optional[float] = None,
                assert_spec_accept_rate: Optional[float] = None,
                assert_max_downsizes: Optional[int] = None,
                assert_max_resizes: Optional[int] = None,
                assert_max_shed_rate: Optional[float] = None,
                assert_max_serve_timeouts: Optional[int] = None,
                assert_max_replica_skew: Optional[float] = None,
                assert_max_replica_restarts: Optional[int] = None
                ) -> List[str]:
    """CI-style regression gates; returns failure messages (empty ==
    pass). Missing data FAILS a requested gate — a run that recorded no
    MFU must not pass an MFU floor by silence. ``tuner_stats`` lets a
    caller that already rendered the tuner section pass its stats in
    instead of re-aggregating the spans."""
    _, stats = mfu_section(data)
    failures: List[str] = []
    serving_gates = (assert_serve_throughput is not None
                     or assert_ttft is not None
                     or assert_spec_accept_rate is not None
                     or assert_max_shed_rate is not None
                     or assert_max_serve_timeouts is not None
                     or assert_max_replica_skew is not None
                     or assert_max_replica_restarts is not None)
    if serving_gates:
        _, sstats = serving_section(data)
        if assert_max_shed_rate is not None:
            rate = sstats.get("serve_shed_rate")
            if rate is None:
                failures.append(
                    "assert-max-shed-rate: no shed telemetry in the run "
                    "dir (serve-summary carries no requests_shed — "
                    "pre-resilience bench, or no summary at all?)"
                )
            elif rate > assert_max_shed_rate:
                failures.append(
                    f"assert-max-shed-rate: shed rate {rate:.3f} > "
                    f"ceiling {assert_max_shed_rate:.3f}"
                )
        if assert_max_serve_timeouts is not None:
            timeouts = sstats.get("serve_timeouts")
            if timeouts is None:
                failures.append(
                    "assert-max-serve-timeouts: no timeout telemetry in "
                    "the run dir (serve-summary carries no "
                    "requests_timeout)"
                )
            elif timeouts > assert_max_serve_timeouts:
                failures.append(
                    f"assert-max-serve-timeouts: {int(timeouts)} "
                    f"deadline timeout(s) > ceiling "
                    f"{assert_max_serve_timeouts}"
                )
        if assert_max_replica_skew is not None:
            skew = sstats.get("serve_replica_skew")
            if skew is None:
                failures.append(
                    "assert-max-replica-skew: no fleet telemetry in the "
                    "run dir (serve-summary carries no replica_stats — "
                    "single-engine bench, or no summary at all?)"
                )
            elif skew > assert_max_replica_skew:
                failures.append(
                    f"assert-max-replica-skew: completed-request skew "
                    f"{'inf' if math.isinf(skew) else format(skew, '.2f')}"
                    f" > ceiling {assert_max_replica_skew:.2f} (a replica "
                    "is starved or dead — check the router rows)"
                )
        if assert_max_replica_restarts is not None:
            restarts = sstats.get("serve_replica_restarts")
            if restarts is None:
                failures.append(
                    "assert-max-replica-restarts: no fleet supervision "
                    "telemetry in the run dir (no serve-replica-* "
                    "lifecycle events — was the bench run with "
                    "--replicas-proc?)"
                )
            elif restarts > assert_max_replica_restarts:
                failures.append(
                    f"assert-max-replica-restarts: {int(restarts)} "
                    f"supervised relaunch(es) > ceiling "
                    f"{assert_max_replica_restarts} (replicas are "
                    "crash-looping — check the fleet timeline)"
                )
            missing = sstats.get("serve_hosts_missing")
            if missing:
                # a planned host with no rendezvous record is a silent
                # capacity loss no restart count would surface
                failures.append(
                    f"assert-max-replica-restarts: {int(missing)} "
                    f"planned host(s) never rendezvoused (of "
                    f"{int(sstats.get('serve_fleet_hosts', 0))} in the "
                    "placement plan) — the fleet ran without them; "
                    "check the hosts line and ssh reachability"
                )
        if assert_spec_accept_rate is not None:
            rate = sstats.get("serve_spec_accept_rate")
            if rate is None:
                failures.append(
                    "assert-spec-accept-rate: no speculative-decoding "
                    "telemetry in the run dir (serve-summary carries no "
                    "spec_accept_rate — was the bench run with --spec-k?)"
                )
            elif rate < assert_spec_accept_rate:
                failures.append(
                    f"assert-spec-accept-rate: accept rate {rate:.3f} "
                    f"< floor {assert_spec_accept_rate:.3f}"
                )
        if assert_serve_throughput is not None:
            tps = sstats.get("serve_tokens_per_s")
            if tps is None:
                has_serve_events = any(
                    e.get("event") in ("serve-request", "serve-summary")
                    for e in data.lifecycle
                )
                failures.append(
                    "assert-serve-throughput: "
                    + ("no serve-summary and too few serve-request events "
                       "to derive a rate (crashed/short run?)"
                       if has_serve_events else
                       "no serving telemetry in the run dir (no "
                       "serve-summary / serve-request events)")
                )
            elif tps < assert_serve_throughput:
                failures.append(
                    f"assert-serve-throughput: {tps:.1f} output tokens/s "
                    f"< floor {assert_serve_throughput:.1f}"
                )
        if assert_ttft is not None:
            p99 = sstats.get("serve_ttft_p99_s")
            if p99 is None:
                failures.append(
                    "assert-ttft: no per-request TTFT samples in the run "
                    "dir (no serve-request events)"
                )
            elif p99 > assert_ttft:
                failures.append(
                    f"assert-ttft: p99 TTFT {p99:.4f}s > ceiling "
                    f"{assert_ttft:.4f}s"
                )
    if assert_tuner_calibration is not None:
        tstats = (
            tuner_stats if tuner_stats is not None
            else tuner_section(data)[1]
        )
        err = tstats.get("tuner_calibration_error")
        if err is None or not math.isfinite(err):
            failures.append(
                "assert-tuner-calibration: no tuner prediction + measured "
                "step time pair in the run dir"
            )
        elif abs(err) > assert_tuner_calibration:
            failures.append(
                f"assert-tuner-calibration: |calibration error| "
                f"{abs(err):.3f} > ceiling {assert_tuner_calibration:.3f} "
                f"(predicted {tstats['tuner_predicted_step_s']:.3f}s vs "
                f"measured {tstats['tuner_measured_step_s']:.3f}s)"
            )
    if assert_max_resizes is not None or assert_max_downsizes is not None:
        # one resize gate, both directions: ``--assert-max-downsizes``
        # predates elastic upsizing and is kept as an alias with the
        # same (resize-counting) semantics — a flapping host that
        # churns the pod up AND down must not pass a downsize-only
        # ceiling on a technicality. Tightest requested ceiling wins.
        flag = ("assert-max-resizes" if assert_max_resizes is not None
                else "assert-max-downsizes")
        ceiling = min(
            c for c in (assert_max_resizes, assert_max_downsizes)
            if c is not None
        )
        # the gate only means something for a SUPERVISED run: without
        # supervisor lifecycle events the absence of resize events is
        # silence, not health — missing data fails, like every gate
        supervised = any(
            e.get("event") == "epoch-start" for e in data.lifecycle
        )
        resizes = sum(
            1 for e in data.lifecycle
            if e.get("event") in ("downsize", "upsize")
        )
        if not supervised:
            failures.append(
                f"{flag}: no supervisor telemetry in the run "
                "dir (no epoch-start events — was the run launched with "
                "runner.supervise?)"
            )
        elif resizes > ceiling:
            failures.append(
                f"{flag}: {resizes} resize(s) > ceiling "
                f"{ceiling} (world-size transitions: "
                f"{', '.join(world_size_transitions(data)) or 'none'})"
            )
    if assert_mfu is not None:
        mean = stats.get("mfu_mean")
        if mean is None:
            failures.append("assert-mfu: no MFU samples in the run dir")
        elif mean < assert_mfu:
            failures.append(
                f"assert-mfu: mean MFU {mean:.4f} < floor {assert_mfu:.4f}"
            )
    if assert_step_time is not None:
        p50 = stats.get("step_time_p50")
        if p50 is None:
            failures.append(
                "assert-step-time: no step_duration samples in the run dir"
            )
        elif p50 > assert_step_time:
            failures.append(
                f"assert-step-time: p50 step time {p50:.3f}s > ceiling "
                f"{assert_step_time:.3f}s"
            )
    return failures
