"""Per-step telemetry the trainer drives on every FETCHED step.

The trainer owns one :class:`StepTelemetry`; ``on_step`` refreshes the
hardware gauges, the step-time EMA and — once the model has declared its
FLOPs-per-token estimate via :meth:`configure` — the achieved-TFLOPs and
MFU gauges, returning the derived values so they ride the same metric
dict ``logger.log_metrics`` renders. ``flush`` then snapshots the whole
registry (including every span histogram) to the metrics JSONL sink.

Contract: ``on_step`` never touches device buffers — it must be safe on
the hot path with ``log_interval=1`` and adds no syncs outside profiler
windows (unit-asserted).
"""

from __future__ import annotations

from typing import Dict, Optional

from ..logging import logger
from .hardware import StepTimeEMA, achieved_tflops, mfu, update_hardware_gauges
from .registry import MetricsRegistry, get_registry


class StepTelemetry:
    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 ema_alpha: float = 0.1):
        self.registry = registry if registry is not None else get_registry()
        self.ema = StepTimeEMA(ema_alpha)
        self.enabled = True
        self.hardware_gauges = True
        self._last_step: Optional[int] = None
        self.flops_per_token: Optional[float] = None
        self.tokens_per_step: Optional[float] = None
        self.world_size: int = 1
        self.peak_tflops: Optional[float] = None

    def configure(self, *, flops_per_token: Optional[float] = None,
                  tokens_per_step: Optional[float] = None,
                  world_size: Optional[int] = None,
                  peak_tflops: Optional[float] = None) -> None:
        """Declare the model/hardware constants MFU accounting needs.

        The transformer entrypoint calls this once at startup; a trainer
        left unconfigured still emits step-time and memory gauges, just
        no MFU."""
        if flops_per_token is not None:
            self.flops_per_token = float(flops_per_token)
        if tokens_per_step is not None:
            self.tokens_per_step = float(tokens_per_step)
        if world_size is not None:
            self.world_size = int(world_size)
        if peak_tflops is not None:
            self.peak_tflops = float(peak_tflops)

    def on_step(self, step: int, step_duration: Optional[float]) -> Dict[str, float]:
        """Update gauges for one fetched step; returns the derived
        metrics to merge into the step's log record."""
        if not self.enabled:
            return {}
        reg = self.registry
        out: Dict[str, float] = {}
        # on_step only runs on FETCHED steps; with log_interval>1 the
        # steps in between were dispatched-but-unlogged, so count the
        # step-number delta, not the call — anyone rating steps/s off
        # the counter must not be off by the log_interval factor
        if self._last_step is not None and step > self._last_step:
            reg.counter("train_steps_total").inc(step - self._last_step)
        else:
            reg.counter("train_steps_total").inc()
        self._last_step = step
        if step_duration is not None and step_duration > 0:
            reg.gauge("step_time_seconds").set(step_duration)
            ema = self.ema.update(step_duration)
            reg.gauge("step_time_ema_seconds").set(ema)
            out["step_time_ema"] = ema
            if self.flops_per_token and self.tokens_per_step:
                ach = achieved_tflops(
                    self.flops_per_token, self.tokens_per_step, step_duration
                )
                reg.gauge("achieved_tflops").set(ach)
                out["achieved_tflops"] = ach
                if self.peak_tflops:
                    u = mfu(ach, self.world_size, self.peak_tflops)
                    reg.gauge("mfu").set(u)
                    out["mfu"] = u
        if self.hardware_gauges:
            update_hardware_gauges(reg)
        return out

    def flush(self, step: int) -> None:
        """Snapshot the registry to the metrics JSONL sink (no-op when no
        sink path is configured — the always-on default costs nothing)."""
        try:
            self.registry.flush_step(step)
        except Exception as e:
            # a full disk — or a serialization surprise from some
            # subsystem's odd metric value — must degrade telemetry,
            # never abort training
            logger.warning(f"metrics registry flush failed: {e!r}")
