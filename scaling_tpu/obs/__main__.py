"""Entry point for ``python -m scaling_tpu.obs`` — pure stdlib, no jax:
the analyzer runs on login nodes and in CI where backend init is dead
weight."""

import sys

from .cli import main

sys.exit(main())
