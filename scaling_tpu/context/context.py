"""Per-run state: iteration counters and RNG.

(reference: src/scaling/core/context/context.py:31-162). The reference
checkpoints the full CUDA/torch RNG state per global rank; with stateless
jax keys the whole RNG state is (seed, iteration counters) — keys are
re-derived, so resume is exact by construction and the MAX-allreduce resync
for relayouts disappears.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Optional

from ..config import BaseConfig
from ..topology import RngTracker, Topology


class ContextConfig(BaseConfig):
    """Marker base for trainer-facing config trees (subclasses add fields)."""


class BaseContext:
    def __init__(self, config: Any, topology: Topology):
        self.config = config
        self.topology = topology
        self.iterations = 0
        self.consumed_samples = 0
        self.consumed_eval_samples = 0
        self._rng: Optional[RngTracker] = None

    def initialize(self, seed: int) -> None:
        self.seed = seed
        self._rng = RngTracker(seed)

    @property
    def rng(self) -> RngTracker:
        assert self._rng is not None, "context not initialized; call initialize(seed)"
        return self._rng

    def step(self) -> None:
        self.iterations += 1
        self.consumed_samples += self.topology.config.global_batch_size

    # ---------------------------------------------------------- checkpoint
    def state_dict(self) -> dict:
        return {
            "iterations": self.iterations,
            "consumed_samples": self.consumed_samples,
            "consumed_eval_samples": self.consumed_eval_samples,
            "seed": getattr(self, "seed", None),
        }

    def load_state_dict(self, state: dict) -> None:
        self.iterations = int(state["iterations"])
        self.consumed_samples = int(state["consumed_samples"])
        self.consumed_eval_samples = int(state.get("consumed_eval_samples", 0))
        if state.get("seed") is not None:
            self.initialize(int(state["seed"]))

    def save_checkpoint(self, dir: Path | str) -> None:
        path = Path(dir)
        path.mkdir(parents=True, exist_ok=True)
        (path / "context.json").write_text(json.dumps(self.state_dict(), indent=2))

    def load_checkpoint(self, dir: Path | str) -> bool:
        f = Path(dir) / "context.json"
        if not f.is_file():
            return False
        self.load_state_dict(json.loads(f.read_text()))
        return True
