from .context import BaseContext, ContextConfig

__all__ = ["BaseContext", "ContextConfig"]
