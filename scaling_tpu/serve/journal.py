"""Crash-replay request journal (docs/SERVING.md "Resilience").

The serving engine has no checkpoint: its durable state is the REQUEST
STREAM, and everything else (KV pools, block tables, slots) is
recomputable from it. The journal records exactly that stream — one
JSON line per submission (prompt + sampling parameters + deadlines),
per tick's emitted tokens, and per terminal status — through the same
single-``write(2)`` O_APPEND appender the metrics sink uses
(``logging.append_jsonl_line``), so a SIGKILL at any instant leaves at
worst one torn tail line, never an unparseable journal.

Recovery is recompute-style, like scheduler preemption: a supervised
relaunch (``serve bench --resume`` under ``--restarts``) replays the
journal, re-enqueues every request with no terminal status — SAME
``req_id``, SAME prompt, SAME sampling params — and the engine
regenerates their outputs from scratch. Token-for-token identity with
the crashed run (and with a fault-free run) holds by construction, not
by luck: every sample draws with the (request id, token position) key
``fold_in(fold_in(base, req), n_generated)``
(``inference.request_sample_key``), so position ``i`` of request ``r``
is the same draw in every process that ever computes it — greedy or
sampled, crashed or not. Requests that already finished are NOT
re-enqueued; their journaled tokens are the delivered output. A
``timeout`` status is terminal too — replaying a request that already
missed its deadline would burn capacity on an answer nobody is waiting
for.

Every append fires the ``serve.journal`` fault point
(``SCALING_TPU_FAULTS``) so tests can kill/fail at an exact record.
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path
from typing import Dict, List, Optional

from ..logging.logger import append_jsonl_line
from ..resilience.faults import get_fault_plan

# journal record kinds (the "kind" field of each JSON line)
SUBMIT = "serve-submit"
TOKENS = "serve-tokens"
FINISH = "serve-finish"
SHED = "serve-shed"


class RequestJournal:
    """Append-only request journal; one writer per engine process."""

    def __init__(self, path):
        self.path = str(path)
        Path(self.path).parent.mkdir(parents=True, exist_ok=True)

    def _append(self, rec: dict) -> None:
        get_fault_plan().fire("serve.journal", path=self.path)
        append_jsonl_line(self.path, json.dumps(rec, sort_keys=True))

    def record_submit(self, request) -> None:
        """The full replay recipe for one request: everything ``submit``
        needs to re-enqueue it bit-identically (the req_id is the
        sampler-key fold, so it MUST survive the crash)."""
        self._append({
            "kind": SUBMIT,
            "req": request.req_id,
            "prompt": [int(t) for t in request.prompt],
            "max_new_tokens": request.max_new_tokens,
            "eos_token_id": request.eos_token_id,
            "temperature": request.temperature,
            "top_k": request.top_k,
            "top_p": request.top_p,
            "deadline_ms": request.deadline_ms,
            "ttft_deadline_ms": request.ttft_deadline_ms,
            # trace identity survives the crash with the replay recipe:
            # the failover re-dispatch adopts it so the survivor's work
            # lands on the ORIGINAL request's trace
            "trace": getattr(request, "trace_id", None),
        })

    def record_tokens(self, req_id: int, tokens: List[int]) -> None:
        """One tick's newly emitted tokens for a request (batched per
        tick, not per token — a decode tick with 8 rows is 8 appends,
        not 8 x tokens)."""
        if not tokens:
            return
        self._append({
            "kind": TOKENS, "req": req_id,
            "toks": [int(t) for t in tokens],
        })

    def record_tokens_batch(self, batches: Dict[int, List[int]]) -> None:
        """EVERY row's tick tokens in ONE append: same per-request
        record lines, one ``write(2)``. A tick that appended 12 separate
        lines paid 12 GIL release/re-acquire round-trips — in the
        threaded fleet each re-acquire can wait a whole switch interval
        behind a peer replica's tick, and the convoy quadrupled tick
        counts. One syscall keeps the journal off the critical path."""
        lines = [
            json.dumps(
                {"kind": TOKENS, "req": int(rid),
                 "toks": [int(t) for t in toks]},
                sort_keys=True,
            )
            for rid, toks in sorted(batches.items()) if toks
        ]
        if not lines:
            return
        get_fault_plan().fire("serve.journal", path=self.path)
        append_jsonl_line(self.path, "\n".join(lines))

    def record_finish(self, req_id: int, status: str,
                      tokens: Optional[List[int]] = None) -> None:
        """Terminal status — with ``tokens``, the request's final token
        batch rides the SAME append (one write per retirement, not
        two)."""
        recs = []
        if tokens:
            recs.append({
                "kind": TOKENS, "req": req_id,
                "toks": [int(t) for t in tokens],
            })
        recs.append({"kind": FINISH, "req": req_id, "status": status})
        get_fault_plan().fire("serve.journal", path=self.path)
        append_jsonl_line(
            self.path,
            "\n".join(json.dumps(r, sort_keys=True) for r in recs),
        )

    def record_shed(self, reason: str) -> None:
        """An overload-shed submission consumed a client offer without
        producing a request: the bench's resume path must skip the
        corresponding workload item (the client was told Backpressure;
        re-offering it after a crash would double-serve its successors
        and silently resurrect a rejection)."""
        self._append({"kind": SHED, "reason": reason})


@dataclasses.dataclass
class JournalReplay:
    """The journal, folded into per-request state. A request
    re-submitted after a crash (its id appears in a LATER submit record)
    resets its token tally — replay regenerates the output from scratch,
    and only the freshest generation is the output."""

    submits: Dict[int, dict]  # req_id -> latest submit record
    tokens: Dict[int, List[int]]  # req_id -> tokens since latest submit
    status: Dict[int, Optional[str]]  # None = still in flight at crash
    shed_count: int = 0  # overload-shed submissions (offered, rejected)
    bad_lines: int = 0  # torn tail from a SIGKILL mid-append

    @property
    def submitted_count(self) -> int:
        """Distinct requests ever submitted (admitted into the engine)."""
        return len(self.submits)

    @property
    def offered_count(self) -> int:
        """Workload items CONSUMED by the crashed run(s): admitted
        submissions plus overload sheds (each shed record is one offer
        the engine rejected — replayed force-admissions never shed, so
        the sum maps 1:1 onto the bench's arrival-ordered workload
        prefix)."""
        return len(self.submits) + self.shed_count

    @property
    def next_req_id(self) -> int:
        return max(self.submits, default=-1) + 1

    @property
    def incomplete(self) -> List[dict]:
        """Submit records to re-enqueue, in request order. Timeouts are
        terminal: a request that missed its deadline is not replayed."""
        return [
            self.submits[r] for r in sorted(self.submits)
            if self.status.get(r) is None
        ]

    @property
    def timeout_count(self) -> int:
        """Requests that hit their deadline in the crashed run(s) —
        terminal, not replayed, but still part of the run dir's story
        (the resumed run folds them into its summary's gate fields)."""
        return sum(1 for s in self.status.values() if s == "timeout")

    @property
    def completed(self) -> Dict[int, List[int]]:
        """req_id -> delivered output tokens, for requests with a
        ``completed`` terminal status."""
        return {
            r: self.tokens[r] for r in sorted(self.submits)
            if self.status.get(r) == "completed"
        }


def journal_path(base_path, replica_id: Optional[int] = None) -> Path:
    """The journal file for one engine: the base path itself for a
    single-engine run, ``<stem>_r<id><suffix>`` for fleet replica
    ``id``. Namespacing per replica is what lets a fleet ``--resume``
    replay each replica's incomplete requests from its OWN stream —
    one shared file would interleave N writers (torn lines beyond the
    single-writer O_APPEND guarantee) and collide their tallies."""
    p = Path(base_path)
    if replica_id is None:
        return p
    return p.with_name(f"{p.stem}_r{int(replica_id)}{p.suffix}")


def open_journal(path, resume: bool, replica_id: Optional[int] = None):
    """The bench's journal lifecycle: returns ``(journal, replay)``.

    ``resume=True`` folds the existing journal FIRST (the crashed
    run's records) and keeps appending to it. ``resume=False`` is a
    FRESH run: any stale journal from a previous drill in the same run
    dir is truncated — the appender is O_APPEND by design (SIGKILL
    safety), so without this a later ``--resume`` would replay the
    previous run's request stream into the new workload.

    ``replica_id`` namespaces the file per fleet replica
    (:func:`journal_path`) so N engine writers never share a stream."""
    p = journal_path(path, replica_id)
    replay = None
    if resume:
        replay = replay_journal(p)
    elif p.exists():
        p.unlink()
    return RequestJournal(p), replay


def failover_split(path):
    """Harvest a DEAD replica's journal for fleet failover: returns
    ``(completed, incomplete, timeout_count)`` — delivered outputs
    (req_id -> tokens) the supervisor folds straight into the run's
    results, submit records to re-dispatch to SURVIVORS (original
    req_ids + ``force=True`` keep the (request, position) sampler keys,
    so any replica regenerates the same tokens), and the dead replica's
    terminal timeouts (counted, never replayed). One named seam so the
    failover policy is unit-testable against a literal journal file."""
    rep = replay_journal(path)
    return rep.completed, rep.incomplete, rep.timeout_count


def submitted_ids(path) -> set:
    """Req ids with a submit record in ``path`` — the admission arbiter
    for in-doubt RPCs: a submit whose reply was lost in a partition was
    admitted iff the (dead) replica's journal carries its record. The
    supervisor consults this at failover so an in-doubt request is
    re-dispatched EXACTLY once — via journal replay when it was admitted,
    via the router's parked copy when it never was."""
    return set(replay_journal(path).submits)


def replay_journal(path) -> JournalReplay:
    """Parse a journal (tolerant of one torn tail line — the SIGKILL
    signature) into :class:`JournalReplay`."""
    replay = JournalReplay(submits={}, tokens={}, status={})
    p = Path(path)
    if not p.is_file():
        return replay
    from ..resilience.guards import retry_io

    # the crash-recovery read itself rides the bounded-retry layer: a
    # flaky shared mount at relaunch time must not turn a recoverable
    # crash into a lost request stream
    journal_text = retry_io(p.read_text, what="request journal replay read")
    for line in journal_text.splitlines():
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            replay.bad_lines += 1
            continue
        kind = rec.get("kind")
        if kind == SUBMIT:
            rid = int(rec["req"])
            replay.submits[rid] = rec
            replay.tokens[rid] = []  # a re-submission restarts the tally
            replay.status[rid] = None
        elif kind == TOKENS and int(rec.get("req", -1)) in replay.submits:
            replay.tokens[int(rec["req"])].extend(
                int(t) for t in rec.get("toks", ())
            )
        elif kind == FINISH and int(rec.get("req", -1)) in replay.submits:
            replay.status[int(rec["req"])] = rec.get("status")
        elif kind == SHED:
            replay.shed_count += 1
        else:
            replay.bad_lines += 1
    return replay
