"""Continuous-batching scheduler (Orca, OSDI '22) — host-side policy.

Pure Python, jax-free: every decision the serving engine makes about
WHICH sequences run each tick lives here, unit-testable without a
backend. The engine (engine.py) owns the device programs; this module
owns admission, the per-tick prefill/decode mix under a token budget,
block accounting, preemption on pool exhaustion, and slot recycling.

Preemption is recompute-style (PagedAttention, SOSP '23 §4.5): the
youngest running sequence drops its blocks and re-enters the waiting
queue with ``prompt + generated-so-far`` as its new prompt. Under greedy
sampling the resumed sequence regenerates token-for-token, so preemption
is invisible in the output — the paged-parity tests pin exactly that.

Two raw-speed policies ride the same tick loop (ISSUE 11):

- **Shared-prefix block reuse** (RadixAttention, arxiv 2312.07104):
  :class:`PrefixCache` is a trie over FULL blocks of prompt tokens.
  Admission walks the trie and maps every matched block straight into
  the new sequence's table (refcounted — the allocator counts sequence
  users per block), so N requests sharing a system prompt pay its
  prefill ONCE; only the unmatched tail streams chunks. Freed cached
  blocks are not returned to the free list — they become LRU-evictable
  trie leaves, reclaimed only under pool pressure.
- **Self-drafting speculative decoding** (Leviathan et al., arxiv
  2211.17192): :func:`ngram_propose` drafts ``k`` candidate tokens per
  decoding row from the row's own history; the engine scores all of
  them in one kernel call and accepts the longest prefix that matches
  what plain decode would have emitted (exact at any temperature — the
  per-(request, position) sample keys make acceptance pathwise, not
  merely distribution, equivalent).
"""

from __future__ import annotations

import dataclasses
import enum
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

from ..resilience.faults import get_fault_plan

# block 0 is the TRASH block: never allocated, it absorbs the jitted
# decode step's writes from inactive slots and padding (nn/attention.py
# PagedKVCacheView). Allocators start handing out ids at 1.
TRASH_BLOCK = 0


class SequenceState(enum.Enum):
    WAITING = "waiting"
    RUNNING = "running"
    FINISHED = "finished"


@dataclasses.dataclass
class Request:
    """One inference request as the load generator / API submits it.

    ``temperature`` / ``top_k`` / ``top_p`` are per-request sampler
    settings carried into the engine's jitted programs as traced per-row
    arrays (inference.sample_rows); ``temperature=0`` (the default) is
    greedy — the zero-temperature special case, not a separate code
    path."""

    req_id: int
    prompt: List[int]
    max_new_tokens: int
    arrival_s: float = 0.0
    eos_token_id: Optional[int] = None
    temperature: float = 0.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    # request deadlines (milliseconds from arrival; None = unbounded):
    # ``ttft_deadline_ms`` bounds the wait for the FIRST token,
    # ``deadline_ms`` the whole request. An expired request is cancelled
    # at the next tick boundary with terminal status 'timeout' — its
    # slot and blocks recycle immediately (docs/SERVING.md "Resilience")
    deadline_ms: Optional[float] = None
    ttft_deadline_ms: Optional[float] = None
    # distributed-tracing identity (docs/OBSERVABILITY.md "Tracing"):
    # assigned by the originating submitter (bench), carried through
    # every RPC hop / journal record / failover re-dispatch so the
    # request reconstructs as ONE trace fleet-wide. None = untraced
    # (warmup, legacy journals) — nothing downstream stamps anything
    trace_id: Optional[str] = None


@dataclasses.dataclass
class Sequence:
    """Scheduler-side state of one request's lifetime."""

    request: Request
    state: SequenceState = SequenceState.WAITING
    generated: List[int] = dataclasses.field(default_factory=list)
    slot: Optional[int] = None  # decode-batch row while RUNNING
    blocks: List[int] = dataclasses.field(default_factory=list)
    num_cached: int = 0  # tokens whose KV sits in the pool
    prefill_len: int = 0  # resume-prompt length at (re-)admission
    preemptions: int = 0
    # shared-prefix reuse: tokens whose blocks came straight from the
    # prefix trie at (re-)admission (their prefill is SKIPPED), and how
    # far this sequence's own full prompt blocks are registered in it
    prefix_cached: int = 0
    cached_upto: int = 0
    # speculative decoding: this tick's drafted candidate tokens (set by
    # propose_drafts, consumed by the engine's mixed program)
    draft: List[int] = dataclasses.field(default_factory=list)
    # telemetry stamps (engine fills these; monotonic seconds)
    first_token_s: Optional[float] = None
    finished_s: Optional[float] = None
    token_stamps: List[float] = dataclasses.field(default_factory=list)
    # terminal status: 'completed' | 'timeout' (set at retirement; rides
    # the serve-request event next to the preemption count)
    finish_status: str = "completed"

    @property
    def resume_prompt(self) -> List[int]:
        """What a (re-)admission must prefill: the original prompt plus
        everything already generated (recompute-style preemption)."""
        return list(self.request.prompt) + list(self.generated)

    @property
    def remaining_tokens(self) -> int:
        return self.request.max_new_tokens - len(self.generated)

    @property
    def prefilling(self) -> bool:
        """RUNNING but the prompt's KV is not fully in the pool yet —
        under chunked prefill such a sequence streams chunks instead of
        decoding (it has no first token to decode from)."""
        return self.slot is not None and self.num_cached < self.prefill_len

    @property
    def done(self) -> bool:
        if len(self.generated) >= self.request.max_new_tokens:
            return True
        eos = self.request.eos_token_id
        return eos is not None and bool(self.generated) and self.generated[-1] == eos


class BlockAllocator:
    """Refcounted free-list over the pool's block ids; block 0 (trash) is
    reserved.

    A block's refcount counts its USERS: one per sequence whose table
    maps it, plus one held by the prefix trie while the block backs a
    cached prefix node (:class:`PrefixCache` — copy-on-write semantics:
    a writer facing ``refcount > 1`` must fork the block first, see
    ``ContinuousBatchingScheduler._fork_shared_write_blocks``). ``free``
    DECREMENTS; the block only returns to the free list at refcount 0."""

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError(
                f"pool needs >=2 blocks (1 trash + 1 usable), got {num_blocks}"
            )
        self.num_blocks = num_blocks
        self._free: Deque[int] = deque(range(1, num_blocks))
        self._ref: Dict[int, int] = {}
        # refcount-transition hook (block, new_rc) — the prefix cache
        # registers here to track its evictable set incrementally
        # instead of rescanning the trie on every capacity question
        self.on_ref_change = None

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    def refcount(self, block: int) -> int:
        return self._ref.get(block, 0)

    def _changed(self, block: int, rc: int) -> None:
        if self.on_ref_change is not None:
            self.on_ref_change(block, rc)

    def alloc(self, n: int) -> List[int]:
        if n > len(self._free):
            raise RuntimeError(
                f"pool exhausted: need {n} block(s), {len(self._free)} free"
            )
        out = [self._free.popleft() for _ in range(n)]
        for b in out:
            self._ref[b] = 1
            self._changed(b, 1)
        return out

    def incref(self, block: int) -> None:
        """A new user (sequence table row or trie node) maps the block."""
        if block == TRASH_BLOCK or block not in self._ref:
            raise ValueError(f"incref on block {block} not allocated")
        self._ref[block] += 1
        self._changed(block, self._ref[block])

    def free(self, blocks: List[int]) -> None:
        """Drop one reference per listed block; refcount-0 blocks return
        to the free list (a block the trie still references stays out —
        LRU eviction, not this, reclaims it)."""
        for b in blocks:
            if b == TRASH_BLOCK or b not in self._ref:
                raise ValueError(f"freeing block {b} not held (double free?)")
            self._ref[b] -= 1
            rc = self._ref[b]
            if rc == 0:
                del self._ref[b]
                self._free.append(b)
            self._changed(b, rc)


class PrefixNode:
    """One FULL block of prompt tokens in the prefix trie. The node's
    path from the root uniquely determines the block's KV content (KV of
    token ``t`` depends on every token before it), so two prompts
    walking the same path can share the same pool block bit-for-bit."""

    __slots__ = ("key", "block", "children", "parent", "last_used")

    def __init__(self, key: Tuple[int, ...], block: int,
                 parent: Optional["PrefixNode"]):
        self.key = key
        self.block = block
        self.children: Dict[Tuple[int, ...], "PrefixNode"] = {}
        self.parent = parent
        self.last_used = 0


class PrefixCache:
    """Shared-prefix block reuse (RadixAttention, arxiv 2312.07104),
    full-block granularity.

    ``match`` maps a new prompt's longest cached full-block prefix into
    its block table (incref per block — the requester becomes a user);
    ``insert`` registers a sequence's freshly-prefilled full prompt
    blocks so LATER requests can reuse them (the trie itself holds one
    reference per cached block). A cached block whose only reference is
    the trie's is *evictable*: eviction is LRU over such leaves (a node
    in use — refcount > 1 — is refused, and since sharing walks root-
    down, an in-use descendant implies in-use ancestors, so leaf-first
    LRU can never strand a live path)."""

    def __init__(self, allocator: BlockAllocator, block_size: int):
        self.allocator = allocator
        self.block_size = block_size
        self._root = PrefixNode((), TRASH_BLOCK, None)
        self._clock = 0
        self._nodes = 0
        # incremental evictable tracking: cached blocks whose only
        # reference is the trie's. Kept current by the allocator's
        # refcount-transition hook so the scheduler's per-tick capacity
        # questions are O(1), not a trie DFS per sequence.
        self._cached_blocks: set = set()
        self._evictable: set = set()
        allocator.on_ref_change = self._ref_changed

    def _ref_changed(self, block: int, rc: int) -> None:
        if block not in self._cached_blocks:
            return
        if rc == 1:
            self._evictable.add(block)
        else:
            self._evictable.discard(block)

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    @property
    def cached_blocks(self) -> int:
        return self._nodes

    def match(self, prompt: List[int]) -> Tuple[List[int], int]:
        """Longest cached full-block prefix of ``prompt``: returns the
        pool blocks to map (one incref each — caller must ``free`` them
        if the admission is abandoned) and the token count they cover.
        At least one prompt token is always left to prefill — the final
        chunk must run to produce the first output token."""
        bs = self.block_size
        cap = ((len(prompt) - 1) // bs) * bs
        node = self._root
        blocks: List[int] = []
        t = 0
        stamp = self._tick()
        while t < cap:
            child = node.children.get(tuple(prompt[t:t + bs]))
            if child is None:
                break
            self.allocator.incref(child.block)
            child.last_used = stamp
            blocks.append(child.block)
            node = child
            t += bs
        # hits are counted by the scheduler (prefix_hit_tokens) on
        # successful admission only — a deferred admission must not
        # inflate the hit rate
        return blocks, t

    def insert(self, path_tokens: List[int], block: int,
               parent_blocks: Optional[List[int]] = None) -> bool:
        """Register ``block`` as the cached KV for the last full block of
        ``path_tokens`` (whose length must be a block multiple). Returns
        True when the trie took a reference; False when the path is
        already cached (by this block or a duplicate prefilled
        concurrently — the caller's block simply stays private).

        ``parent_blocks`` (the inserting sequence's own block table):
        when given, every ancestor node must be backed by the SAME pool
        block the sequence maps at that position. This preserves the
        eviction invariant — an in-use descendant implies in-use
        ancestors — which breaks if a sequence that privately
        re-prefilled a duplicate first block hangs its next block under
        the canonical node: that node could drop to refcount 1 (counted
        evictable) while leaf-only eviction can never reach it, and
        ``available_blocks()`` would promise blocks ``evict()`` cannot
        deliver (allocator raise mid-schedule)."""
        bs = self.block_size
        if len(path_tokens) % bs != 0 or not path_tokens:
            raise ValueError(
                f"prefix paths are full blocks only; got {len(path_tokens)} "
                f"tokens at block_size {bs}"
            )
        node = self._root
        for i, t in enumerate(range(0, len(path_tokens) - bs, bs)):
            node = node.children.get(tuple(path_tokens[t:t + bs]))
            if node is None:
                # parent block was never cached (e.g. evicted between the
                # sequence's chunks): an orphan node would claim a prefix
                # whose ancestors can't be mapped — skip the insert
                return False
            if parent_blocks is not None and node.block != parent_blocks[i]:
                # the chain diverged (this sequence holds a private
                # duplicate of an ancestor): registering under the
                # canonical node would let it pin an ancestor this
                # sequence does not map
                return False
        key = tuple(path_tokens[-bs:])
        if key in node.children:
            return False
        child = PrefixNode(key, block, node)
        child.last_used = self._tick()
        node.children[key] = child
        self._cached_blocks.add(block)
        self.allocator.incref(block)  # the cache's own reference
        self._nodes += 1
        return True

    def evictable_count(self) -> int:
        """Blocks reclaimable right now: cached blocks whose only
        reference is the trie's (in-use descendants imply in-use
        ancestors, so every refcount-1 block is cascade-evictable).
        O(1): the set is maintained through the allocator's
        refcount-transition hook."""
        return len(self._evictable)

    def evict(self, n: int) -> int:
        """Reclaim up to ``n`` blocks, LRU over refcount-1 LEAVES
        (cascading: an evicted leaf may expose its parent). Refuses any
        node a sequence still maps (refcount > 1) — eviction must never
        pull a live block out from under a running request. The leaf
        walk only runs under pool pressure (the steady state never
        enters here); the hot capacity question is ``evictable_count``,
        which is O(1)."""
        freed = 0
        while freed < n and self._evictable:
            victim: Optional[PrefixNode] = None
            stack = list(self._root.children.values())
            while stack:
                node = stack.pop()
                if node.children:
                    stack.extend(node.children.values())
                elif node.block in self._evictable and (
                        victim is None or node.last_used < victim.last_used):
                    victim = node
            if victim is None:
                break
            del victim.parent.children[victim.key]
            self._nodes -= 1
            self.allocator.free([victim.block])  # trie ref -> free list
            self._cached_blocks.discard(victim.block)
            freed += 1
        return freed


# speculative drafting: how far back the n-gram proposer scans (and how
# much history propose_drafts assembles) — one constant, two users
NGRAM_SCAN_WINDOW = 512


def ngram_propose(history: List[int], k: int, max_n: int = 3,
                  max_scan: int = NGRAM_SCAN_WINDOW) -> List[int]:
    """Self-drafting n-gram proposal: find the most recent earlier
    occurrence of the history's final n-gram (longest n first) within
    the last ``max_scan`` tokens and copy the tokens that followed it —
    up to ``k`` candidates. Returns [] when nothing matches (the row
    decodes plainly that tick). Host-side and model-free: the 'draft
    model' is the sequence itself. ``max_scan`` bounds the per-tick host
    cost at O(max_n * max_scan) per row regardless of context length —
    recent history is where self-repetition lives anyway; an
    incremental suffix index is the documented follow-on
    (docs/SERVING.md)."""
    if k <= 0 or len(history) < 2:
        return []
    window = history[-max_scan:] if len(history) > max_scan else history
    for n in range(min(max_n, len(window) - 1), 0, -1):
        pat = window[-n:]
        for i in range(len(window) - n - 1, -1, -1):
            if window[i:i + n] == pat:
                cont = window[i + n:i + n + k]
                if cont:
                    return list(cont)
    return []


@dataclasses.dataclass
class SchedulerConfig:
    num_slots: int = 8  # decode-batch rows (the jitted batch size)
    block_size: int = 16  # tokens per KV block
    num_blocks: int = 128  # pool size incl. the trash block
    max_blocks_per_seq: int = 16  # block-table width (jitted shape)
    token_budget: int = 512  # prompt+decode tokens admitted per tick
    # Sarathi-style chunked prefill: prompts stream into the pool in
    # fixed-size chunks that share the tick budget with decode rows (no
    # prompt ever monopolizes a tick); None = legacy whole-prompt
    # prefill through the pow2 bucket ladder
    prefill_chunk: Optional[int] = None
    # shared-prefix block reuse (chunked mode only: whole-prompt mode
    # can't resume a prefill mid-prompt)
    prefix_cache: bool = True
    # self-drafting speculative decoding: candidate tokens drafted per
    # decoding row per tick (0 = off); requires chunked prefill — the
    # drafts are scored through the mixed program's chunk-width rows
    spec_k: int = 0
    # overload shedding (docs/SERVING.md "Resilience"): above the HIGH
    # pool-pressure watermark new submissions are rejected with a
    # structured Backpressure instead of queueing unboundedly, and keep
    # being rejected until pressure falls back to the LOW watermark
    # (hysteresis — admission must not flap at the boundary). None
    # disables the pressure watermark. ``max_waiting`` is a hard cap on
    # waiting-queue depth (no hysteresis; None = unbounded).
    shed_high_watermark: Optional[float] = None
    shed_low_watermark: Optional[float] = None
    max_waiting: Optional[int] = None

    def __post_init__(self):
        cap = self.max_blocks_per_seq * self.block_size
        if cap < 2:
            raise ValueError("max_blocks_per_seq * block_size must be >= 2")
        if self.prefill_chunk is not None and self.prefill_chunk < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1 (or None for whole-prompt "
                f"prefill), got {self.prefill_chunk}"
            )
        if self.spec_k < 0:
            raise ValueError(f"spec_k must be >= 0, got {self.spec_k}")
        if self.spec_k > 0 and self.prefill_chunk is None:
            raise ValueError(
                "speculative decoding (spec_k > 0) needs chunked prefill: "
                "drafts are scored through the mixed program's s>1 rows"
            )
        high, low = self.shed_high_watermark, self.shed_low_watermark
        if high is not None and not 0.0 < high <= 1.0:
            raise ValueError(
                f"shed_high_watermark must be in (0, 1], got {high}"
            )
        if low is not None:
            if high is None:
                raise ValueError(
                    "shed_low_watermark needs shed_high_watermark"
                )
            if not 0.0 <= low <= high:
                raise ValueError(
                    f"shed_low_watermark must be in [0, high={high}], "
                    f"got {low}"
                )
        if self.max_waiting is not None and self.max_waiting < 1:
            raise ValueError(
                f"max_waiting must be >= 1 (or None), got {self.max_waiting}"
            )


@dataclasses.dataclass
class Backpressure:
    """Structured admission rejection — the overload signal a fleet
    router consumes (retry elsewhere / retry later) instead of a request
    silently queueing unboundedly. ``reason`` is one of
    ``pool-pressure`` (above the high watermark, hysteresis engaged),
    ``queue-depth`` (waiting queue at ``max_waiting``), or ``draining``
    (the engine is shutting down gracefully and admits nothing new)."""

    reason: str
    pool_pressure: float
    waiting: int
    draining: bool = False


@dataclasses.dataclass
class Tick:
    """One scheduling decision: which sequences do prefill work this
    tick (the whole prompt, or ONE chunk each under chunked prefill),
    which decode, who got preempted to make room, and which shared
    blocks must be copy-on-write forked (``(src, dst)`` pool block
    pairs the engine copies BEFORE running the tick's programs)."""

    prefills: List[Sequence]
    decodes: List[Sequence]
    preempted: List[Sequence]
    cow_pairs: List[Tuple[int, int]] = dataclasses.field(default_factory=list)


class ContinuousBatchingScheduler:
    """Admission + per-tick prefill/decode mix + preemption policy."""

    def __init__(self, config: SchedulerConfig):
        self.config = config
        self.allocator = BlockAllocator(config.num_blocks)
        # shared-prefix reuse needs chunked prefill (a prefix hit resumes
        # the prefill mid-prompt, which only the chunk path can do)
        self.prefix_cache: Optional[PrefixCache] = (
            PrefixCache(self.allocator, config.block_size)
            if config.prefix_cache and config.prefill_chunk is not None
            else None
        )
        self.waiting: Deque[Sequence] = deque()
        self.running: Dict[int, Sequence] = {}  # slot -> sequence
        self._free_slots: Deque[int] = deque(range(config.num_slots))
        self.preemption_count = 0
        self.prefix_hit_tokens = 0  # prefill tokens skipped via the trie
        # overload shedding hysteresis: True from the first admission
        # rejected above the high watermark until pressure falls to the
        # low watermark (admission must not flap at the boundary)
        self._shedding = False
        # slots whose sequence left (finish/preempt) since the engine
        # last synced: their decode-batch rows must be zeroed before the
        # next device step, or stale block tables would write into blocks
        # now owned by someone else
        self._freed_slots: List[int] = []

    # ------------------------------------------------------------ intake
    def add_request(self, request: Request) -> Sequence:
        if request.max_new_tokens < 1:
            # prefill emits one token unconditionally; a 0-budget request
            # would receive a token it never asked for
            raise ValueError(
                f"request {request.req_id}: max_new_tokens must be >= 1, "
                f"got {request.max_new_tokens}"
            )
        if not request.prompt:
            raise ValueError(f"request {request.req_id}: empty prompt")
        cap = self.config.max_blocks_per_seq * self.config.block_size
        need = len(request.prompt) + request.max_new_tokens
        if need > cap:
            raise ValueError(
                f"request {request.req_id} needs {need} KV slots but the "
                f"block table holds {cap} "
                f"(max_blocks_per_seq={self.config.max_blocks_per_seq} x "
                f"block_size={self.config.block_size})"
            )
        usable = self.config.num_blocks - 1  # minus the trash block
        if self.blocks_needed(need) > usable:
            raise ValueError(
                f"request {request.req_id} needs "
                f"{self.blocks_needed(need)} blocks at full length but the "
                f"pool holds {usable} — it could never finish"
            )
        seq = Sequence(request=request)
        self.waiting.append(seq)
        return seq

    # ---------------------------------------------------------- accounting
    def blocks_needed(self, num_tokens: int) -> int:
        bs = self.config.block_size
        return (num_tokens + bs - 1) // bs

    def available_blocks(self) -> int:
        """Blocks grantable right now: the free list plus cached prefix
        blocks no sequence maps (LRU-evictable on demand)."""
        extra = (
            self.prefix_cache.evictable_count() if self.prefix_cache else 0
        )
        return self.allocator.free_blocks + extra

    def pool_pressure(self) -> float:
        """Fraction of grantable pool capacity in use, in [0, 1] — the
        overload gauge the shed watermarks compare against (and the
        ``serve_pool_pressure`` gauge on the obs rails)."""
        usable = self.config.num_blocks - 1  # minus the trash block
        if usable <= 0:
            return 1.0
        return (usable - self.available_blocks()) / usable

    def admission_backpressure(self) -> Optional[Backpressure]:
        """The watermark admission decision for ONE new submission:
        None admits; a :class:`Backpressure` rejects (the caller — the
        engine's ``submit`` — returns it to the client/router instead
        of queueing). Pool pressure sheds with hysteresis: above
        ``shed_high_watermark`` shedding starts and it only stops once
        pressure falls to ``shed_low_watermark``; queue depth is a hard
        cap with no hysteresis (depth moves by whole requests, not
        fractions of a block)."""
        cfg = self.config
        pressure = self.pool_pressure()
        if cfg.max_waiting is not None and len(self.waiting) >= cfg.max_waiting:
            return Backpressure(
                reason="queue-depth", pool_pressure=round(pressure, 4),
                waiting=len(self.waiting),
            )
        high = cfg.shed_high_watermark
        if high is None:
            return None
        low = cfg.shed_low_watermark if cfg.shed_low_watermark is not None \
            else high
        if self._shedding and pressure <= low:
            self._shedding = False
        elif not self._shedding and pressure >= high:
            self._shedding = True
        if self._shedding:
            return Backpressure(
                reason="pool-pressure", pool_pressure=round(pressure, 4),
                waiting=len(self.waiting),
            )
        return None

    def cancel(self, seq: Sequence) -> None:
        """Retire a live sequence before completion (deadline timeout):
        a RUNNING sequence releases its slot and drops one reference per
        block — private blocks return to the free list, trie-cached
        blocks stay resident as LRU-evictable prefix nodes (the cache
        outlives its requester by design); a WAITING sequence just
        leaves the queue. Either way the capacity is admissible in the
        very next tick."""
        if seq.state is SequenceState.RUNNING:
            self._evict(seq)
        elif seq.state is SequenceState.WAITING:
            self.waiting.remove(seq)
        else:
            raise ValueError(
                f"cancel on request {seq.request.req_id} in state "
                f"{seq.state} — only live sequences can be cancelled"
            )
        seq.state = SequenceState.FINISHED

    def _take(self, n: int) -> List[int]:
        """Allocate ``n`` blocks, evicting LRU refcount-free prefix
        blocks first when the free list is short (the cache yields to
        live sequences, never the reverse)."""
        get_fault_plan().fire("serve.pool")
        short = n - self.allocator.free_blocks
        if short > 0 and self.prefix_cache is not None:
            self.prefix_cache.evict(short)
        return self.allocator.alloc(n)

    # -------------------------------------------------- speculative drafts
    def propose_drafts(self) -> int:
        """Draft up to ``spec_k`` candidate tokens for every decoding row
        (n-gram self-drafting — no second model). Returns tokens drafted
        this tick. Drafts are capped at ``remaining_tokens - 1`` so a
        fully-accepted run (drafts + bonus token) lands exactly on the
        request's budget. The engine calls this ahead of ``schedule()``
        (under the ``serve.draft`` span) so GROW can book blocks for the
        scored slots."""
        k = self.config.spec_k
        drafted = 0
        for seq in self.running.values():
            seq.draft = []
            if k <= 0 or seq.prefilling or not seq.generated:
                continue
            cap = min(k, seq.remaining_tokens - 1)
            if cap <= 0:
                continue
            # assemble only the scan window, not the full O(L) history
            gen = seq.generated
            w = NGRAM_SCAN_WINDOW
            if len(gen) >= w:
                hist = gen[-w:]
            else:
                hist = seq.request.prompt[-(w - len(gen)):] + gen
            seq.draft = ngram_propose(hist, cap)
            drafted += len(seq.draft)
        return drafted

    # ------------------------------------------------- shared-prefix trie
    def _register_prefix_blocks(self) -> None:
        """Register every running sequence's freshly-prefilled FULL
        prompt blocks in the trie so later prompts can reuse them. Keyed
        by the token path from the root — the only thing the block's KV
        content depends on — so a preempted-and-resumed sequence's
        resume-prompt blocks (prompt + generated) cache correctly too."""
        cache = self.prefix_cache
        if cache is None:
            return
        bs = self.config.block_size
        for seq in self.running.values():
            limit = min(seq.num_cached, seq.prefill_len)
            while seq.cached_upto + bs <= limit:
                end = seq.cached_upto + bs
                cache.insert(
                    seq.resume_prompt[:end], seq.blocks[end // bs - 1],
                    parent_blocks=seq.blocks,
                )
                seq.cached_upto = end

    def _fork_shared_write_blocks(self, seq: Sequence, step: int,
                                  cow_pairs: List[Tuple[int, int]]) -> bool:
        """Copy-on-write: if any block the next ``step`` tokens will be
        written into is shared (refcount > 1 — another sequence's table
        or the prefix trie also maps it), fork it first: allocate a
        private copy, record the (src, dst) pair for the engine's
        device-side block copy, and drop this sequence's reference to
        the shared original. Full-block prefix sharing never writes into
        a shared block (writes land past the shared prefix), so this is
        a safety net that keeps the invariant LOCAL instead of relying
        on every future caller's arithmetic. Returns False when the pool
        can't supply a fork block (caller preempts as usual)."""
        bs = self.config.block_size
        first = seq.num_cached // bs
        last = (seq.num_cached + step - 1) // bs
        for idx in range(first, min(last + 1, len(seq.blocks))):
            src = seq.blocks[idx]
            if self.allocator.refcount(src) <= 1:
                continue
            if self.available_blocks() < 1:
                return False
            dst = self._take(1)[0]
            cow_pairs.append((src, dst))
            self.allocator.free([src])  # this seq's ref on the original
            seq.blocks[idx] = dst
        return True

    # ------------------------------------------------------------- policy
    def schedule(self) -> Tick:
        """One tick's worth of work.

        1. GROW: every running sequence gets the blocks its next tokens
           need — one decode token, or its next prefill CHUNK under
           chunked prefill (blocks are allocated incrementally, not
           reserved for the whole horizon — that is what lets wildly
           different lengths share one pool). On exhaustion the youngest
           running sequence is preempted recompute-style; a sequence that
           cannot grow even after every younger peer is gone preempts
           itself and waits. Oldest-first, so the oldest request always
           progresses — the policy cannot livelock.
        2. CHUNKS (chunked prefill only): every mid-prefill sequence
           streams its next chunk, oldest first, while budget remains;
           the oldest mid-prefill sequence always gets its chunk even on
           a spent budget (it must finish EVENTUALLY), and decode rows
           are charged before any chunk — a long prompt can no longer
           monopolize a tick the way the legacy sole-prefill rule let it.
        3. ADMIT: prefills from the waiting queue while a slot, enough
           pool blocks (first chunk / whole prompt), and token budget
           remain.
        """
        preempted: List[Sequence] = []
        cow_pairs: List[Tuple[int, int]] = []
        chunk = self.config.prefill_chunk
        # freshly-completed full prompt blocks enter the prefix trie
        # BEFORE admission walks it, so a same-tick follower can hit
        self._register_prefix_blocks()

        # --- grow running sequences (oldest first)
        for seq in sorted(self.running.values(),
                          key=lambda s: s.request.req_id):
            if seq.state is not SequenceState.RUNNING:
                continue  # evicted earlier in this very loop
            if chunk is not None and seq.prefilling:
                step = min(chunk, seq.prefill_len - seq.num_cached)
            else:
                # a decode row scores its last token plus this tick's
                # drafts in one call — blocks must cover every scored
                # slot (rejected drafts' slots are simply overwritten)
                step = 1 + len(seq.draft)
            need = self.blocks_needed(seq.num_cached + step) - len(seq.blocks)
            if need > self.available_blocks() and seq.draft:
                # speculation is opportunistic: shed the drafts before
                # preempting anyone for their scratch space
                seq.draft = []
                step = 1
                need = (
                    self.blocks_needed(seq.num_cached + step)
                    - len(seq.blocks)
                )
            if need > 0:
                while (need > self.available_blocks()
                       and self._preempt_youngest(seq, preempted)):
                    pass
                if need > self.available_blocks():
                    # every younger peer is gone and the pool is still
                    # full: this sequence yields to its elders until
                    # blocks free up
                    self._preempt(seq, preempted)
                    continue
                seq.blocks.extend(self._take(need))
            # copy-on-write: fork any shared block the scored slots
            # would write into (full-block prefix sharing never places
            # writes there, but the invariant is enforced, not assumed).
            # Pairs collect per-sequence: if the fork fails and the
            # sequence is preempted, its dst blocks just returned to the
            # free list — publishing the pairs would have the engine
            # copy into blocks another admission may own by now.
            seq_pairs: List[Tuple[int, int]] = []
            while not self._fork_shared_write_blocks(seq, step, seq_pairs):
                if not self._preempt_youngest(seq, preempted):
                    self._preempt(seq, preempted)
                    seq_pairs = []
                    break
            cow_pairs.extend(seq_pairs)

        # each surviving decoding sequence decodes one token this tick;
        # mid-prefill rows don't decode (they have no token yet) and are
        # charged per chunk below instead
        decoding = [
            s for s in self.running.values()
            if not (chunk is not None and s.prefilling)
        ]
        budget = self.config.token_budget - len(decoding)

        prefills: List[Sequence] = []
        if chunk is not None:
            # already-running mid-prefill sequences stream their next
            # chunk, oldest first; the first one is never budget-starved
            # (decode rows recur every tick — waiting for a slack tick
            # could starve the prompt forever)
            for seq in sorted(self.running.values(),
                              key=lambda s: s.request.req_id):
                if not seq.prefilling:
                    continue
                if budget <= 0 and prefills:
                    break
                prefills.append(seq)
                budget -= min(chunk, seq.prefill_len - seq.num_cached)

        while self.waiting and self._free_slots and budget > 0:
            # pop the head BEFORE any preemption: evicted victims re-enter
            # at the queue front, and the head must not be displaced by
            # the very sequence evicted on its behalf
            head = self.waiting.popleft()
            prompt_tokens = len(head.resume_prompt)
            matched_blocks: List[int] = []
            matched = 0
            if chunk is not None:
                # shared-prefix reuse: map every cached full block of
                # the prompt into the table — their prefill is already
                # paid; only the tail streams chunks
                if self.prefix_cache is not None:
                    matched_blocks, matched = self.prefix_cache.match(
                        head.resume_prompt
                    )
                # chunked mode admits at the chunk budget: the first
                # chunk runs this tick, the rest stream on later ticks.
                # A chunk that would cross the remaining budget defers to
                # the next tick — unless the tick has no prefill work at
                # all (the progress guarantee; overshoot is then bounded
                # by one chunk, never by a whole prompt)
                admit_tokens = min(chunk, prompt_tokens - matched)
                if admit_tokens > budget and prefills:
                    if matched_blocks:
                        self.allocator.free(matched_blocks)
                    self.waiting.appendleft(head)
                    break
                first_blocks = (
                    self.blocks_needed(matched + admit_tokens)
                    - len(matched_blocks)
                )
            else:
                # an over-budget prompt admits only as the tick's sole
                # prefill (a prompt longer than the whole budget must
                # still run EVENTUALLY; making it wait for an idle tick
                # would starve it)
                if prompt_tokens > budget and prefills:
                    self.waiting.appendleft(head)
                    break
                admit_tokens = prompt_tokens
                first_blocks = self.blocks_needed(prompt_tokens)
            need = first_blocks
            while (need > self.available_blocks()
                   and self._preempt_youngest(head, preempted)):
                pass
            if need > self.available_blocks():
                # pool genuinely full; running decodes will free blocks
                if matched_blocks:
                    self.allocator.free(matched_blocks)
                self.waiting.appendleft(head)
                break
            head.blocks = matched_blocks + self._take(need)
            head.slot = self._free_slots.popleft()
            head.state = SequenceState.RUNNING
            head.num_cached = matched
            head.prefix_cached = matched
            head.cached_upto = matched
            head.prefill_len = prompt_tokens
            self.running[head.slot] = head
            self.prefix_hit_tokens += matched
            prefills.append(head)
            budget -= admit_tokens
        # a preempted victim re-admitted this tick can be evicted AGAIN by
        # a still-older head later in the same loop — drop it from the
        # prefill list (its slot is gone; it waits at the queue front)
        prefills = [s for s in prefills if s.state == SequenceState.RUNNING]
        # decodes: running sequences that were NOT just admitted (their
        # prefill emits this tick's token), are not mid-prefill, and
        # survived preemption
        new = {id(s) for s in prefills}
        decodes = [
            self.running[slot] for slot in sorted(self.running)
            if id(self.running[slot]) not in new
            and not (chunk is not None and self.running[slot].prefilling)
        ]
        return Tick(prefills=prefills, decodes=decodes, preempted=preempted,
                    cow_pairs=cow_pairs)

    def _preempt_youngest(self, for_seq: Sequence,
                          preempted: List[Sequence]) -> bool:
        """Evict the most-recently-admitted running sequence to free
        blocks for ``for_seq``. Never preempts on behalf of a YOUNGER
        request (arrival order is the fairness clock), and never empties
        the running set below one sequence — someone must make progress.
        Returns True when a sequence was evicted."""
        if len(self.running) <= 1:
            return False
        youngest_slot = max(
            self.running, key=lambda s: self.running[s].request.req_id
        )
        victim = self.running[youngest_slot]
        if victim.request.req_id <= for_seq.request.req_id:
            return False
        self._preempt(victim, preempted)
        return True

    def _preempt(self, victim: Sequence, preempted: List[Sequence]) -> None:
        self._evict(victim)
        victim.preemptions += 1
        self.preemption_count += 1
        victim.state = SequenceState.WAITING
        self.waiting.appendleft(victim)  # resumes ahead of colder requests
        preempted.append(victim)

    def _evict(self, seq: Sequence) -> None:
        # drops ONE reference per block: private blocks return to the
        # free list, trie-cached blocks stay resident (LRU-evictable) —
        # a preempted prefix-sharing sequence releases only what it owns
        self.allocator.free(seq.blocks)
        seq.blocks = []
        seq.num_cached = 0
        # prefix_cached survives as a post-mortem stat; a re-admission
        # overwrites it with the fresh match
        seq.cached_upto = 0
        seq.draft = []
        self.running.pop(seq.slot)
        self._free_slots.append(seq.slot)
        self._freed_slots.append(seq.slot)
        seq.slot = None

    def drain_freed_slots(self) -> List[int]:
        """Slots vacated since the last drain (engine zeroes their rows)."""
        out, self._freed_slots = self._freed_slots, []
        return out

    # ------------------------------------------------------------ lifecycle
    def finish(self, seq: Sequence) -> None:
        """Completed sequence: recycle its slot and blocks immediately —
        the freed capacity is admissible in the very next tick."""
        assert seq.state == SequenceState.RUNNING and seq.slot is not None
        self._evict(seq)
        seq.state = SequenceState.FINISHED

    @property
    def has_work(self) -> bool:
        return bool(self.waiting or self.running)

    def gauges(self) -> Dict[str, float]:
        """Pool/queue occupancy for the obs registry. ``free`` counts
        grantable capacity — the free list plus evictable prefix-cache
        blocks (resident but reclaimable on demand)."""
        cfg = self.config
        usable = cfg.num_blocks - 1
        free = self.available_blocks()
        out = {
            "serve_running_seqs": float(len(self.running)),
            "serve_waiting_seqs": float(len(self.waiting)),
            "serve_prefilling_seqs": float(
                sum(1 for s in self.running.values() if s.prefilling)
            ),
            "serve_free_blocks": float(free),
            "serve_pool_utilization": (usable - free) / usable if usable
            else 0.0,
            # the admission watermarks' input — exported so a router (or
            # a post-mortem) sees the same number the shed decision saw
            "serve_pool_pressure": self.pool_pressure(),
        }
        if self.prefix_cache is not None:
            out["serve_prefix_cached_blocks"] = float(
                self.prefix_cache.cached_blocks
            )
        return out
