"""Continuous-batching scheduler (Orca, OSDI '22) — host-side policy.

Pure Python, jax-free: every decision the serving engine makes about
WHICH sequences run each tick lives here, unit-testable without a
backend. The engine (engine.py) owns the device programs; this module
owns admission, the per-tick prefill/decode mix under a token budget,
block accounting, preemption on pool exhaustion, and slot recycling.

Preemption is recompute-style (PagedAttention, SOSP '23 §4.5): the
youngest running sequence drops its blocks and re-enters the waiting
queue with ``prompt + generated-so-far`` as its new prompt. Under greedy
sampling the resumed sequence regenerates token-for-token, so preemption
is invisible in the output — the paged-parity tests pin exactly that.
"""

from __future__ import annotations

import dataclasses
import enum
from collections import deque
from typing import Deque, Dict, List, Optional

# block 0 is the TRASH block: never allocated, it absorbs the jitted
# decode step's writes from inactive slots and padding (nn/attention.py
# PagedKVCacheView). Allocators start handing out ids at 1.
TRASH_BLOCK = 0


class SequenceState(enum.Enum):
    WAITING = "waiting"
    RUNNING = "running"
    FINISHED = "finished"


@dataclasses.dataclass
class Request:
    """One inference request as the load generator / API submits it.

    ``temperature`` / ``top_k`` are per-request sampler settings carried
    into the engine's jitted programs as traced per-row arrays
    (inference.sample_rows); ``temperature=0`` (the default) is greedy —
    the zero-temperature special case, not a separate code path."""

    req_id: int
    prompt: List[int]
    max_new_tokens: int
    arrival_s: float = 0.0
    eos_token_id: Optional[int] = None
    temperature: float = 0.0
    top_k: Optional[int] = None


@dataclasses.dataclass
class Sequence:
    """Scheduler-side state of one request's lifetime."""

    request: Request
    state: SequenceState = SequenceState.WAITING
    generated: List[int] = dataclasses.field(default_factory=list)
    slot: Optional[int] = None  # decode-batch row while RUNNING
    blocks: List[int] = dataclasses.field(default_factory=list)
    num_cached: int = 0  # tokens whose KV sits in the pool
    prefill_len: int = 0  # resume-prompt length at (re-)admission
    preemptions: int = 0
    # telemetry stamps (engine fills these; monotonic seconds)
    first_token_s: Optional[float] = None
    finished_s: Optional[float] = None
    token_stamps: List[float] = dataclasses.field(default_factory=list)

    @property
    def resume_prompt(self) -> List[int]:
        """What a (re-)admission must prefill: the original prompt plus
        everything already generated (recompute-style preemption)."""
        return list(self.request.prompt) + list(self.generated)

    @property
    def remaining_tokens(self) -> int:
        return self.request.max_new_tokens - len(self.generated)

    @property
    def prefilling(self) -> bool:
        """RUNNING but the prompt's KV is not fully in the pool yet —
        under chunked prefill such a sequence streams chunks instead of
        decoding (it has no first token to decode from)."""
        return self.slot is not None and self.num_cached < self.prefill_len

    @property
    def done(self) -> bool:
        if len(self.generated) >= self.request.max_new_tokens:
            return True
        eos = self.request.eos_token_id
        return eos is not None and bool(self.generated) and self.generated[-1] == eos


class BlockAllocator:
    """Free-list over the pool's block ids; block 0 (trash) is reserved."""

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError(
                f"pool needs >=2 blocks (1 trash + 1 usable), got {num_blocks}"
            )
        self.num_blocks = num_blocks
        self._free: Deque[int] = deque(range(1, num_blocks))
        self._held: set = set()

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    def alloc(self, n: int) -> List[int]:
        if n > len(self._free):
            raise RuntimeError(
                f"pool exhausted: need {n} block(s), {len(self._free)} free"
            )
        out = [self._free.popleft() for _ in range(n)]
        self._held.update(out)
        return out

    def free(self, blocks: List[int]) -> None:
        for b in blocks:
            if b == TRASH_BLOCK or b not in self._held:
                raise ValueError(f"freeing block {b} not held (double free?)")
            self._held.discard(b)
            self._free.append(b)


@dataclasses.dataclass
class SchedulerConfig:
    num_slots: int = 8  # decode-batch rows (the jitted batch size)
    block_size: int = 16  # tokens per KV block
    num_blocks: int = 128  # pool size incl. the trash block
    max_blocks_per_seq: int = 16  # block-table width (jitted shape)
    token_budget: int = 512  # prompt+decode tokens admitted per tick
    # Sarathi-style chunked prefill: prompts stream into the pool in
    # fixed-size chunks that share the tick budget with decode rows (no
    # prompt ever monopolizes a tick); None = legacy whole-prompt
    # prefill through the pow2 bucket ladder
    prefill_chunk: Optional[int] = None

    def __post_init__(self):
        cap = self.max_blocks_per_seq * self.block_size
        if cap < 2:
            raise ValueError("max_blocks_per_seq * block_size must be >= 2")
        if self.prefill_chunk is not None and self.prefill_chunk < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1 (or None for whole-prompt "
                f"prefill), got {self.prefill_chunk}"
            )


@dataclasses.dataclass
class Tick:
    """One scheduling decision: which sequences do prefill work this
    tick (the whole prompt, or ONE chunk each under chunked prefill),
    which decode, who got preempted to make room."""

    prefills: List[Sequence]
    decodes: List[Sequence]
    preempted: List[Sequence]


class ContinuousBatchingScheduler:
    """Admission + per-tick prefill/decode mix + preemption policy."""

    def __init__(self, config: SchedulerConfig):
        self.config = config
        self.allocator = BlockAllocator(config.num_blocks)
        self.waiting: Deque[Sequence] = deque()
        self.running: Dict[int, Sequence] = {}  # slot -> sequence
        self._free_slots: Deque[int] = deque(range(config.num_slots))
        self.preemption_count = 0
        # slots whose sequence left (finish/preempt) since the engine
        # last synced: their decode-batch rows must be zeroed before the
        # next device step, or stale block tables would write into blocks
        # now owned by someone else
        self._freed_slots: List[int] = []

    # ------------------------------------------------------------ intake
    def add_request(self, request: Request) -> Sequence:
        if request.max_new_tokens < 1:
            # prefill emits one token unconditionally; a 0-budget request
            # would receive a token it never asked for
            raise ValueError(
                f"request {request.req_id}: max_new_tokens must be >= 1, "
                f"got {request.max_new_tokens}"
            )
        if not request.prompt:
            raise ValueError(f"request {request.req_id}: empty prompt")
        cap = self.config.max_blocks_per_seq * self.config.block_size
        need = len(request.prompt) + request.max_new_tokens
        if need > cap:
            raise ValueError(
                f"request {request.req_id} needs {need} KV slots but the "
                f"block table holds {cap} "
                f"(max_blocks_per_seq={self.config.max_blocks_per_seq} x "
                f"block_size={self.config.block_size})"
            )
        usable = self.config.num_blocks - 1  # minus the trash block
        if self.blocks_needed(need) > usable:
            raise ValueError(
                f"request {request.req_id} needs "
                f"{self.blocks_needed(need)} blocks at full length but the "
                f"pool holds {usable} — it could never finish"
            )
        seq = Sequence(request=request)
        self.waiting.append(seq)
        return seq

    # ---------------------------------------------------------- accounting
    def blocks_needed(self, num_tokens: int) -> int:
        bs = self.config.block_size
        return (num_tokens + bs - 1) // bs

    # ------------------------------------------------------------- policy
    def schedule(self) -> Tick:
        """One tick's worth of work.

        1. GROW: every running sequence gets the blocks its next tokens
           need — one decode token, or its next prefill CHUNK under
           chunked prefill (blocks are allocated incrementally, not
           reserved for the whole horizon — that is what lets wildly
           different lengths share one pool). On exhaustion the youngest
           running sequence is preempted recompute-style; a sequence that
           cannot grow even after every younger peer is gone preempts
           itself and waits. Oldest-first, so the oldest request always
           progresses — the policy cannot livelock.
        2. CHUNKS (chunked prefill only): every mid-prefill sequence
           streams its next chunk, oldest first, while budget remains;
           the oldest mid-prefill sequence always gets its chunk even on
           a spent budget (it must finish EVENTUALLY), and decode rows
           are charged before any chunk — a long prompt can no longer
           monopolize a tick the way the legacy sole-prefill rule let it.
        3. ADMIT: prefills from the waiting queue while a slot, enough
           pool blocks (first chunk / whole prompt), and token budget
           remain.
        """
        preempted: List[Sequence] = []
        chunk = self.config.prefill_chunk

        # --- grow running sequences (oldest first)
        for seq in sorted(self.running.values(),
                          key=lambda s: s.request.req_id):
            if seq.state is not SequenceState.RUNNING:
                continue  # evicted earlier in this very loop
            if chunk is not None and seq.prefilling:
                step = min(chunk, seq.prefill_len - seq.num_cached)
            else:
                step = 1
            need = self.blocks_needed(seq.num_cached + step) - len(seq.blocks)
            if need <= 0:
                continue
            while (need > self.allocator.free_blocks
                   and self._preempt_youngest(seq, preempted)):
                pass
            if need <= self.allocator.free_blocks:
                seq.blocks.extend(self.allocator.alloc(need))
            else:
                # every younger peer is gone and the pool is still full:
                # this sequence yields to its elders until blocks free up
                self._preempt(seq, preempted)

        # each surviving decoding sequence decodes one token this tick;
        # mid-prefill rows don't decode (they have no token yet) and are
        # charged per chunk below instead
        decoding = [
            s for s in self.running.values()
            if not (chunk is not None and s.prefilling)
        ]
        budget = self.config.token_budget - len(decoding)

        prefills: List[Sequence] = []
        if chunk is not None:
            # already-running mid-prefill sequences stream their next
            # chunk, oldest first; the first one is never budget-starved
            # (decode rows recur every tick — waiting for a slack tick
            # could starve the prompt forever)
            for seq in sorted(self.running.values(),
                              key=lambda s: s.request.req_id):
                if not seq.prefilling:
                    continue
                if budget <= 0 and prefills:
                    break
                prefills.append(seq)
                budget -= min(chunk, seq.prefill_len - seq.num_cached)

        while self.waiting and self._free_slots and budget > 0:
            # pop the head BEFORE any preemption: evicted victims re-enter
            # at the queue front, and the head must not be displaced by
            # the very sequence evicted on its behalf
            head = self.waiting.popleft()
            prompt_tokens = len(head.resume_prompt)
            if chunk is not None:
                # chunked mode admits at the chunk budget: the first
                # chunk runs this tick, the rest stream on later ticks.
                # A chunk that would cross the remaining budget defers to
                # the next tick — unless the tick has no prefill work at
                # all (the progress guarantee; overshoot is then bounded
                # by one chunk, never by a whole prompt)
                admit_tokens = min(chunk, prompt_tokens)
                if admit_tokens > budget and prefills:
                    self.waiting.appendleft(head)
                    break
                first_blocks = self.blocks_needed(admit_tokens)
            else:
                # an over-budget prompt admits only as the tick's sole
                # prefill (a prompt longer than the whole budget must
                # still run EVENTUALLY; making it wait for an idle tick
                # would starve it)
                if prompt_tokens > budget and prefills:
                    self.waiting.appendleft(head)
                    break
                admit_tokens = prompt_tokens
                first_blocks = self.blocks_needed(prompt_tokens)
            need = first_blocks
            while (need > self.allocator.free_blocks
                   and self._preempt_youngest(head, preempted)):
                pass
            if need > self.allocator.free_blocks:
                # pool genuinely full; running decodes will free blocks
                self.waiting.appendleft(head)
                break
            head.blocks = self.allocator.alloc(need)
            head.slot = self._free_slots.popleft()
            head.state = SequenceState.RUNNING
            head.num_cached = 0
            head.prefill_len = prompt_tokens
            self.running[head.slot] = head
            prefills.append(head)
            budget -= admit_tokens
        # a preempted victim re-admitted this tick can be evicted AGAIN by
        # a still-older head later in the same loop — drop it from the
        # prefill list (its slot is gone; it waits at the queue front)
        prefills = [s for s in prefills if s.state == SequenceState.RUNNING]
        # decodes: running sequences that were NOT just admitted (their
        # prefill emits this tick's token), are not mid-prefill, and
        # survived preemption
        new = {id(s) for s in prefills}
        decodes = [
            self.running[slot] for slot in sorted(self.running)
            if id(self.running[slot]) not in new
            and not (chunk is not None and self.running[slot].prefilling)
        ]
        return Tick(prefills=prefills, decodes=decodes, preempted=preempted)

    def _preempt_youngest(self, for_seq: Sequence,
                          preempted: List[Sequence]) -> bool:
        """Evict the most-recently-admitted running sequence to free
        blocks for ``for_seq``. Never preempts on behalf of a YOUNGER
        request (arrival order is the fairness clock), and never empties
        the running set below one sequence — someone must make progress.
        Returns True when a sequence was evicted."""
        if len(self.running) <= 1:
            return False
        youngest_slot = max(
            self.running, key=lambda s: self.running[s].request.req_id
        )
        victim = self.running[youngest_slot]
        if victim.request.req_id <= for_seq.request.req_id:
            return False
        self._preempt(victim, preempted)
        return True

    def _preempt(self, victim: Sequence, preempted: List[Sequence]) -> None:
        self._evict(victim)
        victim.preemptions += 1
        self.preemption_count += 1
        victim.state = SequenceState.WAITING
        self.waiting.appendleft(victim)  # resumes ahead of colder requests
        preempted.append(victim)

    def _evict(self, seq: Sequence) -> None:
        self.allocator.free(seq.blocks)
        seq.blocks = []
        seq.num_cached = 0
        self.running.pop(seq.slot)
        self._free_slots.append(seq.slot)
        self._freed_slots.append(seq.slot)
        seq.slot = None

    def drain_freed_slots(self) -> List[int]:
        """Slots vacated since the last drain (engine zeroes their rows)."""
        out, self._freed_slots = self._freed_slots, []
        return out

    # ------------------------------------------------------------ lifecycle
    def finish(self, seq: Sequence) -> None:
        """Completed sequence: recycle its slot and blocks immediately —
        the freed capacity is admissible in the very next tick."""
        assert seq.state == SequenceState.RUNNING and seq.slot is not None
        self._evict(seq)
        seq.state = SequenceState.FINISHED

    @property
    def has_work(self) -> bool:
        return bool(self.waiting or self.running)

    def gauges(self) -> Dict[str, float]:
        """Pool/queue occupancy for the obs registry."""
        cfg = self.config
        usable = cfg.num_blocks - 1
        held = usable - self.allocator.free_blocks
        return {
            "serve_running_seqs": float(len(self.running)),
            "serve_waiting_seqs": float(len(self.waiting)),
            "serve_prefilling_seqs": float(
                sum(1 for s in self.running.values() if s.prefilling)
            ),
            "serve_free_blocks": float(self.allocator.free_blocks),
            "serve_pool_utilization": held / usable if usable else 0.0,
        }
