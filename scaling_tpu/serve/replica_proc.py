"""Process-isolated serving replicas (docs/SERVING.md "Process mode").

The threaded fleet (PR 14) scales out to N replicas but they share ONE
Python process: a crash, a poisoned request, or an OOM in any replica
takes down the whole fleet. This module moves each replica into its own
supervised subprocess and makes the fleet survive anything a process
can do to you:

- **Worker** (``python -m scaling_tpu.serve.replica_proc --config f``):
  one :class:`~.engine.ServeEngine` behind a line-JSON RPC loop —
  the SAME newline-JSON-over-TCP idioms as
  ``resilience.controlplane.TcpControlPlaneServer`` (serial accept
  loop, one short-lived thread per connection, 64 KiB request cap,
  catch-all handler), not ad-hoc sockets. Ops: ``submit`` (idempotent
  by req_id — a retried submit never double-enqueues), ``poll``
  (cursor-based, so a lost reply re-ships instead of dropping
  records), ``stats`` (the engine's :meth:`stats_snapshot`, doubling
  as the heartbeat: the reply carries the tick loop's age so a wedged
  loop is visible even while the RPC threads still answer), ``drain``,
  ``shutdown``. The worker journals to the same ``journal_r<id>``
  namespace the threaded fleet uses and warms up BEFORE its rendezvous
  entry appears — readiness is the rendezvous record, appended last.

- **Host**: :class:`ProcReplicaHandle` answers the exact
  :class:`~.router.ReplicaHandle` surface over RPC, so the router's
  policy (least-loaded, hash-based prefix affinity, retry-elsewhere)
  is untouched; every call rides ``retry_io`` with per-call timeouts
  and raises :class:`~.router.ReplicaUnreachable` when the process is
  gone. :func:`classify_replicas` is the ``runner.supervise``
  dead/hung split over (exit code, heartbeat age, loop age):
  non-zero exit -> dead, stale heartbeat past the startup grace ->
  hung (SIGKILLed into dead).

- **Failover** (:class:`FleetSupervisor`): a dead replica's journal is
  harvested (:func:`~.journal.failover_split`) — completed outputs
  fold straight into the run's results, incomplete requests
  re-dispatch to SURVIVORS with their original req_ids + ``force=True``
  (the (request, position) sampler keys make the regenerated tokens
  identical on any replica), and the replica relaunches on the shared
  ``runner.supervise.restart_backoff`` curve under a per-replica
  budget. kill -9 any replica mid-tick and the bench completes with
  every request's tokens identical to a fault-free run.

- **Autoscaling**: the supervisor feeds each tick's stats snapshot to
  :class:`~.router.AutoscalePolicy`; sustained fleet-wide pressure
  spawns a replica (fresh id, fresh journal namespace), sustained
  idle drains the youngest — both budgeted and emitted as structured
  events (``serve-replica-{spawn,drain,restart,give-up}``) that
  ``obs report`` renders in the fleet timeline.

- **Host mode** (docs/SERVING.md "Host mode"): the same worker spawns
  on REMOTE machines through the ``runner/`` host-fleet machinery
  (``runner.runner.ssh_wrap``, hostsfile pools); instead of a loopback
  address file each worker appends its ``host:port`` to a rendezvous
  file under the run dir (``rendezvous.jsonl`` — one O_APPEND line per
  incarnation, the journal's multi-writer-safe idiom) and the spawner
  waits for the matching (replica, incarnation) entry. The line-JSON
  contract is transport-agnostic, so submit/poll/stats/drain work
  unchanged over real network sockets. An RPC submit whose reply is
  lost in a partition is parked IN DOUBT by the router (it may have
  been admitted); it is re-offered to the same replica until a
  definitive answer arrives, and arbitrated against the journal at
  failover — never double-admitted, never lost. Drain and abort also
  ride the ``resilience.controlplane`` flag rails (shared-FS control
  dir) so a fleet-wide SIGTERM reaches workers even when RPC cannot.

Fault points (docs in :mod:`..resilience.faults`):
``serve.replica.spawn`` (host, per launch), ``serve.replica.rpc``
(worker, per handled request; advisory ``drop``/``delay``/``partition``
sub-actions emulate the network), ``serve.replica.net_partition``
(worker, before a request is even looked at — the host-scoped partition
drill), ``serve.replica.rendezvous`` (both sides of the rendezvous
file), ``serve.replica.kill`` (worker, before each tick while it has
work — the mid-stream SIGKILL drill).

Host side is jax-free; only the worker imports the engine (each
process owns its devices, so the GIL lessons from PR 14 disappear by
construction).
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import threading
import time
from pathlib import Path
from typing import Callable, Dict, List, Optional, Tuple

from .. import obs
from ..logging import logger
from ..obs import span
from ..resilience.faults import get_fault_plan
from ..resilience.guards import retry_io
from ..runner.runner import LOCAL_HOSTS, ssh_wrap
from ..runner.supervise import remote_pkill, restart_backoff
from .journal import failover_split, journal_path, submitted_ids
from .router import (
    AutoscalePolicy,
    FleetRouter,
    ReplicaStats,
    ReplicaUnreachable,
)
from .scheduler import Backpressure

# worker startup can sit inside a cold jit compile for minutes off-TPU;
# the grace both bounds the host's ready-wait and suppresses hung
# verdicts while the first programs build (runner.supervise's rule)
DEFAULT_STARTUP_GRACE_S = 180.0
DEFAULT_HEARTBEAT_TIMEOUT_S = 10.0
# a drained worker without a shutdown op exits on its own after this
# (the host died — don't serve a dead fleet forever)
DEFAULT_LINGER_S = 60.0


def _atomic_write(path, text: str) -> None:
    """tmp + rename so a reader never observes a torn file (the
    control plane's address-file idiom). Worker-config writes share
    the rendezvous file's failure drill: ``retry_io`` with the
    ``serve.replica.rendezvous`` fault point inside the retried op —
    a transient shared-FS error must not abort a spawn."""
    p = Path(path)
    tmp = p.with_name(p.name + ".tmp")

    def op():
        get_fault_plan().fire("serve.replica.rendezvous", path=p)
        tmp.write_text(text)
        os.replace(tmp, p)

    retry_io(op, what="replica worker config write")


# ========================================================= rendezvous
RENDEZVOUS_NAME = "rendezvous.jsonl"


def rendezvous_file(run_dir) -> Path:
    return Path(run_dir) / RENDEZVOUS_NAME


def publish_rendezvous(path, record: dict) -> None:
    """Append one replica's address record to the rendezvous file.

    One whole line per O_APPEND write — the request journal's
    multi-writer idiom: N workers on N machines share one shared-FS
    file and never tear each other's records. Rides ``retry_io`` with
    the ``serve.replica.rendezvous`` fault point inside the retried op
    (a transient shared-FS error at publish time must not kill a
    freshly warmed worker)."""
    line = json.dumps(record) + "\n"

    def op():
        get_fault_plan().fire("serve.replica.rendezvous", path=path)
        with open(path, "a") as f:
            f.write(line)

    retry_io(op, what="replica rendezvous publish")


def read_rendezvous(path) -> Dict[int, dict]:
    """Newest rendezvous record per replica id (later incarnations of
    a relaunched replica append later lines and win). Tolerant of a
    torn tail line — a reader racing a writer's O_APPEND sees at most
    one partial record, never a corrupted earlier one."""

    def op():
        get_fault_plan().fire("serve.replica.rendezvous", path=path)
        p = Path(path)
        if not p.is_file():
            return {}
        out: Dict[int, dict] = {}
        for line in p.read_text().splitlines():
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
                out[int(rec["replica"])] = rec
            except (ValueError, KeyError, TypeError):
                continue  # torn tail / foreign line: skip, never raise
        return out

    return retry_io(op, what="replica rendezvous read")


# ======================================================== worker side
class ReplicaRpcServer:
    """Line-JSON RPC server for ONE replica worker — the
    ``TcpControlPlaneServer`` idioms verbatim: serial accept loop with
    a short timeout, one short-lived daemon thread per connection (an
    idle prober must not park the accept loop for its full read
    timeout), bounded request lines, and a catch-all handler (a
    malformed request logs a warning and drops the reply; the host's
    retry layer owns the recovery)."""

    MAX_REQUEST_BYTES = 64 * 1024

    def __init__(self, handler: Callable[[dict], dict],
                 host: str = "127.0.0.1", port: int = 0):
        self._handler = handler
        # stays raw: one-time server bind at worker startup — a port
        # conflict or bad address is a config error that must abort the
        # worker loudly, not retry (host REQUESTS ride retry_io)
        self._sock = socket.socket(  # sta: disable=STA011
            socket.AF_INET, socket.SOCK_STREAM
        )
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        # short accept timeout set BEFORE the thread starts: a close()
        # racing the loop's first line must find the timeout installed,
        # not a raw settimeout on an already-closed fd
        self._sock.settimeout(0.2)
        self.address = f"{host}:{self._sock.getsockname()[1]}"
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._serve, name="replica-rpc-server", daemon=True
        )
        self._thread.start()

    def _serve(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # socket closed under us during shutdown
            threading.Thread(
                target=self._handle_conn, args=(conn,), daemon=True
            ).start()

    def _handle_conn(self, conn: socket.socket) -> None:
        try:
            with conn:
                conn.settimeout(5.0)
                data = conn.makefile("r").readline(self.MAX_REQUEST_BYTES)
                if len(data) >= self.MAX_REQUEST_BYTES \
                        and not data.endswith("\n"):
                    raise ValueError(
                        f"request line exceeds "
                        f"{self.MAX_REQUEST_BYTES} bytes"
                    )
                reply = self._handler(json.loads(data))
                conn.sendall((json.dumps(reply) + "\n").encode())
        except Exception as e:
            # survive ANY malformed request or injected rpc fault: an
            # uncaught error kills the thread silently and drops the
            # reply — the host retries, which is the designed window
            logger.warning(f"replica rpc request failed: {e!r}")

    def close(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError as e:
            logger.debug(f"replica rpc server close: {e!r}")
        self._thread.join(timeout=5)


class _ReplicaWorker:
    """One engine + its RPC surface; ``run`` is the tick loop."""

    # _loop_wall is written by the tick loop and read by RPC handler
    # threads with no lock ON PURPOSE: it is a monotonic float beat
    # (GIL-atomic store), and taking the tick lock to read it would
    # make the heartbeat blind to exactly the wedged-tick state it
    # exists to expose. (No `# sta: lock` annotation: the RPC threads
    # are spawned by ReplicaRpcServer, not this class, so the analyzer
    # models no hazard here — a stale annotation would only pre-silence
    # a future real one.)

    def __init__(self, engine, linger_s: float = DEFAULT_LINGER_S,
                 host_id: Optional[int] = None, control=None):
        self.engine = engine
        self.linger_s = linger_s
        self.host_id = host_id
        # optional FileControlPlane over the run dir: the drain/abort
        # flag rail that reaches this worker even when RPC cannot
        self.control = control
        self.dup_submits = 0  # idempotency hits: retried submits deduped
        self.tick_lock = threading.Lock()
        self.shutdown = threading.Event()
        self._loop_wall = time.monotonic()
        self._last_flag_poll = 0.0

    # ------------------------------------------------------------ ops
    def _knows(self, req_id: int) -> bool:
        sched = self.engine.scheduler
        seqs = list(sched.running.values()) + list(sched.waiting) \
            + list(self.engine.finished)
        return any(s.request.req_id == req_id for s in seqs)

    @staticmethod
    def _record(seq) -> dict:
        stamps = seq.token_stamps
        return {
            "req": seq.request.req_id,
            "status": seq.finish_status,
            "toks": [int(t) for t in seq.generated],
            "prompt_len": len(seq.request.prompt),
            "ttft_s": (
                seq.first_token_s - seq.request.arrival_s
                if seq.first_token_s is not None else None
            ),
            "itls": [round(b - a, 6) for a, b in zip(stamps, stamps[1:])],
        }

    def handle(self, req: dict) -> dict:
        # the partition drill fires BEFORE the request is even looked
        # at: on an armed hit the packet "never arrived" — no state
        # change, no reply, the host's retry/in-doubt machinery owns it
        if get_fault_plan().fire("serve.replica.net_partition") \
                in ("partition", "drop"):
            raise OSError("injected network partition: request dropped")
        act = get_fault_plan().fire("serve.replica.rpc")
        if act == "delay":
            time.sleep(0.25)  # a slow or congested link
        elif act == "partition":
            raise OSError("injected rpc partition: request dropped")
        reply = self._dispatch(req)
        if act == "drop":
            # the request WAS served (a submit is admitted, journaled);
            # only the reply dies — the precise ambiguity window the
            # idempotent-submit dedup and in-doubt parking exist for
            raise OSError("injected rpc drop: reply lost after dispatch")
        return reply

    def _dispatch(self, req: dict) -> dict:
        tr = req.get("trace") or {}
        if tr.get("trace_id"):
            # adopt the envelope's inbound trace for this dispatch (the
            # handler runs on a per-connection thread, so adoption is
            # naturally per-request): the engine's admit span, journal
            # submit record and any spans opened here all inherit the
            # ORIGINATING request's trace across the process boundary
            with obs.trace_context(tr["trace_id"], tr.get("parent_span_id")):
                return self._dispatch_op(req)
        return self._dispatch_op(req)

    def _dispatch_op(self, req: dict) -> dict:
        op = req.get("op")
        if op == "submit":
            kw = dict(req.get("kw") or {})
            rid = kw.get("req_id")
            if rid is not None and self._knows(int(rid)):
                # at-least-once made exactly-once: the first attempt's
                # reply was lost; re-enqueueing would serve the request
                # twice (identical tokens — same sampler keys — but
                # double the compute and inflated counts)
                self.dup_submits += 1
                return {"ok": True, "admitted": True, "req": int(rid),
                        "dup": True}
            # NOT under tick_lock: ServeEngine.submit only appends to
            # the waiting deque and reads load state (the PR 14 rule —
            # serializing submits behind the tick starved admission)
            res = self.engine.submit(
                req["prompt"], int(req["max_new_tokens"]), **kw
            )
            if isinstance(res, Backpressure):
                return {"ok": True, "admitted": False, "bp": {
                    "reason": res.reason,
                    "pool_pressure": res.pool_pressure,
                    "waiting": res.waiting,
                    "draining": res.draining,
                }}
            return {"ok": True, "admitted": True,
                    "req": res.request.req_id}
        if op == "stats":
            return {"ok": True, "stats": self.engine.stats_snapshot(),
                    "loop_age_s": time.monotonic() - self._loop_wall,
                    "host": self.host_id,
                    "dups": self.dup_submits}
        if op == "poll":
            # cursor-based and read-only: a reply lost to a retry
            # re-ships the same suffix instead of dropping it
            fin = list(self.engine.finished)
            start = max(0, int(req.get("from", 0)))
            return {"ok": True,
                    "finished": [self._record(s) for s in fin[start:]],
                    "total": len(fin)}
        if op == "drain":
            with self.tick_lock:
                self.engine.begin_drain()
            return {"ok": True}
        if op == "shutdown":
            self.shutdown.set()
            return {"ok": True}
        return {"ok": False, "error": f"unknown op {op!r}"}

    # ------------------------------------------------------ tick loop
    def _poll_control_flags(self) -> Optional[int]:
        """Check the control plane's drain/abort flags (throttled to
        ~4 Hz — whole-file reads on a shared FS). Returns an exit code
        to return from the loop, or None to keep running. This is the
        RPC-independent rail: a partitioned or dying host can still
        drain/abort the whole fleet through the shared control dir."""
        if self.control is None:
            return None
        now = time.monotonic()
        if now - self._last_flag_poll < 0.25:
            return None
        self._last_flag_poll = now
        if self.control.get_flag("serve-abort"):
            logger.warning("control-plane abort flag set; replica exiting")
            return 1
        if not self.engine.draining \
                and self.control.get_flag("serve-drain"):
            logger.warning("control-plane drain flag set; draining")
            with self.tick_lock:
                self.engine.begin_drain()
        return None

    def run(self) -> int:
        idle_since: Optional[float] = None
        while True:
            self._loop_wall = time.monotonic()
            rc = self._poll_control_flags()
            if rc is not None:
                return rc
            if self.engine.scheduler.has_work:
                idle_since = None
                # the chaos drill's SIGKILL lands here: requests are in
                # flight, tokens are mid-stream, the journal has submit
                # records with no terminal status
                get_fault_plan().fire("serve.replica.kill")
                with self.tick_lock:
                    if self.engine.scheduler.has_work:
                        self.engine.tick()
                continue
            if self.shutdown.is_set():
                return 0
            if self.engine.draining:
                if idle_since is None:
                    idle_since = time.monotonic()
                elif time.monotonic() - idle_since > self.linger_s:
                    # drained and the host never said shutdown: the
                    # host is gone — don't serve a dead fleet forever
                    logger.warning(
                        "replica drained and host silent for "
                        f"{self.linger_s:.0f}s; exiting"
                    )
                    return 0
            time.sleep(0.001)


def worker_main(argv: Optional[List[str]] = None) -> int:
    """Entry point of one replica subprocess: build the engine, warm it
    up, start the RPC server, append the rendezvous record (the
    readiness signal — LAST, so the host never routes to a replica
    still inside its cold jit compile), then run the tick loop."""
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m scaling_tpu.serve.replica_proc"
    )
    parser.add_argument("--config", required=True,
                        help="worker config JSON written by the host")
    args = parser.parse_args(argv)
    cfg = json.loads(retry_io(
        Path(args.config).read_text, what="replica config read"
    ))
    replica_id = int(cfg["replica_id"])

    from ..obs import get_registry

    if cfg.get("metrics_path"):
        # the sink appends whole lines via one O_APPEND write, so N
        # worker processes sharing the host's file never tear records
        get_registry().configure(metrics_path=cfg["metrics_path"])

    from .bench import build_toy_inference
    from .engine import EngineConfig, ServeEngine, install_drain_handler
    from .journal import RequestJournal

    inf = build_toy_inference(**cfg["toy"])
    engine = ServeEngine(
        inf, EngineConfig(replica_id=replica_id, **cfg["engine"])
    )
    install_drain_handler(engine)  # direct SIGTERM drains this replica
    warmup = int(cfg.get("warmup", 0))
    if warmup > 0:
        engine.warmup_mode = True
        for _ in range(warmup):
            engine.submit([1], 2)
        engine.run_until_done()
        engine.warmup_mode = False
        engine.finished.clear()
    # attach AFTER warmup: the journal stream starts at the first real
    # request (warmup_mode guards too — this is belt and braces)
    engine.attach_journal(RequestJournal(cfg["journal"]))

    host_id = cfg.get("host_id")
    control = None
    if cfg.get("control_dir"):
        from ..resilience.controlplane import (
            FileControlPlane,
            log_clock_offset,
        )

        control = FileControlPlane(
            cfg["control_dir"],
            host_id=int(host_id) if host_id is not None else replica_id,
            num_hosts=int(cfg.get("num_hosts", 1)),
        )
        # stamp this worker host's clock skew into the shared event
        # stream so obs trace can order its spans against the router's
        log_clock_offset(control)
    worker = _ReplicaWorker(
        engine, linger_s=float(cfg.get("linger_s", DEFAULT_LINGER_S)),
        host_id=int(host_id) if host_id is not None else None,
        control=control,
    )
    # host mode binds all interfaces and advertises the hostsfile name;
    # single-box mode keeps the loopback default
    server = ReplicaRpcServer(
        worker.handle, host=cfg.get("bind_host", "127.0.0.1")
    )
    port = server.address.rsplit(":", 1)[1]
    advertise = f"{cfg['advertise_host']}:{port}" \
        if cfg.get("advertise_host") else server.address
    # readiness signal LAST: warmup is done, the server is accepting
    publish_rendezvous(cfg["rendezvous_path"], {
        "replica": replica_id,
        "host": host_id,
        "addr": advertise,
        "pid": os.getpid(),
        "incarnation": int(cfg.get("incarnation", 0)),
    })
    logger.log_event(
        "serve-replica-ready", replica=replica_id, address=advertise,
        host=host_id,
    )
    try:
        return worker.run()
    finally:
        server.close()


# ========================================================== host side
class ReplicaProcClient:
    """RPC client for one replica worker — the ``TcpControlPlane``
    client idioms: a fresh connection per request, bounded retries for
    transport errors, protocol errors (``ok=false``) never retried."""

    def __init__(self, address: str, timeout_s: float = 5.0):
        host, port = address.rsplit(":", 1)
        self._addr = (host, int(port))
        self._timeout = timeout_s
        self.retries = 0  # transport retries taken (partition forensics)

    def _request_once(self, req: dict, state: Optional[dict] = None) -> dict:
        with socket.create_connection(self._addr, self._timeout) as conn:
            conn.sendall((json.dumps(req) + "\n").encode())
            if state is not None:
                # the request LEFT this host: from here on a failure is
                # ambiguous — the worker may have processed it and only
                # the reply died (the partition's in-doubt window).
                # A refused connection above never sets this.
                state["sent"] = True
            line = conn.makefile("r").readline()
            if not line:
                # the worker's catch-all dropped our reply (injected
                # rpc fault, malformed frame): transport-level, retried
                raise OSError("empty rpc reply (connection closed)")
            return json.loads(line)

    def request(self, req: dict, attempts: int = 3) -> dict:
        state = {"sent": False, "calls": 0}

        def once():
            state["calls"] += 1
            return self._request_once(req, state)

        try:
            reply = retry_io(
                once,
                attempts=attempts,
                retry_on=(OSError, ValueError),
                what=f"replica rpc {req.get('op')!r}",
            )
        except (OSError, ValueError) as e:
            self.retries += max(0, state["calls"] - 1)
            err = ReplicaUnreachable(
                f"replica at {self._addr[0]}:{self._addr[1]} "
                f"unreachable for {req.get('op')!r}: {e!r}"
            )
            # True when any attempt got past sendall: the op may have
            # executed worker-side. The router parks such a submit in
            # doubt instead of re-dispatching it to another replica.
            err.maybe_admitted = state["sent"]
            raise err from e
        self.retries += max(0, state["calls"] - 1)
        if not reply.get("ok"):
            raise RuntimeError(f"replica rpc {req} failed: {reply}")
        return reply


class RemoteAdmit:
    """A submit admitted by a subprocess replica — the process-mode
    stand-in for the in-process :class:`~.scheduler.Sequence` (the
    router only needs "not Backpressure"; outputs ship via ``poll``)."""

    __slots__ = ("req_id", "replica_id")

    def __init__(self, req_id: int, replica_id: int):
        self.req_id = req_id
        self.replica_id = replica_id


class ProcReplicaHandle:
    """The :class:`~.router.ReplicaHandle` surface over a subprocess
    replica: same attributes the router dispatches through
    (``replica_id`` / ``alive`` / ``lock`` / ``stats`` /
    ``block_size``), RPC behind each method. Load answers come from
    the newest ``stats`` snapshot (refreshed every supervisor tick) —
    dispatch reads a cache instead of paying an RPC round-trip per
    submit attempt."""

    def __init__(self, replica_id: int, proc, client: ReplicaProcClient,
                 block_size: int, host_id: Optional[int] = None,
                 hostname: Optional[str] = None,
                 cfg_path: Optional[str] = None):
        self.engine = None  # no in-process engine behind this handle
        self.replica_id = replica_id
        self.alive = True
        self.lock = threading.Lock()
        self.stats = ReplicaStats()
        self.proc = proc
        self.client = client
        self.block_size = block_size
        self.host_id = host_id  # placement: which fleet host runs it
        self.hostname = hostname  # None/localhost -> local subprocess
        self.cfg_path = cfg_path  # remote pkill marker (unique/replica)
        self.spawn_wall = time.monotonic()
        self.last_ok_wall = self.spawn_wall
        self.last_loop_age_s = 0.0
        self.last_stats: dict = {}
        self.last_dups = 0  # worker-side deduped submit retries
        self.rpc_retries_banked = 0  # retries from replaced clients
        self.restarts = 0
        self.retired = False  # drained away by the autoscaler
        self.poll_cursor = 0
        self.ticks_banked = 0  # ticks from incarnations since replaced

    # ---------------------------------------------------------- rpc
    def _rpc(self, req: dict, attempts: int = 3) -> dict:
        with span("serve.replica.rpc_client", op=req.get("op"),
                  replica=self.replica_id, level="debug"):
            reply = self.client.request(req, attempts=attempts)
        self.last_ok_wall = time.monotonic()
        return reply

    def refresh(self) -> dict:
        """``stats`` RPC — the heartbeat: a successful reply refreshes
        ``last_ok_wall`` and the load cache; the reported loop age
        exposes a wedged tick loop whose RPC threads still answer."""
        reply = self._rpc({"op": "stats", "trace": obs.current_trace()})
        self.last_stats = reply["stats"]
        self.last_loop_age_s = float(reply.get("loop_age_s", 0.0))
        self.last_dups = int(reply.get("dups", 0))
        return self.last_stats

    @property
    def rpc_retries(self) -> int:
        return self.rpc_retries_banked + self.client.retries

    def kill(self) -> None:
        """SIGKILL this replica's worker. For a remote replica the
        local Popen is only the ssh client, so killing it strands the
        worker — an ssh pkill on the per-replica config path (unique
        marker) reaps the remote process too."""
        get_fault_plan().fire(
            "serve.replica.teardown", replica=self.replica_id
        )
        with span("serve.replica.teardown", replica=self.replica_id):
            try:
                self.proc.kill()
                self.proc.wait(timeout=10)
            except OSError as e:
                logger.warning(
                    f"SIGKILL replica {self.replica_id} failed: {e!r}"
                )
            if self.hostname and self.hostname not in LOCAL_HOSTS \
                    and self.cfg_path:
                remote_pkill(self.hostname, str(self.cfg_path), "KILL")

    def poll_finished(self) -> List[dict]:
        """Ship finished-request records the host has not seen yet
        (cursor-based: a lost reply re-ships, never drops)."""
        reply = self._rpc({"op": "poll", "from": self.poll_cursor,
                           "trace": obs.current_trace()})
        recs = reply["finished"]
        self.poll_cursor = int(
            reply.get("total", self.poll_cursor + len(recs))
        )
        return recs

    def request_shutdown(self) -> None:
        try:
            self._rpc({"op": "shutdown", "trace": obs.current_trace()},
                      attempts=1)
        except (ReplicaUnreachable, RuntimeError):
            pass  # already gone — that's what shutdown wanted anyway

    def rebind(self, fresh: "ProcReplicaHandle") -> None:
        """Point this handle at a relaunched worker process (the router
        identity — id, dispatch stats — survives the relaunch)."""
        # bank the dead incarnation's tick count (best effort: as of its
        # last heartbeat) so the summary's fleet tick total survives
        self.ticks_banked += int(self.last_stats.get("tick", 0))
        self.rpc_retries_banked += self.client.retries
        self.proc = fresh.proc
        self.client = fresh.client
        self.hostname = fresh.hostname
        self.host_id = fresh.host_id if fresh.host_id is not None \
            else self.host_id
        self.cfg_path = fresh.cfg_path or self.cfg_path
        self.spawn_wall = fresh.spawn_wall
        self.last_ok_wall = fresh.last_ok_wall
        self.last_stats = {}
        self.last_loop_age_s = 0.0
        self.poll_cursor = 0
        self.restarts += 1

    # ------------------------------------------- ReplicaHandle surface
    def load(self) -> Tuple[int, float]:
        s = self.last_stats
        return (int(s.get("queue_depth", 0)),
                float(s.get("pool_pressure", 0.0)))

    def submit(self, prompt: List[int], max_new_tokens: int, **kwargs):
        # arrival_s is the HOST's monotonic clock — meaningless in the
        # worker process; the worker stamps admission itself
        kwargs.pop("arrival_s", None)
        reply = self._rpc({
            "op": "submit",
            "prompt": [int(t) for t in prompt],
            "max_new_tokens": int(max_new_tokens),
            "kw": kwargs,
            # the propagation contract (docs/OBSERVABILITY.md
            # "Tracing", enforced by STA016): every envelope carries
            # the ambient trace context — None outside one — so the
            # worker's dispatch adopts the originating request's trace
            "trace": obs.current_trace(),
        })
        if not reply.get("admitted"):
            bp = reply["bp"]
            return Backpressure(
                reason=bp["reason"],
                pool_pressure=float(bp["pool_pressure"]),
                waiting=int(bp["waiting"]),
                draining=bool(bp["draining"]),
            )
        # optimistic: the fleet loop's exit check reads cached
        # has_work, and the next stats refresh may be a tick away
        self.last_stats["has_work"] = True
        return RemoteAdmit(int(reply["req"]), self.replica_id)

    def begin_drain(self) -> None:
        try:
            self._rpc({"op": "drain", "trace": obs.current_trace()})
        except ReplicaUnreachable:
            pass  # dead replica: the supervisor's liveness pass owns it

    @property
    def has_work(self) -> bool:
        return bool(self.last_stats.get("has_work", False))

    def next_req_id(self) -> int:
        return int(self.last_stats.get("next_req_id", 0))

    def queue_sizes(self) -> Tuple[int, int]:
        s = self.last_stats
        return int(s.get("running", 0)), int(s.get("waiting", 0))


# env keys a remote replica worker needs exported over ssh (the config
# file itself rides the shared-FS run dir)
_REMOTE_ENV_KEYS = (
    "SCALING_TPU_HOST_ID", "SCALING_TPU_FAULTS",
    "SCALING_TPU_EVENTS_PATH", "SCALING_TPU_TEST_CACHE",
    "JAX_PLATFORMS", "XLA_FLAGS", "PYTHONPATH",
)


def spawn_replica_proc(replica_id: int, worker_cfg: dict, run_dir,
                       *, env: Optional[dict] = None,
                       ready_timeout_s: float = DEFAULT_STARTUP_GRACE_S,
                       hostname: Optional[str] = None,
                       host_id: Optional[int] = None,
                       ) -> ProcReplicaHandle:
    """Launch ONE replica worker and wait for its readiness signal.

    Writes the worker config, spawns the subprocess — locally, or on
    ``hostname`` through the runner's ssh wrapping when the host is not
    this machine (the run dir is assumed shared-FS, the launch
    contract) — and blocks until the worker's rendezvous record for
    THIS incarnation appears. ``SCALING_TPU_HOST_ID`` is the fake/real
    host id in host mode (``@host=K`` fault selectors target a whole
    host) and the replica id single-box. Raises OSError when the worker
    dies during startup or the grace expires — the supervisor's
    budgeted backoff absorbs it."""
    get_fault_plan().fire("serve.replica.spawn")
    run_dir = Path(run_dir)
    cfg_path = run_dir / f"replica_{replica_id}.json"
    rdv_path = rendezvous_file(run_dir)
    # a relaunch must not mistake the dead incarnation's entry for
    # readiness: each spawn claims the next incarnation number and the
    # wait below matches on it
    prev = read_rendezvous(rdv_path).get(replica_id)
    incarnation = int(prev["incarnation"]) + 1 if prev else 0
    cfg = dict(
        worker_cfg, replica_id=replica_id,
        rendezvous_path=str(rdv_path),
        incarnation=incarnation,
        host_id=host_id,
        journal=str(journal_path(worker_cfg["journal_base"], replica_id)),
    )
    cfg.pop("journal_base", None)
    remote = hostname is not None and hostname not in LOCAL_HOSTS
    if remote:
        cfg.setdefault("bind_host", "0.0.0.0")
        cfg.setdefault("advertise_host", hostname)
    text = json.dumps(cfg, indent=1)
    retry_io(lambda: cfg_path.write_text(text),
             what="replica config write")
    child_env = dict(os.environ if env is None else env)
    child_env["SCALING_TPU_HOST_ID"] = str(
        host_id if host_id is not None else replica_id
    )
    cmd = [sys.executable, "-m", "scaling_tpu.serve.replica_proc",
           "--config", str(cfg_path)]
    with span("serve.replica.spawn", replica=replica_id, host=host_id):
        if remote:
            exports = {k: child_env[k] for k in _REMOTE_ENV_KEYS
                       if k in child_env}
            proc = subprocess.Popen(ssh_wrap(hostname, cmd, exports))
        else:
            proc = subprocess.Popen(cmd, env=child_env)
        deadline = time.monotonic() + ready_timeout_s
        addr = None
        while True:
            rec = read_rendezvous(rdv_path).get(replica_id)
            if rec is not None \
                    and int(rec.get("incarnation", -1)) == incarnation:
                addr = str(rec["addr"])
                break
            rc = proc.poll()
            if rc is not None:
                raise OSError(
                    f"replica {replica_id} died during startup (rc={rc})"
                )
            if time.monotonic() > deadline:
                proc.kill()
                raise OSError(
                    f"replica {replica_id} not ready within "
                    f"{ready_timeout_s:.0f}s"
                )
            time.sleep(0.05)
    return ProcReplicaHandle(
        replica_id, proc, ReplicaProcClient(addr),
        int(cfg["engine"]["block_size"]),
        host_id=host_id, hostname=hostname, cfg_path=str(cfg_path),
    )


# ---------------------------------------------------------- liveness
def classify_replicas(
    rows: List[dict],
    *,
    heartbeat_timeout_s: float = DEFAULT_HEARTBEAT_TIMEOUT_S,
    startup_grace_s: float = DEFAULT_STARTUP_GRACE_S,
    now: Optional[float] = None,
) -> Dict[str, List[int]]:
    """Split the fleet's replicas into dead / hung / alive — the
    ``runner.supervise.classify_workers`` policy over per-replica rows
    ``{replica, exit_code, spawn_wall, last_ok_wall, loop_age_s,
    retired, draining}``.

    *dead*: the process exited non-zero (SIGKILL is negative).
    *hung*: still running but the heartbeat is stale — and the startup
    grace has passed (cold jit compiles legitimately go silent for
    minutes). An exit-0 or retired (autoscale-drained) replica is
    neither alive nor dead. Pure function: the detection policy is
    unit-testable with literal timestamps.

    Clock discipline (the PR 4 controlplane rule): every timestamp here
    lives on the HOST's monotonic clock. ``last_ok_wall`` is stamped by
    the host at RPC-reply receipt; ``loop_age_s`` is the worker's
    self-reported tick-loop age AT that receipt — a remote-measured
    DURATION, which is skew-free, shifted onto the host timeline by
    adding it to the receipt gap. The last known loop beat is therefore
    ``last_ok_wall - loop_age_s`` (host clock), and staleness is
    ``now - that``. Never compare a remote machine's monotonic or wall
    reading against the host clock directly: two uptimes have unrelated
    origins, and NTP-sized wall skew dwarfs a 10s heartbeat window."""
    now = time.monotonic() if now is None else now
    dead: List[int] = []
    hung: List[int] = []
    alive: List[int] = []
    for r in rows:
        if r.get("retired"):
            continue  # drained on purpose: winding down, never hung
        rc = r.get("exit_code")
        if rc is not None:
            if rc != 0:
                dead.append(r["replica"])
            continue  # exited 0: finished/drained, not alive, not dead
        # time since the worker's tick loop last provably beat, on the
        # host timeline: receipt gap + the loop's age at receipt. A
        # wedged loop whose RPC threads still answer keeps the gap near
        # zero but its reported age grows, so it cannot hide.
        age = (now - r["last_ok_wall"]) \
            + max(0.0, float(r.get("loop_age_s", 0.0)))
        in_grace = now - r["spawn_wall"] <= startup_grace_s
        if age > heartbeat_timeout_s and not in_grace \
                and not r.get("draining"):
            hung.append(r["replica"])
        else:
            alive.append(r["replica"])
    return {"dead": dead, "hung": hung, "alive": alive}


class FleetSupervisor:
    """Liveness + failover + relaunch + autoscaling for a fleet of
    :class:`ProcReplicaHandle` replicas.

    ``tick(now)`` runs one supervision pass on the host thread (the
    proc-mode bench is single-threaded by design — no tick threads, no
    cross-thread router state): refresh heartbeats, classify, SIGKILL
    the hung, fail over the dead (journal harvest + re-dispatch to
    survivors + budgeted relaunch on the shared backoff curve), launch
    due relaunches, and execute the autoscale policy's decision."""

    def __init__(self, router: FleetRouter,
                 spawn_fn: Callable[[int], ProcReplicaHandle],
                 journal_base,
                 *,
                 restart_budget: int = 3,
                 restart_backoff_s: float = 0.5,
                 heartbeat_timeout_s: float = DEFAULT_HEARTBEAT_TIMEOUT_S,
                 startup_grace_s: float = DEFAULT_STARTUP_GRACE_S,
                 policy: Optional[AutoscalePolicy] = None,
                 on_drain: Optional[Callable[
                     [ProcReplicaHandle], None]] = None):
        self.router = router
        self.spawn_fn = spawn_fn
        self.journal_base = journal_base
        self.restart_budget = restart_budget
        self.restart_backoff_s = restart_backoff_s
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.startup_grace_s = startup_grace_s
        self.policy = policy
        # called with a replica about to be autoscale-drained, while it
        # still answers RPCs — the bench's last-poll hook (finished
        # records past the caller's cursor would vanish with the worker)
        self.on_drain = on_drain
        # failover harvest: outputs delivered by dead replicas before
        # they died (completed terminal status in their journal)
        self.recovered: Dict[int, List[int]] = {}
        self.recovered_timeouts = 0
        # incomplete submit records awaiting a live replica (non-empty
        # only when the WHOLE fleet was down at failover time)
        self.orphans: List[dict] = []
        self.restarts = 0  # relaunches performed (fleet-wide)
        self.redispatched = 0  # orphans re-served by survivors
        self._attempts: Dict[int, int] = {}  # per-replica restart count
        self._relaunch_due: Dict[int, dict] = {}
        self.gave_up: List[int] = []

    # ------------------------------------------------------ liveness
    def _snapshot_rows(self) -> List[dict]:
        rows = []
        for h in self.router.replicas:
            rows.append({
                "replica": h.replica_id,
                "host": h.host_id,
                "exit_code": h.proc.poll(),
                "spawn_wall": h.spawn_wall,
                "last_ok_wall": h.last_ok_wall,
                "loop_age_s": h.last_loop_age_s,
                "retired": h.retired,
                "draining": bool(h.last_stats.get("draining", False)),
            })
        return rows

    def tick(self, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        for h in self.router.replicas:
            if not h.alive or h.retired:
                continue
            try:
                h.refresh()
            except ReplicaUnreachable:
                pass  # classified below from exit code / heartbeat age
            except RuntimeError as e:
                logger.warning(f"replica {h.replica_id} stats: {e!r}")
        # re-offer in-doubt submits (lost replies) to their replicas:
        # a healed partition answers dup/admitted and the park clears
        self.router.resolve_in_doubt()
        cls = classify_replicas(
            self._snapshot_rows(),
            heartbeat_timeout_s=self.heartbeat_timeout_s,
            startup_grace_s=self.startup_grace_s,
            now=now,
        )
        for rid in cls["hung"]:
            h = self.router.replica(rid)
            logger.log_event(
                "serve-replica-hung", replica=rid, host=h.host_id,
                hb_age_s=round(now - h.last_ok_wall, 3),
                loop_age_s=round(h.last_loop_age_s, 3),
            )
            # a hung process holds its journal namespace hostage:
            # SIGKILL promotes it to dead and the failover below owns it
            get_fault_plan().fire("serve.replica.hung_kill")
            with span("serve.replica.hung_kill", replica=rid,
                      host=h.host_id):
                h.kill()  # remote-aware: ssh pkill reaps an ssh worker
            cls["dead"].append(rid)
        for rid in cls["dead"]:
            self._failover(rid, now)
        for rid, rec in sorted(self._relaunch_due.items()):
            if rec["due"] <= now:
                self._relaunch(rid, rec["attempt"], now)
        self._dispatch_orphans()
        if self.policy is not None:
            self._autoscale(now)

    # ------------------------------------------------------ failover
    def _failover(self, replica_id: int, now: float) -> None:
        handle = self.router.replica(replica_id)
        if not handle.alive:
            return  # already failed over; relaunch is pending/given up
        self.router.fail_replica(replica_id)
        dead_journal = journal_path(self.journal_base, replica_id)
        completed, incomplete, timeouts = failover_split(dead_journal)
        self.recovered.update(
            {int(k): list(v) for k, v in completed.items()}
        )
        self.recovered_timeouts += timeouts
        self.orphans.extend(incomplete)
        # arbitrate the router's in-doubt parks against the journal:
        # an in-doubt submit WITH a journal record was admitted — the
        # split above already owns it (completed/incomplete/timeout);
        # one WITHOUT was never admitted, so the parked copy is the
        # only copy and joins the orphans. Exactly one path re-serves
        # each request — never both.
        parked = self.router.take_in_doubt(replica_id)
        unadmitted = 0
        if parked:
            admitted = submitted_ids(dead_journal)
            for rec in parked:
                if int(rec["req"]) not in admitted:
                    self.orphans.append(rec)
                    unadmitted += 1
        logger.log_event(
            "serve-replica-dead", replica=replica_id, host=handle.host_id,
            rc=handle.proc.poll(), recovered=len(completed),
            redispatch=len(incomplete) + unadmitted, timeouts=timeouts,
        )
        attempt = self._attempts.get(replica_id, 0) + 1
        if attempt > self.restart_budget:
            logger.log_event(
                "serve-replica-give-up", replica=replica_id,
                host=handle.host_id,
                attempts=attempt - 1, budget=self.restart_budget,
            )
            self.gave_up.append(replica_id)
            return
        self._attempts[replica_id] = attempt
        delay = restart_backoff(attempt, self.restart_backoff_s)
        self._relaunch_due[replica_id] = {
            "due": now + delay, "attempt": attempt,
        }
        logger.log_event(
            "serve-replica-restart", replica=replica_id,
            host=handle.host_id,
            attempt=attempt, budget=self.restart_budget,
            backoff_s=round(delay, 3),
        )

    def _relaunch(self, replica_id: int, attempt: int, now: float) -> None:
        self._relaunch_due.pop(replica_id, None)
        handle = self.router.replica(replica_id)
        # the dead stream was harvested at failover; the relaunched
        # worker starts a FRESH journal in the same namespace (single
        # writer per file holds: the old process is gone)
        journal_path(self.journal_base, replica_id).unlink(missing_ok=True)
        try:
            fresh = self.spawn_fn(replica_id)
        except OSError as e:
            logger.warning(
                f"replica {replica_id} relaunch attempt {attempt} "
                f"failed: {e!r}"
            )
            next_attempt = self._attempts.get(replica_id, attempt) + 1
            if next_attempt > self.restart_budget:
                logger.log_event(
                    "serve-replica-give-up", replica=replica_id,
                    attempts=next_attempt - 1, budget=self.restart_budget,
                )
                self.gave_up.append(replica_id)
                return
            self._attempts[replica_id] = next_attempt
            delay = restart_backoff(next_attempt, self.restart_backoff_s)
            self._relaunch_due[replica_id] = {
                "due": now + delay, "attempt": next_attempt,
            }
            return
        handle.rebind(fresh)
        self.router.restore_replica(replica_id)
        self.restarts += 1

    def _dispatch_orphans(self) -> None:
        if not self.orphans or not self.router.live:
            return
        still: List[dict] = []
        for rec in self.orphans:
            # original req_id + force=True: any replica regenerates the
            # same tokens (the (request, position) sampler-key fold),
            # and recovery work is never shed. The journal/park record's
            # trace is adopted so the survivor's work — and the retry
            # RPC itself — lands on the ORIGINAL request's trace: one
            # trace spanning the dead replica and the survivor
            with obs.trace_context(rec.get("trace")):
                res = self.router.submit(
                    rec["prompt"], rec["max_new_tokens"],
                    eos_token_id=rec.get("eos_token_id"),
                    temperature=rec.get("temperature", 0.0),
                    top_k=rec.get("top_k"), top_p=rec.get("top_p"),
                    deadline_ms=rec.get("deadline_ms"),
                    ttft_deadline_ms=rec.get("ttft_deadline_ms"),
                    req_id=int(rec["req"]), force=True,
                )
            if isinstance(res, Backpressure):
                still.append(rec)  # every replica unreachable: retry
        if len(still) < len(self.orphans):
            self.redispatched += len(self.orphans) - len(still)
            logger.log_event(
                "serve-replica-failover",
                redispatched=len(self.orphans) - len(still),
                stranded=len(still),
            )
        self.orphans = still

    def pending_recovery(self) -> bool:
        """Work the bench loop must not exit under: stranded incomplete
        requests, or a relaunch still owed (the drill's contract is the
        replica COMES BACK, not just that its work moved)."""
        return bool(self.orphans) or bool(self._relaunch_due)

    # ----------------------------------------------------- autoscale
    def _autoscale(self, now: float) -> None:
        rows = []
        for h in self.router.replicas:
            s = h.last_stats
            rows.append({
                "replica": h.replica_id,
                "queue_depth": int(s.get("waiting", 0)),
                "pool_pressure": float(s.get("pool_pressure", 0.0)),
                "in_flight": int(s.get("running", 0))
                + int(s.get("waiting", 0)),
                "alive": h.alive and not h.retired,
            })
        decision = self.policy.decide(now, rows)
        if decision is None:
            return
        action, target = decision
        if action == "spawn":
            self.spawn_replica()
        elif action == "drain":
            self.drain_replica(target)

    def spawn_replica(self) -> Optional[int]:
        """Launch one more replica at the next free id (autoscale-up,
        and the capacity arbiter's spawn-on-leased-host — the caller
        pins the placement through its ``spawn_fn``). Returns the new
        replica id, or None when the spawn failed."""
        new_id = max(h.replica_id for h in self.router.replicas) + 1
        try:
            fresh = self.spawn_fn(new_id)
        except OSError as e:
            logger.warning(f"autoscale spawn failed: {e!r}")
            return None
        self.router.add_replica(fresh)  # logs serve-replica-spawn
        try:
            fresh.refresh()
        except ReplicaUnreachable:
            pass
        return new_id

    def drain_replica(self, target: int, reason: str = "autoscale") -> None:
        """Retire one replica cleanly: drain event, last journal poll
        through ``on_drain`` while it still answers RPCs, then
        drain + shutdown. Shared by the autoscale policy's scale-down
        and the capacity arbiter's reclaim (a leased host going back to
        training must shed its replicas the same clean way)."""
        handle = self.router.replica(target)
        logger.log_event(
            "serve-replica-drain", replica=target,
            host=handle.host_id, restarts=handle.restarts,
            reason=reason,
        )
        if self.on_drain is not None:
            self.on_drain(handle)  # last poll while it still answers
        # the autoscale policy only drains a replica with zero
        # in-flight work, so drain + shutdown is an immediate clean
        # exit; a capacity reclaim may drain with work queued — the
        # worker finishes in-flight requests before exiting
        handle.begin_drain()
        handle.request_shutdown()
        handle.retired = True
        handle.alive = False


def main(argv: Optional[List[str]] = None) -> int:
    return worker_main(argv)


if __name__ == "__main__":
    sys.exit(main())
