"""Block-paged KV cache pools (PagedAttention, SOSP '23).

One device-resident pool per transformer layer: ``(num_blocks,
block_size, n_kv, h)`` for keys and values, carved into fixed-size
blocks that sequences of wildly different lengths share through
per-sequence block tables (replacing the per-request fixed-capacity
``_alloc_caches`` buffers, whose dense ``(b, prompt+max_tokens)`` shape
charged every row the longest row's memory).

KV shapes come from an abstract probe of the real layer stack
(``jax.eval_shape`` over ``prefill_forward``), the same idiom as
``TransformerLayer.init_token_slice_cache`` — GQA / head-dim / dtype
choices can never drift from the attention that fills the pool.

``kv_dtype='int8'`` stores values quantized with per-slot-per-head
scales; the quantizer lives in ``nn/attention.py`` (``kv_quantize_int8``)
so the prefill writer here and the decode-step write inside
``ParallelSelfAttention`` round identically.

**mp > 1 (sharded serving, docs/SERVING.md "The fleet"):** when the
inference module rides a mesh with ``model_parallel_size > 1``, each
pool is SHARDED over the model axis on its kv-head dim — every mp shard
owns the ``(num_blocks, block_size, n_kv/mp, h)`` slice matching the
attention heads it computes, so pool memory per chip drops mp-fold and
big models' caches fit. Block tables / context lengths stay replicated
host state (they are addressing, not content), the engine's jitted
programs run SPMD over the serving mesh, and the Pallas paged kernel
runs per-shard on its slice (nn/attention.py wraps it in shard_map —
pallas calls are opaque to GSPMD).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..nn.attention import (
    PagedKVCacheView,
    paged_flat_slots,
    paged_scatter_kv,
)


def serving_mesh(inference_module):
    """The inference module's mesh when it is model-parallel, else None —
    the ONE predicate the engine, the pool allocator and the audit
    section use to decide whether serving state must be mesh-placed."""
    topo = getattr(inference_module.module, "topology", None)
    if topo is None or topo.model_parallel_size <= 1:
        return None
    return topo.mesh


def build_layer_views(
    state: Tuple,                    # (pool_k, pool_v, scale_k, scale_v)
    block_table: jax.Array,          # (rows, max_blocks) int32
    context_len: jax.Array,          # (rows,) int32
    new_len: Optional[jax.Array] = None,  # (rows,) int32 real new tokens
) -> List[PagedKVCacheView]:
    """Per-layer :class:`PagedKVCacheView` s over the raw pool state —
    the shape the engine's jitted programs thread through ``_run_layers``.

    ``new_len`` carries the chunked-prefill pad contract (mid-prompt
    pad-to-trash routing): of the ``s`` tokens a fixed-size chunk
    program presents, only the first ``new_len`` per row are real — the
    attention path writes the rest to the trash block and masks their
    slots, so ONE compiled chunk program serves every chunk length
    (including the final ragged chunk of every prompt)."""
    pool_k, pool_v, scale_k, scale_v = state
    return [
        PagedKVCacheView(
            pool_k=pool_k[i], pool_v=pool_v[i],
            block_table=block_table, context_len=context_len,
            scale_k=None if scale_k is None else scale_k[i],
            scale_v=None if scale_v is None else scale_v[i],
            new_len=new_len,
        )
        for i in range(len(pool_k))
    ]


class PagedKVPools:
    """Per-layer block pools (the engine builds per-layer views from the
    raw state inside its jitted programs — ``_views_from_state``).

    Pytree-friendly: the device state is plain lists of arrays so the
    jitted prefill/decode programs thread it straight through."""

    def __init__(self, pool_k: List[jax.Array], pool_v: List[jax.Array],
                 scale_k: Optional[List[jax.Array]],
                 scale_v: Optional[List[jax.Array]],
                 block_size: int):
        self.pool_k = pool_k
        self.pool_v = pool_v
        self.scale_k = scale_k
        self.scale_v = scale_v
        self.block_size = block_size

    @property
    def num_layers(self) -> int:
        return len(self.pool_k)

    @property
    def quantized(self) -> bool:
        return self.scale_k is not None

    @property
    def num_blocks(self) -> int:
        return self.pool_k[0].shape[0]

    def absorb_views(self, views: List[PagedKVCacheView]) -> None:
        """Take back the updated pools a jitted program returned."""
        self.pool_k = [v.pool_k for v in views]
        self.pool_v = [v.pool_v for v in views]
        if self.quantized:
            self.scale_k = [v.scale_k for v in views]
            self.scale_v = [v.scale_v for v in views]

    def device_bytes(self) -> int:
        total = 0
        for arrs in (self.pool_k, self.pool_v, self.scale_k, self.scale_v):
            if arrs is None:
                continue
            for a in arrs:
                total += a.size * a.dtype.itemsize
        return total


def init_pools(inference_module, num_blocks: int, block_size: int,
               kv_dtype: str = "native") -> PagedKVPools:
    """Allocate zeroed pools shaped by probing the real layer stack.

    ``kv_dtype``: ``'native'`` keeps the probe's KV dtype (the model's
    compute dtype); ``'int8'`` stores int8 values + float32 scales.

    On a model-parallel mesh each pool is sharded over the model axis on
    its kv-head dim (shape stays the GLOBAL ``(num_blocks, block_size,
    n_kv, h)``; every shard holds ``n_kv/mp`` heads) — the jitted
    programs compile SPMD and per-chip pool memory drops mp-fold."""
    if kv_dtype not in ("native", "int8"):
        raise ValueError(f"kv_dtype must be 'native' or 'int8', got {kv_dtype!r}")
    params = inference_module.params
    probe_tokens = jnp.zeros((1, 1), jnp.int32)
    probe_pos = jnp.zeros((1, 1), jnp.int32)

    def probe(p, t, po):
        return inference_module.prefill_forward(p, t, po)[1]

    kv_shapes = jax.eval_shape(probe, params, probe_tokens, probe_pos)
    # commit the fresh pools to the device(s) the programs will run on:
    # an uncommitted zeros-array keys a SECOND executable-cache entry for
    # the engine's very first program call (every later call sees the
    # committed jit outputs absorb_views hands back) — a silent 2x
    # compile of the largest serving programs
    mesh = serving_mesh(inference_module)
    if mesh is None:
        # co-locate the pools with the params: the fleet bench places
        # each replica's params on its own device, and the pools (and so
        # every jitted program) must follow — mixed placements would pin
        # every replica back onto device 0
        device = jax.local_devices()[0]
        leaves = jax.tree_util.tree_leaves(params)
        if leaves and hasattr(leaves[0], "devices"):
            leaf_devices = leaves[0].devices()
            if len(leaf_devices) == 1:
                device = next(iter(leaf_devices))

        def placed(shape, dtype, head_dim):
            del head_dim
            return jax.device_put(jnp.zeros(shape, dtype), device)
    else:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..topology.topology import MODEL_AXIS

        mp = mesh.shape[MODEL_AXIS]

        def placed(shape, dtype, head_dim):
            n_kv = shape[head_dim]
            if n_kv % mp:
                raise ValueError(
                    f"mp={mp} sharded serving needs kv heads divisible by "
                    f"the model axis; this stack has n_kv={n_kv} — pick an "
                    f"mp that divides it (docs/SERVING.md)"
                )
            spec = [None] * len(shape)
            spec[head_dim] = MODEL_AXIS
            return jax.device_put(
                jnp.zeros(shape, dtype), NamedSharding(mesh, P(*spec))
            )

    pool_k: List[jax.Array] = []
    pool_v: List[jax.Array] = []
    scale_k: Optional[List[jax.Array]] = [] if kv_dtype == "int8" else None
    scale_v: Optional[List[jax.Array]] = [] if kv_dtype == "int8" else None
    for k_aval, v_aval in kv_shapes:
        n_kv, h = k_aval.shape[2], k_aval.shape[3]
        store = jnp.int8 if kv_dtype == "int8" else k_aval.dtype
        pool_k.append(placed((num_blocks, block_size, n_kv, h), store, 2))
        pool_v.append(placed((num_blocks, block_size, n_kv, h), store, 2))
        if kv_dtype == "int8":
            scale_k.append(
                placed((num_blocks, block_size, n_kv), jnp.float32, 2)
            )
            scale_v.append(
                placed((num_blocks, block_size, n_kv), jnp.float32, 2)
            )
    return PagedKVPools(pool_k, pool_v, scale_k, scale_v, block_size)


def write_prompt_kv(
    view: PagedKVCacheView,
    k: jax.Array,  # (1, L_padded, n_kv, h) prompt keys (right-padded)
    v: jax.Array,
    block_row: jax.Array,  # (max_blocks,) the sequence's block table row
    prompt_len: jax.Array,  # scalar: real tokens; pads write to trash
    block_size: int,
) -> PagedKVCacheView:
    """Scatter one prefilled prompt's KV into the pool (traceable).

    Tokens past ``prompt_len`` (the length-bucket padding) are routed to
    the trash block, so a single jitted program per bucket serves every
    prompt length in it."""
    L = k.shape[1]
    positions = jnp.arange(L, dtype=jnp.int32)[None, :]
    # pads: send the flat slot into the trash block
    real = positions < prompt_len
    flat = paged_flat_slots(block_row[None, :], positions, block_size)
    flat = jnp.where(real, flat, 0).reshape(-1)
    return paged_scatter_kv(
        view, flat, k.reshape(L, *k.shape[2:]), v.reshape(L, *v.shape[2:])
    )
