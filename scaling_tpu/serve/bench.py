"""Serving load generator: Poisson arrivals -> engine -> obs telemetry.

``python -m scaling_tpu.serve bench`` drives the continuous-batching
engine with an open-loop Poisson arrival process (exponential
inter-arrival gaps at ``--rate`` req/s) and prompt/output lengths sampled
uniformly from ``--prompt-len``/``--output-len`` ranges, then reports
tokens/s, p50/p99 time-to-first-token and inter-token latency.

Telemetry rides the SAME rails training uses (docs/OBSERVABILITY.md):
metrics through ``obs.get_registry()`` (flushed to ``<run-dir>/
metrics.jsonl``), per-request ``serve-request`` + final ``serve-summary``
events through ``logger.log_event`` — so ``python -m scaling_tpu.obs
report <run-dir>`` grows a serving section, and the
``--assert-serve-throughput`` / ``--assert-ttft`` gates work both here
(self-gating, like ``bench.py --assert-mfu``) and on the analyzer over
the run dir (CI reads the artifacts, not the console).

The model is a randomly initialised toy transformer by default (the
benchmark measures the ENGINE: scheduling, paging, recompile hygiene);
``--checkpoint`` serves a real one.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path
from typing import List, Optional

from .engine import EngineConfig, ServeEngine


def build_toy_inference(hidden: int = 64, layers: int = 2, vocab: int = 128,
                        heads: int = 4, seq_len: int = 256):
    """Random-init tiny model wrapped for inference (no checkpoint)."""
    import jax

    from ..models.transformer import TransformerConfig
    from ..models.transformer.inference import TransformerInferenceModule
    from ..models.transformer.model import init_model

    config = TransformerConfig.from_dict({
        "topology": {
            "model_parallel_size": 1, "pipe_parallel_size": 1,
            "data_parallel_size": 1, "micro_batch_size": 1,
            "gradient_accumulation_steps": 1,
        },
        "transformer_architecture": {
            "vocab_size": vocab, "hidden_size": hidden, "num_layers": layers,
            "num_attention_heads": heads, "sequence_length": seq_len,
            "mlp_type": "swiglu", "mlp_factor": 2.0, "norm_type": "rms",
            "weight_tying": False,
        },
        "optimizer": {"gradient_clipping": 1.0},
        "learning_rate_scheduler": {
            "learning_rate": 3e-4, "learning_rate_warmup_steps": 10,
            "learning_rate_decay_iters": 100,
        },
        "trainer": {"train_iterations": 1, "seed": 0},
        "data": {}, "logger": {"log_dir": None},
    })
    module = init_model(config, None)
    params = module.init_params(jax.random.PRNGKey(0))
    return TransformerInferenceModule(config, module, params)


def sample_workload(n_requests: int, rate: float, prompt_len, output_len,
                    vocab: int, seed: int, shared_prefix_len: int = 0,
                    prefix_families: int = 1):
    """Poisson arrival offsets + per-request prompts/output budgets.

    ``shared_prefix_len > 0`` models the dominant real-traffic shape:
    requests draw one of ``prefix_families`` fixed system prompts of
    that length and append a random tail sampled from ``prompt_len`` —
    the prefix-cache arm of the benchmark (``--shared-prefix-len``)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    prefixes = [
        rng.integers(1, vocab, size=shared_prefix_len).tolist()
        for _ in range(prefix_families)
    ] if shared_prefix_len > 0 else []
    gaps = rng.exponential(1.0 / rate, size=n_requests)
    arrivals = np.cumsum(gaps)
    arrivals[0] = 0.0  # the first request opens the run
    work = []
    for i in range(n_requests):
        plen = int(rng.integers(prompt_len[0], prompt_len[1] + 1))
        olen = int(rng.integers(output_len[0], output_len[1] + 1))
        tail = rng.integers(1, vocab, size=plen).tolist()
        prompt = (prefixes[i % prefix_families] + tail) if prefixes else tail
        work.append((float(arrivals[i]), prompt, olen))
    return work


def run_bench(engine: ServeEngine, workload, time_scale: float = 1.0,
              max_wall_s: float = 600.0) -> dict:
    """Open-loop drive: submit each request when the wall clock crosses
    its arrival offset, tick the engine continuously, drain. Returns the
    summary stats dict (also emitted as the ``serve-summary`` event)."""
    from ..logging import logger
    from ..obs import get_registry, span

    t0 = time.monotonic()
    start_ticks = engine.tick_index  # warmup ticks stay off the books
    pending = sorted(workload, key=lambda w: w[0])
    idx = 0
    while idx < len(pending) or engine.scheduler.has_work:
        now = time.monotonic() - t0
        if now > max_wall_s:
            raise RuntimeError(
                f"bench exceeded --max-wall-s={max_wall_s}: "
                f"{idx}/{len(pending)} submitted, "
                f"{len(engine.finished)} finished"
            )
        while idx < len(pending) and pending[idx][0] * time_scale <= now:
            arrival, prompt, olen = pending[idx]
            engine.submit(prompt, olen, arrival_s=t0 + arrival * time_scale)
            idx += 1
        if engine.scheduler.has_work:
            with span("serve.tick", step=engine.tick_index):
                engine.tick()
        elif idx < len(pending):
            # idle until the next arrival (clamped: stay responsive)
            wait = pending[idx][0] * time_scale - (time.monotonic() - t0)
            if wait > 0:
                time.sleep(min(wait, 0.05))

    wall_s = time.monotonic() - t0
    seqs = engine.finished
    ttfts = sorted(s.first_token_s - s.request.arrival_s for s in seqs)
    itls: List[float] = []
    for s in seqs:
        itls.extend(b - a for a, b in zip(s.token_stamps, s.token_stamps[1:]))
    itls.sort()
    total_tokens = sum(len(s.generated) for s in seqs)

    # the SAME nearest-rank percentile `obs report` uses over the run
    # dir, so the self-gate here and the CI gate there can never
    # disagree about the same run's p99
    from ..obs.report import percentile

    def pct(vals, q):
        return percentile(vals, q) if vals else None

    prompt_tokens = sum(len(s.request.prompt) for s in seqs)
    # hits count every (re-)admission match (a preempted sequence
    # re-matching its own cached blocks included), so the rate is
    # work-avoided / work-demanded: hit / (hit + actually-prefilled) —
    # bounded [0, 1] even when preemptions force re-prefills
    hit = engine.scheduler.prefix_hit_tokens
    prefilled = engine.prefilled_tokens
    stats = {
        "requests": len(seqs),
        "wall_s": round(wall_s, 6),
        "output_tokens": total_tokens,
        "prompt_tokens": prompt_tokens,
        "tokens_per_s": round(total_tokens / wall_s, 3) if wall_s > 0 else 0.0,
        "ttft_p50_s": pct(ttfts, 50),
        "ttft_p99_s": pct(ttfts, 99),
        "itl_p50_s": pct(itls, 50),
        "itl_p99_s": pct(itls, 99),
        "preemptions": engine.scheduler.preemption_count,
        "ticks": engine.tick_index - start_ticks,
        "prefill_compiles": engine.prefill_program_count,
        "max_concurrent_prefills": engine.max_concurrent_prefills,
        # raw-speed rails (ISSUE 11): prefill work actually paid after
        # shared-prefix reuse, and the self-drafting accept rate
        "prefix_hit_tokens": hit,
        "prefix_hit_rate": (
            round(hit / (hit + prefilled), 4) if hit + prefilled else 0.0
        ),
        "prefilled_tokens": prefilled,
        "spec_drafted_tokens": engine.spec_drafted_tokens,
        "spec_accepted_tokens": engine.spec_accepted_tokens,
        "spec_accept_rate": (
            round(engine.spec_accept_rate, 4)
            if engine.spec_accept_rate is not None else None
        ),
    }
    logger.log_event("serve-summary", **stats)
    get_registry().flush_step(engine.tick_index)
    return stats


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m scaling_tpu.serve bench",
        description="continuous-batching serving benchmark (docs/SERVING.md)",
    )
    parser.add_argument("--requests", type=int, default=16)
    parser.add_argument("--rate", type=float, default=8.0,
                        help="Poisson arrival rate, requests/second")
    parser.add_argument("--prompt-len", type=int, nargs=2, default=(4, 24),
                        metavar=("MIN", "MAX"))
    parser.add_argument("--output-len", type=int, nargs=2, default=(4, 16),
                        metavar=("MIN", "MAX"))
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--run-dir", default="runs/serve_bench",
                        help="telemetry output dir (events + metrics jsonl)")
    # engine shape knobs (all land in the jitted programs' signatures)
    parser.add_argument("--num-slots", type=int, default=8)
    parser.add_argument("--block-size", type=int, default=16)
    parser.add_argument("--num-blocks", type=int, default=128)
    parser.add_argument("--max-blocks-per-seq", type=int, default=16)
    parser.add_argument("--token-budget", type=int, default=512)
    parser.add_argument("--kv-dtype", choices=["native", "int8"],
                        default="native")
    parser.add_argument("--prefill-chunk", type=int, default=32,
                        help="Sarathi-style chunked prefill: tokens per "
                        "chunk (prompts stream into the pool sharing the "
                        "tick budget with decodes); 0 = legacy "
                        "whole-prompt prefill")
    parser.add_argument("--paged-kernel", choices=["pallas", "xla"],
                        default="pallas",
                        help="paged-decode attention back-end: the "
                        "streaming Pallas kernel (interpreted off-TPU) or "
                        "the XLA block-window gather fallback")
    parser.add_argument("--spec-k", type=int, default=0,
                        help="self-drafting speculative decoding: n-gram "
                        "draft tokens scored per decode row per tick "
                        "(0 = off)")
    parser.add_argument("--shared-prefix-len", type=int, default=0,
                        help="prefix-cache arm: every request shares one "
                        "of --prefix-families system prompts of this "
                        "length (0 = fully random prompts)")
    parser.add_argument("--prefix-families", type=int, default=1,
                        help="number of distinct shared prefixes for "
                        "--shared-prefix-len")
    parser.add_argument("--no-prefix-cache", action="store_true",
                        help="disable shared-prefix block reuse (the A/B "
                        "for --shared-prefix-len)")
    parser.add_argument("--no-fused-tick", action="store_true",
                        help="legacy dispatch: separate decode + "
                        "per-sequence chunk programs instead of ONE "
                        "mixed program per tick")
    parser.add_argument("--warmup", type=int, default=0,
                        help="serve N throwaway requests (excluded from "
                        "stats) before the open-loop clock starts, so "
                        "first-tick jit compiles don't distort arrival "
                        "timing")
    # toy model knobs / real checkpoint
    parser.add_argument("--hidden", type=int, default=64)
    parser.add_argument("--layers", type=int, default=2)
    parser.add_argument("--vocab", type=int, default=128)
    parser.add_argument("--heads", type=int, default=4)
    parser.add_argument("--checkpoint", help="serve a real checkpoint dir "
                        "instead of the random toy model")
    parser.add_argument("--max-wall-s", type=float, default=600.0)
    parser.add_argument("--json", metavar="FILE",
                        help="also write the summary stats as JSON")
    parser.add_argument("--assert-serve-throughput", type=float,
                        metavar="FLOOR",
                        help="fail (exit 1) when output tokens/s is below "
                        "FLOOR (same gate `obs report` applies to the "
                        "run dir)")
    parser.add_argument("--assert-ttft", type=float, metavar="CEIL",
                        help="fail (exit 1) when p99 time-to-first-token "
                        "exceeds CEIL seconds")
    args = parser.parse_args(argv)
    if args.requests < 1:
        parser.error("--requests must be >= 1")
    if args.rate <= 0:
        parser.error("--rate must be > 0")
    for flag, (lo, hi), floor in (("--prompt-len", args.prompt_len, 1),
                                  ("--output-len", args.output_len, 1)):
        if lo < floor or hi < lo:
            parser.error(f"{flag} needs {floor} <= MIN <= MAX, got {lo} {hi}")

    import os

    run_dir = Path(args.run_dir)
    run_dir.mkdir(parents=True, exist_ok=True)
    # telemetry rails: events via the logger's env hook, metrics via the
    # registry's explicit sink (mirrors how the supervisor wires hosts)
    os.environ.setdefault(
        "SCALING_TPU_EVENTS_PATH", str(run_dir / "events.jsonl")
    )
    from ..obs import get_registry

    get_registry().configure(metrics_path=str(run_dir / "metrics.jsonl"))

    if args.checkpoint:
        from ..models.transformer.inference import TransformerInferenceModule

        inf = TransformerInferenceModule.from_checkpoint(args.checkpoint)
        vocab = inf.architecture.vocab_size
    else:
        inf = build_toy_inference(
            hidden=args.hidden, layers=args.layers, vocab=args.vocab,
            heads=args.heads,
        )
        vocab = args.vocab

    cap = args.max_blocks_per_seq * args.block_size
    longest = (args.prompt_len[1] + args.shared_prefix_len
               + args.output_len[1])
    if longest > cap:
        print(
            f"error: prompt+output can reach {longest} tokens but the "
            f"block table holds {cap}; raise --max-blocks-per-seq or "
            "--block-size", file=sys.stderr,
        )
        return 2
    if args.shared_prefix_len > 0 and args.prefix_families < 1:
        parser.error("--prefix-families must be >= 1")

    engine = ServeEngine(inf, EngineConfig(
        num_slots=args.num_slots, block_size=args.block_size,
        num_blocks=args.num_blocks,
        max_blocks_per_seq=args.max_blocks_per_seq,
        token_budget=args.token_budget, kv_dtype=args.kv_dtype,
        prefill_chunk=args.prefill_chunk or None,
        paged_kernel=args.paged_kernel,
        fused_tick=not args.no_fused_tick,
        enable_prefix_cache=not args.no_prefix_cache,
        spec_k=args.spec_k,
    ))
    workload = sample_workload(
        args.requests, args.rate, tuple(args.prompt_len),
        tuple(args.output_len), vocab, args.seed,
        shared_prefix_len=args.shared_prefix_len,
        prefix_families=args.prefix_families,
    )
    if args.warmup > 0:
        # compile the tick programs off the clock: the first mixed-step
        # call jit-compiles for seconds, and an open-loop workload that
        # arrives during it measures the compiler, not the engine
        engine.warmup_mode = True
        for _ in range(args.warmup):
            engine.submit([1], 2)
        engine.run_until_done()
        engine.warmup_mode = False
        engine.finished.clear()
    stats = run_bench(engine, workload, max_wall_s=args.max_wall_s)

    print("== serve bench ==")
    print(f"  requests={stats['requests']} wall={stats['wall_s']:.3f}s "
          f"ticks={stats['ticks']} preemptions={stats['preemptions']} "
          f"prefill_compiles={stats['prefill_compiles']}")
    print(f"  hot path: paged_kernel={args.paged_kernel} "
          f"prefill_chunk={args.prefill_chunk or 'off'} "
          f"fused_tick={not args.no_fused_tick} "
          f"max_concurrent_prefills={stats['max_concurrent_prefills']}")
    if stats["prefix_hit_tokens"]:
        print(f"  prefix cache: {stats['prefix_hit_tokens']} tokens hit, "
              f"{stats['prefilled_tokens']} prefilled "
              f"({stats['prompt_tokens']} prompt tokens submitted; "
              f"hit rate {stats['prefix_hit_rate']:.1%})")
    if stats["spec_accept_rate"] is not None:
        print(f"  speculation: k={args.spec_k} accepted "
              f"{stats['spec_accepted_tokens']}/"
              f"{stats['spec_drafted_tokens']} drafts "
              f"(accept rate {stats['spec_accept_rate']:.1%})")
    print(f"  output tokens/s: {stats['tokens_per_s']:.1f} "
          f"({stats['output_tokens']} tokens)")
    print(f"  ttft: p50={stats['ttft_p50_s']:.4f}s "
          f"p99={stats['ttft_p99_s']:.4f}s")
    if stats["itl_p50_s"] is not None:
        print(f"  itl:  p50={stats['itl_p50_s']:.4f}s "
              f"p99={stats['itl_p99_s']:.4f}s")
    print(f"  run dir: {run_dir} (analyze: python -m scaling_tpu.obs "
          f"report {run_dir})")

    if args.json:
        Path(args.json).write_text(json.dumps(stats, indent=1) + "\n")

    failures = []
    if (args.assert_serve_throughput is not None
            and stats["tokens_per_s"] < args.assert_serve_throughput):
        failures.append(
            f"assert-serve-throughput: {stats['tokens_per_s']:.1f} tokens/s "
            f"< floor {args.assert_serve_throughput:.1f}"
        )
    if args.assert_ttft is not None and (
            stats["ttft_p99_s"] is None
            or stats["ttft_p99_s"] > args.assert_ttft):
        failures.append(
            f"assert-ttft: p99 TTFT {stats['ttft_p99_s']}s "
            f"> ceiling {args.assert_ttft}s"
        )
    if args.assert_serve_throughput is not None or args.assert_ttft is not None:
        print("== gates ==")
        for f in failures:
            print(f"  FAIL {f}")
        if not failures:
            print("  PASS")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
