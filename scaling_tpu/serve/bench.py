"""Serving load generator: Poisson arrivals -> engine -> obs telemetry.

``python -m scaling_tpu.serve bench`` drives the continuous-batching
engine with an open-loop Poisson arrival process (exponential
inter-arrival gaps at ``--rate`` req/s) and prompt/output lengths sampled
uniformly from ``--prompt-len``/``--output-len`` ranges, then reports
tokens/s, p50/p99 time-to-first-token and inter-token latency.

Telemetry rides the SAME rails training uses (docs/OBSERVABILITY.md):
metrics through ``obs.get_registry()`` (flushed to ``<run-dir>/
metrics.jsonl``), per-request ``serve-request`` + final ``serve-summary``
events through ``logger.log_event`` — so ``python -m scaling_tpu.obs
report <run-dir>`` grows a serving section, and the
``--assert-serve-throughput`` / ``--assert-ttft`` gates work both here
(self-gating, like ``bench.py --assert-mfu``) and on the analyzer over
the run dir (CI reads the artifacts, not the console).

The model is a randomly initialised toy transformer by default (the
benchmark measures the ENGINE: scheduling, paging, recompile hygiene);
``--checkpoint`` serves a real one.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path
from typing import List, Optional

# NOTE: keep this module jax-light at import — the engine (and so jax)
# loads inside main() AFTER _ensure_devices has set the virtual device
# count for --replicas/--mp; an eager engine import would pin the
# process to however many devices the environment happened to have
from .scheduler import Backpressure


def build_toy_inference(hidden: int = 64, layers: int = 2, vocab: int = 128,
                        heads: int = 4, seq_len: int = 256, mp: int = 1,
                        device_offset: int = 0):
    """Random-init tiny model wrapped for inference (no checkpoint).

    ``mp > 1`` builds the model on a model-parallel serving mesh (needs
    that many jax devices): params shard over the model axis, and the
    engine's pools and programs follow (docs/SERVING.md "The fleet").
    Weights are init-key deterministic, so the mp=1 and mp=2 builds of
    the same shape hold the SAME weights — the mp parity tests rely on
    that.

    ``device_offset`` places this instance's params (and mesh, at
    mp > 1) starting at that jax device: fleet replica ``r`` builds at
    offset ``r * mp``, so every replica owns its own device group and
    their tick programs genuinely run concurrently instead of queueing
    on device 0."""
    import jax

    from ..models.transformer import TransformerConfig
    from ..models.transformer.inference import TransformerInferenceModule
    from ..models.transformer.model import init_model

    config = TransformerConfig.from_dict({
        "topology": {
            "model_parallel_size": mp, "pipe_parallel_size": 1,
            "data_parallel_size": 1, "micro_batch_size": 1,
            "gradient_accumulation_steps": 1,
        },
        "transformer_architecture": {
            "vocab_size": vocab, "hidden_size": hidden, "num_layers": layers,
            "num_attention_heads": heads, "sequence_length": seq_len,
            "mlp_type": "swiglu", "mlp_factor": 2.0, "norm_type": "rms",
            "weight_tying": False,
        },
        "optimizer": {"gradient_clipping": 1.0},
        "learning_rate_scheduler": {
            "learning_rate": 3e-4, "learning_rate_warmup_steps": 10,
            "learning_rate_decay_iters": 100,
        },
        "trainer": {"train_iterations": 1, "seed": 0},
        "data": {}, "logger": {"log_dir": None},
    })
    topo = None
    if mp > 1 or device_offset > 0:
        if len(jax.devices()) < device_offset + mp:
            raise RuntimeError(
                f"mp={mp} at device offset {device_offset} needs "
                f"{device_offset + mp} jax devices, found "
                f"{len(jax.devices())} (off-TPU: set XLA_FLAGS="
                f"--xla_force_host_platform_device_count=N)"
            )
    if mp > 1:
        from ..topology import Topology

        topo = Topology(
            config.topology,
            devices=jax.devices()[device_offset:device_offset + mp],
        )
    module = init_model(config, topo)
    params = module.init_params(jax.random.PRNGKey(0))
    if topo is not None:
        params = module.shard_params(params)
    elif device_offset > 0:
        params = jax.device_put(params, jax.devices()[device_offset])
    return TransformerInferenceModule(config, module, params)


def sample_workload(n_requests: int, rate: float, prompt_len, output_len,
                    vocab: int, seed: int, shared_prefix_len: int = 0,
                    prefix_families: int = 1):
    """Poisson arrival offsets + per-request prompts/output budgets.

    ``shared_prefix_len > 0`` models the dominant real-traffic shape:
    requests draw one of ``prefix_families`` fixed system prompts of
    that length and append a random tail sampled from ``prompt_len`` —
    the prefix-cache arm of the benchmark (``--shared-prefix-len``)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    prefixes = [
        rng.integers(1, vocab, size=shared_prefix_len).tolist()
        for _ in range(prefix_families)
    ] if shared_prefix_len > 0 else []
    gaps = rng.exponential(1.0 / rate, size=n_requests)
    arrivals = np.cumsum(gaps)
    arrivals[0] = 0.0  # the first request opens the run
    work = []
    for i in range(n_requests):
        plen = int(rng.integers(prompt_len[0], prompt_len[1] + 1))
        olen = int(rng.integers(output_len[0], output_len[1] + 1))
        tail = rng.integers(1, vocab, size=plen).tolist()
        prompt = (prefixes[i % prefix_families] + tail) if prefixes else tail
        work.append((float(arrivals[i]), prompt, olen))
    return work


def run_bench(engine, workload, time_scale: float = 1.0,
              max_wall_s: float = 600.0, tick_timeout_s: float = 0.0,
              extra_stats: Optional[dict] = None,
              carry: Optional[dict] = None) -> dict:
    """Open-loop drive: submit each request when the wall clock crosses
    its arrival offset, tick the engine continuously, drain. Returns the
    summary stats dict (also emitted as the ``serve-summary`` event).

    Resilience rails (docs/SERVING.md "Resilience"): a submission the
    engine sheds (watermark backpressure) is counted, not retried — the
    open-loop client models a router that took the hint elsewhere. When
    the engine flips to ``draining`` (SIGTERM), submission stops,
    in-flight requests run to completion or their deadlines, and the
    loop exits cleanly with the unsubmitted tail counted.
    ``tick_timeout_s > 0`` arms a tick-stall watchdog (the resilience
    ``StepStallWatchdog``): a tick that stops beating dumps thread
    stacks, logs a ``serve-stall`` event, and then SIGKILLs the process
    — a wedged tick (hung device, dead mount) is unrecoverable
    in-process, and dying loudly is what lets a ``--restarts``
    supervisor replay the journal instead of hanging forever behind a
    silent child. ``carry`` folds a crashed predecessor's terminal
    tallies (completed/timeouts/shed, from the journal replay) into
    the summary so the FINAL summary — the one the shed/timeout gates
    read — describes the whole run dir, not just the last process."""
    import os
    import signal as _signal

    from ..logging import logger
    from ..obs import get_registry, new_trace_id, span, trace_context

    watchdog = None
    if tick_timeout_s > 0:
        from ..resilience import StepStallWatchdog

        def _on_stall(tick, elapsed):
            from ..resilience.faults import get_fault_plan

            logger.log_event(
                "serve-stall", tick=tick, stalled_s=round(elapsed, 3)
            )
            get_fault_plan().fire("serve.stall.kill")
            with span("serve.stall.kill", tick=tick):
                os.kill(os.getpid(), _signal.SIGKILL)

        watchdog = StepStallWatchdog(tick_timeout_s, on_stall=_on_stall)
        watchdog.start()

    t0 = time.monotonic()
    start_ticks = engine.tick_index  # warmup ticks stay off the books
    pending = sorted(workload, key=lambda w: w[0])
    idx = 0
    try:
        while True:
            now = time.monotonic() - t0
            if now > max_wall_s:
                raise RuntimeError(
                    f"bench exceeded --max-wall-s={max_wall_s}: "
                    f"{idx}/{len(pending)} submitted, "
                    f"{len(engine.finished)} finished"
                )
            while not engine.draining and idx < len(pending) and \
                    pending[idx][0] * time_scale <= now:
                arrival, prompt, olen = pending[idx]
                # one fresh trace id per measured request at submit —
                # the origin of the distributed trace every downstream
                # span/event/journal record inherits (warmup traffic
                # runs outside any context and stays untraced)
                with trace_context(new_trace_id()):
                    res = engine.submit(
                        prompt, olen, arrival_s=t0 + arrival * time_scale
                    )
                if isinstance(res, Backpressure) and res.draining:
                    # SIGTERM raced this submission: it was never
                    # offered to a live engine — unsubmitted, not shed
                    break
                idx += 1
            if watchdog is not None:
                # beat every loop pass, idle waits included — the
                # watchdog watches for a WEDGED tick (the loop stuck
                # inside engine.tick() stops beating), not for a
                # healthy bench sleeping between Poisson arrivals
                watchdog.beat(engine.tick_index)
            if engine.scheduler.has_work:
                with span("serve.tick", step=engine.tick_index):
                    engine.tick()
            elif engine.draining or idx >= len(pending):
                break
            else:
                # idle until the next arrival (clamped: stay responsive)
                wait = pending[idx][0] * time_scale - (time.monotonic() - t0)
                if wait > 0:
                    time.sleep(min(wait, 0.05))
    finally:
        if watchdog is not None:
            watchdog.stop()

    wall_s = time.monotonic() - t0
    seqs = engine.finished
    completed = [s for s in seqs if s.finish_status == "completed"]
    ttfts = sorted(
        s.first_token_s - s.request.arrival_s for s in seqs
        if s.first_token_s is not None
    )
    itls: List[float] = []
    for s in seqs:
        itls.extend(b - a for a, b in zip(s.token_stamps, s.token_stamps[1:]))
    itls.sort()
    total_tokens = sum(len(s.generated) for s in seqs)

    # the SAME nearest-rank percentile `obs report` uses over the run
    # dir, so the self-gate here and the CI gate there can never
    # disagree about the same run's p99
    from ..obs.report import percentile

    def pct(vals, q):
        return percentile(vals, q) if vals else None

    prompt_tokens = sum(len(s.request.prompt) for s in seqs)
    # hits count every (re-)admission match (a preempted sequence
    # re-matching its own cached blocks included), so the rate is
    # work-avoided / work-demanded: hit / (hit + actually-prefilled) —
    # bounded [0, 1] even when preemptions force re-prefills
    hit = engine.scheduler.prefix_hit_tokens
    prefilled = engine.prefilled_tokens
    # cumulative across supervised relaunches: `carry` holds the
    # crashed predecessor runs' terminal tallies from the journal
    # replay, so the final summary — the one the shed/timeout gates
    # read — describes the WHOLE run dir, not just this process
    carry = carry or {}
    c_completed = int(carry.get("completed", 0))
    c_timeouts = int(carry.get("timeouts", 0))
    c_shed = int(carry.get("shed", 0))
    total_shed = engine.shed_count + c_shed
    total_timeouts = engine.timeout_count + c_timeouts
    attempts = total_shed + total_timeouts + len(completed) + c_completed
    stats = {
        "requests": len(completed) + c_completed,
        "requests_timeout": total_timeouts,
        "requests_shed": total_shed,
        "shed_rate": (
            round(total_shed / attempts, 4) if attempts else 0.0
        ),
        "drained": engine.draining,
        "unsubmitted": len(pending) - idx,
        "wall_s": round(wall_s, 6),
        "output_tokens": total_tokens,
        "prompt_tokens": prompt_tokens,
        "tokens_per_s": round(total_tokens / wall_s, 3) if wall_s > 0 else 0.0,
        "ttft_p50_s": pct(ttfts, 50),
        "ttft_p99_s": pct(ttfts, 99),
        "itl_p50_s": pct(itls, 50),
        "itl_p99_s": pct(itls, 99),
        "preemptions": engine.scheduler.preemption_count,
        "ticks": engine.tick_index - start_ticks,
        "prefill_compiles": engine.prefill_program_count,
        "max_concurrent_prefills": engine.max_concurrent_prefills,
        # raw-speed rails (ISSUE 11): prefill work actually paid after
        # shared-prefix reuse, and the self-drafting accept rate
        "prefix_hit_tokens": hit,
        "prefix_hit_rate": (
            round(hit / (hit + prefilled), 4) if hit + prefilled else 0.0
        ),
        "prefilled_tokens": prefilled,
        "spec_drafted_tokens": engine.spec_drafted_tokens,
        "spec_accepted_tokens": engine.spec_accepted_tokens,
        "spec_accept_rate": (
            round(engine.spec_accept_rate, 4)
            if engine.spec_accept_rate is not None else None
        ),
        "engine": engine_shape_stats(engine),
    }
    if extra_stats:
        stats.update(extra_stats)
    logger.log_event("serve-summary", **stats)
    get_registry().flush_step(engine.tick_index)
    return stats


def engine_shape_stats(engine, replicas: int = 1) -> dict:
    """The engine-shape facts the serve-summary carries so the tuner's
    serving cost model can calibrate against this run's measured spans
    (tune/serving.py ``ServeCalibration``)."""
    cfg = engine.config
    return {
        "mp": engine.model_parallel,
        "replicas": replicas,
        "num_slots": cfg.num_slots,
        "block_size": cfg.block_size,
        "num_blocks": cfg.num_blocks,
        "token_budget": cfg.token_budget,
        "prefill_chunk": cfg.prefill_chunk,
        "spec_k": cfg.spec_k,
    }


def run_fleet_bench(router, workload, time_scale: float = 1.0,
                    max_wall_s: float = 600.0,
                    extra_stats: Optional[dict] = None,
                    carry: Optional[dict] = None,
                    fleet_journal=None) -> dict:
    """Open-loop drive of the FLEET (docs/SERVING.md "The fleet"): one
    Poisson arrival stream submits through the router (prefix-affinity /
    least-loaded / retry-elsewhere), while one tick thread per replica
    runs its engine's event loop — replicas tick CONCURRENTLY (each owns
    its own device group; the jitted tick releases the GIL), which is
    what makes fleet tokens/s scale with replicas instead of queueing N
    engines on one device.

    A submission the WHOLE fleet sheds is counted (and journaled into
    the fleet-level journal — replica journals only see their own
    admissions) and not retried; SIGTERM drains every replica and the
    loop exits cleanly once the last in-flight request finishes."""
    import threading

    from ..logging import logger
    from ..obs import get_registry, new_trace_id, span, trace_context
    from ..obs.report import percentile

    handles = list(router.replicas)
    engines = [h.engine for h in handles]
    start_ticks = {h.replica_id: h.engine.tick_index for h in handles}
    stop = threading.Event()
    # a replica thread dying must surface as THE bench error, not as a
    # silent hang until --max-wall-s (the survivors keep router.has_work
    # true forever for the dead replica's stranded requests)
    errors: List[BaseException] = []

    def tick_loop(handle):
        eng = handle.engine
        try:
            while not stop.is_set():
                if eng.scheduler.has_work:
                    with handle.lock:
                        if not eng.scheduler.has_work:
                            continue
                        with span("serve.tick", step=eng.tick_index,
                                  replica=handle.replica_id):
                            eng.tick()
                else:
                    time.sleep(0.001)
        except BaseException as e:  # noqa: BLE001 — re-raised below
            errors.append(e)
            stop.set()

    threads = [
        threading.Thread(target=tick_loop, args=(h,), daemon=True,
                         name=f"serve-replica-{h.replica_id}")
        for h in handles if h.alive
    ]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    pending = sorted(workload, key=lambda w: w[0])
    idx = 0
    shed = 0
    try:
        while True:
            if errors:
                raise RuntimeError(
                    "a replica tick thread died"
                ) from errors[0]
            now = time.monotonic() - t0
            if now > max_wall_s:
                raise RuntimeError(
                    f"fleet bench exceeded --max-wall-s={max_wall_s}: "
                    f"{idx}/{len(pending)} submitted, "
                    f"{sum(len(e.finished) for e in engines)} finished"
                )
            draining = any(h.engine.draining for h in handles if h.alive)
            while not draining and idx < len(pending) and \
                    pending[idx][0] * time_scale <= now:
                arrival, prompt, olen = pending[idx]
                # per-request trace origin (same contract as run_bench)
                with trace_context(new_trace_id()):
                    res = router.submit(
                        prompt, olen, arrival_s=t0 + arrival * time_scale
                    )
                if isinstance(res, Backpressure):
                    if res.draining:
                        # SIGTERM raced this submission: unsubmitted
                        draining = True
                        break
                    # the WHOLE fleet shed this offer: consumed,
                    # journaled at fleet level (so --resume skip math
                    # maps 1:1 onto workload items), AND counted on the
                    # unlabeled serve_requests_shed_total counter — the
                    # documented overload signal dashboards watch
                    # (replicas skip their counters via count_shed)
                    shed += 1
                    get_registry().counter(
                        "serve_requests_shed_total"
                    ).inc()
                    if fleet_journal is not None:
                        fleet_journal.record_shed(res.reason)
                idx += 1
            if (draining or idx >= len(pending)) and not router.has_work:
                break
            time.sleep(0.002)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=60)

    wall_s = time.monotonic() - t0
    seqs = [s for e in engines for s in e.finished]
    completed = [s for s in seqs if s.finish_status == "completed"]
    ttfts = sorted(
        s.first_token_s - s.request.arrival_s for s in seqs
        if s.first_token_s is not None
    )
    itls: List[float] = []
    for s in seqs:
        itls.extend(b - a for a, b in zip(s.token_stamps, s.token_stamps[1:]))
    itls.sort()
    total_tokens = sum(len(s.generated) for s in seqs)

    def pct(vals, q):
        return percentile(vals, q) if vals else None

    carry = carry or {}
    c_completed = int(carry.get("completed", 0))
    c_timeouts = int(carry.get("timeouts", 0))
    c_shed = int(carry.get("shed", 0))
    total_shed = shed + c_shed
    total_timeouts = sum(e.timeout_count for e in engines) + c_timeouts
    attempts = total_shed + total_timeouts + len(completed) + c_completed
    hit = sum(e.scheduler.prefix_hit_tokens for e in engines)
    prefilled = sum(e.prefilled_tokens for e in engines)
    drafted = sum(e.spec_drafted_tokens for e in engines)
    accepted = sum(e.spec_accepted_tokens for e in engines)
    rstats = router.stats()
    replica_rows = []
    for h in handles:
        e = h.engine
        per = rstats["per_replica"].get(h.replica_id, {})
        replica_rows.append({
            "replica": h.replica_id,
            "alive": h.alive,
            "requests": sum(
                1 for s in e.finished if s.finish_status == "completed"
            ),
            "output_tokens": sum(len(s.generated) for s in e.finished),
            "timeouts": e.timeout_count,
            "ticks": e.tick_index - start_ticks[h.replica_id],
            "preemptions": e.scheduler.preemption_count,
            "pool_pressure": round(e.scheduler.pool_pressure(), 4),
            **per,
        })
    stats = {
        "requests": len(completed) + c_completed,
        "requests_timeout": total_timeouts,
        "requests_shed": total_shed,
        "shed_rate": (
            round(total_shed / attempts, 4) if attempts else 0.0
        ),
        "drained": any(e.draining for e in engines),
        "unsubmitted": len(pending) - idx,
        "wall_s": round(wall_s, 6),
        "output_tokens": total_tokens,
        "prompt_tokens": sum(len(s.request.prompt) for s in seqs),
        "tokens_per_s": round(total_tokens / wall_s, 3) if wall_s > 0 else 0.0,
        "ttft_p50_s": pct(ttfts, 50),
        "ttft_p99_s": pct(ttfts, 99),
        "itl_p50_s": pct(itls, 50),
        "itl_p99_s": pct(itls, 99),
        "preemptions": sum(e.scheduler.preemption_count for e in engines),
        "ticks": sum(
            e.tick_index - start_ticks[h.replica_id]
            for h, e in zip(handles, engines)
        ),
        "prefill_compiles": sum(e.prefill_program_count for e in engines),
        "max_concurrent_prefills": max(
            e.max_concurrent_prefills for e in engines
        ),
        "prefix_hit_tokens": hit,
        "prefix_hit_rate": (
            round(hit / (hit + prefilled), 4) if hit + prefilled else 0.0
        ),
        "prefilled_tokens": prefilled,
        "spec_drafted_tokens": drafted,
        "spec_accepted_tokens": accepted,
        "spec_accept_rate": (
            round(accepted / drafted, 4) if drafted else None
        ),
        "replicas": len(handles),
        "replica_stats": replica_rows,
        "router": rstats,
        "engine": engine_shape_stats(engines[0], replicas=len(handles)),
    }
    if extra_stats:
        stats.update(extra_stats)
    logger.log_event("serve-summary", **stats)
    get_registry().flush_step(max(e.tick_index for e in engines))
    return stats


def run_supervised(argv: List[str], args) -> int:
    """``--restarts N``: the serving counterpart of
    ``resilience.run_with_resume`` — run the bench as a child process
    and, when it dies (a ``serve.tick`` kill, an OOM, a wedged tick),
    relaunch it with ``--resume`` so the request journal replays: every
    incomplete request re-enqueues with its original id and regenerates
    token-for-token. Exits 0 the moment a child drains cleanly;
    re-raises the child's exit code once the budget is spent.

    A ``SCALING_TPU_FAULTS`` chaos plan arms the FIRST launch only:
    hit counters are per-process, so a persistent plan would kill every
    replay at the same tick and turn a bounded-restart drill into
    guaranteed budget exhaustion.

    SIGTERM to the supervisor is RELAYED to the running child (whose
    own drain handler finishes in-flight work and exits 0) and ends
    the supervision loop — the graceful-drain contract holds in the
    supervised deployment mode too, and no orphan keeps writing to the
    run dir. A child that dies mid-drain is not relaunched (mirroring
    the trainer supervisor's preemption rule)."""
    import os
    import signal
    import subprocess

    from ..logging import logger
    from ..obs import span
    from ..resilience.faults import get_fault_plan

    child_argv: List[str] = []
    skip = False
    for a in argv:
        if skip:
            skip = False
            continue
        if a == "--restarts":
            skip = True
            continue
        if a.startswith("--restarts="):
            continue
        child_argv.append(a)
    run_dir = Path(args.run_dir)
    run_dir.mkdir(parents=True, exist_ok=True)
    # the supervisor's own lifecycle events (serve-restart / give-up)
    # land in the same run dir the children write to
    os.environ.setdefault(
        "SCALING_TPU_EVENTS_PATH", str(run_dir / "events.jsonl")
    )
    env = dict(os.environ)
    state = {"child": None, "draining": False}

    def _relay(signum, frame):
        state["draining"] = True
        child = state["child"]
        if child is not None and child.poll() is None:
            child.send_signal(signal.SIGTERM)

    prev = signal.getsignal(signal.SIGTERM)
    signal.signal(signal.SIGTERM, _relay)
    attempts = 0
    try:
        while True:
            if state["draining"]:
                # SIGTERM landed while no child was running (e.g.
                # between a crash and the relaunch): relaunching would
                # serve the whole remaining workload with the drain
                # request silently ignored — stop here instead
                logger.log_event("serve-drain", supervisor=True)
                return 0
            cmd = [sys.executable, "-m", "scaling_tpu.serve", "bench",
                   *child_argv]
            if attempts > 0 and "--resume" not in child_argv:
                cmd.append("--resume")
            get_fault_plan().fire("serve.supervisor.spawn")
            with span("serve.supervisor.spawn", attempt=attempts):
                state["child"] = subprocess.Popen(cmd, env=env)
            if state["draining"]:
                # the signal raced the launch: the handler saw no child
                state["child"].send_signal(signal.SIGTERM)
            rc = state["child"].wait()
            state["child"] = None
            if rc == 0:
                return 0
            if state["draining"]:
                logger.log_event("serve-drain-failed", rc=rc)
                return rc if rc > 0 else 1
            attempts += 1
            if attempts > args.restarts:
                logger.log_event(
                    "serve-give-up", attempts=attempts - 1, rc=rc,
                )
                return rc if rc > 0 else 1
            logger.log_event("serve-restart", attempt=attempts, rc=rc)
            env.pop("SCALING_TPU_FAULTS", None)
    finally:
        signal.signal(signal.SIGTERM, prev)


def _run_fleet(args, infs, workload, journal_base, make_engine,
               warmup_engine) -> dict:
    """Fleet mode (``--replicas N``): N engines behind the
    prefix-affinity router, per-replica journal namespaces, SIGTERM
    drain fan-out, one Poisson stream through ``run_fleet_bench``."""
    from ..logging import logger
    from .journal import journal_path, open_journal, replay_journal
    from .router import FleetRouter, install_fleet_drain_handler

    engines = [
        make_engine(replica_id=r, inf_override=infs[r])
        for r in range(args.replicas)
    ]
    router = FleetRouter(engines)
    install_fleet_drain_handler(router)
    fleet_journal = None
    fleet_replay = None
    replays = {}
    if not args.no_journal:
        for r, eng in enumerate(engines):
            jr, rep = open_journal(journal_base, args.resume, replica_id=r)
            eng.attach_journal(jr)
            replays[r] = rep
        # the fleet-level journal records only whole-fleet sheds (every
        # replica said Backpressure): the resume skip math needs one
        # record per CONSUMED workload item, and a shed offer produced
        # no submit record in any replica journal
        fleet_journal, fleet_replay = open_journal(journal_base, args.resume)
    elif args.resume:
        for r in range(args.replicas):
            replays[r] = replay_journal(journal_path(journal_base, r))
        fleet_replay = replay_journal(journal_base)
    if args.warmup > 0:
        for eng in engines:
            warmup_engine(eng)
    extra_stats = None
    carry = None
    offered = sum(
        rep.offered_count for rep in replays.values() if rep is not None
    ) + (fleet_replay.shed_count if fleet_replay is not None else 0)
    if args.resume and offered:
        incomplete_total = completed_total = timeout_total = 0
        for r in sorted(replays):
            rep = replays[r]
            if rep is None:
                continue
            eng = engines[r]
            eng._next_req_id = rep.next_req_id
            # each replica replays its OWN journal namespace: original
            # req_ids keep the sampler-key fold, so the regenerated
            # tokens are the ones the crashed replica would have emitted
            for rec in rep.incomplete:
                # a journaled request resumes its pre-crash trace
                # (None for legacy journals — stays untraced)
                eng.submit(
                    rec["prompt"], rec["max_new_tokens"],
                    eos_token_id=rec.get("eos_token_id"),
                    temperature=rec.get("temperature", 0.0),
                    top_k=rec.get("top_k"), top_p=rec.get("top_p"),
                    deadline_ms=rec.get("deadline_ms"),
                    ttft_deadline_ms=rec.get("ttft_deadline_ms"),
                    req_id=int(rec["req"]), force=True,
                    trace=rec.get("trace"),
                )
            incomplete_total += len(rep.incomplete)
            completed_total += len(rep.completed)
            timeout_total += rep.timeout_count
        router.sync_next_req_id()
        workload = sorted(workload, key=lambda w: w[0])[offered:]
        if workload:
            base = workload[0][0]
            workload = [(a - base, p, o) for a, p, o in workload]
        extra_stats = {
            "resumed": True,
            "replayed_incomplete": incomplete_total,
            "replayed_completed": completed_total,
        }
        carry = {
            "completed": completed_total,
            "timeouts": timeout_total,
            "shed": (
                fleet_replay.shed_count if fleet_replay is not None else 0
            ),
        }
        logger.log_event(
            "serve-resume", incomplete=incomplete_total,
            completed=completed_total, remaining_workload=len(workload),
        )
    return run_fleet_bench(
        router, workload, max_wall_s=args.max_wall_s,
        extra_stats=extra_stats, carry=carry, fleet_journal=fleet_journal,
    )


def _fleet_capacity_tick(client, sup, router, plan, host_of,
                         leases, counters) -> None:
    """One arbitration pass on the elastic capacity channel
    (``--capacity-dir``, ``resilience.capacity``): heartbeat the
    fleet's demand, take delivery of granted leases, give reclaimed
    hosts back.

    - **demand**: max pool pressure across alive replicas + total queue
      depth — the signal the training-side ``CapacityManager`` sustains
      over before borrowing or reclaiming a host.
    - **granted**: admit the leased host into the placement plan, spawn
      one replica pinned there, then mark the lease ``active``. A
      failed spawn leaves the lease ``granted`` — retried next tick,
      and expired back to training by the manager if the fleet dies.
    - **reclaiming**: drain the host's replicas through the supervisor
      (clean retire, journal harvested); once every one has actually
      exited, write ``released`` and drop the host from the plan —
      training upsizes back over it.
    """
    from ..logging import logger

    alive = [h for h in router.replicas if h.alive and not h.retired]
    pressure = max(
        (float(h.last_stats.get("pool_pressure", 0.0)) for h in alive),
        default=0.0,
    )
    queue = sum(int(h.last_stats.get("waiting", 0)) for h in alive)
    client.publish(pressure=pressure, queue=queue, replicas=len(alive))
    for lease in client.granted():
        if lease.host in leases:
            continue  # already spawning/active for this grant
        hid = None
        if plan is not None:
            hid = plan.add_host(lease.host, lease.slots).host_id
            # pin BEFORE the spawn so the placement closure lands the
            # new replica on the leased host, not the least-loaded one
            host_of[max(h.replica_id for h in router.replicas) + 1] = hid
        rid = sup.spawn_replica()
        if rid is None:
            if plan is not None:
                plan.remove_host(lease.host, lease.slots)
            continue  # lease stays granted; retried next tick
        try:
            active = client.activate(lease)
        except Exception as e:
            # activation write failed (injected capacity.lease fault or
            # sick channel): the replica must not squat on a host the
            # manager will expire back to training — retire it now
            logger.warning(
                f"lease activation for {lease.host} failed ({e!r}); "
                "draining the replica"
            )
            sup.drain_replica(rid, reason="capacity-activate-failed")
            if plan is not None:
                plan.remove_host(lease.host, lease.slots)
            continue
        leases[lease.host] = {"lease": active, "replicas": [rid]}
        counters["activated"] += 1
    for lease in client.reclaiming():
        rec = leases.get(lease.host)
        rids = list(rec["replicas"]) if rec else []
        still_running = []
        for rid in rids:
            try:
                h = router.replica(rid)
            except (KeyError, ValueError):
                continue
            if h.alive and not h.retired:
                sup.drain_replica(rid, reason="capacity-reclaim")
            if h.proc.poll() is None:
                still_running.append(rid)
        if still_running:
            continue  # release only after the host is actually clear
        client.release(lease)
        if plan is not None:
            plan.remove_host(lease.host, lease.slots)
        leases.pop(lease.host, None)
        counters["released"] += 1


def _run_fleet_proc(args, workload, run_dir, journal_base) -> dict:
    """Process-isolated fleet mode (``--replicas-proc N``,
    docs/SERVING.md "Process mode"): every replica is a SUBPROCESS
    behind the same router policy, supervised by
    ``replica_proc.FleetSupervisor`` — a SIGKILLed replica's journal is
    harvested, its incomplete requests re-dispatch to survivors
    token-exactly, and the process relaunches on budgeted backoff; with
    ``--autoscale`` the supervisor also spawns under sustained pressure
    and drains at sustained idle.

    The HOST stays jax-free and single-threaded: submissions, polling,
    and supervision all run on this loop (each worker process owns its
    own devices, so nothing here needs the threaded fleet's per-replica
    tick threads or their lock discipline). Finished requests ship back
    via cursor-based ``poll`` RPCs; the summary's ``outputs`` map
    (req_id -> tokens) is what the chaos drill diffs against a
    fault-free run."""
    import os
    import signal
    import subprocess
    from concurrent.futures import ThreadPoolExecutor

    from ..logging import logger
    from ..obs import get_registry, new_trace_id, span, trace_context
    from ..obs.report import percentile
    from ..resilience.faults import get_fault_plan
    from .journal import RequestJournal
    from .replica_proc import (
        FleetSupervisor,
        read_rendezvous,
        rendezvous_file,
        spawn_replica_proc,
    )
    from .router import AutoscalePolicy, FleetRouter, ReplicaUnreachable

    # fresh run: stale journals from a previous drill in this dir (ANY
    # replica id — an earlier run may have autoscaled further) would
    # poison failover harvests
    for stale in run_dir.glob(f"{journal_base.stem}*{journal_base.suffix}"):
        stale.unlink()
    fleet_journal = RequestJournal(journal_base)

    # ---- host mode (--hostsfile, docs/SERVING.md "Host mode") ----
    # replicas spawn across the hostsfile's machines (ssh for remote
    # hosts, local exec for localhost entries), publish their host:port
    # into the run dir's rendezvous file, and the control plane's flag
    # files carry drain/abort to workers a partition has cut off from
    # RPC. Placement rides the tuner's PlacementPlan: relaunches pin
    # their recorded host, autoscale spawns go to the least-loaded
    # feasible host.
    plan = None
    control = None
    host_of: dict = {}  # replica_id -> host_id (sticky across relaunch)
    if args.hostsfile:
        from ..resilience.controlplane import (
            FileControlPlane,
            log_clock_offset,
        )
        from ..runner.config import RunnerConfig
        from ..runner.runner import get_resource_pool
        from ..tune.serving import PlacementPlan

        pool = get_resource_pool(RunnerConfig(
            hostsfile=args.hostsfile, default_gpu_count=1,
        ))
        plan = PlacementPlan.from_pool(pool)
        control = FileControlPlane(
            run_dir / "control", host_id=0, num_hosts=len(plan.hosts),
        )
        # the router host's skew stamp (workers stamp their own): the
        # pair is what obs trace aligns cross-host timelines with
        log_clock_offset(control)
        rdv = rendezvous_file(run_dir)
        if rdv.exists():
            # a previous drill's entries would satisfy ready-waits with
            # dead addresses
            rdv.unlink()
    worker_cfg = {
        "journal_base": str(journal_base),
        "metrics_path": str(run_dir / "metrics.jsonl"),
        "warmup": args.warmup,
        "toy": {"hidden": args.hidden, "layers": args.layers,
                "vocab": args.vocab, "heads": args.heads},
        "engine": {
            "num_slots": args.num_slots, "block_size": args.block_size,
            "num_blocks": args.num_blocks,
            "max_blocks_per_seq": args.max_blocks_per_seq,
            "token_budget": args.token_budget, "kv_dtype": args.kv_dtype,
            "prefill_chunk": args.prefill_chunk or None,
            "paged_kernel": args.paged_kernel,
            "fused_tick": not args.no_fused_tick,
            "enable_prefix_cache": not args.no_prefix_cache,
            "spec_k": args.spec_k,
            "default_deadline_ms": args.deadline_ms,
            "default_ttft_deadline_ms": args.ttft_deadline_ms,
            "shed_high_watermark": args.shed_high_watermark,
            "shed_low_watermark": args.shed_low_watermark,
            "max_waiting": args.max_waiting,
        },
    }
    if plan is not None:
        worker_cfg["control_dir"] = str(run_dir / "control")
        worker_cfg["num_hosts"] = len(plan.hosts)
    chaos_env = dict(os.environ)
    clean_env = dict(os.environ)
    # a chaos plan arms the INITIAL spawns only: hit counters are
    # per-process, so a relaunched or autoscaled worker re-armed with
    # the same plan would die at the same hit forever
    # (run_supervised's rule)
    clean_env.pop("SCALING_TPU_FAULTS", None)

    def spawn(replica_id, env=None):
        kw = {}
        if plan is not None:
            hid = host_of.get(replica_id)
            if hid is None:
                # a NEW replica (autoscale): least-loaded feasible host;
                # a relaunch found its pin above and never re-places
                counts: dict = {}
                for hh in host_of.values():
                    counts[hh] = counts.get(hh, 0) + 1
                hid = plan.next_host(counts)
                if hid is None:
                    # every host is slot-full: land on the least loaded
                    # rather than refuse the spawn (oversubscription
                    # beats a stranded relaunch)
                    hid = min(
                        plan.hosts,
                        key=lambda h: (counts.get(h.host_id, 0),
                                       h.host_id),
                    ).host_id
                host_of[replica_id] = hid
            kw = {"hostname": plan.hostname(hid), "host_id": hid}
        return spawn_replica_proc(
            replica_id, worker_cfg, run_dir,
            env=clean_env if env is None else env, **kw,
        )

    drain_req = {"flag": False}

    def _drain_sig(signum, frame):
        # flag only: RPC fan-out happens on the loop, not in the handler
        drain_req["flag"] = True

    # Install before spawning: workers log serve-replica-ready the
    # moment they publish their addr, which is before spawn() returns
    # on the host — a drain signal sent at first-ready must not hit the
    # default SIGTERM disposition and kill the bench under its workers.
    prev = signal.signal(signal.SIGTERM, _drain_sig)

    if plan is not None:
        # place the initial fleet up front (same least-loaded rule the
        # autoscale spawn uses) — infeasible fleets fail loudly here
        for r, hid in enumerate(plan.initial_assignment(args.replicas_proc)):
            host_of[r] = hid
    # parallel launch: every worker pays its cold jit warmup at once
    with ThreadPoolExecutor(max_workers=args.replicas_proc) as ex:
        handles = list(ex.map(
            lambda r: spawn(r, chaos_env), range(args.replicas_proc)
        ))
    router = FleetRouter(handles=handles, block_size=args.block_size)
    policy = None
    if args.autoscale:
        policy = AutoscalePolicy(
            min_replicas=args.min_replicas,
            max_replicas=args.max_replicas,
            sustain_s=args.autoscale_sustain_s,
            idle_sustain_s=args.autoscale_idle_s,
            cooldown_s=args.autoscale_sustain_s,
        )
    recs: dict = {}  # req_id -> finished record, newest wins

    def harvest(handle):
        try:
            for rec in handle.poll_finished():
                recs[int(rec["req"])] = rec
        except ReplicaUnreachable:
            pass  # dead replica: the journal harvest owns its outputs

    sup = FleetSupervisor(
        router, spawn, journal_base,
        restart_budget=args.restart_budget,
        policy=policy, on_drain=harvest,
    )
    # ---- elastic capacity (--capacity-dir, docs/RESILIENCE.md
    # "Elastic capacity") ---- the fleet joins the training
    # supervisor's capacity channel: demand heartbeats feed the
    # arbitration manager, granted leases spawn replicas on the
    # borrowed host, reclaims drain them and hand the host back.
    cap_client = None
    cap_leases: dict = {}  # host -> {"lease": Lease, "replicas": [id]}
    cap_counters = {"activated": 0, "released": 0}
    if args.capacity_dir:
        from ..resilience.capacity import CapacityChannel, FleetCapacityClient

        cap_client = FleetCapacityClient(
            CapacityChannel(Path(args.capacity_dir)),
            publish_interval_s=args.capacity_publish_s,
        )
    pending = sorted(workload, key=lambda w: w[0])
    idx = 0
    shed = 0
    draining = False
    t0 = time.monotonic()
    last_sup = -1.0
    try:
        while True:
            now = time.monotonic() - t0
            if now > args.max_wall_s:
                raise RuntimeError(
                    f"proc fleet bench exceeded --max-wall-s="
                    f"{args.max_wall_s}: {idx}/{len(pending)} submitted, "
                    f"{len(recs)} finished"
                )
            if drain_req["flag"] and not draining:
                draining = True
                logger.log_event(
                    "serve-drain", fleet=True, replicas=len(router.live),
                )
                if control is not None:
                    # the control-plane flag reaches workers a partition
                    # has cut off from the RPC fan-out below
                    control.set_flag("serve-drain")
                router.begin_drain()
            if now - last_sup >= 0.05:
                last_sup = now
                sup.tick()
                if cap_client is not None and not draining:
                    _fleet_capacity_tick(
                        cap_client, sup, router, plan, host_of,
                        cap_leases, cap_counters,
                    )
                for h in router.replicas:
                    if h.alive and not h.retired:
                        harvest(h)
            if sup.gave_up and not router.live:
                raise RuntimeError(
                    "every replica exhausted its restart budget; "
                    f"{len(sup.orphans)} request(s) stranded"
                )
            while not draining and idx < len(pending) \
                    and pending[idx][0] <= now:
                arrival, prompt, olen = pending[idx]
                # per-request trace origin: the RPC envelope carries it
                # to the worker, whose dispatch adopts it (one trace per
                # request across every process in the fleet)
                with trace_context(new_trace_id()):
                    res = router.submit(prompt, olen)
                if isinstance(res, Backpressure):
                    if res.draining:
                        draining = True  # SIGTERM raced this submission
                        break
                    shed += 1
                    get_registry().counter(
                        "serve_requests_shed_total"
                    ).inc()
                    fleet_journal.record_shed(res.reason)
                idx += 1
            if (draining or idx >= len(pending)) and not router.has_work \
                    and not sup.pending_recovery():
                break
            time.sleep(0.002)
        # autoscale settle: hold the fleet at idle long enough for the
        # policy's idle-drain to fire (the drill pins "drains at idle
        # within budget") — bounded by the wall clock
        if policy is not None and not draining:
            deadline = min(
                time.monotonic()
                + (policy.idle_sustain_s + policy.cooldown_s) * 2 + 1.0,
                t0 + args.max_wall_s,
            )
            while (sum(1 for h in router.replicas
                       if h.alive and not h.retired) > policy.min_replicas
                   and policy.drains < policy.drain_budget
                   and time.monotonic() < deadline):
                sup.tick()
                time.sleep(0.02)
        wall_s = time.monotonic() - t0
        for h in router.replicas:
            if h.alive and not h.retired:
                try:
                    h.refresh()
                except ReplicaUnreachable:
                    pass
                harvest(h)
                h.request_shutdown()
        get_fault_plan().fire("serve.fleet.teardown")
        with span("serve.fleet.teardown"):
            for h in router.replicas:
                if h.proc.poll() is None:
                    try:
                        h.proc.wait(timeout=30)
                    except subprocess.TimeoutExpired:
                        logger.warning(
                            f"replica {h.replica_id} ignored shutdown; "
                            "killing"
                        )
                        h.proc.kill()
    except BaseException:
        if control is not None:
            # abort rides the control-plane rails: workers a partition
            # (or a dead ssh channel) cut off from RPC still see the
            # flag file and exit instead of orphaning on their host
            try:
                control.set_flag("serve-abort")
            except OSError:
                pass
        raise
    finally:
        signal.signal(signal.SIGTERM, prev)
        with span("serve.fleet.teardown", phase="finally"):
            for h in router.replicas:
                if h.proc.poll() is None:
                    # no orphan keeps writing to the run dir — kill()
                    # reaches through ssh for remote-host replicas
                    h.kill()

    completed = {
        r: rec for r, rec in recs.items() if rec["status"] == "completed"
    }
    # outputs = polled records, with the failover harvest filling in
    # requests whose serving replica died after finishing them
    outputs = {int(r): list(t) for r, t in sup.recovered.items()}
    outputs.update({r: list(rec["toks"]) for r, rec in completed.items()})
    timeouts = sup.recovered_timeouts + sum(
        1 for rec in recs.values() if rec["status"] == "timeout"
    )
    attempts = shed + timeouts + len(outputs)
    output_tokens = sum(len(rec["toks"]) for rec in recs.values()) + sum(
        len(t) for r, t in sup.recovered.items() if r not in recs
    )
    ttfts = sorted(
        rec["ttft_s"] for rec in recs.values()
        if rec.get("ttft_s") is not None
    )
    itls = sorted(g for rec in recs.values() for g in rec.get("itls", ()))

    def pct(vals, q):
        return percentile(vals, q) if vals else None

    rstats = router.stats()
    agg_keys = ("preemptions", "prefix_hit_tokens", "prefilled_tokens",
                "spec_drafted_tokens", "spec_accepted_tokens",
                "prefill_compiles")
    agg = dict.fromkeys(agg_keys, 0)
    ticks = 0
    max_prefills = 0
    submit_dups = 0
    rpc_retries = 0
    replica_rows = []
    for h in router.replicas:
        s = h.last_stats
        h_ticks = h.ticks_banked + int(s.get("tick", 0))
        ticks += h_ticks
        for k in agg:
            agg[k] += int(s.get(k, 0))
        max_prefills = max(
            max_prefills, int(s.get("max_concurrent_prefills", 0))
        )
        submit_dups += h.last_dups
        rpc_retries += h.rpc_retries
        replica_rows.append({
            "replica": h.replica_id,
            "host": h.host_id,
            "alive": h.alive,
            "retired": h.retired,
            "restarts": h.restarts,
            "dups": h.last_dups,
            "rpc_retries": h.rpc_retries,
            "requests": int(s.get("completed", 0)),
            "output_tokens": int(s.get("output_tokens", 0)),
            "timeouts": int(s.get("timeout_count", 0)),
            "ticks": h_ticks,
            "preemptions": int(s.get("preemptions", 0)),
            "pool_pressure": round(float(s.get("pool_pressure", 0.0)), 4),
            **rstats["per_replica"].get(h.replica_id, {}),
        })
    hit = agg["prefix_hit_tokens"]
    prefilled = agg["prefilled_tokens"]
    drafted = agg["spec_drafted_tokens"]
    stats = {
        "requests": len(outputs),
        "requests_timeout": timeouts,
        "requests_shed": shed,
        "shed_rate": round(shed / attempts, 4) if attempts else 0.0,
        "drained": draining,
        "unsubmitted": len(pending) - idx,
        "wall_s": round(wall_s, 6),
        "output_tokens": output_tokens,
        "prompt_tokens": sum(
            int(rec.get("prompt_len", 0)) for rec in recs.values()
        ),
        "tokens_per_s": (
            round(output_tokens / wall_s, 3) if wall_s > 0 else 0.0
        ),
        "ttft_p50_s": pct(ttfts, 50),
        "ttft_p99_s": pct(ttfts, 99),
        "itl_p50_s": pct(itls, 50),
        "itl_p99_s": pct(itls, 99),
        "preemptions": agg["preemptions"],
        "ticks": ticks,
        "prefill_compiles": agg["prefill_compiles"],
        "max_concurrent_prefills": max_prefills,
        "prefix_hit_tokens": hit,
        "prefix_hit_rate": (
            round(hit / (hit + prefilled), 4) if hit + prefilled else 0.0
        ),
        "prefilled_tokens": prefilled,
        "spec_drafted_tokens": drafted,
        "spec_accepted_tokens": agg["spec_accepted_tokens"],
        "spec_accept_rate": (
            round(agg["spec_accepted_tokens"] / drafted, 4)
            if drafted else None
        ),
        "replicas": len(router.replicas),
        "replica_stats": replica_rows,
        "router": rstats,
        "engine": {
            "mp": 1, "replicas": len(router.replicas),
            "num_slots": args.num_slots, "block_size": args.block_size,
            "num_blocks": args.num_blocks,
            "token_budget": args.token_budget,
            "prefill_chunk": args.prefill_chunk or None,
            "spec_k": args.spec_k,
        },
        # the process-fleet story (obs report's fleet section + the
        # --assert-max-replica-restarts gate read these)
        "proc_fleet": True,
        "replica_restarts": sup.restarts,
        "replica_spawns": policy.spawns if policy else 0,
        "replica_drains": policy.drains if policy else 0,
        "recovered_requests": len(sup.recovered),
        "redispatched_requests": sup.redispatched,
        "replicas_gave_up": len(sup.gave_up),
        # partition-drill counters: worker-side dedup hits (an RPC retry
        # or in-doubt re-offer the engine had already admitted) and
        # client-side transport retries
        "submit_dups": submit_dups,
        "rpc_retries": rpc_retries,
    }
    if cap_client is not None:
        # the arbitration story: borrowed-host leases this fleet
        # activated and handed back (docs/RESILIENCE.md)
        stats["capacity_leases_activated"] = cap_counters["activated"]
        stats["capacity_leases_released"] = cap_counters["released"]
        stats["capacity_leases_open"] = len(cap_leases)
    if plan is not None:
        # the host-mode story: which hosts the plan expected vs which
        # actually rendezvoused (obs report's never-reported gate)
        stats["fleet_hosts"] = [h.host_id for h in plan.hosts]
        try:
            reported = read_rendezvous(rendezvous_file(run_dir))
        except OSError:
            reported = {}
        stats["hosts_reported"] = sorted({
            int(rec["host"]) for rec in reported.values()
            if rec.get("host") is not None
        })
    # the event rides WITHOUT the raw outputs map (events.jsonl is for
    # telemetry, not payloads); the returned stats / --json carry it for
    # the chaos drill's token-exact diff
    logger.log_event("serve-summary", **stats)
    stats["outputs"] = {str(r): outputs[r] for r in sorted(outputs)}
    get_registry().flush_step(ticks)
    return stats


def _run_spec_sweep(args, sweep_ks, workload, make_engine,
                    warmup_engine) -> dict:
    """``--spec-k-sweep``: the SAME workload once per draft length k on
    a fresh engine each, then a FINAL serve-summary (the last one in the
    run dir — the one the analyzer and gates read) carrying the winning
    arm's stats plus the whole sweep table. The tokens/s-optimal k is
    the answer the ROADMAP raw-speed follow-on asked for; accept rate
    per k rides along so the ``--assert-spec-accept-rate`` gate judges
    the winner."""
    from ..logging import logger
    from .engine import install_drain_handler

    arms = []
    for k in sweep_ks:
        eng = make_engine(spec_k=k)
        install_drain_handler(eng)  # chains: SIGTERM drains current arm
        if args.warmup > 0:
            warmup_engine(eng)
        arm = run_bench(
            eng, list(workload), max_wall_s=args.max_wall_s,
            tick_timeout_s=args.tick_timeout_s,
            extra_stats={"spec_k": k},
        )
        arms.append(arm)
        if eng.draining:
            break  # SIGTERM mid-sweep: don't start another arm
    best = max(arms, key=lambda a: a["tokens_per_s"])
    stats = dict(best)
    stats["spec_k_best"] = best["spec_k"]
    stats["spec_k_sweep"] = [
        {
            "spec_k": a["spec_k"],
            "tokens_per_s": a["tokens_per_s"],
            "spec_accept_rate": a["spec_accept_rate"],
            "ttft_p99_s": a["ttft_p99_s"],
        }
        for a in arms
    ]
    logger.log_event("serve-summary", **stats)
    return stats


def _apply_serving_config(args, argv: List[str], parser) -> None:
    """Fold a tuner-emitted serving config (``tune --serve
    --emit-config``) into the parsed args as DEFAULTS: any knob the user
    passed explicitly on the command line wins over the file."""
    from ..resilience.guards import retry_io

    try:
        cfg = json.loads(retry_io(
            Path(args.config).read_text, what="serving config read"
        ))
    except (OSError, ValueError) as e:
        parser.error(f"--config {args.config}: unreadable ({e})")
    passed = {
        a[2:].split("=", 1)[0].replace("-", "_")
        for a in argv if a.startswith("--")
    }
    for key in ("mp", "replicas", "block_size", "token_budget",
                "num_slots", "num_blocks", "max_blocks_per_seq"):
        if key in cfg and key not in passed:
            setattr(args, key, int(cfg[key]))


def _ensure_devices(need: int) -> None:
    """The fleet needs ``replicas * mp`` jax devices. Off-TPU, force the
    virtual host-platform device count BEFORE the first jax import (the
    flag is inert after backend init — if jax is already up with too few
    devices, fail actionably instead of queueing every replica on
    device 0)."""
    import os

    if need <= 1:
        return
    flags = os.environ.get("XLA_FLAGS", "")
    if ("jax" not in sys.modules
            and "--xla_force_host_platform_device_count" not in flags):
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={need}"
        ).strip()
    import jax

    if len(jax.devices()) < need:
        raise SystemExit(
            f"error: --replicas x --mp needs {need} devices, found "
            f"{len(jax.devices())}; off-TPU set XLA_FLAGS="
            f"--xla_force_host_platform_device_count={need} before launch"
        )


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m scaling_tpu.serve bench",
        description="continuous-batching serving benchmark (docs/SERVING.md)",
        # no prefix abbreviations: _apply_serving_config decides which
        # knobs the user passed explicitly by scanning argv, and an
        # abbreviated flag would dodge the scan and lose to --config
        allow_abbrev=False,
    )
    parser.add_argument("--requests", type=int, default=16)
    parser.add_argument("--rate", type=float, default=8.0,
                        help="Poisson arrival rate, requests/second")
    parser.add_argument("--prompt-len", type=int, nargs=2, default=(4, 24),
                        metavar=("MIN", "MAX"))
    parser.add_argument("--output-len", type=int, nargs=2, default=(4, 16),
                        metavar=("MIN", "MAX"))
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--run-dir", default="runs/serve_bench",
                        help="telemetry output dir (events + metrics jsonl)")
    # engine shape knobs (all land in the jitted programs' signatures)
    parser.add_argument("--num-slots", type=int, default=8)
    parser.add_argument("--block-size", type=int, default=16)
    parser.add_argument("--num-blocks", type=int, default=128)
    parser.add_argument("--max-blocks-per-seq", type=int, default=16)
    parser.add_argument("--token-budget", type=int, default=512)
    parser.add_argument("--kv-dtype", choices=["native", "int8"],
                        default="native")
    parser.add_argument("--prefill-chunk", type=int, default=32,
                        help="Sarathi-style chunked prefill: tokens per "
                        "chunk (prompts stream into the pool sharing the "
                        "tick budget with decodes); 0 = legacy "
                        "whole-prompt prefill")
    parser.add_argument("--paged-kernel", choices=["pallas", "xla"],
                        default="pallas",
                        help="paged-decode attention back-end: the "
                        "streaming Pallas kernel (interpreted off-TPU) or "
                        "the XLA block-window gather fallback")
    parser.add_argument("--spec-k", type=int, default=0,
                        help="self-drafting speculative decoding: n-gram "
                        "draft tokens scored per decode row per tick "
                        "(0 = off)")
    parser.add_argument("--spec-k-sweep", metavar="LIST",
                        help="A/B the draft length: comma list of k "
                        "values; the SAME workload runs once per k on a "
                        "fresh engine, the serve-summary reports every "
                        "arm plus the tokens/s-optimal k, and the "
                        "--assert-spec-accept-rate gate judges the "
                        "winning arm (single-replica; disables the "
                        "journal — a sweep is a measurement drill)")
    # ---- the fleet (docs/SERVING.md "The fleet") ----
    parser.add_argument("--replicas", type=int, default=1,
                        help="data-parallel engine replicas behind the "
                        "prefix-affinity router; ONE Poisson stream "
                        "drives the fleet, each replica ticks on its own "
                        "device group (toy model only off-chip)")
    parser.add_argument("--mp", type=int, default=1,
                        help="model-parallel shards per replica: KV "
                        "pools shard over the model axis (each chip "
                        "holds n_kv/mp heads) and the tick programs run "
                        "SPMD; needs replicas*mp devices")
    # ---- process mode (docs/SERVING.md "Process mode") ----
    parser.add_argument("--replicas-proc", type=int, default=0,
                        metavar="N",
                        help="process-isolated fleet: N replica "
                        "SUBPROCESSES behind the router, supervised "
                        "in-run (SIGKILL a replica -> journal-exact "
                        "failover to survivors + budgeted relaunch); "
                        "replaces --replicas, toy model only, mp=1")
    parser.add_argument("--hostsfile", metavar="FILE",
                        help="with --replicas-proc: span the fleet over "
                        "the hosts listed here (runner hostsfile syntax, "
                        "slots= caps replicas per host). Remote hosts "
                        "spawn over ssh, workers publish host:port into "
                        "<run-dir>/rendezvous.jsonl, drain/abort ride "
                        "the control-plane flag files, and relaunches "
                        "pin their recorded host (docs/SERVING.md "
                        "\"Host mode\")")
    parser.add_argument("--autoscale", action="store_true",
                        help="with --replicas-proc: spawn a replica "
                        "under sustained fleet-wide pressure, drain one "
                        "at sustained idle (budgeted, never below "
                        "--min-replicas)")
    parser.add_argument("--min-replicas", type=int, default=1,
                        help="autoscale floor (drains stop here)")
    parser.add_argument("--max-replicas", type=int, default=4,
                        help="autoscale ceiling (spawns stop here)")
    parser.add_argument("--autoscale-sustain-s", type=float, default=2.0,
                        help="seconds the whole fleet must stay above "
                        "the high watermark before a spawn (also the "
                        "action cooldown)")
    parser.add_argument("--autoscale-idle-s", type=float, default=5.0,
                        help="seconds the whole fleet must stay idle "
                        "before a drain")
    parser.add_argument("--restart-budget", type=int, default=3,
                        help="with --replicas-proc: supervised "
                        "relaunches allowed per replica before the "
                        "supervisor gives it up")
    parser.add_argument("--capacity-dir", metavar="DIR",
                        help="with --replicas-proc: join the elastic "
                        "capacity channel at DIR (the training "
                        "supervisor's <control_dir>/capacity — "
                        "docs/RESILIENCE.md \"Elastic capacity\"). The "
                        "fleet heartbeats its pool pressure there; the "
                        "training-side arbiter answers sustained "
                        "pressure by LEASING a training host (the fleet "
                        "spawns a replica on it and activates the "
                        "lease) and reclaims it at sustained idle (the "
                        "fleet drains that host's replicas, then "
                        "releases)")
    parser.add_argument("--capacity-publish-s", type=float, default=0.5,
                        help="demand-heartbeat period on the capacity "
                        "channel")
    parser.add_argument("--config", metavar="FILE",
                        help="tuner-emitted serving config (python -m "
                        "scaling_tpu.tune --serve --emit-config): its "
                        "mp/replicas/block_size/token_budget/num_slots/"
                        "num_blocks become defaults; explicit flags win")
    parser.add_argument("--shared-prefix-len", type=int, default=0,
                        help="prefix-cache arm: every request shares one "
                        "of --prefix-families system prompts of this "
                        "length (0 = fully random prompts)")
    parser.add_argument("--prefix-families", type=int, default=1,
                        help="number of distinct shared prefixes for "
                        "--shared-prefix-len")
    parser.add_argument("--no-prefix-cache", action="store_true",
                        help="disable shared-prefix block reuse (the A/B "
                        "for --shared-prefix-len)")
    parser.add_argument("--no-fused-tick", action="store_true",
                        help="legacy dispatch: separate decode + "
                        "per-sequence chunk programs instead of ONE "
                        "mixed program per tick")
    parser.add_argument("--warmup", type=int, default=0,
                        help="serve N throwaway requests (excluded from "
                        "stats) before the open-loop clock starts, so "
                        "first-tick jit compiles don't distort arrival "
                        "timing")
    # resilience knobs (docs/SERVING.md "Resilience")
    parser.add_argument("--deadline-ms", type=float, default=None,
                        help="per-request total deadline (ms from "
                        "arrival); expired requests are cancelled at the "
                        "next tick boundary with status 'timeout'")
    parser.add_argument("--ttft-deadline-ms", type=float, default=None,
                        help="per-request first-token deadline (ms)")
    parser.add_argument("--shed-high-watermark", type=float, default=None,
                        help="pool-pressure fraction above which new "
                        "submissions are shed with structured "
                        "backpressure (hysteresis down to "
                        "--shed-low-watermark); default: no shedding")
    parser.add_argument("--shed-low-watermark", type=float, default=None,
                        help="pool-pressure fraction at which shedding "
                        "stops again (defaults to the high watermark)")
    parser.add_argument("--max-waiting", type=int, default=None,
                        help="hard waiting-queue depth cap; submissions "
                        "beyond it are shed (default: unbounded)")
    parser.add_argument("--no-journal", action="store_true",
                        help="disable the crash-replay request journal "
                        "(<run-dir>/journal.jsonl)")
    parser.add_argument("--resume", action="store_true",
                        help="replay <run-dir>/journal.jsonl first: "
                        "re-enqueue incomplete requests (same req ids -> "
                        "token-identical continuations) and skip the "
                        "workload items already submitted")
    parser.add_argument("--restarts", type=int, default=0,
                        help="supervised mode: run the bench as child "
                        "processes, relaunching with --resume after a "
                        "crash, up to N restarts (the serving "
                        "run_with_resume)")
    parser.add_argument("--tick-timeout-s", type=float, default=0.0,
                        help="tick-stall watchdog: dump thread stacks + "
                        "log a serve-stall event when no tick completes "
                        "for this long (0 = off)")
    # toy model knobs / real checkpoint
    parser.add_argument("--hidden", type=int, default=64)
    parser.add_argument("--layers", type=int, default=2)
    parser.add_argument("--vocab", type=int, default=128)
    parser.add_argument("--heads", type=int, default=4)
    parser.add_argument("--checkpoint", help="serve a real checkpoint dir "
                        "instead of the random toy model")
    parser.add_argument("--max-wall-s", type=float, default=600.0)
    parser.add_argument("--json", metavar="FILE",
                        help="also write the summary stats as JSON")
    parser.add_argument("--assert-serve-throughput", type=float,
                        metavar="FLOOR",
                        help="fail (exit 1) when output tokens/s is below "
                        "FLOOR (same gate `obs report` applies to the "
                        "run dir)")
    parser.add_argument("--assert-ttft", type=float, metavar="CEIL",
                        help="fail (exit 1) when p99 time-to-first-token "
                        "exceeds CEIL seconds")
    argv = list(sys.argv[1:] if argv is None else argv)
    args = parser.parse_args(argv)
    if args.config:
        _apply_serving_config(args, argv, parser)
    if args.replicas_proc and args.restarts:
        parser.error("--replicas-proc supervises its replicas in-run "
                     "(relaunch + journal failover); --restarts "
                     "supervises the in-process bench — pick one")
    if args.restarts > 0:
        return run_supervised(argv, args)
    if args.requests < 1:
        parser.error("--requests must be >= 1")
    if args.rate <= 0:
        parser.error("--rate must be > 0")
    for flag, (lo, hi), floor in (("--prompt-len", args.prompt_len, 1),
                                  ("--output-len", args.output_len, 1)):
        if lo < floor or hi < lo:
            parser.error(f"{flag} needs {floor} <= MIN <= MAX, got {lo} {hi}")
    if args.replicas < 1 or args.mp < 1:
        parser.error("--replicas and --mp must be >= 1")
    fleet = args.replicas > 1
    sweep_ks: Optional[List[int]] = None
    if args.spec_k_sweep:
        try:
            sweep_ks = sorted({
                int(x) for x in args.spec_k_sweep.split(",") if x.strip()
            })
        except ValueError:
            sweep_ks = None
        if not sweep_ks or any(k < 0 for k in sweep_ks):
            parser.error(
                f"bad --spec-k-sweep {args.spec_k_sweep!r} "
                "(want a comma list of ints >= 0)"
            )
        if fleet:
            parser.error("--spec-k-sweep is single-replica (the sweep "
                         "measures the engine, not the router)")
        if args.resume:
            parser.error("--spec-k-sweep runs without a journal (it is "
                         "a measurement drill); --resume has nothing to "
                         "replay")
    if args.checkpoint and fleet:
        parser.error(
            "--replicas > 1 serves the toy model only (an in-process "
            "fleet of checkpoint-sized replicas is a dev harness, not a "
            "deployment; production runs one process per replica)"
        )
    proc_fleet = args.replicas_proc > 0
    if proc_fleet:
        if args.replicas_proc < 1:
            parser.error("--replicas-proc must be >= 1")
        if fleet:
            parser.error("--replicas-proc IS the fleet (subprocess "
                         "replicas); drop --replicas")
        if args.mp > 1:
            parser.error("--replicas-proc serves mp=1 replicas (each "
                         "worker process owns its own devices)")
        if args.checkpoint:
            parser.error("--replicas-proc serves the toy model only "
                         "(workers rebuild the model from the config "
                         "they are handed)")
        if sweep_ks is not None:
            parser.error("--spec-k-sweep is single-replica")
        if args.resume:
            parser.error("--replicas-proc recovers in-run (the "
                         "supervisor harvests dead replicas' journals); "
                         "--resume is the in-process replay path")
        if args.no_journal:
            parser.error("--replicas-proc needs the journal — failover "
                         "replays it")
        if args.autoscale and args.min_replicas > args.replicas_proc:
            parser.error("--min-replicas exceeds --replicas-proc")
        if args.autoscale and args.max_replicas < args.min_replicas:
            parser.error("--max-replicas < --min-replicas")
    else:
        if args.hostsfile:
            parser.error("--hostsfile spans the PROCESS fleet over "
                         "machines; it needs --replicas-proc")
        _ensure_devices(args.replicas * args.mp)
    # the proc-fleet HOST never builds an engine: the jax-importing
    # modules load only in the worker subprocesses
    if not proc_fleet:
        from .engine import EngineConfig, ServeEngine, install_drain_handler

    import os

    run_dir = Path(args.run_dir)
    run_dir.mkdir(parents=True, exist_ok=True)
    # telemetry rails: events via the logger's env hook, metrics via the
    # registry's explicit sink (mirrors how the supervisor wires hosts)
    os.environ.setdefault(
        "SCALING_TPU_EVENTS_PATH", str(run_dir / "events.jsonl")
    )
    from ..obs import get_registry

    get_registry().configure(metrics_path=str(run_dir / "metrics.jsonl"))

    if proc_fleet:
        # workers build their own toy model from the handed config
        infs = []
        inf = None
        vocab = args.vocab
    elif args.checkpoint:
        from ..models.transformer.inference import TransformerInferenceModule

        topology = (
            {"model_parallel_size": args.mp} if args.mp > 1 else None
        )
        inf = TransformerInferenceModule.from_checkpoint(
            args.checkpoint, topology=topology
        )
        infs = [inf]
        vocab = inf.architecture.vocab_size
    else:
        # one model instance per replica, each on its own device group
        # (offset r*mp): deterministic init keys mean every replica holds
        # the SAME weights — a data-parallel serving fleet
        infs = [
            build_toy_inference(
                hidden=args.hidden, layers=args.layers, vocab=args.vocab,
                heads=args.heads, mp=args.mp, device_offset=r * args.mp,
            )
            for r in range(args.replicas)
        ]
        inf = infs[0]
        vocab = args.vocab

    cap = args.max_blocks_per_seq * args.block_size
    longest = (args.prompt_len[1] + args.shared_prefix_len
               + args.output_len[1])
    if longest > cap:
        print(
            f"error: prompt+output can reach {longest} tokens but the "
            f"block table holds {cap}; raise --max-blocks-per-seq or "
            "--block-size", file=sys.stderr,
        )
        return 2
    if args.shared_prefix_len > 0 and args.prefix_families < 1:
        parser.error("--prefix-families must be >= 1")

    def make_engine(replica_id=None, inf_override=None, spec_k=None):
        return ServeEngine(inf_override or inf, EngineConfig(
            num_slots=args.num_slots, block_size=args.block_size,
            num_blocks=args.num_blocks,
            max_blocks_per_seq=args.max_blocks_per_seq,
            token_budget=args.token_budget, kv_dtype=args.kv_dtype,
            prefill_chunk=args.prefill_chunk or None,
            paged_kernel=args.paged_kernel,
            fused_tick=not args.no_fused_tick,
            enable_prefix_cache=not args.no_prefix_cache,
            spec_k=args.spec_k if spec_k is None else spec_k,
            default_deadline_ms=args.deadline_ms,
            default_ttft_deadline_ms=args.ttft_deadline_ms,
            shed_high_watermark=args.shed_high_watermark,
            shed_low_watermark=args.shed_low_watermark,
            max_waiting=args.max_waiting,
            replica_id=replica_id,
        ))

    def warmup_engine(engine):
        # compile the tick programs off the clock: the first mixed-step
        # call jit-compiles for seconds, and an open-loop workload that
        # arrives during it measures the compiler, not the engine
        engine.warmup_mode = True
        for _ in range(args.warmup):
            engine.submit([1], 2)
        engine.run_until_done()
        engine.warmup_mode = False
        engine.finished.clear()

    workload = sample_workload(
        args.requests, args.rate, tuple(args.prompt_len),
        tuple(args.output_len), vocab, args.seed,
        shared_prefix_len=args.shared_prefix_len,
        prefix_families=args.prefix_families,
    )
    journal_base = run_dir / "journal.jsonl"

    if proc_fleet:
        stats = _run_fleet_proc(args, workload, run_dir, journal_base)
    elif fleet:
        stats = _run_fleet(args, infs, workload, journal_base, make_engine,
                           warmup_engine)
    elif sweep_ks is not None:
        stats = _run_spec_sweep(args, sweep_ks, workload, make_engine,
                                warmup_engine)
    else:
        engine = make_engine()
        # SIGTERM -> graceful drain: stop admitting, finish in-flight,
        # flush telemetry, exit 0 with a parseable run dir
        install_drain_handler(engine)
        replay = None
        if not args.no_journal:
            from .journal import open_journal

            # --resume folds the crashed run's journal first; a fresh run
            # truncates any stale one from a previous drill in this dir
            journal, replay = open_journal(journal_base, args.resume)
            engine.attach_journal(journal)
        elif args.resume:
            from .journal import replay_journal

            replay = replay_journal(journal_base)
        if args.warmup > 0:
            warmup_engine(engine)
        extra_stats = None
        carry = None
        if replay is not None and replay.offered_count:
            from ..logging import logger

            # crash-replay: re-enqueue every request without a terminal
            # status under its ORIGINAL id (the sampler keys fold the id,
            # so the regenerated tokens are the ones the crashed run would
            # have emitted), then serve the workload tail the crashed run
            # never reached. force=True: recovery work is never shed.
            incomplete = replay.incomplete
            engine._next_req_id = replay.next_req_id
            for rec in incomplete:
                engine.submit(
                    rec["prompt"], rec["max_new_tokens"],
                    eos_token_id=rec.get("eos_token_id"),
                    temperature=rec.get("temperature", 0.0),
                    top_k=rec.get("top_k"), top_p=rec.get("top_p"),
                    deadline_ms=rec.get("deadline_ms"),
                    ttft_deadline_ms=rec.get("ttft_deadline_ms"),
                    req_id=int(rec["req"]), force=True,
                    trace=rec.get("trace"),
                )
            # skip every workload item the crashed run(s) CONSUMED — both
            # admitted submissions and overload sheds (a shed offer was
            # answered with Backpressure; re-offering it would double-serve
            # the tail behind it)
            done = replay.offered_count
            workload = sorted(workload, key=lambda w: w[0])[done:]
            if workload:
                base = workload[0][0]  # the tail arrives from t=0 again
                workload = [(a - base, p, o) for a, p, o in workload]
            extra_stats = {
                "resumed": True,
                "replayed_incomplete": len(incomplete),
                "replayed_completed": len(replay.completed),
            }
            # the crashed run(s)' terminal tallies fold into this run's
            # summary so the gates judge the whole run dir
            carry = {
                "completed": len(replay.completed),
                "timeouts": replay.timeout_count,
                "shed": replay.shed_count,
            }
            logger.log_event(
                "serve-resume", incomplete=len(incomplete),
                completed=len(replay.completed),
                remaining_workload=len(workload),
            )
        stats = run_bench(
            engine, workload, max_wall_s=args.max_wall_s,
            tick_timeout_s=args.tick_timeout_s, extra_stats=extra_stats,
            carry=carry,
        )

    print("== serve bench ==")
    print(f"  requests={stats['requests']} wall={stats['wall_s']:.3f}s "
          f"ticks={stats['ticks']} preemptions={stats['preemptions']} "
          f"prefill_compiles={stats['prefill_compiles']}")
    if (stats["requests_shed"] or stats["requests_timeout"]
            or stats["drained"]):
        print(f"  resilience: shed={stats['requests_shed']} "
              f"(rate {stats['shed_rate']:.1%}) "
              f"timeouts={stats['requests_timeout']} "
              f"drained={stats['drained']} "
              f"unsubmitted={stats['unsubmitted']}")
    print(f"  hot path: paged_kernel={args.paged_kernel} "
          f"prefill_chunk={args.prefill_chunk or 'off'} "
          f"fused_tick={not args.no_fused_tick} "
          f"max_concurrent_prefills={stats['max_concurrent_prefills']}")
    if args.mp > 1:
        print(f"  sharding: mp={args.mp} (KV pools sharded over the "
              f"model axis, {args.mp}x less pool memory per chip)")
    if stats.get("replicas", 1) > 1:
        r = stats["router"]
        print(f"  fleet: replicas={stats['replicas']} "
              f"affinity_hits={r['affinity_dispatches']}/{r['dispatches']} "
              f"({r['affinity_hit_rate']:.1%}) "
              f"retries_elsewhere={r['retries_elsewhere']} "
              f"rejected={r['rejected']}")
        for row in stats["replica_stats"]:
            if row.get("retired"):
                mark = " [drained]"
            elif not row.get("alive", True):
                mark = " [FAILED]"
            else:
                mark = ""
            if row.get("restarts"):
                mark = f" restarts={row['restarts']}" + mark
            if row.get("host") is not None:
                mark = f" host={row['host']}" + mark
            print(f"    replica {row['replica']}: "
                  f"requests={row['requests']} "
                  f"tokens={row['output_tokens']} "
                  f"dispatches={row.get('dispatches', 0)} "
                  f"ticks={row['ticks']} "
                  f"pressure={row['pool_pressure']:.2f}" + mark)
    if stats.get("proc_fleet"):
        print(f"  supervision: restarts={stats['replica_restarts']} "
              f"spawns={stats['replica_spawns']} "
              f"drains={stats['replica_drains']} "
              f"recovered={stats['recovered_requests']} "
              f"redispatched={stats['redispatched_requests']}")
        if stats.get("fleet_hosts") is not None:
            print(f"  hosts: planned={stats['fleet_hosts']} "
                  f"reported={stats['hosts_reported']} "
                  f"submit_dups={stats['submit_dups']} "
                  f"rpc_retries={stats['rpc_retries']}")
    if stats.get("spec_k_sweep"):
        print(f"  spec-k sweep (best k={stats['spec_k_best']}):")
        for row in stats["spec_k_sweep"]:
            ar = row["spec_accept_rate"]
            mark = " <- best" if row["spec_k"] == stats["spec_k_best"] else ""
            print(f"    k={row['spec_k']}: {row['tokens_per_s']:.1f} tok/s "
                  f"accept="
                  f"{'n/a' if ar is None else format(ar, '.1%')}{mark}")
    if stats["prefix_hit_tokens"]:
        print(f"  prefix cache: {stats['prefix_hit_tokens']} tokens hit, "
              f"{stats['prefilled_tokens']} prefilled "
              f"({stats['prompt_tokens']} prompt tokens submitted; "
              f"hit rate {stats['prefix_hit_rate']:.1%})")
    if stats["spec_accept_rate"] is not None:
        # a sweep's final stats describe the WINNING arm, not --spec-k
        spec_k = stats.get("engine", {}).get("spec_k", args.spec_k)
        print(f"  speculation: k={spec_k} accepted "
              f"{stats['spec_accepted_tokens']}/"
              f"{stats['spec_drafted_tokens']} drafts "
              f"(accept rate {stats['spec_accept_rate']:.1%})")
    print(f"  output tokens/s: {stats['tokens_per_s']:.1f} "
          f"({stats['output_tokens']} tokens)")
    if stats["ttft_p50_s"] is not None:
        print(f"  ttft: p50={stats['ttft_p50_s']:.4f}s "
              f"p99={stats['ttft_p99_s']:.4f}s")
    if stats["itl_p50_s"] is not None:
        print(f"  itl:  p50={stats['itl_p50_s']:.4f}s "
              f"p99={stats['itl_p99_s']:.4f}s")
    print(f"  run dir: {run_dir} (analyze: python -m scaling_tpu.obs "
          f"report {run_dir})")

    if args.json:
        from ..resilience.guards import retry_io

        stats_text = json.dumps(stats, indent=1) + "\n"
        retry_io(
            lambda: Path(args.json).write_text(stats_text),
            what="bench stats write",
        )

    failures = []
    if (args.assert_serve_throughput is not None
            and stats["tokens_per_s"] < args.assert_serve_throughput):
        failures.append(
            f"assert-serve-throughput: {stats['tokens_per_s']:.1f} tokens/s "
            f"< floor {args.assert_serve_throughput:.1f}"
        )
    if args.assert_ttft is not None and (
            stats["ttft_p99_s"] is None
            or stats["ttft_p99_s"] > args.assert_ttft):
        failures.append(
            f"assert-ttft: p99 TTFT {stats['ttft_p99_s']}s "
            f"> ceiling {args.assert_ttft}s"
        )
    if args.assert_serve_throughput is not None or args.assert_ttft is not None:
        print("== gates ==")
        for f in failures:
            print(f"  FAIL {f}")
        if not failures:
            print("  PASS")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
