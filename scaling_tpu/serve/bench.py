"""Serving load generator: Poisson arrivals -> engine -> obs telemetry.

``python -m scaling_tpu.serve bench`` drives the continuous-batching
engine with an open-loop Poisson arrival process (exponential
inter-arrival gaps at ``--rate`` req/s) and prompt/output lengths sampled
uniformly from ``--prompt-len``/``--output-len`` ranges, then reports
tokens/s, p50/p99 time-to-first-token and inter-token latency.

Telemetry rides the SAME rails training uses (docs/OBSERVABILITY.md):
metrics through ``obs.get_registry()`` (flushed to ``<run-dir>/
metrics.jsonl``), per-request ``serve-request`` + final ``serve-summary``
events through ``logger.log_event`` — so ``python -m scaling_tpu.obs
report <run-dir>`` grows a serving section, and the
``--assert-serve-throughput`` / ``--assert-ttft`` gates work both here
(self-gating, like ``bench.py --assert-mfu``) and on the analyzer over
the run dir (CI reads the artifacts, not the console).

The model is a randomly initialised toy transformer by default (the
benchmark measures the ENGINE: scheduling, paging, recompile hygiene);
``--checkpoint`` serves a real one.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path
from typing import List, Optional

from .engine import EngineConfig, ServeEngine, install_drain_handler
from .scheduler import Backpressure


def build_toy_inference(hidden: int = 64, layers: int = 2, vocab: int = 128,
                        heads: int = 4, seq_len: int = 256):
    """Random-init tiny model wrapped for inference (no checkpoint)."""
    import jax

    from ..models.transformer import TransformerConfig
    from ..models.transformer.inference import TransformerInferenceModule
    from ..models.transformer.model import init_model

    config = TransformerConfig.from_dict({
        "topology": {
            "model_parallel_size": 1, "pipe_parallel_size": 1,
            "data_parallel_size": 1, "micro_batch_size": 1,
            "gradient_accumulation_steps": 1,
        },
        "transformer_architecture": {
            "vocab_size": vocab, "hidden_size": hidden, "num_layers": layers,
            "num_attention_heads": heads, "sequence_length": seq_len,
            "mlp_type": "swiglu", "mlp_factor": 2.0, "norm_type": "rms",
            "weight_tying": False,
        },
        "optimizer": {"gradient_clipping": 1.0},
        "learning_rate_scheduler": {
            "learning_rate": 3e-4, "learning_rate_warmup_steps": 10,
            "learning_rate_decay_iters": 100,
        },
        "trainer": {"train_iterations": 1, "seed": 0},
        "data": {}, "logger": {"log_dir": None},
    })
    module = init_model(config, None)
    params = module.init_params(jax.random.PRNGKey(0))
    return TransformerInferenceModule(config, module, params)


def sample_workload(n_requests: int, rate: float, prompt_len, output_len,
                    vocab: int, seed: int, shared_prefix_len: int = 0,
                    prefix_families: int = 1):
    """Poisson arrival offsets + per-request prompts/output budgets.

    ``shared_prefix_len > 0`` models the dominant real-traffic shape:
    requests draw one of ``prefix_families`` fixed system prompts of
    that length and append a random tail sampled from ``prompt_len`` —
    the prefix-cache arm of the benchmark (``--shared-prefix-len``)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    prefixes = [
        rng.integers(1, vocab, size=shared_prefix_len).tolist()
        for _ in range(prefix_families)
    ] if shared_prefix_len > 0 else []
    gaps = rng.exponential(1.0 / rate, size=n_requests)
    arrivals = np.cumsum(gaps)
    arrivals[0] = 0.0  # the first request opens the run
    work = []
    for i in range(n_requests):
        plen = int(rng.integers(prompt_len[0], prompt_len[1] + 1))
        olen = int(rng.integers(output_len[0], output_len[1] + 1))
        tail = rng.integers(1, vocab, size=plen).tolist()
        prompt = (prefixes[i % prefix_families] + tail) if prefixes else tail
        work.append((float(arrivals[i]), prompt, olen))
    return work


def run_bench(engine: ServeEngine, workload, time_scale: float = 1.0,
              max_wall_s: float = 600.0, tick_timeout_s: float = 0.0,
              extra_stats: Optional[dict] = None,
              carry: Optional[dict] = None) -> dict:
    """Open-loop drive: submit each request when the wall clock crosses
    its arrival offset, tick the engine continuously, drain. Returns the
    summary stats dict (also emitted as the ``serve-summary`` event).

    Resilience rails (docs/SERVING.md "Resilience"): a submission the
    engine sheds (watermark backpressure) is counted, not retried — the
    open-loop client models a router that took the hint elsewhere. When
    the engine flips to ``draining`` (SIGTERM), submission stops,
    in-flight requests run to completion or their deadlines, and the
    loop exits cleanly with the unsubmitted tail counted.
    ``tick_timeout_s > 0`` arms a tick-stall watchdog (the resilience
    ``StepStallWatchdog``): a tick that stops beating dumps thread
    stacks, logs a ``serve-stall`` event, and then SIGKILLs the process
    — a wedged tick (hung device, dead mount) is unrecoverable
    in-process, and dying loudly is what lets a ``--restarts``
    supervisor replay the journal instead of hanging forever behind a
    silent child. ``carry`` folds a crashed predecessor's terminal
    tallies (completed/timeouts/shed, from the journal replay) into
    the summary so the FINAL summary — the one the shed/timeout gates
    read — describes the whole run dir, not just the last process."""
    import os
    import signal as _signal

    from ..logging import logger
    from ..obs import get_registry, span

    watchdog = None
    if tick_timeout_s > 0:
        from ..resilience import StepStallWatchdog

        def _on_stall(tick, elapsed):
            logger.log_event(
                "serve-stall", tick=tick, stalled_s=round(elapsed, 3)
            )
            os.kill(os.getpid(), _signal.SIGKILL)

        watchdog = StepStallWatchdog(tick_timeout_s, on_stall=_on_stall)
        watchdog.start()

    t0 = time.monotonic()
    start_ticks = engine.tick_index  # warmup ticks stay off the books
    pending = sorted(workload, key=lambda w: w[0])
    idx = 0
    try:
        while True:
            now = time.monotonic() - t0
            if now > max_wall_s:
                raise RuntimeError(
                    f"bench exceeded --max-wall-s={max_wall_s}: "
                    f"{idx}/{len(pending)} submitted, "
                    f"{len(engine.finished)} finished"
                )
            while not engine.draining and idx < len(pending) and \
                    pending[idx][0] * time_scale <= now:
                arrival, prompt, olen = pending[idx]
                res = engine.submit(
                    prompt, olen, arrival_s=t0 + arrival * time_scale
                )
                if isinstance(res, Backpressure) and res.draining:
                    # SIGTERM raced this submission: it was never
                    # offered to a live engine — unsubmitted, not shed
                    break
                idx += 1
            if watchdog is not None:
                # beat every loop pass, idle waits included — the
                # watchdog watches for a WEDGED tick (the loop stuck
                # inside engine.tick() stops beating), not for a
                # healthy bench sleeping between Poisson arrivals
                watchdog.beat(engine.tick_index)
            if engine.scheduler.has_work:
                with span("serve.tick", step=engine.tick_index):
                    engine.tick()
            elif engine.draining or idx >= len(pending):
                break
            else:
                # idle until the next arrival (clamped: stay responsive)
                wait = pending[idx][0] * time_scale - (time.monotonic() - t0)
                if wait > 0:
                    time.sleep(min(wait, 0.05))
    finally:
        if watchdog is not None:
            watchdog.stop()

    wall_s = time.monotonic() - t0
    seqs = engine.finished
    completed = [s for s in seqs if s.finish_status == "completed"]
    ttfts = sorted(
        s.first_token_s - s.request.arrival_s for s in seqs
        if s.first_token_s is not None
    )
    itls: List[float] = []
    for s in seqs:
        itls.extend(b - a for a, b in zip(s.token_stamps, s.token_stamps[1:]))
    itls.sort()
    total_tokens = sum(len(s.generated) for s in seqs)

    # the SAME nearest-rank percentile `obs report` uses over the run
    # dir, so the self-gate here and the CI gate there can never
    # disagree about the same run's p99
    from ..obs.report import percentile

    def pct(vals, q):
        return percentile(vals, q) if vals else None

    prompt_tokens = sum(len(s.request.prompt) for s in seqs)
    # hits count every (re-)admission match (a preempted sequence
    # re-matching its own cached blocks included), so the rate is
    # work-avoided / work-demanded: hit / (hit + actually-prefilled) —
    # bounded [0, 1] even when preemptions force re-prefills
    hit = engine.scheduler.prefix_hit_tokens
    prefilled = engine.prefilled_tokens
    # cumulative across supervised relaunches: `carry` holds the
    # crashed predecessor runs' terminal tallies from the journal
    # replay, so the final summary — the one the shed/timeout gates
    # read — describes the WHOLE run dir, not just this process
    carry = carry or {}
    c_completed = int(carry.get("completed", 0))
    c_timeouts = int(carry.get("timeouts", 0))
    c_shed = int(carry.get("shed", 0))
    total_shed = engine.shed_count + c_shed
    total_timeouts = engine.timeout_count + c_timeouts
    attempts = total_shed + total_timeouts + len(completed) + c_completed
    stats = {
        "requests": len(completed) + c_completed,
        "requests_timeout": total_timeouts,
        "requests_shed": total_shed,
        "shed_rate": (
            round(total_shed / attempts, 4) if attempts else 0.0
        ),
        "drained": engine.draining,
        "unsubmitted": len(pending) - idx,
        "wall_s": round(wall_s, 6),
        "output_tokens": total_tokens,
        "prompt_tokens": prompt_tokens,
        "tokens_per_s": round(total_tokens / wall_s, 3) if wall_s > 0 else 0.0,
        "ttft_p50_s": pct(ttfts, 50),
        "ttft_p99_s": pct(ttfts, 99),
        "itl_p50_s": pct(itls, 50),
        "itl_p99_s": pct(itls, 99),
        "preemptions": engine.scheduler.preemption_count,
        "ticks": engine.tick_index - start_ticks,
        "prefill_compiles": engine.prefill_program_count,
        "max_concurrent_prefills": engine.max_concurrent_prefills,
        # raw-speed rails (ISSUE 11): prefill work actually paid after
        # shared-prefix reuse, and the self-drafting accept rate
        "prefix_hit_tokens": hit,
        "prefix_hit_rate": (
            round(hit / (hit + prefilled), 4) if hit + prefilled else 0.0
        ),
        "prefilled_tokens": prefilled,
        "spec_drafted_tokens": engine.spec_drafted_tokens,
        "spec_accepted_tokens": engine.spec_accepted_tokens,
        "spec_accept_rate": (
            round(engine.spec_accept_rate, 4)
            if engine.spec_accept_rate is not None else None
        ),
    }
    if extra_stats:
        stats.update(extra_stats)
    logger.log_event("serve-summary", **stats)
    get_registry().flush_step(engine.tick_index)
    return stats


def run_supervised(argv: List[str], args) -> int:
    """``--restarts N``: the serving counterpart of
    ``resilience.run_with_resume`` — run the bench as a child process
    and, when it dies (a ``serve.tick`` kill, an OOM, a wedged tick),
    relaunch it with ``--resume`` so the request journal replays: every
    incomplete request re-enqueues with its original id and regenerates
    token-for-token. Exits 0 the moment a child drains cleanly;
    re-raises the child's exit code once the budget is spent.

    A ``SCALING_TPU_FAULTS`` chaos plan arms the FIRST launch only:
    hit counters are per-process, so a persistent plan would kill every
    replay at the same tick and turn a bounded-restart drill into
    guaranteed budget exhaustion.

    SIGTERM to the supervisor is RELAYED to the running child (whose
    own drain handler finishes in-flight work and exits 0) and ends
    the supervision loop — the graceful-drain contract holds in the
    supervised deployment mode too, and no orphan keeps writing to the
    run dir. A child that dies mid-drain is not relaunched (mirroring
    the trainer supervisor's preemption rule)."""
    import os
    import signal
    import subprocess

    from ..logging import logger

    child_argv: List[str] = []
    skip = False
    for a in argv:
        if skip:
            skip = False
            continue
        if a == "--restarts":
            skip = True
            continue
        if a.startswith("--restarts="):
            continue
        child_argv.append(a)
    run_dir = Path(args.run_dir)
    run_dir.mkdir(parents=True, exist_ok=True)
    # the supervisor's own lifecycle events (serve-restart / give-up)
    # land in the same run dir the children write to
    os.environ.setdefault(
        "SCALING_TPU_EVENTS_PATH", str(run_dir / "events.jsonl")
    )
    env = dict(os.environ)
    state = {"child": None, "draining": False}

    def _relay(signum, frame):
        state["draining"] = True
        child = state["child"]
        if child is not None and child.poll() is None:
            child.send_signal(signal.SIGTERM)

    prev = signal.getsignal(signal.SIGTERM)
    signal.signal(signal.SIGTERM, _relay)
    attempts = 0
    try:
        while True:
            if state["draining"]:
                # SIGTERM landed while no child was running (e.g.
                # between a crash and the relaunch): relaunching would
                # serve the whole remaining workload with the drain
                # request silently ignored — stop here instead
                logger.log_event("serve-drain", supervisor=True)
                return 0
            cmd = [sys.executable, "-m", "scaling_tpu.serve", "bench",
                   *child_argv]
            if attempts > 0 and "--resume" not in child_argv:
                cmd.append("--resume")
            state["child"] = subprocess.Popen(cmd, env=env)
            if state["draining"]:
                # the signal raced the launch: the handler saw no child
                state["child"].send_signal(signal.SIGTERM)
            rc = state["child"].wait()
            state["child"] = None
            if rc == 0:
                return 0
            if state["draining"]:
                logger.log_event("serve-drain-failed", rc=rc)
                return rc if rc > 0 else 1
            attempts += 1
            if attempts > args.restarts:
                logger.log_event(
                    "serve-give-up", attempts=attempts - 1, rc=rc,
                )
                return rc if rc > 0 else 1
            logger.log_event("serve-restart", attempt=attempts, rc=rc)
            env.pop("SCALING_TPU_FAULTS", None)
    finally:
        signal.signal(signal.SIGTERM, prev)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m scaling_tpu.serve bench",
        description="continuous-batching serving benchmark (docs/SERVING.md)",
    )
    parser.add_argument("--requests", type=int, default=16)
    parser.add_argument("--rate", type=float, default=8.0,
                        help="Poisson arrival rate, requests/second")
    parser.add_argument("--prompt-len", type=int, nargs=2, default=(4, 24),
                        metavar=("MIN", "MAX"))
    parser.add_argument("--output-len", type=int, nargs=2, default=(4, 16),
                        metavar=("MIN", "MAX"))
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--run-dir", default="runs/serve_bench",
                        help="telemetry output dir (events + metrics jsonl)")
    # engine shape knobs (all land in the jitted programs' signatures)
    parser.add_argument("--num-slots", type=int, default=8)
    parser.add_argument("--block-size", type=int, default=16)
    parser.add_argument("--num-blocks", type=int, default=128)
    parser.add_argument("--max-blocks-per-seq", type=int, default=16)
    parser.add_argument("--token-budget", type=int, default=512)
    parser.add_argument("--kv-dtype", choices=["native", "int8"],
                        default="native")
    parser.add_argument("--prefill-chunk", type=int, default=32,
                        help="Sarathi-style chunked prefill: tokens per "
                        "chunk (prompts stream into the pool sharing the "
                        "tick budget with decodes); 0 = legacy "
                        "whole-prompt prefill")
    parser.add_argument("--paged-kernel", choices=["pallas", "xla"],
                        default="pallas",
                        help="paged-decode attention back-end: the "
                        "streaming Pallas kernel (interpreted off-TPU) or "
                        "the XLA block-window gather fallback")
    parser.add_argument("--spec-k", type=int, default=0,
                        help="self-drafting speculative decoding: n-gram "
                        "draft tokens scored per decode row per tick "
                        "(0 = off)")
    parser.add_argument("--shared-prefix-len", type=int, default=0,
                        help="prefix-cache arm: every request shares one "
                        "of --prefix-families system prompts of this "
                        "length (0 = fully random prompts)")
    parser.add_argument("--prefix-families", type=int, default=1,
                        help="number of distinct shared prefixes for "
                        "--shared-prefix-len")
    parser.add_argument("--no-prefix-cache", action="store_true",
                        help="disable shared-prefix block reuse (the A/B "
                        "for --shared-prefix-len)")
    parser.add_argument("--no-fused-tick", action="store_true",
                        help="legacy dispatch: separate decode + "
                        "per-sequence chunk programs instead of ONE "
                        "mixed program per tick")
    parser.add_argument("--warmup", type=int, default=0,
                        help="serve N throwaway requests (excluded from "
                        "stats) before the open-loop clock starts, so "
                        "first-tick jit compiles don't distort arrival "
                        "timing")
    # resilience knobs (docs/SERVING.md "Resilience")
    parser.add_argument("--deadline-ms", type=float, default=None,
                        help="per-request total deadline (ms from "
                        "arrival); expired requests are cancelled at the "
                        "next tick boundary with status 'timeout'")
    parser.add_argument("--ttft-deadline-ms", type=float, default=None,
                        help="per-request first-token deadline (ms)")
    parser.add_argument("--shed-high-watermark", type=float, default=None,
                        help="pool-pressure fraction above which new "
                        "submissions are shed with structured "
                        "backpressure (hysteresis down to "
                        "--shed-low-watermark); default: no shedding")
    parser.add_argument("--shed-low-watermark", type=float, default=None,
                        help="pool-pressure fraction at which shedding "
                        "stops again (defaults to the high watermark)")
    parser.add_argument("--max-waiting", type=int, default=None,
                        help="hard waiting-queue depth cap; submissions "
                        "beyond it are shed (default: unbounded)")
    parser.add_argument("--no-journal", action="store_true",
                        help="disable the crash-replay request journal "
                        "(<run-dir>/journal.jsonl)")
    parser.add_argument("--resume", action="store_true",
                        help="replay <run-dir>/journal.jsonl first: "
                        "re-enqueue incomplete requests (same req ids -> "
                        "token-identical continuations) and skip the "
                        "workload items already submitted")
    parser.add_argument("--restarts", type=int, default=0,
                        help="supervised mode: run the bench as child "
                        "processes, relaunching with --resume after a "
                        "crash, up to N restarts (the serving "
                        "run_with_resume)")
    parser.add_argument("--tick-timeout-s", type=float, default=0.0,
                        help="tick-stall watchdog: dump thread stacks + "
                        "log a serve-stall event when no tick completes "
                        "for this long (0 = off)")
    # toy model knobs / real checkpoint
    parser.add_argument("--hidden", type=int, default=64)
    parser.add_argument("--layers", type=int, default=2)
    parser.add_argument("--vocab", type=int, default=128)
    parser.add_argument("--heads", type=int, default=4)
    parser.add_argument("--checkpoint", help="serve a real checkpoint dir "
                        "instead of the random toy model")
    parser.add_argument("--max-wall-s", type=float, default=600.0)
    parser.add_argument("--json", metavar="FILE",
                        help="also write the summary stats as JSON")
    parser.add_argument("--assert-serve-throughput", type=float,
                        metavar="FLOOR",
                        help="fail (exit 1) when output tokens/s is below "
                        "FLOOR (same gate `obs report` applies to the "
                        "run dir)")
    parser.add_argument("--assert-ttft", type=float, metavar="CEIL",
                        help="fail (exit 1) when p99 time-to-first-token "
                        "exceeds CEIL seconds")
    argv = list(sys.argv[1:] if argv is None else argv)
    args = parser.parse_args(argv)
    if args.restarts > 0:
        return run_supervised(argv, args)
    if args.requests < 1:
        parser.error("--requests must be >= 1")
    if args.rate <= 0:
        parser.error("--rate must be > 0")
    for flag, (lo, hi), floor in (("--prompt-len", args.prompt_len, 1),
                                  ("--output-len", args.output_len, 1)):
        if lo < floor or hi < lo:
            parser.error(f"{flag} needs {floor} <= MIN <= MAX, got {lo} {hi}")

    import os

    run_dir = Path(args.run_dir)
    run_dir.mkdir(parents=True, exist_ok=True)
    # telemetry rails: events via the logger's env hook, metrics via the
    # registry's explicit sink (mirrors how the supervisor wires hosts)
    os.environ.setdefault(
        "SCALING_TPU_EVENTS_PATH", str(run_dir / "events.jsonl")
    )
    from ..obs import get_registry

    get_registry().configure(metrics_path=str(run_dir / "metrics.jsonl"))

    if args.checkpoint:
        from ..models.transformer.inference import TransformerInferenceModule

        inf = TransformerInferenceModule.from_checkpoint(args.checkpoint)
        vocab = inf.architecture.vocab_size
    else:
        inf = build_toy_inference(
            hidden=args.hidden, layers=args.layers, vocab=args.vocab,
            heads=args.heads,
        )
        vocab = args.vocab

    cap = args.max_blocks_per_seq * args.block_size
    longest = (args.prompt_len[1] + args.shared_prefix_len
               + args.output_len[1])
    if longest > cap:
        print(
            f"error: prompt+output can reach {longest} tokens but the "
            f"block table holds {cap}; raise --max-blocks-per-seq or "
            "--block-size", file=sys.stderr,
        )
        return 2
    if args.shared_prefix_len > 0 and args.prefix_families < 1:
        parser.error("--prefix-families must be >= 1")

    engine = ServeEngine(inf, EngineConfig(
        num_slots=args.num_slots, block_size=args.block_size,
        num_blocks=args.num_blocks,
        max_blocks_per_seq=args.max_blocks_per_seq,
        token_budget=args.token_budget, kv_dtype=args.kv_dtype,
        prefill_chunk=args.prefill_chunk or None,
        paged_kernel=args.paged_kernel,
        fused_tick=not args.no_fused_tick,
        enable_prefix_cache=not args.no_prefix_cache,
        spec_k=args.spec_k,
        default_deadline_ms=args.deadline_ms,
        default_ttft_deadline_ms=args.ttft_deadline_ms,
        shed_high_watermark=args.shed_high_watermark,
        shed_low_watermark=args.shed_low_watermark,
        max_waiting=args.max_waiting,
    ))
    # SIGTERM -> graceful drain: stop admitting, finish in-flight, flush
    # telemetry, exit 0 with a parseable run dir
    install_drain_handler(engine)
    journal_path = run_dir / "journal.jsonl"
    replay = None
    if not args.no_journal:
        from .journal import open_journal

        # --resume folds the crashed run's journal first; a fresh run
        # truncates any stale one from a previous drill in this dir
        journal, replay = open_journal(journal_path, args.resume)
        engine.attach_journal(journal)
    elif args.resume:
        from .journal import replay_journal

        replay = replay_journal(journal_path)
    workload = sample_workload(
        args.requests, args.rate, tuple(args.prompt_len),
        tuple(args.output_len), vocab, args.seed,
        shared_prefix_len=args.shared_prefix_len,
        prefix_families=args.prefix_families,
    )
    if args.warmup > 0:
        # compile the tick programs off the clock: the first mixed-step
        # call jit-compiles for seconds, and an open-loop workload that
        # arrives during it measures the compiler, not the engine
        engine.warmup_mode = True
        for _ in range(args.warmup):
            engine.submit([1], 2)
        engine.run_until_done()
        engine.warmup_mode = False
        engine.finished.clear()
    extra_stats = None
    carry = None
    if replay is not None and replay.offered_count:
        from ..logging import logger

        # crash-replay: re-enqueue every request without a terminal
        # status under its ORIGINAL id (the sampler keys fold the id,
        # so the regenerated tokens are the ones the crashed run would
        # have emitted), then serve the workload tail the crashed run
        # never reached. force=True: recovery work is never shed.
        incomplete = replay.incomplete
        engine._next_req_id = replay.next_req_id
        for rec in incomplete:
            engine.submit(
                rec["prompt"], rec["max_new_tokens"],
                eos_token_id=rec.get("eos_token_id"),
                temperature=rec.get("temperature", 0.0),
                top_k=rec.get("top_k"), top_p=rec.get("top_p"),
                deadline_ms=rec.get("deadline_ms"),
                ttft_deadline_ms=rec.get("ttft_deadline_ms"),
                req_id=int(rec["req"]), force=True,
            )
        # skip every workload item the crashed run(s) CONSUMED — both
        # admitted submissions and overload sheds (a shed offer was
        # answered with Backpressure; re-offering it would double-serve
        # the tail behind it)
        done = replay.offered_count
        workload = sorted(workload, key=lambda w: w[0])[done:]
        if workload:
            base = workload[0][0]  # the tail arrives from t=0 again
            workload = [(a - base, p, o) for a, p, o in workload]
        extra_stats = {
            "resumed": True,
            "replayed_incomplete": len(incomplete),
            "replayed_completed": len(replay.completed),
        }
        # the crashed run(s)' terminal tallies fold into this run's
        # summary so the gates judge the whole run dir
        carry = {
            "completed": len(replay.completed),
            "timeouts": replay.timeout_count,
            "shed": replay.shed_count,
        }
        logger.log_event(
            "serve-resume", incomplete=len(incomplete),
            completed=len(replay.completed),
            remaining_workload=len(workload),
        )
    stats = run_bench(
        engine, workload, max_wall_s=args.max_wall_s,
        tick_timeout_s=args.tick_timeout_s, extra_stats=extra_stats,
        carry=carry,
    )

    print("== serve bench ==")
    print(f"  requests={stats['requests']} wall={stats['wall_s']:.3f}s "
          f"ticks={stats['ticks']} preemptions={stats['preemptions']} "
          f"prefill_compiles={stats['prefill_compiles']}")
    if (stats["requests_shed"] or stats["requests_timeout"]
            or stats["drained"]):
        print(f"  resilience: shed={stats['requests_shed']} "
              f"(rate {stats['shed_rate']:.1%}) "
              f"timeouts={stats['requests_timeout']} "
              f"drained={stats['drained']} "
              f"unsubmitted={stats['unsubmitted']}")
    print(f"  hot path: paged_kernel={args.paged_kernel} "
          f"prefill_chunk={args.prefill_chunk or 'off'} "
          f"fused_tick={not args.no_fused_tick} "
          f"max_concurrent_prefills={stats['max_concurrent_prefills']}")
    if stats["prefix_hit_tokens"]:
        print(f"  prefix cache: {stats['prefix_hit_tokens']} tokens hit, "
              f"{stats['prefilled_tokens']} prefilled "
              f"({stats['prompt_tokens']} prompt tokens submitted; "
              f"hit rate {stats['prefix_hit_rate']:.1%})")
    if stats["spec_accept_rate"] is not None:
        print(f"  speculation: k={args.spec_k} accepted "
              f"{stats['spec_accepted_tokens']}/"
              f"{stats['spec_drafted_tokens']} drafts "
              f"(accept rate {stats['spec_accept_rate']:.1%})")
    print(f"  output tokens/s: {stats['tokens_per_s']:.1f} "
          f"({stats['output_tokens']} tokens)")
    if stats["ttft_p50_s"] is not None:
        print(f"  ttft: p50={stats['ttft_p50_s']:.4f}s "
              f"p99={stats['ttft_p99_s']:.4f}s")
    if stats["itl_p50_s"] is not None:
        print(f"  itl:  p50={stats['itl_p50_s']:.4f}s "
              f"p99={stats['itl_p99_s']:.4f}s")
    print(f"  run dir: {run_dir} (analyze: python -m scaling_tpu.obs "
          f"report {run_dir})")

    if args.json:
        Path(args.json).write_text(json.dumps(stats, indent=1) + "\n")

    failures = []
    if (args.assert_serve_throughput is not None
            and stats["tokens_per_s"] < args.assert_serve_throughput):
        failures.append(
            f"assert-serve-throughput: {stats['tokens_per_s']:.1f} tokens/s "
            f"< floor {args.assert_serve_throughput:.1f}"
        )
    if args.assert_ttft is not None and (
            stats["ttft_p99_s"] is None
            or stats["ttft_p99_s"] > args.assert_ttft):
        failures.append(
            f"assert-ttft: p99 TTFT {stats['ttft_p99_s']}s "
            f"> ceiling {args.assert_ttft}s"
        )
    if args.assert_serve_throughput is not None or args.assert_ttft is not None:
        print("== gates ==")
        for f in failures:
            print(f"  FAIL {f}")
        if not failures:
            print("  PASS")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
