"""``python -m scaling_tpu.serve bench`` — serving benchmark entrypoint."""

from __future__ import annotations

import sys


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print("usage: python -m scaling_tpu.serve bench [options]\n"
              "       (see `python -m scaling_tpu.serve bench --help`)")
        return 0 if argv else 2
    command, rest = argv[0], argv[1:]
    if command != "bench":
        print(f"unknown command {command!r}; have: bench", file=sys.stderr)
        return 2
    from .bench import main as bench_main

    return bench_main(rest)


if __name__ == "__main__":
    sys.exit(main())
