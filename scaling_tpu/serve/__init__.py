"""Continuous-batching inference serving (docs/SERVING.md).

The "millions of users" half of the north star: turns the single-request
``TransformerInferenceModule`` generate loop into a serving engine —

- :mod:`.kvcache` — block-paged KV cache: fixed-size blocks allocated
  from one device-resident pool per layer, addressed through per-sequence
  block tables (PagedAttention, SOSP '23); optional int8-quantized values.
- :mod:`.scheduler` — continuous batching (Orca, OSDI '22): admission
  from a request queue, per-tick prefill/decode mixing under a token
  budget, preemption on pool exhaustion, completed-slot recycling.
- :mod:`.engine` — the jitted device programs: ONE fused mixed program
  per tick covering the whole slot set — prefill-chunk rows and
  decode rows (each carrying up to ``spec_k`` self-drafted speculative
  candidates, accepted pathwise-exactly at any temperature) tagged by
  traced lengths (paged attention streamed through the Pallas kernel
  in ``nn/paged_attention.py`` by default, XLA gather as the
  fallback); legacy separate decode/chunk programs behind
  ``fused_tick=False``, bucketed whole-prompt prefill in
  ``prefill_chunk=None`` mode; per-request temperature/top-k/top-p
  sampling as traced per-row arrays (no per-request recompiles;
  signatures pinned in the ``serve_decode`` HLO audit section). The
  scheduler's prefix trie (``PrefixCache``) maps shared-prompt blocks
  straight into new sequences' tables, so a prompt family pays its
  prefill once (docs/SERVING.md "Raw speed").
- :mod:`.bench` / ``python -m scaling_tpu.serve bench`` — Poisson
  load generator reporting tokens/s, TTFT/ITL percentiles, prefix-hit
  and speculative-accept rates through ``obs.get_registry()``, gated
  by ``--assert-serve-throughput`` / ``--assert-ttft`` (mirroring the
  training MFU gates; ``--assert-spec-accept-rate`` /
  ``--assert-max-shed-rate`` / ``--assert-max-serve-timeouts`` ride
  the analyzer).
- :mod:`.router` — the FLEET (docs/SERVING.md "The fleet"): N
  data-parallel engine replicas behind ``FleetRouter`` — least-loaded
  + hash-based prefix-affinity dispatch, retry-elsewhere on
  ``Backpressure``, SIGTERM drain fan-out, per-replica journal
  namespaces (``journal_path``) with token-exact replica-kill
  journal-resume; ``serve bench --replicas N [--mp K]`` drives the
  fleet through one Poisson stream (mp>1 shards every KV pool over
  the model axis — ``kvcache.init_pools`` — so big models fit and
  the mixed tick runs SPMD; ``tune --serve`` plans the (mp, replicas,
  block_size, token_budget) split and ``--config`` runs its pick).
- resilience (docs/SERVING.md "Resilience"): per-request TTFT/total
  deadlines cancelled at tick boundaries (terminal status
  ``timeout``), watermark overload shedding with hysteresis
  (``scheduler.Backpressure`` — the fleet router's signal), SIGTERM
  graceful drain (``engine.install_drain_handler``), the
  :mod:`.journal` crash-replay request journal behind
  ``serve bench --resume`` / ``--restarts`` (token-exact replay via
  the (request, position) sampler keys), and ``serve.tick`` /
  ``serve.admit`` / ``serve.journal`` / ``serve.pool`` fault points
  under ``SCALING_TPU_FAULTS``.

jax-free at import time (the engine imports it lazily): the scheduler and
request/bench plumbing must stay importable from the analyzer and tests
without paying backend init.
"""

from .journal import (
    JournalReplay,
    RequestJournal,
    journal_path,
    open_journal,
    replay_journal,
)
from .router import FleetRouter, ReplicaHandle, ReplicaStats
from .scheduler import (
    Backpressure,
    BlockAllocator,
    ContinuousBatchingScheduler,
    PrefixCache,
    Request,
    SchedulerConfig,
    Sequence,
    SequenceState,
    ngram_propose,
)

__all__ = [
    "Backpressure",
    "BlockAllocator",
    "ContinuousBatchingScheduler",
    "FleetRouter",
    "JournalReplay",
    "PrefixCache",
    "ReplicaHandle",
    "ReplicaStats",
    "Request",
    "RequestJournal",
    "SchedulerConfig",
    "Sequence",
    "SequenceState",
    "journal_path",
    "ngram_propose",
    "open_journal",
    "replay_journal",
]
