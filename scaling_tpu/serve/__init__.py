"""Continuous-batching inference serving (docs/SERVING.md).

The "millions of users" half of the north star: turns the single-request
``TransformerInferenceModule`` generate loop into a serving engine —

- :mod:`.kvcache` — block-paged KV cache: fixed-size blocks allocated
  from one device-resident pool per layer, addressed through per-sequence
  block tables (PagedAttention, SOSP '23); optional int8-quantized values.
- :mod:`.scheduler` — continuous batching (Orca, OSDI '22): admission
  from a request queue, per-tick prefill/decode mixing under a token
  budget, preemption on pool exhaustion, completed-slot recycling.
- :mod:`.engine` — the jitted device programs: ONE decode program for
  the whole slot set (paged attention streamed through the Pallas
  kernel in ``nn/paged_attention.py`` by default, XLA gather as the
  fallback), ONE chunked-prefill program per chunk size (Sarathi-style
  — several prompts stream per tick) or one bucketed whole-prompt
  prefill per length bucket in legacy mode, per-request
  temperature/top-k sampling as traced per-row arrays (no per-request
  recompiles; signatures pinned in the ``serve_decode`` HLO audit
  section).
- :mod:`.bench` / ``python -m scaling_tpu.serve bench`` — Poisson
  load generator reporting tokens/s and TTFT/ITL percentiles through
  ``obs.get_registry()``, gated by ``--assert-serve-throughput`` /
  ``--assert-ttft`` (mirroring the training MFU gates).

jax-free at import time (the engine imports it lazily): the scheduler and
request/bench plumbing must stay importable from the analyzer and tests
without paying backend init.
"""

from .scheduler import (
    BlockAllocator,
    ContinuousBatchingScheduler,
    Request,
    SchedulerConfig,
    Sequence,
    SequenceState,
)

__all__ = [
    "BlockAllocator",
    "ContinuousBatchingScheduler",
    "Request",
    "SchedulerConfig",
    "Sequence",
    "SequenceState",
]
