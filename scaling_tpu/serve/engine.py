"""The serving engine: jitted prefill/decode programs + the tick loop.

Prefill/decode split (Orca; Sarathi): per tick the scheduler mixes new
prompts (prefill — compute-bound, runs through the SAME ``prefill_forward``
the dense-cache generate path uses, so the flash kernel stays active) with
one decode token for every running sequence (memory-bound, one jitted
program over the WHOLE slot set).

No per-request recompiles, by construction:

- the decode program compiles ONCE per engine: its shapes are the fixed
  ``(num_slots, max_blocks_per_seq)`` batch — sequence raggedness lives in
  block tables and context lengths, never in shapes;
- prefill compiles once per PROMPT-LENGTH BUCKET (power-of-two ladder);
  prompts are right-padded to their bucket, pads sit in their own
  attention segment and write KV to the trash block.

Both signatures are pinned in the ``serve_decode`` HLO-audit section
(analysis/goldens/serve_decode.json): a scheduler shape-bucketing change
that would trigger a recompile storm on the chip shows up as golden
drift in CI instead.

Greedy (argmax) sampling: continuous batching re-batches requests across
ticks, and greedy decode is what makes a preempted-and-resumed sequence
regenerate token-for-token (scheduler.py).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional

from .. import obs
from ..logging import logger
from .kvcache import PagedKVPools, init_pools, write_prompt_kv
from .scheduler import (
    ContinuousBatchingScheduler,
    Request,
    SchedulerConfig,
    Sequence,
    Tick,
)

MIN_PREFILL_BUCKET = 8


def prefill_bucket(prompt_len: int) -> int:
    """Power-of-two length ladder; every prompt length in a bucket shares
    one compiled prefill program."""
    b = MIN_PREFILL_BUCKET
    while b < prompt_len:
        b *= 2
    return b


@dataclasses.dataclass
class EngineConfig:
    num_slots: int = 8
    block_size: int = 16
    num_blocks: int = 128
    max_blocks_per_seq: int = 16
    token_budget: int = 512
    kv_dtype: str = "native"  # 'native' | 'int8'
    flush_interval: int = 50  # registry flush cadence (ticks)

    def scheduler_config(self) -> SchedulerConfig:
        return SchedulerConfig(
            num_slots=self.num_slots, block_size=self.block_size,
            num_blocks=self.num_blocks,
            max_blocks_per_seq=self.max_blocks_per_seq,
            token_budget=self.token_budget,
        )


class ServeEngine:
    """Continuous-batching engine over a ``TransformerInferenceModule``."""

    def __init__(self, inference_module, config: Optional[EngineConfig] = None):
        import jax

        self.inf = inference_module
        self.config = config or EngineConfig()
        self.scheduler = ContinuousBatchingScheduler(
            self.config.scheduler_config()
        )
        self.pools: PagedKVPools = init_pools(
            inference_module, self.config.num_blocks, self.config.block_size,
            kv_dtype=self.config.kv_dtype,
        )
        import numpy as np

        self._np = np
        self._jax = jax
        n, m = self.config.num_slots, self.config.max_blocks_per_seq
        self._tables = np.zeros((n, m), np.int32)
        self._ctx = np.zeros((n,), np.int32)
        self._tok = np.zeros((n,), np.int32)
        self._decode_fn = None
        self._prefill_fns: Dict[int, object] = {}
        self.tick_index = 0
        self.finished: List[Sequence] = []
        self._next_req_id = 0
        self._reg = obs.get_registry()

    # ------------------------------------------------------------- intake
    def submit(self, prompt: List[int], max_new_tokens: int,
               arrival_s: Optional[float] = None,
               eos_token_id: Optional[int] = None) -> Sequence:
        req = Request(
            req_id=self._next_req_id, prompt=list(prompt),
            max_new_tokens=max_new_tokens,
            arrival_s=time.monotonic() if arrival_s is None else arrival_s,
            eos_token_id=eos_token_id,
        )
        self._next_req_id += 1
        self._reg.counter("serve_requests_admitted_total").inc()
        return self.scheduler.add_request(req)

    # --------------------------------------------------- device programs
    def _pool_state(self):
        p = self.pools
        return (p.pool_k, p.pool_v, p.scale_k, p.scale_v)

    def _views_from_state(self, state, block_table, context_len):
        pool_k, pool_v, scale_k, scale_v = state
        from ..nn.attention import PagedKVCacheView

        return [
            PagedKVCacheView(
                pool_k=pool_k[i], pool_v=pool_v[i],
                block_table=block_table, context_len=context_len,
                scale_k=None if scale_k is None else scale_k[i],
                scale_v=None if scale_v is None else scale_v[i],
            )
            for i in range(len(pool_k))
        ]

    def _absorb(self, views) -> None:
        self.pools.absorb_views(views)

    def _build_prefill_fn(self, bucket: int):
        jnp = self._jax.numpy
        block_size = self.config.block_size

        def prefill(params, state, tokens, block_row, prompt_len):
            b, L = tokens.shape  # (1, bucket)
            pos = jnp.broadcast_to(jnp.arange(L, dtype=jnp.int32)[None], (b, L))
            # bucket padding sits in its own segment: content never
            # attends to it, it never attends to content
            seg = jnp.where(pos < prompt_len, 0, 1).astype(jnp.int32)
            logits, kvs = self.inf.prefill_forward(
                params, tokens, pos, seg, last_index=prompt_len - 1
            )
            views = self._views_from_state(
                state, block_row[None, :], jnp.zeros((1,), jnp.int32)
            )
            new_views = [
                write_prompt_kv(view, k, v, block_row, prompt_len, block_size)
                for view, (k, v) in zip(views, kvs)
            ]
            next_tok = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
            return next_tok, new_views

        # same lifecycle as decode: the old pool state dies with the call
        # (absorb_views takes the returned arrays), so donation lets XLA
        # scatter in place instead of copying every layer's pool per
        # admitted prompt. CPU can't donate (every call would warn).
        donate = (1,) if self._jax.default_backend() != "cpu" else ()
        return self._jax.jit(prefill, donate_argnums=donate)

    def _build_decode_fn(self):
        jnp = self._jax.numpy

        def decode(params, state, tables, ctx_lens, tokens):
            b = tokens.shape[0]
            batch = self.inf._make_batch(tokens[:, None], ctx_lens[:, None])
            views = self._views_from_state(state, tables, ctx_lens)
            logits, new_views = self.inf._run_layers(params, batch, views, None)
            next_tok = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
            return next_tok, new_views

        # the pool state dies with each call — donating it lets XLA run
        # the scatter updates in place instead of copying every pool
        # block per token. CPU can't donate (every call would warn).
        donate = (1,) if self._jax.default_backend() != "cpu" else ()
        return self._jax.jit(decode, donate_argnums=donate)

    # ------------------------------------------------------------- ticking
    def _reset_rows(self, slots: List[int]) -> None:
        for s in slots:
            self._tables[s] = 0
            self._ctx[s] = 0
            self._tok[s] = 0

    def _run_prefill(self, seq: Sequence) -> None:
        np = self._np
        prompt = seq.resume_prompt
        bucket = prefill_bucket(len(prompt))
        if bucket not in self._prefill_fns:
            self._prefill_fns[bucket] = self._build_prefill_fn(bucket)
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :len(prompt)] = prompt
        block_row = np.zeros((self.config.max_blocks_per_seq,), np.int32)
        block_row[:len(seq.blocks)] = seq.blocks
        with obs.span("serve.prefill", step=self.tick_index,
                      tokens=len(prompt)):
            next_tok, new_views = self._prefill_fns[bucket](
                self.inf.params, self._pool_state(),
                self._jax.numpy.asarray(tokens),
                self._jax.numpy.asarray(block_row),
                self._jax.numpy.int32(len(prompt)),
            )
            tok = int(np.asarray(next_tok)[0])
        self._absorb(new_views)
        now = time.monotonic()
        slot = seq.slot
        self._tables[slot] = block_row
        self._ctx[slot] = len(prompt)
        self._tok[slot] = tok
        seq.num_cached = len(prompt)
        self._emit_token(seq, tok, now)
        self._reg.counter("serve_prefill_tokens_total").inc(len(prompt))

    def _run_decode(self, decodes: List[Sequence]) -> None:
        np = self._np
        if self._decode_fn is None:
            self._decode_fn = self._build_decode_fn()
        for seq in decodes:
            # the scheduler may have grown this row's block list since the
            # table row was last written (incremental allocation)
            row = self._tables[seq.slot]
            row[:] = 0
            row[:len(seq.blocks)] = seq.blocks
        with obs.span("serve.decode", step=self.tick_index,
                      batch=len(decodes)):
            next_tok, new_views = self._decode_fn(
                self.inf.params, self._pool_state(),
                self._jax.numpy.asarray(self._tables),
                self._jax.numpy.asarray(self._ctx),
                self._jax.numpy.asarray(self._tok),
            )
            toks = np.asarray(next_tok)
        self._absorb(new_views)
        now = time.monotonic()
        for seq in decodes:
            slot = seq.slot
            self._ctx[slot] += 1
            seq.num_cached += 1
            tok = int(toks[slot])
            self._tok[slot] = tok
            self._emit_token(seq, tok, now)

    def _emit_token(self, seq: Sequence, tok: int, now: float) -> None:
        seq.generated.append(tok)
        if seq.first_token_s is None:
            seq.first_token_s = now
            self._reg.histogram("serve_ttft_seconds").observe(
                now - seq.request.arrival_s
            )
        elif seq.token_stamps:
            self._reg.histogram("serve_itl_seconds").observe(
                now - seq.token_stamps[-1]
            )
        seq.token_stamps.append(now)
        self._reg.counter("serve_tokens_generated_total").inc()

    def _finish(self, seq: Sequence, now: float) -> None:
        self.scheduler.finish(seq)  # row reset rides the freed-slot drain
        seq.finished_s = now
        self.finished.append(seq)
        self._reg.counter("serve_requests_completed_total").inc()
        itl = [
            b - a for a, b in zip(seq.token_stamps, seq.token_stamps[1:])
        ]
        logger.log_event(
            "serve-request", _level="debug",
            req=seq.request.req_id,
            prompt_tokens=len(seq.request.prompt),
            output_tokens=len(seq.generated),
            ttft_s=round(seq.first_token_s - seq.request.arrival_s, 6),
            e2e_s=round(now - seq.request.arrival_s, 6),
            itl_mean_s=round(sum(itl) / len(itl), 6) if itl else 0.0,
            preemptions=seq.preemptions,
        )

    def tick(self) -> Tick:
        """One engine step: schedule, prefill admissions, decode the
        running set, retire completions."""
        t = self.scheduler.schedule()
        if t.preempted:
            self._reg.counter("serve_preemptions_total").inc(len(t.preempted))
        self._reset_rows(self.scheduler.drain_freed_slots())
        for seq in t.prefills:
            self._run_prefill(seq)
        if t.decodes:
            self._run_decode(t.decodes)
        now = time.monotonic()
        for seq in list(t.prefills) + list(t.decodes):
            if seq.done and seq.slot is not None:
                self._finish(seq, now)
        self._reset_rows(self.scheduler.drain_freed_slots())
        for name, value in self.scheduler.gauges().items():
            self._reg.gauge(name).set(value)
        self.tick_index += 1
        if self.tick_index % self.config.flush_interval == 0:
            self._reg.flush_step(self.tick_index)
        return t

    def run_until_done(self, max_ticks: int = 100_000) -> List[Sequence]:
        """Drain every submitted request; returns finished sequences in
        completion order."""
        ticks = 0
        while self.scheduler.has_work:
            self.tick()
            ticks += 1
            if ticks > max_ticks:
                raise RuntimeError(
                    f"engine made no progress draining the queue within "
                    f"{max_ticks} ticks — scheduler livelock?"
                )
        self._reg.flush_step(self.tick_index)
        return self.finished
