"""The serving engine: jitted prefill/decode programs + the tick loop.

Prefill/decode split (Orca; Sarathi): per tick the scheduler mixes
prompt prefill work with one decode token for every running sequence
(memory-bound, one jitted program over the WHOLE slot set). Prefill runs
in one of two modes:

- **chunked** (default; Sarathi-style): prompts stream into the paged
  pool in fixed-size chunks through ONE compiled chunk program per chunk
  size — each chunk scatters its KV at the sequence's next slots and
  attends over the pool (the same paged-attention path decode uses), so
  several prompts prefill in the same tick and a long prompt can never
  monopolize it;
- **whole-prompt** (``prefill_chunk=None``): one prompt per tick through
  the SAME ``prefill_forward`` the dense-cache generate path uses (the
  flash kernel stays active), compiled once per pow2 prompt-length
  bucket.

Decode attention streams KV blocks through the Pallas paged-decode
kernel by default (``paged_kernel='pallas'``, nn/paged_attention.py —
interpreted off-TPU so the CPU mesh runs the real kernel body); the
XLA gather path stays config-selectable (``paged_kernel='xla'``).

No per-request recompiles, by construction:

- the decode program compiles ONCE per engine: its shapes are the fixed
  ``(num_slots, max_blocks_per_seq)`` batch — sequence raggedness lives
  in block tables and context lengths, never in shapes;
- chunk programs compile once per CHUNK SIZE (the final ragged chunk of
  every prompt pads to the chunk shape; pads write KV to the trash block
  and are masked — ``PagedKVCacheView.new_len``); bucketed prefill
  compiles once per pow2 prompt-length bucket.

All signatures are pinned in the ``serve_decode`` HLO-audit section
(analysis/goldens/serve_decode.json): a scheduler shape-bucketing or
kernel change that would trigger a recompile storm on the chip shows up
as golden drift in CI instead.

Sampling is per-request (``inference.sample_rows``): temperature/top-k
ride the jitted programs as traced per-row arrays, greedy is the
``temperature=0`` default. Sample keys derive from (request id, token
position) — ``inference.request_sample_key`` — so a preempted-and-
resumed sequence redraws the SAME tokens and recompute-style preemption
(scheduler.py) stays invisible in the output even for sampled rows.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional

from .. import obs
from ..logging import logger
from .kvcache import PagedKVPools, build_layer_views, init_pools, write_prompt_kv
from .scheduler import (
    ContinuousBatchingScheduler,
    Request,
    SchedulerConfig,
    Sequence,
    Tick,
)

MIN_PREFILL_BUCKET = 8


def prefill_bucket(prompt_len: int) -> int:
    """Power-of-two length ladder; every prompt length in a bucket shares
    one compiled prefill program (whole-prompt mode only)."""
    b = MIN_PREFILL_BUCKET
    while b < prompt_len:
        b *= 2
    return b


@dataclasses.dataclass
class EngineConfig:
    num_slots: int = 8
    block_size: int = 16
    num_blocks: int = 128
    max_blocks_per_seq: int = 16
    token_budget: int = 512
    kv_dtype: str = "native"  # 'native' | 'int8'
    # Sarathi-style chunked prefill (tokens per chunk); None = legacy
    # whole-prompt prefill through the pow2 bucket ladder
    prefill_chunk: Optional[int] = 32
    # paged-decode attention back-end: 'pallas' streams KV blocks through
    # the flash-style kernel (nn/paged_attention.py; interpreted off-TPU),
    # 'xla' gathers each row's whole block window (the fallback)
    paged_kernel: str = "pallas"
    sample_seed: int = 0  # base key for per-request sampling
    flush_interval: int = 50  # registry flush cadence (ticks)

    def __post_init__(self):
        if self.paged_kernel not in ("pallas", "xla"):
            raise ValueError(
                f"paged_kernel must be 'pallas' or 'xla', "
                f"got {self.paged_kernel!r}"
            )

    def scheduler_config(self) -> SchedulerConfig:
        return SchedulerConfig(
            num_slots=self.num_slots, block_size=self.block_size,
            num_blocks=self.num_blocks,
            max_blocks_per_seq=self.max_blocks_per_seq,
            token_budget=self.token_budget,
            prefill_chunk=self.prefill_chunk,
        )


class ServeEngine:
    """Continuous-batching engine over a ``TransformerInferenceModule``."""

    def __init__(self, inference_module, config: Optional[EngineConfig] = None):
        import jax

        self.inf = inference_module
        self.config = config or EngineConfig()
        self.scheduler = ContinuousBatchingScheduler(
            self.config.scheduler_config()
        )
        self.pools: PagedKVPools = init_pools(
            inference_module, self.config.num_blocks, self.config.block_size,
            kv_dtype=self.config.kv_dtype,
        )
        import numpy as np

        self._np = np
        self._jax = jax
        n, m = self.config.num_slots, self.config.max_blocks_per_seq
        self._tables = np.zeros((n, m), np.int32)
        self._ctx = np.zeros((n,), np.int32)
        self._tok = np.zeros((n,), np.int32)
        # per-slot sampler state (traced per-row arrays in the programs)
        self._temp = np.zeros((n,), np.float32)
        self._topk = np.zeros((n,), np.int32)
        self._reqid = np.zeros((n,), np.int32)
        self._gen = np.zeros((n,), np.int32)
        self._base_key = jax.random.PRNGKey(self.config.sample_seed)
        self._decode_fn = None
        self._prefill_fns: Dict[int, object] = {}  # whole-prompt buckets
        self._chunk_fns: Dict[int, object] = {}  # chunk-size -> program
        self.tick_index = 0
        self.finished: List[Sequence] = []
        self.max_concurrent_prefills = 0
        self._next_req_id = 0
        self._reg = obs.get_registry()

    # ------------------------------------------------------------- intake
    def submit(self, prompt: List[int], max_new_tokens: int,
               arrival_s: Optional[float] = None,
               eos_token_id: Optional[int] = None,
               temperature: float = 0.0,
               top_k: Optional[int] = None) -> Sequence:
        req = Request(
            req_id=self._next_req_id, prompt=list(prompt),
            max_new_tokens=max_new_tokens,
            arrival_s=time.monotonic() if arrival_s is None else arrival_s,
            eos_token_id=eos_token_id,
            temperature=temperature, top_k=top_k,
        )
        self._next_req_id += 1
        self._reg.counter("serve_requests_admitted_total").inc()
        return self.scheduler.add_request(req)

    # --------------------------------------------------- device programs
    def _pool_state(self):
        p = self.pools
        return (p.pool_k, p.pool_v, p.scale_k, p.scale_v)

    def _views_from_state(self, state, block_table, context_len,
                          new_len=None):
        return build_layer_views(state, block_table, context_len, new_len)

    def _absorb(self, views) -> None:
        self.pools.absorb_views(views)

    def _sample_last(self, logits, temps, topks, reqids, gens, base_key):
        """Shared sampling epilogue: per-row keys from (request, position),
        then the per-row temperature/top-k sampler."""
        from ..models.transformer.inference import (
            request_sample_key, sample_rows,
        )

        keys = self._jax.vmap(
            request_sample_key, in_axes=(None, 0, 0)
        )(base_key, reqids, gens)
        return sample_rows(logits, temps, topks, keys)

    def _build_prefill_fn(self, bucket: int):
        jnp = self._jax.numpy
        block_size = self.config.block_size

        def prefill(params, state, tokens, block_row, prompt_len,
                    temp, topk, reqid, gen, base_key):
            b, L = tokens.shape  # (1, bucket)
            pos = jnp.broadcast_to(jnp.arange(L, dtype=jnp.int32)[None], (b, L))
            # bucket padding sits in its own segment: content never
            # attends to it, it never attends to content
            seg = jnp.where(pos < prompt_len, 0, 1).astype(jnp.int32)
            logits, kvs = self.inf.prefill_forward(
                params, tokens, pos, seg, last_index=prompt_len - 1
            )
            views = self._views_from_state(
                state, block_row[None, :], jnp.zeros((1,), jnp.int32)
            )
            new_views = [
                write_prompt_kv(view, k, v, block_row, prompt_len, block_size)
                for view, (k, v) in zip(views, kvs)
            ]
            next_tok = self._sample_last(
                logits[:, -1], temp, topk, reqid, gen, base_key
            )
            return next_tok, new_views

        # same lifecycle as decode: the old pool state dies with the call
        # (absorb_views takes the returned arrays), so donation lets XLA
        # scatter in place instead of copying every layer's pool per
        # admitted prompt. CPU can't donate (every call would warn).
        donate = (1,) if self._jax.default_backend() != "cpu" else ()
        return self._jax.jit(prefill, donate_argnums=donate)

    def _build_chunk_fn(self, chunk: int):
        """ONE compiled program per chunk size: scatter the chunk's KV at
        the sequence's next slots and attend over the pool — the same
        paged path decode uses, so a chunk sees every previous chunk's KV
        without any per-prompt-length shapes. ``new_len`` routes the
        final ragged chunk's padding to the trash block."""
        jnp = self._jax.numpy

        def chunk_prefill(params, state, tokens, block_row, ctx_len, new_len,
                          temp, topk, reqid, gen, base_key):
            b, L = tokens.shape  # (1, chunk)
            pos = ctx_len[:, None] + jnp.arange(L, dtype=jnp.int32)[None, :]
            batch = self.inf._make_batch(tokens, pos)
            views = self._views_from_state(
                state, block_row[None, :], ctx_len, new_len
            )
            logits, new_views = self.inf._run_layers(
                params, batch, views, None,
                paged_kernel=self.config.paged_kernel,
            )
            # the chunk's last REAL position predicts the next token; it
            # only counts when this chunk completes the prompt (host-side
            # decision — mid-prompt samples are discarded)
            last = self._jax.lax.dynamic_slice_in_dim(
                logits, new_len[0] - 1, 1, axis=1
            )[:, 0]
            next_tok = self._sample_last(
                last, temp, topk, reqid, gen, base_key
            )
            return next_tok, new_views

        donate = (1,) if self._jax.default_backend() != "cpu" else ()
        return self._jax.jit(chunk_prefill, donate_argnums=donate)

    def _build_decode_fn(self):
        def decode(params, state, tables, ctx_lens, tokens,
                   temps, topks, reqids, gens, base_key):
            batch = self.inf._make_batch(tokens[:, None], ctx_lens[:, None])
            views = self._views_from_state(state, tables, ctx_lens)
            logits, new_views = self.inf._run_layers(
                params, batch, views, None,
                paged_kernel=self.config.paged_kernel,
            )
            next_tok = self._sample_last(
                logits[:, -1], temps, topks, reqids, gens, base_key
            )
            return next_tok, new_views

        # the pool state dies with each call — donating it lets XLA run
        # the scatter updates in place instead of copying every pool
        # block per token. CPU can't donate (every call would warn).
        donate = (1,) if self._jax.default_backend() != "cpu" else ()
        return self._jax.jit(decode, donate_argnums=donate)

    # ------------------------------------------------------------- ticking
    def _reset_rows(self, slots: List[int]) -> None:
        for s in slots:
            self._tables[s] = 0
            self._ctx[s] = 0
            self._tok[s] = 0
            self._temp[s] = 0.0
            self._topk[s] = 0
            self._reqid[s] = 0
            self._gen[s] = 0

    def _admit_slot(self, seq: Sequence) -> None:
        """Per-slot sampler state for a newly-admitted sequence."""
        slot = seq.slot
        self._temp[slot] = seq.request.temperature
        self._topk[slot] = seq.request.top_k or 0
        self._reqid[slot] = seq.request.req_id

    def _scalar_sample_args(self, seq: Sequence):
        np = self._np
        return (
            np.asarray([seq.request.temperature], np.float32),
            np.asarray([seq.request.top_k or 0], np.int32),
            np.asarray([seq.request.req_id], np.int32),
            np.asarray([len(seq.generated)], np.int32),
        )

    def _run_prefill(self, seq: Sequence) -> None:
        """Whole-prompt prefill (legacy mode): one pow2-bucketed program
        pass over the entire resume prompt."""
        np = self._np
        prompt = seq.resume_prompt
        bucket = prefill_bucket(len(prompt))
        if bucket not in self._prefill_fns:
            self._prefill_fns[bucket] = self._build_prefill_fn(bucket)
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :len(prompt)] = prompt
        block_row = np.zeros((self.config.max_blocks_per_seq,), np.int32)
        block_row[:len(seq.blocks)] = seq.blocks
        self._admit_slot(seq)
        with obs.span("serve.prefill", step=self.tick_index,
                      tokens=len(prompt)):
            next_tok, new_views = self._prefill_fns[bucket](
                self.inf.params, self._pool_state(),
                self._jax.numpy.asarray(tokens),
                self._jax.numpy.asarray(block_row),
                self._jax.numpy.int32(len(prompt)),
                *self._scalar_sample_args(seq), self._base_key,
            )
            tok = int(np.asarray(next_tok)[0])
        self._absorb(new_views)
        now = time.monotonic()
        slot = seq.slot
        self._tables[slot] = block_row
        self._ctx[slot] = len(prompt)
        self._tok[slot] = tok
        seq.num_cached = len(prompt)
        self._emit_token(seq, tok, now)
        self._reg.counter("serve_prefill_tokens_total").inc(len(prompt))

    def _run_prefill_chunk(self, seq: Sequence) -> None:
        """One fixed-size chunk of ``seq``'s prompt: scatter its KV into
        the pool (pads to trash) and, when it completes the prompt, emit
        the first token."""
        np = self._np
        chunk = self.config.prefill_chunk
        if chunk not in self._chunk_fns:
            self._chunk_fns[chunk] = self._build_chunk_fn(chunk)
        prompt = seq.resume_prompt
        start = seq.num_cached
        n_real = min(chunk, len(prompt) - start)
        assert n_real > 0, "chunk scheduled for a fully-prefilled sequence"
        tokens = np.zeros((1, chunk), np.int32)
        tokens[0, :n_real] = prompt[start:start + n_real]
        block_row = np.zeros((self.config.max_blocks_per_seq,), np.int32)
        block_row[:len(seq.blocks)] = seq.blocks
        if start == 0:
            self._admit_slot(seq)
        finishing = start + n_real == len(prompt)
        with obs.span("serve.prefill_chunk", step=self.tick_index,
                      tokens=n_real, start=start):
            next_tok, new_views = self._chunk_fns[chunk](
                self.inf.params, self._pool_state(),
                self._jax.numpy.asarray(tokens),
                self._jax.numpy.asarray(block_row),
                self._jax.numpy.asarray([start], np.int32),
                self._jax.numpy.asarray([n_real], np.int32),
                *self._scalar_sample_args(seq), self._base_key,
            )
            tok = int(np.asarray(next_tok)[0])
        self._absorb(new_views)
        slot = seq.slot
        self._tables[slot] = block_row
        self._ctx[slot] = start + n_real
        seq.num_cached = start + n_real
        self._reg.counter("serve_prefill_tokens_total").inc(n_real)
        if finishing:
            self._tok[slot] = tok
            self._emit_token(seq, tok, time.monotonic())

    def _run_decode(self, decodes: List[Sequence]) -> None:
        np = self._np
        if self._decode_fn is None:
            self._decode_fn = self._build_decode_fn()
        active = np.zeros((self.config.num_slots,), bool)
        for seq in decodes:
            # the scheduler may have grown this row's block list since the
            # table row was last written (incremental allocation)
            row = self._tables[seq.slot]
            row[:] = 0
            row[:len(seq.blocks)] = seq.blocks
            self._gen[seq.slot] = len(seq.generated)
            active[seq.slot] = True
        # rows not decoding this tick (empty, or mid-prefill under
        # chunked prefill) run against an all-trash table with ctx 0:
        # their device-side writes can never land in blocks a prefilling
        # sequence is about to fill
        tables = np.where(active[:, None], self._tables, 0)
        ctx = np.where(active, self._ctx, 0)
        with obs.span("serve.decode", step=self.tick_index,
                      batch=len(decodes)):
            next_tok, new_views = self._decode_fn(
                self.inf.params, self._pool_state(),
                self._jax.numpy.asarray(tables),
                self._jax.numpy.asarray(ctx),
                self._jax.numpy.asarray(self._tok),
                self._jax.numpy.asarray(self._temp),
                self._jax.numpy.asarray(self._topk),
                self._jax.numpy.asarray(self._reqid),
                self._jax.numpy.asarray(self._gen),
                self._base_key,
            )
            toks = np.asarray(next_tok)
        self._absorb(new_views)
        now = time.monotonic()
        for seq in decodes:
            slot = seq.slot
            self._ctx[slot] += 1
            seq.num_cached += 1
            tok = int(toks[slot])
            self._tok[slot] = tok
            self._emit_token(seq, tok, now)

    def _emit_token(self, seq: Sequence, tok: int, now: float) -> None:
        seq.generated.append(tok)
        if seq.first_token_s is None:
            seq.first_token_s = now
            self._reg.histogram("serve_ttft_seconds").observe(
                now - seq.request.arrival_s
            )
        elif seq.token_stamps:
            self._reg.histogram("serve_itl_seconds").observe(
                now - seq.token_stamps[-1]
            )
        seq.token_stamps.append(now)
        self._reg.counter("serve_tokens_generated_total").inc()

    def _finish(self, seq: Sequence, now: float) -> None:
        self.scheduler.finish(seq)  # row reset rides the freed-slot drain
        seq.finished_s = now
        self.finished.append(seq)
        self._reg.counter("serve_requests_completed_total").inc()
        itl = [
            b - a for a, b in zip(seq.token_stamps, seq.token_stamps[1:])
        ]
        logger.log_event(
            "serve-request", _level="debug",
            req=seq.request.req_id,
            prompt_tokens=len(seq.request.prompt),
            output_tokens=len(seq.generated),
            ttft_s=round(seq.first_token_s - seq.request.arrival_s, 6),
            e2e_s=round(now - seq.request.arrival_s, 6),
            itl_mean_s=round(sum(itl) / len(itl), 6) if itl else 0.0,
            preemptions=seq.preemptions,
        )

    def tick(self) -> Tick:
        """One engine step: schedule, prefill admissions/chunks, decode
        the running set, retire completions."""
        t = self.scheduler.schedule()
        if t.preempted:
            self._reg.counter("serve_preemptions_total").inc(len(t.preempted))
        self._reset_rows(self.scheduler.drain_freed_slots())
        chunked = self.config.prefill_chunk is not None
        for seq in t.prefills:
            if chunked:
                self._run_prefill_chunk(seq)
            else:
                self._run_prefill(seq)
        if len(t.prefills) > self.max_concurrent_prefills:
            self.max_concurrent_prefills = len(t.prefills)
        if t.decodes:
            self._run_decode(t.decodes)
        now = time.monotonic()
        for seq in list(t.prefills) + list(t.decodes):
            if seq.done and seq.slot is not None:
                self._finish(seq, now)
        self._reset_rows(self.scheduler.drain_freed_slots())
        for name, value in self.scheduler.gauges().items():
            self._reg.gauge(name).set(value)
        self.tick_index += 1
        if self.tick_index % self.config.flush_interval == 0:
            self._reg.flush_step(self.tick_index)
        return t

    @property
    def prefill_program_count(self) -> int:
        """Compiled prefill-side programs: pow2 buckets (whole-prompt
        mode) plus chunk programs (bounded by the chunk-size set)."""
        return len(self._prefill_fns) + len(self._chunk_fns)

    def run_until_done(self, max_ticks: int = 100_000) -> List[Sequence]:
        """Drain every submitted request; returns finished sequences in
        completion order."""
        ticks = 0
        while self.scheduler.has_work:
            self.tick()
            ticks += 1
            if ticks > max_ticks:
                raise RuntimeError(
                    f"engine made no progress draining the queue within "
                    f"{max_ticks} ticks — scheduler livelock?"
                )
        self._reg.flush_step(self.tick_index)
        return self.finished
