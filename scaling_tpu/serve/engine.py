"""The serving engine: the fused per-tick program + the tick loop.

Per tick the scheduler mixes prompt prefill work with decode work for
every running sequence; the engine runs it all as **ONE fused
Sarathi-style mixed program** (default): every slot row is either a
prefill CHUNK (prompts stream into the paged pool in fixed-size chunks)
or a decode row carrying its last token plus up to ``spec_k``
self-drafted speculative candidates — tagged purely by traced per-row
lengths, so a tick with 4 prefilling prompts dispatches 1 executable,
not 5. Two fallback dispatch modes survive behind config:

- ``fused_tick=False``: the PR 10 separate programs — one decode
  program over the whole slot set plus one chunk program call per
  prefilling sequence (parity-pinned against the mixed program);
- ``prefill_chunk=None``: legacy whole-prompt prefill through the SAME
  ``prefill_forward`` the dense-cache generate path uses, compiled once
  per pow2 prompt-length bucket.

Shared-prefix block reuse and speculative acceptance ride the tick
(docs/SERVING.md "Raw speed"): the scheduler's prefix trie maps cached
prompt blocks straight into new sequences' tables (prefill skipped for
the shared prefix; copy-on-write forks applied by ``_apply_cow`` before
programs run), and ``_accept_speculative`` emits the longest sampled
run consistent with the drafts — pathwise-exact at any temperature
because every scored position draws with the (request, position) key
plain decode would use.

Paged attention streams KV blocks through the Pallas paged-decode
kernel by default (``paged_kernel='pallas'``, nn/paged_attention.py —
interpreted off-TPU so the CPU mesh runs the real kernel body); the
XLA gather path stays config-selectable (``paged_kernel='xla'``).

No per-request recompiles, by construction: the mixed program compiles
once per ``(prefill_chunk, spec_k)`` width signature — its shapes are
the fixed ``(num_slots, mixed_width, max_blocks_per_seq)`` batch, and
sequence raggedness (prompt lengths, prefill offsets, draft lengths)
lives in block tables / context lengths / new_lens, never in shapes.
All signatures are pinned in the ``serve_decode`` HLO-audit section
(analysis/goldens/serve_decode.json): a scheduler shape-bucketing or
kernel change that would trigger a recompile storm on the chip shows up
as golden drift in CI instead.

Sampling is per-request (``inference.sample_rows``): temperature /
top-k / top-p ride the jitted programs as traced per-row arrays, greedy
is the ``temperature=0`` default. Sample keys derive from (request id,
token position) — ``inference.request_sample_key`` — so a preempted-
and-resumed sequence redraws the SAME tokens and recompute-style
preemption (scheduler.py) stays invisible in the output even for
sampled rows, including mid-speculation.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional

from .. import obs
from ..logging import logger
from ..resilience.faults import get_fault_plan
from .kvcache import (
    PagedKVPools,
    build_layer_views,
    init_pools,
    serving_mesh,
    write_prompt_kv,
)
from .scheduler import (
    Backpressure,
    ContinuousBatchingScheduler,
    Request,
    SchedulerConfig,
    Sequence,
    Tick,
)

MIN_PREFILL_BUCKET = 8


def prefill_bucket(prompt_len: int) -> int:
    """Power-of-two length ladder; every prompt length in a bucket shares
    one compiled prefill program (whole-prompt mode only)."""
    b = MIN_PREFILL_BUCKET
    while b < prompt_len:
        b *= 2
    return b


@dataclasses.dataclass
class EngineConfig:
    num_slots: int = 8
    block_size: int = 16
    num_blocks: int = 128
    max_blocks_per_seq: int = 16
    token_budget: int = 512
    kv_dtype: str = "native"  # 'native' | 'int8'
    # Sarathi-style chunked prefill (tokens per chunk); None = legacy
    # whole-prompt prefill through the pow2 bucket ladder
    prefill_chunk: Optional[int] = 32
    # paged-decode attention back-end: 'pallas' streams KV blocks through
    # the flash-style kernel (nn/paged_attention.py; interpreted off-TPU),
    # 'xla' gathers each row's whole block window (the fallback)
    paged_kernel: str = "pallas"
    # ONE fused mixed program per tick (Sarathi piggybacking): every
    # row is a decode row (s>=1 with speculative drafts) or a prefill
    # chunk, tagged by traced lengths — a tick with 4 prefilling prompts
    # dispatches 1 program, not 5. Chunked mode only; False falls back
    # to the PR 10 separate decode + per-sequence chunk programs.
    fused_tick: bool = True
    # shared-prefix KV block reuse (RadixAttention-style trie admission;
    # chunked mode only — see SchedulerConfig.prefix_cache)
    enable_prefix_cache: bool = True
    # self-drafting speculative decoding: n-gram drafts scored k-at-once
    # through the mixed program's s>1 rows; 0 = off
    spec_k: int = 0
    sample_seed: int = 0  # base key for per-request sampling
    flush_interval: int = 50  # registry flush cadence (ticks)
    # ---- resilience (docs/SERVING.md "Resilience") ----
    # per-request deadline defaults (milliseconds from arrival; None =
    # unbounded). A request may carry its own; expiry is checked at
    # every tick boundary and retires the request with terminal status
    # 'timeout', recycling its slot and blocks immediately.
    default_deadline_ms: Optional[float] = None
    default_ttft_deadline_ms: Optional[float] = None
    # overload shedding: watermark admission control over pool pressure
    # (with hysteresis) and waiting-queue depth — above the high
    # watermark `submit` returns a structured Backpressure instead of
    # queueing. None disables (the seed behavior).
    shed_high_watermark: Optional[float] = None
    shed_low_watermark: Optional[float] = None
    max_waiting: Optional[int] = None
    # fleet identity (docs/SERVING.md "The fleet"): set by the router /
    # fleet bench so this replica's metrics carry a ``replica`` label,
    # its serve-request events a ``replica`` field, and its journal a
    # per-replica namespace. None = the single-engine deployment (all
    # telemetry names unchanged).
    replica_id: Optional[int] = None

    def __post_init__(self):
        if self.paged_kernel not in ("pallas", "xla"):
            raise ValueError(
                f"paged_kernel must be 'pallas' or 'xla', "
                f"got {self.paged_kernel!r}"
            )
        if self.spec_k > 0 and (self.prefill_chunk is None
                                or not self.fused_tick):
            raise ValueError(
                "spec_k > 0 needs chunked prefill AND the fused mixed "
                "program (drafts are scored through its s>1 rows)"
            )

    @property
    def fused(self) -> bool:
        """The mixed program replaces decode + chunk dispatch (chunked
        mode only — whole-prompt mode keeps its bucket ladder)."""
        return self.fused_tick and self.prefill_chunk is not None

    @property
    def mixed_width(self) -> int:
        """The mixed program's per-row token width: chunk rows need
        ``prefill_chunk`` slots, speculative decode rows ``spec_k + 1``
        (last accepted token + k drafts). One program per (chunk, k)
        signature — the recompile key the serve_decode golden pins."""
        return max(self.prefill_chunk or 1, self.spec_k + 1)

    @property
    def sample_width(self) -> int:
        """Positions per row the mixed program actually SAMPLES: a
        decode row reads its last token's sample plus one per draft
        (``spec_k + 1`` at most), a finishing chunk row exactly one.
        The program gathers this window of trunk activations per row
        BEFORE the vocab projection, so the lm_head prices
        ``sample_width`` positions instead of all ``mixed_width`` — at
        the default chunk 32 / spec off, a 32x cut in projection work."""
        return min(self.mixed_width, self.spec_k + 1)

    def scheduler_config(self) -> SchedulerConfig:
        return SchedulerConfig(
            num_slots=self.num_slots, block_size=self.block_size,
            num_blocks=self.num_blocks,
            max_blocks_per_seq=self.max_blocks_per_seq,
            token_budget=self.token_budget,
            prefill_chunk=self.prefill_chunk,
            prefix_cache=self.enable_prefix_cache,
            spec_k=self.spec_k if self.fused else 0,
            shed_high_watermark=self.shed_high_watermark,
            shed_low_watermark=self.shed_low_watermark,
            max_waiting=self.max_waiting,
        )


class ServeEngine:
    """Continuous-batching engine over a ``TransformerInferenceModule``."""

    def __init__(self, inference_module, config: Optional[EngineConfig] = None):
        import jax

        self.inf = inference_module
        self.config = config or EngineConfig()
        self.scheduler = ContinuousBatchingScheduler(
            self.config.scheduler_config()
        )
        # mp>1 sharded serving: the pools shard over the model axis and
        # every program runs SPMD over the serving mesh (one mixed
        # program, now partitioned; activation all-reduces come from the
        # same GSPMD constraints training's model axis uses)
        self.mesh = serving_mesh(inference_module)
        self.model_parallel = (
            1 if self.mesh is None
            else int(self.mesh.shape.get("model", 1))
        )
        self._replicated = None
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            self._replicated = NamedSharding(self.mesh, P())
        self.pools: PagedKVPools = init_pools(
            inference_module, self.config.num_blocks, self.config.block_size,
            kv_dtype=self.config.kv_dtype,
        )
        import numpy as np

        self._np = np
        self._jax = jax
        n, m = self.config.num_slots, self.config.max_blocks_per_seq
        self._tables = np.zeros((n, m), np.int32)
        self._ctx = np.zeros((n,), np.int32)
        self._tok = np.zeros((n,), np.int32)
        # per-slot sampler state (traced per-row arrays in the programs)
        self._temp = np.zeros((n,), np.float32)
        self._topk = np.zeros((n,), np.int32)
        self._topp = np.zeros((n,), np.float32)
        self._reqid = np.zeros((n,), np.int32)
        self._gen = np.zeros((n,), np.int32)
        self._base_key = self._dev(
            jax.random.PRNGKey(self.config.sample_seed)
        )
        self._decode_fn = None
        self._prefill_fns: Dict[int, object] = {}  # whole-prompt buckets
        self._chunk_fns: Dict[int, object] = {}  # chunk-size -> program
        # (width,) -> the ONE fused mixed program per (chunk, k) signature
        self._mixed_fns: Dict[int, object] = {}
        self.tick_index = 0
        self.finished: List[Sequence] = []
        self.max_concurrent_prefills = 0
        self._next_req_id = 0
        # bench warmup: while True, completions emit no serve-request
        # events (the analyzer's percentiles must mirror the measured
        # workload, not the off-the-clock compile traffic)
        self.warmup_mode = False
        self._reg = obs.get_registry()
        # fleet mode: every metric this replica records carries a
        # ``replica`` label so per-replica pressure/shed/timeout rows
        # stay separable in the obs report (single-engine: no label, so
        # pre-fleet metric names — and their tests — are unchanged)
        self.replica_id = self.config.replica_id
        self._labels = (
            {"replica": str(self.replica_id)}
            if self.replica_id is not None else None
        )
        self._replica_fields = (
            {"replica": self.replica_id}
            if self.replica_id is not None else {}
        )
        self._prefix_hits_flushed = 0  # scheduler counter already mirrored
        self.prefilled_tokens = 0  # prompt tokens actually prefilled
        self.spec_drafted_tokens = 0
        self.spec_accepted_tokens = 0
        # resilience state (docs/SERVING.md "Resilience"): graceful
        # drain, overload-shed / deadline-timeout tallies, and the
        # crash-replay request journal
        self.draining = False
        self.shed_count = 0
        self.timeout_count = 0
        self.journal = None
        self._journal_pending: Dict[int, List[int]] = {}
        # live requests carrying any deadline: the tick-boundary expiry
        # sweep is skipped entirely while this is zero (the default
        # no-deadline configuration must not pay O(live) per tick).
        # Guarded by its own lock: in a fleet the router's submit thread
        # increments while the replica's tick thread decrements, and a
        # lost update that read 0 would silently skip live deadlines.
        import threading

        self._deadline_live = 0
        self._deadline_lock = threading.Lock()

    # ------------------------------------------------------------- intake
    def submit(self, prompt: List[int], max_new_tokens: int,
               arrival_s: Optional[float] = None,
               eos_token_id: Optional[int] = None,
               temperature: float = 0.0,
               top_k: Optional[int] = None,
               top_p: Optional[float] = None,
               deadline_ms: Optional[float] = None,
               ttft_deadline_ms: Optional[float] = None,
               req_id: Optional[int] = None,
               force: bool = False,
               count_shed: bool = True,
               trace: Optional[str] = None):
        """Admit one request, or reject it with a structured
        :class:`Backpressure` (draining, or over the shed watermarks) —
        the signal a fleet router retries elsewhere on. Returns the
        :class:`Sequence` on admission.

        ``req_id`` pins the request's identity (crash-replay: the
        sampler keys fold the id, so a journal replay MUST reuse it);
        by default ids are assigned sequentially. ``deadline_ms`` /
        ``ttft_deadline_ms`` override the EngineConfig defaults.
        ``force`` bypasses drain/backpressure rejection — journal
        replay re-enqueues recovery work, not new load, and must never
        be shed by the very overload policy the crash left armed.
        ``count_shed=False`` returns the Backpressure WITHOUT counting
        or journaling it: the fleet router passes it because a rejection
        it retries on another replica is not a client-visible shed (the
        router counts the fleet-level rejection itself, and the journal
        shed records must map 1:1 onto consumed workload items).

        ``trace`` pins the request's distributed-trace id explicitly
        (journal replay re-adopting a crashed request's identity); by
        default the ambient ``obs.trace_context`` — set by the bench at
        submit, or adopted from an RPC envelope by the replica worker —
        is inherited. Warmup traffic never allocates or adopts one."""
        get_fault_plan().fire("serve.admit")
        if force:
            bp = None
        elif self.draining:
            bp = Backpressure(
                reason="draining",
                pool_pressure=round(self.scheduler.pool_pressure(), 4),
                waiting=len(self.scheduler.waiting), draining=True,
            )
        else:
            bp = self.scheduler.admission_backpressure()
        if bp is not None:
            if not self.warmup_mode and count_shed:
                # a draining rejection is shutdown, not overload: it
                # stays out of the shed rate the overload gates judge
                # AND out of the journal (the bench does not consume
                # the workload item — it stays unsubmitted)
                if not bp.draining:
                    self.shed_count += 1
                    self._counter("serve_requests_shed_total").inc()
                    if self.journal is not None:
                        self.journal.record_shed(bp.reason)
                logger.log_event(
                    "serve-shed", _level="debug", reason=bp.reason,
                    pool_pressure=bp.pool_pressure, waiting=bp.waiting,
                    **self._replica_fields,
                )
            return bp
        if req_id is None:
            req_id = self._next_req_id
        self._next_req_id = max(self._next_req_id, req_id + 1)
        if self.warmup_mode:
            # warmup hygiene: traffic the --warmup flag keeps off the
            # books must not enter the trace-coverage denominator either
            trace = None
        elif trace is None:
            trace = obs.current_trace_id()
        req = Request(
            req_id=req_id, prompt=list(prompt),
            max_new_tokens=max_new_tokens,
            arrival_s=time.monotonic() if arrival_s is None else arrival_s,
            eos_token_id=eos_token_id,
            temperature=temperature, top_k=top_k, top_p=top_p,
            deadline_ms=(
                deadline_ms if deadline_ms is not None
                else self.config.default_deadline_ms
            ),
            ttft_deadline_ms=(
                ttft_deadline_ms if ttft_deadline_ms is not None
                else self.config.default_ttft_deadline_ms
            ),
            trace_id=trace,
        )
        if trace is not None:
            # the admit span is the trace's first engine-side record;
            # re-assert the context so an explicitly-passed trace
            # (journal replay, orphan re-dispatch) links up even with
            # no ambient context on this thread
            with obs.trace_context(trace):
                with self._span("serve.admit", req=req_id,
                                **self._replica_fields):
                    seq = self.scheduler.add_request(req)
        else:
            seq = self.scheduler.add_request(req)
        if req.deadline_ms is not None or req.ttft_deadline_ms is not None:
            with self._deadline_lock:
                self._deadline_live += 1
        if not self.warmup_mode:
            self._counter("serve_requests_admitted_total").inc()
            if self.journal is not None:
                self.journal.record_submit(req)
        return seq

    def attach_journal(self, journal) -> None:
        """Wire the crash-replay request journal (serve/journal.py):
        every non-warmup submission, tick's emitted tokens, and terminal
        status is appended so a supervised relaunch can replay."""
        self.journal = journal

    def begin_drain(self) -> None:
        """Graceful drain (the serving mirror of the trainer's
        coordinated preemption): admit nothing new — `submit` returns
        Backpressure(reason='draining') — while in-flight requests run
        to completion or their deadlines. The bench's tick loop stops
        submitting and exits 0 once the scheduler empties."""
        if self.draining:
            return
        self.draining = True
        logger.log_event(
            "serve-drain", tick=self.tick_index,
            running=len(self.scheduler.running),
            waiting=len(self.scheduler.waiting),
            **self._replica_fields,
        )

    # --------------------------------------------------- device programs
    def _dev(self, x):
        """Host array(s) -> device operand(s). On a serving mesh the
        host-side addressing state (tables, lengths, tokens, sampler
        rows) is device_put REPLICATED so every program call mixes
        cleanly with the mesh-sharded pools and params; off-mesh it is a
        plain transfer to the engine's device. Accepts a tuple and moves
        it as ONE batched device_put — the mixed program's nine per-tick
        operands cost one dispatch, not nine (the host-side tick
        overhead is what caps fleet thread overlap)."""
        if self._replicated is None:
            return self._jax.device_put(x)
        return self._jax.device_put(x, self._replicated)

    def _counter(self, name: str):
        return self._reg.counter(name, self._labels)

    def _gauge(self, name: str):
        return self._reg.gauge(name, self._labels)

    def _histogram(self, name: str):
        return self._reg.histogram(name, self._labels)

    def _pool_state(self):
        p = self.pools
        return (p.pool_k, p.pool_v, p.scale_k, p.scale_v)

    def _views_from_state(self, state, block_table, context_len,
                          new_len=None):
        return build_layer_views(state, block_table, context_len, new_len)

    def _absorb(self, views) -> None:
        self.pools.absorb_views(views)

    def _span(self, name: str, **fields):
        """obs.span, silenced during bench warmup: warmup ticks carry the
        multi-second first-call jit compile, and a span record for them
        would dominate the analyzer's tick-time attribution for exactly
        the traffic --warmup exists to keep off the books."""
        if self.warmup_mode:
            import contextlib

            return contextlib.nullcontext()
        return obs.span(name, **fields)

    @staticmethod
    def _trace_fields(seqs, key: str = "traces") -> dict:
        """Span annotation linking a batch span to every traced request
        it advanced: ``{key: [trace ids]}``, empty dict when none are
        traced so trace-less runs emit byte-identical span records. The
        analyzer (obs/trace.py) indexes batch spans by these lists."""
        out: List[str] = []
        for s in seqs:
            tid = s.request.trace_id
            if tid and tid not in out:
                out.append(tid)
        return {key: out} if out else {}

    def _sample_last(self, logits, temps, topps, topks, reqids, gens,
                     base_key):
        """Shared sampling epilogue: per-row keys from (request, position),
        then the per-row temperature/top-k/top-p sampler."""
        from ..models.transformer.inference import (
            request_sample_key, sample_rows,
        )

        keys = self._jax.vmap(
            request_sample_key, in_axes=(None, 0, 0)
        )(base_key, reqids, gens)
        return sample_rows(logits, temps, topks, keys, top_ps=topps)

    def _sample_grid(self, logits, temps, topps, topks, reqids, gen0,
                     base_key):
        """Sample EVERY position of a (rows, s, vocab) logit grid with
        the key plain decode would use there: position ``i`` of a row
        draws with ``fold_in(fold_in(base, req), gen0 + i)``. This is
        what makes speculative acceptance PATHWISE exact at any
        temperature — the verifier computes the very token plain decode
        would have emitted, not merely one from the same distribution —
        and what lets chunk rows sample their first token at the last
        real position with the same key the legacy chunk program used
        (``gen0`` is per-row: chunk rows offset it so position
        ``new_len - 1`` folds the true generated count)."""
        from ..models.transformer.inference import (
            request_sample_key, sample_rows,
        )
        jnp = self._jax.numpy

        rows, s, vocab = logits.shape
        positions = gen0[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]
        keys = self._jax.vmap(
            self._jax.vmap(request_sample_key, in_axes=(None, None, 0)),
            in_axes=(None, 0, 0),
        )(base_key, reqids, positions)  # (rows, s, 2)

        def rep(x):
            return jnp.repeat(x, s, axis=0)

        flat = sample_rows(
            logits.reshape(rows * s, vocab), rep(temps), rep(topks),
            keys.reshape(rows * s, keys.shape[-1]), top_ps=rep(topps),
        )
        return flat.reshape(rows, s)

    def _build_prefill_fn(self, bucket: int):
        jnp = self._jax.numpy
        block_size = self.config.block_size

        def prefill(params, state, tokens, block_row, prompt_len,
                    temp, topp, topk, reqid, gen, base_key):
            b, L = tokens.shape  # (1, bucket)
            pos = jnp.broadcast_to(jnp.arange(L, dtype=jnp.int32)[None], (b, L))
            # bucket padding sits in its own segment: content never
            # attends to it, it never attends to content
            seg = jnp.where(pos < prompt_len, 0, 1).astype(jnp.int32)
            logits, kvs = self.inf.prefill_forward(
                params, tokens, pos, seg, last_index=prompt_len - 1
            )
            views = self._views_from_state(
                state, block_row[None, :], jnp.zeros((1,), jnp.int32)
            )
            new_views = [
                write_prompt_kv(view, k, v, block_row, prompt_len, block_size)
                for view, (k, v) in zip(views, kvs)
            ]
            next_tok = self._sample_last(
                logits[:, -1], temp, topp, topk, reqid, gen, base_key
            )
            return next_tok, new_views

        # same lifecycle as decode: the old pool state dies with the call
        # (absorb_views takes the returned arrays), so donation lets XLA
        # scatter in place instead of copying every layer's pool per
        # admitted prompt. CPU can't donate (every call would warn).
        donate = (1,) if self._jax.default_backend() != "cpu" else ()
        return self._jax.jit(prefill, donate_argnums=donate)

    def _build_chunk_fn(self, chunk: int):
        """ONE compiled program per chunk size: scatter the chunk's KV at
        the sequence's next slots and attend over the pool — the same
        paged path decode uses, so a chunk sees every previous chunk's KV
        without any per-prompt-length shapes. ``new_len`` routes the
        final ragged chunk's padding to the trash block."""
        jnp = self._jax.numpy

        def chunk_prefill(params, state, tokens, block_row, ctx_len, new_len,
                          temp, topp, topk, reqid, gen, base_key):
            b, L = tokens.shape  # (1, chunk)
            pos = ctx_len[:, None] + jnp.arange(L, dtype=jnp.int32)[None, :]
            batch = self.inf._make_batch(tokens, pos)
            views = self._views_from_state(
                state, block_row[None, :], ctx_len, new_len
            )
            logits, new_views = self.inf._run_layers(
                params, batch, views, None,
                paged_kernel=self.config.paged_kernel,
            )
            # the chunk's last REAL position predicts the next token; it
            # only counts when this chunk completes the prompt (host-side
            # decision — mid-prompt samples are discarded)
            last = self._jax.lax.dynamic_slice_in_dim(
                logits, new_len[0] - 1, 1, axis=1
            )[:, 0]
            next_tok = self._sample_last(
                last, temp, topp, topk, reqid, gen, base_key
            )
            return next_tok, new_views

        donate = (1,) if self._jax.default_backend() != "cpu" else ()
        return self._jax.jit(chunk_prefill, donate_argnums=donate)

    def _build_decode_fn(self):
        def decode(params, state, tables, ctx_lens, tokens,
                   temps, topps, topks, reqids, gens, base_key):
            batch = self.inf._make_batch(tokens[:, None], ctx_lens[:, None])
            views = self._views_from_state(state, tables, ctx_lens)
            logits, new_views = self.inf._run_layers(
                params, batch, views, None,
                paged_kernel=self.config.paged_kernel,
            )
            next_tok = self._sample_last(
                logits[:, -1], temps, topps, topks, reqids, gens, base_key
            )
            return next_tok, new_views

        # the pool state dies with each call — donating it lets XLA run
        # the scatter updates in place instead of copying every pool
        # block per token. CPU can't donate (every call would warn).
        donate = (1,) if self._jax.default_backend() != "cpu" else ()
        return self._jax.jit(decode, donate_argnums=donate)

    def _build_mixed_fn(self, width: int):
        """ONE fused Sarathi-style program per tick: every slot row is a
        decode row (its last token plus up to ``spec_k`` drafted
        candidates) or a prefill chunk, tagged purely by traced per-row
        lengths — a tick that used to dispatch one decode program plus
        one chunk program PER prefilling sequence now dispatches exactly
        one executable. Rows share the scatter-then-attend paged path
        (``new_len`` routes each row's pads to the trash block; rows
        never share pool blocks, so fusing their writes is exact), and
        EVERY position is sampled with its plain-decode key
        (``_sample_grid``): decode rows read positions ``0..new_len-1``
        for speculative acceptance, a chunk row that completes its
        prompt reads position ``new_len - 1``. Only ``sample_width``
        (= min(width, spec_k+1)) positions per row are ever read, so the
        program GATHERS each row's sampling window of trunk activations
        before the vocab projection (ISSUE 13 satellite): row window =
        positions ``g0 .. g0 + sample_width - 1`` with
        ``g0 = clip(new_len - sample_width, 0)`` — covers positions
        ``0..new_len-1`` for decode rows (new_len ≤ spec_k+1 ⇒ g0 = 0)
        and position ``new_len - 1`` for chunk rows, while the lm_head
        prices ``sample_width`` positions instead of all ``width``.
        Compiles once per (chunk, k) width signature — pinned in the
        serve_decode golden."""
        jnp = self._jax.numpy
        sample_width = self.config.sample_width

        def mixed(params, state, tables, ctx_lens, tokens, new_lens,
                  temps, topps, topks, reqids, gen0, base_key):
            pos = ctx_lens[:, None] + jnp.arange(
                width, dtype=jnp.int32
            )[None, :]
            batch = self.inf._make_batch(tokens, pos)
            views = self._views_from_state(state, tables, ctx_lens,
                                           new_lens)
            g0 = jnp.clip(new_lens - sample_width, 0, width - sample_width)
            logits, new_views = self.inf._run_layers(
                params, batch, views, None,
                paged_kernel=self.config.paged_kernel,
                gather_start=g0, gather_width=sample_width,
            )
            # gathered index j is original position g0 + j: shift the
            # per-row key-fold base so every sample still draws with the
            # (request, position) key plain decode would use there
            sampled = self._sample_grid(
                logits, temps, topps, topks, reqids, gen0 + g0, base_key
            )
            return sampled, new_views

        donate = (1,) if self._jax.default_backend() != "cpu" else ()
        return self._jax.jit(mixed, donate_argnums=donate)

    # ------------------------------------------------------------- ticking
    def _reset_rows(self, slots: List[int]) -> None:
        for s in slots:
            self._tables[s] = 0
            self._ctx[s] = 0
            self._tok[s] = 0
            self._temp[s] = 0.0
            self._topk[s] = 0
            self._topp[s] = 0.0
            self._reqid[s] = 0
            self._gen[s] = 0

    def _admit_slot(self, seq: Sequence) -> None:
        """Per-slot sampler state for a newly-admitted sequence."""
        slot = seq.slot
        self._temp[slot] = seq.request.temperature
        self._topk[slot] = seq.request.top_k or 0
        self._topp[slot] = seq.request.top_p or 0.0
        self._reqid[slot] = seq.request.req_id

    def _scalar_sample_args(self, seq: Sequence):
        np = self._np
        return (
            np.asarray([seq.request.temperature], np.float32),
            np.asarray([seq.request.top_p or 0.0], np.float32),
            np.asarray([seq.request.top_k or 0], np.int32),
            np.asarray([seq.request.req_id], np.int32),
            np.asarray([len(seq.generated)], np.int32),
        )

    def _run_prefill(self, seq: Sequence) -> None:
        """Whole-prompt prefill (legacy mode): one pow2-bucketed program
        pass over the entire resume prompt."""
        np = self._np
        prompt = seq.resume_prompt
        bucket = prefill_bucket(len(prompt))
        if bucket not in self._prefill_fns:
            self._prefill_fns[bucket] = self._build_prefill_fn(bucket)
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :len(prompt)] = prompt
        block_row = np.zeros((self.config.max_blocks_per_seq,), np.int32)
        block_row[:len(seq.blocks)] = seq.blocks
        self._admit_slot(seq)
        with self._span("serve.prefill", step=self.tick_index,
                      tokens=len(prompt), **self._trace_fields([seq])):
            operands = self._dev((
                tokens, block_row, np.int32(len(prompt)),
                *self._scalar_sample_args(seq),
            ))
            next_tok, new_views = self._prefill_fns[bucket](
                self.inf.params, self._pool_state(), *operands,
                self._base_key,
            )
            # deliberate sync: the prefilled token must land on host to
            # be emitted (one pull per prefill, inside the measured span)
            tok = int(np.asarray(next_tok)[0])  # sta: disable=STA010
        self._absorb(new_views)
        now = time.monotonic()
        slot = seq.slot
        self._tables[slot] = block_row
        self._ctx[slot] = len(prompt)
        self._tok[slot] = tok
        seq.num_cached = len(prompt)
        self._emit_token(seq, tok, now)
        if not self.warmup_mode:
            self.prefilled_tokens += len(prompt)
            self._counter("serve_prefill_tokens_total").inc(len(prompt))

    def _run_prefill_chunk(self, seq: Sequence) -> None:
        """One fixed-size chunk of ``seq``'s prompt: scatter its KV into
        the pool (pads to trash) and, when it completes the prompt, emit
        the first token."""
        np = self._np
        chunk = self.config.prefill_chunk
        if chunk not in self._chunk_fns:
            self._chunk_fns[chunk] = self._build_chunk_fn(chunk)
        prompt = seq.resume_prompt
        start = seq.num_cached
        n_real = min(chunk, len(prompt) - start)
        assert n_real > 0, "chunk scheduled for a fully-prefilled sequence"
        tokens = np.zeros((1, chunk), np.int32)
        tokens[0, :n_real] = prompt[start:start + n_real]
        block_row = np.zeros((self.config.max_blocks_per_seq,), np.int32)
        block_row[:len(seq.blocks)] = seq.blocks
        if start == seq.prefix_cached:
            # first chunk of this admission (a prefix hit starts past 0)
            self._admit_slot(seq)
        finishing = start + n_real == len(prompt)
        with self._span("serve.prefill_chunk", step=self.tick_index,
                      tokens=n_real, start=start,
                      **self._trace_fields([seq])):
            operands = self._dev((
                tokens, block_row, np.asarray([start], np.int32),
                np.asarray([n_real], np.int32),
                *self._scalar_sample_args(seq),
            ))
            next_tok, new_views = self._chunk_fns[chunk](
                self.inf.params, self._pool_state(), *operands,
                self._base_key,
            )
            # deliberate sync: the chunk's sampled token must land on
            # host (one pull per chunk, inside the measured span)
            tok = int(np.asarray(next_tok)[0])  # sta: disable=STA010
        self._absorb(new_views)
        slot = seq.slot
        self._tables[slot] = block_row
        self._ctx[slot] = start + n_real
        seq.num_cached = start + n_real
        if not self.warmup_mode:
            self.prefilled_tokens += n_real
            self._counter("serve_prefill_tokens_total").inc(n_real)
        if finishing:
            self._tok[slot] = tok
            self._emit_token(seq, tok, time.monotonic())

    def _run_decode(self, decodes: List[Sequence]) -> None:
        np = self._np
        if self._decode_fn is None:
            self._decode_fn = self._build_decode_fn()
        active = np.zeros((self.config.num_slots,), bool)
        for seq in decodes:
            # the scheduler may have grown this row's block list since the
            # table row was last written (incremental allocation)
            row = self._tables[seq.slot]
            row[:] = 0
            row[:len(seq.blocks)] = seq.blocks
            self._gen[seq.slot] = len(seq.generated)
            active[seq.slot] = True
        # rows not decoding this tick (empty, or mid-prefill under
        # chunked prefill) run against an all-trash table with ctx 0:
        # their device-side writes can never land in blocks a prefilling
        # sequence is about to fill
        tables = np.where(active[:, None], self._tables, 0)
        ctx = np.where(active, self._ctx, 0)
        with self._span("serve.decode", step=self.tick_index,
                      batch=len(decodes), **self._trace_fields(decodes)):
            operands = self._dev((
                tables, ctx, self._tok, self._temp, self._topp,
                self._topk, self._reqid, self._gen,
            ))
            next_tok, new_views = self._decode_fn(
                self.inf.params, self._pool_state(), *operands,
                self._base_key,
            )
            # the tick's ONE deliberate device->host pull: sampled tokens
            # must land on host to be emitted to callers
            toks = np.asarray(next_tok)  # sta: disable=STA010
        self._absorb(new_views)
        now = time.monotonic()
        for seq in decodes:
            slot = seq.slot
            self._ctx[slot] += 1
            seq.num_cached += 1
            tok = int(toks[slot])
            self._tok[slot] = tok
            self._emit_token(seq, tok, now)

    def _apply_cow(self, pairs) -> None:
        """Copy-on-write block forks the scheduler ordered this tick:
        duplicate pool block ``src`` into freshly-allocated ``dst``
        across every layer (K, V, and int8 scales) BEFORE the tick's
        programs run. Eager host-dispatched ops — forks never occur in
        the steady state (full-block prefix sharing places writes past
        every shared block), so this path stays off the hot loop."""
        if not pairs:
            return
        p = self.pools
        for src, dst in pairs:
            for arrs in (p.pool_k, p.pool_v, p.scale_k, p.scale_v):
                if arrs is None:
                    continue
                for i in range(len(arrs)):
                    arrs[i] = arrs[i].at[dst].set(arrs[i][src])
        self._counter("serve_cow_forks_total").inc(len(pairs))

    def _run_mixed(self, t: Tick) -> None:
        """The fused tick (Sarathi piggybacking): ONE program call
        covers every prefill chunk AND the whole decode batch, each row
        tagged by its traced ``new_len``/``ctx_len``. Decode rows carry
        their speculative drafts; acceptance happens host-side on the
        returned per-position samples (``_accept_speculative``)."""
        np = self._np
        jnp = self._jax.numpy
        cfg = self.config
        width = cfg.mixed_width
        if width not in self._mixed_fns:
            self._mixed_fns[width] = self._build_mixed_fn(width)
        n = cfg.num_slots
        tokens = np.zeros((n, width), np.int32)
        new_lens = np.zeros((n,), np.int32)
        ctx = np.zeros((n,), np.int32)
        gen0 = np.zeros((n,), np.int32)
        tables = np.zeros((n, cfg.max_blocks_per_seq), np.int32)
        chunk_rows = []  # (seq, start, n_real)
        for seq in t.prefills:
            slot = seq.slot
            prompt = seq.resume_prompt
            start = seq.num_cached
            n_real = min(cfg.prefill_chunk, seq.prefill_len - start)
            assert n_real > 0, "chunk row scheduled with nothing to prefill"
            tokens[slot, :n_real] = prompt[start:start + n_real]
            new_lens[slot] = n_real
            ctx[slot] = start
            tables[slot, :len(seq.blocks)] = seq.blocks
            if start == seq.prefix_cached:
                # first chunk of this admission (prefix hits start past 0)
                self._admit_slot(seq)
            # the chunk's last REAL position must draw with the key plain
            # decode uses for the request's first generated token
            gen0[slot] = len(seq.generated) - (n_real - 1)
            chunk_rows.append((seq, start, n_real))
        for seq in t.decodes:
            slot = seq.slot
            d = seq.draft
            tokens[slot, 0] = seq.generated[-1]
            if d:
                tokens[slot, 1:1 + len(d)] = d
            new_lens[slot] = 1 + len(d)
            ctx[slot] = seq.num_cached
            tables[slot, :len(seq.blocks)] = seq.blocks
            gen0[slot] = len(seq.generated)
            self._gen[slot] = len(seq.generated)
        # inactive rows keep all-trash tables + new_len 0: their writes
        # land in the trash block and they expose zero visible slots
        with self._span("serve.mixed", step=self.tick_index,
                      decodes=len(t.decodes), chunks=len(t.prefills),
                      **self._trace_fields(t.decodes),
                      **self._trace_fields(t.prefills, "chunk_traces")):
            operands = self._dev((
                tables, ctx, tokens, new_lens, self._temp, self._topp,
                self._topk, self._reqid, gen0,
            ))
            sampled, new_views = self._mixed_fns[width](
                self.inf.params, self._pool_state(), *operands,
                self._base_key,
            )
            # the tick's ONE deliberate device->host pull: the sampled
            # token grid must land on host to be emitted to callers
            host_samples = np.asarray(sampled)  # sta: disable=STA010
        self._absorb(new_views)
        now = time.monotonic()
        sw = cfg.sample_width  # sampled grid covers positions g0..g0+sw-1
        for seq, start, n_real in chunk_rows:
            slot = seq.slot
            seq.num_cached = start + n_real
            self._tables[slot] = tables[slot]
            self._ctx[slot] = seq.num_cached
            if not self.warmup_mode:
                self.prefilled_tokens += n_real
                self._counter("serve_prefill_tokens_total").inc(n_real)
            if seq.num_cached == seq.prefill_len:
                # original position n_real - 1, gathered at index
                # n_real - 1 - g0 with g0 = max(n_real - sw, 0)
                tok = int(host_samples[slot, min(n_real, sw) - 1])
                self._tok[slot] = tok
                self._emit_token(seq, tok, now)
        for seq in t.decodes:
            self._tables[seq.slot] = tables[seq.slot]
            self._accept_speculative(seq, host_samples[seq.slot], now)

    def _accept_speculative(self, seq: Sequence, row_samples, now) -> None:
        """Exact speculative acceptance (Leviathan et al., arxiv
        2211.17192, specialized to pathwise-deterministic keys): every
        scored position was sampled with the key plain decode would use
        there, so position ``j``'s sample IS plain decode's next token
        — PROVIDED the conditioning holds, i.e. every earlier draft
        matched its sample. Emit the sample run up to and including the
        first mismatch; advance the sequence (and so the per-request key
        fold) by tokens ACCEPTED, never tokens scored — a preempted-and-
        resumed sequence mid-speculation redraws identical tokens."""
        draft = seq.draft
        xs = [int(x) for x in row_samples[:len(draft) + 1]]
        emitted = [xs[0]]
        matched = 0
        for j, d in enumerate(draft):
            if d != xs[j]:
                break
            matched += 1
            emitted.append(xs[j + 1])
        # the request's budget and EOS cut the run exactly where plain
        # decode would have stopped asking for tokens
        emitted = emitted[:seq.remaining_tokens]
        eos = seq.request.eos_token_id
        if eos is not None and eos in emitted:
            emitted = emitted[:emitted.index(eos) + 1]
        accepted = min(matched, len(emitted) - 1)
        if self.warmup_mode:
            draft = []
        self.spec_drafted_tokens += len(draft)
        self.spec_accepted_tokens += accepted if draft else 0
        if draft:
            self._counter("serve_spec_drafted_tokens_total").inc(
                len(draft)
            )
            if accepted:
                self._counter("serve_spec_accepted_tokens_total").inc(
                    accepted
                )
        seq.draft = []
        slot = seq.slot
        # KV validity: slot ctx held the last token's write, plus one
        # slot per accepted draft — rejected drafts' slots are simply
        # overwritten by the next call (ctx never admits them)
        seq.num_cached += len(emitted)
        self._ctx[slot] = seq.num_cached
        for tok in emitted:
            self._tok[slot] = tok
            self._emit_token(seq, tok, now)
        self._gen[slot] = len(seq.generated)

    def _emit_token(self, seq: Sequence, tok: int, now: float) -> None:
        seq.generated.append(tok)
        if self.journal is not None and not self.warmup_mode:
            # batched into one journal line per (request, tick) at the
            # end of tick() — crash-replay regenerates anything a
            # mid-tick kill loses before the flush
            self._journal_pending.setdefault(
                seq.request.req_id, []
            ).append(tok)
        if seq.first_token_s is None:
            seq.first_token_s = now
            if not self.warmup_mode:
                self._histogram("serve_ttft_seconds").observe(
                    now - seq.request.arrival_s
                )
        elif seq.token_stamps and not self.warmup_mode:
            self._histogram("serve_itl_seconds").observe(
                now - seq.token_stamps[-1]
            )
        seq.token_stamps.append(now)
        if not self.warmup_mode:
            self._counter("serve_tokens_generated_total").inc()

    def _finish(self, seq: Sequence, now: float) -> None:
        self.scheduler.finish(seq)  # row reset rides the freed-slot drain
        self._retire(seq, now, "completed")

    def _retire(self, seq: Sequence, now: float, status: str) -> None:
        """Shared terminal bookkeeping for every way a request ends:
        journal + telemetry + the ``serve-request`` event whose
        ``status`` field ('completed' | 'timeout') the analyzer and the
        shed/timeout gates read."""
        seq.finish_status = status
        seq.finished_s = now
        self.finished.append(seq)
        req = seq.request
        if req.deadline_ms is not None or req.ttft_deadline_ms is not None:
            with self._deadline_lock:
                self._deadline_live -= 1
        if self.warmup_mode:
            return
        if self.journal is not None:
            pending = self._journal_pending.pop(seq.request.req_id, None)
            # final tokens + terminal status ride ONE append (tokens
            # strictly before status within it)
            self.journal.record_finish(
                seq.request.req_id, status, tokens=pending
            )
        if status == "completed":
            self._counter("serve_requests_completed_total").inc()
        else:
            self.timeout_count += 1
            self._counter("serve_requests_timeout_total").inc()
        itl = [
            b - a for a, b in zip(seq.token_stamps, seq.token_stamps[1:])
        ]
        fields = dict(
            req=seq.request.req_id,
            status=status,
            prompt_tokens=len(seq.request.prompt),
            output_tokens=len(seq.generated),
            e2e_s=round(now - seq.request.arrival_s, 6),
            itl_mean_s=round(sum(itl) / len(itl), 6) if itl else 0.0,
            preemptions=seq.preemptions,
            **self._replica_fields,
        )
        if seq.request.trace_id is not None:
            # the trace's terminal record: obs/trace.py reads e2e_s and
            # status from here and anchors the timeline's end on ts
            fields["trace"] = seq.request.trace_id
        if seq.first_token_s is not None:
            # a TTFT-deadline timeout never produced a first token — the
            # analyzer's percentiles must not see a fabricated sample
            fields["ttft_s"] = round(
                seq.first_token_s - seq.request.arrival_s, 6
            )
        logger.log_event("serve-request", _level="debug", **fields)

    def _expire_deadlines(self, now: float) -> None:
        """Tick-boundary deadline sweep: cancel every live request past
        its total deadline, or past its TTFT deadline with no first
        token yet. The scheduler releases slot + blocks (one reference
        each — trie-shared prefix blocks stay cached for the next
        requester), so the capacity is admissible THIS tick."""
        if not self._deadline_live:
            return
        live = list(self.scheduler.running.values()) + list(
            self.scheduler.waiting
        )
        for seq in live:
            req = seq.request
            waited_ms = (now - req.arrival_s) * 1000.0
            expired = (
                req.deadline_ms is not None and waited_ms > req.deadline_ms
            ) or (
                req.ttft_deadline_ms is not None
                and seq.first_token_s is None
                and waited_ms > req.ttft_deadline_ms
            )
            if not expired:
                continue
            self.scheduler.cancel(seq)
            self._retire(seq, now, "timeout")

    def tick(self) -> Tick:
        """One engine step: expire deadlines, draft speculative
        candidates, schedule, run the fused mixed program (or the
        legacy separate programs), retire completions, flush the
        request journal."""
        get_fault_plan().fire("serve.tick")
        self._expire_deadlines(time.monotonic())
        if self.config.spec_k > 0:
            with self._span("serve.draft", step=self.tick_index):
                self.scheduler.propose_drafts()
        t = self.scheduler.schedule()
        if t.preempted:
            self._counter("serve_preemptions_total").inc(len(t.preempted))
            # a zero-width marker span: records WHICH traced requests
            # got pushed back to waiting this tick, so a trace's timeline
            # shows the preemption that explains its decode gap
            with self._span("serve.preempt", step=self.tick_index,
                            count=len(t.preempted),
                            **self._trace_fields(t.preempted)):
                pass
        sched = self.scheduler
        if sched.prefix_hit_tokens > self._prefix_hits_flushed:
            self._counter("serve_prefix_hit_tokens_total").inc(
                sched.prefix_hit_tokens - self._prefix_hits_flushed
            )
            self._prefix_hits_flushed = sched.prefix_hit_tokens
        self._reset_rows(self.scheduler.drain_freed_slots())
        if t.cow_pairs:
            # forks are ordered by this tick's (re-)admissions — the
            # prefill rows — so their traces are the ones the copy work
            # advanced (Tick flattens the per-seq pairs; the row list is
            # the per-request attribution that survives)
            with self._span("serve.cow", step=self.tick_index,
                            pairs=len(t.cow_pairs),
                            **self._trace_fields(t.prefills)):
                self._apply_cow(t.cow_pairs)
        else:
            self._apply_cow(t.cow_pairs)
        if self.config.fused:
            if t.prefills or t.decodes:
                self._run_mixed(t)
        else:
            chunked = self.config.prefill_chunk is not None
            for seq in t.prefills:
                if chunked:
                    self._run_prefill_chunk(seq)
                else:
                    self._run_prefill(seq)
            if t.decodes:
                self._run_decode(t.decodes)
        if len(t.prefills) > self.max_concurrent_prefills:
            self.max_concurrent_prefills = len(t.prefills)
        now = time.monotonic()
        for seq in list(t.prefills) + list(t.decodes):
            if seq.done and seq.slot is not None:
                self._finish(seq, now)
        self._reset_rows(self.scheduler.drain_freed_slots())
        if self.journal is not None and self._journal_pending:
            # ONE append for every row's tick tokens (completions
            # already flushed theirs inside _retire, tokens before
            # status): per-row appends convoyed the fleet's tick
            # threads on the GIL
            self.journal.record_tokens_batch(self._journal_pending)
            self._journal_pending.clear()
        for name, value in self.scheduler.gauges().items():
            self._gauge(name).set(value)
        if self.spec_drafted_tokens:
            self._gauge("serve_spec_accept_rate").set(
                self.spec_accepted_tokens / self.spec_drafted_tokens
            )
        self.tick_index += 1
        if self.tick_index % self.config.flush_interval == 0:
            self._reg.flush_step(self.tick_index)
        return t

    @property
    def spec_accept_rate(self) -> Optional[float]:
        """Accepted / drafted speculative tokens (None before any
        drafting) — the self-drafting proposer's quality signal."""
        if not self.spec_drafted_tokens:
            return None
        return self.spec_accepted_tokens / self.spec_drafted_tokens

    @property
    def prefill_program_count(self) -> int:
        """Compiled prefill-side programs: pow2 buckets (whole-prompt
        mode), chunk programs, and fused mixed programs (one per
        (chunk, k) width signature)."""
        return (len(self._prefill_fns) + len(self._chunk_fns)
                + len(self._mixed_fns))

    def stats_snapshot(self) -> dict:
        """One JSON-safe dict of the engine's load + lifetime tallies —
        the ``stats`` RPC reply a subprocess replica answers with
        (``serve.replica_proc``), which doubles as its heartbeat: every
        field the router's least-loaded sort, the supervisor's liveness
        pass, and the proc-fleet serve-summary read. Reads are plain
        attribute/len reads (GIL-atomic against a concurrent tick), so
        this is safe to call from an RPC handler thread without the
        tick lock."""
        sched = self.scheduler
        finished = list(self.finished)
        return {
            "replica": self.replica_id,
            "queue_depth": len(sched.waiting) + len(sched.running),
            "waiting": len(sched.waiting),
            "running": len(sched.running),
            "pool_pressure": sched.pool_pressure(),
            "has_work": sched.has_work,
            "draining": self.draining,
            "next_req_id": self._next_req_id,
            "tick": self.tick_index,
            "shed_count": self.shed_count,
            "timeout_count": self.timeout_count,
            "finished": len(finished),
            "completed": sum(
                1 for s in finished if s.finish_status == "completed"
            ),
            "output_tokens": sum(len(s.generated) for s in finished),
            "preemptions": sched.preemption_count,
            "prefix_hit_tokens": sched.prefix_hit_tokens,
            "prefilled_tokens": self.prefilled_tokens,
            "spec_drafted_tokens": self.spec_drafted_tokens,
            "spec_accepted_tokens": self.spec_accepted_tokens,
            "prefill_compiles": self.prefill_program_count,
            "max_concurrent_prefills": self.max_concurrent_prefills,
        }

    def run_until_done(self, max_ticks: int = 100_000) -> List[Sequence]:
        """Drain every submitted request; returns finished sequences in
        completion order."""
        ticks = 0
        while self.scheduler.has_work:
            self.tick()
            ticks += 1
            if ticks > max_ticks:
                raise RuntimeError(
                    f"engine made no progress draining the queue within "
                    f"{max_ticks} ticks — scheduler livelock?"
                )
        self._reg.flush_step(self.tick_index)
        return self.finished


def install_drain_handler(engine: ServeEngine) -> None:
    """SIGTERM -> graceful drain, chaining any previously installed
    handler exactly like the trainer's ``install_preemption_handler``
    (launchers and cluster agents keep theirs): the engine flips to
    draining — no new admissions, in-flight requests finish or hit
    their deadlines — and the bench loop exits 0 with a complete,
    parseable run dir. The serving mirror of the trainer's
    coordinated-preemption contract (docs/RESILIENCE.md)."""
    import signal

    prev = signal.getsignal(signal.SIGTERM)

    def handler(signum, frame):
        engine.begin_drain()
        if callable(prev):  # SIG_DFL/SIG_IGN are enum ints, skipped
            prev(signum, frame)

    signal.signal(signal.SIGTERM, handler)
