"""The fleet router: N data-parallel engine replicas behind one dispatcher.

The scale-OUT half of serving (docs/SERVING.md "The fleet"): one engine
was pushed to 45k tokens/s/chip; the next multiplicative win is N
engines. Every signal this router consumes landed with the resilience
work — structured :class:`~.scheduler.Backpressure` from watermark
admission, the ``serve_pool_pressure`` gauge, SIGTERM drain, and the
per-replica crash-replay journal — so the router is pure dispatch
policy over :class:`~.engine.ServeEngine` replicas:

- **least-loaded dispatch**: a new request goes to the live replica
  with the smallest (queue depth, pool pressure) — exactly the numbers
  the ``serve_waiting_seqs`` / ``serve_pool_pressure`` gauges export,
  so the router and a post-mortem read the same load signal;
- **prefix-affinity dispatch**: the router hashes the prompt's leading
  FULL blocks (the prefix trie's sharing granularity — a partial block
  can never be reused, scheduler.PrefixCache) and remembers which
  replica last served each block-chain; a prompt whose longest hashed
  chain maps to a live replica goes there, so a prompt family's shared
  system prefix is prefilled once per REPLICA instead of once per
  request-shuffle. Hash-based rather than trie-introspecting on
  purpose: the policy needs nothing but the prompt bytes, so it holds
  across process boundaries when replicas move out-of-process;
- **retry-elsewhere**: a replica answering ``submit`` with
  :class:`Backpressure` is not the fleet saying no — the router retries
  the remaining live replicas in load order and only surfaces
  Backpressure when EVERY replica shed (the client-visible overload
  signal);
- **drain fan-out**: ``begin_drain`` drains every replica (the fleet
  mirror of single-engine SIGTERM drain — the bench wires the handler);
- **replica failure**: ``fail_replica`` drops a crashed replica from
  dispatch (its in-flight work is recoverable from its OWN journal
  namespace — serve/journal.py ``journal_path``); ``restore_replica``
  re-registers a relaunched engine under the same id.

Pure host-side policy, jax-free at import: the engines own the device
programs. Thread-safety: all dispatch state mutates under one router
lock; per-replica engine calls are serialized by the per-replica locks
the fleet bench's tick threads share (``ReplicaHandle.lock``).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Optional, Tuple

from ..logging import logger
from .scheduler import Backpressure

# bound on the remembered prefix chains: LRU beyond this (a router that
# never forgets would grow with every distinct prompt ever served)
PREFIX_MAP_CAP = 4096


@dataclasses.dataclass
class ReplicaStats:
    """Per-replica dispatch accounting (rendered by ``obs report``'s
    fleet rows and the ``serve-summary``'s ``replica_stats``)."""

    dispatches: int = 0
    affinity_dispatches: int = 0
    retries_taken: int = 0  # dispatches received as someone's retry
    sheds: int = 0  # Backpressure answers this replica returned

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class ReplicaHandle:
    """One engine replica as the router sees it: the engine, its id,
    a liveness flag, and the lock the fleet bench's tick thread and the
    router's submit path share (engine state is single-writer)."""

    def __init__(self, engine, replica_id: int):
        if engine.replica_id != replica_id:
            raise ValueError(
                f"engine carries replica_id {engine.replica_id!r} but the "
                f"router registers it as {replica_id} — set "
                "EngineConfig.replica_id so telemetry and journal "
                "namespaces agree with dispatch"
            )
        self.engine = engine
        self.replica_id = replica_id
        self.alive = True
        self.lock = threading.Lock()
        self.stats = ReplicaStats()

    def load(self) -> Tuple[int, float]:
        """(queue depth, pool pressure) — the least-loaded sort key.
        Queue depth counts waiting AND running (a replica with free
        slots but a deep backlog is not 'less loaded' than an idle
        one); pool pressure breaks ties the way the shed watermarks
        would."""
        sched = self.engine.scheduler
        depth = len(sched.waiting) + len(sched.running)
        return depth, sched.pool_pressure()


class FleetRouter:
    """Dispatch policy over N :class:`ServeEngine` replicas."""

    def __init__(self, engines: List, block_size: Optional[int] = None):
        if not engines:
            raise ValueError("a fleet needs at least one replica")
        self.replicas: List[ReplicaHandle] = [
            ReplicaHandle(e, e.replica_id if e.replica_id is not None else i)
            for i, e in enumerate(engines)
        ]
        ids = [r.replica_id for r in self.replicas]
        if len(set(ids)) != len(ids):
            raise ValueError(
                f"duplicate replica ids {ids} — journal namespaces and "
                "telemetry labels would collide"
            )
        sizes = {r.engine.config.block_size for r in self.replicas}
        if block_size is None:
            if len(sizes) != 1:
                raise ValueError(
                    f"replicas disagree on block_size ({sorted(sizes)}); "
                    "prefix-affinity hashes full blocks and needs ONE "
                    "granularity"
                )
            block_size = sizes.pop()
        self.block_size = block_size
        self._lock = threading.Lock()
        # prefix-chain hash -> replica id, insertion-ordered for LRU
        self._prefix_owner: Dict[int, int] = {}
        self._next_req_id = 0
        self.retries_elsewhere = 0
        self.rejected = 0  # submissions every live replica shed

    # ---------------------------------------------------------- plumbing
    @property
    def live(self) -> List[ReplicaHandle]:
        return [r for r in self.replicas if r.alive]

    def replica(self, replica_id: int) -> ReplicaHandle:
        for r in self.replicas:
            if r.replica_id == replica_id:
                return r
        raise KeyError(f"no replica {replica_id}")

    def _chain_hashes(self, prompt: List[int]) -> List[int]:
        """One hash per leading FULL block chain of ``prompt`` (chain i
        covers tokens [0, i*block_size)), longest last — mirroring the
        trie's path-from-root sharing rule, including its 'always leave
        one token to prefill' cap. Chains fold INCREMENTALLY (chain i =
        hash of (chain i-1, block i)) so a long prompt costs O(len),
        not O(len^2) rehashing on the dispatch path; int-tuple hashes
        are PYTHONHASHSEED-independent, so the mapping still holds
        across processes."""
        bs = self.block_size
        full = max(0, (len(prompt) - 1) // bs)
        chains: List[int] = []
        acc = 0
        for i in range(full):
            acc = hash((acc, tuple(prompt[i * bs:(i + 1) * bs])))
            chains.append(acc)
        return chains

    def _remember(self, chains: List[int], replica_id: int) -> None:
        for h in chains:
            self._prefix_owner.pop(h, None)  # re-insert = LRU refresh
            self._prefix_owner[h] = replica_id
        while len(self._prefix_owner) > PREFIX_MAP_CAP:
            self._prefix_owner.pop(next(iter(self._prefix_owner)))

    def affinity_replica(self, prompt: List[int]) -> Optional[int]:
        """The live replica whose trie most plausibly holds this
        prompt's longest leading block chain, or None (no affinity)."""
        alive = {r.replica_id for r in self.live}
        for h in reversed(self._chain_hashes(prompt)):
            rid = self._prefix_owner.get(h)
            if rid is not None and rid in alive:
                return rid
        return None

    # ------------------------------------------------------------ policy
    def submit(self, prompt: List[int], max_new_tokens: int, **kwargs):
        """Dispatch one request: prefix-affinity first, then least
        loaded; on Backpressure retry the remaining live replicas in
        load order. Returns the admitted :class:`Sequence` (its engine's
        replica id is on ``seq.request``'s serve events) or the LAST
        :class:`Backpressure` when the whole fleet shed. ``req_id`` is
        router-assigned (globally unique across replicas) unless the
        caller pins one (journal replay)."""
        with self._lock:
            req_id = kwargs.pop("req_id", None)
            if req_id is None:
                req_id = self._next_req_id
            self._next_req_id = max(self._next_req_id, req_id + 1)
            chains = self._chain_hashes(prompt)
            affinity = self.affinity_replica(prompt)
            by_load = sorted(
                self.live, key=lambda r: r.load() + (r.replica_id,)
            )
            if not by_load:
                raise RuntimeError("no live replicas in the fleet")
            order = list(by_load)
            if affinity is not None:
                order.sort(key=lambda r: r.replica_id != affinity)
        bp = None
        for attempt, handle in enumerate(order):
            # NOT under handle.lock: ``ServeEngine.submit`` only appends
            # to the scheduler's waiting deque and reads load state —
            # safe against a concurrent tick under the GIL (the deadline
            # counter has its own lock). Serializing submits behind the
            # replica's tick lock starved admission so badly that fleet
            # batches never filled (4x the ticks for the same tokens).
            # count_shed=False: a rejection the router retries is not a
            # client-visible shed — fleet-level rejections are counted
            # (and journaled) by the fleet bench instead.
            res = handle.engine.submit(
                prompt, max_new_tokens, req_id=req_id,
                count_shed=False, **kwargs
            )
            if isinstance(res, Backpressure):
                bp = res
                with self._lock:
                    handle.stats.sheds += 1
                    if not res.draining and attempt + 1 < len(order):
                        self.retries_elsewhere += 1
                continue
            with self._lock:
                handle.stats.dispatches += 1
                if affinity is not None and handle.replica_id == affinity:
                    handle.stats.affinity_dispatches += 1
                if attempt > 0:
                    handle.stats.retries_taken += 1
                self._remember(chains, handle.replica_id)
            return res
        with self._lock:
            self.rejected += 1
        return bp

    def begin_drain(self) -> None:
        """Drain the whole fleet (the SIGTERM handler's target): every
        live replica stops admitting and finishes in-flight work."""
        for handle in self.live:
            with handle.lock:
                handle.engine.begin_drain()

    def fail_replica(self, replica_id: int) -> None:
        """A replica crashed (or was killed): drop it from dispatch.
        Its incomplete requests are NOT rerouted here — they live in its
        journal namespace, and recovery is the same journal replay a
        single-engine crash uses (``restore_replica`` + re-submission
        with original req_ids keeps them token-exact)."""
        handle = self.replica(replica_id)
        handle.alive = False
        logger.log_event(
            "serve-replica-failed", replica=replica_id,
            running=len(handle.engine.scheduler.running),
            waiting=len(handle.engine.scheduler.waiting),
        )

    def restore_replica(self, replica_id: int, engine) -> ReplicaHandle:
        """Re-register a relaunched engine under a failed replica's id
        (stats continue; the caller replays the replica's journal into
        the fresh engine before opening it to new dispatch)."""
        handle = self.replica(replica_id)
        if handle.alive:
            raise ValueError(f"replica {replica_id} is still live")
        handle.engine = engine
        handle.alive = True
        logger.log_event("serve-replica-restored", replica=replica_id)
        return handle

    # --------------------------------------------------------- telemetry
    @property
    def has_work(self) -> bool:
        return any(r.engine.scheduler.has_work for r in self.live)

    def sync_next_req_id(self) -> None:
        """After journal replay seeded engines with historical ids, the
        router's id counter must move past every engine's (ids are the
        sampler-key fold — a collision would alias two requests)."""
        with self._lock:
            for r in self.replicas:
                self._next_req_id = max(
                    self._next_req_id, r.engine._next_req_id
                )

    def stats(self) -> dict:
        """Router dispatch stats for the ``serve-summary`` /
        ``obs report`` fleet section."""
        with self._lock:
            per = {
                r.replica_id: r.stats.to_dict() for r in self.replicas
            }
            dispatches = sum(s["dispatches"] for s in per.values())
            affinity = sum(s["affinity_dispatches"] for s in per.values())
            return {
                "replicas": len(self.replicas),
                "live_replicas": len(self.live),
                "dispatches": dispatches,
                "affinity_dispatches": affinity,
                "affinity_hit_rate": (
                    round(affinity / dispatches, 4) if dispatches else 0.0
                ),
                "retries_elsewhere": self.retries_elsewhere,
                "rejected": self.rejected,
                "per_replica": per,
            }


def install_fleet_drain_handler(router: FleetRouter) -> None:
    """SIGTERM -> drain the WHOLE fleet, chaining any prior handler —
    the fleet mirror of ``engine.install_drain_handler``: every replica
    stops admitting, in-flight requests finish or hit their deadlines,
    and the fleet bench exits 0 with a complete run dir."""
    import signal

    prev = signal.getsignal(signal.SIGTERM)

    def handler(signum, frame):
        router.begin_drain()
        if callable(prev):  # SIG_DFL/SIG_IGN are enum ints, skipped
            prev(signum, frame)

    signal.signal(signal.SIGTERM, handler)
