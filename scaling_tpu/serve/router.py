"""The fleet router: N data-parallel engine replicas behind one dispatcher.

The scale-OUT half of serving (docs/SERVING.md "The fleet"): one engine
was pushed to 45k tokens/s/chip; the next multiplicative win is N
engines. Every signal this router consumes landed with the resilience
work — structured :class:`~.scheduler.Backpressure` from watermark
admission, the ``serve_pool_pressure`` gauge, SIGTERM drain, and the
per-replica crash-replay journal — so the router is pure dispatch
policy over :class:`~.engine.ServeEngine` replicas:

- **least-loaded dispatch**: a new request goes to the live replica
  with the smallest (queue depth, pool pressure) — exactly the numbers
  the ``serve_waiting_seqs`` / ``serve_pool_pressure`` gauges export,
  so the router and a post-mortem read the same load signal;
- **prefix-affinity dispatch**: the router hashes the prompt's leading
  FULL blocks (the prefix trie's sharing granularity — a partial block
  can never be reused, scheduler.PrefixCache) and remembers which
  replica last served each block-chain; a prompt whose longest hashed
  chain maps to a live replica goes there, so a prompt family's shared
  system prefix is prefilled once per REPLICA instead of once per
  request-shuffle. Hash-based rather than trie-introspecting on
  purpose: the policy needs nothing but the prompt bytes, so it holds
  across process boundaries when replicas move out-of-process;
- **retry-elsewhere**: a replica answering ``submit`` with
  :class:`Backpressure` is not the fleet saying no — the router retries
  the remaining live replicas in load order and only surfaces
  Backpressure when EVERY replica shed (the client-visible overload
  signal);
- **drain fan-out**: ``begin_drain`` drains every replica (the fleet
  mirror of single-engine SIGTERM drain — the bench wires the handler);
- **replica failure**: ``fail_replica`` drops a crashed replica from
  dispatch (its in-flight work is recoverable from its OWN journal
  namespace — serve/journal.py ``journal_path``); ``restore_replica``
  re-registers a relaunched engine under the same id.

Pure host-side policy, jax-free at import: the engines own the device
programs. Thread-safety: all dispatch state mutates under one router
lock; per-replica engine calls are serialized by the per-replica locks
the fleet bench's tick threads share (``ReplicaHandle.lock``).

Since PR 16 the router dispatches through the HANDLE surface
(``submit`` / ``begin_drain`` / ``has_work`` / ``queue_sizes`` /
``next_req_id``) instead of reaching into ``handle.engine`` — the seam
that lets :mod:`.replica_proc`'s subprocess replicas slot in behind the
same policy: a process-backed handle answers the same calls over
line-JSON RPC, and the hash-based prefix affinity (PYTHONHASHSEED-
independent int-tuple hashes) holds across the process boundary by
construction. A handle whose replica process died mid-call raises
:class:`ReplicaUnreachable`; the router treats that exactly like
Backpressure — try the next live replica — and leaves the
dead/hung/relaunch decision to the fleet supervisor
(``replica_proc.FleetSupervisor``).

Host mode adds the partition-tolerance rule: a submit whose transport
failure happened AFTER the request left this host (the client tags
``maybe_admitted`` on :class:`ReplicaUnreachable`) may have been
admitted with only its reply lost. Re-dispatching it to another replica
could serve it TWICE (double compute, inflated counters), so the router
parks it IN DOUBT (:class:`InDoubtAdmit`) pinned to that replica and
re-offers it there every supervisor tick: a healed partition answers
``dup``/admitted and the park clears; a definitive Backpressure sends
it back through normal dispatch; and if the replica is declared dead,
the supervisor arbitrates the park against the dead journal
(``take_in_doubt`` + ``journal.submitted_ids``) — admitted requests ride
the normal journal failover, never-admitted ones re-enter as orphans.
Exactly-once admission either way. A connection REFUSED before the
request was sent is unambiguous and keeps the old retry-elsewhere path.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Optional, Tuple

from .. import obs
from ..logging import logger
from .scheduler import Backpressure

# bound on the remembered prefix chains: LRU beyond this (a router that
# never forgets would grow with every distinct prompt ever served)
PREFIX_MAP_CAP = 4096


class ReplicaUnreachable(OSError):
    """A replica's RPC channel is gone (process dead, socket refused,
    retries exhausted). Raised by process-backed handles; the router's
    dispatch loop skips the replica like a Backpressure answer and the
    supervisor's liveness pass owns the failover. The client sets
    ``maybe_admitted=True`` when any attempt got past send — the op may
    have executed remotely with only the reply lost (a submit in this
    state is parked in doubt, never re-dispatched elsewhere)."""

    maybe_admitted = False


class InDoubtAdmit:
    """A submit whose RPC died after the request left this host: the
    pinned replica may or may not have admitted it. The router owns it
    from here (``resolve_in_doubt`` / failover arbitration); callers
    treat it like an admit — the request is neither shed nor free to
    re-submit."""

    __slots__ = ("req_id", "replica_id")

    def __init__(self, req_id: int, replica_id: int):
        self.req_id = req_id
        self.replica_id = replica_id


@dataclasses.dataclass
class ReplicaStats:
    """Per-replica dispatch accounting (rendered by ``obs report``'s
    fleet rows and the ``serve-summary``'s ``replica_stats``)."""

    dispatches: int = 0
    affinity_dispatches: int = 0
    retries_taken: int = 0  # dispatches received as someone's retry
    sheds: int = 0  # Backpressure answers this replica returned

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class ReplicaHandle:
    """One engine replica as the router sees it: the engine, its id,
    a liveness flag, and the lock the fleet bench's tick thread and the
    router's submit path share (engine state is single-writer)."""

    def __init__(self, engine, replica_id: int):
        if engine.replica_id != replica_id:
            raise ValueError(
                f"engine carries replica_id {engine.replica_id!r} but the "
                f"router registers it as {replica_id} — set "
                "EngineConfig.replica_id so telemetry and journal "
                "namespaces agree with dispatch"
            )
        self.engine = engine
        self.replica_id = replica_id
        self.alive = True
        self.lock = threading.Lock()
        self.stats = ReplicaStats()

    def load(self) -> Tuple[int, float]:
        """(queue depth, pool pressure) — the least-loaded sort key.
        Queue depth counts waiting AND running (a replica with free
        slots but a deep backlog is not 'less loaded' than an idle
        one); pool pressure breaks ties the way the shed watermarks
        would."""
        sched = self.engine.scheduler
        depth = len(sched.waiting) + len(sched.running)
        return depth, sched.pool_pressure()

    # -- the engine-facing surface the router dispatches through; a
    # -- process-backed handle (replica_proc.ProcReplicaHandle)
    # -- overrides exactly these with RPC calls
    @property
    def block_size(self) -> int:
        return self.engine.config.block_size

    def submit(self, prompt: List[int], max_new_tokens: int, **kwargs):
        """Engine admission — Sequence on admit, Backpressure on shed.
        NOT under ``self.lock``: ``ServeEngine.submit`` only appends to
        the scheduler's waiting deque and reads load state, safe
        against a concurrent tick under the GIL (serializing submits
        behind the tick lock starved fleet admission — PR 14)."""
        return self.engine.submit(prompt, max_new_tokens, **kwargs)

    def begin_drain(self) -> None:
        with self.lock:
            self.engine.begin_drain()

    @property
    def has_work(self) -> bool:
        return self.engine.scheduler.has_work

    def next_req_id(self) -> int:
        return self.engine._next_req_id

    def queue_sizes(self) -> Tuple[int, int]:
        """(running, waiting) — the failure event's context fields."""
        sched = self.engine.scheduler
        return len(sched.running), len(sched.waiting)


class FleetRouter:
    """Dispatch policy over N :class:`ServeEngine` replicas."""

    def __init__(self, engines: Optional[List] = None,
                 block_size: Optional[int] = None,
                 handles: Optional[List] = None):
        """Build from in-process ``engines`` (the PR 14 threaded fleet)
        or from pre-built ``handles`` (process-backed replicas — any
        object answering the :class:`ReplicaHandle` surface)."""
        if handles is None:
            if not engines:
                raise ValueError("a fleet needs at least one replica")
            handles = [
                ReplicaHandle(
                    e, e.replica_id if e.replica_id is not None else i
                )
                for i, e in enumerate(engines)
            ]
        if not handles:
            raise ValueError("a fleet needs at least one replica")
        self.replicas: List[ReplicaHandle] = list(handles)
        ids = [r.replica_id for r in self.replicas]
        if len(set(ids)) != len(ids):
            raise ValueError(
                f"duplicate replica ids {ids} — journal namespaces and "
                "telemetry labels would collide"
            )
        sizes = {r.block_size for r in self.replicas}
        if block_size is None:
            if len(sizes) != 1:
                raise ValueError(
                    f"replicas disagree on block_size ({sorted(sizes)}); "
                    "prefix-affinity hashes full blocks and needs ONE "
                    "granularity"
                )
            block_size = sizes.pop()
        self.block_size = block_size
        self._lock = threading.Lock()
        # prefix-chain hash -> replica id, insertion-ordered for LRU
        self._prefix_owner: Dict[int, int] = {}
        self._next_req_id = 0
        self.retries_elsewhere = 0
        self.rejected = 0  # submissions every live replica shed
        # req_id -> journal-submit-shaped record (+ "replica" pin) for
        # submits whose RPC died after send — exactly-once admission
        # bookkeeping (resolve_in_doubt / take_in_doubt)
        self._in_doubt: Dict[int, dict] = {}
        self.in_doubt_parks = 0  # total park events (telemetry)

    # ---------------------------------------------------------- plumbing
    @property
    def live(self) -> List[ReplicaHandle]:
        return [r for r in self.replicas if r.alive]

    def replica(self, replica_id: int) -> ReplicaHandle:
        for r in self.replicas:
            if r.replica_id == replica_id:
                return r
        raise KeyError(f"no replica {replica_id}")

    def _chain_hashes(self, prompt: List[int]) -> List[int]:
        """One hash per leading FULL block chain of ``prompt`` (chain i
        covers tokens [0, i*block_size)), longest last — mirroring the
        trie's path-from-root sharing rule, including its 'always leave
        one token to prefill' cap. Chains fold INCREMENTALLY (chain i =
        hash of (chain i-1, block i)) so a long prompt costs O(len),
        not O(len^2) rehashing on the dispatch path; int-tuple hashes
        are PYTHONHASHSEED-independent, so the mapping still holds
        across processes."""
        bs = self.block_size
        full = max(0, (len(prompt) - 1) // bs)
        chains: List[int] = []
        acc = 0
        for i in range(full):
            acc = hash((acc, tuple(prompt[i * bs:(i + 1) * bs])))
            chains.append(acc)
        return chains

    def _remember(self, chains: List[int], replica_id: int) -> None:
        for h in chains:
            self._prefix_owner.pop(h, None)  # re-insert = LRU refresh
            self._prefix_owner[h] = replica_id
        while len(self._prefix_owner) > PREFIX_MAP_CAP:
            self._prefix_owner.pop(next(iter(self._prefix_owner)))

    def affinity_replica(self, prompt: List[int]) -> Optional[int]:
        """The live replica whose trie most plausibly holds this
        prompt's longest leading block chain, or None (no affinity)."""
        alive = {r.replica_id for r in self.live}
        for h in reversed(self._chain_hashes(prompt)):
            rid = self._prefix_owner.get(h)
            if rid is not None and rid in alive:
                return rid
        return None

    # ------------------------------------------------------------ policy
    def submit(self, prompt: List[int], max_new_tokens: int, **kwargs):
        """Dispatch one request: prefix-affinity first, then least
        loaded; on Backpressure retry the remaining live replicas in
        load order. Returns the admitted :class:`Sequence` (its engine's
        replica id is on ``seq.request``'s serve events) or the LAST
        :class:`Backpressure` when the whole fleet shed. ``req_id`` is
        router-assigned (globally unique across replicas) unless the
        caller pins one (journal replay)."""
        with self._lock:
            req_id = kwargs.pop("req_id", None)
            if req_id is None:
                req_id = self._next_req_id
            self._next_req_id = max(self._next_req_id, req_id + 1)
            chains = self._chain_hashes(prompt)
            affinity = self.affinity_replica(prompt)
            by_load = sorted(
                self.live, key=lambda r: r.load() + (r.replica_id,)
            )
            if not by_load:
                raise RuntimeError("no live replicas in the fleet")
            order = list(by_load)
            if affinity is not None:
                order.sort(key=lambda r: r.replica_id != affinity)
        bp = None
        for attempt, handle in enumerate(order):
            # count_shed=False: a rejection the router retries is not a
            # client-visible shed — fleet-level rejections are counted
            # (and journaled) by the fleet bench instead.
            try:
                res = handle.submit(
                    prompt, max_new_tokens, req_id=req_id,
                    count_shed=False, **kwargs
                )
            except ReplicaUnreachable as err:
                if getattr(err, "maybe_admitted", False):
                    # the request LEFT this host before the channel
                    # died: the replica may have admitted it with only
                    # the reply lost. Re-dispatching elsewhere risks
                    # serving it TWICE — park it pinned to this replica;
                    # resolve_in_doubt / failover arbitration finish the
                    # story exactly once.
                    with self._lock:
                        self._in_doubt[req_id] = self._park_record(
                            req_id, handle.replica_id, prompt,
                            max_new_tokens, kwargs,
                        )
                        self.in_doubt_parks += 1
                    logger.log_event(
                        "serve-submit-in-doubt", req=req_id,
                        replica=handle.replica_id,
                    )
                    return InDoubtAdmit(req_id, handle.replica_id)
                # connection refused before anything was sent: the
                # process died under us mid-dispatch and the request
                # unambiguously never reached it — skip it like a shed
                # (the supervisor's liveness pass will classify it and
                # run the journal failover) and try the next replica
                bp = Backpressure(
                    reason="replica-unreachable", pool_pressure=1.0,
                    waiting=0, draining=False,
                )
                with self._lock:
                    if attempt + 1 < len(order):
                        self.retries_elsewhere += 1
                continue
            if isinstance(res, Backpressure):
                bp = res
                with self._lock:
                    handle.stats.sheds += 1
                    if not res.draining and attempt + 1 < len(order):
                        self.retries_elsewhere += 1
                continue
            with self._lock:
                handle.stats.dispatches += 1
                if affinity is not None and handle.replica_id == affinity:
                    handle.stats.affinity_dispatches += 1
                if attempt > 0:
                    handle.stats.retries_taken += 1
                self._remember(chains, handle.replica_id)
            return res
        with self._lock:
            self.rejected += 1
        return bp

    @staticmethod
    def _park_record(req_id: int, replica_id: int, prompt: List[int],
                     max_new_tokens: int, kwargs: dict) -> dict:
        """The in-doubt park entry: shaped exactly like a journal submit
        record (plus the ``replica`` pin) so an unadmitted park can join
        the supervisor's orphan re-dispatch verbatim."""
        return {
            "kind": "serve-submit",
            "req": int(req_id),
            "replica": int(replica_id),
            "prompt": [int(t) for t in prompt],
            "max_new_tokens": int(max_new_tokens),
            "eos_token_id": kwargs.get("eos_token_id"),
            "temperature": kwargs.get("temperature", 0.0),
            "top_k": kwargs.get("top_k"),
            "top_p": kwargs.get("top_p"),
            "deadline_ms": kwargs.get("deadline_ms"),
            "ttft_deadline_ms": kwargs.get("ttft_deadline_ms"),
            # the originating request's trace rides the park so the
            # re-offer / failover re-dispatch inherits it (doubt-parking
            # must not break the one-request-one-trace invariant)
            "trace": obs.current_trace_id(),
        }

    def resolve_in_doubt(self) -> None:
        """Re-offer every parked in-doubt submit to its pinned replica
        (the supervisor calls this each tick). Idempotent submit makes
        the re-offer safe in every world: a replica that DID admit the
        original answers dup (park clears, nothing double-served); one
        that never saw it admits fresh (park clears); a definitive
        Backpressure proves not-admitted, so the request re-enters
        normal dispatch; a still-unreachable replica keeps the park for
        the next tick. Parks pinned to a dead replica are left for the
        failover's journal arbitration (``take_in_doubt``)."""
        if not self._in_doubt:
            return
        with self._lock:
            pending = list(self._in_doubt.values())
        for rec in pending:
            try:
                handle = self.replica(rec["replica"])
            except KeyError:
                continue
            if not handle.alive:
                continue
            kw = {
                k: rec.get(k)
                for k in ("eos_token_id", "temperature", "top_k",
                          "top_p", "deadline_ms", "ttft_deadline_ms")
            }
            # re-offers run on the supervisor's thread: adopt the parked
            # request's trace so the retry RPC (and an eventual fresh
            # admission) lands on the ORIGINAL trace, not a new one
            with obs.trace_context(rec.get("trace")):
                try:
                    res = handle.submit(
                        rec["prompt"], rec["max_new_tokens"],
                        req_id=rec["req"], count_shed=False, **kw,
                    )
                except ReplicaUnreachable:
                    continue  # still partitioned: parked until next tick
                with self._lock:
                    self._in_doubt.pop(rec["req"], None)
                if isinstance(res, Backpressure):
                    # definitive NOT-admitted: the original send never
                    # landed in the engine. The caller was already told
                    # "admitted", so ownership stands — force it through
                    # normal dispatch like an orphan (recovery work is
                    # never shed).
                    out = self.submit(
                        rec["prompt"], rec["max_new_tokens"],
                        req_id=rec["req"], force=True, **kw,
                    )
                    if isinstance(out, Backpressure):
                        with self._lock:  # nothing reachable: re-park
                            self._in_doubt[rec["req"]] = rec
                    continue
                logger.log_event(
                    "serve-in-doubt-resolved", req=rec["req"],
                    replica=rec["replica"],
                )

    def take_in_doubt(self, replica_id: int) -> List[dict]:
        """Pop every in-doubt submit parked on ``replica_id`` — the
        supervisor calls this at failover and arbitrates each record
        against the dead replica's journal (``journal.submitted_ids``):
        admitted -> the journal replay already owns it; never admitted
        -> the parked record (journal-submit-shaped by construction)
        joins the orphan re-dispatch. Either way, exactly once."""
        with self._lock:
            taken = [
                rec for rec in self._in_doubt.values()
                if rec["replica"] == replica_id
            ]
            for rec in taken:
                self._in_doubt.pop(rec["req"], None)
        return taken

    def begin_drain(self) -> None:
        """Drain the whole fleet (the SIGTERM handler's target): every
        live replica stops admitting and finishes in-flight work."""
        for handle in self.live:
            handle.begin_drain()

    def fail_replica(self, replica_id: int) -> None:
        """A replica crashed (or was killed): drop it from dispatch.
        Its incomplete requests are NOT rerouted here — they live in its
        journal namespace, and recovery is the same journal replay a
        single-engine crash uses (``restore_replica`` + re-submission
        with original req_ids keeps them token-exact)."""
        handle = self.replica(replica_id)
        handle.alive = False
        try:
            running, waiting = handle.queue_sizes()
        except (ReplicaUnreachable, OSError):
            running = waiting = -1  # dead process: last-known is gone
        logger.log_event(
            "serve-replica-failed", replica=replica_id,
            running=running, waiting=waiting,
        )

    def restore_replica(self, replica_id: int,
                        engine=None) -> ReplicaHandle:
        """Re-register a relaunched replica under a failed replica's id
        (stats continue; the caller replays the replica's journal into
        the fresh engine before opening it to new dispatch). In-process
        fleets pass the fresh ``engine``; process fleets rebind the
        handle's RPC channel themselves and pass None."""
        handle = self.replica(replica_id)
        if handle.alive:
            raise ValueError(f"replica {replica_id} is still live")
        if engine is not None:
            handle.engine = engine
        handle.alive = True
        logger.log_event("serve-replica-restored", replica=replica_id)
        return handle

    def add_replica(self, handle: ReplicaHandle) -> ReplicaHandle:
        """Register a NEW replica (autoscale spawn) — the id must be
        fresh; journal namespaces and telemetry labels key on it."""
        with self._lock:
            if handle.replica_id in {r.replica_id for r in self.replicas}:
                raise ValueError(
                    f"replica id {handle.replica_id} already registered"
                )
            if handle.block_size != self.block_size:
                raise ValueError(
                    f"new replica block_size {handle.block_size} != fleet "
                    f"{self.block_size} — prefix affinity needs ONE "
                    "granularity"
                )
            self.replicas.append(handle)
        logger.log_event("serve-replica-spawn", replica=handle.replica_id)
        return handle

    # --------------------------------------------------------- telemetry
    @property
    def has_work(self) -> bool:
        # an in-doubt park is pending work even when every engine's
        # queues are empty — the bench must not declare the run done
        # while an admission is unresolved
        return any(r.has_work for r in self.live) or bool(self._in_doubt)

    def sync_next_req_id(self) -> None:
        """After journal replay seeded engines with historical ids, the
        router's id counter must move past every engine's (ids are the
        sampler-key fold — a collision would alias two requests)."""
        with self._lock:
            for r in self.replicas:
                if not r.alive:
                    continue
                self._next_req_id = max(
                    self._next_req_id, r.next_req_id()
                )

    def stats(self) -> dict:
        """Router dispatch stats for the ``serve-summary`` /
        ``obs report`` fleet section."""
        with self._lock:
            per = {
                r.replica_id: r.stats.to_dict() for r in self.replicas
            }
            dispatches = sum(s["dispatches"] for s in per.values())
            affinity = sum(s["affinity_dispatches"] for s in per.values())
            return {
                "replicas": len(self.replicas),
                "live_replicas": len(self.live),
                "dispatches": dispatches,
                "affinity_dispatches": affinity,
                "affinity_hit_rate": (
                    round(affinity / dispatches, 4) if dispatches else 0.0
                ),
                "retries_elsewhere": self.retries_elsewhere,
                "rejected": self.rejected,
                "in_doubt_parks": self.in_doubt_parks,
                "in_doubt_pending": len(self._in_doubt),
                "per_replica": per,
            }


class AutoscalePolicy:
    """Pure host-side autoscaling policy: watermark hysteresis over the
    fleet's load snapshot, budgeted like supervisor relaunches.

    ``decide(now, replicas)`` consumes a snapshot — one dict per replica
    with ``replica`` (id), ``queue_depth``, ``pool_pressure``,
    ``in_flight``, ``alive`` — and returns ``None`` (hold),
    ``("spawn", None)``, or ``("drain", replica_id)``. No clocks, no
    I/O: the caller stamps ``now`` (``time.monotonic()``), so every
    branch is unit-testable with literal timestamps.

    - **spawn** when EVERY live replica is above the high watermark
      (``pool_pressure >= high_watermark`` or ``queue_depth >=
      queue_high``) sustained for ``sustain_s`` — one hot replica is a
      dispatch-imbalance problem, not a capacity problem;
    - **drain** when the fleet is idle (zero queue, zero in-flight,
      pressure at/below ``low_watermark`` everywhere) sustained for
      ``idle_sustain_s`` — the highest-id live replica goes first
      (spawned last, coldest trie). A drain NEVER fires while any
      request is in flight and NEVER takes the fleet below
      ``min_replicas``;
    - both actions are budgeted (``spawn_budget`` / ``drain_budget``
      per run) and separated by ``cooldown_s`` so a noisy load signal
      can't flap the fleet.
    """

    def __init__(self, *, min_replicas: int = 1, max_replicas: int = 4,
                 high_watermark: float = 0.8, queue_high: int = 8,
                 low_watermark: float = 0.2, sustain_s: float = 2.0,
                 idle_sustain_s: float = 5.0, spawn_budget: int = 2,
                 drain_budget: int = 2, cooldown_s: float = 5.0):
        if min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if max_replicas < min_replicas:
            raise ValueError("max_replicas < min_replicas")
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.high_watermark = high_watermark
        self.queue_high = queue_high
        self.low_watermark = low_watermark
        self.sustain_s = sustain_s
        self.idle_sustain_s = idle_sustain_s
        self.spawn_budget = spawn_budget
        self.drain_budget = drain_budget
        self.cooldown_s = cooldown_s
        self.spawns = 0
        self.drains = 0
        self._high_since: Optional[float] = None
        self._idle_since: Optional[float] = None
        self._last_action: Optional[float] = None

    def _hot(self, r: dict) -> bool:
        return (r["pool_pressure"] >= self.high_watermark
                or r["queue_depth"] >= self.queue_high)

    def decide(self, now: float,
               replicas: List[dict]) -> Optional[Tuple[str, Optional[int]]]:
        live = [r for r in replicas if r.get("alive", True)]
        if not live:
            return None
        in_cooldown = (self._last_action is not None
                       and now - self._last_action < self.cooldown_s)

        overloaded = all(self._hot(r) for r in live)
        if overloaded:
            if self._high_since is None:
                self._high_since = now
        else:
            self._high_since = None

        idle = all(
            r["queue_depth"] == 0 and r["in_flight"] == 0
            and r["pool_pressure"] <= self.low_watermark
            for r in live
        )
        if idle:
            if self._idle_since is None:
                self._idle_since = now
        else:
            self._idle_since = None

        if in_cooldown:
            return None
        if (self._high_since is not None
                and now - self._high_since >= self.sustain_s
                and len(live) < self.max_replicas
                and self.spawns < self.spawn_budget):
            self.spawns += 1
            self._last_action = now
            self._high_since = None
            return ("spawn", None)
        if (self._idle_since is not None
                and now - self._idle_since >= self.idle_sustain_s
                and len(live) > self.min_replicas
                and self.drains < self.drain_budget):
            # in_flight == 0 everywhere is part of `idle` — an idle
            # drain can never abandon a running request
            self.drains += 1
            self._last_action = now
            self._idle_since = None
            target = max(r["replica"] for r in live)
            return ("drain", target)
        return None


def install_fleet_drain_handler(router: FleetRouter) -> None:
    """SIGTERM -> drain the WHOLE fleet, chaining any prior handler —
    the fleet mirror of ``engine.install_drain_handler``: every replica
    stops admitting, in-flight requests finish or hit their deadlines,
    and the fleet bench exits 0 with a complete run dir."""
    import signal

    prev = signal.getsignal(signal.SIGTERM)

    def handler(signum, frame):
        router.begin_drain()
        if callable(prev):  # SIG_DFL/SIG_IGN are enum ints, skipped
            prev(signum, frame)

    signal.signal(signal.SIGTERM, handler)
