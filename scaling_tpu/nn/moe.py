"""Mixture-of-Experts MLP with expert parallelism (beyond the reference).

The reference has no MoE (SURVEY §2.4: "EP absent; TPU build may treat as
out of scope or future mesh axis"); this is the future mesh axis built the
TPU way — the GShard/Switch dense-dispatch formulation:

- the router scores every token against ``num_experts`` experts; top-k
  gating with a Switch-style load-balance auxiliary loss;
- a static ``capacity_factor`` bounds tokens per expert, so every shape is
  static and the whole block is three einsums on the MXU (dispatch,
  expert FFN, combine) — no sorting, no ragged tensors, no host control
  flow;
- the expert dimension is sharded over the ``data`` mesh axis (canonical
  expert-parallel: EP reuses the DP devices) and the expert FFN's hidden
  dim over ``model``; GSPMD derives the token all-to-alls from these
  shardings, the same way the rest of the stack gets its collectives.

Dropped tokens (over capacity) fall through on the residual path, exactly
as in Switch Transformers (Fedus et al. 2021).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from .activation_function import ActivationFunction, get_activation_function
from .base_layer import BaseLayer, ForwardContext
from .param import ParamMeta
from ..topology.topology import DATA_AXIS, MODEL_AXIS


class ParallelMoEMLP(BaseLayer):
    """Top-k routed expert MLPs (SwiGLU or plain) behind one dense dispatch."""

    def __init__(
        self,
        io_features: int,
        intermediate_feature_factor: float,
        num_experts: int,
        top_k: int = 2,
        capacity_factor: float = 1.25,
        aux_loss_coef: float = 0.01,
        glu: bool = True,
        activation: ActivationFunction = ActivationFunction.SILU,
        dtype=None,
    ):
        dtype = dtype or jnp.float32
        intermediate = int(io_features * intermediate_feature_factor)
        assert float(intermediate) == io_features * intermediate_feature_factor
        assert 1 <= top_k <= num_experts
        self.io_features = io_features
        self.intermediate = intermediate
        self.num_experts = num_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.aux_loss_coef = aux_loss_coef
        self.glu = glu
        self.activation_fn = get_activation_function(activation)
        self.dtype = dtype

    # ------------------------------------------------------------------ init
    def init(self, key: jax.Array) -> dict:
        import math

        ks = jax.random.split(key, 4)
        E, h, f = self.num_experts, self.io_features, self.intermediate

        def expert_init(k, shape, dtype):
            # xavier over the PER-EXPERT matmul fans (the leading expert dim
            # is a batch dim, not a fan — feeding it to a 2-D initializer
            # over-scales every expert)
            _, fan_in, fan_out = shape
            std = math.sqrt(2.0 / (fan_in + fan_out))
            return (jax.random.normal(k, shape) * std).astype(dtype)

        params = {
            # router in fp32, near-zero init: routing starts ~uniform and the
            # decisions should not quantize (Switch Transformer practice)
            "router": {
                "weight": (jax.random.normal(ks[0], (h, E)) * 0.02).astype(
                    jnp.float32
                )
            },
            "w_in": expert_init(ks[1], (E, h, f), self.dtype),
            "w_out": expert_init(ks[2], (E, f, h), self.dtype),
        }
        if self.glu:
            params["w_gate"] = expert_init(ks[3], (E, h, f), self.dtype)
        return params

    def param_metas(self) -> dict:
        def expert_meta(name, spec):
            return ParamMeta(
                parameter_name=name,
                partition_spec=spec,
                is_model_parallel=True,
                model_parallel_dimension=spec.index(MODEL_AXIS),
            )

        metas = {
            "router": {
                "weight": ParamMeta(
                    parameter_name="router.weight",
                    partition_spec=(None, None),
                    is_model_parallel_duplicate=True,
                )
            },
            # experts over data (EP), ffn hidden over model (TP inside expert)
            "w_in": expert_meta("w_in", (DATA_AXIS, None, MODEL_AXIS)),
            "w_out": expert_meta("w_out", (DATA_AXIS, MODEL_AXIS, None)),
        }
        if self.glu:
            metas["w_gate"] = expert_meta("w_gate", (DATA_AXIS, None, MODEL_AXIS))
        return metas

    def __call__(
        self, params: dict, x: jax.Array, ctx: ForwardContext
    ) -> Tuple[jax.Array, jax.Array]:
        """Returns (output (b,s,h), aux_loss scalar — already coefficient-
        scaled, ready to add to the training loss)."""
        b, s, h = x.shape
        E, k = self.num_experts, self.top_k
        C = max(1, int(self.capacity_factor * k * s / E))

        router_w = params["router"]["weight"]
        logits = jnp.einsum("bsh,he->bse", x.astype(jnp.float32), router_w)
        probs = jax.nn.softmax(logits, axis=-1)  # (b, s, E)

        # Switch load-balance loss: E * sum_e mean_prob_e * assigned_frac_e,
        # with assignment fractions from the top-1 choice
        top1 = jnp.argmax(probs, axis=-1)
        assigned = jax.nn.one_hot(top1, E, dtype=jnp.float32)  # (b, s, E)
        aux = E * jnp.sum(probs.mean(axis=(0, 1)) * assigned.mean(axis=(0, 1)))
        aux = (aux * self.aux_loss_coef).astype(jnp.float32)

        # top-k choices per token, each with its gate weight
        gate_vals, gate_idx = jax.lax.top_k(probs, k)  # (b, s, k)
        gate_vals = gate_vals / jnp.maximum(
            gate_vals.sum(axis=-1, keepdims=True), 1e-9
        )

        # position of each (token, choice) in its expert's capacity buffer:
        # running count of prior tokens routed to the same expert. Choices
        # are flattened (s, k) -> priority order matches GShard's
        # token-major, choice-minor scan.
        choice_exp = jax.nn.one_hot(gate_idx, E, dtype=jnp.float32)  # (b,s,k,E)
        flat = choice_exp.reshape(b, s * k, E)
        position = jnp.cumsum(flat, axis=1) - flat  # prior count, (b, s*k, E)
        pos_in_exp = jnp.einsum("bte,bte->bt", position, flat).reshape(b, s, k)
        pos_in_exp = pos_in_exp.astype(jnp.int32)  # exact small counts
        keep = (pos_in_exp < C).astype(jnp.float32)  # dropped past capacity

        # dispatch/combine (b, s, E, C)
        pos_oh = jax.nn.one_hot(pos_in_exp, C, dtype=jnp.float32)  # (b,s,k,C)
        combine = jnp.einsum(
            "bsk,bsk,bske,bskc->bsec", gate_vals, keep, choice_exp, pos_oh
        )
        dispatch = jnp.einsum("bsk,bske,bskc->bsec", keep, choice_exp, pos_oh)

        xin = jnp.einsum("bsec,bsh->ebch", dispatch.astype(x.dtype), x)
        w_in = params["w_in"].astype(x.dtype)
        up = jnp.einsum("ebch,ehf->ebcf", xin, w_in)
        if self.glu:
            gate = jnp.einsum(
                "ebch,ehf->ebcf", xin, params["w_gate"].astype(x.dtype)
            )
            act = self.activation_fn(gate) * up
        else:
            act = self.activation_fn(up)
        out = jnp.einsum("ebcf,efh->ebch", act, params["w_out"].astype(x.dtype))
        y = jnp.einsum("bsec,ebch->bsh", combine.astype(x.dtype), out)
        return y, aux
