"""Tensor-parallel self attention.

Capability parity with the reference's ``ParallelSelfAttention``
(reference: src/scaling/core/nn/attention/attention.py:268-796): fused or
separate QKV (GQA via ``num_kv_heads``), rotary / rotary-complex, optional
key/query norm, sequence packing, causal + per-head local attention windows,
attention-probs dropout under MP-constant keys, LoRA injection on
query/key/value/dense, KV cache for incremental decode, row-parallel output
with sequence-parallel reduce-scatter.

TPU-first design choices:
- batch-major (b, s, n, h) instead of (s, b, n, h);
- sequence packing is carried as per-token segment ids (static shapes under
  jit) instead of varlen cu_seqlens; conversion helpers in seq_packing;
- the unfused path materialises the (b, n, s, s) scores through
  ``MaskedSoftmax`` (= reference 'torch' kernel); the fused path calls the
  Pallas flash-attention kernel with segment ids (= reference
  'flash_attention' kernel);
- head sharding over the model axis comes from GSPMD constraints on the
  column-parallel QKV outputs — no explicit head bookkeeping needed.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from .base_layer import BaseLayer, ForwardContext
from .linear import ColumnParallelLinear, RowParallelLinear, xavier_normal_init
from .lora import LoRAModuleType, LoRaConfig, ParallelLoRa
from .masked_softmax import MaskedSoftmax, MaskedSoftmaxConfig, MaskedSoftmaxKernel
from .norm import LayerNormConfig, NormType, get_norm
from .param import tree_prefix
from .rotary import (
    RelativePositionEmbeddingType,
    RotaryConfig,
    RotaryEmbedding,
    RotaryEmbeddingComplex,
)
from .seq_packing import segment_ids_to_mask


def repeat_kv(x: jax.Array, n_rep: int) -> jax.Array:
    """(b, s, n_kv, h) -> (b, s, n_kv * n_rep, h) for GQA."""
    if n_rep == 1:
        return x
    b, s, n_kv, h = x.shape
    x = jnp.broadcast_to(x[:, :, :, None, :], (b, s, n_kv, n_rep, h))
    return x.reshape(b, s, n_kv * n_rep, h)


class PagedKVCacheView(NamedTuple):
    """One layer's slice of the serving engine's block-paged KV pool
    (serve/kvcache.py), plus the batch's addressing state.

    The pool is a device-resident buffer of fixed-size blocks shared by
    every in-flight sequence (PagedAttention, SOSP '23); each decode row
    addresses its scattered blocks through ``block_table`` and its
    logical length through ``context_len``. Block 0 is the TRASH block:
    never allocated to content, it absorbs writes from inactive rows and
    padding so the jitted decode step needs no per-row branching.

    ``pool_k``/``pool_v`` are ``(num_blocks, block_size, n_kv, h)``;
    float (dense) or int8 with per-slot-per-head ``scale_k``/``scale_v``
    of shape ``(num_blocks, block_size, n_kv)`` (quantized KV).

    ``new_len`` (per row, optional) is how many of the ``s`` presented
    tokens are REAL: a prefill CHUNK padded to its fixed program shape
    routes its pad tokens' KV to the trash block and excludes their
    slots from every mask, so one compiled chunk program serves every
    chunk length (Sarathi-style chunked prefill, serve/engine.py).
    ``None`` means all ``s`` tokens are real (the decode step).
    """

    pool_k: jax.Array
    pool_v: jax.Array
    block_table: jax.Array  # (b, max_blocks) int32 block ids; 0 = trash
    context_len: jax.Array  # (b,) int32 tokens already cached per row
    scale_k: Optional[jax.Array] = None
    scale_v: Optional[jax.Array] = None
    new_len: Optional[jax.Array] = None  # (b,) int32 real tokens among s

    @property
    def quantized(self) -> bool:
        return self.scale_k is not None


def kv_quantize_int8(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Symmetric per-token-per-head int8: ``x`` (..., n_kv, h) -> (q, scale)
    with ``scale`` (..., n_kv). The ONE quantizer both the prefill pool
    writer (serve/kvcache.py) and the decode-step write below use, so the
    cache a prompt left behind and the cache decode appends to can never
    disagree about the rounding."""
    xf = x.astype(jnp.float32)
    scale = jnp.max(jnp.abs(xf), axis=-1) / 127.0
    scale = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(xf / scale[..., None]), -127, 127).astype(jnp.int8)
    return q, scale


def kv_dequantize_int8(q: jax.Array, scale: jax.Array, dtype) -> jax.Array:
    return (q.astype(jnp.float32) * scale[..., None].astype(jnp.float32)).astype(dtype)


def paged_flat_slots(block_table: jax.Array, positions: jax.Array,
                     block_size: int) -> jax.Array:
    """Map per-row logical token ``positions`` (b, s) to flat pool slots
    ``block_id * block_size + offset`` via each row's block table.
    Positions past the table's reach route into the trash block (id 0 by
    convention sits at flat slots [0, block_size)) — NEVER into the
    row's last real block, where a clamped write would silently corrupt
    live cache."""
    max_blocks = block_table.shape[1]
    blk_idx = positions // block_size
    blocks = jnp.take_along_axis(
        block_table, jnp.clip(blk_idx, 0, max_blocks - 1), axis=1
    )
    blocks = jnp.where(blk_idx < max_blocks, blocks, 0)
    return blocks * block_size + positions % block_size


def paged_scatter_kv(view: PagedKVCacheView, flat: jax.Array,
                     k_rows: jax.Array, v_rows: jax.Array) -> PagedKVCacheView:
    """Scatter new K/V rows (``(n, n_kv, h)``) into the pool at flat
    slots ``flat`` (``(n,)``), quantizing when the pool is int8 — the ONE
    pool writer shared by the decode step (``_paged_attention``) and the
    prefill writer (serve/kvcache.py), so the cache a prompt left behind
    and the cache decode appends to can never disagree about layout or
    rounding. Returns the view with updated pools (tables/lengths
    untouched)."""
    num_blocks, block_size = view.pool_k.shape[0], view.pool_k.shape[1]
    flat_len = num_blocks * block_size
    pk = view.pool_k.reshape(flat_len, *view.pool_k.shape[2:])
    pv = view.pool_v.reshape(flat_len, *view.pool_v.shape[2:])
    scale_k, scale_v = view.scale_k, view.scale_v
    if view.quantized:
        qk, sk = kv_quantize_int8(k_rows)
        qv, sv = kv_quantize_int8(v_rows)
        pk = pk.at[flat].set(qk)
        pv = pv.at[flat].set(qv)
        scale_k = view.scale_k.reshape(flat_len, -1)
        scale_v = view.scale_v.reshape(flat_len, -1)
        scale_k = scale_k.at[flat].set(sk).reshape(view.scale_k.shape)
        scale_v = scale_v.at[flat].set(sv).reshape(view.scale_v.shape)
    else:
        pk = pk.at[flat].set(k_rows.astype(pk.dtype))
        pv = pv.at[flat].set(v_rows.astype(pv.dtype))
    return view._replace(
        pool_k=pk.reshape(view.pool_k.shape),
        pool_v=pv.reshape(view.pool_v.shape),
        scale_k=scale_k, scale_v=scale_v,
    )


def flash_path_active(
    *,
    kernel_is_flash: bool,
    causal: bool,
    dropout_attention_probs: float,
    deterministic: bool,
    context_parallel_size: int,
    seq_len: int,
    head_dim: int,
    has_kv_cache: bool = False,
    has_scores_manipulation: bool = False,
) -> bool:
    """Single source of truth for the flash-vs-XLA kernel gate.

    ``ParallelSelfAttention.__call__`` decides through this, and bench.py
    reports through it, so the artifact's ``kernel`` label cannot drift
    from the path that actually ran (mirrors the reference's kernel switch,
    masked_softmax_config.py:8-37)."""
    if not kernel_is_flash or has_kv_cache or has_scores_manipulation:
        return False
    if not causal or context_parallel_size > 1:
        return False
    if dropout_attention_probs > 0.0 and not deterministic:
        return False
    from ..ops.flash_attention import flash_attention_supported

    return flash_attention_supported(seq_len, head_dim)


def multi_head_attention(
    query: jax.Array,  # (b, s_q, n, h)
    key: jax.Array,  # (b, s_k, n, h)
    value: jax.Array,  # (b, s_k, n, h)
    mask: jax.Array,  # (b, 1, s_q, s_k) True = forbidden
    scaling_factor: float,
    softmax: MaskedSoftmax,
    dropout_fn: Optional[Callable[[jax.Array], jax.Array]] = None,
    attention_scores_manipulation: Optional[jax.Array] = None,
    scores_manipulation_log_additive: bool = True,
) -> jax.Array:
    """Unfused attention: QK^T -> masked softmax -> PV. Returns (b, s_q, n, h)."""
    scores = jnp.einsum("bqnh,bknh->bnqk", query, key) * scaling_factor
    if attention_scores_manipulation is not None:
        m = attention_scores_manipulation.astype(scores.dtype)
        if scores_manipulation_log_additive:
            scores = scores + m
        else:
            # multiplicative variant (reference attention.py:166-170):
            # shift so the minimum UNMASKED score is 0, then scale — the
            # factors act on a non-negative score range
            filled = jnp.where(mask, jnp.asarray(10000.0, scores.dtype), scores)
            scores = (scores - jnp.min(filled, axis=-1, keepdims=True)) * m
    probs = softmax(scores, mask)
    if dropout_fn is not None:
        probs = dropout_fn(probs)
    out = jnp.einsum("bnqk,bknh->bqnh", probs.astype(value.dtype), value)
    return out


class ParallelSelfAttention(BaseLayer):
    def __init__(
        self,
        hidden_size: int,
        num_attention_heads: int,
        masked_softmax_config: Optional[MaskedSoftmaxConfig] = None,
        causal: bool = True,
        num_local_attention_heads: int = 0,
        local_attention_window_size: Optional[int] = None,
        scaling_factor: Optional[float] = None,
        dropout_attention_probs: float = 0.0,
        rotary_config: Optional[RotaryConfig] = None,
        relative_position_embedding_type: str = RelativePositionEmbeddingType.ROTARY,
        bias: bool = True,
        dtype=jnp.float32,
        init_method: Callable = xavier_normal_init,
        bitfit_bias_name: Optional[str] = None,
        lora_config: Optional[LoRaConfig] = None,
        norm_type: NormType = NormType.LAYERNORM,
        key_query_norm: bool = False,
        layernorm_config: Optional[LayerNormConfig] = None,
        qkv_in_one: bool = True,
        num_kv_heads: Optional[int] = None,
    ):
        assert hidden_size % num_attention_heads == 0, (
            f"hidden size ({hidden_size}) must be divisible by "
            f"num_attention_heads ({num_attention_heads})"
        )
        self.hidden_size = hidden_size
        self.num_attention_heads = num_attention_heads
        self.head_dim = hidden_size // num_attention_heads
        self.causal = causal
        self.masked_softmax_config = masked_softmax_config or MaskedSoftmaxConfig()
        self.use_flash = self.masked_softmax_config.kernel == MaskedSoftmaxKernel.FLASH_ATTENTION
        self.num_local_attention_heads = num_local_attention_heads
        self.local_attention_window_size = local_attention_window_size
        if num_local_attention_heads > 0:
            assert local_attention_window_size is not None, (
                "local_attention_window_size needs to be set if num_local_attention_heads"
            )
        self.dropout_attention_probs = dropout_attention_probs
        self.scaling_factor = (
            scaling_factor if scaling_factor is not None else 1.0 / math.sqrt(self.head_dim)
        )
        self.dtype = dtype

        self.qkv_in_one = qkv_in_one
        self.num_kv_heads = num_kv_heads
        if num_kv_heads:
            assert not qkv_in_one, "for a differing number of kv heads, qkv cannot be stored in one"
            assert num_attention_heads % num_kv_heads == 0
            self.num_repeat_kv = num_attention_heads // num_kv_heads
        else:
            self.num_kv_heads = num_attention_heads
            self.num_repeat_kv = 1

        common = dict(bias=bias, dtype=dtype, init_method=init_method,
                      bitfit_bias_name=bitfit_bias_name)
        if qkv_in_one:
            self.query_key_value = ColumnParallelLinear(
                hidden_size, hidden_size * 3, parallel_output=True, **common
            )
        else:
            kv_size = self.num_kv_heads * self.head_dim
            self.query = ColumnParallelLinear(hidden_size, hidden_size, parallel_output=True, **common)
            self.key = ColumnParallelLinear(hidden_size, kv_size, parallel_output=True, **common)
            self.value = ColumnParallelLinear(hidden_size, kv_size, parallel_output=True, **common)

        self.dense = RowParallelLinear(
            hidden_size, hidden_size, parallel_input=True, parallel_output=True, **common
        )

        # rotary
        self.rotary_embedding: Any = None
        if relative_position_embedding_type == RelativePositionEmbeddingType.ROTARY:
            assert rotary_config is not None
            self.rotary_embedding = RotaryEmbedding(rotary_config)
        elif relative_position_embedding_type == RelativePositionEmbeddingType.ROTARY_COMPLEX:
            assert rotary_config is not None
            self.rotary_embedding = RotaryEmbeddingComplex(rotary_config)

        # key/query norm
        self.key_query_norm = key_query_norm
        if key_query_norm:
            self.norm_query = get_norm(norm_type, self.head_dim, layernorm_config, dtype, bitfit_bias_name)
            self.norm_key = get_norm(norm_type, self.head_dim, layernorm_config, dtype, bitfit_bias_name)

        self.masked_softmax = MaskedSoftmax(self.masked_softmax_config)

        # LoRA
        self.lora_config = lora_config
        self.lora_modules: Dict[str, ParallelLoRa] = {}
        if lora_config:
            for module_type in lora_config.parallel_modules:
                if module_type in (LoRAModuleType.DENSE, LoRAModuleType.QUERY):
                    out_features = hidden_size
                else:
                    out_features = self.num_kv_heads * self.head_dim
                self.lora_modules[f"{module_type.value}_{lora_config.name}"] = ParallelLoRa(
                    in_features=hidden_size,
                    out_features=out_features,
                    rank=lora_config.rank,
                    lora_module_type=module_type,
                    alpha=lora_config.alpha,
                    dropout=lora_config.dropout,
                    bias=lora_config.bias,
                    kaiming_a=lora_config.kaiming_a,
                    dtype=dtype,
                    name=lora_config.name,
                )

    # ------------------------------------------------------------------ init
    def init(self, key: jax.Array) -> dict:
        keys = jax.random.split(key, 8)
        params: dict = {}
        if self.qkv_in_one:
            params["query_key_value"] = self.query_key_value.init(keys[0])
        else:
            params["query"] = self.query.init(keys[0])
            params["key"] = self.key.init(keys[1])
            params["value"] = self.value.init(keys[2])
        params["dense"] = self.dense.init(keys[3])
        if self.key_query_norm:
            params["norm_query"] = self.norm_query.init(keys[4])
            params["norm_key"] = self.norm_key.init(keys[5])
        for i, (name, mod) in enumerate(sorted(self.lora_modules.items())):
            params[name] = mod.init(jax.random.fold_in(keys[6], i))
        return params

    def param_metas(self) -> dict:
        metas: dict = {}
        if self.qkv_in_one:
            metas["query_key_value"] = tree_prefix(self.query_key_value.param_metas(), "query_key_value")
        else:
            metas["query"] = tree_prefix(self.query.param_metas(), "query")
            metas["key"] = tree_prefix(self.key.param_metas(), "key")
            metas["value"] = tree_prefix(self.value.param_metas(), "value")
        metas["dense"] = tree_prefix(self.dense.param_metas(), "dense")
        if self.key_query_norm:
            metas["norm_query"] = tree_prefix(self.norm_query.param_metas(), "norm_query")
            metas["norm_key"] = tree_prefix(self.norm_key.param_metas(), "norm_key")
        for name, mod in sorted(self.lora_modules.items()):
            metas[name] = tree_prefix(mod.param_metas(), name)
        return metas

    # --------------------------------------------------------------- forward
    def _qkv(self, params: dict, x: jax.Array, ctx: ForwardContext):
        b, s, _ = x.shape
        if self.qkv_in_one:
            qkv = self.query_key_value(params["query_key_value"], x, ctx)
            qkv = qkv.reshape(b, s, self.num_attention_heads, 3 * self.head_dim)
            q, k, v = jnp.split(qkv, 3, axis=-1)
        else:
            q = self.query(params["query"], x, ctx).reshape(b, s, self.num_attention_heads, self.head_dim)
            k = self.key(params["key"], x, ctx).reshape(b, s, self.num_kv_heads, self.head_dim)
            v = self.value(params["value"], x, ctx).reshape(b, s, self.num_kv_heads, self.head_dim)
        # LoRA deltas
        if self.lora_config:
            lc = self.lora_config
            for mt, arr, nheads in (
                (LoRAModuleType.QUERY, "q", self.num_attention_heads),
                (LoRAModuleType.KEY, "k", self.num_kv_heads),
                (LoRAModuleType.VALUE, "v", self.num_kv_heads),
            ):
                name = f"{mt.value}_{lc.name}"
                if name in self.lora_modules:
                    delta = self.lora_modules[name](params[name], x, ctx)
                    delta = delta.reshape(b, s, nheads, self.head_dim)
                    if arr == "q":
                        q = q + delta
                    elif arr == "k":
                        k = k + delta
                    else:
                        v = v + delta
        return q, k, v

    def __call__(
        self,
        params: dict,
        x: jax.Array,  # (b, s, hidden)
        ctx: ForwardContext,
        segment_ids: Optional[jax.Array] = None,  # (b, s) packed-doc ids
        position_ids: Optional[jax.Array] = None,  # (b, s)
        kv_cache: Optional[Tuple[jax.Array, jax.Array]] = None,
        cache_offset: Optional[jax.Array] = None,
        attention_scores_manipulation: Optional[jax.Array] = None,
        attention_scores_manipulation_log_additive: bool = True,
        return_kv: bool = False,
    ):
        b, s, _ = x.shape
        q, k, v = self._qkv(params, x, ctx)

        if self.key_query_norm:
            q = self.norm_query(params["norm_query"], q, ctx)
            k = self.norm_key(params["norm_key"], k, ctx)

        if self.rotary_embedding is not None:
            q, k = self.rotary_embedding(q, k, position_ids, position_ids)

        new_kv = (k, v) if return_kv else None

        if isinstance(kv_cache, PagedKVCacheView):
            # block-paged decode (serve/): append the new tokens' K/V into
            # the shared block pool at each row's next slots, then attend
            # over the row's gathered blocks. position_ids stays the rotary
            # clock (applied above); context_len is the causal clock.
            assert attention_scores_manipulation is None, (
                "attention_scores_manipulation is unsupported on the paged "
                "decode path"
            )
            assert self.num_local_attention_heads == 0, (
                "local-window heads are unsupported on the paged decode path"
            )
            out, new_view = self._paged_attention(q, k, v, kv_cache, b, s, ctx)
            return self._project_out(params, out, ctx, b, s, new_view)

        if kv_cache is not None:
            # incremental decode / token-slice pipelining: append new k/v at
            # cache_offset. A 3-tuple cache carries the cached slots'
            # segment ids too, so packed-document masking survives sequence
            # slicing (TeraPipe); the decode paths keep their 2-tuples and
            # the slots-only mask.
            cseg = None
            if len(kv_cache) == 3:
                ck, cv, cseg = kv_cache
            else:
                ck, cv = kv_cache
            assert cache_offset is not None
            ck = jax.lax.dynamic_update_slice_in_dim(ck, k.astype(ck.dtype), cache_offset, axis=1)
            cv = jax.lax.dynamic_update_slice_in_dim(cv, v.astype(cv.dtype), cache_offset, axis=1)
            k, v = ck, cv
            new_kv = (ck, cv)
            s_k = k.shape[1]
            # masking runs on CACHE SLOT indices, not on position_ids:
            # under left-padded (ragged) prompts a row's rotary positions
            # lag its slot indices by the pad width, and masking by rotary
            # position would forbid the most recent slots. position_ids
            # stays the rotary clock; slots are the causal clock.
            slots_k = jnp.broadcast_to(jnp.arange(s_k)[None, :], (b, s_k))
            slots_q = cache_offset + jnp.broadcast_to(
                jnp.arange(s)[None, :], (b, s)
            )
            # mask out unwritten cache slots + causal vs slot order
            valid_k = slots_k < (cache_offset + s)
            allowed = valid_k[:, None, :] & (slots_k[:, None, :] <= slots_q[:, :, None])
            if cseg is not None:
                seg_q = (
                    segment_ids
                    if segment_ids is not None
                    else jnp.zeros((b, s), jnp.int32)
                )
                cseg = jax.lax.dynamic_update_slice_in_dim(
                    cseg, seg_q.astype(cseg.dtype), cache_offset, axis=1
                )
                allowed = allowed & (cseg[:, None, :] == seg_q[:, :, None])
                new_kv = (ck, cv, cseg)
            mask = ~allowed[:, None, :, :]
        else:
            if segment_ids is None:
                segment_ids = jnp.zeros((b, s), dtype=jnp.int32)
            mask = segment_ids_to_mask(
                segment_ids, None, causal=self.causal,
                positions_q=None, positions_k=None,
            )

        dropout_fn = None
        if self.dropout_attention_probs > 0.0 and not ctx.deterministic:
            dropout_fn = lambda p: ctx.dropout(p, self.dropout_attention_probs)  # noqa: E731

        n_local = self.num_local_attention_heads

        # the flash (splash) kernel consumes UNREPEATED kv heads — the KV
        # bandwidth/memory win of GQA — and covers mixed local/global heads
        # via per-head masks; every other path repeats below
        use_flash_here = flash_path_active(
            kernel_is_flash=self.use_flash,
            causal=self.causal,
            dropout_attention_probs=self.dropout_attention_probs,
            deterministic=ctx.deterministic,
            context_parallel_size=ctx.context_parallel_size,
            seq_len=s,
            head_dim=self.head_dim,
            has_kv_cache=kv_cache is not None,
            has_scores_manipulation=attention_scores_manipulation is not None,
        )
        if use_flash_here:
            from ..ops.flash_attention import flash_attention_fused
            out = flash_attention_fused(
                q, k, v, segment_ids, causal=True, sm_scale=self.scaling_factor,
                num_local_heads=n_local,
                local_window=self.local_attention_window_size,
                mesh=ctx.mesh,
            )
            return self._project_out(params, out, ctx, b, s, new_kv)

        if ctx.context_parallel_size > 1 and kv_cache is None:
            # context parallelism: sequence sharded over the context mesh
            # axis. Two variants (topology.context_parallel_variant): 'ring'
            # rotates K/V blocks over ICI (ops/ring_attention.py); 'ulysses'
            # all-to-alls heads for sequence (ops/ulysses_attention.py).
            # Both are GQA-native — unrepeated KV cuts ICI traffic by the
            # group factor — but kv heads must still divide over the model
            # axis (and, for ulysses, over the context axis too); repeat
            # only as far as divisibility requires.
            assert attention_scores_manipulation is None, (
                "attention_scores_manipulation is unsupported under context "
                "parallelism"
            )
            assert n_local == 0, "local-window heads are unsupported under CP"
            assert dropout_fn is None, "attention-prob dropout unsupported under CP"
            from ..topology.topology import MODEL_AXIS

            assert ctx.context_parallel_variant in ("ring", "ulysses"), (
                f"unknown context_parallel_variant "
                f"{ctx.context_parallel_variant!r} (expected 'ring' or "
                "'ulysses') — refusing to silently pick a collective pattern"
            )
            ulysses = ctx.context_parallel_variant == "ulysses"
            mp = (
                ctx.mesh.shape[MODEL_AXIS]
                if ctx.mesh is not None and MODEL_AXIS in ctx.mesh.axis_names
                else 1
            )
            # kv heads must split cleanly over the model axis — and for
            # ulysses also over the context axis after the model split
            div = mp * (ctx.context_parallel_size if ulysses else 1)
            kr, vr = k, v
            n_kv = k.shape[2]
            if n_kv % div != 0:
                # repeat_kv's consecutive copies stay aligned with the
                # grouped-head reshape both variants use
                import math

                rep = div // math.gcd(n_kv, div)
                if self.num_repeat_kv % rep != 0:
                    rep = self.num_repeat_kv  # fallback: full repeat
                kr = repeat_kv(k, rep)
                vr = repeat_kv(v, rep)
            if ulysses:
                from ..ops.ulysses_attention import ulysses_attention

                out = ulysses_attention(
                    q, kr, vr, segment_ids, ctx.mesh,
                    causal=self.causal, sm_scale=self.scaling_factor,
                )
            else:
                from ..ops.ring_attention import ring_attention

                out = ring_attention(
                    q, kr, vr, segment_ids, ctx.mesh,
                    causal=self.causal, sm_scale=self.scaling_factor,
                )
            return self._project_out(params, out, ctx, b, s, new_kv)

        k = repeat_kv(k, self.num_repeat_kv)
        v = repeat_kv(v, self.num_repeat_kv)

        if n_local > 0 and kv_cache is None:
            # mixed local/global heads: first (n - n_local) heads global,
            # last n_local heads restricted to the window
            local_mask = segment_ids_to_mask(
                segment_ids, None, causal=self.causal,
                local_window=self.local_attention_window_size,
            )
            n_global = self.num_attention_heads - n_local
            out_g = multi_head_attention(
                q[:, :, :n_global], k[:, :, :n_global], v[:, :, :n_global],
                mask, self.scaling_factor, self.masked_softmax, dropout_fn,
                attention_scores_manipulation,
                attention_scores_manipulation_log_additive,
            ) if n_global > 0 else None
            out_l = multi_head_attention(
                q[:, :, n_global:], k[:, :, n_global:], v[:, :, n_global:],
                local_mask, self.scaling_factor, self.masked_softmax, dropout_fn,
                attention_scores_manipulation,
                attention_scores_manipulation_log_additive,
            )
            out = out_l if out_g is None else jnp.concatenate([out_g, out_l], axis=2)
        else:
            out = multi_head_attention(
                q, k, v, mask, self.scaling_factor, self.masked_softmax,
                dropout_fn, attention_scores_manipulation,
                attention_scores_manipulation_log_additive,
            )

        return self._project_out(params, out, ctx, b, s, new_kv)

    def _paged_attention(self, q, k, v, view: PagedKVCacheView, b: int, s: int,
                         ctx: ForwardContext):
        """Decode (or chunk-prefill) through the block-paged KV pool:
        scatter the ``s`` new tokens per row into the pool, then attend
        each row over its blocks with slot-validity + causal masking. One
        jitted program serves every mix of sequence lengths — raggedness
        lives entirely in ``block_table``/``context_len``/``new_len``,
        never in shapes.

        Two attention back-ends behind one scatter (``ctx.paged_kernel``):

        - ``'pallas'`` — the flash-style streaming kernel
          (nn/paged_attention.py): KV blocks DMA from the pool per row
          into an online softmax; no gathered window is materialized.
          Runs interpreted off-TPU, so the CPU mesh tests the real body.
        - ``'xla'`` — the fallback: gather each row's blocks as one
          contiguous (b, max_blocks*block_size, n_kv, h) window, then run
          the unfused attention. Fine on CPU, pure extra HBM traffic on
          a chip.
        """
        block_size = view.pool_k.shape[1]
        max_blocks = view.block_table.shape[1]
        window = max_blocks * block_size
        ctx_len = view.context_len.astype(jnp.int32)
        if view.new_len is None:
            new_len = jnp.full((b,), s, jnp.int32)
        else:
            new_len = view.new_len.astype(jnp.int32)

        # --- write: rows' next new_len slots (inactive rows: table is
        # all-trash); chunk padding past new_len routes to the trash block
        # — a clamped write into the row's own blocks would corrupt the
        # slots the NEXT chunk is about to fill
        positions = ctx_len[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]
        real = jnp.arange(s, dtype=jnp.int32)[None, :] < new_len[:, None]
        flat = paged_flat_slots(view.block_table, positions, block_size)
        flat = jnp.where(real, flat, 0)
        new_view = paged_scatter_kv(
            view, flat.reshape(-1),
            k.reshape(b * s, *k.shape[2:]), v.reshape(b * s, *v.shape[2:]),
        )

        valid_len = ctx_len + new_len  # written slots per row
        kernel = getattr(ctx, "paged_kernel", "xla")
        if kernel == "pallas":
            import functools

            from .paged_attention import paged_decode_attention
            from ..topology.topology import MODEL_AXIS

            mp = (
                ctx.mesh.shape[MODEL_AXIS]
                if ctx.mesh is not None and MODEL_AXIS in ctx.mesh.axis_names
                else 1
            )
            call = functools.partial(
                paged_decode_attention,
                sm_scale=self.scaling_factor,
                num_repeat_kv=self.num_repeat_kv,
            )
            if mp > 1:
                # mp>1 sharded serving: pallas calls are opaque to GSPMD
                # (which would gather the whole pool to every device), so
                # partition the kernel itself — each model shard streams
                # its OWN (num_blocks, block_size, n_kv/mp, h) pool slice
                # under its n/mp query heads. Addressing state (tables,
                # lengths) is replicated; the GQA repeat factor is
                # unchanged per shard because q and kv heads divide mp
                # together (enforced at pool init, serve/kvcache.py).
                from jax.sharding import PartitionSpec as P

                from ..parallel.sharding import shard_map

                heads = P(None, None, MODEL_AXIS, None)
                rep2, rep1 = P(None, None), P(None)
                quant = view.quantized
                in_specs = [heads, heads, heads, rep2, rep1, rep1]
                if quant:
                    in_specs += [P(None, None, MODEL_AXIS)] * 2

                def run_shard(qq, pk, pv, tab, vl, qb, *scales):
                    sk, sv = scales if quant else (None, None)
                    return call(qq, pk, pv, tab, vl, qb,
                                scale_k=sk, scale_v=sv)

                operands = [
                    q, new_view.pool_k, new_view.pool_v,
                    view.block_table, valid_len, ctx_len,
                ]
                if quant:
                    operands += [new_view.scale_k, new_view.scale_v]
                out = shard_map(
                    run_shard, mesh=ctx.mesh, in_specs=tuple(in_specs),
                    out_specs=heads, check_vma=False,
                )(*operands)
            else:
                out = call(
                    q, new_view.pool_k, new_view.pool_v,
                    view.block_table, valid_len, ctx_len,
                    scale_k=new_view.scale_k, scale_v=new_view.scale_v,
                )
            return out, new_view
        assert kernel == "xla", (
            f"unknown paged_kernel {kernel!r} (expected 'pallas' or 'xla') "
            "— refusing to silently pick an attention path"
        )

        # --- gather: each row's blocks as one contiguous KV window
        gk = new_view.pool_k[view.block_table]  # (b, max_blocks, bs, n_kv, h)
        gv = new_view.pool_v[view.block_table]
        gk = gk.reshape(b, window, *gk.shape[3:])
        gv = gv.reshape(b, window, *gv.shape[3:])
        if view.quantized:
            gsk = new_view.scale_k[view.block_table].reshape(b, window, -1)
            gsv = new_view.scale_v[view.block_table].reshape(b, window, -1)
            gk = kv_dequantize_int8(gk, gsk, k.dtype)
            gv = kv_dequantize_int8(gv, gsv, v.dtype)

        # masking runs on LOGICAL slot indices (the causal clock), exactly
        # like the dense cache path: unwritten slots are invalid, written
        # slots obey causal order against the query's slot
        slots_k = jnp.broadcast_to(
            jnp.arange(window, dtype=jnp.int32)[None, :], (b, window)
        )
        slots_q = positions  # (b, s)
        valid_k = slots_k < valid_len[:, None]
        allowed = valid_k[:, None, :] & (
            slots_k[:, None, :] <= slots_q[:, :, None]
        )
        mask = ~allowed[:, None, :, :]

        gk = repeat_kv(gk, self.num_repeat_kv)
        gv = repeat_kv(gv, self.num_repeat_kv)
        out = multi_head_attention(
            q, gk, gv, mask, self.scaling_factor, self.masked_softmax, None
        )
        return out, new_view

    def _project_out(self, params, out, ctx, b, s, new_kv):
        """Shared epilogue: heads -> hidden, dense projection + LoRA delta."""
        out = out.reshape(b, s, self.hidden_size)
        y = self.dense(params["dense"], out, ctx)
        if self.lora_config:
            name = f"{LoRAModuleType.DENSE.value}_{self.lora_config.name}"
            if name in self.lora_modules:
                y = y + self.lora_modules[name](params[name], out, ctx)
        if new_kv is not None:
            return y, new_kv
        return y

    # ----------------------------------------------------------------- merge
    def merge_lora_weights(self, params: dict) -> dict:
        """Fold LoRA deltas into base weights; returns updated params tree.

        The reference mutates base weights and deletes the lora modules
        (attention.py:766-797). Functionally the same thing here: the delta
        is folded into the host weight and the lora_b factor is zeroed, so
        the still-present LoRA path contributes exactly nothing afterwards.
        A trained LoRA bias is folded into the host projection's bias (the
        reference silently drops it with the deleted module); merging raises
        if the host has no bias to absorb it rather than changing the model
        function silently.
        """
        if not self.lora_config:
            return params
        params = dict(params)
        lc = self.lora_config

        def fold_bias(host: dict, lora_bias, what: str) -> dict:
            if lora_bias is None or not jnp.asarray(lora_bias).size:
                return host
            if "bias" not in host:
                raise ValueError(
                    f"cannot merge LoRA bias on {what}: the host projection "
                    "has no bias parameter to absorb it (set lora bias=False "
                    "or keep the LoRA unmerged)"
                )
            host["bias"] = host["bias"] + lora_bias.astype(host["bias"].dtype)
            return host

        for mt in lc.parallel_modules:
            name = f"{mt.value}_{lc.name}"
            if name not in self.lora_modules:
                continue
            delta = self.lora_modules[name].get_delta_weights(params[name])
            lora_bias = params[name].get("bias")
            disabled = {
                **params[name],
                "lora_b": jnp.zeros_like(params[name]["lora_b"]),
            }
            if "bias" in disabled:
                disabled["bias"] = jnp.zeros_like(disabled["bias"])
            params[name] = disabled
            if mt == LoRAModuleType.DENSE:
                host = dict(params["dense"])
                host["weight"] = host["weight"] + delta.astype(host["weight"].dtype)
                params["dense"] = fold_bias(host, lora_bias, "dense")
            elif self.qkv_in_one:
                if lora_bias is not None:
                    raise NotImplementedError(
                        "LoRA bias merge is unsupported for the fused "
                        "query_key_value layout; set attention_qkv_in_one "
                        "false or lora bias=False"
                    )
                host = dict(params["query_key_value"])
                w = host["weight"].reshape(
                    self.hidden_size, self.num_attention_heads, 3 * self.head_dim
                )
                idx = {"query": 0, "key": 1, "value": 2}[mt.value]
                d = delta.reshape(self.hidden_size, self.num_attention_heads, self.head_dim)
                w = w.at[:, :, idx * self.head_dim : (idx + 1) * self.head_dim].add(
                    d.astype(w.dtype)
                )
                host["weight"] = w.reshape(self.hidden_size, 3 * self.hidden_size)
                params["query_key_value"] = host
            else:
                host = dict(params[mt.value])
                host["weight"] = host["weight"] + delta.astype(host["weight"].dtype)
                params[mt.value] = fold_bias(host, lora_bias, mt.value)
        return params
