"""Masked softmax with a kernel switch.

(reference: src/scaling/core/nn/masked_softmax/masked_softmax.py:8-49,
masked_softmax_config.py:8-37). Kernels:

- ``torch``: the reference's plain path — here the XLA path (fp32 upcast
  option, pre-softmax scale, additive -10000 mask fill). Name kept so
  reference configs load unchanged.
- ``flash_attention``: selects the fused attention path (Pallas on TPU);
  the softmax module itself becomes a no-op marker, as in the reference.
"""

from __future__ import annotations

from enum import Enum

import jax
import jax.numpy as jnp
from pydantic import Field

from ..config import BaseConfig


class MaskedSoftmaxKernel(Enum):
    TORCH = "torch"  # plain XLA path (name kept for config parity)
    FLASH_ATTENTION = "flash_attention"  # fused path (Pallas on TPU)


class MaskedSoftmaxConfig(BaseConfig):
    kernel: MaskedSoftmaxKernel = Field(
        MaskedSoftmaxKernel.TORCH,
        description="attention kernel: 'torch' = unfused XLA path, "
        "'flash_attention' = fused Pallas flash attention",
    )
    softmax_in_fp32: bool = Field(
        False,
        description="Cast scores to fp32 before softmax for higher precision",
    )
    scale: float = Field(
        1.0,
        description="Scale scores are multiplied by (not divided!) before softmax",
    )
    deterministic_flash_attn_bwd: bool = Field(
        False,
        description="deterministic backward for the fused kernel (parity knob; "
        "the Pallas kernel is always deterministic)",
    )


class MaskedSoftmax:
    def __init__(self, config: MaskedSoftmaxConfig):
        self.config = config

    def __call__(self, scores: jax.Array, mask: jax.Array) -> jax.Array:
        """scores: (b, n, s_q, s_k); mask: True where attention is FORBIDDEN."""
        input_dtype = scores.dtype
        if self.config.softmax_in_fp32 and scores.dtype != jnp.float32:
            scores = scores.astype(jnp.float32)
        if self.config.scale != 1.0:
            scores = scores * self.config.scale
        scores = jnp.where(mask, jnp.asarray(-10000.0, dtype=scores.dtype), scores)
        probs = jax.nn.softmax(scores, axis=-1)
        if self.config.softmax_in_fp32 and probs.dtype != input_dtype:
            probs = probs.astype(input_dtype)
        return probs
