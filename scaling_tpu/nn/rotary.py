"""Rotary position embeddings, both variants.

Parity with the reference (reference: src/scaling/core/nn/rotary.py:142-255):

- ``RotaryEmbedding``: GPT-NeoX-style half-rotation with precomputed cos/sin
  tables, partial application via ``rotary_percentage`` (dimensions < head
  dim), position-id gather;
- ``RotaryEmbeddingComplex``: llama-style pairwise complex multiplication
  (``freqs_cis``), which pairs adjacent dims instead of split halves.

Layout is batch-major (b, s, n_heads, head_dim), vs the reference's
(s, b, n, h). Tables are computed in fp32 and applied in the activation
dtype (neox path) / fp32 (complex path), matching reference numerics.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from pydantic import Field

from ..config import BaseConfig


class RotaryConfig(BaseConfig):
    dimensions: int = Field(0, description="number of leading head dims to rotate")
    base: int = Field(10000, description="rotary frequency base")
    max_seq_length: int = Field(2048, description="table length")


def _cos_sin_tables(dimensions: int, max_seq_length: int, base: float):
    # host-side numpy: the tables embed into jitted programs as constants,
    # which must not require a device->host fetch at trace time
    inv_freq = 1.0 / (base ** (np.arange(0, dimensions, 2, dtype=np.float32) / dimensions))
    t = np.arange(max_seq_length, dtype=np.float32)
    freqs = np.outer(t, inv_freq)  # (s, d/2)
    emb = np.concatenate([freqs, freqs], axis=-1)  # (s, d)
    return np.cos(emb), np.sin(emb)


def rotate_half(x: jax.Array) -> jax.Array:
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([-x2, x1], axis=-1)


def apply_rotary_pos_emb(
    x: jax.Array,  # (b, s, n, d_rot)
    cos: jax.Array,  # (s_table, d_rot)
    sin: jax.Array,
    position_ids: Optional[jax.Array],  # (b, s) or None
) -> jax.Array:
    cos, sin = jnp.asarray(cos), jnp.asarray(sin)
    if position_ids is None:
        s = x.shape[1]
        cos_g = cos[None, :s, None, :]
        sin_g = sin[None, :s, None, :]
    else:
        cos_g = cos[position_ids][:, :, None, :]  # (b, s, 1, d)
        sin_g = sin[position_ids][:, :, None, :]
    return x * cos_g.astype(x.dtype) + rotate_half(x) * sin_g.astype(x.dtype)


class RotaryEmbedding:
    """Half-rotation rotary, optionally applied to a leading slice of dims."""

    def __init__(self, config: RotaryConfig):
        assert config.dimensions > 1, "RotaryEmbedding cannot use dimensions <= 1"
        self.dimensions = config.dimensions
        self.cos, self.sin = _cos_sin_tables(config.dimensions, config.max_seq_length, config.base)

    def __call__(
        self,
        query: jax.Array,  # (b, s, n, h)
        key: jax.Array,  # (b, s, n_kv, h)
        query_position_ids: Optional[jax.Array] = None,
        key_position_ids: Optional[jax.Array] = None,
    ) -> tuple[jax.Array, jax.Array]:
        d = self.dimensions
        if query.shape[-1] != d:
            assert query.shape[-1] > d, f"query dims {query.shape[-1]} < rotary dims {d}"
            q_rot = apply_rotary_pos_emb(query[..., :d], self.cos, self.sin, query_position_ids)
            k_rot = apply_rotary_pos_emb(key[..., :d], self.cos, self.sin, key_position_ids)
            query = jnp.concatenate([q_rot, query[..., d:]], axis=-1)
            key = jnp.concatenate([k_rot, key[..., d:]], axis=-1)
            return query, key
        return (
            apply_rotary_pos_emb(query, self.cos, self.sin, query_position_ids),
            apply_rotary_pos_emb(key, self.cos, self.sin, key_position_ids),
        )


def precompute_freqs_cis(dim: int, end: int, theta: float) -> np.ndarray:
    """Complex rotation factors e^{i t f} as a (end, dim/2) complex64 array.

    Host-side numpy (see _cos_sin_tables); stored as cos/sin would be too,
    but complex64 keeps the llama pairing arithmetic one multiply.
    """
    freqs = 1.0 / (theta ** (np.arange(0, dim, 2, dtype=np.float32)[: dim // 2] / dim))
    t = np.arange(end, dtype=np.float32)
    angles = np.outer(t, freqs)
    return (np.cos(angles) + 1j * np.sin(angles)).astype(np.complex64)


def apply_complex_rotary_emb(
    x: jax.Array,  # (b, s, n, h)
    freqs_cis: jax.Array,  # (s_table, h/2) complex
    position_ids: Optional[jax.Array],
) -> jax.Array:
    """Llama-style adjacent-pair rotation, in real arithmetic: complex64 is
    software-emulated on TPU and measured ~8% slower end-to-end."""
    b, s, n, h = x.shape
    xf = x.astype(jnp.float32)
    x_even, x_odd = xf[..., 0::2], xf[..., 1::2]  # (b, s, n, h/2)
    # split host-side: complex never reaches the device
    freqs_np = np.asarray(freqs_cis)
    cos_t = jnp.asarray(np.real(freqs_np).astype(np.float32))
    sin_t = jnp.asarray(np.imag(freqs_np).astype(np.float32))
    if position_ids is None:
        cos = cos_t[None, :s, None, :]
        sin = sin_t[None, :s, None, :]
    else:
        cos = cos_t[position_ids][:, :, None, :]
        sin = sin_t[position_ids][:, :, None, :]
    r_even = x_even * cos - x_odd * sin
    r_odd = x_even * sin + x_odd * cos
    out = jnp.stack([r_even, r_odd], axis=-1).reshape(b, s, n, h)
    return out.astype(x.dtype)


class RotaryEmbeddingComplex:
    """Llama-style rotary via complex multiplication (adjacent-dim pairs)."""

    def __init__(self, config: RotaryConfig):
        assert config.dimensions > 1, "RotaryEmbedding cannot use dimensions <= 1"
        self.freqs_cis = precompute_freqs_cis(
            config.dimensions, config.max_seq_length, float(config.base)
        )

    def __call__(
        self,
        query: jax.Array,
        key: jax.Array,
        query_position_ids: Optional[jax.Array] = None,
        key_position_ids: Optional[jax.Array] = None,
    ) -> tuple[jax.Array, jax.Array]:
        return (
            apply_complex_rotary_emb(query, self.freqs_cis, query_position_ids),
            apply_complex_rotary_emb(key, self.freqs_cis, key_position_ids),
        )


class RelativePositionEmbeddingType:
    NONE = "none"
    ROTARY = "rotary"
    ROTARY_COMPLEX = "rotary_complex"
