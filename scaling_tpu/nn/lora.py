"""LoRA adapters.

(reference: src/scaling/core/nn/lora.py:12, lora_config.py). ``ParallelLoRa``
is A (kaiming-init, column-parallel) -> dropout -> B (zero-init) scaled by
alpha/rank; injected on query/key/value/dense inside attention. Merge support
computes the delta weight for folding into the base matrix.
"""

from __future__ import annotations

import math
from enum import Enum
from typing import List, Optional

import jax
import jax.numpy as jnp
from pydantic import Field

from ..config import BaseConfig
from ..topology.topology import MODEL_AXIS
from .base_layer import BaseLayer, ForwardContext
from .param import ParamMeta, model_parallel_meta


class LoRAModuleType(Enum):
    QUERY = "query"
    KEY = "key"
    VALUE = "value"
    DENSE = "dense"


class LoRaConfig(BaseConfig):
    name: str = Field("default_lora", description="adapter name (used in param keys)")
    rank: int = Field(8, description="LoRA rank r")
    alpha: int = Field(8, description="scaling numerator; delta = (alpha/r) B A x")
    dropout: float = Field(0.0, description="dropout on the input of A")
    bias: bool = Field(False, description="bias on the B projection")
    kaiming_a: float = Field(
        math.sqrt(5.0), description="kaiming-uniform `a` used to init A"
    )
    parallel_modules: List[LoRAModuleType] = Field(
        default_factory=lambda: [
            LoRAModuleType.QUERY,
            LoRAModuleType.KEY,
            LoRAModuleType.VALUE,
            LoRAModuleType.DENSE,
        ],
        description="which attention projections receive adapters",
    )


def _kaiming_uniform(key: jax.Array, shape: tuple, a: float, dtype) -> jax.Array:
    fan_in = shape[0]
    gain = math.sqrt(2.0 / (1.0 + a * a))
    bound = gain * math.sqrt(3.0 / fan_in)
    return jax.random.uniform(key, shape, minval=-bound, maxval=bound).astype(dtype)


class ParallelLoRa(BaseLayer):
    """x -> (alpha/r) * B(A(dropout(x))); B zero-init so delta starts at 0.

    Sharding follows the host projection: for column-parallel hosts
    (query/key/value) B's output dim is model-sharded; for the dense
    (row-parallel) host A's input dim is model-sharded.
    """

    def __init__(
        self,
        in_features: int,
        out_features: int,
        rank: int,
        lora_module_type: LoRAModuleType,
        alpha: int = 8,
        dropout: float = 0.0,
        bias: bool = False,
        kaiming_a: float = math.sqrt(5.0),
        dtype=jnp.float32,
        name: str = "default_lora",
    ):
        self.in_features = in_features
        self.out_features = out_features
        self.rank = rank
        self.alpha = alpha
        self.scaling = alpha / rank
        self.dropout_rate = dropout
        self.use_bias = bias
        self.kaiming_a = kaiming_a
        self.dtype = dtype
        self.module_type = lora_module_type
        self.name = name

    def init(self, key: jax.Array) -> dict:
        ka, kb = jax.random.split(key)
        params = {
            "lora_a": _kaiming_uniform(ka, (self.in_features, self.rank), self.kaiming_a, self.dtype),
            "lora_b": jnp.zeros((self.rank, self.out_features), dtype=self.dtype),
        }
        if self.use_bias:
            params["bias"] = jnp.zeros((self.out_features,), dtype=self.dtype)
        return params

    def param_metas(self) -> dict:
        if self.module_type == LoRAModuleType.DENSE:
            # host is row-parallel: input sharded, B replicated on out
            metas = {
                "lora_a": model_parallel_meta(0, parameter_name="lora_a", no_weight_decay=True),
                "lora_b": ParamMeta(
                    parameter_name="lora_b", partition_spec=(None, None),
                    is_model_parallel_duplicate=True, no_weight_decay=True,
                ),
            }
        else:
            metas = {
                "lora_a": ParamMeta(
                    parameter_name="lora_a", partition_spec=(None, None),
                    is_model_parallel_duplicate=True, no_weight_decay=True,
                ),
                "lora_b": model_parallel_meta(1, parameter_name="lora_b", no_weight_decay=True),
            }
        if self.use_bias:
            metas["bias"] = ParamMeta(
                parameter_name="bias",
                partition_spec=(MODEL_AXIS,) if self.module_type != LoRAModuleType.DENSE else (None,),
                no_weight_decay=True,
            )
        return metas

    def __call__(self, params: dict, x: jax.Array, ctx: ForwardContext) -> jax.Array:
        h = ctx.dropout(x, self.dropout_rate)
        delta = (h @ params["lora_a"].astype(x.dtype)) @ params["lora_b"].astype(x.dtype)
        delta = delta * self.scaling
        if self.use_bias:
            delta = delta + params["bias"].astype(x.dtype)
        return delta

    def get_delta_weights(self, params: dict) -> jax.Array:
        """(in, out) weight delta for merging into the host matrix."""
        return (params["lora_a"] @ params["lora_b"]) * self.scaling
