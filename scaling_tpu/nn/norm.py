"""Layer norms.

Parity with the reference's norm stack
(reference: src/scaling/core/nn/norm/layernorm.py:14-87, rms_norm.py:21-63,
get_norm.py): LayerNorm with optional bitfit bias, RMSNorm, a factory keyed
by ``NormType``. The reference's ``fused`` optimization type (flash-attn's
CUDA fused rms_norm) maps to the Pallas kernel in ``ops/rms_norm.py``;
``torch`` is the plain XLA path, which XLA fuses into neighbouring ops on
its own.

Sequence-parallel contract: norms sit *between* TP regions, so under SP
their input/output stay sequence-sharded; the surrounding linears change
layout. Norm params are replicated over the model axis and flagged
``is_sequence_parallel_norm`` so the optimizer knows their grads already
include every token's contribution only after a psum over the model axis —
with GSPMD the backward collective is emitted automatically, so the flag is
informational for grad-norm bookkeeping parity.
"""

from __future__ import annotations

from enum import Enum
from typing import Optional

import jax
import jax.numpy as jnp
from pydantic import Field

from ..config import BaseConfig
from .base_layer import BaseLayer, ForwardContext
from .param import ParamMeta


class NormType(Enum):
    LAYERNORM = "layernorm"
    RMS = "rms"


class LayerNormOptimizationType(Enum):
    TORCH = "torch"
    FUSED = "fused"


class LayerNormConfig(BaseConfig):
    optimization_type: LayerNormOptimizationType = Field(
        LayerNormOptimizationType.TORCH,
        description="norm implementation; 'torch' is the XLA-fused path, "
        "'fused' selects the Pallas kernel where available",
    )
    layernorm_epsilon: float = Field(
        1e-5, description="A value added to the denominator for numerical stability"
    )


def _norm_meta(name: str) -> ParamMeta:
    return ParamMeta(
        parameter_name=name,
        partition_spec=(None,),
        is_model_parallel=False,
        is_model_parallel_duplicate=True,
        no_weight_decay=True,
        is_sequence_parallel_norm=True,
    )


class LayerNorm(BaseLayer):
    def __init__(
        self,
        dimensions: int,
        config: Optional[LayerNormConfig] = None,
        dtype=jnp.float32,
        bitfit_bias_name: Optional[str] = None,
    ):
        self.dimensions = dimensions
        self.config = config or LayerNormConfig()
        self.dtype = dtype
        self.bitfit_bias_name = bitfit_bias_name

    @property
    def bias_name(self) -> str:
        return f"bias_{self.bitfit_bias_name}" if self.bitfit_bias_name else "bias"

    def init(self, key: jax.Array) -> dict:
        return {
            "weight": jnp.ones((self.dimensions,), dtype=self.dtype),
            self.bias_name: jnp.zeros((self.dimensions,), dtype=self.dtype),
        }

    def param_metas(self) -> dict:
        return {
            "weight": _norm_meta("weight"),
            self.bias_name: _norm_meta(self.bias_name),
        }

    def __call__(self, params: dict, x: jax.Array, ctx: ForwardContext) -> jax.Array:
        dtype = x.dtype
        x32 = x.astype(jnp.float32)
        mean = x32.mean(axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
        y = (x32 - mean) * jax.lax.rsqrt(var + self.config.layernorm_epsilon)
        y = y * params["weight"].astype(jnp.float32) + params[self.bias_name].astype(jnp.float32)
        return y.astype(dtype)


class RMSNorm(BaseLayer):
    def __init__(
        self,
        dimensions: int,
        config: Optional[LayerNormConfig] = None,
        dtype=jnp.float32,
        bitfit_bias_name: Optional[str] = None,
    ):
        self.dimensions = dimensions
        self.config = config or LayerNormConfig()
        self.dtype = dtype
        self.bitfit_bias_name = bitfit_bias_name  # rmsnorm has no bias; kept for API parity

    def init(self, key: jax.Array) -> dict:
        return {"weight": jnp.ones((self.dimensions,), dtype=self.dtype)}

    def param_metas(self) -> dict:
        return {"weight": _norm_meta("weight")}

    def __call__(self, params: dict, x: jax.Array, ctx: ForwardContext) -> jax.Array:
        if self.config.optimization_type == LayerNormOptimizationType.FUSED:
            from ..ops.rms_norm import (
                rms_norm_fused,
                rms_norm_fused_shardable,
                rms_norm_fused_sharded,
                rms_norm_fused_supported,
            )

            # pallas calls are opaque to GSPMD (see ops/flash_attention.py's
            # shard_map handling), so on a multi-device mesh the kernel is
            # partitioned explicitly: rows split over data x (context, model)
            # — the model-axis split IS sequence parallelism. Inside a
            # spatial pipeline (stage-local operands) or on indivisible
            # shapes the XLA path remains.
            if rms_norm_fused_supported(self.dimensions):
                if ctx.mesh is None or ctx.mesh.size <= 1:
                    return rms_norm_fused(
                        x, params["weight"], self.config.layernorm_epsilon
                    )
                if rms_norm_fused_shardable(ctx.mesh, x.shape):
                    return rms_norm_fused_sharded(
                        x,
                        params["weight"],
                        self.config.layernorm_epsilon,
                        ctx.mesh,
                    )
        dtype = x.dtype
        x32 = x.astype(jnp.float32)
        var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
        y = x32 * jax.lax.rsqrt(var + self.config.layernorm_epsilon)
        return (y * params["weight"].astype(jnp.float32)).astype(dtype)


def get_norm(
    norm_type: NormType,
    dimensions: int,
    layernorm_config: Optional[LayerNormConfig] = None,
    dtype=jnp.float32,
    bitfit_bias_name: Optional[str] = None,
) -> BaseLayer:
    if norm_type == NormType.LAYERNORM:
        return LayerNorm(dimensions, layernorm_config, dtype, bitfit_bias_name)
    if norm_type == NormType.RMS:
        return RMSNorm(dimensions, layernorm_config, dtype, bitfit_bias_name)
    raise NotImplementedError(f"norm type {norm_type}")
