"""Layer contract and assembly specs.

The reference's ``BaseLayer`` needs tuple-conversion hooks because pipe
communication and activation checkpointing move opaque tuples between
processes (reference: src/scaling/core/nn/parallel_module/base_layer.py:16).
Under jit everything is a pytree with static treedef, so the contract
collapses to: ``init(key) -> params``, ``param_metas() -> metas``,
``__call__(params, x, ctx) -> y`` where x/y are pytrees.

``LayerSpec``/``TiedLayerSpec`` keep the reference's deferred-construction
API (reference: src/scaling/core/nn/parallel_module/layer_spec.py:8-29) so
model assembly code reads the same.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Type

import jax


@dataclass
class ForwardContext:
    """Per-call state threaded through layers (all jit-compatible)."""

    # dropout master key for this microbatch/step; None => deterministic
    dropout_key: Optional[jax.Array] = None
    # train vs eval; static under jit
    deterministic: bool = True
    # topology flags the layers need (static)
    sequence_parallel: bool = False
    model_parallel_size: int = 1
    context_parallel_size: int = 1
    # "ring" (K/V rotation) or "ulysses" (head all-to-all); see
    # topology.config.ContextParallelVariant
    context_parallel_variant: str = "ring"
    # mesh is needed for explicit collectives; None on single device
    mesh: Optional[Any] = None
    # paged-decode attention back-end (static): 'xla' gathers each row's
    # block window, 'pallas' streams blocks through the flash-style
    # kernel (nn/paged_attention.py). Only the serving engine's programs
    # flip this (TransformerInferenceModule._run_layers paged_kernel=).
    paged_kernel: str = "xla"

    _key_counter: int = 0

    def next_key(self) -> Optional[jax.Array]:
        """Derive a fresh dropout key; deterministic given call order."""
        if self.dropout_key is None or self.deterministic:
            return None
        self._key_counter += 1
        return jax.random.fold_in(self.dropout_key, self._key_counter)

    def dropout(self, x: jax.Array, rate: float) -> jax.Array:
        if rate == 0.0 or self.deterministic:
            return x
        key = self.next_key()
        if key is None:
            return x
        keep = 1.0 - rate
        mask = jax.random.bernoulli(key, p=keep, shape=x.shape)
        return jax.numpy.where(mask, x / keep, 0).astype(x.dtype)


class BaseLayer:
    """Stateless layer: owns hyperparameters, emits params/metas trees."""

    def init(self, key: jax.Array) -> Any:
        raise NotImplementedError

    def param_metas(self) -> Any:
        raise NotImplementedError

    def __call__(self, params: Any, x: Any, ctx: ForwardContext) -> Any:
        raise NotImplementedError


@dataclass
class LayerSpec:
    """Deferred layer construction for pipeline assembly."""

    module_class: Type[BaseLayer]
    args: tuple = ()
    kwargs: dict = field(default_factory=dict)

    def __init__(self, module_class: Type[BaseLayer], *args: Any, **kwargs: Any):
        self.module_class = module_class
        self.args = args
        self.kwargs = kwargs

    def initialize(self) -> BaseLayer:
        return self.module_class(*self.args, **self.kwargs)


class PipelineBodySpec(LayerSpec):
    """A homogeneous run of ``num_layers`` identical layers, executed as one
    stage-stacked pipelined body (spatial GPipe over the ``pipe`` mesh axis).

    Replaces ``num_layers`` consecutive LayerSpecs of the same class; the
    constructed template layer supplies init/param_metas/__call__ for one
    layer. Checkpoints still see the individual layers (the ParallelModule
    un-stacks them into per-layer files), so a checkpoint written at one
    pipe_parallel_size loads at any other
    (reference partitioning: pipeline_partitioning.py:38-136).
    """

    def __init__(self, module_class: Type[BaseLayer], num_layers: int,
                 *args: Any, **kwargs: Any):
        super().__init__(module_class, *args, **kwargs)
        self.num_layers = num_layers


class TiedLayerSpec(LayerSpec):
    """LayerSpec whose named params are shared with other specs of same key.

    ``tied_weight_attributes`` lists param-tree paths (dot notation) tied
    across occurrences, e.g. embedding weight reused by the LM head.
    """

    def __init__(
        self,
        module_class: Type[BaseLayer],
        *args: Any,
        key: str,
        tied_weight_attributes: Optional[list[str]] = None,
        **kwargs: Any,
    ):
        super().__init__(module_class, *args, **kwargs)
        self.key = key
        self.tied_weight_attributes = tied_weight_attributes or ["weight"]
