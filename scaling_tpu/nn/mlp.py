"""Tensor-parallel MLPs.

(reference: src/scaling/core/nn/mlp.py:21-167) ``ParallelMLP`` is
column-parallel -> activation -> row-parallel; ``ParallelSwiGLUMLP`` gates a
silu branch against a linear branch before the row-parallel projection.
``io_features * intermediate_feature_factor`` must be a natural number —
same contract as the reference, so configs produce identical shapes.
"""

from __future__ import annotations

from typing import Optional

import jax

from .activation_function import ActivationFunction, get_activation_function
from .base_layer import BaseLayer, ForwardContext
from .linear import ColumnParallelLinear, RowParallelLinear, xavier_normal_init
from .param import tree_prefix


class ParallelMLP(BaseLayer):
    def __init__(
        self,
        io_features: int,
        intermediate_feature_factor: float = 4.0,
        activation: ActivationFunction = ActivationFunction.GELU,
        bias: bool = True,
        dtype=None,
        init_method=xavier_normal_init,
        bitfit_bias_name: Optional[str] = None,
        sequence_parallel_output: bool = False,
    ):
        import jax.numpy as jnp

        dtype = dtype or jnp.float32
        assert float(int(io_features * intermediate_feature_factor)) == (
            io_features * intermediate_feature_factor
        ), "io_features * intermediate_feature_factor must be a natural number"
        intermediate = int(io_features * intermediate_feature_factor)
        self.activation_fn = get_activation_function(activation)
        self.dense_in = ColumnParallelLinear(
            io_features, intermediate, bias=bias, dtype=dtype,
            init_method=init_method, bitfit_bias_name=bitfit_bias_name,
            parallel_output=True,
        )
        self.dense_out = RowParallelLinear(
            intermediate, io_features, bias=bias, dtype=dtype,
            init_method=init_method, bitfit_bias_name=bitfit_bias_name,
            parallel_input=True, parallel_output=sequence_parallel_output,
        )

    def init(self, key: jax.Array) -> dict:
        k1, k2 = jax.random.split(key)
        return {"dense_in": self.dense_in.init(k1), "dense_out": self.dense_out.init(k2)}

    def param_metas(self) -> dict:
        return {
            "dense_in": tree_prefix(self.dense_in.param_metas(), "dense_in"),
            "dense_out": tree_prefix(self.dense_out.param_metas(), "dense_out"),
        }

    def __call__(self, params: dict, x: jax.Array, ctx: ForwardContext) -> jax.Array:
        h = self.dense_in(params["dense_in"], x, ctx)
        h = self.activation_fn(h)
        return self.dense_out(params["dense_out"], h, ctx)


class ParallelSwiGLUMLP(BaseLayer):
    """silu(x W_gate) * (x W_up) -> W_down, all tensor-parallel."""

    def __init__(
        self,
        io_features: int,
        intermediate_feature_factor: float = 8.0 / 3.0,
        bias: bool = False,
        dtype=None,
        init_method=xavier_normal_init,
        bitfit_bias_name: Optional[str] = None,
        sequence_parallel_output: bool = False,
    ):
        import jax.numpy as jnp

        dtype = dtype or jnp.float32
        assert float(int(io_features * intermediate_feature_factor)) == (
            io_features * intermediate_feature_factor
        ), "io_features * intermediate_feature_factor must be a natural number"
        intermediate = int(io_features * intermediate_feature_factor)
        self.intermediate = intermediate
        self.silu = get_activation_function(ActivationFunction.SILU)
        self.gate_proj = ColumnParallelLinear(
            io_features, intermediate, bias=bias, dtype=dtype,
            init_method=init_method, bitfit_bias_name=bitfit_bias_name,
            parallel_output=True,
        )
        self.up_proj = ColumnParallelLinear(
            io_features, intermediate, bias=bias, dtype=dtype,
            init_method=init_method, bitfit_bias_name=bitfit_bias_name,
            parallel_output=True,
        )
        self.down_proj = RowParallelLinear(
            intermediate, io_features, bias=bias, dtype=dtype,
            init_method=init_method, bitfit_bias_name=bitfit_bias_name,
            parallel_input=True, parallel_output=sequence_parallel_output,
        )

    def init(self, key: jax.Array) -> dict:
        k1, k2, k3 = jax.random.split(key, 3)
        return {
            "gate_proj": self.gate_proj.init(k1),
            "up_proj": self.up_proj.init(k2),
            "down_proj": self.down_proj.init(k3),
        }

    def param_metas(self) -> dict:
        return {
            "gate_proj": tree_prefix(self.gate_proj.param_metas(), "gate_proj"),
            "up_proj": tree_prefix(self.up_proj.param_metas(), "up_proj"),
            "down_proj": tree_prefix(self.down_proj.param_metas(), "down_proj"),
        }

    def __call__(self, params: dict, x: jax.Array, ctx: ForwardContext) -> jax.Array:
        gate = self.silu(self.gate_proj(params["gate_proj"], x, ctx))
        up = self.up_proj(params["up_proj"], x, ctx)
        return self.down_proj(params["down_proj"], gate * up, ctx)
