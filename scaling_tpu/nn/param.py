"""Parameter metadata.

Every parameter in the framework carries a ``ParamMeta`` describing how it
shards over the mesh, where it lives in the layer stack, and how the
optimizer/checkpoint machinery should treat it. This plays the role of the
reference's ``CoreParameterMeta``
(reference: src/scaling/core/nn/parameter_meta.py:17-151): the
layout-independent ``key`` makes checkpoints survive topology changes and
lets non-strict PEFT loading match parameters by name rather than position.

Parameters and metas live in *parallel pytrees* with identical structure:
layers return a nested-dict params tree from ``init`` and the same-shaped
meta tree from ``param_metas``.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any, Optional

import jax
from jax.sharding import PartitionSpec as P

from ..topology.topology import MODEL_AXIS


@dataclass(frozen=True)
class ParamMeta:
    parameter_name: str = ""
    layer_index: Optional[int] = None
    layer_class_name: str = ""
    # mesh sharding of the parameter itself; () = replicated
    partition_spec: tuple = ()
    is_model_parallel: bool = False
    model_parallel_dimension: Optional[int] = None
    # weight tying: parameters sharing a tied_key are the same array
    tied_key: Optional[str] = None
    # true for params replicated across mp that must stay bit-identical
    is_model_parallel_duplicate: bool = False
    no_weight_decay: bool = False
    # learning-rate group: "default" | "embedding"
    lr_group: str = "default"
    # marks norm params whose grads need mp-summing under sequence parallel
    is_sequence_parallel_norm: bool = False

    @property
    def key(self) -> str:
        """Layout-independent identity used for checkpoint matching."""
        return f"layer_{self.layer_index}_{self.layer_class_name}.{self.parameter_name}"

    def spec(self) -> P:
        return P(*self.partition_spec)

    def with_layer(self, layer_index: int, layer_class_name: str) -> "ParamMeta":
        return replace(self, layer_index=layer_index, layer_class_name=layer_class_name)

    def prefixed(self, prefix: str) -> "ParamMeta":
        name = f"{prefix}.{self.parameter_name}" if self.parameter_name else prefix
        return replace(self, parameter_name=name)


def model_parallel_meta(dim: int, **kwargs: Any) -> ParamMeta:
    """Meta for a weight sharded over the model axis along ``dim``."""
    spec: list = [None, None]
    spec[dim] = MODEL_AXIS
    return ParamMeta(
        partition_spec=tuple(spec),
        is_model_parallel=True,
        model_parallel_dimension=dim,
        **kwargs,
    )


def replicated_meta(ndim: int = 1, **kwargs: Any) -> ParamMeta:
    return ParamMeta(
        partition_spec=(None,) * ndim,
        is_model_parallel=False,
        is_model_parallel_duplicate=True,
        **kwargs,
    )


# ------------------------------------------------------------------ tree ops
def tree_prefix(metas: Any, prefix: str) -> Any:
    """Prefix every meta's parameter_name with ``prefix.``"""
    return jax.tree.map(
        lambda m: m.prefixed(prefix), metas, is_leaf=lambda x: isinstance(x, ParamMeta)
    )


def tree_with_layer(metas: Any, layer_index: int, layer_class_name: str) -> Any:
    return jax.tree.map(
        lambda m: m.with_layer(layer_index, layer_class_name),
        metas,
        is_leaf=lambda x: isinstance(x, ParamMeta),
    )


def named_parameters(params: Any, metas: Any) -> list[tuple[str, jax.Array, ParamMeta]]:
    """Flatten parallel trees into (key, array, meta) triples."""
    p_leaves, p_def = jax.tree.flatten(params)
    m_leaves, m_def = jax.tree.flatten(metas, is_leaf=lambda x: isinstance(x, ParamMeta))
    if len(p_leaves) != len(m_leaves):
        raise ValueError(
            f"params tree has {len(p_leaves)} leaves but metas tree has {len(m_leaves)}"
        )
    return [(m.key, p, m) for p, m in zip(p_leaves, m_leaves)]
