"""Activation function registry.

(reference: src/scaling/core/nn/activation_function.py)
"""

from __future__ import annotations

from enum import Enum
from typing import Callable

import jax
import jax.numpy as jnp


class ActivationFunction(Enum):
    GELU = "gelu"
    SILU = "silu"
    RELU = "relu"
    TANH = "tanh"
    SIGMOID = "sigmoid"


_FUNCTIONS: dict[ActivationFunction, Callable] = {
    ActivationFunction.GELU: jax.nn.gelu,
    ActivationFunction.SILU: jax.nn.silu,
    ActivationFunction.RELU: jax.nn.relu,
    ActivationFunction.TANH: jnp.tanh,
    ActivationFunction.SIGMOID: jax.nn.sigmoid,
}


def get_activation_function(activation: ActivationFunction) -> Callable:
    return _FUNCTIONS[activation]
