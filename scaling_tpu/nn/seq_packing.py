"""Sequence-packing representations.

The reference threads ``cumulative_seq_lengths`` (flash-attn varlen cu_seqlens)
through the whole stack (reference: src/scaling/transformer/data/utils.py:4-108,
core/nn/attention/attention.py:69-93). Under jit's static shapes the natural
TPU representation is per-token **segment ids**: token t belongs to packed
document ``segment_ids[b, t]``; attention is allowed only within equal
segment ids. Both forms are supported — cu_seqlens (padded with -1, the
reference's pipe-comm trick) converts to segment ids losslessly.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def cumulative_seq_lengths_to_segment_ids(
    cumulative_seq_lengths: jax.Array | np.ndarray,
    batch_size: int,
    seq_length: int,
) -> jax.Array:
    """cu_seqlens over the flattened (b*s) token stream -> (b, s) segment ids.

    ``cumulative_seq_lengths`` is [0, e_1, e_2, ..., b*s] with -1 padding
    allowed after the final entry (static-shape padding).
    """
    cu = jnp.asarray(cumulative_seq_lengths)
    flat_positions = jnp.arange(batch_size * seq_length)
    # segment id of a token = number of boundaries <= position (ignore pads)
    valid = cu >= 0
    boundaries = jnp.where(valid, cu, jnp.iinfo(jnp.int32).max)
    seg = jnp.searchsorted(boundaries, flat_positions, side="right")
    return seg.reshape(batch_size, seq_length).astype(jnp.int32)


def segment_ids_to_mask(
    segment_ids_q: jax.Array,  # (b, s_q)
    segment_ids_k: Optional[jax.Array] = None,  # (b, s_k)
    causal: bool = True,
    positions_q: Optional[jax.Array] = None,  # (b, s_q) absolute positions
    positions_k: Optional[jax.Array] = None,
    local_window: Optional[int] = None,
) -> jax.Array:
    """Boolean mask (b, 1, s_q, s_k), True where attention is FORBIDDEN."""
    if segment_ids_k is None:
        segment_ids_k = segment_ids_q
    b, s_q = segment_ids_q.shape
    s_k = segment_ids_k.shape[1]
    same_segment = segment_ids_q[:, :, None] == segment_ids_k[:, None, :]
    allowed = same_segment
    if causal or local_window is not None:
        if positions_q is None:
            positions_q = jnp.broadcast_to(jnp.arange(s_q)[None, :], (b, s_q))
        if positions_k is None:
            positions_k = jnp.broadcast_to(jnp.arange(s_k)[None, :], (b, s_k))
        rel = positions_q[:, :, None] - positions_k[:, None, :]
        if causal:
            allowed = allowed & (rel >= 0)
        if local_window is not None:
            allowed = allowed & (jnp.abs(rel) <= local_window)
    return ~allowed[:, None, :, :]


def get_cumulative_seq_lengths(
    token_ids: np.ndarray, reset_attention_mask: bool = True, eod_token: int = 0
) -> np.ndarray:
    """EOD-token splits over the flattened batch -> cu_seqlens.

    (reference: src/scaling/transformer/data/utils.py:40-75). If
    ``reset_attention_mask`` is False, one segment per batch row.
    """
    batch_size, seq_length = token_ids.shape
    if not reset_attention_mask:
        return np.arange(0, (batch_size + 1) * seq_length, seq_length, dtype=np.int32)
    boundaries = [0]
    flat = token_ids.reshape(-1)
    for row in range(batch_size):
        row_tokens = token_ids[row]
        eods = np.where(row_tokens == eod_token)[0]
        for e in eods:
            pos = row * seq_length + int(e) + 1
            if pos != boundaries[-1] and pos < flat.size:
                boundaries.append(pos)
        row_end = (row + 1) * seq_length
        if boundaries[-1] != row_end:
            boundaries.append(row_end)
    return np.asarray(boundaries, dtype=np.int32)


def get_position_ids(
    token_ids: np.ndarray, reset_position_ids: bool = True, eod_token: int = 0
) -> np.ndarray:
    """Per-token positions, restarting at 0 after each EOD when resetting.

    (reference: src/scaling/transformer/data/utils.py:78-108)
    """
    batch_size, seq_length = token_ids.shape
    if not reset_position_ids:
        return np.tile(np.arange(seq_length, dtype=np.int64), (batch_size, 1))
    position_ids = np.zeros((batch_size, seq_length), dtype=np.int64)
    for row in range(batch_size):
        pos = 0
        for t in range(seq_length):
            position_ids[row, t] = pos
            pos += 1
            if token_ids[row, t] == eod_token:
                pos = 0
    return position_ids


def get_segment_ids(token_ids: np.ndarray, eod_token: int = 0) -> np.ndarray:
    """Per-token packed-document ids: increments after each EOD token.

    Vectorised equivalent of the reference's EOD-split bookkeeping
    (reference: src/scaling/transformer/data/utils.py:40-75) in the
    TPU-native segment-id representation.
    """
    after_eod = np.zeros(token_ids.shape, dtype=np.int32)
    after_eod[:, 1:] = token_ids[:, :-1] == eod_token
    return np.cumsum(after_eod, axis=1).astype(np.int32)


def get_position_ids_from_segments(segment_ids: np.ndarray) -> np.ndarray:
    """Positions restarting at 0 at each segment boundary (vectorised)."""
    b, s = segment_ids.shape
    idx = np.arange(s, dtype=np.int64)[None, :]
    is_start = np.zeros((b, s), dtype=bool)
    is_start[:, 0] = True
    is_start[:, 1:] = segment_ids[:, 1:] != segment_ids[:, :-1]
    start_idx = np.maximum.accumulate(np.where(is_start, idx, 0), axis=1)
    return idx - start_idx


def add_cumulative_seq_lengths_padding(cu: np.ndarray, pad_to: int) -> np.ndarray:
    """-1-pad to a fixed length (static shape under jit).

    (reference: src/scaling/transformer/data/utils.py:4-38)
    """
    assert cu.size <= pad_to, f"cu_seqlens size {cu.size} exceeds pad length {pad_to}"
    out = np.full((pad_to,), -1, dtype=np.int32)
    out[: cu.size] = cu
    return out


def remove_cumulative_seq_lengths_padding(cu: np.ndarray) -> np.ndarray:
    return np.asarray(cu)[np.asarray(cu) >= 0]
