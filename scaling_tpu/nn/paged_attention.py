"""Pallas paged-decode attention: stream KV blocks, never gather windows.

The serving hot path (serve/engine.py) decodes every slot each tick
through the block-paged KV pool. The original XLA path materializes each
row's FULL block window per layer — ``pool[block_table]`` gathers
``(slots, max_blocks * block_size, n_kv, h)`` into a fresh buffer before
a single token's attention runs. On a chip that is pure HBM traffic the
MXU never sees twice: once to build the window, once to read it.

This kernel removes the window. A ``PrefetchScalarGridSpec`` prefetches
the block table so the BlockSpec ``index_map`` can address the pool
directly: grid step ``(row, j)`` DMAs pool block ``table[row, j]`` into
VMEM and folds it into a flash-style online softmax (running max ``m``,
normalizer ``l``, unnormalized accumulator in f32 scratch — Dao et al.,
arxiv 2205.14135), so each KV byte moves HBM->VMEM exactly once and no
``(rows, window)`` buffer ever exists. Blocks past a row's context are
skipped with ``pl.when`` (their DMA still lands, but no FLOPs run).

Variants share one kernel body:

- native: pool blocks arrive in the pool dtype and are attended as-is;
- int8: pool blocks arrive quantized; the kernel dequantizes IN VMEM with
  the same per-slot-per-head ``kv_quantize_int8`` scales the pool writer
  produced (``nn.attention.paged_scatter_kv``) — the f32 window the XLA
  path materialized in HBM never exists here either.

Masking follows the paged-decode contract exactly (``nn/attention.py``
``_paged_attention``): LOGICAL slot indices are the causal clock; slot
``k`` is visible to query slot ``q`` iff ``k < valid_len`` (written) and
``k <= q`` (causal). Queries may be a single decode token (s=1), a
prefill CHUNK (s=chunk), or a decode token plus its speculative DRAFTS
(s=k+1 — the engine's mixed program scores all k candidates in this one
call; rejected candidates' writes are simply re-covered by the next
call because ``valid_len`` never admits them) — K/V are scattered into
the pool by the caller before attending, and the same per-row
``valid_len``/``q_slot_base`` math serves every row kind, so one fused
program covers a whole mixed tick (serve/engine.py ``_build_mixed_fn``).
Rows past their real tokens (``new_len`` pads) produce garbage query
outputs that the host discards; their writes land in the trash block.

Off-TPU the kernel runs with ``interpret=True`` (the whole grid executes
as traced jax ops), so the CPU-mesh tests exercise the REAL kernel body,
not a stand-in; the XLA gather branch stays config-selectable
(``EngineConfig.paged_kernel = 'xla'``) as the fallback.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp

# pallas resolves lazily on first kernel build so importing scaling_tpu.nn
# never pulls the pallas machinery on jax-light paths; the kernel body
# reads these globals at trace time, strictly after _ensure_pallas ran
pl = None  # type: ignore[assignment]
pltpu = None  # type: ignore[assignment]


def _ensure_pallas():
    global pl, pltpu
    if pl is None:
        from jax.experimental import pallas as _pl
        from jax.experimental.pallas import tpu as _pltpu

        pl, pltpu = _pl, _pltpu


def paged_kernel_interpret(platform: Optional[str] = None) -> bool:
    """Interpret mode off-TPU (CPU mesh tests run the real kernel body);
    ``SCALING_TPU_PAGED_INTERPRET=1`` forces it for on-chip debugging."""
    if os.environ.get("SCALING_TPU_PAGED_INTERPRET") == "1":
        return True
    return (platform or jax.default_backend()) != "tpu"


def _paged_attention_kernel(
    # scalar prefetch (available to the index_maps before the body runs)
    tab_ref,      # (rows, max_blocks) int32 pool block ids
    valid_ref,    # (rows,) int32 valid slot count per row (ctx + new real)
    base_ref,     # (rows,) int32 slot of each row's first query token
    # blocks (VMEM)
    q_ref,        # (1, s, n, h)
    k_ref,        # (1, block_size, n_kv, h) pool dtype (or int8)
    v_ref,
    *rest,        # [scale_k_ref, scale_v_ref,] o_ref, m_ref, l_ref, acc_ref
    block_size: int,
    sm_scale: float,
    num_repeat_kv: int,
    quantized: bool,
):
    if quantized:
        scale_k_ref, scale_v_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        scale_k_ref, scale_v_ref = None, None
        o_ref, m_ref, l_ref, acc_ref = rest
    row = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    valid_len = valid_ref[row]

    @pl.when(j * block_size < valid_len)
    def _block():
        q = q_ref[0].astype(jnp.float32)  # (s, n, h)
        k = k_ref[0].astype(jnp.float32)  # (bs, n_kv, h)
        v = v_ref[0].astype(jnp.float32)
        if quantized:
            # dequant-in-kernel: the same kv_quantize_int8 scales the pool
            # writer produced; the f32 window never round-trips HBM
            k = k * scale_k_ref[0].astype(jnp.float32)[..., None]
            v = v * scale_v_ref[0].astype(jnp.float32)[..., None]
        if num_repeat_kv > 1:
            bs, n_kv, h = k.shape
            k = jnp.broadcast_to(
                k[:, :, None, :], (bs, n_kv, num_repeat_kv, h)
            ).reshape(bs, n_kv * num_repeat_kv, h)
            v = jnp.broadcast_to(
                v[:, :, None, :], (bs, n_kv, num_repeat_kv, h)
            ).reshape(bs, n_kv * num_repeat_kv, h)
        s = q.shape[0]
        scores = jnp.einsum("snh,knh->snk", q, k) * sm_scale  # (s, n, bs)
        # logical slots this grid step covers, vs each query's slot
        slot = j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, 1, block_size), 2
        )
        q_slot = base_ref[row] + jax.lax.broadcasted_iota(
            jnp.int32, (s, 1, 1), 0
        )
        allowed = (slot < valid_len) & (slot <= q_slot)
        scores = jnp.where(allowed, scores, -jnp.inf)
        # online softmax: all-masked tails keep m at -inf; the safe shift
        # avoids exp(-inf - -inf) = nan without branching
        m_old = m_ref[...]  # (s, n)
        m_new = jnp.maximum(m_old, scores.max(axis=-1))
        m_safe = jnp.where(m_new == -jnp.inf, 0.0, m_new)
        p = jnp.where(allowed, jnp.exp(scores - m_safe[..., None]), 0.0)
        alpha = jnp.where(m_old == -jnp.inf, 0.0, jnp.exp(m_old - m_safe))
        l_ref[...] = alpha * l_ref[...] + p.sum(axis=-1)
        acc_ref[...] = (
            alpha[..., None] * acc_ref[...] + jnp.einsum("snk,knh->snh", p, v)
        )
        m_ref[...] = m_new

    @pl.when(j == pl.num_programs(1) - 1)
    def _finalize():
        l = l_ref[...]
        # rows with zero visible slots (fully-trash inactive rows can't
        # reach here, but keep the guard total) emit zeros, not nan
        o_ref[0] = (
            acc_ref[...] / jnp.where(l == 0.0, 1.0, l)[..., None]
        ).astype(o_ref.dtype)


def paged_decode_attention(
    q: jax.Array,               # (rows, s, n, h) rotary-applied queries
    pool_k: jax.Array,          # (num_blocks, block_size, n_kv, h)
    pool_v: jax.Array,
    block_table: jax.Array,     # (rows, max_blocks) int32; 0 = trash
    valid_len: jax.Array,       # (rows,) int32 slots visible per row
    q_slot_base: jax.Array,     # (rows,) int32 slot of first query token
    *,
    sm_scale: float,
    num_repeat_kv: int = 1,
    scale_k: Optional[jax.Array] = None,  # (num_blocks, block_size, n_kv)
    scale_v: Optional[jax.Array] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Flash-style paged attention over a block pool; returns (rows, s, n, h).

    The pool must already contain the query tokens' K/V (the caller
    scatters through ``nn.attention.paged_scatter_kv`` first — ONE pool
    writer, so kernel and XLA fallback read identical bytes)."""
    _ensure_pallas()
    rows, s, n, h = q.shape
    _, block_size, n_kv, _ = pool_k.shape
    max_blocks = block_table.shape[1]
    quantized = scale_k is not None
    if interpret is None:
        interpret = paged_kernel_interpret()

    def _row(bi, j, tab, valid, base):
        del j, tab, valid, base
        return (bi, 0, 0, 0)

    def _blk(bi, j, tab, valid, base):
        del valid, base
        return (tab[bi, j], 0, 0, 0)

    def _blk_scale(bi, j, tab, valid, base):
        del valid, base
        return (tab[bi, j], 0, 0)

    in_specs = [
        pl.BlockSpec((1, s, n, h), _row),
        pl.BlockSpec((1, block_size, n_kv, h), _blk),
        pl.BlockSpec((1, block_size, n_kv, h), _blk),
    ]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, block_size, n_kv), _blk_scale),
            pl.BlockSpec((1, block_size, n_kv), _blk_scale),
        ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(rows, max_blocks),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, s, n, h), _row),
        scratch_shapes=[
            pltpu.VMEM((s, n), jnp.float32),      # running max m
            pltpu.VMEM((s, n), jnp.float32),      # normalizer l
            pltpu.VMEM((s, n, h), jnp.float32),   # unnormalized accumulator
        ],
    )
    kernel = functools.partial(
        _paged_attention_kernel,
        block_size=block_size, sm_scale=sm_scale,
        num_repeat_kv=num_repeat_kv, quantized=quantized,
    )
    operands = [q, pool_k, pool_v]
    if quantized:
        operands += [scale_k, scale_v]
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(
        block_table.astype(jnp.int32),
        valid_len.astype(jnp.int32),
        q_slot_base.astype(jnp.int32),
        *operands,
    )
