"""Tensor-parallel linear layers and vocab-parallel embedding.

Capability parity with the reference's Column/Row/VocabParallel layers
(reference: src/scaling/core/nn/linear/column_parallel_linear.py:23,
row_parallel_linear.py:16, vocab_parallel_embedding.py:19), re-designed for
GSPMD: weights carry PartitionSpecs over the ``model`` mesh axis and
activation sharding constraints make XLA emit the same collectives the
reference hand-rolls (copy-to-region, all-gather, all-reduce,
reduce-scatter-to-sequence-parallel). Weight layout is (in, out) —
jnp convention — vs the reference's torch (out, in).

``parallel_output`` / ``parallel_input`` keep the reference's fusion
contract: a column-parallel with ``parallel_output=True`` feeds a
row-parallel with ``parallel_input=True`` without leaving the TP region.
"""

from __future__ import annotations

import math
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from ..parallel.sharding import (
    constrain,
    shard_activation_replicated_h,
    shard_activation_sp,
    shard_activation_tp,
)
from ..topology.topology import DATA_AXIS, MODEL_AXIS
from .base_layer import BaseLayer, ForwardContext
from .param import ParamMeta, model_parallel_meta, replicated_meta


def xavier_normal_init(key: jax.Array, shape: tuple, dtype=jnp.float32) -> jax.Array:
    fan_in, fan_out = shape[0], shape[1]
    std = math.sqrt(2.0 / (fan_in + fan_out))
    return (jax.random.normal(key, shape) * std).astype(dtype)


def normal_init(std: float) -> Callable:
    def init(key: jax.Array, shape: tuple, dtype=jnp.float32) -> jax.Array:
        return (jax.random.normal(key, shape) * std).astype(dtype)

    return init


class ColumnParallelLinear(BaseLayer):
    """Y = X W + b with W's output dim sharded over the model axis."""

    def __init__(
        self,
        in_features: int,
        out_features: int,
        bias: bool = True,
        dtype=jnp.float32,
        init_method: Callable = xavier_normal_init,
        bitfit_bias_name: Optional[str] = None,
        parallel_output: bool = False,
    ):
        self.in_features = in_features
        self.out_features = out_features
        self.use_bias = bias
        self.dtype = dtype
        self.init_method = init_method
        self.bitfit_bias_name = bitfit_bias_name
        self.parallel_output = parallel_output

    @property
    def bias_name(self) -> str:
        return f"bias_{self.bitfit_bias_name}" if self.bitfit_bias_name else "bias"

    def init(self, key: jax.Array) -> dict:
        params = {"weight": self.init_method(key, (self.in_features, self.out_features), self.dtype)}
        if self.use_bias:
            params[self.bias_name] = jnp.zeros((self.out_features,), dtype=self.dtype)
        return params

    def param_metas(self) -> dict:
        metas = {
            "weight": model_parallel_meta(1, parameter_name="weight"),
        }
        if self.use_bias:
            metas[self.bias_name] = ParamMeta(
                parameter_name=self.bias_name,
                partition_spec=(MODEL_AXIS,),
                is_model_parallel=True,
                model_parallel_dimension=0,
            )
        return metas

    def __call__(self, params: dict, x: jax.Array, ctx: ForwardContext) -> jax.Array:
        # entering the TP region: under SP the input arrives seq-sharded and
        # XLA all-gathers it here (reference skips the copy op under SP)
        y = x @ params["weight"].astype(x.dtype)
        if self.use_bias:
            y = y + params[self.bias_name].astype(x.dtype)
        if y.ndim == 3:
            if self.parallel_output:
                y = shard_activation_tp(y, ctx.mesh)
            else:
                y = shard_activation_replicated_h(y, ctx.mesh)
        return y


class RowParallelLinear(BaseLayer):
    """Y = X W + b with W's input dim sharded over the model axis."""

    def __init__(
        self,
        in_features: int,
        out_features: int,
        bias: bool = True,
        dtype=jnp.float32,
        init_method: Callable = xavier_normal_init,
        bitfit_bias_name: Optional[str] = None,
        parallel_input: bool = True,
        parallel_output: bool = False,
    ):
        self.in_features = in_features
        self.out_features = out_features
        self.use_bias = bias
        self.dtype = dtype
        self.init_method = init_method
        self.bitfit_bias_name = bitfit_bias_name
        self.parallel_input = parallel_input
        self.parallel_output = parallel_output  # True => reduce-scatter to SP

    @property
    def bias_name(self) -> str:
        return f"bias_{self.bitfit_bias_name}" if self.bitfit_bias_name else "bias"

    def init(self, key: jax.Array) -> dict:
        params = {"weight": self.init_method(key, (self.in_features, self.out_features), self.dtype)}
        if self.use_bias:
            params[self.bias_name] = jnp.zeros((self.out_features,), dtype=self.dtype)
        return params

    def param_metas(self) -> dict:
        metas = {"weight": model_parallel_meta(0, parameter_name="weight")}
        if self.use_bias:
            # bias added after the reduce => replicated, mp-duplicate
            metas[self.bias_name] = replicated_meta(1, parameter_name=self.bias_name)
        return metas

    def __call__(self, params: dict, x: jax.Array, ctx: ForwardContext) -> jax.Array:
        y = x @ params["weight"].astype(x.dtype)
        if y.ndim == 3:
            if self.parallel_output and ctx.sequence_parallel:
                # leave the TP region into sequence-parallel layout:
                # XLA lowers this to a reduce-scatter along seq
                y = shard_activation_sp(y, ctx.mesh)
            else:
                # all-reduce over the model axis (partial sums -> full)
                y = shard_activation_replicated_h(y, ctx.mesh)
        if self.use_bias:
            y = y + params[self.bias_name].astype(x.dtype)
        return y


class VocabParallelEmbedding(BaseLayer):
    """Embedding with the vocabulary sharded over the model axis."""

    def __init__(
        self,
        num_embeddings: int,
        embedding_dim: int,
        dtype=jnp.float32,
        init_method: Callable = xavier_normal_init,
        finetunable_token_ids: Optional[list[int]] = None,
    ):
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.dtype = dtype
        self.init_method = init_method
        self.finetunable_token_ids = finetunable_token_ids or []

    def init(self, key: jax.Array) -> dict:
        return {
            "weight": self.init_method(key, (self.num_embeddings, self.embedding_dim), self.dtype)
        }

    def param_metas(self) -> dict:
        return {
            "weight": ParamMeta(
                parameter_name="weight",
                partition_spec=(MODEL_AXIS, None),
                is_model_parallel=True,
                model_parallel_dimension=0,
                lr_group="embedding",
            )
        }

    def __call__(self, params: dict, token_ids: jax.Array, ctx: ForwardContext) -> jax.Array:
        # gather from the vocab-sharded table; XLA handles the out-of-shard
        # masking + psum that the reference hand-codes
        weight = params["weight"]
        y = weight.astype(self.dtype)[token_ids]
        if ctx.sequence_parallel:
            y = shard_activation_sp(y, ctx.mesh)
        else:
            y = shard_activation_replicated_h(y, ctx.mesh)
        return y

    def finetunable_grad_mask(self) -> Optional[jax.Array]:
        """0/1 row mask for finetunable-token-only training; None if unused."""
        if not self.finetunable_token_ids:
            return None
        mask = jnp.zeros((self.num_embeddings, 1), dtype=jnp.float32)
        return mask.at[jnp.array(self.finetunable_token_ids)].set(1.0)
