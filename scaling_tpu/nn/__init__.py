from .activation_function import ActivationFunction, get_activation_function
from .attention import (
    PagedKVCacheView,
    ParallelSelfAttention,
    multi_head_attention,
    repeat_kv,
)
from .paged_attention import paged_decode_attention
from .base_layer import BaseLayer, ForwardContext, LayerSpec, PipelineBodySpec, TiedLayerSpec
from .linear import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
    normal_init,
    xavier_normal_init,
)
from .lora import LoRAModuleType, LoRaConfig, ParallelLoRa
from .masked_softmax import MaskedSoftmax, MaskedSoftmaxConfig, MaskedSoftmaxKernel
from .mlp import ParallelMLP, ParallelSwiGLUMLP
from .norm import (
    LayerNorm,
    LayerNormConfig,
    LayerNormOptimizationType,
    NormType,
    RMSNorm,
    get_norm,
)
from .param import ParamMeta, model_parallel_meta, named_parameters, replicated_meta, tree_prefix, tree_with_layer
from .rotary import (
    RelativePositionEmbeddingType,
    RotaryConfig,
    RotaryEmbedding,
    RotaryEmbeddingComplex,
)
from .seq_packing import (
    add_cumulative_seq_lengths_padding,
    cumulative_seq_lengths_to_segment_ids,
    get_cumulative_seq_lengths,
    get_position_ids,
    remove_cumulative_seq_lengths_padding,
    segment_ids_to_mask,
)

__all__ = [
    "ActivationFunction",
    "get_activation_function",
    "PagedKVCacheView",
    "ParallelSelfAttention",
    "multi_head_attention",
    "paged_decode_attention",
    "repeat_kv",
    "BaseLayer",
    "ForwardContext",
    "LayerSpec",
    "PipelineBodySpec",
    "TiedLayerSpec",
    "ColumnParallelLinear",
    "RowParallelLinear",
    "VocabParallelEmbedding",
    "normal_init",
    "xavier_normal_init",
    "LoRAModuleType",
    "LoRaConfig",
    "ParallelLoRa",
    "MaskedSoftmax",
    "MaskedSoftmaxConfig",
    "MaskedSoftmaxKernel",
    "ParallelMLP",
    "ParallelSwiGLUMLP",
    "LayerNorm",
    "LayerNormConfig",
    "LayerNormOptimizationType",
    "NormType",
    "RMSNorm",
    "get_norm",
    "ParamMeta",
    "model_parallel_meta",
    "named_parameters",
    "replicated_meta",
    "tree_prefix",
    "tree_with_layer",
    "RelativePositionEmbeddingType",
    "RotaryConfig",
    "RotaryEmbedding",
    "RotaryEmbeddingComplex",
    "add_cumulative_seq_lengths_padding",
    "cumulative_seq_lengths_to_segment_ids",
    "get_cumulative_seq_lengths",
    "get_position_ids",
    "remove_cumulative_seq_lengths_padding",
    "segment_ids_to_mask",
]
