from .checkpoint import (
    AsyncCheckpointWriter,
    load_model_checkpoint,
    load_optimizer_checkpoint,
    save_model_checkpoint,
    save_optimizer_checkpoint,
)

__all__ = [
    "AsyncCheckpointWriter",
    "load_model_checkpoint",
    "load_optimizer_checkpoint",
    "save_model_checkpoint",
    "save_optimizer_checkpoint",
]
