"""Orbax/tensorstore checkpoint backend: sharded collective save/restore.

The multi-host-scale alternative to the npz per-layer format: every
process writes only the shards its devices hold, and restore re-shards to
the caller's target layout. Free functions so both the trainer
(`trainer.BaseTrainer._save_orbax` etc.) and multi-process tests drive
the same product code. All entry points are COLLECTIVE — call them on
every process.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any, Dict

import jax


def orbax_abstract(tree: Any) -> Any:
    """ShapeDtypeStruct targets carrying the current leaves' shardings."""
    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(
            x.shape, x.dtype, sharding=getattr(x, "sharding", None)
        ),
        tree,
    )


def _empty(leaf: Any) -> bool:
    return getattr(leaf, "size", 1) == 0


def _sentinel_empties(tree: Any) -> Any:
    """Replace zero-size leaves with a 1-element zero of the same dtype.

    PEFT optimizer states carry ``(0,)`` placeholders for frozen-backbone
    leaves (optimizer.py init_state), and orbax refuses zero-size arrays
    outright ("Cannot save arrays with zero size") — a LoRA finetune with
    checkpoint_backend=orbax would crash at its first save. The sentinel
    keeps the tree structure identical both ways; restore discards the
    sentinel values and keeps the live placeholders
    (``_restore_keeping_empties``), which also preserves their
    uncommitted placement (the npz loader once committed them to one
    device, breaking the next jitted step under a mesh).

    Sentinels are built REPLICATED over the mesh of an adjacent real leaf
    when one exists: every entry point here is collective, and a plain
    per-process ``jnp.zeros`` would be a host-local array that orbax
    cannot treat as one global tensor on multi-host."""
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec

    mesh = None
    for leaf in jax.tree.leaves(tree):
        sh = getattr(leaf, "sharding", None)
        if isinstance(sh, NamedSharding):
            mesh = sh.mesh
            break

    def sentinel(x):
        if mesh is None:
            return jnp.zeros((1,), x.dtype)
        return jax.make_array_from_callback(
            (1,),
            NamedSharding(mesh, PartitionSpec()),
            lambda idx: np.zeros((1,), x.dtype),
        )

    return jax.tree.map(lambda x: sentinel(x) if _empty(x) else x, tree)


def _restore_keeping_empties(current: Any, restored: Any) -> Any:
    return jax.tree.map(
        lambda cur, res: cur if _empty(cur) else res, current, restored
    )


def save_orbax(step_dir: Path, params_view: Any, opt_view: Dict[str, Any]) -> None:
    """Write ``step_dir/orbax/{model,optimizer}``; overwrites an existing
    save of the same step (crash-recovery re-reaches steps)."""
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save((step_dir / "orbax" / "model").absolute(), params_view, force=True)
        ckptr.save(
            (step_dir / "orbax" / "optimizer").absolute(),
            _sentinel_empties(opt_view),
            force=True,
        )


def _committed(d: Path) -> bool:
    """True when ``d`` is a finalized orbax checkpoint directory.

    Uses orbax's own finalization predicate (atomic-rename storage commits
    by the final dir appearing; commit-file storage by ``commit_success``),
    plus the pytree ``_METADATA`` file as a guard against in-place
    corruption that the rename semantics cannot see."""
    if not d.is_dir():
        return False
    import orbax.checkpoint as ocp

    return bool(ocp.utils.is_checkpoint_finalized(d)) and (d / "_METADATA").is_file()


def orbax_model_valid(step_dir: Path) -> bool:
    """True when ``step_dir/orbax/model`` is a COMMITTED orbax checkpoint.
    Callers use this to avoid letting a torn orbax save shadow valid npz
    files in the same step directory."""
    return _committed(step_dir / "orbax" / "model")


def restore_orbax_params(
    step_dir: Path,
    params_view_like: Any,
    metas: Any = None,
    allowed_missing_keys: Any = None,
    allowed_unexpected_keys: Any = None,
    ignore_keys: Any = None,
    restored_keys: Any = None,
) -> Any:
    """Restore the param view tree, re-sharded to ``params_view_like``'s
    current layout (orbax reads each shard from tensorstore).

    With ``metas`` (the matching ``ckpt_metas()`` view tree) the restore is
    NON-STRICT under the same allow-list regexes as the npz loader
    (reference: ``load_model_checkpoint``): keys of the current model absent
    from the checkpoint must match ``allowed_missing_keys`` (kept at their
    current/re-initialised values — the PEFT path), checkpoint-only keys
    must match ``allowed_unexpected_keys`` (dropped), and ``ignore_keys``
    keeps current values even when the checkpoint has them. Without
    ``metas`` the restore is strict, as before."""
    import orbax.checkpoint as ocp

    model_dir = (step_dir / "orbax" / "model").absolute()
    with ocp.StandardCheckpointer() as ckptr:
        if metas is None:
            return ckptr.restore(model_dir, orbax_abstract(params_view_like))

        import jax.tree_util as jtu

        from .checkpoint import (
            _compile_patterns,
            _matches_any,
            _meta_leaves,
            enforce_allow_lists,
        )

        allowed_missing = _compile_patterns(allowed_missing_keys)
        allowed_unexpected = _compile_patterns(allowed_unexpected_keys)
        ignore = _compile_patterns(ignore_keys)

        cur_flat, cur_treedef = jtu.tree_flatten_with_path(params_view_like)
        m_leaves = _meta_leaves(metas)
        assert len(cur_flat) == len(m_leaves), (
            f"params/metas mismatch: {len(cur_flat)} vs {len(m_leaves)}"
        )
        key_by_path = {path: m.key for (path, _), m in zip(cur_flat, m_leaves)}
        # view top-level name ("layer_{i}") -> (index, class), so
        # checkpoint-only keys inside a layer the model HAS print in the
        # same "layer_{i}_{Class}.{name}" format the npz loader uses and
        # npz-written allow-list regexes match unchanged. A WHOLE layer the
        # model lacks has no recoverable class (the orbax tree stores only
        # "layer_{i}" keys), so those print as the dotted path
        # ("layer_12.attn.weight") — allow-lists dropping whole layers must
        # match that form.
        layer_info = {
            str(getattr(path[0], "key", path[0])): (m.layer_index, m.layer_class_name)
            for (path, _), m in zip(cur_flat, m_leaves)
        }

        # orbax API drift: newer releases wrap the saved-tree metadata in
        # CheckpointMetadata (.item_metadata.tree); orbax 0.7.x returns
        # the tree directly from StandardCheckpointer.metadata()
        saved_tree = ckptr.metadata(model_dir)
        for attr in ("item_metadata", "tree"):
            saved_tree = getattr(saved_tree, attr, saved_tree)
        saved_by_path = dict(jtu.tree_flatten_with_path(saved_tree)[0])

        def saved_key(path) -> str:
            parts = [str(getattr(k, "key", k)) for k in path]
            info = layer_info.get(parts[0])
            if info is not None and len(parts) > 1:
                return f"layer_{info[0]}_{info[1]}." + ".".join(parts[1:])
            return ".".join(parts)

        # shared paths print as their meta key on both sides, so the diff
        # runs in the npz loader's key space with its exact contract
        enforce_allow_lists(
            key_by_path.values(),
            (saved_key(p) for p in saved_by_path),
            allowed_missing,
            allowed_unexpected,
        )

        # restore ONLY the intersection (shared, non-ignored paths), each at
        # the current leaf's dtype + sharding (orbax casts and re-shards).
        # partial_restore skips everything absent from the target tree, so
        # ignored and checkpoint-only leaves cost no tensorstore reads and
        # no unsharded host materialization — like the npz loader, which
        # never opens them.
        subset: dict = {}
        n_wanted = 0
        for path, cur in cur_flat:
            md = saved_by_path.get(path)
            if md is None or _matches_any(key_by_path[path], ignore):
                continue
            if tuple(md.shape) != tuple(cur.shape):
                raise ValueError(
                    f"shape mismatch for {key_by_path[path]}: checkpoint "
                    f"{tuple(md.shape)} vs model {tuple(cur.shape)}"
                )
            if restored_keys is not None:
                restored_keys.add(key_by_path[path])
            node = subset
            parts = [str(getattr(k, "key", k)) for k in path]
            for k in parts[:-1]:
                node = node.setdefault(k, {})
            node[parts[-1]] = jax.ShapeDtypeStruct(
                tuple(md.shape), cur.dtype, sharding=getattr(cur, "sharding", None)
            )
            n_wanted += 1
        restored_by_path: dict = {}
        if n_wanted:
            # PyTreeRestore ignores the sharding on ShapeDtypeStruct items
            # (it re-reads the SAVED sharding file), so relayout targets
            # must go through explicit ArrayRestoreArgs
            restore_args = jax.tree.map(
                lambda sds: ocp.ArrayRestoreArgs(
                    sharding=sds.sharding, global_shape=sds.shape, dtype=sds.dtype
                ),
                subset,
            )
            with ocp.PyTreeCheckpointer() as pt_ckptr:
                try:
                    restored = pt_ckptr.restore(
                        model_dir,
                        ocp.args.PyTreeRestore(
                            item=subset,
                            restore_args=restore_args,
                            partial_restore=True,
                        ),
                    )
                except TypeError:
                    # orbax API drift: 0.7.x has no partial_restore — fall
                    # back to restoring the FULL saved tree (unwanted
                    # leaves read at their saved layout, then dropped).
                    # Costs extra tensorstore reads on old orbax only;
                    # the targeted subset still lands re-sharded/cast.
                    full_item: dict = {}
                    full_args: dict = {}

                    def _nest(root, path, value):
                        node = root
                        parts = [str(getattr(k, "key", k)) for k in path]
                        for k in parts[:-1]:
                            node = node.setdefault(k, {})
                        node[parts[-1]] = value

                    sub_flat = dict(jtu.tree_flatten_with_path(subset)[0])
                    arg_flat = dict(jtu.tree_flatten_with_path(restore_args)[0])
                    for path, md in saved_by_path.items():
                        if path in sub_flat:
                            _nest(full_item, path, sub_flat[path])
                            _nest(full_args, path, arg_flat[path])
                        else:
                            _nest(full_item, path, jax.ShapeDtypeStruct(
                                tuple(md.shape), md.dtype))
                            _nest(full_args, path, ocp.ArrayRestoreArgs())
                    restored_full = pt_ckptr.restore(
                        model_dir,
                        ocp.args.PyTreeRestore(
                            item=full_item, restore_args=full_args
                        ),
                    )
                    full_by_path = dict(
                        jtu.tree_flatten_with_path(restored_full)[0]
                    )
                    restored = jtu.tree_unflatten(
                        jtu.tree_structure(subset),
                        [full_by_path[p] for p in sub_flat],
                    )
            restored_by_path = dict(jtu.tree_flatten_with_path(restored)[0])
        new_leaves = [restored_by_path.get(path, cur) for path, cur in cur_flat]
        # every wanted leaf must have round-tripped through the rebuilt
        # plain-dict subset — a path-format mismatch would otherwise keep
        # random init values silently
        n_merged = sum(1 for path, _ in cur_flat if path in restored_by_path)
        assert n_merged == n_wanted == len(restored_by_path), (
            f"orbax restore path mismatch: wanted {n_wanted}, restored "
            f"{len(restored_by_path)}, merged {n_merged}"
        )
        return jtu.tree_unflatten(cur_treedef, new_leaves)


def restore_orbax_opt(step_dir: Path, opt_view_like: Dict[str, Any]) -> Dict[str, Any]:
    """Restore the optimizer view dict.

    Raises FileNotFoundError when the tree is ABSENT — callers fall back to
    fresh state, matching the npz backend: an absent tree is
    indistinguishable from deliberate pruning (``delete_past_optimizer_
    states``, disk-saving rmtree), and on atomic-rename storage a crash
    mid-save also leaves the dir absent. Raises OSError when the tree is
    PRESENT but uncommitted (torn in place / commit-file storage without
    its commit marker) — that is never deliberate, so the resume aborts
    instead of silently resetting Adam moments."""
    import orbax.checkpoint as ocp

    opt_dir = step_dir / "orbax" / "optimizer"
    if not opt_dir.is_dir():
        raise FileNotFoundError(str(opt_dir))
    if not _committed(opt_dir):
        raise OSError(
            f"{opt_dir} exists but is not a committed orbax checkpoint "
            "(torn save?); delete it to resume with fresh optimizer state"
        )
    with ocp.StandardCheckpointer() as ckptr:
        restored = ckptr.restore(
            opt_dir.absolute(), orbax_abstract(_sentinel_empties(opt_view_like))
        )
    return _restore_keeping_empties(opt_view_like, restored)
