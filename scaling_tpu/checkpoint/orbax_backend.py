"""Orbax/tensorstore checkpoint backend: sharded collective save/restore.

The multi-host-scale alternative to the npz per-layer format: every
process writes only the shards its devices hold, and restore re-shards to
the caller's target layout. Free functions so both the trainer
(`trainer.BaseTrainer._save_orbax` etc.) and multi-process tests drive
the same product code. All entry points are COLLECTIVE — call them on
every process.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any, Dict

import jax


def orbax_abstract(tree: Any) -> Any:
    """ShapeDtypeStruct targets carrying the current leaves' shardings."""
    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(
            x.shape, x.dtype, sharding=getattr(x, "sharding", None)
        ),
        tree,
    )


def save_orbax(step_dir: Path, params_view: Any, opt_view: Dict[str, Any]) -> None:
    """Write ``step_dir/orbax/{model,optimizer}``; overwrites an existing
    save of the same step (crash-recovery re-reaches steps)."""
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save((step_dir / "orbax" / "model").absolute(), params_view, force=True)
        ckptr.save(
            (step_dir / "orbax" / "optimizer").absolute(), opt_view, force=True
        )


def restore_orbax_params(step_dir: Path, params_view_like: Any) -> Any:
    """Restore the param view tree, re-sharded to ``params_view_like``'s
    current layout (orbax reads each shard from tensorstore)."""
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        return ckptr.restore(
            (step_dir / "orbax" / "model").absolute(),
            orbax_abstract(params_view_like),
        )


def restore_orbax_opt(step_dir: Path, opt_view_like: Dict[str, Any]) -> Dict[str, Any]:
    """Restore the optimizer view dict; raises FileNotFoundError when the
    tree is absent (callers fall back to fresh state)."""
    import orbax.checkpoint as ocp

    opt_dir = step_dir / "orbax" / "optimizer"
    if not opt_dir.is_dir():
        raise FileNotFoundError(str(opt_dir))
    with ocp.StandardCheckpointer() as ckptr:
        return ckptr.restore(opt_dir.absolute(), orbax_abstract(opt_view_like))
