"""Export this framework's checkpoints to the reference's torch format.

The other half of the migration path (see ``import_reference.py``): users
who trained here can hand a checkpoint back to the reference repo's
tooling (``model_state_layer_{i}_{Class}.pt`` files, reference:
partitioned_module.py:197-257). Exactly the importer's mapping, inverted:

- our ``(in, out)`` 2-D projection weights transpose back to torch
  ``nn.Linear``'s ``(out, in)``;
- ``attention.`` renames to the reference's ``self_attention.``;
- bottleneck Adapter ``down``/``up`` factors become the reference's
  ``{attn,mlp}_adapter_{n}.dense_{in,out}.weight`` ParallelMLP naming
  (reference: layer.py:147-181);
- PEFT side files ``{Class}__{name}.npz`` become the reference's
  single-underscore ``{Class}_{name}.pt``;
- structurally-tied LM heads regain the reference's duplicated embedding
  table in ``TransformerLMHeadTied.pt`` (its state dict holds the shared
  ``embedding.weight``, reference: lm_head_tied.py:27-40).

Round-trip (export -> import) is bit-exact:
tests/transformer/test_reference_weight_import.py.
"""

from __future__ import annotations

import re
from pathlib import Path
from typing import Any, Dict

import numpy as np
import yaml

from .import_reference import _LINEAR_HOSTS


def _unmap_param(name: str, arr: np.ndarray):
    """our per-layer param name -> (reference name, reference array)."""
    m = re.match(r"adapter_(attention|mlp)_([^.]+)\.(down|up)$", name)
    if m:
        host = "attn" if m.group(1) == "attention" else "mlp"
        direction = "in" if m.group(3) == "down" else "out"
        ref = f"{host}_adapter_{m.group(2)}.dense_{direction}.weight"
        return ref, np.ascontiguousarray(arr.T)
    if (
        arr.ndim == 2
        and name.endswith(".weight")
        and any(h in name for h in _LINEAR_HOSTS)
        and not name.startswith("embedding.")
    ):
        arr = np.ascontiguousarray(arr.T)
    return name.replace("attention.", "self_attention."), arr


def export_layer(arrays: Dict[str, np.ndarray]) -> Dict[str, Any]:
    """One of our layers' arrays -> a reference-format torch state dict."""
    import torch

    out: Dict[str, Any] = {}
    for name, arr in arrays.items():
        ref_name, ref_arr = _unmap_param(name, np.asarray(arr))
        ref_arr = np.ascontiguousarray(ref_arr)
        # dtype matched by NAME so the npz->pt conversion path keeps its
        # numpy+torch-only dependency footprint (no jax import)
        if ref_arr.dtype.name == "bfloat16":
            # torch.from_numpy rejects ml_dtypes outright; the bit pattern
            # is torch.bfloat16's, so view through uint16 (npz-sourced
            # exports arrive as float32 already — checkpoint.py widens)
            tensor = torch.from_numpy(ref_arr.view(np.uint16)).view(torch.bfloat16)
        else:
            tensor = torch.from_numpy(ref_arr)
        out[ref_name] = tensor
    return out


def export_reference_checkpoint(src_dir: Path | str, dst_dir: Path | str) -> int:
    """Our npz checkpoint directory -> reference .pt files; returns the
    number of files written. ``src_dir`` may be the save root (with a
    ``latest`` pointer) or a ``global_step{N}`` directory."""
    import torch

    from ..resilience.guards import retry_io

    src = Path(src_dir)
    latest = src / "latest"
    if latest.is_file():
        src = src / retry_io(
            latest.read_text, what="latest pointer read"
        ).strip()
    dst = Path(dst_dir)
    dst.mkdir(parents=True, exist_ok=True)

    config_file = src / "config.yml"
    cfg = (
        yaml.safe_load(retry_io(
            config_file.read_text, what="export config read"
        )) or {}
        if config_file.is_file()
        else {}
    )
    arch = cfg.get("transformer_architecture", {})
    # npz checkpoints arrive pre-widened (checkpoint._write_npz stores bf16
    # as lossless float32); when the configured precision is bfloat16, cast
    # back so BOTH export paths (live params / npz round trip) produce the
    # same on-disk torch.bfloat16 (ADVICE r5)
    cast_bf16 = arch.get("precision") == "bfloat16"
    if cast_bf16:
        import ml_dtypes

        def _restore_precision(arr: np.ndarray) -> np.ndarray:
            if arr.dtype == np.float32:
                return arr.astype(ml_dtypes.bfloat16)
            return arr
    else:
        def _restore_precision(arr: np.ndarray) -> np.ndarray:
            return arr

    written = 0
    embedding_table = None
    norm_index = None
    for f in sorted(src.glob("model_state_layer_*.npz")):
        m = re.match(r"model_state_layer_(\d+)_(.+)\.npz", f.name)
        if m is None:
            continue
        layer_index = int(m.group(1))
        stem = m.group(2)
        if "__" in stem:  # PEFT side file: our double underscore -> single
            cls, suffix = stem.split("__", 1)
            ref_stem = f"model_state_layer_{layer_index}_{cls}_{suffix}"
        else:
            ref_stem = f"model_state_layer_{layer_index}_{stem}"
            if stem == "LayerNormWrapper":
                norm_index = layer_index
        arrays = {
            k: _restore_precision(np.asarray(v))
            for k, v in np.load(f).items()
        }
        if layer_index == 0 and "embedding.weight" in arrays:
            embedding_table = np.asarray(arrays["embedding.weight"])
        torch.save(export_layer(arrays), dst / f"{ref_stem}.pt")
        written += 1

    # tied models hold one structural copy of the table; the reference's
    # checkpoint format expects the duplicate in the tied head's file. The
    # head's slot is the final norm's index + 1 (get_transformer_layer_specs
    # order: embedding, layers, LayerNormWrapper, head[, embedding head]) —
    # NOT max-index + 1, which an embedding-head or PEFT side file after
    # the head's slot would push past the hole the head must fill.
    if arch.get("weight_tying") and embedding_table is not None:
        if norm_index is None:
            raise ValueError(
                "weight-tied checkpoint without a LayerNormWrapper "
                "layer file: cannot place the tied head's slot"
            )
        # export_layer (not a bare from_numpy) so a bf16-restored table
        # takes the same uint16-view path as every other bf16 array
        torch.save(
            export_layer({"embedding.weight": embedding_table}),
            dst / f"model_state_layer_{norm_index + 1}_TransformerLMHeadTied.pt",
        )
        written += 1
    return written
