"""Layout-independent checkpoints.

Mirrors the reference's artifact families
(reference: src/scaling/core/nn/parallel_module/partitioned_module.py:197-371,
optimizer.py:335-734): per-layer model files named
``model_state_layer_{i}_{ClassName}.npz`` holding merged (unsharded) arrays
keyed by parameter path; per-layer optimizer files
``optimizer_state_layer_{i}.npz`` with master/exp_avg/exp_avg_sq; parameters
matched by ``ParamMeta.key`` so checkpoints survive topology changes (jax
re-shards on load via the current metas — the reference's merge/split
broadcast loops disappear).

Non-strict loading supports the reference's PEFT workflows: regex lists of
allowed-missing keys (fresh adapters), allowed-unexpected keys (dropping a
finetune), and ignored keys (reinit parts of a pretrained model).
"""

from __future__ import annotations

import json
import re
from concurrent.futures import Future, ThreadPoolExecutor
from pathlib import Path
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..logging import logger
from ..nn.param import ParamMeta


class AsyncCheckpointWriter:
    """Runs checkpoint file writes on a background thread.

    Arrays are fetched to host *before* submission (the jitted train step
    donates its input buffers, so device arrays must not outlive the call
    that scheduled the save) — only the np.savez disk I/O happens off the
    train loop. ``wait()`` blocks until all pending writes are durable;
    a new save waits for the previous one so files never interleave.

    Once any write fails, every later-submitted task of the same save is
    skipped (so e.g. the trailing commit/"latest" tasks never land on a
    partially-written checkpoint); the original exception re-raises from
    ``wait()`` — and ONLY from ``wait()``: the backpressure drain in
    ``submit`` records a writer failure instead of re-raising it on the
    submitting (train-loop) thread, which used to leave
    ``_pending``/``_failed`` inconsistent mid-loop. Submission applies
    backpressure past ``max_queued`` pending writes to bound host RAM at
    a few layers' worth of arrays.
    """

    def __init__(self, max_queued: int = 4) -> None:
        self._pool = ThreadPoolExecutor(max_workers=1, thread_name_prefix="ckpt-writer")
        self._pending: List[Future] = []
        self._max_queued = max_queued
        # first failure of the in-flight save; tasks record it here (so
        # futures themselves never carry exceptions) and later tasks
        # no-op while it is set
        self._first_error: Optional[BaseException] = None

    def submit(self, fn, *args) -> None:
        def run():
            if self._first_error is not None:
                return
            try:
                fn(*args)
            except BaseException as e:
                self._first_error = e
                logger.error(f"checkpoint writer task failed: {e!r}")

        while len([f for f in self._pending if not f.done()]) >= self._max_queued:
            # drain for backpressure only — failures stay recorded in
            # _first_error and re-raise from wait(), not here
            self._pending[0].result()
            self._pending.pop(0)
        self._pending.append(self._pool.submit(run))

    def wait(self) -> None:
        pending, self._pending = self._pending, []
        for f in pending:
            f.result()  # tasks never raise; this is a completion barrier
        err, self._first_error = self._first_error, None
        if err is not None:
            raise err  # a later save may retry on a healthy disk

    def close(self) -> None:
        self.wait()
        self._pool.shutdown(wait=True)


def _write_npz(path: Path, arrays: Dict[str, np.ndarray],
               recorder=None) -> None:
    import io
    import os

    from ..resilience.faults import get_fault_plan
    from ..resilience.guards import retry_io
    from ..resilience.manifest import crc32_bytes

    # numpy serializes ml_dtypes extension dtypes (bfloat16, fp8) as raw
    # void records that np.load returns as uncastable |V2 — store them as
    # float32 instead (lossless widening for bf16); the loader casts every
    # array back to the model's parameter dtype anyway
    arrays = {
        k: v.astype(np.float32) if v.dtype.kind == "V" else v
        for k, v in arrays.items()
    }
    # serialize once, off disk: the digest recorded for the manifest is of
    # the INTENDED bytes, so corruption introduced at/after the write
    # (torn page, bad DMA, injected) is caught by restore verification
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    data = buf.getvalue()
    if recorder is not None:
        recorder(path, len(data), crc32_bytes(data))

    def _put():
        act = get_fault_plan().fire("ckpt.write", path=path)
        with open(path, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        if act == "corrupt":
            get_fault_plan().corrupt_file(path)

    retry_io(_put, what=f"checkpoint write {path.name}")


def _emit(writer: Optional[AsyncCheckpointWriter], path: Path,
          arrays: Dict[str, np.ndarray], recorder=None) -> None:
    if writer is None:
        _write_npz(path, arrays, recorder)
    else:
        writer.submit(_write_npz, path, arrays, recorder)


def _meta_leaves(metas: Any) -> list[ParamMeta]:
    return jax.tree.leaves(metas, is_leaf=lambda x: isinstance(x, ParamMeta))


def _grouped_by_layer(params: Any, metas: Any):
    """-> {(layer_index, layer_class): {param_name: array}}"""
    p_leaves = jax.tree.leaves(params)
    m_leaves = _meta_leaves(metas)
    assert len(p_leaves) == len(m_leaves), (
        f"params/metas mismatch: {len(p_leaves)} vs {len(m_leaves)}"
    )
    groups: dict = {}
    for p, m in zip(p_leaves, m_leaves):
        groups.setdefault((m.layer_index, m.layer_class_name), {})[m.parameter_name] = p
    return groups


def save_model_checkpoint(
    dir: Path | str,
    params: Any,
    metas: Any,
    separate_file_for_parameters: Optional[List[str]] = None,
    writer: Optional[AsyncCheckpointWriter] = None,
    recorder=None,
) -> None:
    """One npz per layer; PEFT params split into ``..._{name}.npz`` files.

    Arrays are host-gathered here; with ``writer`` the disk writes happen on
    its background thread instead of blocking the train loop. ``recorder``
    (``CheckpointCommit.record``) collects each file's intended (size,
    crc32) for the integrity manifest.
    """
    path = Path(dir)
    path.mkdir(parents=True, exist_ok=True)
    for (layer_index, layer_class), group in _grouped_by_layer(params, metas).items():
        main = {}
        separate: dict[str, dict] = {}
        for name, arr in group.items():
            target = None
            for sep in separate_file_for_parameters or []:
                if sep in name:
                    target = sep
                    break
            np_arr = np.asarray(jax.device_get(arr))
            if target is None:
                main[name] = np_arr
            else:
                separate.setdefault(target, {})[name] = np_arr
        fname = f"model_state_layer_{layer_index}_{layer_class}.npz"
        if main:
            _emit(writer, path / fname, main, recorder)
        # double underscore separates the PEFT suffix from the class name so
        # the loader can recover the class unambiguously
        for sep, group_arrs in separate.items():
            sep_name = f"model_state_layer_{layer_index}_{layer_class}__{sep}.npz"
            _emit(writer, path / sep_name, group_arrs, recorder)


def _load_artifact(path: Path):
    """Open one checkpoint npz for leaf assembly. Fires the
    ``restore.assemble`` fault point (docs/RESILIENCE.md): an injected
    failure here is an OSError, so the trainer's bounded-retry load
    layer retries it and a persistent one demotes the candidate —
    restore falls back to the newest valid checkpoint instead of
    aborting mid-reshard."""
    from ..resilience.faults import get_fault_plan

    get_fault_plan().fire("restore.assemble", path=path)
    return np.load(path)


def _compile_patterns(patterns: Optional[List[str]]) -> list:
    return [re.compile(p) for p in (patterns or [])]


def _matches_any(key: str, patterns: list) -> bool:
    return any(p.search(key) for p in patterns)


def enforce_allow_lists(
    model_keys, available_keys, allowed_missing: list, allowed_unexpected: list
) -> None:
    """The non-strict loading contract, shared by the npz and orbax
    backends: model keys absent from the checkpoint must match the
    ``allowed_missing`` compiled patterns, checkpoint keys the model lacks
    must match ``allowed_unexpected``; anything else raises KeyError."""
    model_set, available_set = set(model_keys), set(available_keys)
    missing = sorted(
        k for k in model_set - available_set if not _matches_any(k, allowed_missing)
    )
    unexpected = sorted(
        k for k in available_set - model_set if not _matches_any(k, allowed_unexpected)
    )
    if missing:
        raise KeyError(
            f"checkpoint missing parameters: {missing[:8]}"
            f"{'...' if len(missing) > 8 else ''}"
        )
    if unexpected:
        raise KeyError(
            f"checkpoint has unexpected parameters: {unexpected[:8]}"
            f"{'...' if len(unexpected) > 8 else ''}"
        )


def load_model_checkpoint(
    dir: Path | str,
    params: Any,
    metas: Any,
    allowed_missing_keys: Optional[List[str]] = None,
    allowed_unexpected_keys: Optional[List[str]] = None,
    ignore_keys: Optional[List[str]] = None,
    restored_keys: Optional[set] = None,
) -> Any:
    """Returns a new params tree with checkpoint values loaded by key.

    Missing/unexpected keys raise unless matched by the corresponding
    allow-list regexes; ``ignore_keys`` keeps current (re-initialised)
    values even when the checkpoint has them. When ``restored_keys`` is a
    set, the meta key of every leaf actually taken from the checkpoint is
    added to it (callers use this to tell restored from re-initialised
    subtrees, e.g. the pretrained-CLIP splice gate).
    """
    path = Path(dir)
    allowed_missing = _compile_patterns(allowed_missing_keys)
    allowed_unexpected = _compile_patterns(allowed_unexpected_keys)
    ignore = _compile_patterns(ignore_keys)

    # index checkpoint contents: key -> (file, param_name)
    available: dict[str, tuple[Path, str]] = {}
    for f in sorted(path.glob("model_state_layer_*.npz")):
        with np.load(f) as z:
            stem = f.stem  # model_state_layer_{i}_{Class}[_{sep}]
            m = re.match(r"model_state_layer_(\d+)_(.+)", stem)
            layer_index = int(m.group(1))
            layer_class = m.group(2).split("__")[0]
            for name in z.files:
                key = f"layer_{layer_index}_{layer_class}.{name}"
                available[key] = (f, name)

    p_leaves, treedef = jax.tree.flatten(params)
    m_leaves = _meta_leaves(metas)
    model_keys = [m.key for m in m_leaves]

    enforce_allow_lists(model_keys, available, allowed_missing, allowed_unexpected)

    # load per-file lazily — leaves stream through one file's worth of
    # host arrays at a time, which is what keeps a reshard restore's
    # memory bounded no matter the saving mesh
    cache: dict[Path, Any] = {}
    new_leaves = []
    for p, m in zip(p_leaves, m_leaves):
        key = m.key
        if key not in available or _matches_any(key, ignore):
            new_leaves.append(p)
            continue
        if restored_keys is not None:
            restored_keys.add(key)
        f, name = available[key]
        if f not in cache:
            cache[f] = _load_artifact(f)
        arr = cache[f][name]
        if tuple(arr.shape) != tuple(p.shape):
            raise ValueError(
                f"shape mismatch for {key}: checkpoint {arr.shape} vs model {p.shape}"
            )
        new_leaves.append(
            jax.device_put(jnp.asarray(arr, dtype=p.dtype), p.sharding)
            if hasattr(p, "sharding")
            else jnp.asarray(arr, dtype=p.dtype)
        )
    for z in cache.values():
        z.close()
    return jax.tree.unflatten(treedef, new_leaves)


OPT_FIELDS = ("master", "exp_avg", "exp_avg_sq")


def save_optimizer_checkpoint(
    dir: Path | str, opt_state, metas: Any,
    writer: Optional[AsyncCheckpointWriter] = None,
    recorder=None,
) -> None:
    """One ``optimizer_state_layer_{i}.npz`` per layer, written exactly once,
    holding all three Adam fields as ``{field}.{param_name}`` entries."""
    path = Path(dir)
    path.mkdir(parents=True, exist_ok=True)

    # group device arrays (cheap references) per layer first, then gather and
    # write ONE layer at a time — host RAM peaks at a layer of fp32 state,
    # not the whole model's (the writer's backpressure bounds the async case)
    per_layer: dict[int, dict[str, jax.Array]] = {}
    for field in OPT_FIELDS:
        tree = getattr(opt_state, field)
        for (layer_index, _cls), group in _grouped_by_layer(tree, metas).items():
            bucket = per_layer.setdefault(layer_index, {})
            for name, arr in group.items():
                bucket[f"{field}.{name}"] = arr
    for layer_index, refs in per_layer.items():
        arrays = {k: np.asarray(jax.device_get(v)) for k, v in refs.items()}
        _emit(writer, path / f"optimizer_state_layer_{layer_index}.npz", arrays,
              recorder)

    scalars = {
        "step": int(opt_state.step),
        "loss_scaler": {
            "current_scale": float(opt_state.loss_scaler.current_scale),
            "current_hysteresis": float(opt_state.loss_scaler.current_hysteresis),
            "no_overflow_steps": int(opt_state.loss_scaler.no_overflow_steps),
        },
    }
    from ..resilience.guards import retry_io

    scalars_text = json.dumps(scalars)
    retry_io(
        lambda: (path / "optimizer_state.json").write_text(scalars_text),
        what="optimizer scalar state write",
    )


def load_optimizer_checkpoint(dir: Path | str, opt_state, metas: Any):
    """Returns a new OptimizerState with loaded master/moments/scalars."""
    from ..optimizer.optimizer import OptimizerState
    from ..optimizer.loss_scaler import LossScalerState

    path = Path(dir)
    m_leaves = _meta_leaves(metas)

    cache: dict[Path, Any] = {}

    def load_entry(field: str, layer_index: int, param_name: str) -> np.ndarray:
        f = path / f"optimizer_state_layer_{layer_index}.npz"
        legacy = path / f"optimizer_state_layer_{layer_index}_{field}.npz"
        if f.exists():
            if f not in cache:
                cache[f] = _load_artifact(f)
            return cache[f][f"{field}.{param_name}"]
        if legacy.exists():
            # pre-r2 layout: one file per (layer, field), plain param keys
            if legacy not in cache:
                cache[legacy] = _load_artifact(legacy)
            return cache[legacy][param_name]
        raise FileNotFoundError(f"optimizer checkpoint file missing: {f}")

    def load_tree(field: str, current):
        c_leaves, treedef = jax.tree.flatten(current)
        new_leaves = []
        for p, m in zip(c_leaves, m_leaves):
            if getattr(p, "size", None) == 0:
                # frozen-leaf (0,) placeholder (PEFT: no master/moments for
                # the backbone): nothing meaningful to load — and a
                # device_put would COMMIT it to one device, which then
                # conflicts with the mesh-committed params inside jit
                new_leaves.append(p)
                continue
            arr = load_entry(field, m.layer_index, m.parameter_name)
            new_leaves.append(
                jax.device_put(jnp.asarray(arr, dtype=p.dtype), p.sharding)
                if hasattr(p, "sharding")
                else jnp.asarray(arr, dtype=p.dtype)
            )
        return jax.tree.unflatten(treedef, new_leaves)

    scalars = json.loads((path / "optimizer_state.json").read_text())
    master = load_tree("master", opt_state.master)
    exp_avg = load_tree("exp_avg", opt_state.exp_avg)
    exp_avg_sq = load_tree("exp_avg_sq", opt_state.exp_avg_sq)
    for z in cache.values():
        z.close()
    return OptimizerState(
        step=jnp.asarray(scalars["step"], jnp.int32),
        master=master,
        exp_avg=exp_avg,
        exp_avg_sq=exp_avg_sq,
        loss_scaler=LossScalerState(
            current_scale=jnp.asarray(scalars["loss_scaler"]["current_scale"], jnp.float32),
            current_hysteresis=jnp.asarray(scalars["loss_scaler"]["current_hysteresis"], jnp.float32),
            no_overflow_steps=jnp.asarray(scalars["loss_scaler"]["no_overflow_steps"], jnp.int32),
        ),
    )
