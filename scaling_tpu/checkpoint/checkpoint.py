"""Layout-independent checkpoints.

Mirrors the reference's artifact families
(reference: src/scaling/core/nn/parallel_module/partitioned_module.py:197-371,
optimizer.py:335-734): per-layer model files named
``model_state_layer_{i}_{ClassName}.npz`` holding merged (unsharded) arrays
keyed by parameter path; per-layer optimizer files
``optimizer_state_layer_{i}.npz`` with master/exp_avg/exp_avg_sq; parameters
matched by ``ParamMeta.key`` so checkpoints survive topology changes (jax
re-shards on load via the current metas — the reference's merge/split
broadcast loops disappear).

Non-strict loading supports the reference's PEFT workflows: regex lists of
allowed-missing keys (fresh adapters), allowed-unexpected keys (dropping a
finetune), and ignored keys (reinit parts of a pretrained model).
"""

from __future__ import annotations

import json
import re
from pathlib import Path
from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..logging import logger
from ..nn.param import ParamMeta


def _meta_leaves(metas: Any) -> list[ParamMeta]:
    return jax.tree.leaves(metas, is_leaf=lambda x: isinstance(x, ParamMeta))


def _grouped_by_layer(params: Any, metas: Any):
    """-> {(layer_index, layer_class): {param_name: array}}"""
    p_leaves = jax.tree.leaves(params)
    m_leaves = _meta_leaves(metas)
    assert len(p_leaves) == len(m_leaves), (
        f"params/metas mismatch: {len(p_leaves)} vs {len(m_leaves)}"
    )
    groups: dict = {}
    for p, m in zip(p_leaves, m_leaves):
        groups.setdefault((m.layer_index, m.layer_class_name), {})[m.parameter_name] = p
    return groups


def save_model_checkpoint(
    dir: Path | str,
    params: Any,
    metas: Any,
    separate_file_for_parameters: Optional[List[str]] = None,
) -> None:
    """One npz per layer; PEFT params split into ``..._{name}.npz`` files."""
    path = Path(dir)
    path.mkdir(parents=True, exist_ok=True)
    for (layer_index, layer_class), group in _grouped_by_layer(params, metas).items():
        main = {}
        separate: dict[str, dict] = {}
        for name, arr in group.items():
            target = None
            for sep in separate_file_for_parameters or []:
                if sep in name:
                    target = sep
                    break
            np_arr = np.asarray(jax.device_get(arr))
            if target is None:
                main[name] = np_arr
            else:
                separate.setdefault(target, {})[name] = np_arr
        fname = f"model_state_layer_{layer_index}_{layer_class}.npz"
        if main:
            np.savez(path / fname, **main)
        # double underscore separates the PEFT suffix from the class name so
        # the loader can recover the class unambiguously
        for sep, group_arrs in separate.items():
            sep_name = f"model_state_layer_{layer_index}_{layer_class}__{sep}.npz"
            np.savez(path / sep_name, **group_arrs)


def _compile_patterns(patterns: Optional[List[str]]) -> list:
    return [re.compile(p) for p in (patterns or [])]


def _matches_any(key: str, patterns: list) -> bool:
    return any(p.search(key) for p in patterns)


def load_model_checkpoint(
    dir: Path | str,
    params: Any,
    metas: Any,
    allowed_missing_keys: Optional[List[str]] = None,
    allowed_unexpected_keys: Optional[List[str]] = None,
    ignore_keys: Optional[List[str]] = None,
) -> Any:
    """Returns a new params tree with checkpoint values loaded by key.

    Missing/unexpected keys raise unless matched by the corresponding
    allow-list regexes; ``ignore_keys`` keeps current (re-initialised)
    values even when the checkpoint has them.
    """
    path = Path(dir)
    allowed_missing = _compile_patterns(allowed_missing_keys)
    allowed_unexpected = _compile_patterns(allowed_unexpected_keys)
    ignore = _compile_patterns(ignore_keys)

    # index checkpoint contents: key -> (file, param_name)
    available: dict[str, tuple[Path, str]] = {}
    for f in sorted(path.glob("model_state_layer_*.npz")):
        with np.load(f) as z:
            stem = f.stem  # model_state_layer_{i}_{Class}[_{sep}]
            m = re.match(r"model_state_layer_(\d+)_(.+)", stem)
            layer_index = int(m.group(1))
            layer_class = m.group(2).split("__")[0]
            for name in z.files:
                key = f"layer_{layer_index}_{layer_class}.{name}"
                available[key] = (f, name)

    p_leaves, treedef = jax.tree.flatten(params)
    m_leaves = _meta_leaves(metas)
    model_keys = [m.key for m in m_leaves]

    missing = [
        k for k in model_keys if k not in available and not _matches_any(k, allowed_missing)
    ]
    unexpected = [
        k for k in available if k not in set(model_keys) and not _matches_any(k, allowed_unexpected)
    ]
    if missing:
        raise KeyError(f"checkpoint missing parameters: {missing[:8]}{'...' if len(missing) > 8 else ''}")
    if unexpected:
        raise KeyError(f"checkpoint has unexpected parameters: {unexpected[:8]}{'...' if len(unexpected) > 8 else ''}")

    # load per-file lazily
    cache: dict[Path, Any] = {}
    new_leaves = []
    for p, m in zip(p_leaves, m_leaves):
        key = m.key
        if key not in available or _matches_any(key, ignore):
            new_leaves.append(p)
            continue
        f, name = available[key]
        if f not in cache:
            cache[f] = np.load(f)
        arr = cache[f][name]
        if tuple(arr.shape) != tuple(p.shape):
            raise ValueError(
                f"shape mismatch for {key}: checkpoint {arr.shape} vs model {p.shape}"
            )
        new_leaves.append(
            jax.device_put(jnp.asarray(arr, dtype=p.dtype), p.sharding)
            if hasattr(p, "sharding")
            else jnp.asarray(arr, dtype=p.dtype)
        )
    for z in cache.values():
        z.close()
    return jax.tree.unflatten(treedef, new_leaves)


def save_optimizer_checkpoint(dir: Path | str, opt_state, metas: Any) -> None:
    path = Path(dir)
    path.mkdir(parents=True, exist_ok=True)
    m_leaves = _meta_leaves(metas)

    for field in ("master", "exp_avg", "exp_avg_sq"):
        tree = getattr(opt_state, field)
        groups = _grouped_by_layer(tree, metas)
        for (layer_index, _layer_class), group in groups.items():
            fname = path / f"optimizer_state_layer_{layer_index}_{field}.npz"
            existing = {}
            if fname.exists():
                with np.load(fname) as z:
                    existing = {k: z[k] for k in z.files}
            existing.update({k: np.asarray(jax.device_get(v)) for k, v in group.items()})
            np.savez(fname, **existing)

    scalars = {
        "step": int(opt_state.step),
        "loss_scaler": {
            "current_scale": float(opt_state.loss_scaler.current_scale),
            "current_hysteresis": float(opt_state.loss_scaler.current_hysteresis),
            "no_overflow_steps": int(opt_state.loss_scaler.no_overflow_steps),
        },
    }
    (path / "optimizer_state.json").write_text(json.dumps(scalars))


def load_optimizer_checkpoint(dir: Path | str, opt_state, metas: Any):
    """Returns a new OptimizerState with loaded master/moments/scalars."""
    from ..optimizer.optimizer import OptimizerState
    from ..optimizer.loss_scaler import LossScalerState

    path = Path(dir)
    m_leaves = _meta_leaves(metas)

    def load_tree(field: str, current):
        c_leaves, treedef = jax.tree.flatten(current)
        new_leaves = []
        cache: dict[Path, Any] = {}
        for p, m in zip(c_leaves, m_leaves):
            f = path / f"optimizer_state_layer_{m.layer_index}_{field}.npz"
            if not f.exists():
                raise FileNotFoundError(f"optimizer checkpoint file missing: {f}")
            if f not in cache:
                cache[f] = np.load(f)
            arr = cache[f][m.parameter_name]
            new_leaves.append(
                jax.device_put(jnp.asarray(arr, dtype=p.dtype), p.sharding)
                if hasattr(p, "sharding")
                else jnp.asarray(arr, dtype=p.dtype)
            )
        for z in cache.values():
            z.close()
        return jax.tree.unflatten(treedef, new_leaves)

    scalars = json.loads((path / "optimizer_state.json").read_text())
    return OptimizerState(
        step=jnp.asarray(scalars["step"], jnp.int32),
        master=load_tree("master", opt_state.master),
        exp_avg=load_tree("exp_avg", opt_state.exp_avg),
        exp_avg_sq=load_tree("exp_avg_sq", opt_state.exp_avg_sq),
        loss_scaler=LossScalerState(
            current_scale=jnp.asarray(scalars["loss_scaler"]["current_scale"], jnp.float32),
            current_hysteresis=jnp.asarray(scalars["loss_scaler"]["current_hysteresis"], jnp.float32),
            no_overflow_steps=jnp.asarray(scalars["loss_scaler"]["no_overflow_steps"], jnp.int32),
        ),
    )
