"""Import reference (PyTorch) checkpoints into this framework's format.

The migration path for users of the reference repo: its partitioned
checkpoints (``model_state_layer_{i}_{Class}.pt``, reference:
partitioned_module.py:197-257) and its legacy whole-model state dicts
(reference: tests/transformer/test_backwards_compatibility.py:20-43)
convert into the npz layout written by ``save_model_checkpoint``. Layer
class names match one-to-one; within a layer the differences are

- torch ``nn.Linear`` stores ``(out, in)`` — our linears store
  ``(in, out)``, so 2-D projection weights transpose;
- the reference's attention attribute is ``self_attention``, ours is
  ``attention`` (the fused query_key_value head-major [q|k|v] layout is
  identical on both sides);
- rotary ``inv_freq`` buffers are derived values here and are dropped;
- a tied LM head duplicates the embedding table in reference checkpoints —
  structural tying holds a single copy, so the duplicate is dropped.

Verified against the reference's own shipped golden artifacts
(state_dict.pt + ground_truth.pt logits) in
tests/transformer/test_reference_weight_import.py.
"""

from __future__ import annotations

import re
from pathlib import Path
from typing import Any, Dict

import numpy as np

_LINEAR_HOSTS = ("attention.", "mlp.", "linear", "embedding_head")


def _map_param(name: str, arr: np.ndarray):
    """reference per-layer param name -> (our name, our array) or None."""
    if name.endswith(".inv_freq"):
        return None
    name = name.replace("self_attention.", "attention.")
    # legacy MLP naming (reference: test_backwards_compatibility.py:36-37)
    name = name.replace("dense_h_to_4h", "dense_in")
    name = name.replace("dense_4h_to_h", "dense_out")
    # adapters: the reference hosts ParallelMLPs named attn_adapter_{n} /
    # mlp_adapter_{n} (layer.py:147-181); ours are bottleneck Adapters named
    # adapter_attention_{n} / adapter_mlp_{n} with down/up factors
    m = re.match(r"(attn|mlp)_adapter_([^.]+)\.dense_(in|out)\.weight$", name)
    if m:
        host = "attention" if m.group(1) == "attn" else "mlp"
        direction = "down" if m.group(3) == "in" else "up"
        name = f"adapter_{host}_{m.group(2)}.{direction}"
        return name, np.ascontiguousarray(arr.T)
    if (
        arr.ndim == 2
        and name.endswith(".weight")
        and any(h in name for h in _LINEAR_HOSTS)
        and not name.startswith("embedding.")
    ):
        arr = np.ascontiguousarray(arr.T)
    return name, arr


def _to_numpy(value: Any) -> np.ndarray:
    if hasattr(value, "detach"):
        value = value.detach().cpu()
        if str(value.dtype) == "torch.bfloat16":
            # numpy has no bf16: round-trip through fp32 (exact superset)
            value = value.float()
        value = value.numpy()
    return np.asarray(value)


def convert_reference_layer(state_dict: Dict[str, Any]) -> Dict[str, np.ndarray]:
    """One reference layer's state dict -> our param-name->array mapping."""
    out: Dict[str, np.ndarray] = {}
    for name, value in state_dict.items():
        mapped = _map_param(name, _to_numpy(value))
        if mapped is not None:
            out[mapped[0]] = mapped[1]
    return out


# reference layer classes, longest-match first so PEFT suffixes split off
# correctly (reference writes "{Class}_{peft_name}.pt" with a SINGLE
# underscore, partitioned_module.py; our loader expects "{Class}__{name}")
_LAYER_CLASSES = (
    "TransformerLMHeadTied",
    "TransformerEmbeddingHead",
    "TransformerLMHead",
    "TransformerLayer",
    "LayerNormWrapper",
    "EmbeddingInput",
)


def _split_class_suffix(stem: str):
    """'TransformerLayer_lora' -> ('TransformerLayer', 'lora')."""
    for cls in _LAYER_CLASSES:
        if stem == cls:
            return cls, None
        if stem.startswith(cls + "_"):
            return cls, stem[len(cls) + 1 :]
    return stem, None


def convert_reference_checkpoint(src_dir: Path | str, dst_dir: Path | str) -> int:
    """Convert a reference partitioned checkpoint directory to our npz
    layout; returns the number of npz files written. Base tied-LM-head
    files are skipped (tying is structural here — the embedding layer owns
    the single copy); their PEFT-suffix side files still convert."""
    import torch

    src, dst = Path(src_dir), Path(dst_dir)
    dst.mkdir(parents=True, exist_ok=True)
    written = 0
    for f in sorted(src.glob("model_state_layer_*.pt")):
        m = re.match(r"model_state_layer_(\d+)_(.+)\.pt", f.name)
        if m is None:
            continue
        layer_index = int(m.group(1))
        layer_class, peft_suffix = _split_class_suffix(m.group(2))
        if layer_class == "TransformerLMHeadTied" and peft_suffix is None:
            continue  # nothing to write: the owner layer has the table
        sd = torch.load(f, map_location="cpu", weights_only=False)
        arrays = convert_reference_layer(sd)
        stem = f"model_state_layer_{layer_index}_{layer_class}"
        if peft_suffix is not None:
            stem += f"__{peft_suffix}"
        np.savez(dst / f"{stem}.npz", **arrays)
        written += 1
    return written


# legacy whole-model state dicts (pre-partitioned codebase) --------------------

_LEGACY_LAYER_CLASSES = ("EmbeddingInput", "TransformerLayer", "LayerNormWrapper")


def convert_legacy_state_dict(
    state_dict: Dict[str, Any], num_layers: int
) -> Dict[str, Dict[str, np.ndarray]]:
    """Legacy ``transformer.*`` state dict -> {layer_file_stem: arrays}.

    Mirrors the reference's own legacy translation
    (test_backwards_compatibility.py:20-43): word embeddings -> layer 0,
    ``transformer.layerN`` -> layer N+1, final norm -> layer num_layers+1;
    the tied head copy the reference appends is implicit here.
    """
    layers: Dict[int, Dict[str, Any]] = {}

    def put(idx: int, name: str, value):
        layers.setdefault(idx, {})[name] = value

    for k, v in state_dict.items():
        if k.endswith(".inv_freq"):
            continue
        if k == "transformer.embeddings.word_embeddings.weight":
            put(0, "embedding.weight", v)
            continue
        m = re.match(r"transformer\.layer(\d+)\.(.+)", k)
        if m:
            put(1 + int(m.group(1)), m.group(2), v)
            continue
        m = re.match(r"transformer\.norm\.(.+)", k)
        if m:
            put(1 + num_layers, f"norm.{m.group(1)}", v)
            continue
        raise ValueError(f"unrecognized legacy parameter {k!r}")

    out: Dict[str, Dict[str, np.ndarray]] = {}
    for idx, sd in layers.items():
        if idx == 0:
            cls = "EmbeddingInput"
        elif idx == 1 + num_layers:
            cls = "LayerNormWrapper"
        else:
            cls = "TransformerLayer"
        out[f"model_state_layer_{idx}_{cls}"] = convert_reference_layer(sd)
    return out


def write_converted_layers(
    layers: Dict[str, Dict[str, np.ndarray]], dst_dir: Path | str
) -> None:
    dst = Path(dst_dir)
    dst.mkdir(parents=True, exist_ok=True)
    for stem, arrays in layers.items():
        np.savez(dst / f"{stem}.npz", **arrays)
