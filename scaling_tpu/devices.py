"""Watchdogged first contact with the JAX device backend.

Backend init can block indefinitely when a tunneled accelerator's link is
down (observed live: ``jax.devices()`` never returned while the process
stayed healthy). Anything that must not hang — the bench, the driver's
multichip dryrun — probes through here instead of calling ``jax.devices()``
directly.
"""

from __future__ import annotations

import threading
from typing import Any, List, Optional, Tuple


def probe_devices(
    timeout_s: float = 30.0,
) -> Tuple[Optional[List[Any]], Optional[BaseException | str]]:
    """Return ``(devices, None)`` on success, ``(None, reason)`` on failure.

    ``reason`` is the raised exception if ``jax.devices()`` failed, or a
    timeout description if it never answered. Runs in a daemon thread so a
    hung backend cannot hang the caller."""
    import jax

    box: dict = {}

    def run():
        try:
            box["devs"] = jax.devices()
        except Exception as e:
            box["err"] = e

    th = threading.Thread(target=run, daemon=True)
    th.start()
    th.join(timeout_s)
    if "devs" in box:
        return box["devs"], None
    return None, box.get("err", f"no response in {timeout_s:.0f}s")
