"""Deterministic, resumable data loading.

Keeps the reference's sample-order contract
(reference: src/scaling/core/data/dataloader.py:55-162):

- each epoch reshuffles the dataset with ``seed + epoch``;
- within an epoch, DP rank ``r`` sees indices ``i*dp + r + consumed_in_epoch``;
- ``consumed_samples`` advances by ``micro_batch_size * dp`` per micro batch,
  making mid-epoch checkpoint resume exact;
- trailing samples that don't fill a full micro batch x dp grid are dropped.

Single-controller difference: one loader feeds ALL data-parallel shards —
each ``__next__`` returns the micro batch for every dp rank stacked along the
batch axis (shard r occupying rows [r*mbs, (r+1)*mbs)), ready to be sharded
over the mesh's data axis. This full-global-batch form is ALSO the
multi-host training contract: every host builds the identical stacked
batch (the stream is a pure function of seed + consumed samples) and
``ParallelModule.shard_batch`` materializes only the host's own shards.
``dp_rank`` gives per-rank iteration for inspection and custom pipelines;
do NOT feed per-rank slices to ``shard_batch`` (it rejects them).
"""

from __future__ import annotations

from typing import Any, Generator, Optional

import numpy as np

from ..resilience.faults import get_fault_plan
from ..resilience.guards import (
    DEFAULT_RETRY_ATTEMPTS,
    DEFAULT_RETRY_BACKOFF_SECONDS,
    retry_io,
)
from ..topology import Topology
from .base_dataset import BaseDataset


class RandomSampler:
    """Yields per-micro-step index lists, DP-strided, resumable."""

    def __init__(
        self,
        dataset: BaseDataset,
        seed: int,
        consumed_samples: int,
        topology: Topology,
        shuffle: bool = True,
        dp_rank: Optional[int] = None,
    ):
        self.dataset = dataset
        self.seed = seed
        self.consumed_samples = consumed_samples
        self.topology = topology
        self.shuffle = shuffle
        self.dp_rank = dp_rank  # None -> all ranks stacked

        mbs = topology.config.micro_batch_size
        dp = topology.config.data_parallel_size
        self.total_samples = len(dataset)
        self.total_micro_batches = self.total_samples // mbs
        self.total_micro_batches_per_data_parallel = self.total_micro_batches // dp
        self.usable_total_samples = self.total_micro_batches_per_data_parallel * mbs * dp
        if self.usable_total_samples <= 0:
            raise AssertionError(
                "no usable samples; the dataset is too small for the provided "
                "data parallel size and micro batch size"
            )
        if consumed_samples % (mbs * dp) != 0:
            raise AssertionError(
                f"consumed_samples ({consumed_samples}) must be a multiple of "
                f"micro_batch_size * data_parallel_size ({mbs * dp}); a checkpoint "
                "written by this framework always satisfies this"
            )

    def __len__(self) -> int:
        """Micro batches yielded per epoch (each consumes mbs * dp samples)."""
        return self.total_micro_batches_per_data_parallel

    def _epoch_indices(self, dp_rank: int, start: int, count: int) -> np.ndarray:
        return np.arange(count, dtype=np.int64) * self.topology.config.data_parallel_size + dp_rank + start

    def __iter__(self) -> Generator[list[int], None, None]:
        mbs = self.topology.config.micro_batch_size
        dp = self.topology.config.data_parallel_size
        while True:  # infinite: epochs chain with fresh shuffles
            epoch = self.consumed_samples // self.usable_total_samples
            in_epoch = self.consumed_samples % self.usable_total_samples
            remaining = self.usable_total_samples - in_epoch
            self.dataset.set_seed(seed=self.seed + epoch, shuffle=self.shuffle)

            per_rank = remaining // dp
            n_micro = per_rank // mbs
            assert n_micro > 0, (
                f"internal error: zero micro batches for epoch {epoch} "
                f"(remaining={remaining}, dp={dp}, mbs={mbs})"
            )
            if self.dp_rank is not None:
                rank_indices = self._epoch_indices(self.dp_rank, in_epoch, per_rank)
                for m in range(n_micro):
                    self.consumed_samples += mbs * dp
                    yield rank_indices[m * mbs : (m + 1) * mbs].tolist()
            else:
                all_rank_indices = [self._epoch_indices(r, in_epoch, per_rank) for r in range(dp)]
                for m in range(n_micro):
                    batch: list[int] = []
                    for r in range(dp):
                        batch.extend(all_rank_indices[r][m * mbs : (m + 1) * mbs].tolist())
                    self.consumed_samples += mbs * dp
                    yield batch


class DataLoader:
    """Infinite iterator over micro batches; ``next(loader)`` -> batch pytree."""

    def __init__(
        self,
        seed: int,
        consumed_samples: int,
        dataset: BaseDataset,
        topology: Topology,
        shuffle: bool = True,
        dp_rank: Optional[int] = None,
        retry_attempts: int = DEFAULT_RETRY_ATTEMPTS,
        retry_backoff: float = DEFAULT_RETRY_BACKOFF_SECONDS,
    ):
        self.seed = seed
        self.consumed_samples = consumed_samples
        self.dataset = dataset
        self.topology = topology
        self.retry_attempts = retry_attempts
        self.retry_backoff = retry_backoff
        if len(dataset) < topology.config.micro_batch_size:
            raise AssertionError(
                f"cannot instantiate data loader with micro_batch_size "
                f"{topology.config.micro_batch_size} because dataset has only "
                f"length {len(dataset)}"
            )
        self._sampler = RandomSampler(
            dataset=dataset,
            seed=seed,
            consumed_samples=consumed_samples,
            topology=topology,
            shuffle=shuffle,
            dp_rank=dp_rank,
        )
        self._iter = iter(self._sampler)

    def _read_batch(self, indices: list) -> Any:
        # fault point + item reads together: both retried, and the reads
        # are index-based (idempotent), so a retry re-reads the same
        # samples — the stream stays a pure function of consumed_samples
        get_fault_plan().fire("data.read")
        items = [self.dataset[i] for i in indices]
        return self.dataset.collate(items)

    def __next__(self) -> Any:
        indices = next(self._iter)
        # the sampler is NOT retried (re-advancing it would skip
        # samples); only the idempotent reads/collate are
        batch = retry_io(
            lambda: self._read_batch(indices),
            attempts=self.retry_attempts,
            base_delay=self.retry_backoff,
            what=f"dataloader read ({len(indices)} samples)",
        )
        self.consumed_samples = self._sampler.consumed_samples
        return batch

    def __iter__(self):
        return self
