"""Dataset contracts.

Mirrors the reference's ``BaseDatasetItem/Batch``/``BaseDataset`` surface
(reference: src/scaling/core/data/base_dataset.py:11-108), minus torch: a
batch is a pytree of numpy/jax arrays; ``sync_batch_to_model_parallel``
disappears under single-controller SPMD (the loader materialises the global
batch and jax shards it), but the hook is kept for multi-host feeding.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Generic, List, Optional, TypeVar

T = TypeVar("T")
TBatch = TypeVar("TBatch")


class BaseDatasetItem:
    """Marker base class for single dataset items."""


class BaseDatasetBatch(ABC):
    """A batch pytree; subclasses register as jax pytrees where needed."""

    def only_inputs(self):
        """Strip target-only fields (first pipe stage feed)."""
        return self

    def only_targets(self):
        """Strip input-only fields (last pipe stage feed)."""
        return self


class BaseDataset(ABC, Generic[T, TBatch]):
    """Seeded, shuffleable dataset yielding items collatable into batches."""

    def __init__(self, seed: int, shuffle: bool = True):
        self.seed: Optional[int] = None
        self.set_seed(seed=seed, shuffle=shuffle)

    @abstractmethod
    def ident(self) -> str:
        """Stable identity string (used for blended-index cache keys)."""

    @abstractmethod
    def __len__(self) -> int: ...

    @abstractmethod
    def __getitem__(self, index: int) -> T: ...

    @abstractmethod
    def set_seed(self, seed: int, shuffle: bool = True) -> None:
        """Reshuffle the dataset deterministically for a new epoch."""

    @abstractmethod
    def collate(self, batch: List[T]) -> TBatch: ...

    def __repr__(self) -> str:
        return self.__class__.__name__
