"""Megatron-format indexed dataset reader (clean-room, numpy only).

(reference: src/scaling/transformer/data/legacy_dataset/indexed_dataset.py
— torch-based loader for the two public Megatron-LM binary layouts). Both
formats store a flat ``.bin`` of concatenated token arrays plus an ``.idx``:

- **MMIDIDX** (mmap impl): 9-byte magic ``MMIDIDX\\x00\\x00``, version u64,
  dtype-code u8, sequence count u64, document count u64, then
  sizes i32[count], pointers i64[count] (byte offsets), doc_idx i64[docs].
- **TNTIDX** (cached impl): 8-byte magic ``TNTIDX\\x00\\x00``, version u64,
  (dtype-code, element_size) u64 pair, (count, size-entries) u64 pair,
  doc_count u64, then dim_offsets i64[count+1], data_offsets i64[count+1]
  (element offsets), sizes i64[s], doc_idx i64[docs].

Exposes the same document-store interface as ``MemoryMapDataset`` (sizes /
__getitem__ / read_span) so ``TextDataset`` can pack legacy data unchanged.
"""

from __future__ import annotations

import struct
from pathlib import Path

import numpy as np

_DTYPES = {
    1: np.uint8,
    2: np.int8,
    3: np.int16,
    4: np.int32,
    5: np.int64,
    6: np.float32,
    7: np.float64,
    8: np.uint16,
}
_MMAP_MAGIC = b"MMIDIDX\x00\x00"
_CACHED_MAGIC = b"TNTIDX\x00\x00"


class LegacyIndexedDataset:
    """Reads either Megatron binary layout; documents are the items."""

    def __init__(self, prefix: Path | str, load_index_to_memory: bool = False):
        self.prefix = Path(prefix)
        idx_path = self.prefix.with_suffix(".idx")
        bin_path = self.prefix.with_suffix(".bin")
        with open(idx_path, "rb") as f:
            head = f.read(9)
        if head == _MMAP_MAGIC:
            self._read_mmap_index(idx_path)
        elif head[:8] == _CACHED_MAGIC:
            self._read_cached_index(idx_path)
        else:
            raise ValueError(f"{idx_path}: not a Megatron indexed dataset")
        self._data = np.memmap(bin_path, dtype=self.dtype, mode="r")
        if load_index_to_memory:
            self._sizes = np.asarray(self._sizes)
            self._element_starts = np.asarray(self._element_starts)

    # ------------------------------------------------------------- parsing
    def _read_mmap_index(self, path: Path) -> None:
        with open(path, "rb") as f:
            assert f.read(9) == _MMAP_MAGIC
            (version,) = struct.unpack("<Q", f.read(8))
            assert version == 1, f"unsupported index version {version}"
            (dtype_code,) = struct.unpack("<B", f.read(1))
            self.dtype = np.dtype(_DTYPES[dtype_code])
            (count,) = struct.unpack("<Q", f.read(8))
            (doc_count,) = struct.unpack("<Q", f.read(8))
            offset = f.tell()
        buf = np.memmap(path, mode="r")
        self._sizes = np.frombuffer(buf, np.int32, count=count, offset=offset)
        pointers = np.frombuffer(
            buf, np.int64, count=count, offset=offset + self._sizes.nbytes
        )
        # byte pointers -> element offsets into the flat stream
        self._element_starts = pointers // self.dtype.itemsize
        self.doc_idx = np.frombuffer(
            buf, np.int64, count=doc_count,
            offset=offset + self._sizes.nbytes + pointers.nbytes,
        )

    def _read_cached_index(self, path: Path) -> None:
        with open(path, "rb") as f:
            assert f.read(8) == _CACHED_MAGIC
            (version,) = struct.unpack("<Q", f.read(8))
            assert version == 1, f"unsupported index version {version}"
            dtype_code, element_size = struct.unpack("<QQ", f.read(16))
            self.dtype = np.dtype(_DTYPES[dtype_code])
            assert self.dtype.itemsize == element_size
            count, s = struct.unpack("<QQ", f.read(16))
            (doc_count,) = struct.unpack("<Q", f.read(8))
            dim_offsets = np.fromfile(f, np.int64, count + 1)
            data_offsets = np.fromfile(f, np.int64, count + 1)  # element units
            sizes = np.fromfile(f, np.int64, s)
            self.doc_idx = np.fromfile(f, np.int64, doc_count)
        # flatten possible multi-dim entries to per-item token counts
        self._sizes = np.asarray(
            [
                int(np.prod(sizes[dim_offsets[i] : dim_offsets[i + 1]]))
                for i in range(count)
            ],
            dtype=np.int64,
        )
        self._element_starts = data_offsets[:-1]

    # ----------------------------------------------------- store interface
    def sizes(self) -> np.ndarray:
        return np.asarray(self._sizes, dtype=np.int64)

    def __len__(self) -> int:
        return len(self._sizes)

    def __getitem__(self, index: int) -> np.ndarray:
        start = int(self._element_starts[index])
        n = int(self._sizes[index])
        return np.asarray(self._data[start : start + n])

    def read_span(self, start: int, n: int) -> np.ndarray:
        """Read n tokens from the concatenated document stream."""
        return np.asarray(self._data[start : start + n])


class LegacyMMapIndexWriter:
    """Writes the MMIDIDX layout (tests + data conversion tooling)."""

    def __init__(self, prefix: Path | str, dtype=np.uint16):
        self.prefix = Path(prefix)
        self.dtype = np.dtype(dtype)
        self._sizes: list[int] = []
        self._doc_idx: list[int] = [0]
        self._bin = open(self.prefix.with_suffix(".bin"), "wb")

    def add(self, tokens: np.ndarray) -> None:
        arr = np.asarray(tokens, dtype=self.dtype)
        self._bin.write(arr.tobytes(order="C"))
        self._sizes.append(len(arr))
        self._doc_idx.append(len(self._sizes))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def close(self) -> None:
        self._bin.close()
        code = {np.dtype(v): k for k, v in _DTYPES.items()}[self.dtype]
        pointers = np.concatenate(
            [[0], np.cumsum(np.asarray(self._sizes[:-1], np.int64))]
        ) * self.dtype.itemsize if self._sizes else np.asarray([], np.int64)
        with open(self.prefix.with_suffix(".idx"), "wb") as f:
            f.write(_MMAP_MAGIC)
            f.write(struct.pack("<Q", 1))
            f.write(struct.pack("<B", code))
            f.write(struct.pack("<Q", len(self._sizes)))
            f.write(struct.pack("<Q", len(self._doc_idx)))
            f.write(np.asarray(self._sizes, np.int32).tobytes(order="C"))
            f.write(np.asarray(pointers, np.int64).tobytes(order="C"))
            f.write(np.asarray(self._doc_idx, np.int64).tobytes(order="C"))
