"""Non-memory-mapped document dataset.

Counterpart of the reference's ``FileDataset``
(reference: src/scaling/core/data/file_dataset.py): same on-disk triple as
``MemoryMapDataset`` but reads with a persistent file handle and seeks —
useful on filesystems where mmap misbehaves (e.g. some network mounts).
"""

from __future__ import annotations

from pathlib import Path

import numpy as np

from .memory_map import DocumentIndex


class FileDataset:
    def __init__(self, prefix_path: Path | str):
        self.prefix_path = Path(prefix_path)
        self._layout = DocumentIndex(self.prefix_path)
        self._data_file = open(self._layout.file_path_data, "rb")

    @property
    def dtype(self) -> np.dtype:
        return self._layout.dtype

    @property
    def document_count(self) -> int:
        return self._layout.document_count

    def __len__(self) -> int:
        return self._layout.document_count

    def sizes(self, idx: int | None = None) -> np.ndarray:
        return self._layout.sizes(idx)

    def __getitem__(self, idx: int) -> np.ndarray:
        start, size = self._layout.span(idx)
        self._data_file.seek(start * self._layout.dtype.itemsize)
        buf = self._data_file.read(size * self._layout.dtype.itemsize)
        return np.frombuffer(buf, dtype=self._layout.dtype)

    def close(self) -> None:
        self._data_file.close()
