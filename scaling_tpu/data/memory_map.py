"""Memory-mapped token datasets.

On-disk format is compatible with the reference
(reference: src/scaling/core/data/memory_map.py:8-250):
``<prefix>.bin`` raw item values, ``<prefix>.idx`` int pairs
``(start_index, size)`` per document, ``<prefix>.meta.json`` with
``{dtype, index_dtype, document_count}`` — so datasets tokenized for the
reference load unchanged. Implementation here reads the whole index
vectorised instead of per-document ``frombuffer`` calls.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Iterator

import numpy as np


class DocumentIndex:
    """Parses the ``.bin/.idx/.meta.json`` triple's meta + document index.

    Shared by MemoryMapDataset and FileDataset so the on-disk format is
    defined in exactly one place.
    """

    def __init__(self, prefix_path: Path | str, load_index_to_memory: bool = True):
        self.prefix_path = Path(prefix_path)
        for p in (self.file_path_data, self.file_path_index, self.file_path_meta):
            if not p.is_file():
                raise FileNotFoundError(f"cannot initialize memory map, file not found: {p}")
        meta = json.loads(self.file_path_meta.read_text())
        self.dtype = np.dtype(meta["dtype"])
        self.index_dtype = np.dtype(meta["index_dtype"])
        self.document_count = int(meta["document_count"])
        index_mmap = np.memmap(self.file_path_index, mode="r", dtype=self.index_dtype)
        index = index_mmap.reshape(self.document_count, 2)
        # the index is tiny relative to the data; keep it in RAM by default
        self._index = np.array(index) if load_index_to_memory else index

    @property
    def file_path_data(self) -> Path:
        return Path(str(self.prefix_path) + ".bin")

    @property
    def file_path_index(self) -> Path:
        return Path(str(self.prefix_path) + ".idx")

    @property
    def file_path_meta(self) -> Path:
        return Path(str(self.prefix_path) + ".meta.json")

    def sizes(self, idx: int | None = None) -> np.ndarray:
        if idx is None:
            return self._index[:, 1]
        return self._index[idx, 1]

    def span(self, idx: int) -> tuple[int, int]:
        if idx < 0 or idx >= self.document_count:
            raise IndexError(
                f"cannot retrieve document idx {idx} from {self.document_count} documents"
            )
        start, size = (int(v) for v in self._index[idx])
        return start, size


class MemoryMapDataset:
    """Random access to variable-length documents in a flat binary file."""

    def __init__(self, prefix_path: Path | str, load_index_to_memory: bool = True):
        self._layout = DocumentIndex(prefix_path, load_index_to_memory=load_index_to_memory)
        self.prefix_path = self._layout.prefix_path
        self.dtype = self._layout.dtype
        self.index_dtype = self._layout.index_dtype
        self.document_count = self._layout.document_count
        self._data = np.memmap(self.file_path_data, mode="r", dtype=self.dtype)

    @property
    def file_path_data(self) -> Path:
        return self._layout.file_path_data

    @property
    def file_path_index(self) -> Path:
        return self._layout.file_path_index

    @property
    def file_path_meta(self) -> Path:
        return self._layout.file_path_meta

    def sizes(self, idx: int | None = None) -> np.ndarray:
        return self._layout.sizes(idx)

    def __len__(self) -> int:
        return self.document_count

    def __getitem__(self, idx: int) -> np.ndarray:
        start, size = self._layout.span(idx)
        return np.asarray(self._data[start : start + size])

    def read_span(self, start_token: int, num_tokens: int) -> np.ndarray:
        """Read a flat token span irrespective of document boundaries.

        Deliberately NOT retried here: transient-I/O retry (and the
        ``data.read`` fault point) live at exactly one layer — the
        DataLoader batch read that drives this — so retry budgets don't
        multiply and fault-injection hit counts stay aimable."""
        return np.asarray(self._data[start_token : start_token + num_tokens])

    def __iter__(self) -> Iterator[np.ndarray]:
        for i in range(len(self)):
            yield self[i]


class MemoryMapDatasetBuilder:
    """Streaming writer producing the ``.bin``/``.idx``/``.meta.json`` triple."""

    def __init__(
        self,
        prefix_path: Path | str,
        dtype: np.dtype = np.dtype(np.int32),
        index_dtype: np.dtype = np.dtype(np.int64),
    ):
        self.prefix_path = Path(prefix_path)
        self.dtype = np.dtype(dtype)
        self.index_dtype = np.dtype(index_dtype)
        data_path = Path(str(self.prefix_path) + ".bin")
        index_path = Path(str(self.prefix_path) + ".idx")
        if data_path.is_file():
            raise FileExistsError(f"data file already exists: {data_path}")
        if index_path.is_file():
            raise FileExistsError(f"index file already exists: {index_path}")
        data_path.parent.mkdir(parents=True, exist_ok=True)
        self._data_file = open(data_path, "wb")
        self._index_file = open(index_path, "wb")
        self._cursor = 0
        self.document_count = 0

    def add(self, array: np.ndarray) -> None:
        array = np.asarray(array)
        if array.ndim != 1:
            raise ValueError("cannot add arrays of more than one dimension")
        array = array.astype(self.dtype, copy=False)
        self._data_file.write(array.tobytes())
        self._index_file.write(
            np.array([self._cursor, array.size], dtype=self.index_dtype).tobytes()
        )
        self._cursor += array.size
        self.document_count += 1

    def finalize(self) -> None:
        self._data_file.close()
        self._index_file.close()
        meta = {
            "dtype": self.dtype.name,
            "index_dtype": self.index_dtype.name,
            "document_count": self.document_count,
        }
        Path(str(self.prefix_path) + ".meta.json").write_text(json.dumps(meta))

    def __enter__(self) -> "MemoryMapDatasetBuilder":
        return self

    def __exit__(self, exc_type, exc_val, exc_tb) -> bool:
        if exc_type is None:
            self.finalize()
        else:
            # don't publish meta for a torn dataset; leave .bin/.idx for debris
            # inspection but a reader will refuse without .meta.json
            self._data_file.close()
            self._index_file.close()
        return False
