"""Blended (multi-source) datasets.

Mixes N component datasets with weights from one of three schemes
(reference: src/scaling/core/data/blended_dataset.py:24-120):

- ``weight_by_num_documents``: p(L) proportional to |L|**alpha (XLM-R style);
- ``weight_examples_proportional``: r_m = min(e_m, K)/sum(min(e_n, K)) with
  temperature 1/T (T5 mixing);
- explicit user ``weights``.

The interleave index (which (dataset, sample) pair each global index maps to)
spreads each dataset's samples as evenly as possible and is cached on disk
keyed by (seed, dataset idents, weights). The reference computes this index
in a native Rust extension (``blended_dataset_loop``); here it is a
vectorised numpy argsort (O(N log N), no per-sample Python loop). Cache
files are published with atomic renames (meta last), so concurrent builders
on a shared filesystem either see complete files or rebuild identical ones.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
from pathlib import Path
from typing import List, Optional, Sequence

import numpy as np
from pydantic import Field

from ..config import BaseConfig
from ..logging import logger
from .base_dataset import BaseDataset


class BlendedDatasetConfig(BaseConfig):
    weight_by_num_documents: bool = Field(
        True,
        description="Build dataset weights from a multinomial distribution over "
        "groups of data according to the number of documents in each group. "
        "WARNING: setting this to True will override any user provided weights",
    )
    weighted_sampler_alpha: float = Field(
        0.3,
        description="Alpha value for weight_by_num_documents. alpha=1 keeps the "
        "natural distribution, alpha->0 equalises groups.",
    )
    weights: Optional[List[float]] = Field(
        None,
        description="weights of singular datasets. The list needs to have the same "
        "length and order as the datasets provided",
    )
    weight_examples_proportional: bool = Field(
        False,
        description="Examples-proportional mixing: r_m = min(e_m, K)/sum(min(e_n, K)) "
        "with temperature scaling (see https://arxiv.org/pdf/1910.10683.pdf p31)",
    )
    ep_maximum: Optional[int] = Field(
        None, description="rate limit K used in weight_examples_proportional"
    )
    ep_temperature: float = Field(
        1.0, description="Temperature for weight_examples_proportional"
    )
    minimum_dataset_size: int = Field(0, description="Minimal size of the dataset.")
    cache_directory: Optional[Path] = Field(
        None, description="directory to cache the blended dataset index"
    )
    shuffle_dataset_indices: bool = Field(
        True, description="shuffle the interleaved index so sources mix"
    )


def weights_by_num_docs(examples: list[int], alpha: float = 0.3) -> np.ndarray:
    """p_i ∝ n_i; q_i ∝ p_i**alpha; weight_i ∝ q_i / p_i (normalised)."""
    n = np.asarray(examples, dtype=np.float64)
    p = n / n.sum()
    q = p**alpha
    q = q / q.sum()
    w = q / p
    return w / w.sum()


def weights_examples_proportional(
    examples: list[int], temperature: float = 1.0, maximum: Optional[float] = None
) -> np.ndarray:
    assert temperature, "temperature must be a non-zero float"
    n = np.asarray(examples, dtype=np.float64)
    p = n / n.sum()
    capped = n.copy()
    if maximum:
        assert maximum > 0, f"examples-proportional sampling requires maximum > 0 (got {maximum})"
        capped = np.minimum(capped, maximum)
    r = capped / capped.sum()
    if temperature != 1.0:
        r = r ** (1.0 / temperature)
        r = r / r.sum()
    w = r / p
    return w / w.sum()


def interleave_counts(counts: np.ndarray) -> np.ndarray:
    """Error-diffusion interleave of ``counts[d]`` samples per dataset.

    Returns an int64 array of shape (sum(counts), 2): (dataset_index,
    sample_index_within_dataset), ordered so each dataset's samples are spread
    evenly over the whole range. Equivalent role to the reference's native
    ``blended_dataset_loop.sample``; computed here by sorting each dataset's
    evenly spaced target positions, which yields the same even spreading in
    O(N log N) vectorised numpy.
    """
    counts = np.asarray(counts, dtype=np.int64)
    total = int(counts.sum())
    ds_col = np.repeat(np.arange(len(counts), dtype=np.int64), counts)
    within = np.concatenate([np.arange(c, dtype=np.int64) for c in counts]) if total else np.empty(0, np.int64)
    # target position of sample j of dataset d: (j + 0.5) / counts[d]
    pos = (within + 0.5) / np.repeat(counts, counts)
    order = np.argsort(pos, kind="stable")
    return np.stack([ds_col[order], within[order]], axis=1)


class BaseBlendedDataset(BaseDataset):
    """Blend of component datasets; global index -> (dataset, sample)."""

    def __init__(
        self,
        seed: int,
        config: BlendedDatasetConfig,
        datasets: Sequence[BaseDataset],
    ):
        self.config = config
        self.datasets = list(datasets)
        self.num_datasets = len(self.datasets)
        assert self.num_datasets > 0, "need at least one component dataset"
        self.seed: Optional[int] = None
        self.weights: Optional[np.ndarray] = None
        self.set_seed(seed=seed, shuffle=True)

    # ------------------------------------------------------------- identity
    def ident(self) -> str:
        prefix_hash = hashlib.md5("-".join(d.ident() for d in self.datasets).encode()).hexdigest()
        weights = self.weights if self.weights is not None else np.ones(self.num_datasets)
        weight_hash = hashlib.md5(
            "-".join(str(round(float(w) * 100) / 100) for w in weights).encode()
        ).hexdigest()
        return f"{self.datasets[0].__class__.__name__}_prefix_{prefix_hash}_weights_{weight_hash}"

    # ---------------------------------------------------------------- index
    def _compute_weights(self, sizes: list[int]) -> np.ndarray:
        if self.config.weight_by_num_documents:
            if self.config.weight_examples_proportional:
                return weights_examples_proportional(
                    sizes, self.config.ep_temperature, self.config.ep_maximum
                )
            return weights_by_num_docs(sizes, self.config.weighted_sampler_alpha)
        assert self.config.weights is not None, "weights required when weight_by_num_documents=False"
        assert len(self.config.weights) == self.num_datasets
        w = np.asarray(self.config.weights, dtype=np.float64)
        assert w.sum() > 0.0
        return w / w.sum()

    def set_seed(self, seed: int, shuffle: bool = True) -> None:
        if seed == self.seed:
            return
        self.seed = seed
        assert shuffle, "Blended datasets should always be shuffled"

        if self.num_datasets == 1:
            self.datasets[0].set_seed(seed=seed, shuffle=shuffle)
            self.size = len(self.datasets[0])
            self.dataset_indices = None
            return

        sizes = []
        for ds in self.datasets:
            ds.set_seed(seed=seed, shuffle=shuffle)
            sizes.append(len(ds))
        self.weights = self._compute_weights(sizes)

        # samples taken per dataset: the largest-weighted dataset is fully
        # represented, the rest scaled down proportionally
        rel = self.weights / self.weights.max()
        if self.config.weight_examples_proportional:
            counts = np.array(
                [max(1, int(round(p * n))) for n, p in zip(sizes, rel)], dtype=np.int64
            )
        else:
            counts = np.array(
                [max(1, int(p * n)) for n, p in zip(sizes, rel)], dtype=np.int64
            )

        index = self._load_or_build_index(seed, counts)
        if self.config.shuffle_dataset_indices:
            rng = np.random.RandomState(seed=seed)
            rng.shuffle(index)
        self.dataset_indices = index
        self.size = index.shape[0]

    def _load_or_build_index(self, seed: int, counts: np.ndarray) -> np.ndarray:
        if self.config.cache_directory is None:
            return interleave_counts(counts)
        cache_dir = Path(self.config.cache_directory)
        cache_dir.mkdir(parents=True, exist_ok=True)
        stem = cache_dir / f"index_cache_blended_dataset_seed_{seed}_{self.ident()}"
        bin_path = Path(str(stem) + ".bin")
        meta_path = Path(str(stem) + ".meta.json")
        input_path = Path(str(stem) + ".input.json")
        if meta_path.is_file() and bin_path.is_file():
            meta = json.loads(meta_path.read_text())
            data = np.fromfile(bin_path, dtype=np.dtype(meta["dtype"]))
            # the cache stem hashes weights only to 2 decimals; validate the
            # exact per-dataset counts so a changed mixture never reuses a
            # stale index
            cached_counts = None
            if input_path.is_file():
                cached_counts = json.loads(input_path.read_text()).get("counts")
            if data.size == int(np.prod(meta["shape"])) and cached_counts == counts.tolist():
                return data.reshape(tuple(meta["shape"]))
            logger.warning(f"blended index cache at {bin_path} is stale or truncated; rebuilding")
        logger.info(f"{self.__class__.__name__}: computing blended index for seed {seed}")
        index = interleave_counts(counts)
        # atomic publish: bin first, meta last; readers only trust meta
        def _atomic_write(path: Path, payload: bytes) -> None:
            fd, tmp = tempfile.mkstemp(dir=str(path.parent), prefix=path.name + ".tmp")
            with os.fdopen(fd, "wb") as f:
                f.write(payload)
            os.replace(tmp, path)

        _atomic_write(bin_path, index.tobytes())
        _atomic_write(
            input_path,
            json.dumps({"counts": counts.tolist(), "seed": seed}).encode(),
        )
        _atomic_write(
            meta_path,
            json.dumps({"dtype": index.dtype.name, "shape": list(index.shape)}).encode(),
        )
        return index

    # ---------------------------------------------------------------- access
    def __len__(self) -> int:
        return max(self.size, self.config.minimum_dataset_size)

    def __getitem__(self, index: int):
        if self.size < self.config.minimum_dataset_size:
            index %= self.size
        if self.num_datasets == 1:
            return self.datasets[0][index]
        ds_idx, sample_idx = self.dataset_indices[index]
        return self.datasets[int(ds_idx)][int(sample_idx)]

    def collate(self, batch: list):
        return self.datasets[0].collate(batch=batch)

    def __repr__(self) -> str:
        return f"{self.__class__.__name__}_{self.datasets[0].__class__.__name__}"
