from .base_dataset import BaseDataset, BaseDatasetBatch, BaseDatasetItem
from .blended_dataset import (
    BaseBlendedDataset,
    BlendedDatasetConfig,
    interleave_counts,
    weights_by_num_docs,
    weights_examples_proportional,
)
from .dataloader import DataLoader, RandomSampler
from .file_dataset import FileDataset
from .memory_map import MemoryMapDataset, MemoryMapDatasetBuilder

__all__ = [
    "BaseDataset",
    "BaseDatasetBatch",
    "BaseDatasetItem",
    "BaseBlendedDataset",
    "BlendedDatasetConfig",
    "interleave_counts",
    "weights_by_num_docs",
    "weights_examples_proportional",
    "DataLoader",
    "RandomSampler",
    "FileDataset",
    "MemoryMapDataset",
    "MemoryMapDatasetBuilder",
]
