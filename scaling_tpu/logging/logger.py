"""Singleton logger with rank-scoped sinks.

Parity with the reference's logging stack (reference:
src/scaling/core/logging/logging.py:46-209): colored console, per-rank file
logs, rank-gated TensorBoard/wandb metric sinks. TensorBoard and wandb are
optional imports — absent packages degrade to no-ops.
"""

from __future__ import annotations

import logging as _pylogging
import sys
from pathlib import Path
from typing import Any, List, Optional

from pydantic import Field, model_validator

from ..config import BaseConfig

_LEVELS = {
    "debug": _pylogging.DEBUG,
    "info": _pylogging.INFO,
    "warning": _pylogging.WARNING,
    "error": _pylogging.ERROR,
    "critical": _pylogging.CRITICAL,
}

# ---------------------------------------------------------------- tracing
# obs.spans registers a provider at import time so every log_event record
# emitted under an active trace context carries the trace id. The hook
# lives HERE (a module-level callback, not an import) because logging
# sits below obs in the layering — obs depends on logging and never the
# reverse — yet the ISSUE-20 stamping contract belongs to log_event
# itself: serve-request events, capacity-lease events and supervisor
# transitions all gain trace identity without each call site opting in.
_trace_provider = None


def set_trace_provider(provider) -> None:
    """Register a zero-arg callable returning extra fields (or ``None``)
    to merge into every ``log_event`` record. Explicit fields win; a
    raising/absent provider costs nothing (telemetry is best-effort)."""
    global _trace_provider
    _trace_provider = provider


class LoggerConfig(BaseConfig):
    log_level: str = Field("info", description="")
    log_dir: Optional[str] = Field(None, description="directory for per-rank log files")
    events_path: Optional[str] = Field(
        None,
        description="jsonl file for structured lifecycle events "
        "(supervisor transitions, stall reports, preemption broadcasts) "
        "— machine-parseable post-mortems instead of stderr scraping. "
        "The SCALING_TPU_EVENTS_PATH env var overrides/provides this for "
        "subprocesses",
    )
    metrics_path: Optional[str] = Field(
        None,
        description="jsonl file for per-step metric records (the run-dir "
        "analyzer's input, see docs/OBSERVABILITY.md). Defaults to "
        "<log_dir>/metrics_rank_<rank>.jsonl whenever log_dir is set, so "
        "telemetry is on by default for any run that logs at all; the "
        "SCALING_TPU_METRICS_PATH env var overrides both",
    )
    metrics_jsonl: bool = Field(
        True,
        description="explicit off switch for the metrics jsonl sink "
        "(false disables it even when log_dir/metrics_path is set; the "
        "env var still wins)",
    )
    metrics_ranks: Optional[List[int]] = Field(
        None, description="global ranks that record metrics; None -> rank 0 only"
    )
    use_wandb: bool = Field(False, description="")
    use_tensorboard: bool = Field(False, description="")
    tensorboard_ranks: Optional[List[int]] = Field(
        None,
        description="global ranks that write to tensorboard. None -> rank 0 only.",
    )
    determined_metrics_ranks: Optional[List[int]] = Field(
        None,
        description="kept for config parity (reference logger_config.py:55); "
        "there is no Determined master here to report to",
    )
    wandb_ranks: Optional[List[int]] = Field(
        None, description="global ranks that log to wandb. None -> rank 0 only."
    )
    wandb_host: Optional[str] = Field(None, description="")
    wandb_team: Optional[str] = Field(None, description="")
    wandb_project: str = Field("scaling_tpu", description="")
    wandb_group: str = Field("default", description="")
    wandb_api_key: Optional[str] = Field(None, description="")

    @model_validator(mode="after")
    def _check_wandb_key(self):
        """(reference: logger_config.py wandb/api-key validation)"""
        import os

        if self.use_wandb and not (self.wandb_api_key or os.environ.get("WANDB_API_KEY")):
            raise ValueError(
                "If 'use_wandb' is set to True a wandb api key needs to be "
                "provided (wandb_api_key or the WANDB_API_KEY env variable)."
            )
        return self


def _rank_enabled(ranks: Optional[List[int]], rank: int) -> bool:
    if ranks is None:
        return rank == 0
    return rank in ranks


class _Logger:
    """Process-wide logger; ``configure`` wires sinks, default = console."""

    def __init__(self) -> None:
        self._log = _pylogging.getLogger("scaling_tpu")
        self._log.propagate = False
        self._configured = False
        self._rank = 0
        self._config: Optional[LoggerConfig] = None
        self._tb_writer: Any = None
        self._wandb: Any = None
        self._warned_nonnumeric: set = set()
        self._ensure_console()

    def _ensure_console(self) -> None:
        if not self._log.handlers:
            handler = _pylogging.StreamHandler(sys.stdout)
            handler.setFormatter(
                _pylogging.Formatter("[%(asctime)s] [%(levelname)s] %(message)s")
            )
            self._log.addHandler(handler)
            self._log.setLevel(_pylogging.INFO)

    def configure(
        self,
        config: Optional[LoggerConfig] = None,
        name: str = "",
        global_rank: int = 0,
    ) -> None:
        config = config or LoggerConfig()
        self._config = config
        self._rank = global_rank
        self._log.setLevel(_LEVELS.get(config.log_level, _pylogging.INFO))
        prefix = f"[rank {global_rank}]" + (f" [{name}]" if name else "")
        for h in list(self._log.handlers):
            self._log.removeHandler(h)
        console = _pylogging.StreamHandler(sys.stdout)
        console.setFormatter(
            _pylogging.Formatter(f"[%(asctime)s] {prefix} [%(levelname)s] %(message)s")
        )
        self._log.addHandler(console)
        if config.log_dir:
            log_dir = Path(config.log_dir)
            log_dir.mkdir(parents=True, exist_ok=True)
            fh = _pylogging.FileHandler(log_dir / f"rank_{global_rank}.log")
            fh.setFormatter(
                _pylogging.Formatter(f"[%(asctime)s] {prefix} [%(levelname)s] %(message)s")
            )
            self._log.addHandler(fh)
        if config.use_tensorboard and _rank_enabled(config.tensorboard_ranks, global_rank):
            try:
                from torch.utils.tensorboard import SummaryWriter

                tb_dir = Path(config.log_dir or ".") / "tensorboard"
                self._tb_writer = SummaryWriter(log_dir=str(tb_dir))
            except Exception:  # pragma: no cover - optional dep
                self.warning("tensorboard requested but unavailable; disabled")
        if config.use_wandb and _rank_enabled(config.wandb_ranks, global_rank):
            try:  # pragma: no cover - optional dep
                import os as _os

                if config.wandb_host:
                    _os.environ["WANDB_BASE_URL"] = config.wandb_host
                if config.wandb_api_key:
                    _os.environ["WANDB_API_KEY"] = config.wandb_api_key
                import wandb

                wandb.init(
                    project=config.wandb_project,
                    group=config.wandb_group,
                    entity=config.wandb_team,
                    name=name or None,
                )
                self._wandb = wandb
            except Exception as e:  # pragma: no cover
                self.warning(f"wandb requested but unavailable; disabled ({e})")
        self._configured = True

    # ------------------------------------------------------------ passthru
    def debug(self, msg: Any) -> None:
        self._log.debug(msg)

    def info(self, msg: Any) -> None:
        self._log.info(msg)

    def warning(self, msg: Any) -> None:
        self._log.warning(msg)

    def error(self, msg: Any) -> None:
        self._log.error(msg)

    def critical(self, msg: Any) -> None:
        self._log.critical(msg)

    # ------------------------------------------------------------- metrics
    def metrics_path(self) -> Optional[str]:
        """Resolved per-step metrics JSONL path, or None when the sink is
        off. ``metrics_ranks`` gates this resolution exactly like it
        gates ``log_metrics`` — the registry's ``flush_step`` rides the
        same decision, so a rank configured not to record metrics never
        writes snapshots either. For an enabled rank: env override first
        (a launcher redirecting a subprocess must win, same contract as
        the events path), then the explicit config path, then the
        log-dir default."""
        import os

        if self._config is not None and not _rank_enabled(
            self._config.metrics_ranks, self._rank
        ):
            return None
        env = os.environ.get("SCALING_TPU_METRICS_PATH")
        if env:
            return env
        c = self._config
        if c is None or not c.metrics_jsonl:
            return None
        if c.metrics_path:
            return c.metrics_path
        if c.log_dir:
            return str(Path(c.log_dir) / f"metrics_rank_{self._rank}.jsonl")
        return None

    def _warn_dropped_metrics(self, keys: List[str]) -> None:
        """One-time (per key) warning for non-numeric metric values the
        structured sinks (jsonl/tensorboard) cannot record — silent drops
        hide typos like logging a whole array object under 'loss'."""
        fresh = [k for k in keys if k not in self._warned_nonnumeric]
        if not fresh:
            return
        self._warned_nonnumeric.update(fresh)
        self.warning(
            "non-numeric metric value(s) dropped from structured sinks "
            f"(console still shows them): {sorted(fresh)} — logged once "
            "per key"
        )

    def log_metrics(self, metrics: dict, step: int) -> None:
        if self._config is not None and not _rank_enabled(
            self._config.metrics_ranks, self._rank
        ):
            return
        rendered = " | ".join(
            f"{k}: {float(v):.6g}" if _is_number(v) else f"{k}: {v}"
            for k, v in metrics.items()
        )
        self.info(f"step {step} | {rendered}")
        numeric = {k: float(v) for k, v in metrics.items()
                   if _is_number(v) and v is not None}
        dropped = [k for k in metrics if k not in numeric]
        if dropped:
            self._warn_dropped_metrics(dropped)
        path = self.metrics_path()
        if path:
            import json as _json
            import math as _math
            import time as _time

            rec = {
                "kind": "step", "step": step, "ts": _time.time(),
                "host": _host_id(self._rank),
                # NaN/Inf serialize as invalid-JSON bare tokens, which
                # would corrupt the file exactly during the non-finite
                # incidents this telemetry exists to diagnose; null keeps
                # the line parseable everywhere (jq, Go/JS parsers) and
                # the analyzer skips nulls
                "metrics": {
                    k: (v if _math.isfinite(v) else None)
                    for k, v in numeric.items()
                },
            }
            # single-syscall append (multi-writer-safe), no fsync: metric
            # lines are per-step and advisory, unlike lifecycle events
            try:
                Path(path).parent.mkdir(parents=True, exist_ok=True)
                append_jsonl_line(path, _json.dumps(rec, sort_keys=True))
            except OSError as e:
                self.warning(f"could not append metrics to {path}: {e!r}")
        if self._tb_writer is not None:
            for k, v in numeric.items():
                self._tb_writer.add_scalar(k, v, step)
        if self._wandb is not None:  # pragma: no cover
            self._wandb.log(metrics, step=step)

    def log_config(self, config: BaseConfig) -> None:
        self.info(f"config:\n{config.as_str()}")

    # -------------------------------------------------------------- events
    def log_event(self, event: str, _level: str = "info",
                  _fsync: bool = True, **fields: Any) -> None:
        """Structured lifecycle event: one JSON line, append-only.

        Post-mortems of supervised multi-host runs (who died, when the
        relaunch happened, which host broadcast preemption) must not
        depend on scraping human-formatted stderr — each event lands as
        a single flushed JSON object in the events file
        (the ``SCALING_TPU_EVENTS_PATH`` env var, else
        ``LoggerConfig.events_path``) and is mirrored to the normal log.
        Without a configured path only the mirror line is emitted.
        ``_level`` tunes only the mirror: high-frequency span events
        mirror at debug so steady-state training stays readable, while
        the events file receives every record either way. ``_fsync``
        defaults on for lifecycle events (a crashed supervisor must not
        lose its last transition); per-step span records pass False —
        an fsync per span on the step path is exactly the overhead the
        metrics sink already declines."""
        import json as _json
        import os as _os
        import time as _time

        rec = {"event": event, "ts": _time.time(), **fields}
        if _trace_provider is not None:
            try:
                extra = _trace_provider()
            except Exception:
                extra = None
            if extra:
                for k, v in extra.items():
                    rec.setdefault(k, v)
        line = _json.dumps(rec, sort_keys=True, default=str)
        getattr(self, _level, self.info)(f"EVENT {line}")
        # env first: the field doc promises the env var OVERRIDES the
        # config value (a launcher redirecting a subprocess whose config
        # already declares a path must win)
        path = _os.environ.get("SCALING_TPU_EVENTS_PATH") or (
            self._config.events_path if self._config is not None else None
        )
        if path:
            try:
                with open(path, "a") as f:
                    f.write(line + "\n")
                    f.flush()
                    if _fsync:
                        _os.fsync(f.fileno())
            except OSError as e:
                self.warning(f"could not append event to {path}: {e!r}")


def append_jsonl_line(path: Any, line: str) -> None:
    """Append one line in a SINGLE ``write(2)`` on an O_APPEND fd.

    Multiple host processes may share one metrics file (the supervised
    pod wires every worker's ``SCALING_TPU_METRICS_PATH`` at the same
    place); Python's buffered file object splits writes above its 8 KiB
    buffer into several syscalls, and a registry snapshot with many
    labelled histograms can cross that — two hosts' partial writes would
    interleave into torn lines. One syscall keeps the append atomic.
    Lives here (stdlib-only, below both packages) so ``obs`` depends on
    ``logging`` and never the reverse."""
    import os

    fd = os.open(str(path), os.O_WRONLY | os.O_APPEND | os.O_CREAT, 0o644)
    try:
        os.write(fd, (line + "\n").encode())
    finally:
        os.close(fd)


def _is_number(v: Any) -> bool:
    try:
        float(v)
        return True
    except (TypeError, ValueError):
        return False


def _host_id(rank: int) -> int:
    """Pod host id for metric records: the supervisor's env var when
    present (fake pods and real ones both set it), else the rank."""
    import os

    try:
        return int(os.environ.get("SCALING_TPU_HOST_ID", rank))
    except ValueError:
        return rank


logger = _Logger()
