from .logger import LoggerConfig, logger

__all__ = ["LoggerConfig", "logger"]
