"""Pipeline schedule instruction DSL + simulator.

The reference precomputes per-rank 1F1B instruction lists and ships a
simulator that replays a recorded profile to predict idle time
(reference: src/scaling/core/nn/pipeline_schedule/instructions.py:5-61,
train.py:32-174, inference.py:16-75, base.py:276-595). On TPU the *executor*
is the jitted spatial pipeline in ``pipeline.py``, but the instruction DSL
remains valuable: it documents the schedule, drives the simulator for
capacity planning, and keeps parity with reference tooling. All pure Python
— no devices needed.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, NamedTuple, Optional


# ------------------------------------------------------------- instructions
class Instruction(NamedTuple):
    name: str
    micro_batch_id: Optional[int] = None
    buffer_id: Optional[int] = None


def InstructionLoadMicroBatch(micro_batch_id, buffer_id):
    return Instruction("load_micro_batch", micro_batch_id, buffer_id)


def InstructionRecvActivation(micro_batch_id, buffer_id):
    return Instruction("recv_activation", micro_batch_id, buffer_id)


def InstructionSendActivation(micro_batch_id, buffer_id):
    return Instruction("send_activation", micro_batch_id, buffer_id)


def InstructionForwardPass(micro_batch_id, buffer_id):
    return Instruction("forward_pass", micro_batch_id, buffer_id)


def InstructionLoss(micro_batch_id, buffer_id):
    return Instruction("loss", micro_batch_id, buffer_id)


def InstructionBackwardPass(micro_batch_id, buffer_id):
    return Instruction("backward_pass", micro_batch_id, buffer_id)


def InstructionSendGrad(micro_batch_id, buffer_id):
    return Instruction("send_grad", micro_batch_id, buffer_id)


def InstructionRecvGrad(micro_batch_id, buffer_id):
    return Instruction("recv_grad", micro_batch_id, buffer_id)


def InstructionReduceTiedGrads():
    return Instruction("reduce_tied_grads")


def InstructionOptimizerStep():
    return Instruction("optimizer_step")


def InstructionStoreMicroBatch(micro_batch_id, buffer_id):
    return Instruction("store_micro_batch", micro_batch_id, buffer_id)


# ---------------------------------------------------------------- schedules
@dataclass
class PipelineScheduleBase:
    pipe_parallel_size: int
    pipe_parallel_rank: int
    gradient_accumulation_steps: int

    @property
    def num_buffers(self) -> int:
        return max(
            2,
            min(
                self.pipe_parallel_size - self.pipe_parallel_rank + 1,
                self.gradient_accumulation_steps,
            ),
        )

    def buffer_for(self, micro_batch_id: int) -> int:
        return micro_batch_id % self.num_buffers

    def instructions(self) -> List[Instruction]:
        raise NotImplementedError


class PipelineScheduleTrain(PipelineScheduleBase):
    """1F1B: warmup forwards, steady 1F1B interleave, cooldown backwards.

    Per-rank step count is ``2 * (grad_accum + pp - 1)`` (reference:
    train.py:41-43); each step slot is a forward or backward opportunity
    offset by the rank so neighbouring ranks interleave.
    """

    def instructions(self) -> List[Instruction]:
        pp = self.pipe_parallel_size
        rank = self.pipe_parallel_rank
        gas = self.gradient_accumulation_steps
        is_first = rank == 0
        is_last = rank == pp - 1

        # number of warmup forwards before the 1F1B steady state
        warmup = min(pp - rank - 1, gas)
        instructions: List[Instruction] = []
        fwd_id = 0
        bwd_id = 0

        def forward(mb: int):
            buf = self.buffer_for(mb)
            if is_first:
                instructions.append(InstructionLoadMicroBatch(mb, buf))
            else:
                instructions.append(InstructionRecvActivation(mb, buf))
            instructions.append(InstructionForwardPass(mb, buf))
            if is_last:
                instructions.append(InstructionLoss(mb, buf))
            else:
                instructions.append(InstructionSendActivation(mb, buf))

        def backward(mb: int):
            buf = self.buffer_for(mb)
            if not is_last:
                instructions.append(InstructionRecvGrad(mb, buf))
            instructions.append(InstructionBackwardPass(mb, buf))
            if not is_first:
                instructions.append(InstructionSendGrad(mb, buf))

        for _ in range(warmup):
            forward(fwd_id)
            fwd_id += 1
        while fwd_id < gas:
            forward(fwd_id)
            fwd_id += 1
            backward(bwd_id)
            bwd_id += 1
        while bwd_id < gas:
            backward(bwd_id)
            bwd_id += 1

        instructions.append(InstructionReduceTiedGrads())
        instructions.append(InstructionOptimizerStep())
        return instructions


class PipelineScheduleInference(PipelineScheduleBase):
    """Forward-only, alternating two buffers (reference: inference.py:16-75)."""

    def instructions(self) -> List[Instruction]:
        pp = self.pipe_parallel_size
        rank = self.pipe_parallel_rank
        gas = self.gradient_accumulation_steps
        instructions: List[Instruction] = []
        for mb in range(gas):
            buf = mb % 2
            if rank == 0:
                instructions.append(InstructionLoadMicroBatch(mb, buf))
            else:
                instructions.append(InstructionRecvActivation(mb, buf))
            instructions.append(InstructionForwardPass(mb, buf))
            if rank == pp - 1:
                instructions.append(InstructionStoreMicroBatch(mb, buf))
            else:
                instructions.append(InstructionSendActivation(mb, buf))
        return instructions


# ----------------------------------------------------------------- simulator
@dataclass
class SimulationEngine:
    """Replays a profile (instruction durations) into per-rank timelines.

    ``durations``: {instruction_name: seconds}, optionally overridden per
    (name, rank). Communication instructions synchronise sender/receiver.
    Produces total time and per-rank idle fraction — the reference renders
    this as a PNG timeline; here the data structure is returned for tooling.
    (reference: pipeline_schedule/base.py:276-595)
    """

    pipe_parallel_size: int
    gradient_accumulation_steps: int
    durations: Dict[str, float] = field(default_factory=dict)

    DEFAULTS = {
        "load_micro_batch": 0.1,
        "recv_activation": 0.1,
        "send_activation": 0.1,
        "forward_pass": 1.0,
        "loss": 0.1,
        "backward_pass": 2.0,
        "send_grad": 0.1,
        "recv_grad": 0.1,
        "reduce_tied_grads": 0.2,
        "optimizer_step": 0.5,
        "store_micro_batch": 0.1,
    }

    def duration(self, name: str) -> float:
        return self.durations.get(name, self.DEFAULTS.get(name, 0.0))

    def simulate(self, schedule_cls=PipelineScheduleTrain) -> dict:
        pp = self.pipe_parallel_size
        schedules = [
            schedule_cls(
                pipe_parallel_size=pp,
                pipe_parallel_rank=r,
                gradient_accumulation_steps=self.gradient_accumulation_steps,
            ).instructions()
            for r in range(pp)
        ]
        cursors = [0] * pp
        times = [0.0] * pp
        busy = [0.0] * pp
        timeline: List[dict] = []
        # comm matching: sends/recvs of (kind, mb) pair between neighbours
        pending: Dict[tuple, float] = {}

        def comm_peer(name: str, rank: int) -> Optional[int]:
            if name in ("send_activation", "recv_grad"):
                return rank + 1
            if name in ("recv_activation", "send_grad"):
                return rank - 1
            return None

        progressed = True
        while progressed:
            progressed = False
            for r in range(pp):
                while cursors[r] < len(schedules[r]):
                    ins = schedules[r][cursors[r]]
                    peer = comm_peer(ins.name, r)
                    if peer is None:
                        start = times[r]
                        end = start + self.duration(ins.name)
                        timeline.append(
                            {"rank": r, "name": ins.name, "micro_batch": ins.micro_batch_id,
                             "start": start, "end": end}
                        )
                        busy[r] += end - start
                        times[r] = end
                        cursors[r] += 1
                        progressed = True
                        continue
                    mb = ins.micro_batch_id
                    kind = "act" if "activation" in ins.name else "grad"
                    lo, hi = min(r, peer), max(r, peer)
                    key = (kind, mb, lo, hi)
                    if ins.name.startswith("send"):
                        # sends are async: post completion time and continue
                        end = times[r] + self.duration(ins.name)
                        pending[key] = end
                        busy[r] += self.duration(ins.name)
                        timeline.append(
                            {"rank": r, "name": ins.name, "micro_batch": mb,
                             "start": times[r], "end": end}
                        )
                        times[r] = end
                        cursors[r] += 1
                        progressed = True
                        continue
                    # recvs BLOCK until the matching send has completed —
                    # this is what creates the pipeline bubble the simulator
                    # exists to predict
                    if key in pending:
                        data_ready = pending.pop(key)
                        start = max(times[r], data_ready)
                        end = start + self.duration(ins.name)
                        busy[r] += self.duration(ins.name)
                        times[r] = end
                        timeline.append(
                            {"rank": r, "name": ins.name, "micro_batch": mb,
                             "start": start, "end": end}
                        )
                        cursors[r] += 1
                        progressed = True
                        continue
                    break  # blocked on an unposted send; retry next sweep
        total = max(times)
        deadlocked = any(cursors[r] < len(schedules[r]) for r in range(pp))
        idle = [1.0 - (b / total if total else 0.0) for b in busy]
        return {
            "total_time": total,
            "idle_fraction": idle,
            "timeline": timeline,
            "deadlocked": deadlocked,
        }


def durations_from_profile(
    observations: list,
    gradient_accumulation_steps: int,
) -> Dict[str, float]:
    """Calibrate simulator instruction durations from the trainer's
    recorded profile (``profiler_output`` JSON: one ``step_time`` per
    step, the whole fused program).

    The fused XLA step has no per-instruction timers — the instructions
    don't exist at runtime — so the measured step time is split across
    the schedule's compute instructions at the simulator's own 1:2
    forward:backward ratio, one (forward + loss + backward) triple per
    micro-batch. Communication instructions keep their defaults (they are
    overlapped collective-permutes here). The result feeds
    ``SimulationEngine``/``illustrate`` to ask layout questions — "what
    does idle % look like at twice the micro-batches?" — anchored to a
    real measurement (reference: profile JSON -> SimulationEngine,
    pipeline_schedule/base.py:568-595)."""
    steps = [o["step_time"] for o in observations if "step_time" in o]
    if not steps:
        raise ValueError("profile has no step_time observations")
    mean_step = sum(steps) / len(steps)
    unit = mean_step / (gradient_accumulation_steps * 3.2)
    return {
        "forward_pass": unit,
        "backward_pass": 2.0 * unit,
        "loss": 0.1 * unit,
        "optimizer_step": 0.1 * unit,
        # comm rides overlapped collective-permutes here; scaled with the
        # computed unit so the ABSOLUTE defaults (tuned for the default
        # 1.0/2.0 compute times) can't swamp a calibrated fast step
        "load_micro_batch": 0.05 * unit,
        "store_micro_batch": 0.05 * unit,
        "recv_activation": 0.05 * unit,
        "send_activation": 0.05 * unit,
        "send_grad": 0.05 * unit,
        "recv_grad": 0.05 * unit,
    }


def illustrate(
    pipe_parallel_size: int,
    gradient_accumulation_steps: int,
    schedule_cls=PipelineScheduleTrain,
    width: int = 100,
    durations: Optional[Dict[str, float]] = None,
) -> str:
    """ASCII timeline of a simulated schedule — one row per pipe rank,
    F/B/· cells (reference renders a PNG, pipeline_schedule/base.py:41-149;
    the text form diffs cleanly in tests and terminals)."""
    sim = SimulationEngine(
        pipe_parallel_size=pipe_parallel_size,
        gradient_accumulation_steps=gradient_accumulation_steps,
        durations=durations or {},
    )
    result = sim.simulate(schedule_cls)
    total = result["total_time"] or 1.0
    rows = [[" "] * width for _ in range(pipe_parallel_size)]
    glyphs = {"forward_pass": "F", "backward_pass": "B", "optimizer_step": "O",
              "loss": "L", "load_micro_batch": "d", "store_micro_batch": "s"}
    for ev in result["timeline"]:
        g = glyphs.get(ev["name"])
        if g is None:
            continue
        lo = int(ev["start"] / total * (width - 1))
        hi = max(lo + 1, int(ev["end"] / total * (width - 1)))
        for c in range(lo, min(hi, width)):
            rows[ev["rank"]][c] = g
    lines = [f"rank {r}: |{''.join(row)}|" for r, row in enumerate(rows)]
    idle = ", ".join(f"{i:.0%}" for i in result["idle_fraction"])
    lines.append(f"total {result['total_time']:.2f}s  idle per rank: {idle}")
    return "\n".join(lines)


def visualize(
    pipe_parallel_size: int,
    gradient_accumulation_steps: int,
    output_path,
    schedule_cls=PipelineScheduleTrain,
    durations: Optional[Dict[str, float]] = None,
) -> None:
    """Render the simulated schedule as a PNG Gantt timeline — one lane per
    pipe rank, forward/backward/comm blocks colored and labeled with their
    micro-batch id (reference: pipeline_schedule/base.py:276-690 renders the
    same view with matplotlib)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    from matplotlib.patches import Patch

    sim = SimulationEngine(
        pipe_parallel_size=pipe_parallel_size,
        gradient_accumulation_steps=gradient_accumulation_steps,
        durations=durations or {},
    )
    result = sim.simulate(schedule_cls)

    colors = {
        "forward_pass": "#4878cf",
        "backward_pass": "#d65f5f",
        "optimizer_step": "#6acc65",
        "loss": "#956cb4",
        "send_activation": "#c4ad66",
        "recv_activation": "#c4ad66",
        "send_grad": "#77bedb",
        "recv_grad": "#77bedb",
        "load_micro_batch": "#bbbbbb",
        "store_micro_batch": "#bbbbbb",
        "reduce_tied_grads": "#8c613c",
    }
    fig, ax = plt.subplots(
        figsize=(12, 0.8 * pipe_parallel_size + 1.5), constrained_layout=True
    )
    for ev in result["timeline"]:
        color = colors.get(ev["name"], "#dddddd")
        ax.barh(
            ev["rank"], ev["end"] - ev["start"], left=ev["start"], height=0.7,
            color=color, edgecolor="white", linewidth=0.3,
        )
        if ev["name"] in ("forward_pass", "backward_pass") and ev["micro_batch"] is not None:
            ax.text(
                (ev["start"] + ev["end"]) / 2, ev["rank"], str(ev["micro_batch"]),
                ha="center", va="center", fontsize=7, color="white",
            )
    ax.set_yticks(range(pipe_parallel_size))
    ax.set_yticklabels([f"rank {r}" for r in range(pipe_parallel_size)])
    ax.invert_yaxis()
    ax.set_xlabel("time (s, simulated)")
    idle = ", ".join(f"{i:.0%}" for i in result["idle_fraction"])
    ax.set_title(
        f"{schedule_cls.__name__}  pp={pipe_parallel_size} "
        f"gas={gradient_accumulation_steps}  total {result['total_time']:.2f}s  "
        f"idle: {idle}"
    )
    shown = {n: c for n, c in colors.items()
             if any(ev["name"] == n for ev in result["timeline"])}
    ax.legend(
        handles=[Patch(color=c, label=n) for n, c in shown.items()],
        loc="upper right", fontsize=7, ncol=2,
    )
    fig.savefig(output_path, dpi=120)
    plt.close(fig)
