"""Pipeline schedule instruction DSL + simulator.

The reference precomputes per-rank 1F1B instruction lists and ships a
simulator that replays a recorded profile to predict idle time
(reference: src/scaling/core/nn/pipeline_schedule/instructions.py:5-61,
train.py:32-174, inference.py:16-75, base.py:276-595). On TPU the *executor*
is the jitted spatial pipeline in ``pipeline.py``, but the instruction DSL
remains valuable: it documents the schedule, drives the simulator for
capacity planning, and keeps parity with reference tooling. All pure Python
— no devices needed.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, NamedTuple, Optional


# ------------------------------------------------------------- instructions
class Instruction(NamedTuple):
    name: str
    micro_batch_id: Optional[int] = None
    buffer_id: Optional[int] = None
    # explicit comm peer (interleaved wrap: stage pp-1 sends to stage 0);
    # None keeps the classic linear neighbour convention
    peer: Optional[int] = None
    # disambiguates repeated crossings of the same (kind, micro_batch)
    # between the same rank pair (a chunk/round id); None for the classic
    # schedules, whose crossings are unique
    tag: Optional[int] = None


def InstructionLoadMicroBatch(micro_batch_id, buffer_id):
    return Instruction("load_micro_batch", micro_batch_id, buffer_id)


def InstructionRecvActivation(micro_batch_id, buffer_id, peer=None, tag=None):
    return Instruction("recv_activation", micro_batch_id, buffer_id, peer, tag)


def InstructionSendActivation(micro_batch_id, buffer_id, peer=None, tag=None):
    return Instruction("send_activation", micro_batch_id, buffer_id, peer, tag)


def InstructionForwardPass(micro_batch_id, buffer_id):
    return Instruction("forward_pass", micro_batch_id, buffer_id)


def InstructionLoss(micro_batch_id, buffer_id):
    return Instruction("loss", micro_batch_id, buffer_id)


def InstructionBackwardPass(micro_batch_id, buffer_id):
    return Instruction("backward_pass", micro_batch_id, buffer_id)


def InstructionSendGrad(micro_batch_id, buffer_id, peer=None, tag=None):
    return Instruction("send_grad", micro_batch_id, buffer_id, peer, tag)


def InstructionRecvGrad(micro_batch_id, buffer_id, peer=None, tag=None):
    return Instruction("recv_grad", micro_batch_id, buffer_id, peer, tag)


def InstructionReduceTiedGrads():
    return Instruction("reduce_tied_grads")


def InstructionOptimizerStep():
    return Instruction("optimizer_step")


def InstructionStoreMicroBatch(micro_batch_id, buffer_id):
    return Instruction("store_micro_batch", micro_batch_id, buffer_id)


# ---------------------------------------------------------------- schedules
@dataclass
class PipelineScheduleBase:
    pipe_parallel_size: int
    pipe_parallel_rank: int
    gradient_accumulation_steps: int

    @property
    def num_buffers(self) -> int:
        return max(
            2,
            min(
                self.pipe_parallel_size - self.pipe_parallel_rank + 1,
                self.gradient_accumulation_steps,
            ),
        )

    def buffer_for(self, micro_batch_id: int) -> int:
        return micro_batch_id % self.num_buffers

    def duration_scale(self, name: str) -> float:
        """Per-instruction duration multiplier: schedules whose work items
        are fractions of a micro-batch (virtual-stage chunks, token
        slices) scale their compute (and, where the payload shrinks,
        comm) below the profile's full-micro-batch durations."""
        return 1.0

    def instructions(self) -> List[Instruction]:
        raise NotImplementedError


class PipelineScheduleTrain(PipelineScheduleBase):
    """1F1B: warmup forwards, steady 1F1B interleave, cooldown backwards.

    Per-rank step count is ``2 * (grad_accum + pp - 1)`` (reference:
    train.py:41-43); each step slot is a forward or backward opportunity
    offset by the rank so neighbouring ranks interleave.
    """

    def instructions(self) -> List[Instruction]:
        pp = self.pipe_parallel_size
        rank = self.pipe_parallel_rank
        gas = self.gradient_accumulation_steps
        is_first = rank == 0
        is_last = rank == pp - 1

        # number of warmup forwards before the 1F1B steady state
        warmup = min(pp - rank - 1, gas)
        instructions: List[Instruction] = []
        fwd_id = 0
        bwd_id = 0

        def forward(mb: int):
            buf = self.buffer_for(mb)
            if is_first:
                instructions.append(InstructionLoadMicroBatch(mb, buf))
            else:
                instructions.append(InstructionRecvActivation(mb, buf))
            instructions.append(InstructionForwardPass(mb, buf))
            if is_last:
                instructions.append(InstructionLoss(mb, buf))
            else:
                instructions.append(InstructionSendActivation(mb, buf))

        def backward(mb: int):
            buf = self.buffer_for(mb)
            if not is_last:
                instructions.append(InstructionRecvGrad(mb, buf))
            instructions.append(InstructionBackwardPass(mb, buf))
            if not is_first:
                instructions.append(InstructionSendGrad(mb, buf))

        for _ in range(warmup):
            forward(fwd_id)
            fwd_id += 1
        while fwd_id < gas:
            forward(fwd_id)
            fwd_id += 1
            backward(bwd_id)
            bwd_id += 1
        while bwd_id < gas:
            backward(bwd_id)
            bwd_id += 1

        instructions.append(InstructionReduceTiedGrads())
        instructions.append(InstructionOptimizerStep())
        return instructions


class PipelineScheduleInference(PipelineScheduleBase):
    """Forward-only, alternating two buffers (reference: inference.py:16-75)."""

    def instructions(self) -> List[Instruction]:
        pp = self.pipe_parallel_size
        rank = self.pipe_parallel_rank
        gas = self.gradient_accumulation_steps
        instructions: List[Instruction] = []
        for mb in range(gas):
            buf = mb % 2
            if rank == 0:
                instructions.append(InstructionLoadMicroBatch(mb, buf))
            else:
                instructions.append(InstructionRecvActivation(mb, buf))
            instructions.append(InstructionForwardPass(mb, buf))
            if rank == pp - 1:
                instructions.append(InstructionStoreMicroBatch(mb, buf))
            else:
                instructions.append(InstructionSendActivation(mb, buf))
        return instructions


def _interleaved_work_items(gas: int, pp: int, virtual_size: int):
    """Injection order of the spatial interleaved executor
    (pipeline.py): micro-batches in groups of pp, each group cycling
    ``virtual_size`` rounds through the stage ring before the next group
    starts."""
    items = []
    for g0 in range(0, gas, pp):
        group = range(g0, min(g0 + pp, gas))
        for rnd in range(virtual_size):
            for m in group:
                items.append((m, rnd))
    return items


@dataclass
class PipelineScheduleInterleaved(PipelineScheduleBase):
    """Spatial interleaved virtual stages (Megatron-LM, arxiv
    2104.04473; executor: pipeline.py ``PipelinedBody._interleaved``).

    Each rank runs one ``1/virtual_size``-thick layer chunk per work
    item; stage pp-1's output wraps back to stage 0 between rounds (the
    explicit ``peer`` on the comm instructions). Forward streams all
    work items, backward mirrors them in reverse — ``jax.grad`` through
    the tick scan, not 1F1B. At ``virtual_size=1`` this degenerates to
    the naive spatial fill-drain schedule and is the bubble baseline the
    interleaved/token-slice variants are judged against."""

    virtual_size: int = 2

    def duration_scale(self, name: str) -> float:
        # chunks carry the full micro-batch's activations (comm unscaled,
        # and v x more of it) but 1/v of its layers (compute scaled)
        if name in ("forward_pass", "backward_pass"):
            return 1.0 / self.virtual_size
        return 1.0

    def instructions(self) -> List[Instruction]:
        pp = self.pipe_parallel_size
        r = self.pipe_parallel_rank
        gas = self.gradient_accumulation_steps
        v = self.virtual_size
        items = _interleaved_work_items(gas, pp, v)
        ins: List[Instruction] = []
        # each forward edge is tagged by its receiving chunk id
        # (rnd*pp + rank), unique per crossing — at pp=2 the linear hop and
        # the wrap cross the SAME rank pair, and an untagged match would
        # pair a send with the wrong round's recv
        for m, rnd in items:
            buf = self.buffer_for(m)
            chunk = rnd * pp + r
            if r == 0 and rnd == 0:
                ins.append(InstructionLoadMicroBatch(m, buf))
            else:
                ins.append(InstructionRecvActivation(
                    m, buf, peer=(pp - 1 if r == 0 else r - 1), tag=chunk))
            ins.append(InstructionForwardPass(m, buf))
            if r == pp - 1 and rnd == v - 1:
                ins.append(InstructionLoss(m, buf))
            else:
                ins.append(InstructionSendActivation(
                    m, buf, peer=(0 if r == pp - 1 else r + 1), tag=chunk + 1))
        for m, rnd in reversed(items):
            buf = self.buffer_for(m)
            chunk = rnd * pp + r
            if not (r == pp - 1 and rnd == v - 1):
                ins.append(InstructionRecvGrad(
                    m, buf, peer=(0 if r == pp - 1 else r + 1), tag=chunk + 1))
            ins.append(InstructionBackwardPass(m, buf))
            if not (r == 0 and rnd == 0):
                ins.append(InstructionSendGrad(
                    m, buf, peer=(pp - 1 if r == 0 else r - 1), tag=chunk))
        ins.append(InstructionReduceTiedGrads())
        ins.append(InstructionOptimizerStep())
        return ins


@dataclass
class PipelineScheduleFillDrain(PipelineScheduleInterleaved):
    """Naive spatial fill-drain (GPipe): the ``virtual_size=1``
    degenerate of the interleaved schedule — the baseline the simulator
    compares bubble fractions against."""

    virtual_size: int = 1


@dataclass
class PipelineScheduleTokenSlice(PipelineScheduleBase):
    """TeraPipe token slicing (arxiv 2102.07988; executor:
    ``PipelinedBody._token_sliced``): each micro-batch splits into
    ``token_slices`` causal sequence chunks pipelined as independent
    work items (m-major order keeps a micro-batch's chunks causal at
    every stage). First-order cost model: compute AND comm scale 1/S
    (the payload is 1/S of the sequence; the attention prefix term is
    folded into the same scale)."""

    token_slices: int = 2

    _SCALED = (
        "forward_pass", "backward_pass", "loss", "load_micro_batch",
        "store_micro_batch", "send_activation", "recv_activation",
        "send_grad", "recv_grad",
    )

    def duration_scale(self, name: str) -> float:
        return 1.0 / self.token_slices if name in self._SCALED else 1.0

    def instructions(self) -> List[Instruction]:
        pp = self.pipe_parallel_size
        r = self.pipe_parallel_rank
        gas = self.gradient_accumulation_steps
        S = self.token_slices
        items = [(m, k) for m in range(gas) for k in range(S)]
        ins: List[Instruction] = []
        for m, k in items:
            buf = self.buffer_for(m)
            if r == 0:
                ins.append(InstructionLoadMicroBatch(m, buf))
            else:
                ins.append(InstructionRecvActivation(m, buf, peer=r - 1, tag=k))
            ins.append(InstructionForwardPass(m, buf))
            if r == pp - 1:
                ins.append(InstructionLoss(m, buf))
            else:
                ins.append(InstructionSendActivation(m, buf, peer=r + 1, tag=k))
        for m, k in reversed(items):
            buf = self.buffer_for(m)
            if r != pp - 1:
                ins.append(InstructionRecvGrad(m, buf, peer=r + 1, tag=k))
            ins.append(InstructionBackwardPass(m, buf))
            if r != 0:
                ins.append(InstructionSendGrad(m, buf, peer=r - 1, tag=k))
        ins.append(InstructionReduceTiedGrads())
        ins.append(InstructionOptimizerStep())
        return ins


# ----------------------------------------------------------------- simulator
@dataclass
class SimulationEngine:
    """Replays a profile (instruction durations) into per-rank timelines.

    ``durations``: {instruction_name: seconds}, optionally overridden per
    (name, rank). Communication instructions synchronise sender/receiver.
    Produces total time and per-rank idle fraction — the reference renders
    this as a PNG timeline; here the data structure is returned for tooling.
    (reference: pipeline_schedule/base.py:276-595)
    """

    pipe_parallel_size: int
    gradient_accumulation_steps: int
    durations: Dict[str, float] = field(default_factory=dict)

    DEFAULTS = {
        "load_micro_batch": 0.1,
        "recv_activation": 0.1,
        "send_activation": 0.1,
        "forward_pass": 1.0,
        "loss": 0.1,
        "backward_pass": 2.0,
        "send_grad": 0.1,
        "recv_grad": 0.1,
        "reduce_tied_grads": 0.2,
        "optimizer_step": 0.5,
        "store_micro_batch": 0.1,
    }

    def duration(self, name: str) -> float:
        return self.durations.get(name, self.DEFAULTS.get(name, 0.0))

    def simulate(self, schedule_cls=PipelineScheduleTrain) -> dict:
        pp = self.pipe_parallel_size
        scheds = [
            schedule_cls(
                pipe_parallel_size=pp,
                pipe_parallel_rank=r,
                gradient_accumulation_steps=self.gradient_accumulation_steps,
            )
            for r in range(pp)
        ]
        schedules = [s.instructions() for s in scheds]
        cursors = [0] * pp
        times = [0.0] * pp
        busy = [0.0] * pp
        timeline: List[dict] = []
        # comm matching: sends/recvs of (kind, mb[, tag]) pair between
        # peers — the tag separates repeated crossings of the same pair
        # (interleaved rounds at pp=2 wrap over the same two ranks)
        pending: Dict[tuple, float] = {}

        def comm_peer(name: str, rank: int) -> Optional[int]:
            if name in ("send_activation", "recv_grad"):
                return rank + 1
            if name in ("recv_activation", "send_grad"):
                return rank - 1
            return None

        def dur(rank: int, name: str) -> float:
            return self.duration(name) * scheds[rank].duration_scale(name)

        progressed = True
        while progressed:
            progressed = False
            for r in range(pp):
                while cursors[r] < len(schedules[r]):
                    ins = schedules[r][cursors[r]]
                    peer = (
                        ins.peer if ins.peer is not None
                        else comm_peer(ins.name, r)
                    )
                    if peer is None:
                        start = times[r]
                        end = start + dur(r, ins.name)
                        timeline.append(
                            {"rank": r, "name": ins.name, "micro_batch": ins.micro_batch_id,
                             "start": start, "end": end}
                        )
                        busy[r] += end - start
                        times[r] = end
                        cursors[r] += 1
                        progressed = True
                        continue
                    mb = ins.micro_batch_id
                    kind = "act" if "activation" in ins.name else "grad"
                    lo, hi = min(r, peer), max(r, peer)
                    key = (kind, mb, ins.tag, lo, hi)
                    if ins.name.startswith("send"):
                        # sends are async: post completion time and continue
                        end = times[r] + dur(r, ins.name)
                        pending[key] = end
                        busy[r] += dur(r, ins.name)
                        timeline.append(
                            {"rank": r, "name": ins.name, "micro_batch": mb,
                             "start": times[r], "end": end}
                        )
                        times[r] = end
                        cursors[r] += 1
                        progressed = True
                        continue
                    # recvs BLOCK until the matching send has completed —
                    # this is what creates the pipeline bubble the simulator
                    # exists to predict
                    if key in pending:
                        data_ready = pending.pop(key)
                        start = max(times[r], data_ready)
                        end = start + dur(r, ins.name)
                        busy[r] += dur(r, ins.name)
                        times[r] = end
                        timeline.append(
                            {"rank": r, "name": ins.name, "micro_batch": mb,
                             "start": start, "end": end}
                        )
                        cursors[r] += 1
                        progressed = True
                        continue
                    break  # blocked on an unposted send; retry next sweep
        total = max(times)
        deadlocked = any(cursors[r] < len(schedules[r]) for r in range(pp))
        idle = [1.0 - (b / total if total else 0.0) for b in busy]
        return {
            "total_time": total,
            "idle_fraction": idle,
            "timeline": timeline,
            "deadlocked": deadlocked,
        }


def schedule_class_for(virtual_size: int = 1, token_slices: int = 1):
    """The spatial executor's schedule for a layout's knobs: interleaved
    virtual stages, TeraPipe token slices, or the fill-drain baseline.
    Returns a factory ``SimulationEngine.simulate`` accepts."""
    import functools

    if virtual_size > 1 and token_slices > 1:
        raise ValueError("virtual stages and token slices are mutually "
                         "exclusive (TopologyConfig enforces this)")
    if virtual_size > 1:
        return functools.partial(
            PipelineScheduleInterleaved, virtual_size=virtual_size
        )
    if token_slices > 1:
        return functools.partial(
            PipelineScheduleTokenSlice, token_slices=token_slices
        )
    return PipelineScheduleFillDrain


def simulate_layout(
    pipe_parallel_size: int,
    gradient_accumulation_steps: int,
    durations: Optional[Dict[str, float]] = None,
    virtual_size: int = 1,
    token_slices: int = 1,
) -> dict:
    """One layout's schedule replayed through the simulator — the surface
    the auto-sharding tuner (``scaling_tpu.tune``, docs/TUNING.md) prices
    pipeline bubbles with. Returns the engine's result plus the schedule
    label and the mean idle fraction as ``bubble_fraction``."""
    if virtual_size > 1:
        label = f"interleaved(v={virtual_size})"
    elif token_slices > 1:
        label = f"token-slice(S={token_slices})"
    else:
        label = "fill-drain"
    engine = SimulationEngine(
        pipe_parallel_size=pipe_parallel_size,
        gradient_accumulation_steps=gradient_accumulation_steps,
        durations=durations or {},
    )
    result = engine.simulate(schedule_class_for(virtual_size, token_slices))
    if result["deadlocked"]:
        raise RuntimeError(
            f"schedule {label} (pp={pipe_parallel_size}, "
            f"gas={gradient_accumulation_steps}) deadlocked in simulation; "
            "a layout the tuner prices must replay cleanly"
        )
    result["schedule"] = label
    idle = result["idle_fraction"]
    result["bubble_fraction"] = sum(idle) / len(idle) if idle else 0.0
    return result


def durations_from_profile(
    observations: Optional[list],
    gradient_accumulation_steps: int,
    run_dir=None,
) -> Dict[str, float]:
    """Calibrate simulator instruction durations from a real measurement.

    Preferred source (``run_dir``): an obs run directory whose
    ``step.fwdbwd`` / ``step.sync`` span records bound the fused step's
    actual device-compute window (dispatch + drain — excludes data
    loading, logging and eval, which the old ``step_time / 3.2`` fudge
    silently smeared into compute), and whose ``step.data`` spans
    calibrate ``load_micro_batch`` directly. The fused XLA program still
    has no internal fwd/bwd boundary, so the forward:backward split keeps
    the simulator's 1:2 prior over the measured compute — that prior is
    the documented fallback, the TOTAL and the data-load cost are
    measured. When the run dir has no usable spans, or ``run_dir`` is
    None, the legacy path splits the profile's mean ``step_time`` with
    the 3.2 fudge factor as before.

    The result feeds ``SimulationEngine``/``illustrate`` to ask layout
    questions — "what does idle % look like at twice the micro-batches?"
    — anchored to a real measurement (reference: profile JSON ->
    SimulationEngine, pipeline_schedule/base.py:568-595)."""
    gas = gradient_accumulation_steps
    if run_dir is not None:
        calibrated = _durations_from_run_dir(run_dir, gas)
        if calibrated is not None:
            return calibrated
    steps = [o["step_time"] for o in (observations or []) if "step_time" in o]
    if not steps:
        raise ValueError("profile has no step_time observations")
    mean_step = sum(steps) / len(steps)
    unit = mean_step / (gas * 3.2)
    return {
        "forward_pass": unit,
        "backward_pass": 2.0 * unit,
        "loss": 0.1 * unit,
        "optimizer_step": 0.1 * unit,
        # comm rides overlapped collective-permutes here; scaled with the
        # computed unit so the ABSOLUTE defaults (tuned for the default
        # 1.0/2.0 compute times) can't swamp a calibrated fast step
        "load_micro_batch": 0.05 * unit,
        "store_micro_batch": 0.05 * unit,
        "recv_activation": 0.05 * unit,
        "send_activation": 0.05 * unit,
        "send_grad": 0.05 * unit,
        "recv_grad": 0.05 * unit,
    }


def _durations_from_run_dir(run_dir, gas: int) -> Optional[Dict[str, float]]:
    """Span-calibrated instruction durations, or None when the run dir has
    no ``step.fwdbwd`` spans to calibrate from. Aggregation (incl. the
    compile-step drop) is shared with the obs report's pipeline section
    via ``step_span_sums``."""
    from ..obs.report import (  # stdlib-only
        load_run_dir,
        step_compute_samples,
        step_span_sums,
    )

    data = load_run_dir(run_dir)
    by_host = step_span_sums(
        data.spans, ("step.fwdbwd", "step.sync", "step.data")
    )
    recs = [
        rec
        for steps in by_host.values()
        for rec in steps.values()
        if "step.fwdbwd" in rec
    ]
    if not recs:
        return None
    # per-host amortized compute (log_interval > 1 leaves most steps with
    # a dispatch-only fwdbwd record; the sync drains the backlog — the
    # shared amortization handles both regimes). Aggregated over the
    # compute spans alone so a data-only record can't dilute the mean.
    compute = sorted(step_compute_samples(
        step_span_sums(data.spans, ("step.fwdbwd", "step.sync"))
    ))
    compute_p50 = compute[len(compute) // 2]
    # fwd(1) + bwd(2) per micro-batch over the MEASURED compute window —
    # loss/optimizer ride the same window, folded in as small multiples
    unit = compute_p50 / (gas * 3.0)
    datas = sorted(r["step.data"] for r in recs if "step.data" in r)
    load = (
        datas[len(datas) // 2] / gas if datas else 0.05 * unit
    )
    return {
        "forward_pass": unit,
        "backward_pass": 2.0 * unit,
        "loss": 0.1 * unit,
        "optimizer_step": 0.1 * unit,
        "load_micro_batch": load,
        "store_micro_batch": 0.05 * unit,
        "recv_activation": 0.05 * unit,
        "send_activation": 0.05 * unit,
        "send_grad": 0.05 * unit,
        "recv_grad": 0.05 * unit,
    }


def illustrate(
    pipe_parallel_size: int,
    gradient_accumulation_steps: int,
    schedule_cls=PipelineScheduleTrain,
    width: int = 100,
    durations: Optional[Dict[str, float]] = None,
) -> str:
    """ASCII timeline of a simulated schedule — one row per pipe rank,
    F/B/· cells (reference renders a PNG, pipeline_schedule/base.py:41-149;
    the text form diffs cleanly in tests and terminals)."""
    sim = SimulationEngine(
        pipe_parallel_size=pipe_parallel_size,
        gradient_accumulation_steps=gradient_accumulation_steps,
        durations=durations or {},
    )
    result = sim.simulate(schedule_cls)
    total = result["total_time"] or 1.0
    rows = [[" "] * width for _ in range(pipe_parallel_size)]
    glyphs = {"forward_pass": "F", "backward_pass": "B", "optimizer_step": "O",
              "loss": "L", "load_micro_batch": "d", "store_micro_batch": "s"}
    for ev in result["timeline"]:
        g = glyphs.get(ev["name"])
        if g is None:
            continue
        lo = int(ev["start"] / total * (width - 1))
        hi = max(lo + 1, int(ev["end"] / total * (width - 1)))
        for c in range(lo, min(hi, width)):
            rows[ev["rank"]][c] = g
    lines = [f"rank {r}: |{''.join(row)}|" for r, row in enumerate(rows)]
    idle = ", ".join(f"{i:.0%}" for i in result["idle_fraction"])
    lines.append(f"total {result['total_time']:.2f}s  idle per rank: {idle}")
    if result["deadlocked"]:
        # a partial Gantt with no warning reads as a (great-looking)
        # schedule; make the failure impossible to miss
        banner = (
            "!! DEADLOCK: schedule never completed — unmatched sends/recvs; "
            "the timeline above is PARTIAL and its idle numbers meaningless"
        )
        lines.insert(0, banner)
        lines.append(banner)
    return "\n".join(lines)


def visualize(
    pipe_parallel_size: int,
    gradient_accumulation_steps: int,
    output_path,
    schedule_cls=PipelineScheduleTrain,
    durations: Optional[Dict[str, float]] = None,
) -> None:
    """Render the simulated schedule as a PNG Gantt timeline — one lane per
    pipe rank, forward/backward/comm blocks colored and labeled with their
    micro-batch id (reference: pipeline_schedule/base.py:276-690 renders the
    same view with matplotlib)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    from matplotlib.patches import Patch

    sim = SimulationEngine(
        pipe_parallel_size=pipe_parallel_size,
        gradient_accumulation_steps=gradient_accumulation_steps,
        durations=durations or {},
    )
    result = sim.simulate(schedule_cls)
    if result["deadlocked"]:
        raise RuntimeError(
            "schedule deadlocked (unmatched sends/recvs — simulate() "
            "reports deadlocked=true); refusing to render a partial, "
            "misleading Gantt timeline"
        )

    colors = {
        "forward_pass": "#4878cf",
        "backward_pass": "#d65f5f",
        "optimizer_step": "#6acc65",
        "loss": "#956cb4",
        "send_activation": "#c4ad66",
        "recv_activation": "#c4ad66",
        "send_grad": "#77bedb",
        "recv_grad": "#77bedb",
        "load_micro_batch": "#bbbbbb",
        "store_micro_batch": "#bbbbbb",
        "reduce_tied_grads": "#8c613c",
    }
    fig, ax = plt.subplots(
        figsize=(12, 0.8 * pipe_parallel_size + 1.5), constrained_layout=True
    )
    for ev in result["timeline"]:
        color = colors.get(ev["name"], "#dddddd")
        ax.barh(
            ev["rank"], ev["end"] - ev["start"], left=ev["start"], height=0.7,
            color=color, edgecolor="white", linewidth=0.3,
        )
        if ev["name"] in ("forward_pass", "backward_pass") and ev["micro_batch"] is not None:
            ax.text(
                (ev["start"] + ev["end"]) / 2, ev["rank"], str(ev["micro_batch"]),
                ha="center", va="center", fontsize=7, color="white",
            )
    ax.set_yticks(range(pipe_parallel_size))
    ax.set_yticklabels([f"rank {r}" for r in range(pipe_parallel_size)])
    ax.invert_yaxis()
    ax.set_xlabel("time (s, simulated)")
    idle = ", ".join(f"{i:.0%}" for i in result["idle_fraction"])
    ax.set_title(
        f"{schedule_cls.__name__}  pp={pipe_parallel_size} "
        f"gas={gradient_accumulation_steps}  total {result['total_time']:.2f}s  "
        f"idle: {idle}"
    )
    shown = {n: c for n, c in colors.items()
             if any(ev["name"] == n for ev in result["timeline"])}
    ax.legend(
        handles=[Patch(color=c, label=n) for n, c in shown.items()],
        loc="upper right", fontsize=7, ncol=2,
    )
    fig.savefig(output_path, dpi=120)
    plt.close(fig)
