"""Sharding helpers: constraint-driven tensor parallelism.

The reference implements TP with hand-written autograd collectives
(reference: src/scaling/core/nn/linear/utils.py:20-361). On TPU the idiomatic
equivalent is GSPMD: parameters and activations carry ``PartitionSpec``
annotations and XLA inserts the all-reduce/all-gather/reduce-scatter pairs —
including the transposed collectives for the backward pass — choosing
ICI-friendly schedules. These helpers apply constraints only when a mesh with
the named axis is active, so the same layer code runs on a single device.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..topology.topology import CONTEXT_AXIS, DATA_AXIS, MODEL_AXIS, PIPE_AXIS


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True, **kw):
    """Version-portable ``shard_map``: the top-level ``jax.shard_map``
    (with its ``check_vma`` kwarg) moved out of ``jax.experimental`` only
    in newer jax; older releases (this container ships 0.4.x) keep it in
    ``jax.experimental.shard_map`` under the old ``check_rep`` spelling.
    One shim here instead of four drifting call sites in ops/."""
    import inspect

    try:
        from jax import shard_map as _shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map as _shard_map
    try:
        spells_vma = "check_vma" in inspect.signature(_shard_map).parameters
    except (TypeError, ValueError):
        spells_vma = True
    kw["check_vma" if spells_vma else "check_rep"] = check_vma
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


def _axis_in_mesh(mesh: Optional[Mesh], axis: str) -> bool:
    return mesh is not None and axis in mesh.axis_names


def constrain(x: jax.Array, mesh: Optional[Mesh], *spec) -> jax.Array:
    """with_sharding_constraint that degrades to identity without a mesh."""
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P(*spec)))


def spec_with_data_axis(spec, shape, dp: int):
    """Extend a partition spec with DATA_AXIS on the LAST free dim whose
    size is divisible by ``dp`` — the ZeRO/FSDP sharding rule shared by the
    optimizer's master/moment placement (stage 1) and the compute params
    themselves (stage 3). Returns the spec unchanged when the data axis is
    already consumed (e.g. expert-parallel params) or no dim divides.

    Last-free-dim (the innermost weight dim) keeps per-layer slices of
    stage-stacked pipeline bodies contiguous on their (pipe, layer)
    leading dims, so GSPMD's per-use all-gather stays a plain collective
    rather than a strided reshard."""
    spec = list(spec)
    while len(spec) < len(shape):
        spec.append(None)
    used = {
        a
        for entry in spec
        if entry is not None
        for a in (entry if isinstance(entry, tuple) else (entry,))
    }
    if dp <= 1 or DATA_AXIS in used:
        return tuple(spec)
    for d in reversed(range(len(shape))):
        if spec[d] is None and shape[d] % dp == 0 and shape[d] > 0:
            spec[d] = DATA_AXIS
            break
    return tuple(spec)


def _seq_axis(mesh: Optional[Mesh]):
    """Sequence dims shard over the context axis when it exists (ring
    attention context parallelism); None otherwise."""
    return CONTEXT_AXIS if _axis_in_mesh(mesh, CONTEXT_AXIS) else None


def shard_batch(x: jax.Array, mesh: Optional[Mesh]) -> jax.Array:
    """(b, s, ...) activation: batch over data, sequence over context."""
    if not _axis_in_mesh(mesh, DATA_AXIS):
        return x
    seq = [_seq_axis(mesh)] if x.ndim > 1 else []
    return constrain(x, mesh, DATA_AXIS, *seq, *([None] * (x.ndim - 1 - len(seq))))


def shard_activation_tp(x: jax.Array, mesh: Optional[Mesh]) -> jax.Array:
    """(b, s, h) activation inside a TP region: h sharded over model axis."""
    if not _axis_in_mesh(mesh, MODEL_AXIS):
        return x
    return constrain(x, mesh, DATA_AXIS, _seq_axis(mesh), MODEL_AXIS)


def shard_activation_replicated_h(x: jax.Array, mesh: Optional[Mesh]) -> jax.Array:
    """(b, s, h) activation with h replicated (after TP all-reduce)."""
    if mesh is None:
        return x
    return constrain(x, mesh, DATA_AXIS, _seq_axis(mesh), None)


def shard_activation_sp(x: jax.Array, mesh: Optional[Mesh]) -> jax.Array:
    """(b, s, h) activation between TP regions under sequence parallelism:
    sequence sharded over the model axis (Megatron-style SP)."""
    if not _axis_in_mesh(mesh, MODEL_AXIS):
        return x
    seq = _seq_axis(mesh)
    sp_axes = (seq, MODEL_AXIS) if seq else MODEL_AXIS
    return constrain(x, mesh, DATA_AXIS, sp_axes, None)


def shard_param(x: jax.Array, mesh: Optional[Mesh], spec: tuple) -> jax.Array:
    if mesh is None:
        return x
    return jax.device_put(x, NamedSharding(mesh, P(*spec)))
