"""Pipeline parallelism: spatial GPipe over the ``pipe`` mesh axis.

The reference implements PP as a 1F1B instruction interpreter with NCCL
send/recv transport (reference: src/scaling/core/nn/pipeline_schedule/train.py:33-174,
communicator.py:193-510). Inside one jitted SPMD program the idiomatic TPU
formulation is *spatial* pipelining:

- the homogeneous transformer body is stacked ``(pp, layers_per_stage, ...)``
  and sharded ``P('pipe')`` on the stage dim;
- an in-flight state buffer ``(pp, mbs, ...)`` holds one micro-batch per
  stage; each tick shifts it one stage down (XLA lowers the shift on a
  pipe-sharded dim to an ICI collective-permute) and applies every stage in
  parallel via ``vmap``;
- ``n_micro + pp - 1`` ticks drain the pipeline; ``jax.grad`` through the
  scan gives the backward schedule. ``jax.checkpoint`` on the stage body
  plus sqrt(T)-chunked remat over the tick scan bounds boundary-activation
  memory to O(sqrt(n_micro) * pp) — measured sublinear in
  tests/transformer/test_training_pipeline.py (the reference's 1F1B holds
  its pp in-flight micro-batches; an unchunked scan would hold all
  n_micro).

Two schedule refinements shrink the fill/drain bubble (docs/PIPELINE.md):

- **Interleaved virtual stages** (``pipe_virtual_size`` = v, Megatron-LM
  arxiv 2104.04473): params stack ``(pp, v, layers_per_virtual, ...)``,
  the ``pp * v`` layer chunks are assigned round-robin over the stages
  (stage s holds chunks ``{r*pp + s}``), and micro-batches circulate v
  times through the stage ring — the per-tick shift becomes a CIRCULAR
  permute (``jnp.roll`` on the pipe-sharded dim, still one ICI
  collective-permute). Fill/drain shrinks from ``(pp-1)`` full-stage
  ticks to ``(pp-1)`` thin virtual-stage ticks (~v x less garbage
  compute) at the cost of v x more permutes per step.
- **Token slicing** (``pipe_token_slices`` = S, TeraPipe arxiv
  2102.07988): each micro-batch's sequence splits into S causal chunks
  which pipeline through the stages as independent work items; each
  stage keeps a per-layer KV(+segment) cache of the chunks it already
  saw, so causal attention over the prefix is exact (packed-document
  masks included). For long sequences at low grad-accum this recovers
  the parallelism micro-batch pipelining runs out of.

The instruction DSL and its simulator survive as the pure-Python
planning/visualisation tool in ``pipeline_schedule.py`` — including
``PipelineScheduleInterleaved`` / ``PipelineScheduleTokenSlice``, whose
predicted bubble fractions the ``obs report`` pipeline section checks
against span-measured step time.

Heterogeneous edges (embedding, final norm, lm head) run outside the
pipelined region: their FLOPs are negligible next to the body. Their big
vocab-dim parameters are sharded over (pipe, model) rather than replicated
per stage (parallel_module.py:_lift_edge_meta_over_pipe) — the memory
equivalent of the reference placing them on the first/last stage only.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..nn.base_layer import BaseLayer, ForwardContext
from ..nn.param import ParamMeta
from ..topology.topology import CONTEXT_AXIS, DATA_AXIS, PIPE_AXIS, Topology


# --------------------------------------------------------------- partitioning
def pipe_partition_uniform(num_items: int, num_partitions: int) -> List[int]:
    """Boundaries [b_0..b_pp]: even split, residual spread from the front.

    (reference: pipeline_partitioning.py:38-57)
    """
    base = num_items // num_partitions
    residual = num_items % num_partitions
    sizes = [base + (1 if i < residual else 0) for i in range(num_partitions)]
    bounds = [0]
    for s in sizes:
        bounds.append(bounds[-1] + s)
    return bounds


def pipe_partition_balanced(weights: List[int], num_partitions: int) -> List[int]:
    """Boundaries minimising the heaviest partition (binary search over the
    bottleneck, reference: pipeline_partitioning.py:60-136)."""
    weights_arr = np.asarray(weights, dtype=np.int64)
    prefix = np.concatenate([[0], np.cumsum(weights_arr)])

    def partitions_needed(limit: int) -> Optional[List[int]]:
        bounds = [0]
        start = 0
        for _ in range(num_partitions):
            # furthest end with sum(start..end) <= limit
            end = int(np.searchsorted(prefix, prefix[start] + limit, side="right")) - 1
            if end <= start and start < len(weights_arr):
                return None  # single item exceeds limit
            end = min(end, len(weights_arr))
            bounds.append(end)
            start = end
            if start >= len(weights_arr):
                bounds.extend([len(weights_arr)] * (num_partitions - (len(bounds) - 1)))
                return bounds[: num_partitions + 1]
        return bounds if start >= len(weights_arr) else None

    lo, hi = int(weights_arr.max(initial=0)), int(prefix[-1])
    best = None
    while lo <= hi:
        mid = (lo + hi) // 2
        b = partitions_needed(mid)
        if b is not None:
            best = b
            hi = mid - 1
        else:
            lo = mid + 1
    assert best is not None
    return best


def pipe_partition_from_indices(bounds: List[int], num_items: int, num_partitions: int) -> List[int]:
    assert len(bounds) == num_partitions + 1
    assert bounds[0] == 0 and bounds[-1] == num_items
    assert all(b2 >= b1 for b1, b2 in zip(bounds, bounds[1:]))
    return list(bounds)


# ----------------------------------------------------------------- pipelining
def _fold_key(ctx: ForwardContext, key: jax.Array, idx) -> ForwardContext:
    """Context with dropout key folded with ``idx``; no-op when deterministic."""
    if ctx.dropout_key is None or ctx.deterministic:
        return ctx
    return dataclasses.replace(ctx, dropout_key=jax.random.fold_in(key, idx))


class PipelinedBody:
    """A homogeneous layer repeated ``num_layers`` times, stage-stacked.

    ``template`` supplies init/param_metas/__call__ for one layer; the whole
    stack's params get a leading (pp, layers_per_stage) pair of dims with the
    stage dim sharded over the pipe axis. Requires num_layers % pp == 0 (the
    uniform partition); the balanced planner remains available for the
    schedule simulator.
    """

    def __init__(self, template: BaseLayer, num_layers: int, topology: Optional[Topology]):
        self.template = template
        self.num_layers = num_layers
        self.topology = topology
        self.pp = topology.pipe_parallel_size if topology else 1
        self.vpp = topology.pipe_virtual_size if topology else 1
        self.token_slices = topology.pipe_token_slices if topology else 1
        assert num_layers % max(self.pp * self.vpp, 1) == 0, (
            f"spatial pipelining needs num_layers ({num_layers}) divisible by "
            f"pipe_parallel_size ({self.pp}) * pipe_virtual_size ({self.vpp})"
        )
        self.layers_per_stage = num_layers // max(self.pp, 1)
        self.layers_per_virtual = num_layers // max(self.pp * self.vpp, 1)

    # params: every leaf gains leading dims (pp, layers_per_stage) — or
    # (pp, vpp, layers_per_virtual) under interleaving, where stage s's
    # virtual index r holds the round-robin chunk r*pp + s
    def _stack_layer_major(self, stacked: Any) -> Any:
        if self.vpp > 1:
            return jax.tree.map(
                lambda x: jnp.moveaxis(
                    x.reshape(
                        self.vpp, self.pp, self.layers_per_virtual, *x.shape[1:]
                    ),
                    0, 1,
                ),
                stacked,
            )
        return jax.tree.map(
            lambda x: x.reshape(self.pp, self.layers_per_stage, *x.shape[1:]), stacked
        )

    def init(self, key: jax.Array) -> Any:
        per_layer = [
            self.template.init(jax.random.fold_in(key, i)) for i in range(self.num_layers)
        ]
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *per_layer)
        return self._stack_layer_major(stacked)

    def param_metas(self) -> Any:
        lead = (PIPE_AXIS, None, None) if self.vpp > 1 else (PIPE_AXIS, None)

        def lift(m: ParamMeta) -> ParamMeta:
            spec = lead + tuple(m.partition_spec)
            return ParamMeta(**{**m.__dict__, "partition_spec": spec})

        return jax.tree.map(
            lift, self.template.param_metas(), is_leaf=lambda x: isinstance(x, ParamMeta)
        )

    def __call__(
        self,
        params: Any,
        x_microbatches: jax.Array,  # pytree with leaves (n_micro, mbs, ...)
        ctx: ForwardContext,
        layer_call: Optional[Callable] = None,
        remat: bool = True,
        stacked: bool = True,
        remat_policy=None,
    ) -> jax.Array:
        """Run all micro-batches through the pipelined stack.

        Returns outputs stacked (n_micro, mbs, ...); with ``stacked=False``
        the input is one micro-batch and the output is unstacked too.
        ``layer_call(params, x, ctx, layer_index)`` defaults to the
        template's __call__. ``remat_policy`` is forwarded to every
        ``jax.checkpoint`` here (None = save nothing).
        """
        call = layer_call or (lambda p, xx, c, _i: self.template(p, xx, c))
        pp, per_stage = self.pp, self.layers_per_stage

        if not stacked:
            # single micro-batch (eval/inference): run it as a 1-deep stack
            lifted = jax.tree.map(lambda x: x[None], x_microbatches)
            out = self(params, lifted, ctx, layer_call=layer_call, remat=remat,
                       remat_policy=remat_policy)
            return jax.tree.map(lambda x: x[0], out)

        n_micro = _leading(x_microbatches)
        assert n_micro is not None, "pipelined body expects stacked micro-batches"

        if pp == 1:
            def run_all(x, mb_key):
                def body(h, wi):
                    w, i = wi
                    # fold the traced layer index into the per-micro-batch
                    # key: the Python-side key counter is baked once at trace
                    # time, so without this every scan iteration would reuse
                    # the same masks (reference per-layer RNG:
                    # rng_tracker.py:59-96)
                    return call(w, h, _fold_key(ctx, mb_key, i), i), None
                if remat:
                    body = jax.checkpoint(body, policy=remat_policy)
                squeezed = jax.tree.map(lambda p: p.reshape(self.num_layers, *p.shape[2:]), params)
                h, _ = jax.lax.scan(body, x, (squeezed, jnp.arange(self.num_layers)))
                return h

            base = ctx.dropout_key if ctx.dropout_key is not None else jax.random.PRNGKey(0)
            mb_keys = jax.vmap(lambda m: jax.random.fold_in(base, m))(jnp.arange(n_micro))
            return jax.vmap(run_all)(x_microbatches, mb_keys)

        mesh = ctx.mesh
        from jax.sharding import NamedSharding, PartitionSpec as P

        def constrain_state(s):
            if mesh is None:
                return s
            def spec_for(x):
                # (pp, mbs, s, ...): stage over pipe, batch over data,
                # sequence over context (size-1 unless cp>1, which excludes
                # pp>1 anyway — named for consistency)
                axes = [PIPE_AXIS, DATA_AXIS, CONTEXT_AXIS][: x.ndim]
                return P(*axes, *([None] * (x.ndim - len(axes))))

            return jax.tree.map(
                lambda x: jax.lax.with_sharding_constraint(
                    x, NamedSharding(mesh, spec_for(x))
                ),
                s,
            )

        # ONE dropout base key and shard count for every pp>1 schedule:
        # independently-edited copies could silently decorrelate them
        base_key = (
            ctx.dropout_key
            if ctx.dropout_key is not None
            else jax.random.PRNGKey(0)
        )
        state_shards = pp * (
            self.topology.data_parallel_size
            * self.topology.context_parallel_size
            if self.topology is not None
            else 1
        )
        if self.vpp > 1:
            return self._interleaved(
                params, x_microbatches, ctx, call, remat, remat_policy,
                constrain_state, base_key, state_shards, n_micro,
            )
        if self.token_slices > 1:
            return self._token_sliced(
                params, x_microbatches, ctx, call, layer_call, remat,
                remat_policy, constrain_state, base_key, state_shards, n_micro,
            )

        stage_indices = jnp.arange(pp)

        def stage_fn(stage_params, x, stage_idx, tick_key):
            # decorrelate dropout: micro-batch m meets stage s at tick
            # t = m + s, so the per-(tick, stage) key is distinct and
            # deterministic per (stage, micro-batch); folding the layer index
            # on top gives each layer within the stage its own masks
            def body(h, wi):
                w, j = wi
                layer_index = stage_idx * per_stage + j
                return call(w, h, _fold_key(ctx, tick_key, layer_index), layer_index), None

            h, _ = jax.lax.scan(body, x, (stage_params, jnp.arange(per_stage)))
            return h

        if remat:
            stage_fn = jax.checkpoint(stage_fn, static_argnums=(), policy=remat_policy)

        def tick(state, t):
            tick_key = jax.random.fold_in(base_key, t)
            inp = jax.tree.map(
                lambda xs: jax.lax.dynamic_index_in_dim(
                    xs, jnp.clip(t, 0, n_micro - 1), keepdims=False
                ),
                x_microbatches,
            )
            # roll-then-overwrite, NOT concatenate([inp[None], s[:-1]]):
            # with model-parallel params in the stage vmap, XLA SPMD
            # miscompiles the concatenate form of the shift on the
            # pipe-sharded dim (wrong activations, reproduced down to a
            # 60-line pure-matmul case on jax 0.4.37 CPU: max err ~11 vs
            # the roll form's 5e-7 against the sequential reference —
            # tests/core/test_nn/test_pipeline.py guards this). The rolled
            # row 0 (old last stage's output) is discarded by the
            # overwrite, so semantics are identical.
            shifted = jax.tree.map(
                lambda i, s: jnp.roll(s, 1, axis=0).at[0].set(i), inp, state
            )
            shifted = constrain_state(shifted)
            tick_keys = jax.vmap(lambda s: jax.random.fold_in(tick_key, s))(stage_indices)
            new_state = jax.vmap(stage_fn)(params, shifted, stage_indices, tick_keys)
            new_state = constrain_state(new_state)
            out = jax.tree.map(lambda s: s[-1], new_state)
            return new_state, out

        zero_state = jax.tree.map(
            lambda xs: jnp.zeros((pp,) + xs.shape[1:], dtype=xs.dtype), x_microbatches
        )
        zero_state = constrain_state(zero_state)
        n_ticks = n_micro + pp - 1
        outs = _scan_ticks(
            tick, zero_state, n_ticks, remat, remat_policy, state_shards
        )
        return jax.tree.map(lambda o: o[pp - 1 :], outs)

    # ------------------------------------------------- interleaved (vpp > 1)
    def _interleaved(self, params, x_microbatches, ctx, call, remat,
                     remat_policy, constrain_state, base_key, state_shards,
                     n_micro):
        """Interleaved virtual stages: micro-batches circulate ``vpp``
        rounds through the stage ring, one thin ``layers_per_virtual``
        chunk per tick; stage s applies chunk ``r*pp + s`` on round r.

        Injection runs in groups of pp micro-batches: group g's round-r
        items enter stage 0 at ticks ``g*pp*vpp + r*pp + p`` (round 0 by
        fresh injection, later rounds via the circular wrap of stage
        pp-1's output — ``jnp.roll`` on the pipe-sharded dim lowers to one
        ICI collective-permute per tick). Fill/drain is ``pp - 1`` THIN
        ticks instead of the naive schedule's ``pp - 1`` full ticks: ~vpp
        x less bubble, vpp x more permutes. When n_micro is not a multiple
        of pp (eval's single micro-batch), the empty injection slots carry
        clipped duplicates whose outputs are never gathered."""
        pp, v, lpv = self.pp, self.vpp, self.layers_per_virtual
        stage_indices = jnp.arange(pp)
        period = pp * v

        def stage_fn(stage_params, x, stage_idx, round_idx, tick_key):
            chunk = jax.tree.map(
                lambda p: jax.lax.dynamic_index_in_dim(
                    p, round_idx, axis=0, keepdims=False
                ),
                stage_params,
            )

            def body(h, wj):
                w, j = wj
                layer_index = (round_idx * pp + stage_idx) * lpv + j
                return call(w, h, _fold_key(ctx, tick_key, layer_index), layer_index), None

            h, _ = jax.lax.scan(body, x, (chunk, jnp.arange(lpv)))
            return h

        if remat:
            stage_fn = jax.checkpoint(stage_fn, policy=remat_policy)

        def tick(state, t):
            tick_key = jax.random.fold_in(base_key, t)
            within = t % period
            inject = within < pp
            mb_idx = jnp.clip((t // period) * pp + within, 0, n_micro - 1)
            inp = jax.tree.map(
                lambda xs: jax.lax.dynamic_index_in_dim(xs, mb_idx, keepdims=False),
                x_microbatches,
            )
            # circular shift: stage 0 receives stage pp-1's wrap unless this
            # tick injects a fresh round-0 micro-batch over it
            rolled = jax.tree.map(lambda s: jnp.roll(s, 1, axis=0), state)
            shifted = jax.tree.map(
                lambda i, r: r.at[0].set(jnp.where(inject, i, r[0])), inp, rolled
            )
            shifted = constrain_state(shifted)
            rounds = ((t - stage_indices) % period) // pp
            tick_keys = jax.vmap(lambda s: jax.random.fold_in(tick_key, s))(stage_indices)
            new_state = jax.vmap(stage_fn)(
                params, shifted, stage_indices, rounds, tick_keys
            )
            new_state = constrain_state(new_state)
            out = jax.tree.map(lambda s: s[-1], new_state)
            return new_state, out

        zero_state = jax.tree.map(
            lambda xs: jnp.zeros((pp,) + xs.shape[1:], dtype=xs.dtype), x_microbatches
        )
        zero_state = constrain_state(zero_state)
        # micro-batch m (group g, position p) makes its last-round exit from
        # stage pp-1 at tick g*pp*v + v*pp + p - 1
        out_ticks = [
            (m // pp) * period + v * pp + (m % pp) - 1 for m in range(n_micro)
        ]
        n_ticks = out_ticks[-1] + 1
        outs = _scan_ticks(
            tick, zero_state, n_ticks, remat, remat_policy, state_shards
        )
        idx = jnp.asarray(out_ticks)
        return jax.tree.map(lambda o: jnp.take(o, idx, axis=0), outs)

    # ---------------------------------------------- token slicing (TeraPipe)
    def _token_sliced(self, params, x_microbatches, ctx, call, layer_call,
                      remat, remat_policy, constrain_state, base_key,
                      state_shards, n_micro):
        """TeraPipe token slicing: each micro-batch's sequence splits into
        ``token_slices`` causal chunks that pipeline through the stages as
        independent work items (injection order m-major, so a micro-batch's
        chunks hit each stage consecutively and in causal order).

        Exactness across chunks comes from a per-stage, per-layer
        KV(+segment-id) cache carried across ticks: chunk k runs with
        ``cache_offset = k * slice_len`` against the cache its
        predecessors wrote, reproducing full causal (and packed-document)
        attention over the prefix. Templates advertise the cache protocol
        via ``init_token_slice_cache``; templates whose math is
        position-local (no cross-token mixing) run cache-free. Slots
        beyond the current chunk are masked by the attention's
        ``valid_k`` gate, so caches never need resetting between
        micro-batches — every valid slot was freshly written by the
        current one."""
        pp, S = self.pp, self.token_slices
        per_stage = self.layers_per_stage
        stage_indices = jnp.arange(pp)

        s_total = None
        for leaf in jax.tree.leaves(x_microbatches):
            if leaf.ndim < 3:
                raise ValueError(
                    "token slicing needs every state leaf shaped "
                    f"(n_micro, mbs, seq, ...); got {leaf.shape}"
                )
            if s_total is None:
                s_total = leaf.shape[2]
            if leaf.shape[2] != s_total:
                raise ValueError(
                    "token slicing needs one shared sequence dim; got "
                    f"{leaf.shape[2]} vs {s_total}"
                )
        if s_total % S != 0:
            raise ValueError(
                f"pipe_token_slices ({S}) must divide the sequence length "
                f"({s_total})"
            )
        slice_len = s_total // S
        n_work = n_micro * S

        def split(leaf):
            x = leaf.reshape(
                n_micro, leaf.shape[1], S, slice_len, *leaf.shape[3:]
            )
            x = jnp.moveaxis(x, 2, 1)
            return x.reshape(n_work, leaf.shape[1], slice_len, *leaf.shape[3:])

        work_items = jax.tree.map(split, x_microbatches)

        cached = hasattr(self.template, "init_token_slice_cache")
        if cached and layer_call is not None:
            # the cached stage loop calls the template's cache-protocol
            # signature directly; silently dropping a caller's wrapper
            # would be wrong behavior with zero signal
            raise NotImplementedError(
                "token slicing with a KV-cache template does not support "
                "layer_call overrides (the cache protocol bypasses them)"
            )
        if not cached:
            import inspect

            try:
                takes_cache = "kv_cache" in inspect.signature(
                    type(self.template).__call__
                ).parameters
            except (TypeError, ValueError):
                takes_cache = False
            if takes_cache:
                raise NotImplementedError(
                    f"{type(self.template).__name__} takes kv_cache but does "
                    "not implement init_token_slice_cache; token slicing "
                    "cannot run its attention exactly without the cache "
                    "protocol"
                )

        zero_caches = None
        if cached:
            probe_ctx = dataclasses.replace(
                ctx, dropout_key=None, deterministic=True
            )
            w0 = jax.tree.map(lambda p: p[0, 0], params)
            x0 = jax.tree.map(lambda l: l[0], work_items)
            layer_cache = self.template.init_token_slice_cache(
                w0, x0, probe_ctx, capacity=s_total
            )
            zero_caches = jax.tree.map(
                lambda l: jnp.zeros((pp, per_stage) + l.shape, l.dtype),
                layer_cache,
            )

        def constrain_caches(c):
            if ctx.mesh is None or c is None:
                return c
            from jax.sharding import NamedSharding, PartitionSpec as P

            def spec_for(x):
                # (pp, per_stage, mbs, seq, ...): stage over pipe, the
                # cached batch dim over data
                axes = [PIPE_AXIS, None, DATA_AXIS][: min(x.ndim, 3)]
                return P(*axes, *([None] * (x.ndim - len(axes))))

            return jax.tree.map(
                lambda x: jax.lax.with_sharding_constraint(
                    x, NamedSharding(ctx.mesh, spec_for(x))
                ),
                c,
            )

        zero_caches = constrain_caches(zero_caches)

        if cached:
            def stage_fn(stage_params, stage_cache, x, stage_idx, offset, tick_key):
                def body(h, wjc):
                    w, j, cache_j = wjc
                    layer_index = stage_idx * per_stage + j
                    out, new_cache = self.template(
                        w, h, _fold_key(ctx, tick_key, layer_index),
                        kv_cache=cache_j, cache_offset=offset,
                    )
                    return out, new_cache

                h, new_caches = jax.lax.scan(
                    body, x, (stage_params, jnp.arange(per_stage), stage_cache)
                )
                return h, new_caches
        else:
            def stage_fn(stage_params, stage_cache, x, stage_idx, offset, tick_key):
                del stage_cache, offset

                def body(h, wj):
                    w, j = wj
                    layer_index = stage_idx * per_stage + j
                    return call(
                        w, h, _fold_key(ctx, tick_key, layer_index), layer_index
                    ), None

                h, _ = jax.lax.scan(body, x, (stage_params, jnp.arange(per_stage)))
                return h, None

        if remat:
            stage_fn = jax.checkpoint(stage_fn, policy=remat_policy)

        def tick(carry, t):
            state, caches = carry
            tick_key = jax.random.fold_in(base_key, t)
            inp = jax.tree.map(
                lambda xs: jax.lax.dynamic_index_in_dim(
                    xs, jnp.clip(t, 0, n_work - 1), keepdims=False
                ),
                work_items,
            )
            # roll-then-overwrite shift — same SPMD-miscompile guard as the
            # naive path (see the comment there)
            shifted = jax.tree.map(
                lambda i, s: jnp.roll(s, 1, axis=0).at[0].set(i), inp, state
            )
            shifted = constrain_state(shifted)
            # the chunk index of the work item at each stage sets where its
            # K/V land in the cache (garbage fill/drain writes are masked or
            # overwritten before any valid read)
            w_at = jnp.clip(t - stage_indices, 0, None)
            offsets = (w_at % S) * slice_len
            tick_keys = jax.vmap(lambda s: jax.random.fold_in(tick_key, s))(stage_indices)
            new_state, new_caches = jax.vmap(stage_fn)(
                params, caches, shifted, stage_indices, offsets, tick_keys
            )
            new_state = constrain_state(new_state)
            new_caches = constrain_caches(new_caches)
            out = jax.tree.map(lambda s: s[-1], new_state)
            return (new_state, new_caches), out

        zero_state = jax.tree.map(
            lambda xs: jnp.zeros((pp,) + xs.shape[1:], dtype=xs.dtype), work_items
        )
        zero_state = constrain_state(zero_state)
        n_ticks = n_work + pp - 1
        outs = _scan_ticks(
            tick, (zero_state, zero_caches), n_ticks, remat, remat_policy,
            state_shards,
        )
        outs = jax.tree.map(lambda o: o[pp - 1 :], outs)

        def join(leaf):
            rest = leaf.shape[3:]
            x = leaf.reshape(n_micro, S, leaf.shape[1], slice_len, *rest)
            x = jnp.moveaxis(x, 1, 2)
            return x.reshape(n_micro, leaf.shape[1], s_total, *rest)

        return jax.tree.map(join, outs)


def _scan_ticks(tick, zero_carry, n_ticks, remat, remat_policy,
                state_shards) -> Any:
    """The ONE tick scan behind every pp>1 schedule, with the budgeted
    sqrt(T)-chunked remat trade.

    A plain scan saves every tick's carry for backward — O(n_ticks)
    boundary activations, where the reference's 1F1B holds only its pp
    in-flight micro-batches (pipeline_schedule/train.py:109-117).
    Checkpointing chunks of ~sqrt(T) ticks stores only chunk-edge
    carries + one chunk's internal carries during its backward:
    O(sqrt(T)) memory for one extra body forward. That extra forward is
    ~+25% step time (b = 2f: (3f+b)/(2f+b)) — real wall-clock, unlike
    the fill/drain garbage ticks which overlap 1F1B's bubble — so it is
    paid ONLY when the carries would actually strain HBM (at BASELINE
    #4's pp=2 gas=8 the carries are ~144 MB/device: the plain scan
    matches a 1F1B executor's wall-clock there; see PERF.md "Spatial
    pipeline vs a 1F1B executor").

    The budget gate sees the WHOLE carry (KV caches included under token
    slicing) and the schedule's true ``n_ticks`` (v x more, thinner
    ticks under interleaving; S x under token slicing), so chunking
    engages on real carry volume — not v x too early."""
    if remat and n_ticks >= 4 and _tick_carries_exceed_budget(
        zero_carry, n_ticks, state_shards
    ):
        chunk, n_chunks = _remat_chunking(n_ticks)
        padded = n_chunks * chunk  # excess ticks produce discarded outputs

        @partial(jax.checkpoint, policy=remat_policy)
        def chunk_body(carry, ts):
            return jax.lax.scan(tick, carry, ts)

        tick_ids = jnp.arange(padded).reshape(n_chunks, chunk)
        _, outs = jax.lax.scan(chunk_body, zero_carry, tick_ids)
        return jax.tree.map(
            lambda o: o.reshape((padded,) + o.shape[2:])[:n_ticks], outs
        )
    _, outs = jax.lax.scan(tick, zero_carry, jnp.arange(n_ticks))
    return outs


def _tick_carries_exceed_budget(state: Any, n_ticks: int,
                                n_state_shards: int) -> bool:
    """Decide whether the tick scan's saved carries justify chunked remat.

    A plain scan saves one state carry per tick for the backward; the
    state's GLOBAL shape is ``(pp, mbs*dp, s, ...)`` sharded over
    ``(pipe, data, context)`` (``constrain_state``), so ``n_state_shards``
    is ``pp * dp * cp`` — dividing by ``pp`` alone would overestimate
    per-device carries by the data-parallel factor and engage the chunked
    trade dp-times too early. When the per-device total fits comfortably
    in HBM, chunked remat would trade nothing for an extra full body
    forward — pure wall-clock loss.
    ``SCALING_TPU_PIPE_CARRY_BUDGET_MB`` (default 1024) sets the
    per-device budget; 0 forces chunking (the memory-lean mode, and what
    the chunking tests pin). Works on concrete arrays and
    ShapeDtypeStructs alike (the compile pin evaluates the same gate on
    abstract shapes)."""
    import os

    budget_mb = float(os.environ.get("SCALING_TPU_PIPE_CARRY_BUDGET_MB", "1024"))
    per_device_tick = sum(
        int(np.prod(leaf.shape)) * jnp.dtype(leaf.dtype).itemsize
        for leaf in jax.tree.leaves(state)
    ) / max(n_state_shards, 1)
    return per_device_tick * n_ticks > budget_mb * 2**20


def _remat_chunking(n_ticks: int) -> tuple[int, int]:
    """(chunk, n_chunks) for the sqrt(T)-chunked remat scan, chosen to
    MINIMIZE padding: every padded tick runs the full stage vmap and its
    outputs are discarded, so padding is pure wall-clock waste. Among chunk
    sizes within ±2 of sqrt(T) whose chunk count also stays O(sqrt(T)) the
    smallest padding wins (ties to the size nearest sqrt(T)); padding is
    zero whenever T factors as chunk x n_chunks inside those bounds, and
    never exceeds the naive ceil(sqrt(T)) chunking's."""
    root = int(np.ceil(np.sqrt(n_ticks)))
    best = None
    for chunk in range(max(2, root - 2), root + 3):
        n_chunks = int(np.ceil(n_ticks / chunk))
        # both factors stay O(sqrt(T)) — chunk bounds the recompute span,
        # n_chunks the edge carries — and a single chunk (no outer scan)
        # would hold every inner carry during its backward
        if n_chunks < 2 or n_chunks > root + 2:
            continue
        padding = n_chunks * chunk - n_ticks
        rank = (padding, abs(chunk - root))
        if best is None or rank < best[0]:
            best = (rank, chunk, n_chunks)
    if best is None:  # unreachable for n_ticks >= 4; keep the naive split
        chunk = root
        return chunk, int(np.ceil(n_ticks / chunk))
    return best[1], best[2]


def _leading(tree: Any) -> Optional[int]:
    leaves = jax.tree.leaves(tree)
    if not leaves:
        return None
    return leaves[0].shape[0] if leaves[0].ndim > 0 else None
