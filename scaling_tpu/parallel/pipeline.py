"""Pipeline parallelism: spatial GPipe over the ``pipe`` mesh axis.

The reference implements PP as a 1F1B instruction interpreter with NCCL
send/recv transport (reference: src/scaling/core/nn/pipeline_schedule/train.py:33-174,
communicator.py:193-510). Inside one jitted SPMD program the idiomatic TPU
formulation is *spatial* pipelining:

- the homogeneous transformer body is stacked ``(pp, layers_per_stage, ...)``
  and sharded ``P('pipe')`` on the stage dim;
- an in-flight state buffer ``(pp, mbs, ...)`` holds one micro-batch per
  stage; each tick shifts it one stage down (XLA lowers the shift on a
  pipe-sharded dim to an ICI collective-permute) and applies every stage in
  parallel via ``vmap``;
- ``n_micro + pp - 1`` ticks drain the pipeline; ``jax.grad`` through the
  scan gives the backward schedule. ``jax.checkpoint`` on the stage body
  plus sqrt(T)-chunked remat over the tick scan bounds boundary-activation
  memory to O(sqrt(n_micro) * pp) — measured sublinear in
  tests/transformer/test_training_pipeline.py (the reference's 1F1B holds
  its pp in-flight micro-batches; an unchunked scan would hold all
  n_micro).

The 1F1B instruction DSL and its simulator survive as the pure-Python
planning/visualisation tool in ``pipeline_schedule.py``.

Heterogeneous edges (embedding, final norm, lm head) run outside the
pipelined region: their FLOPs are negligible next to the body. Their big
vocab-dim parameters are sharded over (pipe, model) rather than replicated
per stage (parallel_module.py:_lift_edge_meta_over_pipe) — the memory
equivalent of the reference placing them on the first/last stage only.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..nn.base_layer import BaseLayer, ForwardContext
from ..nn.param import ParamMeta
from ..topology.topology import CONTEXT_AXIS, DATA_AXIS, PIPE_AXIS, Topology


# --------------------------------------------------------------- partitioning
def pipe_partition_uniform(num_items: int, num_partitions: int) -> List[int]:
    """Boundaries [b_0..b_pp]: even split, residual spread from the front.

    (reference: pipeline_partitioning.py:38-57)
    """
    base = num_items // num_partitions
    residual = num_items % num_partitions
    sizes = [base + (1 if i < residual else 0) for i in range(num_partitions)]
    bounds = [0]
    for s in sizes:
        bounds.append(bounds[-1] + s)
    return bounds


def pipe_partition_balanced(weights: List[int], num_partitions: int) -> List[int]:
    """Boundaries minimising the heaviest partition (binary search over the
    bottleneck, reference: pipeline_partitioning.py:60-136)."""
    weights_arr = np.asarray(weights, dtype=np.int64)
    prefix = np.concatenate([[0], np.cumsum(weights_arr)])

    def partitions_needed(limit: int) -> Optional[List[int]]:
        bounds = [0]
        start = 0
        for _ in range(num_partitions):
            # furthest end with sum(start..end) <= limit
            end = int(np.searchsorted(prefix, prefix[start] + limit, side="right")) - 1
            if end <= start and start < len(weights_arr):
                return None  # single item exceeds limit
            end = min(end, len(weights_arr))
            bounds.append(end)
            start = end
            if start >= len(weights_arr):
                bounds.extend([len(weights_arr)] * (num_partitions - (len(bounds) - 1)))
                return bounds[: num_partitions + 1]
        return bounds if start >= len(weights_arr) else None

    lo, hi = int(weights_arr.max(initial=0)), int(prefix[-1])
    best = None
    while lo <= hi:
        mid = (lo + hi) // 2
        b = partitions_needed(mid)
        if b is not None:
            best = b
            hi = mid - 1
        else:
            lo = mid + 1
    assert best is not None
    return best


def pipe_partition_from_indices(bounds: List[int], num_items: int, num_partitions: int) -> List[int]:
    assert len(bounds) == num_partitions + 1
    assert bounds[0] == 0 and bounds[-1] == num_items
    assert all(b2 >= b1 for b1, b2 in zip(bounds, bounds[1:]))
    return list(bounds)


# ----------------------------------------------------------------- pipelining
def _fold_key(ctx: ForwardContext, key: jax.Array, idx) -> ForwardContext:
    """Context with dropout key folded with ``idx``; no-op when deterministic."""
    if ctx.dropout_key is None or ctx.deterministic:
        return ctx
    return dataclasses.replace(ctx, dropout_key=jax.random.fold_in(key, idx))


class PipelinedBody:
    """A homogeneous layer repeated ``num_layers`` times, stage-stacked.

    ``template`` supplies init/param_metas/__call__ for one layer; the whole
    stack's params get a leading (pp, layers_per_stage) pair of dims with the
    stage dim sharded over the pipe axis. Requires num_layers % pp == 0 (the
    uniform partition); the balanced planner remains available for the
    schedule simulator.
    """

    def __init__(self, template: BaseLayer, num_layers: int, topology: Optional[Topology]):
        self.template = template
        self.num_layers = num_layers
        self.topology = topology
        self.pp = topology.pipe_parallel_size if topology else 1
        assert num_layers % max(self.pp, 1) == 0, (
            f"spatial pipelining needs num_layers ({num_layers}) divisible by "
            f"pipe_parallel_size ({self.pp})"
        )
        self.layers_per_stage = num_layers // max(self.pp, 1)

    # params: every leaf gains leading dims (pp, layers_per_stage)
    def init(self, key: jax.Array) -> Any:
        per_layer = [
            self.template.init(jax.random.fold_in(key, i)) for i in range(self.num_layers)
        ]
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *per_layer)
        return jax.tree.map(
            lambda x: x.reshape(self.pp, self.layers_per_stage, *x.shape[1:]), stacked
        )

    def param_metas(self) -> Any:
        def lift(m: ParamMeta) -> ParamMeta:
            spec = (PIPE_AXIS, None) + tuple(m.partition_spec)
            return ParamMeta(**{**m.__dict__, "partition_spec": spec})

        return jax.tree.map(
            lift, self.template.param_metas(), is_leaf=lambda x: isinstance(x, ParamMeta)
        )

    def __call__(
        self,
        params: Any,
        x_microbatches: jax.Array,  # pytree with leaves (n_micro, mbs, ...)
        ctx: ForwardContext,
        layer_call: Optional[Callable] = None,
        remat: bool = True,
        stacked: bool = True,
        remat_policy=None,
    ) -> jax.Array:
        """Run all micro-batches through the pipelined stack.

        Returns outputs stacked (n_micro, mbs, ...); with ``stacked=False``
        the input is one micro-batch and the output is unstacked too.
        ``layer_call(params, x, ctx, layer_index)`` defaults to the
        template's __call__. ``remat_policy`` is forwarded to every
        ``jax.checkpoint`` here (None = save nothing).
        """
        call = layer_call or (lambda p, xx, c, _i: self.template(p, xx, c))
        pp, per_stage = self.pp, self.layers_per_stage

        if not stacked:
            # single micro-batch (eval/inference): run it as a 1-deep stack
            lifted = jax.tree.map(lambda x: x[None], x_microbatches)
            out = self(params, lifted, ctx, layer_call=layer_call, remat=remat,
                       remat_policy=remat_policy)
            return jax.tree.map(lambda x: x[0], out)

        n_micro = _leading(x_microbatches)
        assert n_micro is not None, "pipelined body expects stacked micro-batches"

        if pp == 1:
            def run_all(x, mb_key):
                def body(h, wi):
                    w, i = wi
                    # fold the traced layer index into the per-micro-batch
                    # key: the Python-side key counter is baked once at trace
                    # time, so without this every scan iteration would reuse
                    # the same masks (reference per-layer RNG:
                    # rng_tracker.py:59-96)
                    return call(w, h, _fold_key(ctx, mb_key, i), i), None
                if remat:
                    body = jax.checkpoint(body, policy=remat_policy)
                squeezed = jax.tree.map(lambda p: p.reshape(self.num_layers, *p.shape[2:]), params)
                h, _ = jax.lax.scan(body, x, (squeezed, jnp.arange(self.num_layers)))
                return h

            base = ctx.dropout_key if ctx.dropout_key is not None else jax.random.PRNGKey(0)
            mb_keys = jax.vmap(lambda m: jax.random.fold_in(base, m))(jnp.arange(n_micro))
            return jax.vmap(run_all)(x_microbatches, mb_keys)

        mesh = ctx.mesh
        from jax.sharding import NamedSharding, PartitionSpec as P

        def constrain_state(s):
            if mesh is None:
                return s
            def spec_for(x):
                # (pp, mbs, s, ...): stage over pipe, batch over data,
                # sequence over context (size-1 unless cp>1, which excludes
                # pp>1 anyway — named for consistency)
                axes = [PIPE_AXIS, DATA_AXIS, CONTEXT_AXIS][: x.ndim]
                return P(*axes, *([None] * (x.ndim - len(axes))))

            return jax.tree.map(
                lambda x: jax.lax.with_sharding_constraint(
                    x, NamedSharding(mesh, spec_for(x))
                ),
                s,
            )

        stage_indices = jnp.arange(pp)

        def stage_fn(stage_params, x, stage_idx, tick_key):
            # decorrelate dropout: micro-batch m meets stage s at tick
            # t = m + s, so the per-(tick, stage) key is distinct and
            # deterministic per (stage, micro-batch); folding the layer index
            # on top gives each layer within the stage its own masks
            def body(h, wi):
                w, j = wi
                layer_index = stage_idx * per_stage + j
                return call(w, h, _fold_key(ctx, tick_key, layer_index), layer_index), None

            h, _ = jax.lax.scan(body, x, (stage_params, jnp.arange(per_stage)))
            return h

        if remat:
            stage_fn = jax.checkpoint(stage_fn, static_argnums=(), policy=remat_policy)

        base_key = (
            ctx.dropout_key
            if ctx.dropout_key is not None
            else jax.random.PRNGKey(0)
        )

        def tick(state, t):
            tick_key = jax.random.fold_in(base_key, t)
            inp = jax.tree.map(
                lambda xs: jax.lax.dynamic_index_in_dim(
                    xs, jnp.clip(t, 0, n_micro - 1), keepdims=False
                ),
                x_microbatches,
            )
            shifted = jax.tree.map(
                lambda i, s: jnp.concatenate([i[None], s[:-1]], axis=0), inp, state
            )
            shifted = constrain_state(shifted)
            tick_keys = jax.vmap(lambda s: jax.random.fold_in(tick_key, s))(stage_indices)
            new_state = jax.vmap(stage_fn)(params, shifted, stage_indices, tick_keys)
            new_state = constrain_state(new_state)
            out = jax.tree.map(lambda s: s[-1], new_state)
            return new_state, out

        zero_state = jax.tree.map(
            lambda xs: jnp.zeros((pp,) + xs.shape[1:], dtype=xs.dtype), x_microbatches
        )
        zero_state = constrain_state(zero_state)
        n_ticks = n_micro + pp - 1
        state_shards = pp * (
            self.topology.data_parallel_size
            * self.topology.context_parallel_size
            if self.topology is not None
            else 1
        )
        if remat and n_ticks >= 4 and _tick_carries_exceed_budget(
            zero_state, n_ticks, state_shards
        ):
            # sqrt(T)-chunked remat over the tick scan: a plain scan saves
            # every tick's carry for backward — O(n_micro * pp) boundary
            # activations, where the reference's 1F1B holds only its pp
            # in-flight micro-batches (pipeline_schedule/train.py:109-117).
            # Checkpointing chunks of ~sqrt(T) ticks stores only chunk-edge
            # carries + one chunk's internal carries during its backward:
            # O(sqrt(n_micro) * pp) memory for one extra body forward.
            #
            # That extra forward is ~+25% step time (b = 2f: (3f+b)/(2f+b))
            # — real wall-clock, unlike the fill/drain garbage ticks which
            # overlap 1F1B's bubble — so it is paid ONLY when the carries
            # would actually strain HBM (at BASELINE #4's pp=2 gas=8 the
            # carries are ~144 MB/device: the plain scan matches a 1F1B
            # executor's wall-clock there; see PERF.md "Spatial pipeline
            # vs a 1F1B executor").
            chunk, n_chunks = _remat_chunking(n_ticks)
            padded = n_chunks * chunk  # excess ticks produce discarded outputs
            tick_ids = jnp.arange(padded).reshape(n_chunks, chunk)

            @partial(jax.checkpoint, policy=remat_policy)
            def chunk_body(state, ts):
                return jax.lax.scan(tick, state, ts)

            _, outs = jax.lax.scan(chunk_body, zero_state, tick_ids)
            outs = jax.tree.map(
                lambda o: o.reshape((padded,) + o.shape[2:])[pp - 1 : n_ticks], outs
            )
            return outs
        _, outs = jax.lax.scan(tick, zero_state, jnp.arange(n_ticks))
        return jax.tree.map(lambda o: o[pp - 1 :], outs)


def _tick_carries_exceed_budget(state: Any, n_ticks: int,
                                n_state_shards: int) -> bool:
    """Decide whether the tick scan's saved carries justify chunked remat.

    A plain scan saves one state carry per tick for the backward; the
    state's GLOBAL shape is ``(pp, mbs*dp, s, ...)`` sharded over
    ``(pipe, data, context)`` (``constrain_state``), so ``n_state_shards``
    is ``pp * dp * cp`` — dividing by ``pp`` alone would overestimate
    per-device carries by the data-parallel factor and engage the chunked
    trade dp-times too early. When the per-device total fits comfortably
    in HBM, chunked remat would trade nothing for an extra full body
    forward — pure wall-clock loss.
    ``SCALING_TPU_PIPE_CARRY_BUDGET_MB`` (default 1024) sets the
    per-device budget; 0 forces chunking (the memory-lean mode, and what
    the chunking tests pin). Works on concrete arrays and
    ShapeDtypeStructs alike (the compile pin evaluates the same gate on
    abstract shapes)."""
    import os

    budget_mb = float(os.environ.get("SCALING_TPU_PIPE_CARRY_BUDGET_MB", "1024"))
    per_device_tick = sum(
        int(np.prod(leaf.shape)) * jnp.dtype(leaf.dtype).itemsize
        for leaf in jax.tree.leaves(state)
    ) / max(n_state_shards, 1)
    return per_device_tick * n_ticks > budget_mb * 2**20


def _remat_chunking(n_ticks: int) -> tuple[int, int]:
    """(chunk, n_chunks) for the sqrt(T)-chunked remat scan, chosen to
    MINIMIZE padding: every padded tick runs the full stage vmap and its
    outputs are discarded, so padding is pure wall-clock waste. Among chunk
    sizes within ±2 of sqrt(T) whose chunk count also stays O(sqrt(T)) the
    smallest padding wins (ties to the size nearest sqrt(T)); padding is
    zero whenever T factors as chunk x n_chunks inside those bounds, and
    never exceeds the naive ceil(sqrt(T)) chunking's."""
    root = int(np.ceil(np.sqrt(n_ticks)))
    best = None
    for chunk in range(max(2, root - 2), root + 3):
        n_chunks = int(np.ceil(n_ticks / chunk))
        # both factors stay O(sqrt(T)) — chunk bounds the recompute span,
        # n_chunks the edge carries — and a single chunk (no outer scan)
        # would hold every inner carry during its backward
        if n_chunks < 2 or n_chunks > root + 2:
            continue
        padding = n_chunks * chunk - n_ticks
        rank = (padding, abs(chunk - root))
        if best is None or rank < best[0]:
            best = (rank, chunk, n_chunks)
    if best is None:  # unreachable for n_ticks >= 4; keep the naive split
        chunk = root
        return chunk, int(np.ceil(n_ticks / chunk))
    return best[1], best[2]


def _leading(tree: Any) -> Optional[int]:
    leaves = jax.tree.leaves(tree)
    if not leaves:
        return None
    return leaves[0].shape[0] if leaves[0].ndim > 0 else None
