"""ParallelModule: layer-spec assembly + the jitted train/eval step.

The reference's ParallelModule interprets a precomputed 1F1B instruction
list per step, moving micro-batches through buffers and NCCL P2P
(reference: src/scaling/core/nn/parallel_module/parallel_module.py:89-747).
Under single-controller SPMD the entire train step — grad accumulation over
micro-batches, forward/backward, optimizer update, ZeRO collectives — is ONE
jitted program: the instruction loop becomes a ``lax.scan`` over stacked
micro-batches and XLA schedules the communication. Pipeline parallelism
(pp > 1) runs the layer stack through the pipelined executor in
``pipeline.py`` (collective-permute over the ``pipe`` axis) inside the same
step function.

Weight tying (reference: tied_layer_index.py:74-224) becomes structural:
tied attributes live once in the owner layer's params; consumer layers get
them injected at call time, so gradients flow to a single array and no
tied-grad all-reduce exists.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Callable, Dict, List, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from typing import TYPE_CHECKING

from ..nn.base_layer import (
    BaseLayer,
    ForwardContext,
    LayerSpec,
    PipelineBodySpec,
    TiedLayerSpec,
)
from ..nn.param import ParamMeta, named_parameters, tree_with_layer
from ..topology import ActivationCheckpointingType, Topology
from ..topology.topology import MODEL_AXIS, PIPE_AXIS


def remat_policy(ckpt_type: ActivationCheckpointingType):
    """jax.checkpoint policy for a checkpointing mode (None = save nothing,
    recompute everything inside the checkpointed region)."""
    if ckpt_type == ActivationCheckpointingType.EVERY_LAYER_SAVE_DOTS:
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    return None
from .pipeline import PipelinedBody

if TYPE_CHECKING:  # break the optimizer <-> parallel import cycle
    from ..optimizer.optimizer import Optimizer
from .sharding import shard_batch


class TrainStepOutput(NamedTuple):
    loss: Any
    metrics: Dict[str, Any]
    global_grad_norm: Optional[Any] = None
    learning_rates: Optional[Dict[str, Any]] = None
    overflow: Optional[Any] = None
    no_overflow_steps: Optional[Any] = None
    current_loss_scale: Optional[Any] = None
    debug_dict: Optional[Dict[str, Any]] = None
    step_duration: Optional[float] = None
    # False on steps where trainer.log_interval skipped the device->host
    # sync: numeric fields are still-in-flight jax arrays, not floats, and
    # the logging path must not touch them (that would reintroduce the sync)
    fetched: bool = True


class EvaluationStepOutput(NamedTuple):
    loss: Any
    metrics: Dict[str, Any]
    step_duration: Optional[float] = None


def _lift_edge_meta_over_pipe(meta: ParamMeta) -> ParamMeta:
    """Shard edge-layer model-parallel dims over (pipe, model) when pp > 1.

    Layers outside the pipelined body (embedding, lm head) would otherwise
    be replicated on every pipe stage — at a 7B/128k-vocab scale that wastes
    several GB of params + fp32 master/moments per stage. The reference
    instead places these on the first/last stage (partitioned_module.py);
    spatially, splitting their vocab dim across the pipe axis is the
    equivalent memory footprint, and GSPMD inserts the pipe-axis collectives.
    """
    if not getattr(meta, "is_model_parallel", False):
        return meta
    dim = meta.model_parallel_dimension or 0
    spec = list(meta.partition_spec)
    if dim >= len(spec) or spec[dim] != MODEL_AXIS:
        return meta
    spec[dim] = (PIPE_AXIS, MODEL_AXIS)
    return ParamMeta(**{**meta.__dict__, "partition_spec": tuple(spec)})


def _get_path(tree: dict, path: str):
    node = tree
    for part in path.split("."):
        node = node[part]
    return node


def _set_path(tree: dict, path: str, value) -> dict:
    parts = path.split(".")
    tree = dict(tree)
    node = tree
    for part in parts[:-1]:
        node[part] = dict(node[part])
        node = node[part]
    node[parts[-1]] = value
    return tree


def _del_path(tree: dict, path: str) -> dict:
    parts = path.split(".")
    tree = dict(tree)
    node = tree
    for part in parts[:-1]:
        node[part] = dict(node[part])
        node = node[part]
    del node[parts[-1]]
    return tree


@dataclass
class TiedInfo:
    key: str
    owner_layer: int
    attributes: List[str]
    consumers: List[int]


class ParallelModule:
    """Assembles a LayerSpec list into params/metas trees + step functions."""

    def __init__(
        self,
        layer_specs: List[LayerSpec],
        topology: Optional[Topology] = None,
        compute_dtype=jnp.float32,
    ):
        self.layer_specs = layer_specs
        self.topology = topology
        self.compute_dtype = compute_dtype
        # body specs expand to PipelinedBody executors; logical layer indices
        # count through them so checkpoints name each inner layer like the
        # per-layer assembly would (reference: partitioned_module.py:249-257)
        self.layers: List[Any] = []
        self._logical_start: List[int] = []
        logical = 0
        for spec in layer_specs:
            self._logical_start.append(logical)
            if isinstance(spec, PipelineBodySpec):
                self.layers.append(
                    PipelinedBody(spec.initialize(), spec.num_layers, topology)
                )
                logical += spec.num_layers
            else:
                self.layers.append(spec.initialize())
                logical += 1
        self.num_logical_layers = logical
        self._has_spatial_pp = any(
            isinstance(l, PipelinedBody) and l.pp > 1 for l in self.layers
        )

        # tied-weight bookkeeping
        self.tied: Dict[str, TiedInfo] = {}
        for i, spec in enumerate(layer_specs):
            if isinstance(spec, TiedLayerSpec):
                if spec.key not in self.tied:
                    self.tied[spec.key] = TiedInfo(
                        key=spec.key, owner_layer=i,
                        attributes=spec.tied_weight_attributes, consumers=[],
                    )
                else:
                    assert self.tied[spec.key].attributes == spec.tied_weight_attributes
                    self.tied[spec.key].consumers.append(i)

    # ----------------------------------------------------------- params
    def layer_name(self, i: int) -> str:
        return f"layer_{self._logical_start[i]}"

    def _layer_class_name(self, i: int) -> str:
        layer = self.layers[i]
        if isinstance(layer, PipelinedBody):
            return type(layer.template).__name__
        return type(layer).__name__

    def init_params(self, key: jax.Array) -> dict:
        params = {}
        for i, layer in enumerate(self.layers):
            params[self.layer_name(i)] = layer.init(jax.random.fold_in(key, i))
        # drop tied attrs from consumers; owner holds the single copy
        for info in self.tied.values():
            for c in info.consumers:
                for attr in info.attributes:
                    params[self.layer_name(c)] = _del_path(params[self.layer_name(c)], attr)
        return params

    def param_metas(self) -> dict:
        metas = {}
        pp = self.topology.pipe_parallel_size if self.topology else 1
        for i, layer in enumerate(self.layers):
            m = layer.param_metas()
            if pp > 1 and not isinstance(layer, PipelinedBody):
                m = jax.tree.map(
                    _lift_edge_meta_over_pipe, m,
                    is_leaf=lambda x: isinstance(x, ParamMeta),
                )
            m = tree_with_layer(m, self._logical_start[i], self._layer_class_name(i))
            metas[self.layer_name(i)] = m
        for info in self.tied.values():
            owner_name = self.layer_name(info.owner_layer)
            for attr in info.attributes:
                meta = _get_path(metas[owner_name], attr)
                metas[owner_name] = _set_path(
                    metas[owner_name], attr,
                    type(meta)(**{**meta.__dict__, "tied_key": info.key}),
                )
            for c in info.consumers:
                for attr in info.attributes:
                    metas[self.layer_name(c)] = _del_path(metas[self.layer_name(c)], attr)
        return metas

    def named_parameters(self, params: dict) -> list:
        return named_parameters(params, self.param_metas())

    # ------------------------------------------------- checkpoint views
    # Stage-stacked body params are unstacked into per-logical-layer trees
    # before hitting disk, so checkpoint files are identical no matter the
    # pipe_parallel_size they were written under (the reference gets the
    # same property from merged layer files, partitioned_module.py:197-257).
    def ckpt_view(self, tree: dict) -> dict:
        view: dict = {}
        for i, layer in enumerate(self.layers):
            name = self.layer_name(i)
            sub = tree[name]
            if isinstance(layer, PipelinedBody):
                start = self._logical_start[i]
                L = layer.num_layers

                def to_layer_major(x, _layer=layer, _L=L):
                    # empty (0,) leaves are frozen-param placeholders in
                    # optimizer-state trees: not stacked, pass through
                    if not x.size:
                        return x
                    if _layer.vpp > 1:
                        # (pp, v, lpv, ...): stage s's virtual index r is
                        # the round-robin chunk r*pp + s — undo via
                        # (v, pp, lpv) flattening
                        x = jnp.moveaxis(x, 0, 1)
                        return x.reshape(_L, *x.shape[3:])
                    return x.reshape(_L, *x.shape[2:])

                flat = jax.tree.map(to_layer_major, sub)
                for j in range(L):
                    view[f"layer_{start + j}"] = jax.tree.map(
                        lambda x, _j=j: x[_j] if x.size else x, flat
                    )
            else:
                view[name] = sub
        return view

    def ckpt_unview(self, view: dict, like: dict) -> dict:
        """Inverse of ckpt_view; ``like`` supplies sharding/placement."""
        out: dict = {}
        for i, layer in enumerate(self.layers):
            name = self.layer_name(i)
            if isinstance(layer, PipelinedBody):
                start = self._logical_start[i]
                L, pp = layer.num_layers, max(layer.pp, 1)
                vpp = max(layer.vpp, 1)
                per_layer = [view[f"layer_{start + j}"] for j in range(L)]

                def restack(old, *xs, _vpp=vpp):
                    if old.size == 0:  # frozen-param placeholder
                        return old
                    new = jnp.stack(xs, axis=0)
                    if _vpp > 1:
                        # layer-major -> (v, pp, lpv, ...) -> interleaved
                        # (pp, v, lpv, ...) chunk layout (chunk r*pp + s
                        # lives at stage s, virtual index r)
                        new = jnp.moveaxis(
                            new.reshape(_vpp, pp, L // (pp * _vpp), *xs[0].shape),
                            0, 1,
                        )
                    else:
                        new = new.reshape(pp, L // pp, *xs[0].shape)
                    return (
                        jax.device_put(new, old.sharding)
                        if hasattr(old, "sharding")
                        else new
                    )

                out[name] = jax.tree.map(restack, like[name], *per_layer)
            else:
                out[name] = view[name]
        return out

    def ckpt_metas(self) -> dict:
        metas: dict = {}
        for i, layer in enumerate(self.layers):
            name = self.layer_name(i)
            start = self._logical_start[i]
            if isinstance(layer, PipelinedBody):
                template_metas = layer.template.param_metas()
                cls = self._layer_class_name(i)
                for j in range(layer.num_layers):
                    metas[f"layer_{start + j}"] = tree_with_layer(
                        template_metas, start + j, cls
                    )
            else:
                m = tree_with_layer(
                    layer.param_metas(), start, self._layer_class_name(i)
                )
                metas[name] = m
        # mirror the tied-attribute dropping of param_metas()
        for info in self.tied.values():
            owner_name = self.layer_name(info.owner_layer)
            for attr in info.attributes:
                meta = _get_path(metas[owner_name], attr)
                metas[owner_name] = _set_path(
                    metas[owner_name], attr,
                    type(meta)(**{**meta.__dict__, "tied_key": info.key}),
                )
            for c in info.consumers:
                for attr in info.attributes:
                    metas[self.layer_name(c)] = _del_path(metas[self.layer_name(c)], attr)
        return metas

    def parameter_count(self, params: dict) -> int:
        return sum(int(p.size) for p in jax.tree.leaves(params))

    def merge_lora_weights(self, params: dict) -> dict:
        """Fold LoRA deltas into base weights on every layer that has them.

        Backs ``trainer.merge_lora_after_loading_checkpoint`` (reference:
        attention.py:766-797 via trainer config). Stage-stacked pipeline
        bodies are merged per layer via nested vmap over the (pp,
        layers_per_stage) leading dims.
        """
        params = dict(params)
        for i, layer in enumerate(self.layers):
            name = self.layer_name(i)
            if isinstance(layer, PipelinedBody):
                template = layer.template
                if hasattr(template, "merge_lora_weights"):
                    merge = jax.vmap(jax.vmap(template.merge_lora_weights))
                    if layer.vpp > 1:  # extra (v) leading dim to map over
                        merge = jax.vmap(merge)
                    params[name] = merge(params[name])
            elif hasattr(layer, "merge_lora_weights"):
                params[name] = layer.merge_lora_weights(params[name])
        return params

    # ---------------------------------------------------------- forward
    def _layer_params(self, params: dict, i: int) -> dict:
        p = params[self.layer_name(i)]
        for info in self.tied.values():
            if i in info.consumers:
                for attr in info.attributes:
                    owner_p = _get_path(params[self.layer_name(info.owner_layer)], attr)
                    p = _set_path(p, attr, owner_p)
        return p

    def forward(self, params: dict, x: Any, ctx: ForwardContext) -> Any:
        ckpt_type = (
            self.topology.activation_checkpointing_type
            if self.topology is not None
            else ActivationCheckpointingType.DISABLED
        )
        policy = remat_policy(ckpt_type)
        for i, layer in enumerate(self.layers):
            layer_p = self._layer_params(params, i)
            if isinstance(layer, PipelinedBody):
                # the body remats its own stage/layer scans
                x = layer(
                    layer_p, x, ctx, stacked=False,
                    remat=ckpt_type != ActivationCheckpointingType.DISABLED,
                    remat_policy=policy,
                )
            elif ckpt_type in (
                ActivationCheckpointingType.EVERY_LAYER,
                ActivationCheckpointingType.EVERY_LAYER_SAVE_DOTS,
            ):
                x = jax.checkpoint(
                    lambda p, xx, _layer=layer: _layer(p, xx, ctx),
                    policy=policy,
                )(layer_p, x)
            else:
                x = layer(layer_p, x, ctx)
        return x

    def _make_ctx(self, deterministic: bool, dropout_key) -> ForwardContext:
        topo = self.topology
        return ForwardContext(
            dropout_key=dropout_key,
            deterministic=deterministic,
            sequence_parallel=bool(topo and topo.sequence_parallel),
            model_parallel_size=topo.model_parallel_size if topo else 1,
            context_parallel_size=topo.context_parallel_size if topo else 1,
            context_parallel_variant=(
                topo.context_parallel_variant if topo else "ring"
            ),
            mesh=topo.mesh if topo else None,
        )

    # ------------------------------------------------------- train step
    def build_train_step(
        self,
        optimizer: Optimizer,
        loss_function: Callable[[Any, Any], tuple],
        donate: bool = True,
    ) -> Callable:
        """Returns jitted ``step(params, opt_state, micro_batches, dropout_key)``.

        ``micro_batches``: pytree whose leaves are stacked
        (grad_accumulation_steps, dp * micro_batch_size, ...) arrays.
        Output loss/metrics are means over micro batches (reference:
        parallel_module.py:288, optimizer.py:99-105).
        """
        gas = self.topology.gradient_accumulation_steps if self.topology else 1

        scaler_enabled = optimizer.config.loss_scaler.enable

        if self._has_spatial_pp:
            return self._build_spatial_train_step(optimizer, loss_function, donate)

        def microbatch_loss(params, mb, dropout_key, loss_scale):
            # PEFT: frozen leaves produce constant-zero grads, so XLA drops
            # their weight-grad matmuls and DP syncs (optimizer.py)
            params = optimizer.freeze_frozen_params(params)
            ctx = self._make_ctx(deterministic=False, dropout_key=dropout_key)
            out = self.forward(params, mb, ctx)
            loss, metrics = loss_function(out, mb)
            scaled = loss.astype(jnp.float32) / gas
            if scaler_enabled:
                scaled = scaled * loss_scale
            return scaled, (loss, metrics)

        def step(params, opt_state, micro_batches, dropout_key):
            loss_scale = opt_state.loss_scaler.current_scale

            grad_fn = jax.value_and_grad(microbatch_loss, has_aux=True)

            def body(carry, mb_and_idx):
                grads_acc, loss_acc, metrics_acc = carry
                mb, idx = mb_and_idx
                mb_key = jax.random.fold_in(dropout_key, idx)
                (_, (loss, metrics)), grads = grad_fn(params, mb, mb_key, loss_scale)
                grads_acc = jax.tree.map(jnp.add, grads_acc, grads)
                loss_acc = loss_acc + loss.astype(jnp.float32)
                metrics_acc = jax.tree.map(
                    lambda a, b: a + jnp.asarray(b, jnp.float32), metrics_acc, metrics
                )
                return (grads_acc, loss_acc, metrics_acc), None

            zero_grads = jax.tree.map(lambda p: jnp.zeros_like(p, dtype=jnp.float32), params)
            first_mb = jax.tree.map(lambda x: x[0], micro_batches)
            # learn the metrics structure without burning flops
            metrics0 = jax.eval_shape(
                lambda p, mb, k, s: microbatch_loss(p, mb, k, s)[1][1],
                params,
                first_mb,
                dropout_key,
                loss_scale,
            )
            zero_metrics = jax.tree.map(lambda m: jnp.zeros((), jnp.float32), metrics0)

            if gas == 1:
                (grads, loss_sum, metrics_sum), _ = body(
                    (zero_grads, jnp.float32(0), zero_metrics),
                    (first_mb, jnp.int32(0)),
                )
            else:
                idxs = jnp.arange(gas)
                (grads, loss_sum, metrics_sum), _ = jax.lax.scan(
                    body, (zero_grads, jnp.float32(0), zero_metrics), (micro_batches, idxs)
                )

            new_params, new_opt_state, opt_out = optimizer.step(
                params, grads, opt_state, compute_dtype=self.compute_dtype
            )
            loss = loss_sum / gas
            metrics = jax.tree.map(lambda m: m / gas, metrics_sum)
            return new_params, new_opt_state, loss, metrics, opt_out

        return jax.jit(step, donate_argnums=(0, 1) if donate else ())

    def _build_spatial_train_step(
        self, optimizer, loss_function: Callable, donate: bool
    ) -> Callable:
        """Train step for pipe_parallel_size > 1: all micro-batches flow
        through the stage-stacked body at once (spatial GPipe); edge layers
        and the loss run per micro-batch under vmap/scan. Gradients come
        from ONE backward over the whole pipelined program — XLA schedules
        the collective-permutes, matching the reference's 1F1B+grad-accum
        semantics (reference: pipeline_schedule/train.py:33-174) without the
        instruction interpreter.
        """
        topo = self.topology
        gas = topo.gradient_accumulation_steps
        scaler_enabled = optimizer.config.loss_scaler.enable
        remat = (
            topo.activation_checkpointing_type != ActivationCheckpointingType.DISABLED
        )
        policy = remat_policy(topo.activation_checkpointing_type)
        body_ids = [
            i for i, l in enumerate(self.layers) if isinstance(l, PipelinedBody)
        ]
        if len(body_ids) != 1:
            raise NotImplementedError(
                f"spatial pipelining expects exactly one PipelineBodySpec, got {len(body_ids)}"
            )
        body_idx = body_ids[0]
        pre_ids = list(range(body_idx))
        post_ids = list(range(body_idx + 1, len(self.layers)))

        def spatial_loss(params, micro_batches, dropout_key, loss_scale):
            params = optimizer.freeze_frozen_params(params)
            mb_keys = jax.vmap(
                lambda m: jax.random.fold_in(dropout_key, m)
            )(jnp.arange(gas))

            def run_pre(mb, k):
                ctx = self._make_ctx(deterministic=False, dropout_key=k)
                x = mb
                for i in pre_ids:
                    x = self.layers[i](self._layer_params(params, i), x, ctx)
                return x

            xs = jax.vmap(run_pre)(micro_batches, mb_keys)

            body_ctx = self._make_ctx(
                deterministic=False,
                dropout_key=jax.random.fold_in(dropout_key, 0x0B0D),
            )
            xs = self.layers[body_idx](
                self._layer_params(params, body_idx), xs, body_ctx, remat=remat,
                remat_policy=policy,
            )

            def run_post(x, mb, k):
                ctx = self._make_ctx(
                    deterministic=False, dropout_key=jax.random.fold_in(k, 1)
                )
                for i in post_ids:
                    x = self.layers[i](self._layer_params(params, i), x, ctx)
                loss, metrics = loss_function(x, mb)
                return (
                    loss.astype(jnp.float32),
                    jax.tree.map(lambda v: jnp.asarray(v, jnp.float32), metrics),
                )

            # scan (not vmap) over micro-batches + remat: only one
            # micro-batch worth of vocab-sized logits is ever live
            run_post_ck = jax.checkpoint(run_post, policy=policy)

            def post_scan(_, inp):
                x, mb, k = inp
                return None, run_post_ck(x, mb, k)

            _, (losses, metrics) = jax.lax.scan(
                post_scan, None, (xs, micro_batches, mb_keys)
            )
            loss = losses.mean()
            metrics = jax.tree.map(lambda v: v.mean(axis=0), metrics)
            scaled = loss * loss_scale if scaler_enabled else loss
            return scaled, (loss, metrics)

        def step(params, opt_state, micro_batches, dropout_key):
            loss_scale = opt_state.loss_scaler.current_scale
            (_, (loss, metrics)), grads = jax.value_and_grad(
                spatial_loss, has_aux=True
            )(params, micro_batches, dropout_key, loss_scale)
            new_params, new_opt_state, opt_out = optimizer.step(
                params, grads, opt_state, compute_dtype=self.compute_dtype
            )
            return new_params, new_opt_state, loss, metrics, opt_out

        return jax.jit(step, donate_argnums=(0, 1) if donate else ())

    def build_eval_step(self, loss_function: Callable) -> Callable:
        def eval_step(params, micro_batch):
            ctx = self._make_ctx(deterministic=True, dropout_key=None)
            out = self.forward(params, micro_batch, ctx)
            loss, metrics = loss_function(out, micro_batch)
            return loss, metrics

        return jax.jit(eval_step)

    # ------------------------------------------------ inference forward
    def build_forward(self, deterministic: bool = True) -> Callable:
        def fwd(params, x):
            ctx = self._make_ctx(deterministic=deterministic, dropout_key=None)
            return self.forward(params, x, ctx)

        return jax.jit(fwd)

    def shard_params(self, params: dict, fsdp_data_axis: bool = False) -> dict:
        """Place params on the mesh according to their metas.

        ``fsdp_data_axis`` (ZeRO stage 3) additionally shards every param
        over the data axis on its last free divisible dim — GSPMD inserts
        the per-use all-gather in forward/backward and the transposed
        reduce-scatter for the grads, so per-device parameter memory drops
        by ~dp while the step math is unchanged."""
        if self.topology is None:
            return params
        from jax.sharding import NamedSharding, PartitionSpec as P

        from .sharding import spec_with_data_axis

        metas = self.param_metas()
        dp = self.topology.data_parallel_size if fsdp_data_axis else 1

        def place(p, m):
            spec = m.partition_spec
            if fsdp_data_axis:
                spec = spec_with_data_axis(spec, p.shape, dp)
            return jax.device_put(p, NamedSharding(self.topology.mesh, P(*spec)))

        return jax.tree.map(
            place, params, metas, is_leaf=lambda x: isinstance(x, ParamMeta)
        )

    def shard_batch(self, batch: Any, stacked: bool = True) -> Any:
        """Place a batch on the mesh: the batch axis shards over ``data``.

        ``stacked=True`` for train batches with a leading grad-accum axis
        (gas, dp*mbs, ...); False for single micro batches (dp*mbs, ...).

        Multi-host: every process passes the same full global batch (the
        loader stream is a pure function of seed + consumed samples, so
        identical on all hosts) and each host materializes only the slices
        its own devices hold — the JAX equivalent of the reference's
        broadcast_data + DP-strided loader split (broadcast_data.py:103,
        dataloader.py:69-80).
        """
        if self.topology is None:
            return batch
        from jax.sharding import NamedSharding, PartitionSpec as P

        # batch dims shard over data; the sequence dim (first after batch)
        # shards over the context axis for ring attention (no-op at cp=1)
        lead = (None, "data", "context") if stacked else ("data", "context")
        multiprocess = jax.process_count() > 1
        batch_axis = 1 if stacked else 0
        global_batch = (
            self.topology.micro_batch_size * self.topology.data_parallel_size
        )

        def put(x):
            if not hasattr(x, "ndim") or x.ndim < len(lead) - 1:
                return x
            spec = lead[: x.ndim] + (None,) * (x.ndim - len(lead))
            sharding = NamedSharding(self.topology.mesh, P(*spec))
            if multiprocess:
                # every host must pass the same FULL global batch: a
                # per-rank slice has a locally-consistent shape too, so
                # without this guard each host would silently train on
                # different data under one "global" array
                if x.ndim > batch_axis and x.shape[batch_axis] != global_batch:
                    raise ValueError(
                        f"multi-host shard_batch needs the full global batch "
                        f"(dim {batch_axis} == micro_batch_size * dp = "
                        f"{global_batch}), got shape {x.shape}; do not feed "
                        "per-dp_rank slices here"
                    )
                # device_put cannot target non-addressable devices; the
                # callback is invoked only for this host's shard indices
                x_np = np.asarray(x)
                return jax.make_array_from_callback(
                    x_np.shape, sharding, lambda idx: x_np[idx]
                )
            return jax.device_put(x, sharding)

        return jax.tree.map(put, batch)
