from .parallel_module import EvaluationStepOutput, ParallelModule, TrainStepOutput
from .pipeline import (
    PipelinedBody,
    pipe_partition_balanced,
    pipe_partition_from_indices,
    pipe_partition_uniform,
)
from .pipeline_schedule import (
    PipelineScheduleFillDrain,
    PipelineScheduleInference,
    PipelineScheduleInterleaved,
    PipelineScheduleTokenSlice,
    PipelineScheduleTrain,
    SimulationEngine,
    visualize,
)
from .sharding import (
    constrain,
    shard_activation_replicated_h,
    shard_activation_sp,
    shard_activation_tp,
    shard_batch,
    shard_param,
)

__all__ = [
    "EvaluationStepOutput",
    "ParallelModule",
    "TrainStepOutput",
    "PipelinedBody",
    "pipe_partition_balanced",
    "pipe_partition_from_indices",
    "pipe_partition_uniform",
    "PipelineScheduleFillDrain",
    "PipelineScheduleInference",
    "PipelineScheduleInterleaved",
    "PipelineScheduleTokenSlice",
    "PipelineScheduleTrain",
    "SimulationEngine",
    "visualize",
    "constrain",
    "shard_activation_replicated_h",
    "shard_activation_sp",
    "shard_activation_tp",
    "shard_batch",
    "shard_param",
]
