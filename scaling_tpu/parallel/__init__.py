from .sharding import (
    constrain,
    shard_activation_replicated_h,
    shard_activation_sp,
    shard_activation_tp,
    shard_batch,
    shard_param,
)

__all__ = [
    "constrain",
    "shard_activation_replicated_h",
    "shard_activation_sp",
    "shard_activation_tp",
    "shard_batch",
    "shard_param",
]
