"""Learning rate schedules.

Semantics identical to the reference (reference:
src/scaling/core/optimizer/learning_rate_scheduler/learning_rate_scheduler.py:18-48,
per https://openreview.net/pdf?id=BJYwwY9ll p.4): linear warmup to the base
LR, then constant / linear / cosine decay to ``learning_rate_minimum`` at
``learning_rate_decay_iters``, flat minimum afterwards.

``get_lr`` accepts either a Python int (host-side logging) or a traced jnp
scalar (inside the jitted train step) — all branching is ``jnp.where``.
"""

from __future__ import annotations

from enum import Enum

import jax.numpy as jnp
from pydantic import Field

from ..config import BaseConfig


class LearningRateDecayStyle(Enum):
    CONSTANT = "constant"
    LINEAR = "linear"
    COSINE = "cosine"


class LearningRateSchedulerConfig(BaseConfig):
    learning_rate: float = Field(
        0.0, description="Base learning rate; this is also the maximum learning rate."
    )
    learning_rate_minimum: float = Field(
        0.0,
        description="Minimum learning rate below which a step's learning rate will "
        "never drop. This is the final learning rate after the schedule has been applied.",
    )
    learning_rate_decay_style: LearningRateDecayStyle = Field(
        LearningRateDecayStyle.COSINE,
        description="Shape of the learning rate decay after warm up",
    )
    learning_rate_decay_iters: int = Field(
        0,
        description="Number of iterations within which the learning rate follows the "
        "schedule. Warmup iterations are included.",
    )
    learning_rate_warmup_steps: int = Field(
        0,
        description="Number of warmup steps during which the learning rate is linearly "
        "increased to the maximum learning rate.",
    )


class LearningRateScheduler:
    def __init__(self, config: LearningRateSchedulerConfig):
        self.config = config

    def get_lr(self, step_index):
        c = self.config
        step = jnp.asarray(step_index, dtype=jnp.float32)

        warmup_lr = c.learning_rate * step / max(float(c.learning_rate_warmup_steps), 1.0)

        if c.learning_rate_decay_style == LearningRateDecayStyle.CONSTANT:
            post_warmup = jnp.asarray(c.learning_rate, dtype=jnp.float32)
        else:
            decay_span = max(float(c.learning_rate_decay_iters - c.learning_rate_warmup_steps), 1.0)
            decay_ratio = jnp.clip(
                (step - c.learning_rate_warmup_steps) / decay_span, 0.0, 1.0
            )
            if c.learning_rate_decay_style == LearningRateDecayStyle.LINEAR:
                coeff = 1.0 - decay_ratio
            else:  # cosine
                coeff = 0.5 * (jnp.cos(jnp.pi * decay_ratio) + 1.0)
            delta = c.learning_rate - c.learning_rate_minimum
            post_warmup = c.learning_rate_minimum + coeff * delta
            post_warmup = jnp.where(
                step > c.learning_rate_decay_iters,
                c.learning_rate_minimum,
                post_warmup,
            )

        in_warmup = (c.learning_rate_warmup_steps > 0) & (step <= c.learning_rate_warmup_steps)
        return jnp.where(in_warmup, warmup_lr, post_warmup)
