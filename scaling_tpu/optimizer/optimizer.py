"""Mixed-precision AdamW with ZeRO-1 state sharding.

Capability parity with the reference's optimizer stack
(reference: src/scaling/core/optimizer/optimizer.py:37-734,
parameter_group.py:81-667): AdamW (torch semantics incl. bias correction and
decoupled weight decay), fp32 master weights with low-precision compute
params, per-group weight decay + LR schedules (separate embedding LR),
global-grad-norm clipping, dynamic loss scaling with overflow step-skip.

TPU-native re-design: the whole step is one pure function inside jit. The
reference's ZeRO-1 machinery — NCCL-aligned flat buffers, DP partitions,
grad copy prequel, all-gather sequel (parameter_group.py:26-472) — is
replaced by sharding the fp32 master + moment trees over the ``data`` mesh
axis with ``NamedSharding``; XLA inserts the reduce-scatter/all-gather pair
around the (sharded) update. Overflow skip uses ``jnp.where`` on the whole
state instead of aborting the step.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from pydantic import Field, model_validator

from ..config import BaseConfig
from ..nn.param import ParamMeta
from ..topology.topology import DATA_AXIS, Topology
from .learning_rate_scheduler import LearningRateScheduler, LearningRateSchedulerConfig
from .loss_scaler import (
    LossScaler,
    LossScalerConfig,
    LossScalerState,
    has_inf_or_nan_tree,
)


class OptimizerConfig(BaseConfig):
    beta1: float = Field(
        0.9,
        description="First coefficient used for computing running averages of "
        "gradient and its square",
    )
    beta2: float = Field(
        0.95,
        description="Second coefficient used for computing running averages of "
        "gradient and its square",
    )
    eps: float = Field(
        1e-8,
        description="term added to the denominator to improve numerical stability",
    )
    gradient_clipping: float = Field(
        0.0, description="clip global l2 grads to this value, deactivate if 0.0"
    )
    allreduce_bucket_size: int = Field(
        500000000,
        description="number of floating points to allreduce in one go "
        "(kept for config parity; XLA schedules collectives itself)",
    )
    loss_scaler: LossScalerConfig = Field(
        LossScalerConfig(), description="Configuration of the loss scaler"
    )
    zero: bool = Field(
        False,
        description="enable zero stage 1: shard fp32 master weights and moments "
        "over the data axis",
    )
    zero_stage: int = Field(
        1,
        description="with zero enabled: 1 shards only optimizer state "
        "(reference surface); 3 additionally shards the COMPUTE params over "
        "the data axis (FSDP — beyond the reference), with GSPMD inserting "
        "the per-use all-gather and the grad reduce-scatter. Stage 2 is "
        "implicit in SPMD (grads never materialize unsharded) and is "
        "rejected.",
        ge=1,
        le=3,
    )
    zero_save_static: bool = Field(
        False,
        description="kept for config parity (reference optimizer_config.py:36): "
        "checkpoints here always save per-layer unsharded arrays, so there is "
        "no merge step to skip",
    )
    debug_log: bool = Field(False, description="per-parameter grad/weight norms")

    @model_validator(mode="after")
    def _validate_zero_stage(self):
        if self.zero_stage == 2:
            raise ValueError(
                "zero_stage 2 is implicit under GSPMD (gradients are "
                "reduce-scattered, never materialized unsharded); use 1 or 3"
            )
        if self.zero_stage != 1 and not self.zero:
            raise ValueError(
                f"zero_stage {self.zero_stage} requires zero: true — "
                "without it the stage setting would silently no-op"
            )
        return self


AdamWOptimizerConfig = OptimizerConfig  # reference alias


class OptimizerParamGroup:
    """Named parameter subset with its own weight decay and LR schedule.

    Membership is by ``ParamMeta.key``; ``parameters`` may be a sub-tree
    mask produced by the model's ``get_parameter_groups``.
    """

    def __init__(
        self,
        keys: set[str],
        weight_decay: float = 0.0,
        learning_rate_scheduler: Optional[LearningRateSchedulerConfig] = None,
        name: str = "param_group",
        lr_scale: float = 1.0,
    ):
        self.keys = set(keys)
        self.weight_decay = weight_decay
        self.lr_config = learning_rate_scheduler or LearningRateSchedulerConfig()
        self.scheduler = LearningRateScheduler(self.lr_config)
        self.name = name
        # constant multiplier on the scheduled LR; muP width scaling rides
        # here (models/transformer/model.py get_parameter_groups)
        self.lr_scale = lr_scale


class OptimizerState(NamedTuple):
    step: jax.Array  # i32, number of completed optimizer steps
    master: Any  # fp32 master params pytree
    exp_avg: Any
    exp_avg_sq: Any
    loss_scaler: LossScalerState


class OptimizerStepOutput(NamedTuple):
    global_grad_norm: Optional[jax.Array] = None
    global_grad_norm_clipped: Optional[jax.Array] = None
    learning_rates: Optional[dict] = None
    overflow: Optional[jax.Array] = None
    no_overflow_steps: Optional[jax.Array] = None
    current_loss_scale: Optional[jax.Array] = None
    debug_dict: Optional[dict] = None


class Optimizer:
    """AdamW over (params, metas) trees, grouped by ParamMeta.key."""

    def __init__(
        self,
        config: OptimizerConfig,
        parameter_groups: list[OptimizerParamGroup],
        metas: Any,
        topology: Optional[Topology] = None,
    ):
        self.config = config
        self.parameter_groups = parameter_groups
        self.metas = metas
        self.topology = topology
        self.loss_scaler = LossScaler(config.loss_scaler)

        # leaf -> group index (-1 = frozen / not optimized)
        meta_leaves = jax.tree.leaves(
            metas, is_leaf=lambda x: isinstance(x, ParamMeta)
        )
        self._group_index: list[int] = []
        claimed: set[str] = set()
        for m in meta_leaves:
            gi = -1
            for i, g in enumerate(parameter_groups):
                if m.key in g.keys:
                    gi = i
                    claimed.add(m.key)
                    break
            self._group_index.append(gi)
        all_keys = {k for g in parameter_groups for k in g.keys}
        missing = all_keys - claimed
        if missing:
            raise ValueError(f"parameter group keys not found in model: {sorted(missing)[:5]}")
        self._meta_leaves = meta_leaves
        self._treedef = jax.tree.structure(
            metas, is_leaf=lambda x: isinstance(x, ParamMeta)
        )

    # --------------------------------------------------------------- state
    def _master_sharding(self, meta: ParamMeta, shape: tuple):
        """ZeRO: additionally shard the master/moments over the data axis
        (the rule shared with stage-3 param sharding — aligned placements
        mean the master->param cast needs no resharding)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..parallel.sharding import spec_with_data_axis

        if self.topology is None:
            return None
        spec = meta.partition_spec
        if self.config.zero:
            spec = spec_with_data_axis(
                spec, shape, self.topology.data_parallel_size
            )
        return NamedSharding(self.topology.mesh, P(*spec))

    def abstract_state(self, params: Any) -> OptimizerState:
        """``init_state``'s output as ShapeDtypeStructs with the ZeRO
        master shardings attached.

        ``jax.eval_shape(init_state, ...)`` drops shardings, which would
        let an AOT compile place the fp32 masters replicated — hiding
        exactly the per-chip memory ZeRO-1 exists to shard. This keeps the
        placement so huge layouts (the BASELINE #4 7B at TP×PP×DP) can be
        ``step.lower(...)``-compiled and cost/memory-pinned without
        materializing 12 bytes/param."""
        empty = jax.ShapeDtypeStruct((0,), jnp.float32)
        masters = []
        for p, m, gi in zip(
            jax.tree.leaves(params), self._meta_leaves, self._group_index
        ):
            if gi < 0:
                masters.append(empty)
                continue
            sh = self._master_sharding(m, p.shape)
            masters.append(
                jax.ShapeDtypeStruct(p.shape, jnp.float32, sharding=sh)
                if sh is not None
                else jax.ShapeDtypeStruct(p.shape, jnp.float32)
            )
        tree = jax.tree.unflatten(self._treedef, masters)
        return OptimizerState(
            step=jax.ShapeDtypeStruct((), jnp.int32),
            master=tree,
            exp_avg=tree,
            exp_avg_sq=tree,
            loss_scaler=jax.eval_shape(self.loss_scaler.init_state),
        )

    def init_state(self, params: Any, only=None) -> OptimizerState:
        """Fresh state (fp32 masters from ``params``, zero moments).

        ``only`` (an optional ``ParamMeta -> bool`` predicate) limits real
        allocation to matching leaves; the rest get the same cheap ``(0,)``
        placeholders as frozen params. Callers that graft a fresh SUBTREE
        into loaded state (the pretrained-CLIP splice) use it to avoid
        transiently materializing 12 bytes/param for the whole model."""

        def make_master(p, m, gi):
            # explicit copy: astype is a no-op for fp32 params and the master
            # must not alias the compute params (donation would double-free)
            x = jnp.array(p, dtype=jnp.float32, copy=True)
            sh = self._master_sharding(m, x.shape)
            return jax.device_put(x, sh) if sh is not None else x

        p_leaves = jax.tree.leaves(params)
        masters, avgs, avg_sqs = [], [], []
        # one fresh (0,) buffer per slot: a shared placeholder would be the
        # same buffer donated many times in the jitted step (XLA rejects it)
        empty = lambda: jnp.zeros((0,), dtype=jnp.float32)  # noqa: E731
        for p, m, gi in zip(p_leaves, self._meta_leaves, self._group_index):
            if gi < 0 or (only is not None and not only(m)):
                # frozen (or outside the requested subtree): no fp32 master
                # or moments — a 7B frozen backbone would otherwise burn
                # 12 bytes/param of device memory
                masters.append(empty())
                avgs.append(empty())
                avg_sqs.append(empty())
                continue
            masters.append(make_master(p, m, gi))
            sh = self._master_sharding(m, p.shape)

            def zeros():
                z = jnp.zeros(p.shape, dtype=jnp.float32)
                return jax.device_put(z, sh) if sh is not None else z

            avgs.append(zeros())
            avg_sqs.append(zeros())
        unflatten = lambda ls: jax.tree.unflatten(self._treedef, ls)  # noqa: E731
        return OptimizerState(
            step=jnp.asarray(0, jnp.int32),
            master=unflatten(masters),
            exp_avg=unflatten(avgs),
            exp_avg_sq=unflatten(avg_sqs),
            loss_scaler=self.loss_scaler.init_state(),
        )

    # ---------------------------------------------------------------- step
    def scale_loss(self, loss: jax.Array, state: OptimizerState) -> jax.Array:
        return self.loss_scaler.scale_loss(loss, state.loss_scaler)

    def freeze_frozen_params(self, params: Any) -> Any:
        """stop_gradient every leaf that belongs to no parameter group.

        A PEFT step would otherwise compute, DP-sync and overflow-check
        full model-sized gradients that ``step`` then drops on the floor:
        the frozen weight-grad matmuls stay live because
        ``has_inf_or_nan_tree`` consumes every grad leaf, and GSPMD's
        gradient psum over the data axis rides along with them (measured
        at TP=2 × DP=4: LoRA's collective bytes *exceeded* full
        finetuning's). With frozen leaves stopped inside the loss, their
        gradients are constant zeros and XLA deletes the matmuls and
        collectives outright — backward cost scales with the adapters,
        which is the point of BASELINE #5's PEFT layout.

        Deliberate loss-scaling consequence: under fp16 dynamic scaling,
        a non-finite value confined to a FROZEN leaf's gradient no longer
        trips ``has_inf_or_nan_tree`` (the leaf's grad is now a constant
        zero rather than inf/nan), so it causes neither a skipped step nor
        a scale backoff. That is correct — those gradients were discarded
        anyway, and an overflow that only a dropped tensor would have seen
        should not perturb the training of the live adapters. Covered by
        ``test_frozen_leaf_overflow_invisible_to_scaler``."""
        if all(gi >= 0 for gi in self._group_index):
            return params
        leaves, td = jax.tree.flatten(params)
        return jax.tree.unflatten(
            td,
            [
                leaf if gi >= 0 else jax.lax.stop_gradient(leaf)
                for leaf, gi in zip(leaves, self._group_index)
            ],
        )

    def step(
        self,
        params: Any,
        grads: Any,
        state: OptimizerState,
        compute_dtype=None,
    ) -> tuple[Any, OptimizerState, OptimizerStepOutput]:
        c = self.config
        g_leaves = jax.tree.leaves(grads)
        p_leaves = jax.tree.leaves(params)
        m_leaves = jax.tree.leaves(state.master)
        a_leaves = jax.tree.leaves(state.exp_avg)
        s_leaves = jax.tree.leaves(state.exp_avg_sq)

        # ---- overflow check on the raw (scaled) grads. The step-skip only
        # applies under dynamic loss scaling (reference semantics: without a
        # scaler a non-finite grad propagates loudly instead of freezing the
        # run); the raw flag is always surfaced in the output.
        raw_overflow = has_inf_or_nan_tree(grads)
        overflow = raw_overflow if c.loss_scaler.enable else jnp.asarray(False)
        scaler_state, scaler_out = self.loss_scaler.step(state.loss_scaler, overflow)

        # ---- unscale
        inv_scale = jnp.where(
            jnp.asarray(c.loss_scaler.enable),
            1.0 / state.loss_scaler.current_scale,
            1.0,
        ).astype(jnp.float32)
        g32 = [g.astype(jnp.float32) * inv_scale for g in g_leaves]

        # ---- global grad norm over optimized leaves
        sq = [
            jnp.sum(jnp.square(g))
            for g, gi in zip(g32, self._group_index)
            if gi >= 0
        ]
        global_norm = jnp.sqrt(jnp.sum(jnp.stack(sq))) if sq else jnp.asarray(0.0)
        if c.gradient_clipping > 0.0:
            clip_coeff = jnp.minimum(
                1.0, c.gradient_clipping / (global_norm + 1e-6)
            )
            g32 = [g * clip_coeff for g in g32]
            clipped_norm = jnp.minimum(global_norm, c.gradient_clipping)
        else:
            clipped_norm = global_norm

        # ---- per-group learning rates at step+1 (reference steps then logs)
        step_index = state.step + 1
        group_lrs = [
            g.scheduler.get_lr(step_index) * g.lr_scale
            for g in self.parameter_groups
        ]

        beta1, beta2 = c.beta1, c.beta2
        t = step_index.astype(jnp.float32)
        bc1 = 1.0 - beta1**t
        bc2 = 1.0 - beta2**t

        new_p, new_m, new_a, new_s = [], [], [], []
        for p, g, master, avg, avg_sq, gi in zip(
            p_leaves, g32, m_leaves, a_leaves, s_leaves, self._group_index
        ):
            if gi < 0:  # frozen
                new_p.append(p)
                new_m.append(master)
                new_a.append(avg)
                new_s.append(avg_sq)
                continue
            lr = group_lrs[gi].astype(jnp.float32)
            # decoupled decay uses lr*wd, so an lr_scale (muP width rule)
            # would silently rescale regularization too; dividing wd by the
            # scale keeps lr*wd — the decay actually applied — exactly as
            # tuned at the base width ("independent weight decay")
            grp = self.parameter_groups[gi]
            wd = grp.weight_decay / grp.lr_scale
            m2 = master * (1.0 - lr * wd) if wd else master
            a2 = beta1 * avg + (1.0 - beta1) * g
            s2 = beta2 * avg_sq + (1.0 - beta2) * jnp.square(g)
            denom = jnp.sqrt(s2) / jnp.sqrt(bc2) + c.eps
            m2 = m2 - (lr / bc1) * a2 / denom
            # overflow => keep everything unchanged (step skip)
            m2 = jnp.where(overflow, master, m2)
            a2 = jnp.where(overflow, avg, a2)
            s2 = jnp.where(overflow, avg_sq, s2)
            new_m.append(m2)
            new_a.append(a2)
            new_s.append(s2)
            new_p.append(m2.astype(compute_dtype or p.dtype))

        unflatten = lambda ls: jax.tree.unflatten(jax.tree.structure(params), ls)  # noqa: E731
        new_state = OptimizerState(
            step=jnp.where(overflow, state.step, state.step + 1),
            master=unflatten(new_m),
            exp_avg=unflatten(new_a),
            exp_avg_sq=unflatten(new_s),
            loss_scaler=scaler_state,
        )
        debug = None
        if c.debug_log:
            debug = {
                m.key: jnp.sqrt(jnp.sum(jnp.square(g)))
                for m, g in zip(self._meta_leaves, g32)
            }
        output = OptimizerStepOutput(
            global_grad_norm=global_norm,
            global_grad_norm_clipped=clipped_norm,
            learning_rates={
                g.name: lr for g, lr in zip(self.parameter_groups, group_lrs)
            },
            overflow=scaler_out.overflow if c.loss_scaler.enable else raw_overflow,
            no_overflow_steps=scaler_out.no_overflow_steps if c.loss_scaler.enable else None,
            current_loss_scale=scaler_out.current_loss_scale if c.loss_scaler.enable else None,
            debug_dict=debug,
        )
        return unflatten(new_p), new_state, output
