from .learning_rate_scheduler import (
    LearningRateDecayStyle,
    LearningRateScheduler,
    LearningRateSchedulerConfig,
)
from .loss_scaler import (
    LossScaler,
    LossScalerConfig,
    LossScalerOutput,
    LossScalerState,
    has_inf_or_nan_tree,
)
from .optimizer import (
    AdamWOptimizerConfig,
    Optimizer,
    OptimizerConfig,
    OptimizerParamGroup,
    OptimizerState,
    OptimizerStepOutput,
)

__all__ = [
    "LearningRateDecayStyle",
    "LearningRateScheduler",
    "LearningRateSchedulerConfig",
    "LossScaler",
    "LossScalerConfig",
    "LossScalerOutput",
    "LossScalerState",
    "has_inf_or_nan_tree",
    "AdamWOptimizerConfig",
    "Optimizer",
    "OptimizerConfig",
    "OptimizerParamGroup",
    "OptimizerState",
    "OptimizerStepOutput",
]
