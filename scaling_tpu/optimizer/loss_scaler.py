"""Dynamic fp16 loss scaling, as a functional jit-compatible state machine.

Semantics identical to the reference
(reference: src/scaling/core/optimizer/loss_scaler.py:50-132): ride the edge
of the highest non-overflowing scale — on overflow burn a hysteresis credit
or back off by ``factor`` (floored at ``min_scale``); after ``window``
consecutive clean steps scale back up by ``factor``. The reference's
global MAX-allreduce overflow check becomes a plain jnp reduction (grads are
globally visible under SPMD).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from pydantic import Field

from ..config import BaseConfig


class LossScalerConfig(BaseConfig):
    enable: bool = Field(False, description="")
    initial_scale: float = Field(2.0**32, description="Initial loss scale")
    window: int = Field(1000, description="")
    hysteresis: float = Field(2, description="")
    consecutive_hysteresis: bool = Field(False, description="")
    min_scale: float = Field(1.0, description="")
    factor: float = Field(2.0, description="")


class LossScalerState(NamedTuple):
    current_scale: jax.Array  # f32 scalar
    current_hysteresis: jax.Array  # f32 scalar
    no_overflow_steps: jax.Array  # i32 scalar


class LossScalerOutput(NamedTuple):
    overflow: jax.Array  # bool scalar
    no_overflow_steps: jax.Array
    current_loss_scale: jax.Array


def has_inf_or_nan_tree(grads) -> jax.Array:
    leaves = jax.tree.leaves(grads)
    flags = [~jnp.isfinite(g.astype(jnp.float32)).all() for g in leaves]
    return jnp.any(jnp.stack(flags)) if flags else jnp.asarray(False)


class LossScaler:
    def __init__(self, config: LossScalerConfig):
        self.config = config

    def init_state(self) -> LossScalerState:
        return LossScalerState(
            current_scale=jnp.asarray(self.config.initial_scale, jnp.float32),
            current_hysteresis=jnp.asarray(self.config.hysteresis, jnp.float32),
            no_overflow_steps=jnp.asarray(0, jnp.int32),
        )

    def scale_loss(self, loss: jax.Array, state: LossScalerState) -> jax.Array:
        if not self.config.enable:
            return loss
        return loss * state.current_scale.astype(loss.dtype)

    def step(
        self, state: LossScalerState, overflow: jax.Array
    ) -> tuple[LossScalerState, LossScalerOutput]:
        c = self.config
        if not c.enable:
            out = LossScalerOutput(
                overflow=jnp.asarray(False),
                no_overflow_steps=state.no_overflow_steps,
                current_loss_scale=state.current_scale,
            )
            return state, out

        # ---- overflow branch
        burn_credit = (c.hysteresis != 1) & (state.current_hysteresis > 1)
        scale_on_overflow = jnp.where(
            burn_credit,
            state.current_scale,
            jnp.maximum(state.current_scale / c.factor, c.min_scale),
        )
        hyst_on_overflow = jnp.where(
            burn_credit, state.current_hysteresis - 1, state.current_hysteresis
        )

        # ---- clean branch
        window_hit = (state.no_overflow_steps > 0) & (
            state.no_overflow_steps % c.window == 0
        )
        scale_on_clean = jnp.where(
            window_hit, state.current_scale * c.factor, state.current_scale
        )
        hyst_on_clean = jnp.where(
            jnp.asarray(c.consecutive_hysteresis) | window_hit,
            jnp.asarray(float(c.hysteresis), jnp.float32),
            state.current_hysteresis,
        )

        new_state = LossScalerState(
            current_scale=jnp.where(overflow, scale_on_overflow, scale_on_clean),
            current_hysteresis=jnp.where(overflow, hyst_on_overflow, hyst_on_clean),
            no_overflow_steps=jnp.where(
                overflow, jnp.asarray(0, jnp.int32), state.no_overflow_steps + 1
            ),
        )
        out = LossScalerOutput(
            overflow=overflow,
            no_overflow_steps=new_state.no_overflow_steps,
            current_loss_scale=new_state.current_scale,
        )
        return new_state, out
