"""Transformer model assembly unit tests (reference:
tests/transformer/test_training.py model-shape coverage + test_nn parity)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scaling_tpu.models.transformer import (
    TransformerConfig,
    get_transformer_layer_specs,
    init_model,
    init_optimizer,
    loss_function,
)
from scaling_tpu.models.transformer.layers import (
    EmbeddingInput,
    LayerNormWrapper,
    TransformerLayer,
    TransformerLMHead,
    TransformerLMHeadTied,
)
from scaling_tpu.topology import Topology


def make_config(
    vocab_size=128,
    hidden_size=32,
    num_layers=2,
    num_attention_heads=4,
    sequence_length=16,
    mp=1,
    dp=1,
    mbs=2,
    gas=1,
    **arch_overrides,
):
    return TransformerConfig.from_dict(
        {
            "topology": {
                "model_parallel_size": mp,
                "pipe_parallel_size": 1,
                "data_parallel_size": dp,
                "micro_batch_size": mbs,
                "gradient_accumulation_steps": gas,
            },
            "transformer_architecture": {
                "vocab_size": vocab_size,
                "hidden_size": hidden_size,
                "num_layers": num_layers,
                "num_attention_heads": num_attention_heads,
                "sequence_length": sequence_length,
                **arch_overrides,
            },
            "trainer": {"train_iterations": 5, "assert_checkpoint_loaded": False},
            "learning_rate_scheduler": {
                "learning_rate": 0.01,
                "learning_rate_decay_style": "constant",
            },
            "logger": {"log_dir": None},
        }
    )


def make_batch(rng, vocab_size=128, b=2, s=16, stacked_gas=None):
    tokens = rng.integers(1, vocab_size, size=(b, s + 1))
    batch = {
        "token_ids": tokens[:, :-1].astype(np.int32),
        "target_token_ids": tokens[:, 1:].astype(np.int32),
        "position_ids": np.tile(np.arange(s, dtype=np.int32), (b, 1)),
        "segment_ids": np.zeros((b, s), np.int32),
        "loss_weights": np.ones((b, s), np.float32),
    }
    if stacked_gas:
        batch = {k: np.stack([v] * stacked_gas) for k, v in batch.items()}
    return batch


def test_layer_specs_assembly():
    config = make_config()
    specs = get_transformer_layer_specs(config.transformer_architecture)
    classes = [s.module_class for s in specs]
    assert classes[0] is EmbeddingInput
    assert classes[1] is TransformerLayer and classes[2] is TransformerLayer
    assert classes[3] is LayerNormWrapper
    assert classes[4] is TransformerLMHead
    assert len(specs) == 5


def test_weight_tying_shares_one_array():
    config = make_config(weight_tying=True)
    specs = get_transformer_layer_specs(config.transformer_architecture)
    assert specs[-1].module_class is TransformerLMHeadTied
    module = init_model(config, None)
    params = module.init_params(jax.random.PRNGKey(0))
    # consumer's tied param dropped from the tree: only one copy exists
    assert "weight" not in params[module.layer_name(len(specs) - 1)].get("embedding", {})
    n_total = module.parameter_count(params)
    config_untied = make_config(weight_tying=False)
    untied = init_model(config_untied, None)
    n_untied = untied.parameter_count(untied.init_params(jax.random.PRNGKey(0)))
    arch = config.transformer_architecture
    assert n_untied - n_total == arch.vocab_size * arch.hidden_size


@pytest.mark.parametrize(
    "arch",
    [
        {},
        {"weight_tying": True},
        {"mlp_type": "swiglu", "mlp_factor": 2.0, "norm_type": "rms"},
        {"attention_num_kv_heads": 2, "attention_qkv_in_one": False},
        {"num_local_attention_heads": 2, "local_attention_window_size": 4},
        {"key_query_norm": True},
        {"relative_position_embedding_type": "rotary_complex"},
        {"precision": "bfloat16"},
    ],
    ids=[
        "default",
        "tied",
        "swiglu_rms",
        "gqa",
        "local_attention",
        "kq_norm",
        "rotary_complex",
        "bf16",
    ],
)
def test_train_loss_decreases(arch):
    config = make_config(**arch)
    topo = Topology(config.topology)
    module = init_model(config, topo)
    params = module.init_params(jax.random.PRNGKey(0))
    if config.transformer_architecture.precision.value == "bfloat16":
        params = jax.tree.map(
            lambda p: p.astype(jnp.bfloat16)
            if jnp.issubdtype(p.dtype, jnp.floating) else p,
            params,
        )
    optimizer = init_optimizer(config, module, topo)
    state = optimizer.init_state(params)
    step = module.build_train_step(optimizer, loss_function)
    rng = np.random.default_rng(0)
    batch = make_batch(rng, stacked_gas=1)
    losses = []
    for i in range(8):
        params, state, loss, metrics, _ = step(params, state, batch, jax.random.PRNGKey(i))
        losses.append(float(loss))
    assert losses[-1] < losses[0] - 0.5, losses
    assert 0.0 <= float(metrics["accuracy"]) <= 1.0


def test_tensor_parallel_matches_single_device():
    """mp=4 and mp=1 must produce the same loss from the same init
    (reference: tests/core/test_nn/test_parallel_linear.py pattern)."""
    losses = {}
    for mp in (1, 4):
        config = make_config(mp=mp)
        topo = Topology(config.topology)
        module = init_model(config, topo)
        params = module.init_params(jax.random.PRNGKey(7))
        params = module.shard_params(params)
        optimizer = init_optimizer(config, module, topo)
        state = optimizer.init_state(params)
        step = module.build_train_step(optimizer, loss_function)
        rng = np.random.default_rng(3)
        batch = module.shard_batch(make_batch(rng, stacked_gas=1))
        run = []
        for i in range(3):
            params, state, loss, _, _ = step(params, state, batch, jax.random.PRNGKey(i))
            run.append(float(loss))
        losses[mp] = run
    np.testing.assert_allclose(losses[1], losses[4], rtol=2e-4)


def test_gqa_kv_head_count():
    config = make_config(attention_num_kv_heads=2, attention_qkv_in_one=False)
    module = init_model(config, None)
    params = module.init_params(jax.random.PRNGKey(0))
    layer1 = params["layer_1"]["attention"]
    arch = config.transformer_architecture
    head_dim = arch.hidden_size // arch.num_attention_heads
    assert layer1["key"]["weight"].shape == (arch.hidden_size, 2 * head_dim)
    assert layer1["query"]["weight"].shape == (arch.hidden_size, arch.hidden_size)


def test_packed_sequences_respect_segments():
    """Tokens in segment B must not attend to segment A: replacing segment
    A's content must not change segment B's logits."""
    config = make_config(num_layers=1, dropout_embedding=0.0)
    module = init_model(config, None)
    params = module.init_params(jax.random.PRNGKey(0))
    fwd = module.build_forward()

    s = 16
    rng = np.random.default_rng(0)
    tokens = rng.integers(1, 128, size=(1, s)).astype(np.int32)
    segment_ids = np.concatenate([np.zeros((1, 8)), np.ones((1, 8))], axis=1).astype(np.int32)
    position_ids = np.concatenate([np.arange(8), np.arange(8)])[None].astype(np.int32)
    base = {
        "token_ids": tokens,
        "target_token_ids": tokens,
        "position_ids": position_ids,
        "segment_ids": segment_ids,
        "loss_weights": np.ones((1, s), np.float32),
    }
    out1 = fwd(params, base)["activations"]
    tokens2 = tokens.copy()
    tokens2[0, :8] = rng.integers(1, 128, size=8)
    out2 = fwd(params, {**base, "token_ids": tokens2})["activations"]
    np.testing.assert_allclose(
        np.asarray(out1[0, 8:]), np.asarray(out2[0, 8:]), rtol=1e-5, atol=1e-5
    )
    assert not np.allclose(np.asarray(out1[0, :8]), np.asarray(out2[0, :8]))


def test_config_round_trip():
    """model_dump -> from_dict round-trip: the runner payload path re-parses
    a dumped config (reference: runner.py:199-203, launch_config.py:60-72)."""
    from scaling_tpu.models.transformer import TransformerConfig

    cfg = TransformerConfig.from_dict(
        {
            "topology": {
                "model_parallel_size": 1, "pipe_parallel_size": 1,
                "data_parallel_size": 1, "micro_batch_size": 2,
                "gradient_accumulation_steps": 1,
            },
            "transformer_architecture": {
                "vocab_size": 96, "hidden_size": 32, "num_layers": 2,
                "num_attention_heads": 4, "sequence_length": 24,
            },
        }
    )
    cfg2 = TransformerConfig.from_dict(cfg.model_dump(mode="json"))
    assert cfg2.topology.world_size == cfg.topology.world_size
    assert cfg2.transformer_architecture.hidden_size == 32
