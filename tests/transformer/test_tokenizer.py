"""Tokenizer wrapper: eos detection, pair loading, default fallback
(reference: src/scaling/transformer/tokenizer/tokenizer.py)."""

import json

import pytest

from scaling_tpu.models.transformer.tokenizer import Tokenizer, load_tokenizers


def test_default_tokenizer_round_trips():
    tok = Tokenizer.default()
    ids = tok.encode("hello TPU world")
    assert tok.decode(ids) == "hello TPU world"
    assert tok.eos_token_id is not None
    assert len(tok) == tok.vocab_size == 257


def test_from_str_matches_from_file(tmp_path):
    tok = Tokenizer.default()
    serialized = tok.tokenizer.to_str()
    again = Tokenizer.from_str(serialized)
    assert again.encode("abc") == tok.encode("abc")
    assert again.eos_token_id == tok.eos_token_id


def test_eos_detection_variants(tmp_path):
    from tokenizers import Tokenizer as HFTokenizer
    from tokenizers.models import WordLevel

    vocab = {"</s>": 0, "<unk>": 1, "x": 2}
    tok = HFTokenizer(WordLevel(vocab, unk_token="<unk>"))
    path = tmp_path / "v.json"
    tok.save(str(path))
    wrapped = Tokenizer.from_file(path)
    assert wrapped.eos_token == "</s>"
    assert wrapped.eos_token_id == 0


def test_pair_loader_strips_prefix_space(tmp_path):
    """Metaspace tokenizers get the no-prefix-space variant for chat
    concatenation (reference: tokenizer.py:64-103)."""
    from tokenizers import Tokenizer as HFTokenizer
    from tokenizers.models import WordLevel
    from tokenizers.pre_tokenizers import Metaspace

    vocab = {"▁hi": 0, "hi": 1, "<unk>": 2, "<|endoftext|>": 3}
    tok = HFTokenizer(WordLevel(vocab, unk_token="<unk>"))
    tok.pre_tokenizer = Metaspace()
    path = tmp_path / "m.json"
    tok.save(str(path))

    normal, no_prefix = load_tokenizers(path)
    assert normal.encode("hi") == [0]  # leading metaspace applied
    assert no_prefix.encode("hi") == [1]  # mid-sentence continuation form


def test_from_file_names_the_expected_format(tmp_path):
    """A bare vocab map must fail with a message naming the file and the
    expected tokenizer.json format, not the rust parser's bare
    'expected `,` or `}`'."""
    import json

    import pytest

    from scaling_tpu.models.transformer.tokenizer import Tokenizer

    bad = tmp_path / "vocab.json"
    bad.write_text(json.dumps({"a": 1, "b": 2}))
    with pytest.raises(ValueError, match="tokenizer.json format"):
        Tokenizer.from_file(bad)
