"""The committed golden checkpoint must keep loading and reproducing its
recorded training trajectory (reference:
tests/transformer/test_backwards_compatibility.py + committed
files/backward_compatibility_checkpoint/).

If this fails after an intentional format change, regenerate via
``python tests/transformer/files/generate_backward_compatibility_checkpoint.py``
and say so in the commit message; if the change was unintentional, the
format broke — fix the code, not the fixture.
"""

import json
from pathlib import Path

import numpy as np

from .test_training import build_capturing_trainer, make_config, train_capture

FILES = Path(__file__).parent / "files" / "backward_compatibility_checkpoint"


import pytest


@pytest.mark.parametrize(
    "ckpt_dir,backend,truth_key",
    [("ckpt", "npz", "resumed_losses"),
     ("orbax_ckpt", "orbax", "orbax_resumed_losses")],
)
def test_golden_checkpoint_resumes_exactly(devices, ckpt_dir, backend, truth_key):
    """Every on-disk format gets its own pin (reference discipline: one
    golden artifact per format): the committed fixture must keep loading
    and reproducing its recorded post-resume losses."""
    truth = json.loads((FILES / "ground_truth.json").read_text())
    config = make_config(
        FILES, FILES / "data", train_iterations=5, save_interval=100,
        load_dir=FILES / ckpt_dir,
    )
    d = config.model_dump(mode="json")
    d["trainer"]["save_dir"] = None
    d["trainer"]["checkpoint_backend"] = backend
    d["trainer"]["assert_checkpoint_loaded"] = True
    config = type(config).from_dict(d)
    trainer = build_capturing_trainer(config, load=True)
    losses = train_capture(trainer, 2)
    np.testing.assert_allclose(
        np.asarray(losses, np.float32),
        np.asarray(truth[truth_key], np.float32),
        rtol=1e-4,
        err_msg=f"the committed {backend} checkpoint no longer reproduces "
        "its recorded post-resume losses — the on-disk format or training "
        "math changed",
    )


def test_orbax_golden_checkpoint_files_present():
    step = FILES / "orbax_ckpt" / "global_step3"
    assert (step / "orbax" / "model" / "_METADATA").is_file()
    assert (step / "orbax" / "model" / "_CHECKPOINT_METADATA").is_file()
    assert (step / "orbax" / "optimizer" / "_METADATA").is_file()
    assert (step / "context.json").is_file()
    assert (step / "config.yml").is_file()


def test_golden_checkpoint_files_present():
    step = FILES / "ckpt" / "global_step3"
    names = sorted(p.name for p in step.iterdir())
    # the exact artifact family is part of the pinned format
    assert "context.json" in names
    assert "optimizer_state.json" in names
    assert "config.yml" in names
    assert sum(n.startswith("model_state_layer_") for n in names) == 5
    assert sum(n.startswith("optimizer_state_layer_") for n in names) == 5


def test_old_checkpoint_config_with_removed_keys_loads(tmp_path):
    """Checkpoints written by earlier releases carry config keys that no
    longer exist (umup, embedding_dataset); from_checkpoint must strip them
    instead of refusing the checkpoint (extra='forbid')."""
    import shutil

    import yaml

    from scaling_tpu.models.transformer.inference import TransformerInferenceModule

    src = FILES / "ckpt"
    dst = tmp_path / "ckpt"
    shutil.copytree(src, dst)
    step = dst / "global_step3"
    cfg = yaml.safe_load((step / "config.yml").read_text())
    cfg["transformer_architecture"]["umup"] = {"enable": False}
    cfg["data"]["embedding_dataset"] = False
    cfg["data"]["embedding_dataset_memory_map"] = False
    (step / "config.yml").write_text(yaml.safe_dump(cfg))

    module = TransformerInferenceModule.from_checkpoint(dst)
    logits = module.logits([3, 7, 11])
    assert logits.shape[1] == 3
