"""Context-parallel (ring attention) training: cp=2 loss parity with cp=1
from identical weights (same pattern as the pipeline parity test)."""

from pathlib import Path

import numpy as np
import pytest

from scaling_tpu.data.memory_map import MemoryMapDatasetBuilder

from .test_training import build_capturing_trainer, make_config, train_capture


@pytest.fixture(scope="module")
def data_prefix(tmp_path_factory):
    prefix = tmp_path_factory.mktemp("dataset") / "data"
    rng = np.random.default_rng(53)
    with MemoryMapDatasetBuilder(prefix, dtype=np.uint16) as builder:
        for _ in range(64):
            doc = rng.integers(1, 96, size=rng.integers(8, 64))
            builder.add(np.append(doc, 0).astype(np.uint16))
    return prefix


def cp_config(tmp_path, data_prefix, cp, load_dir=None, variant="ring"):
    cfg = make_config(tmp_path, data_prefix, train_iterations=5, save_interval=100,
                      load_dir=load_dir)
    d = cfg.model_dump(mode="json")
    d["topology"]["context_parallel_size"] = cp
    d["topology"]["context_parallel_variant"] = variant
    d["topology"]["world_size"] = cp
    return type(cfg).from_dict(d)


@pytest.fixture(scope="module")
def cp1_baseline(tmp_path_factory, data_prefix):
    """Variant-independent half of the parity test, computed once: a seed
    checkpoint plus the cp=1 losses trained from it (cp=1 never reaches
    the variant branch)."""
    tmp = tmp_path_factory.mktemp("cp_base")
    seed_cfg = make_config(tmp / "seed", data_prefix, train_iterations=1,
                           save_interval=100)
    t0 = build_capturing_trainer(seed_cfg)
    t0.save_checkpoint()
    seed_dir = Path(seed_cfg.trainer.save_dir)
    cfg = cp_config(tmp / "cp1", data_prefix, 1, load_dir=seed_dir)
    losses = train_capture(build_capturing_trainer(cfg, load=True), 5)
    return seed_dir, losses


@pytest.mark.parametrize("variant", ["ring", "ulysses"])
def test_cp2_loss_matches_cp1(tmp_path, data_prefix, cp1_baseline, variant):
    """Either context-parallel variant must reproduce the cp=1 losses from
    identical weights — the variant changes attention internals only."""
    seed_dir, cp1_losses = cp1_baseline
    cfg = cp_config(tmp_path / "cp2", data_prefix, 2, load_dir=seed_dir,
                    variant=variant)
    cp2_losses = train_capture(build_capturing_trainer(cfg, load=True), 5)
    np.testing.assert_allclose(
        np.asarray(cp1_losses, np.float32), np.asarray(cp2_losses, np.float32),
        rtol=2e-4, atol=2e-4,
    )
