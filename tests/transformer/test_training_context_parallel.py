"""Context-parallel (ring attention) training: cp=2 loss parity with cp=1
from identical weights (same pattern as the pipeline parity test)."""

from pathlib import Path

import numpy as np
import pytest

from scaling_tpu.data.memory_map import MemoryMapDatasetBuilder

from .test_training import build_capturing_trainer, make_config, train_capture


@pytest.fixture(scope="module")
def data_prefix(tmp_path_factory):
    prefix = tmp_path_factory.mktemp("dataset") / "data"
    rng = np.random.default_rng(53)
    with MemoryMapDatasetBuilder(prefix, dtype=np.uint16) as builder:
        for _ in range(64):
            doc = rng.integers(1, 96, size=rng.integers(8, 64))
            builder.add(np.append(doc, 0).astype(np.uint16))
    return prefix


def cp_config(tmp_path, data_prefix, cp, load_dir=None, variant="ring"):
    cfg = make_config(tmp_path, data_prefix, train_iterations=5, save_interval=100,
                      load_dir=load_dir)
    d = cfg.model_dump(mode="json")
    d["topology"]["context_parallel_size"] = cp
    d["topology"]["context_parallel_variant"] = variant
    d["topology"]["world_size"] = cp
    return type(cfg).from_dict(d)


@pytest.mark.parametrize("variant", ["ring", "ulysses"])
def test_cp2_loss_matches_cp1(tmp_path, data_prefix, variant):
    """Either context-parallel variant must reproduce the cp=1 losses from
    identical weights — the variant changes attention internals only."""
    seed_cfg = make_config(tmp_path / "seed", data_prefix, train_iterations=1,
                           save_interval=100)
    t0 = build_capturing_trainer(seed_cfg)
    t0.save_checkpoint()

    losses = {}
    for cp in (1, 2):
        cfg = cp_config(tmp_path / f"cp{cp}", data_prefix, cp,
                        load_dir=Path(seed_cfg.trainer.save_dir), variant=variant)
        t = build_capturing_trainer(cfg, load=True)
        losses[cp] = train_capture(t, 5)
    np.testing.assert_allclose(
        np.asarray(losses[1], np.float32), np.asarray(losses[2], np.float32),
        rtol=2e-4, atol=2e-4,
    )
