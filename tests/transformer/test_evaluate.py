"""Standalone evaluation CLI: deterministic scoring of a saved checkpoint."""

from pathlib import Path

import numpy as np

from scaling_tpu.data.memory_map import MemoryMapDatasetBuilder
from scaling_tpu.models.transformer.evaluate import evaluate

from .test_training import build_capturing_trainer, make_config, train_capture


def test_evaluate_scores_checkpoint(tmp_path):
    prefix = tmp_path / "data"
    rng = np.random.default_rng(41)
    with MemoryMapDatasetBuilder(prefix, dtype=np.uint16) as builder:
        for _ in range(48):
            doc = rng.integers(1, 96, size=rng.integers(8, 48))
            builder.add(np.append(doc, 0).astype(np.uint16))
    cfg = make_config(tmp_path, prefix, train_iterations=3, save_interval=3)
    losses = train_capture(build_capturing_trainer(cfg), 3)
    assert np.isfinite(losses).all()

    ckpt = Path(cfg.trainer.save_dir)
    stats = evaluate(ckpt, prefix, batch_size=4)
    assert stats["tokens"] > 0 and np.isfinite(stats["loss"])
    assert stats["perplexity"] > 1.0
    # deterministic: same inputs, same number (and batch size must not
    # change the aggregate — per-token sums, not per-batch means)
    again = evaluate(ckpt, prefix, batch_size=4)
    assert again == stats
    other_bs = evaluate(ckpt, prefix, batch_size=7)  # trailing partial batch
    np.testing.assert_allclose(other_bs["loss"], stats["loss"], rtol=1e-5)
    assert other_bs["tokens"] == stats["tokens"]

    # max_batches bounds the work
    bounded = evaluate(ckpt, prefix, batch_size=4, max_batches=2)
    assert bounded["batches"] == 2 and bounded["tokens"] < stats["tokens"]


def test_evaluate_legacy_dataset(tmp_path):
    """--legacy-dataset scores Megatron .bin/.idx data through the same
    path (reference: legacy_dataset/indexed_dataset.py)."""
    from scaling_tpu.data.legacy_indexed_dataset import LegacyMMapIndexWriter

    rng = np.random.default_rng(13)
    npz_prefix = tmp_path / "train"
    with MemoryMapDatasetBuilder(npz_prefix, dtype=np.uint16) as builder:
        for _ in range(32):
            builder.add(np.append(rng.integers(1, 96, size=20), 0).astype(np.uint16))
    cfg = make_config(tmp_path, npz_prefix, train_iterations=2, save_interval=2)
    train_capture(build_capturing_trainer(cfg), 2)

    # identical documents in BOTH formats: the legacy reader must produce
    # the exact same evaluation, not merely a finite one
    docs = [np.append(rng.integers(1, 96, size=20), 0).astype(np.uint16)
            for _ in range(16)]
    legacy_prefix = tmp_path / "legacy"
    with LegacyMMapIndexWriter(legacy_prefix, dtype=np.uint16) as w:
        for d in docs:
            w.add(d)
    mmap_prefix = tmp_path / "same_docs"
    with MemoryMapDatasetBuilder(mmap_prefix, dtype=np.uint16) as builder:
        for d in docs:
            builder.add(d)
    ckpt = Path(cfg.trainer.save_dir)
    legacy_stats = evaluate(ckpt, legacy_prefix, batch_size=4, legacy_dataset=True)
    mmap_stats = evaluate(ckpt, mmap_prefix, batch_size=4)
    assert legacy_stats["tokens"] > 0 and np.isfinite(legacy_stats["loss"])
    assert legacy_stats == mmap_stats
