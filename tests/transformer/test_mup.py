"""muP (maximal-update parametrization) coordinate checks.

The property that makes muP real (and the reference's dead ``umup`` knob
was not): the *update* to the network function after an optimizer step is
width-independent, so learning rates tuned at the base width transfer to
any width. Verified here by the standard coordinate check — logit change
after steps at width 4x the base must stay the same order under muP while
standard parametrization grows with width."""

import numpy as np
import pytest

from scaling_tpu.data.memory_map import MemoryMapDatasetBuilder

from .test_training import build_capturing_trainer, make_config, train_capture

BASE, WIDE = 32, 128


@pytest.fixture(scope="module")
def data_prefix(tmp_path_factory):
    prefix = tmp_path_factory.mktemp("mup_data") / "data"
    rng = np.random.default_rng(17)
    with MemoryMapDatasetBuilder(prefix, dtype=np.uint16) as builder:
        for _ in range(48):
            doc = rng.integers(1, 96, size=rng.integers(8, 48))
            builder.add(np.append(doc, 0).astype(np.uint16))
    return prefix


def _config(tmp_path, data_prefix, hidden, mup: bool):
    arch = {
        "hidden_size": hidden,
        "weight_tying": False,
        "norm_type": "rms",
    }
    if mup:
        arch["mup"] = {"base_hidden_size": BASE}
    return make_config(
        tmp_path, data_prefix, train_iterations=3, save_interval=100, **arch
    )


def _logit_update_rms(tmp_path, data_prefix, hidden, mup):
    """RMS of (logits after 3 steps - logits at init) on a fixed batch."""
    import jax
    import jax.numpy as jnp

    cfg = _config(tmp_path, data_prefix, hidden, mup)
    trainer = build_capturing_trainer(cfg)
    probe = {
        "token_ids": jnp.asarray(np.arange(24)[None] % 60 + 1, jnp.int32),
        "position_ids": jnp.asarray(np.arange(24)[None], jnp.int32),
        "segment_ids": jnp.zeros((1, 24), jnp.int32),
    }

    def probe_logits():
        fwd = trainer.module.build_forward(deterministic=True)
        return np.asarray(fwd(trainer.params, probe)["activations"], np.float32)

    before = probe_logits()
    losses = train_capture(trainer, 3)
    assert np.isfinite(losses).all()
    after = probe_logits()
    return float(np.sqrt(np.mean((after - before) ** 2)))


def test_mup_logit_updates_width_independent(tmp_path, data_prefix):
    """Same LR at base and 4x width: muP keeps the logit update the same
    order; standard parametrization's update grows with width. The muP
    width ratio must stay within a constant band AND beat the standard
    ratio (the discriminating comparison)."""
    upd = {}
    for mup in (True, False):
        for hidden in (BASE, WIDE):
            key = ("mup" if mup else "sp", hidden)
            upd[key] = _logit_update_rms(
                tmp_path / f"{key[0]}{hidden}", data_prefix, hidden, mup
            )
    mup_ratio = upd[("mup", WIDE)] / upd[("mup", BASE)]
    sp_ratio = upd[("sp", WIDE)] / upd[("sp", BASE)]
    # muP: width-independent updates (band allows constant-factor noise)
    assert 0.2 < mup_ratio < 3.0, (upd, mup_ratio)
    # and the check must actually discriminate
    assert mup_ratio < sp_ratio, (upd, mup_ratio, sp_ratio)


def test_mup_rules_wired(tmp_path, data_prefix):
    """The three mechanical rules: scaled attention logits, zero-init
    readout with the output multiplier, and 1/m matrix LR scale."""
    import math

    from scaling_tpu.models.transformer.model import (
        get_parameter_groups,
        init_model,
    )

    cfg = _config(tmp_path, data_prefix, WIDE, mup=True)
    arch = cfg.transformer_architecture
    m = arch.mup_width_mult
    assert m == WIDE / BASE

    module = init_model(cfg, topology=None)
    # attention scale: sqrt(base_head_dim)/head_dim
    layer = module.layers[1]
    head_dim = arch.hidden_size // arch.num_attention_heads
    assert math.isclose(
        layer.attention.scaling_factor, math.sqrt(head_dim / m) / head_dim
    )
    # readout zero-init + logits multiplier
    import jax

    params = module.init_params(jax.random.PRNGKey(0))
    head_params = module._layer_params(params, len(module.layers) - 1)
    assert float(np.abs(np.asarray(head_params["linear"]["weight"])).max()) == 0.0
    assert module.layers[-1].logit_mult == 1.0  # output_mult, width-free
    # matrix group LR scaled, vector/embedding groups not
    groups = {g.name: g for g in get_parameter_groups(cfg, module)}
    assert groups["weight_decay_params"].lr_scale == 1.0 / m
    assert groups["no_weight_decay_params"].lr_scale == 1.0


def test_mup_base_head_count_keeps_scale_when_adding_heads(tmp_path, data_prefix):
    """Width grown by adding heads keeps head_dim — and must keep the base
    model's attention scale 1/sqrt(head_dim) exactly."""
    import math

    from scaling_tpu.models.transformer.model import init_model

    cfg = make_config(
        tmp_path, data_prefix,
        hidden_size=WIDE, num_attention_heads=16, weight_tying=False,
        mup={"base_hidden_size": BASE, "base_num_attention_heads": 4},
    )
    module = init_model(cfg, topology=None)
    head_dim = WIDE // 16
    assert head_dim == BASE // 4  # same head_dim at base and wide
    assert math.isclose(
        module.layers[1].attention.scaling_factor, 1.0 / math.sqrt(head_dim)
    )


def test_mup_fixed_width_matrices_keep_base_lr(tmp_path, data_prefix):
    """Adapter up-projections and lora_b have width-independent fan-in:
    under muP they keep the base LR while down/lora_a scale 1/m."""
    from scaling_tpu.models.transformer.model import (
        get_parameter_groups,
        init_model,
    )

    cfg = make_config(
        tmp_path, data_prefix,
        hidden_size=WIDE, weight_tying=False,
        mup={"base_hidden_size": BASE},
        adapter_config={"name": "ad", "attention_downsampling_factor": 0.25},
        lora_config={"name": "lo", "rank": 2, "alpha": 4},
    )
    module = init_model(cfg, topology=None)
    groups = {g.name: g for g in get_parameter_groups(cfg, module)}
    scaled = groups["weight_decay_params"]
    fixed = groups["weight_decay_params_fixed_width"]
    assert scaled.lr_scale == BASE / WIDE and fixed.lr_scale == 1.0
    # decay (lr*wd) stays width-invariant despite the lr scale
    assert scaled.weight_decay == fixed.weight_decay
    assert any(".down" in k for k in scaled.keys)
    assert any(".up" in k for k in fixed.keys)
    # lora matrices are no-decay (reference parity); lora_a's fan-in scales
    # with width, lora_b's is the fixed rank
    nd_scaled = groups["no_weight_decay_params_width_scaled"]
    nd_fixed = groups["no_weight_decay_params"]
    assert nd_scaled.lr_scale == BASE / WIDE and nd_fixed.lr_scale == 1.0
    assert any("lora_a" in k for k in nd_scaled.keys)
    assert any("lora_b" in k for k in nd_fixed.keys)
    assert all("lora" not in k for k in scaled.keys | fixed.keys)


def test_mup_rejects_weight_tying(tmp_path, data_prefix):
    with pytest.raises(Exception, match="weight_tying"):
        make_config(
            tmp_path, data_prefix,
            hidden_size=WIDE, weight_tying=True,
            mup={"base_hidden_size": BASE},
        )
