"""TextDataset packing/collation tests (reference:
tests/transformer/test_data/ coverage)."""

import numpy as np
import pytest

from scaling_tpu.data.memory_map import MemoryMapDatasetBuilder
from scaling_tpu.models.transformer.data import TextDataset
from scaling_tpu.nn.seq_packing import get_position_ids_from_segments, get_segment_ids


@pytest.fixture()
def data_prefix(tmp_path):
    prefix = tmp_path / "data"
    rng = np.random.default_rng(5)
    with MemoryMapDatasetBuilder(prefix, dtype=np.uint16) as builder:
        for _ in range(32):
            doc = rng.integers(1, 200, size=rng.integers(4, 40))
            builder.add(np.append(doc, 0).astype(np.uint16))
    return prefix


def test_packing_covers_stream_without_overlap(data_prefix):
    ds = TextDataset(data_prefix, sequence_length=16, seed=1)
    assert len(ds) > 0
    first = ds[0].token_ids
    second = ds[1].token_ids
    assert first.shape == (17,)
    # consecutive items overlap by exactly one token (input/target shift)
    assert first[-1] == second[0] or True  # windows are L apart, L+1 long
    mm_tokens = np.concatenate([ds.memory_map[i] for i in range(len(ds.memory_map))])
    np.testing.assert_array_equal(first, mm_tokens[:17])
    np.testing.assert_array_equal(second, mm_tokens[16:33])


def test_collate_shapes_and_shift(data_prefix):
    ds = TextDataset(data_prefix, sequence_length=16, seed=1)
    batch = ds.collate([ds[0], ds[1]])
    assert batch.token_ids.shape == (2, 16)
    np.testing.assert_array_equal(batch.token_ids[0][1:], batch.target_token_ids[0][:-1])
    assert batch.segment_ids.dtype == np.int32
    # position ids restart at document boundaries
    eods = np.where(ds[0].token_ids[:-1] == 0)[0]
    if len(eods) > 0:
        first_eod = int(eods[0])
        if first_eod + 1 < 16:
            assert batch.position_ids[0, first_eod + 1] == 0


def test_segment_ids_reset_on_eod():
    tokens = np.array([[5, 6, 0, 7, 8, 0, 9, 3]])
    seg = get_segment_ids(tokens, eod_token=0)
    np.testing.assert_array_equal(seg, [[0, 0, 0, 1, 1, 1, 2, 2]])
    pos = get_position_ids_from_segments(seg)
    np.testing.assert_array_equal(pos, [[0, 1, 2, 0, 1, 2, 0, 1]])


def test_only_full_sequences(data_prefix):
    L = 32
    ds = TextDataset(data_prefix, sequence_length=L, seed=1, only_full_sequences=True)
    sizes = ds.memory_map.sizes().astype(np.int64)
    doc_offsets = np.concatenate([[0], np.cumsum(sizes)])
    mm_tokens = np.concatenate([ds.memory_map[i] for i in range(len(ds.memory_map))])
    for i in range(len(ds)):
        start = int(ds._item_starts[i])
        at_boundary = start == 0 or mm_tokens[start - 1] == 0
        if not at_boundary:
            # mid-doc starts are allowed only when cutting a doc longer
            # than the window, aligned to L from the doc start
            d = int(np.searchsorted(doc_offsets, start, side="right")) - 1
            doc_len = int(sizes[d])
            assert doc_len > L and (start - int(doc_offsets[d])) % L == 0, (
                f"item {i} starts mid-document at {start}"
            )


def test_only_full_sequences_no_leak_or_overlap(data_prefix):
    """A window must not contain the head of a document belonging to the
    next window (truncated partial doc) nor predict any token twice.
    Mid-document cuts overlap by exactly the 1 input/target-shift token."""
    L = 32
    ds = TextDataset(data_prefix, sequence_length=L, seed=1, only_full_sequences=True)
    for i in range(len(ds) - 1):
        start, end = int(ds._item_starts[i]), int(ds._item_ends[i])
        next_start = int(ds._item_starts[i + 1])
        # predicted positions are start+1..end; they must not overlap the
        # next window's predictions (next_start+1..)
        assert end <= next_start + 1, f"windows {i},{i+1} double-predict"
        tokens = ds[i].token_ids
        span = end - start
        # everything past this window's own tokens is EOD padding
        assert (tokens[min(span, L + 1):] == ds.eod_token_id).all()


def test_only_full_sequences_long_doc_windows(tmp_path):
    """Mid-document windows of an over-long doc carry L+1 real tokens —
    no spurious EOD is ever a weighted prediction target mid-document."""
    L = 16
    prefix = tmp_path / "long"
    rng = np.random.default_rng(9)
    with MemoryMapDatasetBuilder(prefix, dtype=np.uint16) as builder:
        builder.add(np.append(rng.integers(1, 200, size=70), 0).astype(np.uint16))
        builder.add(np.append(rng.integers(1, 200, size=5), 0).astype(np.uint16))
    ds = TextDataset(prefix, sequence_length=L, seed=1, only_full_sequences=True,
                     allow_incomplete_sequences_every_n=1)
    mm_tokens = np.concatenate([ds.memory_map[i] for i in range(len(ds.memory_map))])
    for i in range(len(ds)):
        start, end = int(ds._item_starts[i]), int(ds._item_ends[i])
        item = ds[i].token_ids
        np.testing.assert_array_equal(item[: end - start], mm_tokens[start:end])
        if end < len(mm_tokens) and mm_tokens[end - 1] != ds.eod_token_id:
            # mid-document cut: the window must be full L+1 real tokens so
            # collate never sees a padded EOD target with weight 1
            assert end - start == L + 1, (i, start, end)
    # consecutive mid-doc windows overlap by exactly one token
    assert int(ds._item_starts[1]) == int(ds._item_ends[0]) - 1


def test_deterministic_order(data_prefix):
    a = TextDataset(data_prefix, sequence_length=16, seed=3)
    b = TextDataset(data_prefix, sequence_length=16, seed=3)
    np.testing.assert_array_equal(a[4].token_ids, b[4].token_ids)
