"""Sequence parallelism: SP on vs off must produce the same losses
(reference: tests/transformer/test_training_sequence_parallel.py:45-55)."""

import numpy as np
import pytest

from scaling_tpu.data.memory_map import MemoryMapDatasetBuilder

from .test_training import build_capturing_trainer, make_config, train_capture


@pytest.fixture(scope="module")
def data_prefix(tmp_path_factory):
    prefix = tmp_path_factory.mktemp("dataset") / "data"
    rng = np.random.default_rng(41)
    with MemoryMapDatasetBuilder(prefix, dtype=np.uint16) as builder:
        for _ in range(64):
            doc = rng.integers(1, 96, size=rng.integers(8, 64))
            builder.add(np.append(doc, 0).astype(np.uint16))
    return prefix


def sp_config(tmp_path, data_prefix, sequence_parallel):
    cfg = make_config(tmp_path, data_prefix, mp=2, train_iterations=5,
                      save_interval=100)
    d = cfg.model_dump(mode="json")
    d["topology"]["sequence_parallel"] = sequence_parallel
    return type(cfg).from_dict(d)


def test_sequence_parallel_loss_parity(tmp_path, data_prefix):
    losses = {}
    for sp in (False, True):
        cfg = sp_config(tmp_path / f"sp{int(sp)}", data_prefix, sp)
        trainer = build_capturing_trainer(cfg)
        losses[sp] = train_capture(trainer, 5)
    np.testing.assert_allclose(
        np.asarray(losses[False], np.float32),
        np.asarray(losses[True], np.float32),
        rtol=2e-4, atol=2e-4,
    )
