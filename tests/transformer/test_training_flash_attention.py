"""End-to-end training with the flash (splash) attention kernel matches the
XLA kernel's losses (reference: tests/transformer/test_training_flash_attention.py
flash-vs-torch loss parity grid)."""

import numpy as np
import pytest

from scaling_tpu.data.memory_map import MemoryMapDatasetBuilder
from scaling_tpu.ops.flash_attention import force_flash_interpret

from .test_training import build_capturing_trainer, make_config, train_capture


@pytest.fixture(scope="module")
def data_prefix(tmp_path_factory):
    prefix = tmp_path_factory.mktemp("flashdata") / "data"
    rng = np.random.default_rng(31)
    with MemoryMapDatasetBuilder(prefix, dtype=np.uint16) as builder:
        for _ in range(48):
            doc = rng.integers(1, 96, size=rng.integers(16, 120))
            builder.add(np.append(doc, 0).astype(np.uint16))
    return prefix


def _config(tmp_path, data_prefix, kernel):
    # flash needs seq % 128 == 0 and head_dim >= 64
    return make_config(
        tmp_path, data_prefix, train_iterations=6, save_interval=100,
        hidden_size=128, num_attention_heads=2, attention_num_kv_heads=1,
        sequence_length=128, attention_qkv_in_one=False,
        masked_softmax={"kernel": kernel},
    )


def test_flash_training_matches_xla(tmp_path, data_prefix, devices):
    losses = {}
    for kernel in ("torch", "flash_attention"):
        cfg = _config(tmp_path / kernel, data_prefix, kernel)
        with force_flash_interpret():
            trainer = build_capturing_trainer(cfg)
            losses[kernel] = train_capture(trainer, 6)
    np.testing.assert_allclose(
        np.asarray(losses["torch"], np.float32),
        np.asarray(losses["flash_attention"], np.float32),
        rtol=2e-3, atol=2e-3,
    )
    fl = np.asarray(losses["flash_attention"], np.float32)
    assert np.isfinite(fl).all()
    # training makes progress (de-flaked: early steps can tick up briefly)
    assert fl[-2:].mean() < fl[0]
