"""MoE transformer end-to-end: training descends, aux loss reported,
expert-parallel sharding trains on the mesh (beyond the reference —
SURVEY §2.4 lists EP as absent there)."""

import numpy as np
import pytest

from scaling_tpu.data.memory_map import MemoryMapDatasetBuilder

from .test_training import build_capturing_trainer, make_config, train_capture


@pytest.fixture(scope="module")
def data_prefix(tmp_path_factory):
    prefix = tmp_path_factory.mktemp("moedata") / "data"
    rng = np.random.default_rng(41)
    with MemoryMapDatasetBuilder(prefix, dtype=np.uint16) as builder:
        for _ in range(48):
            doc = rng.integers(1, 96, size=rng.integers(8, 64))
            builder.add(np.append(doc, 0).astype(np.uint16))
    return prefix


def moe_config(tmp_path, data_prefix, mp=1, dp=1, **kw):
    return make_config(
        tmp_path, data_prefix, mp=mp, dp=dp, train_iterations=32,
        save_interval=100, mlp_type="moe", mlp_factor=2.0,
        moe_num_experts=4, moe_top_k=2, moe_capacity_factor=2.0,
        moe_aux_loss_coef=0.01, norm_type="rms", mlp_bias=False, **kw,
    )


def test_moe_training_descends(tmp_path, data_prefix, devices):
    trainer = build_capturing_trainer(moe_config(tmp_path, data_prefix))
    metrics = []

    losses = []
    for _ in range(16):
        out = trainer.train_step()
        losses.append(out.loss)
        metrics.append(out.metrics)
    assert np.isfinite(losses).all()
    # routing noise makes single steps jumpy; compare windowed means
    assert np.mean(losses[-4:]) < np.mean(losses[:2])
    # the router balance term is reported and positive
    assert all(m["moe_aux_loss"] > 0 for m in metrics)


def test_moe_expert_parallel_trains(tmp_path, data_prefix, devices):
    """dp=2 x mp=2: experts shard over the data axis, expert ffn over model.
    One step must run and the expert weights must actually be sharded."""
    trainer = build_capturing_trainer(
        moe_config(tmp_path, data_prefix, mp=2, dp=2, gas=2)
    )
    out = trainer.train_step()
    assert np.isfinite(out.loss)
    sharded = 0
    for key, p, meta in trainer.module.named_parameters(trainer.params):
        if key.endswith("w_in") or key.endswith("w_out"):
            assert p.shape[0] == 4  # expert dim
            shard_experts = {s.data.shape[0] for s in p.addressable_shards}
            assert shard_experts == {2}, (key, shard_experts)  # 4 experts / dp 2
            sharded += 1
    assert sharded >= 2


def test_moe_checkpoint_resume_exact(tmp_path, data_prefix, devices):
    """Expert weights and router state checkpoint/resume bit-exactly."""
    cfg = moe_config(tmp_path, data_prefix)
    trainer = build_capturing_trainer(cfg)
    train_capture(trainer, 3)
    trainer.save_checkpoint()
    losses_continued = train_capture(trainer, 3)

    d = cfg.model_dump(mode="json")
    d["trainer"]["load_dir"] = d["trainer"]["save_dir"]
    resumed = build_capturing_trainer(type(cfg).from_dict(d), load=True)
    assert resumed.context.iterations == 3
    losses_resumed = train_capture(resumed, 3)
    np.testing.assert_array_equal(
        np.asarray(losses_continued, np.float32),
        np.asarray(losses_resumed, np.float32),
    )
