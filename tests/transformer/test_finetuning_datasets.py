"""Finetuning datasets: loss masking, padding, chat flags, e2e training
(reference: tests/transformer/test_finetuning*.py coverage)."""

import json

import numpy as np
import pytest

from scaling_tpu.data.memory_map import MemoryMapDatasetBuilder
from scaling_tpu.models.transformer.data.finetuning import (
    FinetuningChatDataset,
    FinetuningTextDataset,
)


@pytest.fixture(scope="module")
def vocab_file(tmp_path_factory):
    """Minimal word-level tokenizer with an <|endoftext|> token."""
    from tokenizers import Tokenizer as HFTokenizer
    from tokenizers.models import WordLevel
    from tokenizers.pre_tokenizers import Whitespace

    words = ["hello", "world", "foo", "bar", "baz", "question", "answer", "the"]
    vocab = {"<|endoftext|>": 0, "<unk>": 1}
    for i, w in enumerate(words):
        vocab[w] = i + 2
    tok = HFTokenizer(WordLevel(vocab, unk_token="<unk>"))
    tok.pre_tokenizer = Whitespace()
    path = tmp_path_factory.mktemp("tok") / "vocab.json"
    tok.save(str(path))
    return path


@pytest.fixture()
def text_jsonl(tmp_path):
    path = tmp_path / "data.jsonl"
    rows = [
        {"prompt": "question foo bar", "completion": "answer baz"},
        {"prompt": "hello", "completion": "world world"},
    ]
    path.write_text("\n".join(json.dumps(r) for r in rows))
    return path


def test_text_loss_masking(vocab_file, text_jsonl):
    ds = FinetuningTextDataset(text_jsonl, sequence_length=12, vocab_file=vocab_file,
                               shuffle=False)
    assert len(ds) == 2
    item = ds[0]
    # prompt "question foo bar" = 3 tokens, completion "answer baz" = 2 + eos
    assert item.token_ids.shape == (12,)
    w = item.loss_weights
    # weights: 0 on first len(prompt)-1 = 2, then 1 on completion+eos = 3
    np.testing.assert_array_equal(w[:2], 0)
    np.testing.assert_array_equal(w[2:5], 1)
    np.testing.assert_array_equal(w[5:], 0)  # padding
    # shifted next-token pairs: target[i] == input[i+1] inside the stream
    np.testing.assert_array_equal(item.target_token_ids[:4], item.token_ids[1:5])


def test_text_truncation_keeps_completion(vocab_file, tmp_path):
    path = tmp_path / "long.jsonl"
    row = {"prompt": " ".join(["the"] * 30), "completion": "answer"}
    path.write_text(json.dumps(row))
    ds = FinetuningTextDataset(path, sequence_length=8, vocab_file=vocab_file)
    item = ds[0]
    assert item.token_ids.shape == (8,)
    # the trained completion token survives truncation
    assert item.loss_weights.sum() >= 1


def test_text_memory_map_variant(vocab_file, tmp_path):
    prefix = tmp_path / "ft"
    with MemoryMapDatasetBuilder(prefix, dtype=np.uint16) as b:
        # record = [len_prompt, prompt..., completion...]
        b.add(np.asarray([3, 5, 6, 7, 8, 9], dtype=np.uint16))
    ds = FinetuningTextDataset(prefix, sequence_length=10, vocab_file=vocab_file,
                               memory_map_dataset=True)
    item = ds[0]
    np.testing.assert_array_equal(item.token_ids[:5], [5, 6, 7, 8, 9])
    np.testing.assert_array_equal(item.loss_weights[:5], [0, 0, 1, 1, 1])


def test_chat_has_loss_flags(vocab_file, tmp_path):
    path = tmp_path / "chat.jsonl"
    convo = [
        {"type": "text", "content": "question foo", "has_loss": False},
        {"type": "text", "content": "answer bar <|endoftext|>", "has_loss": True},
    ]
    path.write_text(json.dumps(convo))
    ds = FinetuningChatDataset(path, sequence_length=10, vocab_file=vocab_file)
    item = ds[0]
    # 2 prompt tokens (no loss) then loss on the answer part
    w = item.loss_weights
    assert w[0] == 0
    assert w[1:4].sum() >= 2  # answer tokens trained


def test_collate_shapes(vocab_file, text_jsonl):
    ds = FinetuningTextDataset(text_jsonl, sequence_length=12, vocab_file=vocab_file)
    batch = ds.collate([ds[0], ds[1]])
    assert batch.token_ids.shape == (2, 12)
    assert batch.loss_weights.dtype == np.float32
    assert (batch.position_ids[:, 0] == 0).all()


def test_finetuning_end_to_end(vocab_file, text_jsonl, tmp_path):
    """Train a few steps through the standard entry with the finetuning flag
    (reference: test_finetuning.py life-cycle)."""
    from scaling_tpu.models.transformer import TransformerConfig
    from scaling_tpu.models.transformer.train import main

    config = TransformerConfig.from_dict(
        {
            "topology": {
                "model_parallel_size": 1, "pipe_parallel_size": 1,
                "data_parallel_size": 1, "micro_batch_size": 2,
                "gradient_accumulation_steps": 1,
            },
            "transformer_architecture": {
                "vocab_size": 16, "hidden_size": 32, "num_layers": 2,
                "num_attention_heads": 4, "sequence_length": 12,
                "vocab_file": str(vocab_file),
            },
            "learning_rate_scheduler": {
                "learning_rate": 0.01, "learning_rate_warmup_steps": 1,
                "learning_rate_decay_iters": 10,
            },
            "trainer": {
                "train_iterations": 3, "seed": 7,
                "save_dir": str(tmp_path / "ckpt"), "save_interval": 3,
            },
            "data": {
                "data_prefixes": [str(text_jsonl)],
                "finetuning_dataset": True,
            },
            "logger": {"log_dir": None},
        }
    )
    trainer = main(config)
    assert trainer.context.iterations == 3


def _write_png(path, rng):
    from PIL import Image

    arr = rng.integers(0, 255, size=(20, 30, 3), dtype=np.uint8)
    Image.fromarray(arr).save(path)


def test_chat_image_entries(vocab_file, tmp_path):
    """Image elements become 144 loss-free placeholder tokens with recorded
    splice locations (reference: finetuning_chat_dataset.py:120-134)."""
    from scaling_tpu.models.transformer.data.finetuning import (
        IMAGE_ENCODER_TOKEN_COUNT,
        IMAGE_SIZE,
    )

    rng = np.random.default_rng(0)
    _write_png(tmp_path / "img.png", rng)
    rows = [
        [{"type": "text", "content": "question foo"},
         {"type": "image", "content": "img.png"},
         {"type": "text", "content": "answer <|endoftext|>", "has_loss": True}],
        [{"type": "text", "content": "hello"},
         {"type": "text", "content": "world <|endoftext|>", "has_loss": True}],
    ]
    path = tmp_path / "chat.jsonl"
    path.write_text("\n".join(json.dumps(r) for r in rows))
    L = IMAGE_ENCODER_TOKEN_COUNT + 8
    ds = FinetuningChatDataset(path, sequence_length=L, vocab_file=vocab_file)

    item = ds[0]
    assert item.images and len(item.images) == 1
    assert item.images[0].shape == (IMAGE_SIZE, IMAGE_SIZE, 3)
    assert item.image_locations == [2]  # after the 2 "question foo" tokens
    # placeholder span carries no loss (weights are target-aligned: the last
    # placeholder position predicts the first has_loss token, so it is 1)
    assert item.loss_weights[1 : 1 + IMAGE_ENCODER_TOKEN_COUNT].sum() == 0

    batch = ds.collate([ds[0], ds[1]])
    assert batch.input_images.shape == (2, 1, IMAGE_SIZE, IMAGE_SIZE, 3)
    assert batch.input_image_mask.tolist() == [[True], [False]]
    model_in = batch.as_model_input()
    assert "input_images" in model_in


def test_chat_truncates_back_keeping_head(vocab_file, tmp_path):
    rows = [[{"type": "text", "content": "hello " * 20, "has_loss": True}]]
    path = tmp_path / "chat.jsonl"
    path.write_text("\n".join(json.dumps(r) for r in rows))
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        ds = FinetuningChatDataset(path, sequence_length=8, vocab_file=vocab_file)
    item = ds[0]
    hello_id = ds.tokenizer.encode("hello")[0]
    # head survives: all 8 positions are the leading "hello" tokens
    assert item.token_ids.tolist() == [hello_id] * 8


def test_chat_softprompt_prefix(vocab_file, tmp_path):
    rows = [[{"type": "text", "content": "hello <|endoftext|>", "has_loss": True}]]
    path = tmp_path / "chat.jsonl"
    path.write_text("\n".join(json.dumps(r) for r in rows))
    ds = FinetuningChatDataset(
        path, sequence_length=10, vocab_file=vocab_file, softprompt_n_tokens=4
    )
    item = ds[0]
    assert item.token_ids[:4].tolist() == [0] * 4
    assert item.loss_weights[:4].sum() == 0  # softprompt positions carry no loss


def test_chat_image_end_to_end_training(vocab_file, tmp_path):
    """Chat data with images trains through the multimodal model: the image
    encoder gets gradients and the masked splice leaves padded slots alone."""
    from scaling_tpu.models.transformer import TransformerConfig
    from .test_training import build_capturing_trainer, train_capture

    rng = np.random.default_rng(1)
    _write_png(tmp_path / "a.png", rng)
    rows = []
    for i in range(4):
        rows.append(
            [{"type": "text", "content": "question foo"},
             {"type": "image", "content": "a.png"},
             {"type": "text", "content": "answer baz <|endoftext|>", "has_loss": True}]
        )
        rows.append(
            [{"type": "text", "content": "hello"},
             {"type": "text", "content": "world <|endoftext|>", "has_loss": True}]
        )
    (tmp_path / "chat.jsonl").write_text("\n".join(json.dumps(r) for r in rows))

    config = TransformerConfig.from_dict(
        {
            "topology": {
                "model_parallel_size": 1, "pipe_parallel_size": 1,
                "data_parallel_size": 1, "micro_batch_size": 2,
                "gradient_accumulation_steps": 1,
            },
            "transformer_architecture": {
                "vocab_size": 16, "hidden_size": 32, "num_layers": 1,
                "num_attention_heads": 2, "sequence_length": 160,
                "vocab_file": str(vocab_file),
                "image_encoder": True, "image_encoder_width": 32,
                "image_encoder_layers": 1, "image_encoder_heads": 2,
            },
            "optimizer": {"gradient_clipping": 1.0},
            "learning_rate_scheduler": {"learning_rate": 0.01,
                                        "learning_rate_warmup_steps": 1,
                                        "learning_rate_decay_iters": 10},
            "trainer": {"train_iterations": 2, "seed": 7,
                        "save_dir": str(tmp_path / "ckpt"), "save_interval": 100},
            "data": {"finetuning_chat_dataset": True,
                     "data_prefixes": [str(tmp_path / "chat.jsonl")]},
            "logger": {"log_dir": None},
        }
    )
    trainer = build_capturing_trainer(config)
    losses = train_capture(trainer, 2)
    assert np.isfinite(losses).all()


def test_legacy_blended_dataset(tmp_path):
    """LegacyBlendedDataset blends Megatron-format datasets with the
    furthest-off-target interleave (reference: legacy_blended_dataset.py)."""
    from scaling_tpu.data.blended_dataset import BlendedDatasetConfig
    from scaling_tpu.data.legacy_indexed_dataset import LegacyMMapIndexWriter
    from scaling_tpu.models.transformer.data import (
        LegacyBlendedDataset,
        TextDataset,
    )

    rng = np.random.default_rng(3)
    prefixes = []
    for name, n_docs in (("a", 12), ("b", 4)):
        prefix = tmp_path / name
        with LegacyMMapIndexWriter(prefix, dtype=np.uint16) as w:
            for _ in range(n_docs):
                w.add(np.append(rng.integers(1, 50, size=24), 0).astype(np.uint16))
        prefixes.append(prefix)

    datasets = [
        TextDataset(p, sequence_length=16, seed=5, legacy_dataset=True)
        for p in prefixes
    ]
    blended = LegacyBlendedDataset(
        seed=5,
        config=BlendedDatasetConfig(
            weight_by_num_documents=True, weighted_sampler_alpha=0.5,
            cache_directory=str(tmp_path / "cache"),
        ),
        datasets=datasets,
    )
    assert len(blended) > 0
    items = [blended[i] for i in range(len(blended))]
    # TextDataset items carry seq_len + 1 tokens (inputs and shifted targets)
    assert all(i.token_ids.shape == (17,) for i in items)
    # deterministic: same seed + cache round-trip gives the same mixture
    blended2 = LegacyBlendedDataset(
        seed=5,
        config=BlendedDatasetConfig(
            weight_by_num_documents=True, weighted_sampler_alpha=0.5,
            cache_directory=str(tmp_path / "cache"),
        ),
        datasets=datasets,
    )
    np.testing.assert_array_equal(blended.dataset_indices, blended2.dataset_indices)


REFERENCE = __import__("pathlib").Path("/root/reference")


@pytest.mark.skipif(not REFERENCE.is_dir(), reason="reference checkout absent")
def test_reference_finetuning_fixtures_load_unchanged():
    """The reference's shipped finetuning fixtures (jsonl, chat jsonl,
    memory map) and its llama2 tokenizer drive our datasets unchanged."""
    files = REFERENCE / "tests/transformer/files"
    vocab = files / "llama2-tokenizer.json"

    ds = FinetuningTextDataset(
        files / "dataset/finetuning.jsonl", sequence_length=32, vocab_file=vocab
    )
    assert len(ds) > 0
    item = ds[0]
    assert item.token_ids.shape == (32,)
    assert item.loss_weights.sum() > 0  # completion carries loss
    # prompt span carries none: first tokens are loss-free
    assert item.loss_weights[0] == 0

    chat = FinetuningChatDataset(
        files / "dataset/finetuning_chat.jsonl", sequence_length=96,
        vocab_file=vocab,
    )
    assert len(chat) > 0
    citem = chat[0]
    w = citem.loss_weights
    assert 0 < w.sum() < w.size  # role masking: some spans train, some don't

    mm = FinetuningTextDataset(
        files / "dataset/finetuning_memory_map/dataset", sequence_length=32,
        vocab_file=vocab, memory_map_dataset=True,
    )
    assert len(mm) > 0
    assert mm[0].token_ids.shape == (32,)
