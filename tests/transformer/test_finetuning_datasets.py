"""Finetuning datasets: loss masking, padding, chat flags, e2e training
(reference: tests/transformer/test_finetuning*.py coverage)."""

import json

import numpy as np
import pytest

from scaling_tpu.data.memory_map import MemoryMapDatasetBuilder
from scaling_tpu.models.transformer.data.finetuning import (
    FinetuningChatDataset,
    FinetuningTextDataset,
)


@pytest.fixture(scope="module")
def vocab_file(tmp_path_factory):
    """Minimal word-level tokenizer with an <|endoftext|> token."""
    from tokenizers import Tokenizer as HFTokenizer
    from tokenizers.models import WordLevel
    from tokenizers.pre_tokenizers import Whitespace

    words = ["hello", "world", "foo", "bar", "baz", "question", "answer", "the"]
    vocab = {"<|endoftext|>": 0, "<unk>": 1}
    for i, w in enumerate(words):
        vocab[w] = i + 2
    tok = HFTokenizer(WordLevel(vocab, unk_token="<unk>"))
    tok.pre_tokenizer = Whitespace()
    path = tmp_path_factory.mktemp("tok") / "vocab.json"
    tok.save(str(path))
    return path


@pytest.fixture()
def text_jsonl(tmp_path):
    path = tmp_path / "data.jsonl"
    rows = [
        {"prompt": "question foo bar", "completion": "answer baz"},
        {"prompt": "hello", "completion": "world world"},
    ]
    path.write_text("\n".join(json.dumps(r) for r in rows))
    return path


def test_text_loss_masking(vocab_file, text_jsonl):
    ds = FinetuningTextDataset(text_jsonl, sequence_length=12, vocab_file=vocab_file,
                               shuffle=False)
    assert len(ds) == 2
    item = ds[0]
    # prompt "question foo bar" = 3 tokens, completion "answer baz" = 2 + eos
    assert item.token_ids.shape == (12,)
    w = item.loss_weights
    # weights: 0 on first len(prompt)-1 = 2, then 1 on completion+eos = 3
    np.testing.assert_array_equal(w[:2], 0)
    np.testing.assert_array_equal(w[2:5], 1)
    np.testing.assert_array_equal(w[5:], 0)  # padding
    # shifted next-token pairs: target[i] == input[i+1] inside the stream
    np.testing.assert_array_equal(item.target_token_ids[:4], item.token_ids[1:5])


def test_text_truncation_keeps_completion(vocab_file, tmp_path):
    path = tmp_path / "long.jsonl"
    row = {"prompt": " ".join(["the"] * 30), "completion": "answer"}
    path.write_text(json.dumps(row))
    ds = FinetuningTextDataset(path, sequence_length=8, vocab_file=vocab_file)
    item = ds[0]
    assert item.token_ids.shape == (8,)
    # the trained completion token survives truncation
    assert item.loss_weights.sum() >= 1


def test_text_memory_map_variant(vocab_file, tmp_path):
    prefix = tmp_path / "ft"
    with MemoryMapDatasetBuilder(prefix, dtype=np.uint16) as b:
        # record = [len_prompt, prompt..., completion...]
        b.add(np.asarray([3, 5, 6, 7, 8, 9], dtype=np.uint16))
    ds = FinetuningTextDataset(prefix, sequence_length=10, vocab_file=vocab_file,
                               memory_map_dataset=True)
    item = ds[0]
    np.testing.assert_array_equal(item.token_ids[:5], [5, 6, 7, 8, 9])
    np.testing.assert_array_equal(item.loss_weights[:5], [0, 0, 1, 1, 1])


def test_chat_has_loss_flags(vocab_file, tmp_path):
    path = tmp_path / "chat.jsonl"
    convo = [
        {"type": "text", "content": "question foo", "has_loss": False},
        {"type": "text", "content": "answer bar <|endoftext|>", "has_loss": True},
    ]
    path.write_text(json.dumps(convo))
    ds = FinetuningChatDataset(path, sequence_length=10, vocab_file=vocab_file)
    item = ds[0]
    # 2 prompt tokens (no loss) then loss on the answer part
    w = item.loss_weights
    assert w[0] == 0
    assert w[1:4].sum() >= 2  # answer tokens trained


def test_collate_shapes(vocab_file, text_jsonl):
    ds = FinetuningTextDataset(text_jsonl, sequence_length=12, vocab_file=vocab_file)
    batch = ds.collate([ds[0], ds[1]])
    assert batch.token_ids.shape == (2, 12)
    assert batch.loss_weights.dtype == np.float32
    assert (batch.position_ids[:, 0] == 0).all()


def test_finetuning_end_to_end(vocab_file, text_jsonl, tmp_path):
    """Train a few steps through the standard entry with the finetuning flag
    (reference: test_finetuning.py life-cycle)."""
    from scaling_tpu.models.transformer import TransformerConfig
    from scaling_tpu.models.transformer.train import main

    config = TransformerConfig.from_dict(
        {
            "topology": {
                "model_parallel_size": 1, "pipe_parallel_size": 1,
                "data_parallel_size": 1, "micro_batch_size": 2,
                "gradient_accumulation_steps": 1,
            },
            "transformer_architecture": {
                "vocab_size": 16, "hidden_size": 32, "num_layers": 2,
                "num_attention_heads": 4, "sequence_length": 12,
                "vocab_file": str(vocab_file),
            },
            "learning_rate_scheduler": {
                "learning_rate": 0.01, "learning_rate_warmup_steps": 1,
                "learning_rate_decay_iters": 10,
            },
            "trainer": {
                "train_iterations": 3, "seed": 7,
                "save_dir": str(tmp_path / "ckpt"), "save_interval": 3,
            },
            "data": {
                "data_prefixes": [str(text_jsonl)],
                "finetuning_dataset": True,
            },
            "logger": {"log_dir": None},
        }
    )
    trainer = main(config)
    assert trainer.context.iterations == 3
