"""Chip-free performance regression pins (VERDICT r3 #3).

The compiled-HLO program IS the cost model: XLA's cost analysis (FLOPs),
buffer assignment (peak temp/argument bytes) and the collective ops in the
optimized module are all available on the virtual CPU mesh, so a refactor
that regresses step cost — duplicated compute, a remat blowup, per-micro-
batch gradient syncs, an accidental full-replication — fails the suite
without needing hardware. Bands are calibrated against the current
implementation with headroom for XLA version noise; the analytic anchors
(6·N·T FLOPs, fp32 parameter bytes) keep them meaningful, not circular.

Reference analogue: the runtime TFLOPs instrumentation it logs each step
(src/scaling/transformer/utils/get_tflops.py:12-334) — here turned into
compile-time assertions.
"""

import jax
import pytest

from scaling_tpu.analysis.hlo_audit import collective_bytes
from scaling_tpu.models.transformer import TransformerConfig
from scaling_tpu.models.transformer.model import (
    init_model,
    init_optimizer,
)
from scaling_tpu.models.transformer.utils.get_tflops import (
    get_model_parameter_count,
)
from scaling_tpu.topology import Topology


def make_config(seq=256, mbs=2, hidden=256, layers=4, vocab=2048, mp=1, dp=1,
                gas=1, zero=False, remat=None):
    """The bench's flagship structure (GQA + RoPE + SwiGLU + RMS) through
    the shared auditor builder — one config recipe for the pins and the
    analysis goldens."""
    from scaling_tpu.analysis.hlo_audit import make_train_config

    return make_train_config(
        seq=seq, mbs=mbs, hidden=hidden, layers=layers, vocab=vocab,
        mp=mp, dp=dp, gas=gas, zero=zero, remat=remat,
        kv_heads=max(1, hidden // 128), mlp_factor=2.75,
    )


def compile_step(config):
    """Compile (never run) the real jitted train step for ``config`` —
    the shared auditor recipe, so these pins and the analysis goldens
    measure the same program."""
    from scaling_tpu.analysis.hlo_audit import lower_train_step

    return lower_train_step(config)[0].compile()


def per_partition_flops(compiled):
    an = compiled.cost_analysis()
    an = an[0] if isinstance(an, list) else an
    return float(an["flops"])


# collective_bytes moved to scaling_tpu.analysis.hlo_audit (the shared
# auditor these pins seeded — ISSUE 2); same parsing, same per-partition
# result-bytes accounting, plus replica-group axis attribution the CLI
# report adds on top.


def analytic_step_flops(config):
    """6·N·T dense + 12·L·h·s²·b attention matmuls (fwd+bwd), the same
    accounting the runtime megatron estimator uses."""
    arch = config.transformer_architecture
    topo = config.topology
    n = get_model_parameter_count(
        arch.hidden_size, arch.num_layers, arch.vocab_size, arch.mlp_factor,
        glu=True,
    )
    tokens = (
        topo.micro_batch_size * topo.data_parallel_size
        * topo.gradient_accumulation_steps * arch.sequence_length
    )
    attn = (
        12 * arch.num_layers * arch.hidden_size * arch.sequence_length ** 2
        * topo.micro_batch_size * topo.data_parallel_size
        * topo.gradient_accumulation_steps
    )
    return 6 * n * tokens + attn


def test_train_step_flops_match_analytic():
    """Total step FLOPs stay within a tight band of the analytic count —
    duplicated compute (e.g. a second unintended forward) lands far
    outside [0.95, 1.12] (measured: 1.007)."""
    config = make_config()
    ratio = per_partition_flops(compile_step(config)) / analytic_step_flops(config)
    assert 0.95 <= ratio <= 1.12, ratio


def test_remat_flop_overhead_within_band():
    """Activation checkpointing must stay a bounded FLOPs-for-memory trade:
    one extra forward at most over the body ([1.05, 1.5]; measured 1.23).
    A remat policy that recomputes the backward too would land near 2.
    The save-dots policy must sit strictly between: it keeps the matmul
    outputs, so its recompute is elementwise-only."""
    base = per_partition_flops(compile_step(make_config()))
    remat = per_partition_flops(compile_step(make_config(remat="every_layer")))
    assert 1.05 <= remat / base <= 1.5, remat / base
    dots = per_partition_flops(
        compile_step(make_config(remat="every_layer_save_dots"))
    )
    assert base * 0.999 <= dots <= remat, (base, dots, remat)


def test_sharded_step_balances_flops_and_pins_grad_sync_bytes(devices):
    """TP=2 × DP=4 with ZeRO-1 on the 8-device mesh: (a) per-partition
    FLOPs stay balanced — partitions × per-partition ≈ global-batch-scaled
    single-device FLOPs within [0.98, 1.18] (measured 1.072; replication
    of the body would double it); (b) total sync traffic (DP grad sync +
    TP activation reductions) stays within [0.6, 2.4] × fp32 parameter
    bytes (measured 1.70 with variadic tuple collectives counted;
    syncing per micro batch would blow past the top — and the gas
    flatness test below pins that directly)."""
    single = per_partition_flops(compile_step(make_config()))
    config = make_config(mp=2, dp=4, zero=True)
    compiled = compile_step(config)
    total = per_partition_flops(compiled) * 8
    # sharded run carries 4x the global batch of the single-device config
    balance = total / (4 * single)
    assert 0.98 <= balance <= 1.18, balance

    cb = collective_bytes(compiled)
    sync_bytes = sum(
        cb.get(op, 0) for op in ("all-reduce", "all-gather", "reduce-scatter")
    )
    arch = config.transformer_architecture
    param_bytes_fp32 = 4 * get_model_parameter_count(
        arch.hidden_size, arch.num_layers, arch.vocab_size, arch.mlp_factor,
        glu=True,
    )
    ratio = sync_bytes / param_bytes_fp32
    assert 0.6 <= ratio <= 2.4, (cb, ratio)


def test_collective_bytes_flat_in_gradient_accumulation(devices):
    """Gradients sync once per STEP, not per micro-batch: doubling gas must
    not grow collective traffic (the scan-over-microbatches design keeps
    the sync outside the scan; a regression moving it inside doubles
    bytes immediately)."""
    cb1 = collective_bytes(compile_step(make_config(dp=2, gas=1)))
    cb2 = collective_bytes(compile_step(make_config(dp=2, gas=2)))
    total1 = sum(cb1.values())
    total2 = sum(cb2.values())
    assert total1 > 0, cb1
    assert total2 <= total1 * 1.1, (cb1, cb2)


@pytest.mark.slow
def test_bench_half_b_shape_flops_and_memory_drift():
    """The exact 0.5B shape bench.py measures on the chip: FLOPs within
    the analytic band, plus a memory DRIFT pin. The absolute bytes here
    are not the chip's (this CPU compile takes the `torch` attention path,
    which saves per-layer s² score tensors the splash kernel never
    materializes — measured 58.8 GB vs the ~9 GB the chip needs), but a
    jump past the band still means someone made the step hold more live
    state."""
    config = make_config(seq=2048, mbs=4, hidden=2048, layers=8, vocab=32768)
    compiled = compile_step(config)
    ratio = per_partition_flops(compiled) / analytic_step_flops(config)
    assert 0.95 <= ratio <= 1.12, ratio
    mem = compiled.memory_analysis()
    resident = mem.argument_size_in_bytes + mem.temp_size_in_bytes
    assert resident < 70e9, resident


@pytest.mark.slow
def test_baseline3_one_b_shape_fits_per_chip(devices):
    """BASELINE #3's 1B GQA+RoPE+SwiGLU model at TP=2 × DP=4 with ZeRO-1
    and every-layer remat: the parameter count really is ~1B, and the
    per-chip footprint (sharded args + temps) fits a 16 GB v5e with room
    for the runtime (measured ~6.7 GB at seq 512)."""
    config = make_config(
        seq=512, mbs=1, hidden=2048, layers=20, vocab=32768,
        mp=2, dp=4, zero=True, remat="every_layer",
    )
    arch = config.transformer_architecture
    n = get_model_parameter_count(
        arch.hidden_size, arch.num_layers, arch.vocab_size, arch.mlp_factor,
        glu=True,
    )
    assert 0.9e9 <= n <= 1.3e9, n
    compiled = compile_step(config)
    mem = compiled.memory_analysis()
    resident = mem.argument_size_in_bytes + mem.temp_size_in_bytes
    assert resident < 12e9, resident


def peft_lora_config(**kw):
    """make_config + LoRA adapters with the backbone frozen (the
    BASELINE #5 PEFT layout at virtual-mesh scale)."""
    cfg = make_config(**kw)
    d = cfg.model_dump(mode="json")
    d["transformer_architecture"]["lora_config"] = {
        "name": "lo", "rank": 2, "alpha": 4,
    }
    d["training"] = {"finetune": True, "finetunable_parameters": []}
    return TransformerConfig.from_dict(d)


def test_peft_step_cost_scales_with_adapters_not_model(devices):
    """BASELINE #5 is a PEFT finetune at TP×DP; its economics hinge on the
    frozen backbone costing nothing beyond the forward. Frozen leaves are
    stop_gradient'd inside the loss, so (a) the backward drops the frozen
    weight-grad matmuls — the LoRA step must compile to at least 15% fewer
    FLOPs than full finetuning (measured 28% fewer at this shape) — and
    (b) the DP gradient sync moves adapter-sized traffic: LoRA all-reduce
    bytes at most 0.75x full finetuning's (measured 0.60x; before the fix
    LoRA's traffic EXCEEDED full's because has_inf_or_nan_tree kept every
    frozen grad and its psum alive)."""
    full = compile_step(make_config(mp=2, dp=4))
    lora = compile_step(peft_lora_config(mp=2, dp=4))
    assert per_partition_flops(lora) < 0.85 * per_partition_flops(full), (
        per_partition_flops(lora), per_partition_flops(full))
    ar_full = collective_bytes(full).get("all-reduce", 0)
    ar_lora = collective_bytes(lora).get("all-reduce", 0)
    assert ar_lora < 0.75 * ar_full, (ar_lora, ar_full)


def test_peft_optimizer_state_holds_adapters_only(devices):
    """Masters/moments exist for the adapters, not the frozen backbone
    (the ZeRO analogue of the reference's parameter-group filtering)."""

    def opt_bytes(cfg):
        topo = Topology(cfg.topology)
        module = init_model(cfg, topo)
        opt = init_optimizer(cfg, module, topo)
        params = module.shard_params(module.init_params(jax.random.PRNGKey(0)))
        return sum(x.nbytes for x in jax.tree.leaves(opt.init_state(params)))

    full = opt_bytes(make_config(mp=2, dp=4))
    lora = opt_bytes(peft_lora_config(mp=2, dp=4))
    assert lora < 0.02 * full, (lora, full)


@pytest.mark.slow
def test_baseline4_layout_compile_pin_small_proxy():
    """benchmarks/compile_pin_7b.py is the chip-free evidence for the
    BASELINE #4 layout (TP=4 × PP=2 × DP=8 + ZeRO-1 + remat on 64 virtual
    devices); this runs its CI-sized proxy in a subprocess (own process:
    the 64-device count can't coexist with the suite's 8) and checks the
    JSON contract the artifact relies on."""
    import json as _json
    import os as _os
    import subprocess as _sp
    import sys as _sys

    repo = _os.path.dirname(_os.path.dirname(_os.path.dirname(
        _os.path.abspath(__file__))))
    p = _sp.run(
        [_sys.executable, _os.path.join(repo, "benchmarks", "compile_pin_7b.py"),
         "--small"],
        capture_output=True, text=True, timeout=900,
    )
    assert p.returncode == 0, p.stderr[-2000:]
    rec = _json.loads(p.stdout.strip().splitlines()[-1])
    assert rec["model"] == "small-proxy"
    assert rec["devices"] == 64
    assert rec["fits_v5p_95g"] is True
    assert rec["per_chip_gb"] < 1.0
    assert rec["collective_bytes_per_iter"]
    # the useful-token MFU ceiling n_micro/(n_micro+pp-1) — identical for
    # the spatial pipeline (fill/drain garbage) and non-interleaved 1F1B
    # (bubble) — must be reported per layout (VERDICT r4 #7)
    pl = rec["pipeline"]
    assert pl["pp"] == 2
    assert pl["useful_token_mfu_ceiling"] == pytest.approx(
        pl["n_micro"] / (pl["n_micro"] + pl["pp"] - 1), abs=1e-4
    )
    assert pl["scan_carries_mb_per_device"] > 0


def test_abstract_state_mirrors_init_state(devices):
    """benchmarks/compile_pin_7b.py trusts Optimizer.abstract_state to be a
    faithful aval mirror of init_state — structure, shapes, dtypes, and
    the ZeRO master shardings eval_shape would drop. A drift (say, a new
    OptimizerState field) must fail here, not silently skew the 7B pin."""
    config = make_config(mp=2, dp=4, zero=True)
    topology = Topology(config.topology)
    module = init_model(config, topology)
    optimizer = init_optimizer(config, module, topology)
    params = module.shard_params(module.init_params(jax.random.PRNGKey(0)))
    real = optimizer.init_state(params)
    abstract = optimizer.abstract_state(params)
    assert jax.tree.structure(real) == jax.tree.structure(abstract)
    for r, a in zip(jax.tree.leaves(real), jax.tree.leaves(abstract)):
        assert r.shape == a.shape and r.dtype == a.dtype, (r.shape, a.shape)
    for field in ("master", "exp_avg", "exp_avg_sq"):
        for r, a in zip(
            jax.tree.leaves(getattr(real, field)),
            jax.tree.leaves(getattr(abstract, field)),
        ):
            if r.size:  # (0,) placeholders for frozen leaves carry none
                assert a.sharding == r.sharding, (field, a.sharding, r.sharding)
