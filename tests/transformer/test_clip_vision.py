"""Pretrained CLIP vision import: our ClipVisionEncoder must reproduce a
huggingface CLIPVisionModel's features from imported weights (the
pretrained-prior capability of the reference's CLIP trunk, clip.py)."""

import jax
import numpy as np
import pytest
import torch

from scaling_tpu.models.transformer.clip_vision import (
    ClipVisionEncoder,
    import_clip_vision_weights,
)
from scaling_tpu.nn import ForwardContext

CTX = ForwardContext()


def tiny_hf_clip(image_size, patch_size=32, width=64, layers=2, heads=4,
                 intermediate=None):
    from transformers import CLIPVisionConfig, CLIPVisionModel

    cfg = CLIPVisionConfig(
        hidden_size=width, intermediate_size=intermediate or 2 * width,
        num_hidden_layers=layers, num_attention_heads=heads,
        image_size=image_size, patch_size=patch_size,
    )
    torch.manual_seed(7)
    return CLIPVisionModel(cfg).eval()


def our_encoder_for(model, image_size):
    c = model.config
    return ClipVisionEncoder(
        width=c.hidden_size, layers=c.num_hidden_layers,
        heads=c.num_attention_heads, patch_size=c.patch_size,
        image_size=image_size, intermediate=c.intermediate_size,
    )


def test_clip_import_reproduces_hf_features():
    """Imported weights reproduce last_hidden_state[:, 1:] (the spatial
    tokens magma consumes) within float tolerance."""
    model = tiny_hf_clip(image_size=96)
    enc = our_encoder_for(model, image_size=96)
    params = import_clip_vision_weights(enc, model.state_dict())

    rng = np.random.default_rng(0)
    pixels = rng.normal(size=(2, 3, 96, 96)).astype(np.float32)
    with torch.no_grad():
        want = model(torch.from_numpy(pixels)).last_hidden_state[:, 1:].numpy()
    got = enc(params, np.transpose(pixels, (0, 2, 3, 1)), CTX)
    assert got.shape == want.shape == (2, 9, 64)
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-4)


def test_clip_import_interpolates_position_embeddings():
    """A checkpoint trained at one resolution imports at another: the
    position grid is bicubic-interpolated exactly as HF's
    interpolate_pos_encoding (the reference runs its CLIP at 384 regardless
    of the pretrain resolution, image_encoder.py:20-27)."""
    model = tiny_hf_clip(image_size=64)  # native grid 2x2
    enc = our_encoder_for(model, image_size=96)  # target grid 3x3
    params = import_clip_vision_weights(enc, model.state_dict())

    rng = np.random.default_rng(1)
    pixels = rng.normal(size=(1, 3, 96, 96)).astype(np.float32)
    with torch.no_grad():
        want = model(
            torch.from_numpy(pixels), interpolate_pos_encoding=True
        ).last_hidden_state[:, 1:].numpy()
    got = enc(params, np.transpose(pixels, (0, 2, 3, 1)), CTX)
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-4)


def test_clip_import_rejects_geometry_mismatch():
    """Silently importing a truncated or resized trunk would train on a
    model the user believes is the full pretrained tower."""
    enc = ClipVisionEncoder(width=64, layers=2, heads=4, patch_size=32,
                            image_size=96, intermediate=128)
    with pytest.raises(AssertionError, match="patch"):
        import_clip_vision_weights(
            enc, tiny_hf_clip(image_size=64, patch_size=16).state_dict())
    with pytest.raises(ValueError, match="layers"):
        import_clip_vision_weights(
            enc, tiny_hf_clip(image_size=64, layers=4).state_dict())
    with pytest.raises(ValueError, match="width"):
        import_clip_vision_weights(
            enc, tiny_hf_clip(image_size=64, width=32, heads=2).state_dict())
    with pytest.raises(ValueError, match="mlp width"):
        import_clip_vision_weights(
            enc, tiny_hf_clip(image_size=64, intermediate=64).state_dict())


def test_image_encoder_clip_backbone():
    """backbone='clip' end to end at the reference geometry: 384x384 in,
    144 projected prefix tokens out, params/metas trees structure-aligned
    (the checkpoint machinery zips them), pretrained trunk loadable."""
    from scaling_tpu.models.transformer.image_encoder import ImageEncoder

    enc = ImageEncoder(out_features=32, width=64, layers=2, heads=4,
                       backbone="clip")
    params = enc.init(jax.random.PRNGKey(0))
    metas = enc.param_metas()
    assert jax.tree.structure(params) == jax.tree.structure(
        metas, is_leaf=lambda x: not isinstance(x, dict)
    )

    model = tiny_hf_clip(image_size=384, intermediate=256)  # trunk uses 4x width
    params = enc.load_clip_weights(params, model.state_dict())
    rng = np.random.default_rng(2)
    images = rng.normal(size=(1, 384, 384, 3)).astype(np.float32)
    out = enc(params, images, CTX)
    assert out.shape == (1, 144, 32)
    assert np.isfinite(np.asarray(out)).all()


def test_clip_checkpoint_applied_at_train_startup(tmp_path):
    """The image_encoder_clip_checkpoint knob end to end: main() splices
    the pretrained trunk into a fresh run (text-only data; the trunk just
    rides along) and the trained model's trunk carries the checkpoint's
    class embedding, not the random init."""
    from scaling_tpu.data.memory_map import MemoryMapDatasetBuilder
    from scaling_tpu.models.transformer.train import main

    prefix = tmp_path / "data"
    rng = np.random.default_rng(5)
    with MemoryMapDatasetBuilder(prefix, dtype=np.uint16) as b:
        for _ in range(32):
            doc = rng.integers(1, 96, size=rng.integers(8, 48))
            b.add(np.append(doc, 0).astype(np.uint16))

    model = tiny_hf_clip(image_size=384, intermediate=256)
    ckpt = tmp_path / "clip_vision.pt"
    torch.save(model.state_dict(), ckpt)

    from scaling_tpu.models.transformer import TransformerConfig

    cfg = TransformerConfig.from_dict({
        "topology": {"model_parallel_size": 1, "pipe_parallel_size": 1,
                     "data_parallel_size": 1, "micro_batch_size": 2,
                     "gradient_accumulation_steps": 1},
        "transformer_architecture": {
            "vocab_size": 96, "hidden_size": 32, "num_layers": 1,
            "num_attention_heads": 4, "sequence_length": 160,
            "image_encoder": True, "image_encoder_width": 64,
            "image_encoder_layers": 2, "image_encoder_heads": 4,
            "image_encoder_backbone": "clip",
            "image_encoder_clip_checkpoint": str(ckpt),
        },
        "optimizer": {"gradient_clipping": 1.0},
        "learning_rate_scheduler": {"learning_rate": 0.01,
                                    "learning_rate_warmup_steps": 2,
                                    "learning_rate_decay_iters": 50},
        "trainer": {"train_iterations": 1, "seed": 42,
                    "save_dir": str(tmp_path / "ckpt"), "save_interval": 100},
        "data": {"data_prefixes": [str(prefix)]},
        "logger": {"log_dir": None},
    })
    trainer = main(cfg)
    for key, p, _ in trainer.module.named_parameters(trainer.params):
        if key.endswith("image_encoder.clip.class_embedding"):
            want = model.state_dict()["vision_model.embeddings.class_embedding"]
            np.testing.assert_allclose(
                np.asarray(p, np.float32), want.numpy(), atol=1e-5)
            break
    else:
        raise AssertionError("clip trunk parameter not found")


def _splice_cfg(tmp_path, prefix, ckpt, **trainer_overrides):
    from scaling_tpu.models.transformer import TransformerConfig

    trainer = {"train_iterations": 1, "seed": 42,
               "save_dir": str(tmp_path / "ckpt"), "save_interval": 1}
    trainer.update(trainer_overrides)
    return TransformerConfig.from_dict({
        "topology": {"model_parallel_size": 1, "pipe_parallel_size": 1,
                     "data_parallel_size": 1, "micro_batch_size": 2,
                     "gradient_accumulation_steps": 1},
        "transformer_architecture": {
            "vocab_size": 96, "hidden_size": 32, "num_layers": 1,
            "num_attention_heads": 4, "sequence_length": 160,
            "image_encoder": True, "image_encoder_width": 64,
            "image_encoder_layers": 2, "image_encoder_heads": 4,
            "image_encoder_backbone": "clip",
            "image_encoder_clip_checkpoint": str(ckpt),
        },
        "optimizer": {"gradient_clipping": 1.0},
        "learning_rate_scheduler": {"learning_rate": 0.01,
                                    "learning_rate_warmup_steps": 2,
                                    "learning_rate_decay_iters": 50},
        "trainer": trainer,
        "data": {"data_prefixes": [str(prefix)]},
        "logger": {"log_dir": None},
    })


def _text_data(tmp_path):
    from scaling_tpu.data.memory_map import MemoryMapDatasetBuilder

    prefix = tmp_path / "data"
    rng = np.random.default_rng(5)
    with MemoryMapDatasetBuilder(prefix, dtype=np.uint16) as b:
        for _ in range(32):
            doc = rng.integers(1, 96, size=rng.integers(8, 48))
            b.add(np.append(doc, 0).astype(np.uint16))
    return prefix


def _trunk_class_embedding(trainer):
    for key, p, _ in trainer.module.named_parameters(trainer.params):
        if key.endswith("image_encoder.clip.class_embedding"):
            return np.asarray(p, np.float32)
    raise AssertionError("clip trunk parameter not found")


def _max_abs_exp_avg(trainer):
    return max(
        float(np.max(np.abs(np.asarray(leaf))))
        for leaf in jax.tree.leaves(trainer.opt_state.exp_avg)
        if leaf.size
    )


def test_clip_splice_skipped_when_checkpoint_restored_trunk(tmp_path):
    """A finetune that loads a checkpoint containing a trained trunk with
    load_context=False (iterations stays 0) must NOT re-splice pretrained
    CLIP over it, and must keep the loaded Adam moments."""
    from scaling_tpu.models.transformer.train import main

    prefix = _text_data(tmp_path)
    model = tiny_hf_clip(image_size=384, intermediate=256)
    ckpt = tmp_path / "clip_vision.pt"
    torch.save(model.state_dict(), ckpt)
    main(_splice_cfg(tmp_path, prefix, ckpt))  # trains 1 step, saves

    # second run: same splice knob but pointing at a SHIFTED trunk — if the
    # gate fails, the shift lands in the weights and the moments reset
    shifted = {k: v + 1.0 if k == "vision_model.embeddings.class_embedding"
               else v for k, v in model.state_dict().items()}
    ckpt2 = tmp_path / "clip_vision_shifted.pt"
    torch.save(shifted, ckpt2)
    t2 = main(_splice_cfg(
        tmp_path, prefix, ckpt2, train_iterations=0, save_dir=None,
        load_dir=str(tmp_path / "ckpt"), load_context=False,
    ))
    want = model.state_dict()["vision_model.embeddings.class_embedding"].numpy()
    got = _trunk_class_embedding(t2)
    np.testing.assert_allclose(got, want, atol=1e-3)  # kept, not re-spliced
    assert _max_abs_exp_avg(t2) > 0  # loaded moments survived


def test_clip_splice_graft_keeps_loaded_moments(tmp_path):
    """When the trunk is deliberately NOT restored (ignore_keys) the splice
    applies — but only the image-encoder slice of the optimizer state
    re-derives; the LM's loaded moments survive."""
    from scaling_tpu.models.transformer.train import main

    prefix = _text_data(tmp_path)
    model = tiny_hf_clip(image_size=384, intermediate=256)
    ckpt = tmp_path / "clip_vision.pt"
    torch.save(model.state_dict(), ckpt)
    main(_splice_cfg(tmp_path, prefix, ckpt))

    shifted = {k: v + 1.0 if k == "vision_model.embeddings.class_embedding"
               else v for k, v in model.state_dict().items()}
    ckpt2 = tmp_path / "clip_vision_shifted.pt"
    torch.save(shifted, ckpt2)
    t2 = main(_splice_cfg(
        tmp_path, prefix, ckpt2, train_iterations=0, save_dir=None,
        load_dir=str(tmp_path / "ckpt"), load_context=False,
        ignore_keys_in_checkpoint=[".*image_encoder.*"],
    ))
    want = (model.state_dict()["vision_model.embeddings.class_embedding"]
            .numpy() + 1.0)
    np.testing.assert_allclose(_trunk_class_embedding(t2), want, atol=1e-3)
    assert _max_abs_exp_avg(t2) > 0  # LM moments kept through the graft
