"""The reference's own example config must load verbatim.

BASELINE.json config #2 requires
/root/reference/examples/transformer_example/config.yml to run unchanged;
this pins the config surface (attention_bias / mlp_bias /
attention_use_matmul / dropout_image_encoder, legacy aliases) against the
reference's field set (reference: src/scaling/transformer/context/config.py).
"""

from pathlib import Path

import pytest

from scaling_tpu.models.transformer import TransformerConfig
from scaling_tpu.models.transformer.model import init_model

REFERENCE = Path("/root/reference")

pytestmark = pytest.mark.skipif(
    not REFERENCE.is_dir(), reason="reference checkout not present"
)


def test_reference_example_config_loads_verbatim():
    cfg = TransformerConfig.from_yaml(
        REFERENCE / "examples/transformer_example/config.yml"
    )
    arch = cfg.transformer_architecture
    assert arch.attention_bias is False
    assert arch.mlp_bias is False
    assert arch.vocab_size == 128000
    assert arch.mlp_type.value == "swiglu"
    assert cfg.optimizer.zero is True
    assert cfg.training.weight_decay == 0.01


def test_reference_example_config_builds_model():
    cfg = TransformerConfig.from_yaml(
        REFERENCE / "examples/transformer_example/config.yml"
    )
    module = init_model(cfg, topology=None)
    import jax

    params = module.init_params(jax.random.PRNGKey(0))
    names = {k for k, _, _ in module.named_parameters(params)}
    # bias switches must actually take effect in the parameter tree
    assert not any("attention" in n and n.endswith(".bias") for n in names)
    assert not any(".mlp." in n and n.endswith(".bias") for n in names)


def test_legacy_misspelled_alias():
    cfg = TransformerConfig.from_dict(
        {
            "topology": {
                "model_parallel_size": 1,
                "pipe_parallel_size": 1,
                "data_parallel_size": 1,
                "micro_batch_size": 1,
                "gradient_accumulation_steps": 1,
            },
            "transformer_architecture": {
                "vocab_size": 8,
                "hidden_size": 8,
                "num_layers": 1,
                "num_attention_heads": 1,
            },
            # the reference supports this historical misspelling
            # (reference: context/config.py:55-57)
            "training": {"use_seperate_lr_on_embeddings": True},
        }
    )
    assert cfg.training.use_separate_lr_on_embeddings is True


def test_bias_fields_default_on():
    cfg = TransformerConfig.from_dict(
        {
            "topology": {
                "model_parallel_size": 1,
                "pipe_parallel_size": 1,
                "data_parallel_size": 1,
                "micro_batch_size": 1,
                "gradient_accumulation_steps": 1,
            },
            "transformer_architecture": {
                "vocab_size": 8,
                "hidden_size": 8,
                "num_layers": 1,
                "num_attention_heads": 1,
            },
        }
    )
    # reference defaults (config.py:200,220)
    assert cfg.transformer_architecture.attention_bias is True
    assert cfg.transformer_architecture.mlp_bias is True
    assert cfg.transformer_architecture.attention_use_matmul is False


def test_reference_example_config_trains_end_to_end(tmp_path, devices):
    """BASELINE config #2: the reference's example config + the reference's
    own shipped dataset run through our train stack unchanged (only
    operational overrides: absolute data path, tmp save dir, fewer steps)."""
    import numpy as np

    from .test_training import build_capturing_trainer, train_capture

    cfg = TransformerConfig.from_yaml(
        REFERENCE / "examples/transformer_example/config.yml",
        overwrite_values={
            "data": {
                "data_prefixes": [
                    str(REFERENCE / "tests/transformer/files/dataset/data")
                ],
                "blended_dataset": {"cache_directory": str(tmp_path / "cache")},
            },
            "trainer": {
                "save_dir": str(tmp_path / "ckpt"),
                "train_iterations": 8,
                "save_interval": 8,
            },
            "runner": None,
        },
    )
    trainer = build_capturing_trainer(cfg)
    losses = train_capture(trainer, 8)
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]  # 128k-vocab from-scratch: fast early drop
    assert (tmp_path / "ckpt" / "global_step8").is_dir()


def test_example_configs_parse():
    """Every shipped example config must load through TransformerConfig
    (guards the examples against config-surface drift)."""
    from pathlib import Path

    for yml in sorted(Path("examples").glob("*example/config*.yml")):
        if "mlp" in str(yml):
            continue  # mlp example uses its own config class
        cfg = TransformerConfig.from_yaml(yml)
        assert cfg.transformer_architecture.hidden_size > 0, yml
